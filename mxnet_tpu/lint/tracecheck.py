"""tracecheck: trace-time jaxpr/HLO analysis of the owned XLA entry points.

graftlint (``rules.py``) works on source text; whole classes of silent
performance/correctness bugs only exist in the *lowered program* and are
invisible to an AST pass — a closure-baked weight matrix, an accidental
f64 widening, a host callback compiled into the train step, a donated
buffer that can never alias an output.  The reference framework closed
the same gap with graph-level passes over NNVM IR rather than C++ lint
(SURVEY layer map; cf. TVM/NNVM graph passes and Grappler's analyzers in
PAPERS.md).  This module is that tier for the JAX rebuild: it lowers the
programs the framework actually ships to XLA — AOT, on CPU, from
``ShapeDtypeStruct`` specimens, no TPU and no real data — and walks the
resulting jaxprs with a rule registry mirroring graftlint's.

Rule catalogue (rationale in docs/LINT.md):

JX101 baked-constant          large arrays captured by closure become
                              jaxpr constants: copied into every compiled
                              variant, silently stale after updates.
JX102 dtype-widening          f64/i64 appearing in a program whose inputs
                              are all <=32-bit: 2x HBM + matmul slowdown,
                              usually one forgotten ``np.float64`` scalar.
JX103 host-callback           ``pure_callback``/``io_callback``/
                              ``debug.print`` compiled into an owned hot
                              program: a host round-trip per step.
JX104 donation-waste          donated args that cannot alias any output
                              (buffer freed for nothing), large
                              non-donated args that alias outputs in a
                              program that already donates, and dead
                              (pass-through / constant) outputs.
JX105 retrace-explainer       on a ``watch_jit`` recompile, diff the new
                              avals/statics against the cached variants
                              and NAME the axis that changed — turns the
                              telemetry retrace-storm warning into a
                              diagnosis.  Runtime-only (``MXNET_TRACECHECK``).

Two drivers share the registry:

* AOT (``check_entry_points`` / ``tools/graftcheck.py`` /
  ``python -m mxnet_tpu.lint --trace``): every owned jit entry point
  declares a ``tracecheck_programs()`` provider next to the jit itself
  (executor, fused trainer, optimizer, kvstore, module cached step,
  gluon cached op); the driver traces each with specimen shapes and runs
  JX101-JX104.  CI gates on zero findings (tests/test_tracecheck_clean.py).
* Runtime (``on_compile``): ``telemetry._WatchedJit`` calls in on every
  compile event when ``MXNET_TRACECHECK`` is truthy; findings are booked
  into the ``tracecheck_findings`` counter, the flight ring, and one
  structured log line each — JX105 included, because only the runtime
  hook sees *two* variants to diff.

Import-light on purpose: jax is imported inside functions only, so the
stdlib-only lint CLI can show the JX catalogue (``--list-rules``) without
initializing a backend.
"""
from __future__ import annotations

import json
import logging
import os

from .core import Finding

__all__ = ["TRACE_RULES", "TraceRule", "TraceConfig", "ProgramRecord",
           "trace_program", "run_rules", "check_entry_points",
           "iter_owned_programs", "on_compile", "signature",
           "explain_retrace", "ENTRY_POINTS"]
# NOTE: the MXNET_TRACECHECK gate itself lives in telemetry.core
# (_env_tracecheck) — the hook's caller owns the env parsing.

_LOG = logging.getLogger("mxnet_tpu.lint.tracecheck")

_WIDE_DTYPES = ("float64", "int64", "uint64", "complex128")


class TraceConfig:
    """Thresholds for the size-gated rules.

    The defaults are deliberately conservative: the AOT driver runs tiny
    specimen models, so an owned entry point only fires when it bakes or
    wastes something *structurally* (a closure-captured table, an
    unaliasable donation), never because a real model is large.  Tests
    shrink the thresholds to exercise the rules on toy programs.
    """

    __slots__ = ("const_bytes", "donation_bytes", "passthrough_bytes")

    def __init__(self, const_bytes=64 << 10, donation_bytes=1 << 20,
                 passthrough_bytes=64 << 10):
        self.const_bytes = const_bytes
        self.donation_bytes = donation_bytes
        self.passthrough_bytes = passthrough_bytes


DEFAULT_CONFIG = TraceConfig()


# ---------------------------------------------------------------------------
# rule registry (mirrors rules.RULES)
# ---------------------------------------------------------------------------

TRACE_RULES = {}


class TraceRule:
    __slots__ = ("code", "name", "rationale", "_check")

    def __init__(self, code, name, rationale, check):
        self.code, self.name, self.rationale = code, name, rationale
        self._check = check

    def check(self, record, config):
        if self._check is None:        # runtime-only rule (JX105)
            return []
        return list(self._check(record, config))


def trace_rule(code, name, rationale):
    def deco(fn):
        TRACE_RULES[code] = TraceRule(code, name, rationale, fn)
        return fn
    return deco


# ---------------------------------------------------------------------------
# program record: one traced entry point
# ---------------------------------------------------------------------------

def _spec(leaf):
    """ShapeDtypeStruct skeleton of one pytree leaf (python scalars pass
    through and trace as weak-typed scalars, exactly like at runtime)."""
    shape = getattr(leaf, "shape", None)
    dtype = getattr(leaf, "dtype", None)
    if shape is None or dtype is None:
        return leaf
    import jax
    return jax.ShapeDtypeStruct(tuple(shape), dtype)


def _aval_nbytes(aval):
    n = 1
    for d in getattr(aval, "shape", ()):
        n *= int(d)
    dtype = getattr(aval, "dtype", None)
    return n * (dtype.itemsize if dtype is not None else 1)


def _aval_key(aval):
    return (tuple(getattr(aval, "shape", ())), str(getattr(aval, "dtype",
                                                           "?")))


def _fmt_aval(aval):
    return "%s[%s]" % (getattr(aval, "dtype", "?"),
                       ",".join(str(d) for d in getattr(aval, "shape", ())))


class ProgramRecord:
    """One owned program, traced: jaxpr + flat arg labels/avals/donation."""

    __slots__ = ("name", "origin", "closed_jaxpr", "arg_labels", "in_avals",
                 "donated", "out_avals")

    def __init__(self, name, origin, closed_jaxpr, arg_labels, in_avals,
                 donated, out_avals):
        self.name = name
        self.origin = origin
        self.closed_jaxpr = closed_jaxpr
        self.arg_labels = arg_labels      # flat, parallel to in_avals
        self.in_avals = in_avals
        self.donated = donated            # set of flat arg indices
        self.out_avals = out_avals

    @property
    def jaxpr(self):
        return self.closed_jaxpr.jaxpr

    @property
    def consts(self):
        return self.closed_jaxpr.consts

    def label(self, i):
        if 0 <= i < len(self.arg_labels):
            return self.arg_labels[i]
        return "arg[%d]" % i

    def finding(self, rule, message, key=""):
        """A Finding whose fingerprint is stable across runs: the path is
        the program identity, the snippet a short structural key (NOT the
        prose message, which may carry sizes that drift)."""
        return Finding(rule, "trace://%s" % self.name, 0, 0,
                       "%s [%s]: %s" % (self.name, self.origin, message),
                       snippet=key or rule)


def trace_program(name, fn, args, kwargs=None, origin=""):
    """Trace *fn* (a jitted callable or its watch_jit wrapper) with
    ShapeDtypeStruct skeletons of *args*/*kwargs* and return the
    :class:`ProgramRecord` the JX rules analyze.  Nothing is compiled or
    executed; lowering metadata supplies per-argument donation flags.
    """
    import jax
    kwargs = dict(kwargs or {})
    fn = getattr(fn, "_fn", fn)          # unwrap telemetry._WatchedJit
    sargs, skwargs = jax.tree_util.tree_map(_spec, (tuple(args), kwargs))
    traced = fn.trace(*sargs, **skwargs)
    closed = traced.jaxpr
    lowered = traced.lower()

    flat, _ = jax.tree_util.tree_flatten_with_path((sargs, skwargs))
    labels = []
    for path, _leaf in flat:
        label = jax.tree_util.keystr(path)
        # keystr yields "[0][1]['lr']": [0]=args/[1]=kwargs bucket, next
        # index the position — keep it verbatim but drop the bucket
        labels.append("arg%s" % label[3:] if label.startswith("[0]")
                      else "kwarg%s" % label[3:])

    donated = set()
    info_leaves = jax.tree_util.tree_leaves(
        lowered.args_info, is_leaf=lambda v: hasattr(v, "donated"))
    for i, info in enumerate(info_leaves):
        if getattr(info, "donated", False):
            donated.add(i)

    return ProgramRecord(name, origin, closed, labels,
                         list(closed.in_avals), donated,
                         list(closed.out_avals))


def _iter_eqns(jaxpr):
    """Every eqn in *jaxpr* and its nested sub-jaxprs (pjit bodies, scan
    carries, cond branches, custom-vjp closures, ...)."""
    for eqn in jaxpr.eqns:
        yield eqn
        for sub in _sub_jaxprs(eqn):
            yield from _iter_eqns(sub)


def _sub_jaxprs(eqn):
    for val in eqn.params.values():
        yield from _extract_jaxprs(val)


def _extract_jaxprs(val):
    # a ClosedJaxpr has .jaxpr; a raw Jaxpr has .eqns
    inner = getattr(val, "jaxpr", None)
    if inner is not None and hasattr(inner, "eqns"):
        yield inner
    elif hasattr(val, "eqns"):
        yield val
    elif isinstance(val, (tuple, list)):
        for item in val:
            yield from _extract_jaxprs(item)


# ---------------------------------------------------------------------------
# JX101 baked-constant
# ---------------------------------------------------------------------------

@trace_rule("JX101", "baked-constant",
            "large arrays captured by closure become jaxpr constants — "
            "copied into every compiled variant and silently stale after "
            "host-side updates; pass them as arguments")
def _jx101(rec, cfg):
    for var, const in zip(rec.jaxpr.constvars, rec.consts):
        nbytes = _aval_nbytes(var.aval)
        if nbytes < cfg.const_bytes:
            continue
        yield rec.finding(
            "JX101",
            "%s constant (%d bytes) baked into the program — a closure "
            "capture; the compiled program holds a frozen copy that host "
            "mutations never reach. Pass it as an argument instead."
            % (_fmt_aval(var.aval), nbytes),
            key="const:%s" % _fmt_aval(var.aval))


# ---------------------------------------------------------------------------
# JX102 dtype-widening
# ---------------------------------------------------------------------------

@trace_rule("JX102", "dtype-widening",
            "f64/i64 values inside a program whose inputs are all "
            "<=32-bit: doubled HBM traffic and slow double-precision "
            "units, usually one forgotten numpy float64 scalar")
def _jx102(rec, cfg):
    def wide(aval):
        return str(getattr(aval, "dtype", "")) in _WIDE_DTYPES

    if any(wide(a) for a in rec.in_avals):
        return          # wide inputs: the caller asked for 64-bit
    seen = set()
    for var, _const in zip(rec.jaxpr.constvars, rec.consts):
        if wide(var.aval):
            key = ("const", str(var.aval.dtype))
            if key not in seen:
                seen.add(key)
                yield rec.finding(
                    "JX102",
                    "closure constant is %s while every program input is "
                    "<=32-bit — the widening happens before the program "
                    "boundary" % _fmt_aval(var.aval),
                    key="widen-const:%s" % var.aval.dtype)
    for eqn in _iter_eqns(rec.jaxpr):
        for var in eqn.outvars:
            aval = getattr(var, "aval", None)
            if aval is None or not wide(aval):
                continue
            key = (eqn.primitive.name, str(aval.dtype))
            if key in seen:
                continue
            seen.add(key)
            yield rec.finding(
                "JX102",
                "'%s' produces %s in a program whose inputs are all "
                "<=32-bit — check for a python float / np.float64 scalar "
                "or an explicit astype widening the lattice"
                % (eqn.primitive.name, _fmt_aval(aval)),
                key="widen:%s:%s" % (eqn.primitive.name, aval.dtype))


# ---------------------------------------------------------------------------
# JX103 host-callback-in-hot-program
# ---------------------------------------------------------------------------

_CALLBACK_PRIMS = {"pure_callback", "io_callback", "debug_callback"}

@trace_rule("JX103", "host-callback",
            "pure_callback/io_callback/debug.print compiled into an owned "
            "hot program: every execution round-trips through the host — "
            "the async dispatch pipeline stalls behind python")
def _jx103(rec, cfg):
    seen = set()
    for eqn in _iter_eqns(rec.jaxpr):
        prim = eqn.primitive.name
        if prim not in _CALLBACK_PRIMS or prim in seen:
            continue
        seen.add(prim)
        yield rec.finding(
            "JX103",
            "'%s' is compiled into this program: a host python call per "
            "execution. Debug prints belong outside the jit; data-dependent "
            "host logic belongs between programs, not inside them." % prim,
            key="callback:%s" % prim)


# ---------------------------------------------------------------------------
# JX104 donation-waste
# ---------------------------------------------------------------------------

@trace_rule("JX104", "donation-waste",
            "donated buffers that cannot alias any output (freed for "
            "nothing), large aliasable args left undonated in a program "
            "that already donates, and dead pass-through/constant outputs")
def _jx104(rec, cfg):
    # multiset of output avals available for aliasing
    pool = {}
    for aval in rec.out_avals:
        key = _aval_key(aval)
        pool[key] = pool.get(key, 0) + 1

    # donated args consume matching outputs first (they will alias)
    for i in sorted(rec.donated):
        aval = rec.in_avals[i]
        key = _aval_key(aval)
        if pool.get(key, 0) > 0:
            pool[key] -= 1
        else:
            yield rec.finding(
                "JX104",
                "%s (%s) is donated but no output has a matching "
                "shape/dtype — XLA frees the buffer without reusing it, "
                "and the caller lost the ability to read it for nothing"
                % (rec.label(i), _fmt_aval(aval)),
                key="donate-unaliasable:%s" % rec.label(i))

    # a program that already donates, leaving a LARGE aliasable arg
    # undonated, is leaving HBM on the table (grads kept for grad_req=add
    # are the legitimate exception — suppress or baseline those)
    if rec.donated:
        for i, aval in enumerate(rec.in_avals):
            if i in rec.donated:
                continue
            nbytes = _aval_nbytes(aval)
            if nbytes < cfg.donation_bytes:
                continue
            key = _aval_key(aval)
            if pool.get(key, 0) > 0:
                pool[key] -= 1
                yield rec.finding(
                    "JX104",
                    "%s (%s, %d bytes) aliases an output aval but is not "
                    "donated in a program that donates other args — "
                    "donating it would save one HBM-resident copy"
                    % (rec.label(i), _fmt_aval(aval), nbytes),
                    key="donate-missed:%s" % rec.label(i))

    # dead outputs: identity pass-through of an input, or a constant
    invar_pos = {id(v): i for i, v in enumerate(rec.jaxpr.invars)}
    for k, var in enumerate(rec.jaxpr.outvars):
        aval = getattr(var, "aval", None)
        if aval is None or _aval_nbytes(aval) < cfg.passthrough_bytes:
            continue
        if id(var) in invar_pos:
            i = invar_pos[id(var)]
            if i in rec.donated:
                continue   # donated pass-through: XLA aliases it, free
            yield rec.finding(
                "JX104",
                "output #%d (%s) is an unmodified pass-through of input "
                "%s — XLA must still materialize a fresh output copy; "
                "drop it from the returns and reuse the input at the "
                "call site" % (k, _fmt_aval(aval), rec.label(i)),
                key="dead-output:passthrough:%d" % k)
        elif hasattr(var, "val"):     # Literal output
            yield rec.finding(
                "JX104",
                "output #%d (%s) is a compile-time constant — computed "
                "nowhere, transferred every call" % (k, _fmt_aval(aval)),
                key="dead-output:const:%d" % k)


# ---------------------------------------------------------------------------
# JX105 retrace-explainer (runtime-only; registered for the catalogue)
# ---------------------------------------------------------------------------

TRACE_RULES["JX105"] = TraceRule(
    "JX105", "retrace-explainer",
    "on a watch_jit recompile, diff the new avals/static args against "
    "the cached variants and name the axis that changed (runtime tier, "
    "MXNET_TRACECHECK)", None)


def signature(args, kwargs):
    """Flat trace signature of a call: [(label, kind, detail...)] —
    arrays collapse to shape/dtype, everything else to type + repr."""
    import jax
    flat, treedef = jax.tree_util.tree_flatten_with_path(
        (tuple(args), dict(kwargs or {})))
    sig = []
    for path, leaf in flat:
        label = jax.tree_util.keystr(path)
        label = ("arg%s" % label[3:]) if label.startswith("[0]") \
            else ("kwarg%s" % label[3:])
        shape = getattr(leaf, "shape", None)
        dtype = getattr(leaf, "dtype", None)
        if shape is not None and dtype is not None:
            sig.append((label, "array", tuple(shape), str(dtype)))
        else:
            sig.append((label, "static", type(leaf).__name__,
                        repr(leaf)[:80]))
    return sig


def _diff_entries(old, new):
    """Human sentences for what changed between two signature entries."""
    label = new[0]
    if old[1] == "array" and new[1] == "array":
        msgs = []
        if old[2] != new[2]:
            axes = [("axis %d: %s->%s" % (d, o, n))
                    for d, (o, n) in enumerate(zip(old[2], new[2]))
                    if o != n]
            if len(old[2]) != len(new[2]):
                axes.append("rank %d->%d" % (len(old[2]), len(new[2])))
            msgs.append("%s shape %s->%s (%s)"
                        % (label, old[2], new[2], ", ".join(axes)))
        if old[3] != new[3]:
            msgs.append("%s dtype %s->%s" % (label, old[3], new[3]))
        return msgs
    if old[1] != new[1]:
        return ["%s changed kind %s->%s" % (label, old[1], new[1])]
    if old[2:] != new[2:]:
        return ["%s static value %s -> %s (each distinct hashable value "
                "is a separate compiled variant)" % (label, old[3], new[3])]
    return []


def explain_retrace(name, history, new_sig):
    """Diff *new_sig* against its closest cached variant and name the
    axis of change.  Returns the one-line diagnosis."""
    def diffs_against(old):
        old_map = {e[0]: e for e in old}
        new_map = {e[0]: e for e in new_sig}
        out = []
        for label, entry in new_map.items():
            if label in old_map:
                out.extend(_diff_entries(old_map[label], entry))
            else:
                out.append("%s appeared (structure change)" % label)
        for label in old_map:
            if label not in new_map:
                out.append("%s disappeared (structure change)" % label)
        return out

    best = min((diffs_against(old) for old in history), key=len)
    if not best:
        return ("recompile of '%s' with no visible shape/dtype/structure "
                "change — suspect weak_type promotion, sharding change, or "
                "a non-pytree closure input" % name)
    shown = "; ".join(best[:4])
    if len(best) > 4:
        shown += "; ... %d more" % (len(best) - 4)
    return ("recompile of '%s' caused by: %s — pad or bucket the changing "
            "axis so the compiled program is reused" % (name, shown))


# ---------------------------------------------------------------------------
# running rules
# ---------------------------------------------------------------------------

def run_rules(record, select=None, config=None):
    cfg = config or DEFAULT_CONFIG
    findings = []
    for code, rule in sorted(TRACE_RULES.items()):
        if select is not None and code not in select:
            continue
        findings.extend(rule.check(record, cfg))
    return findings


# ---------------------------------------------------------------------------
# AOT driver over the owned entry points
# ---------------------------------------------------------------------------

# (group, module) — each module owns jits and exposes tracecheck_programs()
# yielding (name, fn, args, kwargs) specimens for every program it ships.
ENTRY_POINTS = (
    ("kvstore", "mxnet_tpu.kvstore"),
    ("collective", "mxnet_tpu.parallel.collective"),
    ("optimizer", "mxnet_tpu.optimizer"),
    ("fused_trainer", "mxnet_tpu.gluon.fused_trainer"),
    ("executor", "mxnet_tpu.executor"),
    ("module_cached_step", "mxnet_tpu.module.cached_step"),
    ("gluon_cached_op", "mxnet_tpu.gluon.block"),
    ("predict", "mxnet_tpu.predict"),
    ("serving", "mxnet_tpu.serving.program"),
    ("guardian", "mxnet_tpu.guardian"),
    ("gluon_utils", "mxnet_tpu.gluon.utils"),
    ("pipeline", "mxnet_tpu.parallel.pipeline"),
    ("ring_attention", "mxnet_tpu.parallel.ring_attention"),
    ("sharded_trainer", "mxnet_tpu.parallel.sharded"),
    ("transformer", "mxnet_tpu.models.transformer"),
    ("model_stats", "mxnet_tpu.model_stats"),
)


def iter_owned_programs(entries=None):
    """Yield (group, ProgramRecord-or-Finding) over every owned entry
    point.  A provider that fails to build/trace yields a JX000 finding —
    silent skips would read as coverage."""
    import importlib
    for group, modpath in ENTRY_POINTS:
        if entries is not None and group not in entries:
            continue
        origin = modpath.replace(".", "/") + ".py"
        try:
            mod = importlib.import_module(modpath)
            programs = list(mod.tracecheck_programs())
        except Exception as exc:
            yield group, Finding(
                "JX000", "trace://%s" % group, 0, 0,
                "entry point provider %s failed: %r" % (modpath, exc),
                snippet="provider:%s" % group)
            continue
        for name, fn, args, kwargs in programs:
            try:
                yield group, trace_program(name, fn, args, kwargs,
                                           origin=origin)
            except Exception as exc:
                yield group, Finding(
                    "JX000", "trace://%s" % name, 0, 0,
                    "tracing '%s' (%s) failed: %r" % (name, origin, exc),
                    snippet="trace:%s" % name)


def check_entry_points(entries=None, select=None, config=None):
    """Run the JX rules over every owned program; returns (findings,
    program_names) — names prove coverage to the CI gate."""
    findings, names = [], []
    for _group, item in iter_owned_programs(entries):
        if isinstance(item, Finding):
            findings.append(item)
            continue
        names.append(item.name)
        findings.extend(run_rules(item, select=select, config=config))
    findings.sort(key=lambda f: (f.path, f.rule, f.snippet))
    return findings, names


# ---------------------------------------------------------------------------
# runtime hook (MXNET_TRACECHECK): called by telemetry on compile events
# ---------------------------------------------------------------------------

_SIG_HISTORY = {}    # (watch name, id(jit)) -> [signature, ...] (last 8)
_RUNTIME_CONFIG = DEFAULT_CONFIG


def reset_runtime():
    _SIG_HISTORY.clear()


def on_compile(name, fn, args, kwargs):
    """Analyze the program a watched jit just compiled.

    Called from ``telemetry._WatchedJit`` on cache growth when
    ``MXNET_TRACECHECK`` is truthy.  JX105 diffs the call signature
    against this name's previous variants; JX101-JX104 re-trace the
    function from specs (cheap next to the XLA compile that just
    happened).  Findings are booked into the ``tracecheck_findings``
    counter, the flight ring, and one structured log line each; this
    function never raises into the training step.
    """
    findings = []
    try:
        sig = signature(args, kwargs)
    except Exception:
        sig = None
    # keyed per jitted fn, not per watch name: distinct programs sharing
    # a name (a cached op's train/eval pair, every optimizer instance
    # under "optimizer_update_step") are separate compile caches — their
    # first compiles are not recompiles of each other
    history = _SIG_HISTORY.setdefault((name, id(fn)), [])
    if sig is not None:
        if history:
            findings.append(Finding(
                "JX105", "trace://%s" % name, 0, 0,
                explain_retrace(name, history, sig), snippet=name))
        history.append(sig)
        del history[:-8]
    try:
        record = trace_program(name, fn, args, kwargs)
        findings.extend(run_rules(record, config=_RUNTIME_CONFIG))
    except Exception:
        pass                   # analysis must never break a step
    _book(findings)
    return findings


def _book(findings):
    if not findings:
        return
    try:
        from .. import telemetry as _tel
        from ..telemetry import flight as _flight
        _tel.bump("tracecheck_findings", len(findings))
        for f in findings:
            _flight.record("tracecheck", f.rule, detail=f.message[:200])
            _LOG.warning("tracecheck %s", json.dumps(
                {"rule": f.rule, "program": f.path[len("trace://"):],
                 "finding": f.message}, sort_keys=True))
    except Exception:
        pass


