"""graftlock — static lock-graph analysis for the threaded tier.

Three rules over the shared graftlint AST facts plus the cross-module
``link_project`` call graph:

* **JG009 lock-order-cycle** — build the per-module lock-acquisition
  graph (``with self._lock:`` / ``.acquire()`` sites), propagate
  acquisitions through the call graph (including cross-module edges),
  and flag cycles in the global lock-order digraph.  A cycle means two
  threads taking the same locks in opposite orders can deadlock.
* **JG010 blocking-under-lock** — a JG007-class blocking call (socket
  recv, connection send, ``queue.get``/``Condition.wait`` without
  timeout, engine/device waits) reachable while a lock is held turns
  one slow peer into a process-wide stall.
* **JG011 unguarded-shared-mutation** — a ``self.X`` attribute written
  both from a thread-entry path (``Thread(target=...)`` / ``Timer`` /
  an escaping bound-method callback) and from a public method with no
  common guarding lock.

Lock identity
-------------
A lock is identified by its *declaring* attribute, class-qualified:
``self._lock = threading.Lock()`` inside ``class Scheduler`` is the
node ``Scheduler._lock``; module-level locks are module-qualified
(``engine._TASKS_LOCK``).  ``threading.Condition(self._lock)`` — and
the :mod:`.lockwitness` funnel's ``make_condition(self._lock, ...)`` —
alias the condition attribute to its underlying lock, so waiting on
``self._cv`` *is* holding ``Server._lock``.  An acquisition through a
receiver whose class cannot be inferred (``handle._lock`` where
``handle`` came out of a dict) counts as *held* for JG010 but
contributes no order edge: a wrong identity guess would fabricate
cycles, and a fabricated deadlock report is worse than a missed edge.

Non-blocking acquires (``acquire(blocking=False)`` or with a timeout)
take no order edge either — a trylock cannot complete a deadlock cycle
— but the lock still counts as held for everything nested under it.
``Condition.wait()`` while holding only that condition's own lock is
the sanctioned wait idiom and is exempt from JG010; the same wait
reached while any *other* lock is held is flagged.
"""
from __future__ import annotations

import ast
import re

from .core import parent
from .rules import (_facts, _fixpoint, _import_targets, _module_dotted,
                    register)

__all__ = ["link_lock_project"]

# constructor spellings that declare a lock: stdlib threading plus the
# lockwitness runtime funnel (the repo's own constructors after PR 20)
_CTOR_KIND = {
    "Lock": "lock", "RLock": "rlock", "Condition": "condition",
    "make_lock": "lock", "make_rlock": "rlock",
    "make_condition": "condition",
}
_THREADING_HEADS = ("threading", "_thread")

# sync primitives whose internal state is already thread-safe: writes to
# these attributes are not JG011 shared-mutation hazards
_PRIMITIVE_CTORS = {
    "Lock", "RLock", "Condition", "Event", "Semaphore",
    "BoundedSemaphore", "Barrier", "Queue", "LifoQueue", "PriorityQueue",
    "SimpleQueue", "local", "make_lock", "make_rlock", "make_condition",
}

# receivers that look like a lock even when undeclared (for `with x:`
# disambiguation against files/meshes/jit-disable context managers)
_LOCKISH_RE = re.compile(
    r"(^|_)(lock|rlock|mutex|cv|cond|condition)\d*$", re.IGNORECASE)

# receivers that look like a connection/socket (blocking send surface)
_CONNISH_RE = re.compile(
    r"(^|_)(conn|sock|socket|peer|sched|chan|pipe)\d*$", re.IGNORECASE)

# receivers that look like a queue (same doctrine as JG007)
_QUEUEISH_RE = re.compile(r"(^|_)(q|queue|inbox|mailbox)$", re.IGNORECASE)

_ENGINE_WAITS = {"wait_for_all", "wait_for_var", "wait_to_read",
                 "block_until_ready"}

_MUTATOR_METHODS = {"append", "extend", "add", "insert", "remove",
                    "discard", "pop", "popleft", "popitem", "clear",
                    "update", "setdefault", "appendleft"}

# names collections/stdlib primitives answer to: never resolve these via
# the unique-method-owner fallback
_GENERIC_METHODS = {"get", "put", "wait", "notify", "notify_all", "join",
                    "send", "recv", "close", "items", "keys", "values",
                    "copy", "start", "cancel", "set", "read", "write"}

_THREAD_CTOR_RE = re.compile(r"(^|\.)(Thread|Timer)$")


# ---------------------------------------------------------------------------
# project model
# ---------------------------------------------------------------------------

class _ClassInfo:
    def __init__(self, name, mod, node):
        self.name = name
        self.mod = mod
        self.node = node
        self.locks = {}            # attr -> kind
        self.cond_alias = {}       # condition attr -> underlying lock attr
        self.attr_types = {}       # attr -> class name (self.x = Foo())
        self.primitive_attrs = set()
        self.methods = {}          # name -> [(mod, FunctionDef)]

    def lock_id(self, attr):
        seen = set()
        while attr in self.cond_alias and attr not in seen:
            seen.add(attr)
            attr = self.cond_alias[attr]
        return "%s.%s" % (self.name, attr)


class _FuncScan:
    """Per-function summary: what it acquires, where it blocks, whom it
    calls (with the locks held at each point), and what it mutates."""

    def __init__(self, fkey, mod, fd, cls):
        self.fkey = fkey
        self.mod = mod
        self.fd = fd
        self.cls = cls
        self.local_types = {}
        self.acquires = []    # (lock_id|None, label, node, held, blocking)
        self.blockings = []   # (desc, node, held, exempt)
        self.calls = []       # (call_node, held)
        self.call_targets = {}    # id(call_node) -> [callee fkeys]
        self.mutations = []   # (attr, node, held)
        self.acq_closure = set()
        self.block_closure = {}   # desc -> "path:line"
        self.caller_guard = None  # locks held at EVERY call site, or None


class _Project:
    """One linked analysis over every module in the scan."""

    def __init__(self, mods):
        self.mods = mods
        self.classes = {}          # class name -> _ClassInfo (first wins)
        self.module_locks = {}     # (modtail, name) -> kind
        self.lock_decl_attr = {}   # attr -> {class names declaring it}
        self.method_owners = {}    # method name -> {class names}
        self.funcs = {}            # fkey -> _FuncScan
        self.edges = {}            # (held_id, acquired_id) -> (mod, node)
        self.findings = {}         # mod -> rule -> [(node, message)]
        self.modnames = {}         # mod -> dotted name
        self.modtails = {}         # mod -> short name
        for mod in mods:
            dotted = _module_dotted(mod.path) or mod.path
            self.modnames[mod] = dotted
            self.modtails[mod] = dotted.rsplit(".", 1)[-1]
            self.findings[mod] = {"JG009": [], "JG010": [], "JG011": []}

    def book(self, rule, mod, node, message):
        self.findings[mod][rule].append((node, message))


def _held_ids(held):
    return frozenset(h[0] for h in held if h[0] is not None)


def _held_names(held):
    out = []
    for h in held:
        name = h[0] or h[1]
        if name not in out:
            out.append(name)
    return out


# ---------------------------------------------------------------------------
# pass 1: declarations (locks, aliases, attribute types, methods)
# ---------------------------------------------------------------------------

def _ctor_kind(facts, value):
    if not isinstance(value, ast.Call):
        return None
    qual = facts.qualname(value.func)
    if qual is None:
        return None
    last = qual.rsplit(".", 1)[-1]
    if last in ("Lock", "RLock", "Condition"):
        # require a threading base so e.g. multiprocessing.Lock or a
        # project class named Lock does not register as one
        head = qual.split(".")[0].lstrip(".")
        if head in _THREADING_HEADS or "lockwitness" in qual:
            return _CTOR_KIND[last]
        return None
    return _CTOR_KIND.get(last)


def _is_primitive_ctor(facts, value):
    if not isinstance(value, ast.Call):
        return False
    qual = facts.qualname(value.func)
    return qual is not None \
        and qual.rsplit(".", 1)[-1] in _PRIMITIVE_CTORS


def _enclosing_class(node):
    p = parent(node)
    while p is not None:
        if isinstance(p, ast.ClassDef):
            return p
        if isinstance(p, (ast.FunctionDef, ast.AsyncFunctionDef)):
            # a method's statements belong to the class; keep climbing
            p = parent(p)
            continue
        if isinstance(p, ast.Module):
            return None
        p = parent(p)
    return None


def _inside_function(node):
    p = parent(node)
    while p is not None:
        if isinstance(p, (ast.FunctionDef, ast.AsyncFunctionDef,
                          ast.Lambda)):
            return True
        p = parent(p)
    return False


def _cond_underlying(call):
    """The ``self.X`` attr a Condition/make_condition wraps, if any."""
    cands = list(call.args[:1]) + \
        [kw.value for kw in call.keywords if kw.arg == "lock"]
    for arg in cands:
        if isinstance(arg, ast.Attribute) \
                and isinstance(arg.value, ast.Name) \
                and arg.value.id == "self":
            return arg.attr
    return None


def _collect_declarations(proj):
    for mod in proj.mods:
        facts = _facts(mod)
        tail = proj.modtails[mod]
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.ClassDef):
                info = proj.classes.setdefault(
                    node.name, _ClassInfo(node.name, mod, node))
                for item in node.body:
                    if isinstance(item, (ast.FunctionDef,
                                         ast.AsyncFunctionDef)):
                        info.methods.setdefault(item.name, []).append(
                            (mod, item))
                        proj.method_owners.setdefault(
                            item.name, set()).add(node.name)
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Assign) or len(node.targets) != 1:
                continue
            tgt, val = node.targets[0], node.value
            kind = _ctor_kind(facts, val)
            if isinstance(tgt, ast.Attribute) \
                    and isinstance(tgt.value, ast.Name) \
                    and tgt.value.id == "self":
                cls = _enclosing_class(node)
                if cls is None or cls.name not in proj.classes:
                    continue
                info = proj.classes[cls.name]
                if kind is not None:
                    info.locks[tgt.attr] = kind
                    proj.lock_decl_attr.setdefault(
                        tgt.attr, set()).add(cls.name)
                    if kind == "condition":
                        under = _cond_underlying(val)
                        if under is not None:
                            info.cond_alias[tgt.attr] = under
                if _is_primitive_ctor(facts, val):
                    info.primitive_attrs.add(tgt.attr)
                if isinstance(val, ast.Call) \
                        and isinstance(val.func, ast.Name):
                    info.attr_types[tgt.attr] = val.func.id
            elif isinstance(tgt, ast.Name) and kind is not None \
                    and _enclosing_class(node) is None \
                    and not _inside_function(node):
                proj.module_locks[(tail, tgt.id)] = kind


# ---------------------------------------------------------------------------
# pass 2: per-function scan with lexical held-sets
# ---------------------------------------------------------------------------

def _recv_name(expr):
    """Rightmost simple name of a receiver (``self.a.b`` -> "b")."""
    if isinstance(expr, ast.Attribute):
        return expr.attr
    if isinstance(expr, ast.Name):
        return expr.id
    return None


def _expr_label(expr):
    parts = []
    node = expr
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
    elif not parts:
        return None
    return ".".join(reversed(parts))


def _local_types(fd, proj):
    """name -> class for ``x = ClassName(...)`` assignments in *fd*."""
    out = {}
    for node in ast.walk(fd):
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name) \
                and isinstance(node.value, ast.Call) \
                and isinstance(node.value.func, ast.Name) \
                and node.value.func.id in proj.classes:
            out[node.targets[0].id] = node.value.func.id
    return out


def _resolve_lock_expr(proj, scan, expr):
    """(lock_id, label) for an expression used as a lock; (None, label)
    when it is lock-like but unresolvable; (None, None) when it is not a
    lock at all."""
    label = _expr_label(expr)
    tail = proj.modtails[scan.mod]
    if isinstance(expr, ast.Attribute) and isinstance(expr.value,
                                                     ast.Name):
        base, attr = expr.value.id, expr.attr
        if base == "self":
            cls = scan.cls
            if cls is not None and attr in cls.locks:
                return cls.lock_id(attr), label
        else:
            cname = scan.local_types.get(base)
            if cname is None and scan.cls is not None:
                cname = scan.cls.attr_types.get(base)
            if cname is not None and cname in proj.classes \
                    and attr in proj.classes[cname].locks:
                return proj.classes[cname].lock_id(attr), label
        owners = proj.lock_decl_attr.get(attr)
        if owners is not None and len(owners) == 1:
            return proj.classes[next(iter(owners))].lock_id(attr), label
        return (None, label) if _LOCKISH_RE.search(attr) else (None, None)
    if isinstance(expr, ast.Attribute):        # deeper chain: self.a.b
        attr = expr.attr
        owners = proj.lock_decl_attr.get(attr)
        if owners is not None and len(owners) == 1:
            return proj.classes[next(iter(owners))].lock_id(attr), label
        return (None, label) if _LOCKISH_RE.search(attr) else (None, None)
    if isinstance(expr, ast.Name):
        if (tail, expr.id) in proj.module_locks:
            return "%s.%s" % (tail, expr.id), label
        return (None, label) if _LOCKISH_RE.search(expr.id) \
            else (None, None)
    return None, None


def _timeout_kw(call, names=("timeout",)):
    for kw in call.keywords:
        if kw.arg in names:
            return kw
    return None


def _is_none(node):
    return isinstance(node, ast.Constant) and node.value is None


def _nonblocking_acquire(call):
    """acquire(blocking=False) / acquire(0) / acquire(timeout=...)."""
    if call.args:
        a0 = call.args[0]
        if isinstance(a0, ast.Constant) and not a0.value:
            return True
        if len(call.args) > 1:      # positional timeout
            return True
    for kw in call.keywords:
        if kw.arg == "blocking" and isinstance(kw.value, ast.Constant) \
                and not kw.value.value:
            return True
        if kw.arg == "timeout" and not _is_none(kw.value):
            return True
    return False


def _blocking_desc(call):
    """(description, cond_receiver) when *call* is a JG007-class blocking
    call, else None.  *cond_receiver* is the ``X`` of ``X.wait()`` so the
    caller can apply the wait-on-own-lock exemption."""
    func = call.func
    if not isinstance(func, ast.Attribute):
        return None
    attr = func.attr
    rname = _recv_name(func.value) or ""
    if attr == "recv":
        kw = _timeout_kw(call)
        if kw is None or _is_none(kw.value):
            return ("unbounded %s.recv()" % (rname or "peer"), None)
        return None
    if attr in ("send", "sendall"):
        if _CONNISH_RE.search(rname):
            return ("%s.%s() peer write" % (rname, attr), None)
        return None
    if attr == "get":
        if not _QUEUEISH_RE.search(rname):
            return None
        blockkw = next((k for k in call.keywords if k.arg == "block"),
                       None)
        if blockkw is not None and isinstance(blockkw.value,
                                              ast.Constant) \
                and not blockkw.value.value:
            return None
        if _timeout_kw(call) is not None:
            return None
        if len(call.args) > 1 and not _is_none(call.args[1]):
            return None               # get(block, timeout)
        return ("%s.get() without timeout" % rname, None)
    if attr == "join":
        if not call.args and not call.keywords:
            return ("%s.join() without timeout" % (rname or "thread"),
                    None)
        return None
    if attr == "wait":
        if not call.args and _timeout_kw(call) is None:
            return ("%s.wait() without timeout" % (rname or "event"),
                    func.value)
        return None
    if attr == "wait_for":
        if len(call.args) < 2 and _timeout_kw(call) is None:
            return ("%s.wait_for() without timeout" % (rname or "cond"),
                    func.value)
        return None
    if attr in _ENGINE_WAITS:
        return ("%s() engine/device wait" % attr, None)
    if attr == "accept":
        return ("%s.accept()" % (rname or "socket"), None)
    return None


def _own_nodes(node):
    """Walk *node* without descending into nested function bodies: a
    nested def runs on its own schedule, not under the enclosing held
    set (it is scanned separately as its own function)."""
    stack = [node]
    while stack:
        n = stack.pop()
        yield n
        for c in ast.iter_child_nodes(n):
            if isinstance(c, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.Lambda)):
                continue
            stack.append(c)


class _Scanner:
    def __init__(self, proj, scan):
        self.proj = proj
        self.scan = scan

    def run(self):
        if isinstance(self.scan.fd, ast.Lambda):
            return
        self.stmts(self.scan.fd.body, ())

    # -- statement walk with lexical held-sets ------------------------------

    def stmts(self, body, held):
        for stmt in body:
            held = self.stmt(stmt, held)

    def stmt(self, stmt, held):
        """Process one statement; returns the held-set for statements
        after it in the same suite (grows across a bare ``.acquire()``
        until the matching ``.release()`` or the end of the suite)."""
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            return held
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            inner = held
            for item in stmt.items:
                self._exprs(item.context_expr, inner)
                lid, label = _resolve_lock_expr(self.proj, self.scan,
                                                item.context_expr)
                if lid is not None or label is not None:
                    self.scan.acquires.append(
                        (lid, label, item.context_expr, inner, True))
                    inner = inner + ((lid, label, item.context_expr),)
            self.stmts(stmt.body, inner)
            return held
        if isinstance(stmt, ast.If):
            self._exprs(stmt.test, held)
            self.stmts(stmt.body, held)
            self.stmts(stmt.orelse, held)
            return held
        if isinstance(stmt, (ast.For, ast.AsyncFor)):
            self._exprs(stmt.iter, held)
            self.stmts(stmt.body, held)
            self.stmts(stmt.orelse, held)
            return held
        if isinstance(stmt, ast.While):
            self._exprs(stmt.test, held)
            self.stmts(stmt.body, held)
            self.stmts(stmt.orelse, held)
            return held
        if isinstance(stmt, ast.Try):
            self.stmts(stmt.body, held)
            for h in stmt.handlers:
                self.stmts(h.body, held)
            self.stmts(stmt.orelse, held)
            self.stmts(stmt.finalbody, held)
            return held
        call = self._bare_call(stmt)
        if call is not None and isinstance(call.func, ast.Attribute):
            if call.func.attr == "acquire":
                lid, label = _resolve_lock_expr(self.proj, self.scan,
                                                call.func.value)
                if lid is not None or label is not None:
                    self.scan.acquires.append(
                        (lid, label, call, held,
                         not _nonblocking_acquire(call)))
                    return held + ((lid, label, call),)
            elif call.func.attr == "release":
                lid, label = _resolve_lock_expr(self.proj, self.scan,
                                                call.func.value)
                return tuple(h for h in held
                             if not (h[0] == lid and h[1] == label))
        self._exprs(stmt, held)
        return held

    @staticmethod
    def _bare_call(stmt):
        if isinstance(stmt, ast.Expr) and isinstance(stmt.value,
                                                     ast.Call):
            return stmt.value
        return None

    # -- event recording ----------------------------------------------------

    def _exprs(self, root, held):
        """Record blocking calls, call sites, and self-attr mutations in
        the expression nodes of one statement."""
        scan = self.scan
        for node in _own_nodes(root):
            if isinstance(node, ast.Call):
                func = node.func
                if isinstance(func, ast.Attribute) \
                        and func.attr in ("acquire", "release", "locked"):
                    continue          # handled by the statement walk
                desc = None
                if not self._is_project_method(func):
                    desc = _blocking_desc(node)
                if desc is not None:
                    text, cond_expr = desc
                    exempt = False
                    cond_lid = None
                    if cond_expr is not None:
                        lid, label = _resolve_lock_expr(self.proj, scan,
                                                        cond_expr)
                        cond_lid = lid
                        own = lid if lid is not None else label
                        if held:
                            exempt = not [h for h in held
                                          if (h[0] or h[1]) != own]
                    scan.blockings.append(
                        (text, node, held, exempt, cond_lid))
                else:
                    scan.calls.append((node, held))
            elif isinstance(node, (ast.Assign, ast.AugAssign)):
                targets = node.targets if isinstance(node, ast.Assign) \
                    else [node.target]
                for tgt in targets:
                    attr = self._self_attr(tgt)
                    if attr is not None:
                        scan.mutations.append((attr, node, held))
            elif isinstance(node, ast.Attribute) \
                    and node.attr in _MUTATOR_METHODS:
                par = parent(node)
                if isinstance(par, ast.Call) and par.func is node:
                    attr = self._self_attr_base(node.value)
                    if attr is not None:
                        scan.mutations.append((attr, node, held))

    def _is_project_method(self, func):
        """``self.wait()`` where the class defines ``wait`` is a method
        call for the call graph, not a stdlib blocking primitive (the
        callee's own blockings propagate through the closure instead)."""
        if not (isinstance(func, ast.Attribute)
                and isinstance(func.value, ast.Name)):
            return False
        if func.value.id == "self":
            cls = self.scan.cls
            return cls is not None and func.attr in cls.methods
        cname = self.scan.local_types.get(func.value.id)
        if cname is None and self.scan.cls is not None:
            cname = self.scan.cls.attr_types.get(func.value.id)
        return cname is not None and cname in self.proj.classes \
            and func.attr in self.proj.classes[cname].methods

    @staticmethod
    def _self_attr(tgt):
        """``self.X`` / ``self.X[...]`` assignment target -> "X"."""
        if isinstance(tgt, ast.Subscript):
            tgt = tgt.value
        if isinstance(tgt, ast.Attribute) \
                and isinstance(tgt.value, ast.Name) \
                and tgt.value.id == "self":
            return tgt.attr
        return None

    @staticmethod
    def _self_attr_base(expr):
        """``self.X.append(...)`` receiver -> "X"."""
        if isinstance(expr, ast.Attribute) \
                and isinstance(expr.value, ast.Name) \
                and expr.value.id == "self":
            return expr.attr
        return None


# ---------------------------------------------------------------------------
# pass 3: call graph + closures
# ---------------------------------------------------------------------------

def _resolve_call(proj, scan, call, imports, defs_by_mod, index):
    """fkeys a call may land in: same-class methods, same-module defs,
    imported defs (cross-module), or a unique-named method project-wide."""
    func = call.func
    out = []
    if isinstance(func, ast.Attribute) and isinstance(func.value,
                                                      ast.Name) \
            and func.value.id == "self" and scan.cls is not None:
        for _m_mod, m_fd in scan.cls.methods.get(func.attr, ()):
            out.append(id(m_fd))
        if out:
            return out
    if isinstance(func, ast.Name):
        modname = proj.modnames[scan.mod]
        for fd in defs_by_mod.get(modname, {}).get(func.id, ()):
            out.append(id(fd))
        if out:
            return out
        tgt = imports.get(func.id)
        if tgt is not None:
            for cut in range(len(tgt) - 1, 0, -1):
                m = ".".join(tgt[:cut])
                if m in index:
                    for fd in defs_by_mod.get(m, {}).get(tgt[cut], ()):
                        out.append(id(fd))
                    return out
        return out
    if isinstance(func, ast.Attribute):
        base = _expr_label(func.value)
        if base is not None and "." not in base:
            tgt = imports.get(base)
            if tgt is not None:
                for cut in range(len(tgt), 0, -1):
                    m = ".".join(tgt[:cut])
                    if m in index:
                        for fd in defs_by_mod.get(m, {}).get(
                                func.attr, ()):
                            out.append(id(fd))
                        if out:
                            return out
        cname = None
        if isinstance(func.value, ast.Name):
            cname = scan.local_types.get(func.value.id)
        elif isinstance(func.value, ast.Attribute) \
                and isinstance(func.value.value, ast.Name) \
                and func.value.value.id == "self" \
                and scan.cls is not None:
            cname = scan.cls.attr_types.get(func.value.attr)
        if cname is None and func.attr not in _MUTATOR_METHODS \
                and func.attr not in _GENERIC_METHODS:
            # unique-name fallback — but never for names that collections
            # and stdlib primitives also answer to (``b.waiting.discard``
            # is a set method, not OverlapSession.discard)
            owners = proj.method_owners.get(func.attr)
            if owners is not None and len(owners) == 1:
                cname = next(iter(owners))
        if cname is not None and cname in proj.classes:
            for _m_mod, m_fd in proj.classes[cname].methods.get(
                    func.attr, ()):
                out.append(id(m_fd))
    return out


def _compute_closures(proj, call_edges):
    """Fixpoint acquire- and blocking-closures over the call graph."""
    changed = True
    while changed:
        changed = False
        for fkey, scan in proj.funcs.items():
            acq = set(lid for (lid, _lab, _n, _h, _b) in scan.acquires
                      if lid is not None)
            blk = {}
            for desc, node, _held, _exempt, cond_lid in scan.blockings:
                if cond_lid is not None:
                    # a wait on a Condition tied to a known project lock
                    # RELEASES that lock — callers holding it are the
                    # intended wait pattern (Server._wait_key), not a
                    # stall; keep it out of the call-graph closure
                    continue
                blk.setdefault(desc, "%s:%d" % (scan.mod.path,
                                                node.lineno))
            for callee in call_edges.get(fkey, ()):
                sub = proj.funcs.get(callee)
                if sub is None:
                    continue
                acq |= sub.acq_closure
                for desc, site in sub.block_closure.items():
                    blk.setdefault(desc, site)
            if acq != scan.acq_closure:
                scan.acq_closure = acq
                changed = True
            if blk != scan.block_closure:
                scan.block_closure = blk
                changed = True


# ---------------------------------------------------------------------------
# pass 4: findings
# ---------------------------------------------------------------------------

def _compute_caller_guards(proj):
    """For each function, the locks held at EVERY project call site
    (``Server._apply`` only ever runs under ``Server._lock``, so its
    mutations count as guarded).  Intersection fixpoint: start unknown
    (None = ⊤) and narrow with each caller's effective held-set; a
    function with no known callers — or used as a thread target — gets
    the empty guard."""
    for _fkey, scan in proj.funcs.items():
        scan.caller_guard = None
    changed = True
    rounds = 0
    while changed and rounds < 20:
        changed = False
        rounds += 1
        incoming = {}
        for fkey, scan in proj.funcs.items():
            base = scan.caller_guard or frozenset()
            for call, held in scan.calls:
                eff = _held_ids(held) | base
                for callee in scan.call_targets.get(id(call), ()):
                    prev = incoming.get(callee)
                    incoming[callee] = eff if prev is None \
                        else (prev & eff)
        for fkey, scan in proj.funcs.items():
            new = frozenset(incoming.get(fkey) or ())
            if new != (scan.caller_guard
                       if scan.caller_guard is not None else None):
                scan.caller_guard = new
                changed = True


def _order_edges(proj):
    """held-lock -> acquired-lock edges, attributed to their sites."""
    for fkey, scan in proj.funcs.items():
        for lid, _label, node, held, blocking in scan.acquires:
            if lid is None or not blocking:
                continue
            for h in _held_ids(held):
                if h != lid:
                    proj.edges.setdefault((h, lid), (scan.mod, node))
        for call, held in scan.calls:
            hids = _held_ids(held)
            if not hids:
                continue
            for callee in scan.call_targets.get(id(call), ()):
                sub = proj.funcs.get(callee)
                if sub is None:
                    continue
                for lid in sub.acq_closure:
                    for h in hids:
                        if h != lid:
                            proj.edges.setdefault((h, lid),
                                                  (scan.mod, call))


def _find_cycles(proj):
    """One JG009 finding per strongly-connected lock cluster."""
    adj = {}
    for (a, b) in proj.edges:
        adj.setdefault(a, set()).add(b)
    nodes = sorted(set(adj) | {b for (_a, b) in proj.edges})

    index_of, low, on_stack = {}, {}, set()
    stack, sccs, counter = [], [], [0]

    for root in nodes:
        if root in index_of:
            continue
        work = [(root, iter(sorted(adj.get(root, ()))))]
        index_of[root] = low[root] = counter[0]
        counter[0] += 1
        stack.append(root)
        on_stack.add(root)
        while work:
            node, it = work[-1]
            advanced = False
            for w in it:
                if w not in index_of:
                    index_of[w] = low[w] = counter[0]
                    counter[0] += 1
                    stack.append(w)
                    on_stack.add(w)
                    work.append((w, iter(sorted(adj.get(w, ())))))
                    advanced = True
                    break
                elif w in on_stack:
                    low[node] = min(low[node], index_of[w])
            if advanced:
                continue
            work.pop()
            if low[node] == index_of[node]:
                comp = []
                while True:
                    w = stack.pop()
                    on_stack.discard(w)
                    comp.append(w)
                    if w == node:
                        break
                if len(comp) > 1:
                    sccs.append(sorted(comp))
            if work:
                low[work[-1][0]] = min(low[work[-1][0]], low[node])

    for comp in sorted(sccs):
        members = set(comp)
        start = comp[0]
        path, seen = [start], {start}
        node = start
        while True:
            nxts = [w for w in sorted(adj.get(node, ()))
                    if w in members and w not in seen] \
                or ([start] if start in adj.get(node, ()) else [])
            if not nxts:
                break
            node = nxts[0]
            if node == start:
                break
            path.append(node)
            seen.add(node)
        cycle = " -> ".join(path + [start])
        witness = None
        for a, b in zip(path, path[1:] + [start]):
            witness = proj.edges.get((a, b))
            if witness is not None:
                break
        if witness is None:
            witness = next(v for k, v in sorted(proj.edges.items())
                           if k[0] in members and k[1] in members)
        mod, node_ = witness
        proj.book(
            "JG009", mod, node_,
            "lock-order cycle: %s — threads taking these locks in "
            "opposite orders can deadlock; pick one global acquisition "
            "order" % cycle)


def _blocking_findings(proj):
    for fkey, scan in proj.funcs.items():
        for desc, node, held, exempt, _cond_lid in scan.blockings:
            if exempt or not held:
                continue
            proj.book(
                "JG010", scan.mod, node,
                "blocking call (%s) while holding %s — one stalled "
                "peer wedges every thread contending for the lock; "
                "move the call outside the critical section"
                % (desc, ", ".join(_held_names(held))))
        for call, held in scan.calls:
            if not held:
                continue
            for callee in scan.call_targets.get(id(call), ()):
                sub = proj.funcs.get(callee)
                if sub is None or not sub.block_closure:
                    continue
                desc, site = sorted(sub.block_closure.items())[0]
                proj.book(
                    "JG010", scan.mod, call,
                    "call to %s() may block (%s at %s) while holding "
                    "%s — move the call outside the critical section"
                    % (getattr(sub.fd, "name", "?"), desc, site,
                       ", ".join(_held_names(held))))
                break


def _thread_targets(proj):
    """(class name, method name) pairs used as thread entry points or
    escaping bound-method callbacks."""
    entries = set()
    for fkey, scan in proj.funcs.items():
        facts = _facts(scan.mod)
        for node in _own_nodes(scan.fd):
            if not isinstance(node, ast.Call):
                continue
            qual = facts.qualname(node.func)
            cand = []
            if qual is not None and _THREAD_CTOR_RE.search(qual):
                for kw in node.keywords:
                    if kw.arg == "target":
                        cand.append(kw.value)
                if qual.endswith("Timer") and len(node.args) > 1:
                    cand.append(node.args[1])
            else:
                # escaping bound-method callback: self.m / obj.m handed
                # to anything (registered hooks, accept loops, executors)
                for arg in list(node.args) + [k.value
                                              for k in node.keywords]:
                    if isinstance(arg, ast.Attribute) \
                            and isinstance(arg.value, ast.Name):
                        cand.append(arg)
            for c in cand:
                if not (isinstance(c, ast.Attribute)
                        and isinstance(c.value, ast.Name)):
                    continue
                if c.value.id == "self" and scan.cls is not None:
                    if c.attr in scan.cls.methods:
                        entries.add((scan.cls.name, c.attr))
                    continue
                cname = scan.local_types.get(c.value.id)
                if cname is not None and cname in proj.classes \
                        and c.attr in proj.classes[cname].methods:
                    entries.add((cname, c.attr))
    return entries


def _method_closure(proj, cls, seeds):
    """Methods of *cls* reachable from *seeds* via same-class calls."""
    edges = {}
    for mname, impls in cls.methods.items():
        outs = set()
        for _m_mod, m_fd in impls:
            scan = proj.funcs.get(id(m_fd))
            if scan is None:
                continue
            for call, _held in scan.calls:
                f = call.func
                if isinstance(f, ast.Attribute) \
                        and isinstance(f.value, ast.Name) \
                        and f.value.id == "self" \
                        and f.attr in cls.methods:
                    outs.add(f.attr)
        edges[mname] = outs
    return _fixpoint(set(seeds) & set(cls.methods), edges)


def _mutation_findings(proj):
    by_cls = {}
    for cname, mname in _thread_targets(proj):
        by_cls.setdefault(cname, set()).add(mname)
    for cname, seeds in sorted(by_cls.items()):
        cls = proj.classes.get(cname)
        if cls is None:
            continue
        entry_methods = _method_closure(proj, cls, seeds)
        public = {m for m in cls.methods if not m.startswith("_")}
        public_methods = _method_closure(proj, cls, public)
        sides = {"entry": {}, "public": {}}
        for mname, impls in cls.methods.items():
            if mname in ("__init__", "__new__"):
                continue
            in_entry = mname in entry_methods
            in_public = mname in public_methods
            if not (in_entry or in_public):
                continue
            for m_mod, m_fd in impls:
                scan = proj.funcs.get(id(m_fd))
                if scan is None:
                    continue
                # a private helper only ever invoked under a lock is
                # guarded by its callers; thread seeds and directly
                # public methods get no such credit (their callers —
                # Thread.run, external code — hold nothing)
                inherited = scan.caller_guard or frozenset()
                for attr, node, held in scan.mutations:
                    if attr in cls.primitive_attrs or attr in cls.locks:
                        continue
                    guards = frozenset(h[0] or h[1] for h in held)
                    if in_entry:
                        e_guards = guards if mname in seeds \
                            else guards | inherited
                        sides["entry"].setdefault(attr, []).append(
                            (mname, node, e_guards, scan.mod))
                    if in_public:
                        p_guards = guards if mname in public \
                            else guards | inherited
                        sides["public"].setdefault(attr, []).append(
                            (mname, node, p_guards, scan.mod))
        for attr in sorted(set(sides["entry"]) & set(sides["public"])):
            done = False
            for e_name, e_node, e_guards, _e_mod in sides["entry"][attr]:
                if done:
                    break
                for p_name, p_node, p_guards, p_mod in \
                        sides["public"][attr]:
                    if e_name == p_name:
                        continue
                    if e_guards & p_guards:
                        continue
                    proj.book(
                        "JG011", p_mod, p_node,
                        "self.%s is written by thread-entry path %s.%s "
                        "(line %d) and by public %s.%s with no common "
                        "lock — guard both sides with one lock"
                        % (attr, cname, e_name, e_node.lineno, cname,
                           p_name))
                    done = True
                    break


# ---------------------------------------------------------------------------
# driver
# ---------------------------------------------------------------------------

def link_lock_project(mods):
    """Run the whole-project lock analysis once and stash per-module
    findings on each SourceModule (``mod._graftlock``).  Called from
    ``rules.link_project`` for multi-module scans and lazily by the
    rule bodies for single-module lints."""
    proj = _Project(mods)
    _collect_declarations(proj)

    index = {}
    defs_by_mod = {}
    for mod in mods:
        modname = proj.modnames[mod]
        index[modname] = mod
        by_name = {}
        for fd in _facts(mod).funcdefs:
            by_name.setdefault(fd.name, []).append(fd)
        defs_by_mod[modname] = by_name

    for mod in mods:
        for fd in _facts(mod).funcdefs:
            cls_node = _enclosing_class(fd)
            cls = proj.classes.get(cls_node.name) \
                if cls_node is not None else None
            scan = _FuncScan(id(fd), mod, fd, cls)
            scan.local_types = _local_types(fd, proj)
            proj.funcs[id(fd)] = scan
            _Scanner(proj, scan).run()

    call_edges = {}
    for fkey, scan in proj.funcs.items():
        imports = _import_targets(scan.mod, proj.modnames[scan.mod])
        outs = set()
        for call, _held in scan.calls:
            targets = [tkey for tkey in
                       _resolve_call(proj, scan, call, imports,
                                     defs_by_mod, index)
                       if tkey != fkey]
            scan.call_targets[id(call)] = targets
            outs.update(targets)
        call_edges[fkey] = outs

    _compute_closures(proj, call_edges)
    _compute_caller_guards(proj)
    _order_edges(proj)
    _find_cycles(proj)
    _blocking_findings(proj)
    _mutation_findings(proj)

    for mod in mods:
        mod._graftlock = proj.findings[mod]
    return proj


def _ensure(mod):
    booked = getattr(mod, "_graftlock", None)
    if booked is None:
        link_lock_project([mod])
        booked = mod._graftlock
    return booked


@register("JG009", "lock-order-cycle",
          "two threads taking the same locks in opposite orders can "
          "deadlock; the global lock-order graph must stay acyclic")
def _jg009(mod, facts):
    for node, msg in _ensure(mod)["JG009"]:
        yield mod.finding("JG009", node, msg)


@register("JG010", "blocking-under-lock",
          "an unbounded blocking call inside a critical section turns "
          "one slow peer into a process-wide stall")
def _jg010(mod, facts):
    for node, msg in _ensure(mod)["JG010"]:
        yield mod.finding("JG010", node, msg)


@register("JG011", "unguarded-shared-mutation",
          "an attribute written from both a thread-entry path and a "
          "public method needs one common guarding lock")
def _jg011(mod, facts):
    for node, msg in _ensure(mod)["JG011"]:
        yield mod.finding("JG011", node, msg)
