"""Runtime lock-order witness (``MXNET_LOCKCHECK``) for the threaded tier.

The static side (:mod:`.lockcheck`) proves the lock-order graph the AST
admits is acyclic; this module witnesses the graph the *process*
actually walks.  The repo's threaded tier constructs its locks through
the funnel below (``make_lock`` / ``make_rlock`` / ``make_condition``)
instead of bare ``threading`` constructors.  Off (the default) the
funnel returns plain stdlib primitives — the only cost anywhere is one
cached module-level mode check at construction time, nothing per
acquire.  Under ``MXNET_LOCKCHECK=warn`` (or ``=1`` to raise) every
funnel lock is wrapped: each blocking acquire while other tracked locks
are held records a ``held -> acquired`` edge in a process-global
acquisition-order graph, and an edge that completes a cycle (the ABBA
inversion) fires a structured violation — one warning per edge, a
``lockcheck_violations`` telemetry bump, a flight-ring event, and an
exception under ``=1`` so tests fail loudly.

The chaos tier calls :func:`note_blocking` from its delayed/stalled
``conn.send``/``conn.recv`` seams, so any lock held across a delayed
peer write shows up in the report — running ``tools/chaos_smoke.py`` or
``tools/fleet_smoke.py`` with ``MXNET_LOCKCHECK=1`` doubles as a
lock-order witness for the whole dist/serving stack (both export
:func:`snapshot`, which must come back ``cycle_free``).

Mode is sampled once at import (``refresh_from_env`` / ``configure``
re-sample for tests).  Wrapped locks interoperate with
``threading.Condition``: the wrapper exposes ``acquire``/``release``/
``_is_owned``, so ``Condition.wait`` releases and re-acquires through
the tracked path and the held-stack stays truthful across waits.
"""
from __future__ import annotations

import os
import threading
import warnings

__all__ = ["enabled", "mode", "configure", "refresh_from_env",
           "make_lock", "make_rlock", "make_condition", "held_locks",
           "note_blocking", "snapshot", "reset", "violations"]


def _env_mode():
    raw = os.environ.get("MXNET_LOCKCHECK", "").strip().lower()
    if raw in ("1", "true", "on", "yes", "raise"):
        return "raise"
    if raw == "warn":
        return "warn"
    return "off"


_MODE = _env_mode()

_tls = threading.local()

# the witness's own bookkeeping lock is a PLAIN lock on purpose: it must
# never appear in the graph it guards
_graph_lock = threading.Lock()
_edges = {}        # (held_name, acquired_name) -> edge record dict
_adj = {}          # held_name -> set(acquired_name)
_violations = []   # violation record dicts
_warned = set()    # (a, b) pairs already warned (warn mode)
_blocked = []      # note_blocking reports (site, held) dicts


class LockOrderError(RuntimeError):
    """An acquisition-order inversion detected live (MXNET_LOCKCHECK=1)."""


def mode():
    return _MODE


def enabled():
    return _MODE != "off"


def configure(new_mode):
    """Set the witness mode programmatically ("off" | "warn" | "raise").

    Only locks constructed *after* enabling are tracked — re-create the
    objects under test after calling this."""
    global _MODE
    if new_mode not in ("off", "warn", "raise"):
        raise ValueError("MXNET_LOCKCHECK mode must be off/warn/raise, "
                         "got %r" % (new_mode,))
    _MODE = new_mode


def refresh_from_env():
    global _MODE
    _MODE = _env_mode()
    return _MODE


def reset():
    """Drop the recorded graph and violations (test isolation)."""
    with _graph_lock:
        _edges.clear()
        _adj.clear()
        del _violations[:]
        _warned.clear()
        del _blocked[:]


# ---------------------------------------------------------------------------
# internals
# ---------------------------------------------------------------------------

def _site():
    """file:line(function) of the first frame outside this module."""
    import sys
    f = sys._getframe(2)
    here = __file__
    while f is not None and f.f_code.co_filename == here:
        f = f.f_back
    if f is None:       # pragma: no cover - defensive
        return "<unknown>"
    return "%s:%d(%s)" % (os.path.basename(f.f_code.co_filename),
                          f.f_lineno, f.f_code.co_name)


def _held_stack():
    stack = getattr(_tls, "held", None)
    if stack is None:
        stack = _tls.held = []
    return stack


def _find_path(src, dst):
    """A path src -> ... -> dst in the recorded graph, or None."""
    seen = {src}
    stack = [(src, [src])]
    while stack:
        node, path = stack.pop()
        for nxt in sorted(_adj.get(node, ())):
            if nxt == dst:
                return path + [dst]
            if nxt not in seen:
                seen.add(nxt)
                stack.append((nxt, path + [nxt]))
    return None


def _violation(record):
    """Book one lock-order violation: telemetry + flight + warn/raise."""
    _violations.append(record)
    try:
        from ..telemetry import core as _tel
        _tel.bump("lockcheck_violations")
    except Exception:       # pragma: no cover - telemetry unavailable
        pass
    try:
        from ..telemetry import flight as _flight
        _flight.record("lockcheck_violation", record["edge"],
                       cycle=record["cycle"], site=record["site"])
    except Exception:       # pragma: no cover
        pass
    msg = ("MXNET_LOCKCHECK: lock-order inversion %s at %s "
           "(cycle: %s; prior order established at %s)"
           % (record["edge"], record["site"], record["cycle"],
              record["prior_site"]))
    if _MODE == "raise":
        raise LockOrderError(msg)
    warnings.warn(msg, RuntimeWarning, stacklevel=3)


def _note_edge(held_entry, lock, site):
    """Record held -> acquired; detect the cycle the new edge closes."""
    a, b = held_entry[0].name, lock.name
    if a == b:
        return
    with _graph_lock:
        key = (a, b)
        rec = _edges.get(key)
        if rec is not None:
            rec["count"] += 1
            return
        # a cycle exists iff b already reaches a BEFORE inserting a->b
        back = _find_path(b, a)
        _edges[key] = {"from": a, "to": b, "count": 1,
                       "from_site": held_entry[1], "to_site": site}
        _adj.setdefault(a, set()).add(b)
        if back is None:
            return
        cycle = " -> ".join([a, b] + back[1:])
        prior = _edges.get((b, back[1] if len(back) > 1 else a), {})
        record = {"edge": "%s -> %s" % (a, b), "cycle": cycle,
                  "site": site,
                  "prior_site": prior.get("to_site", "<unknown>")}
        if key in _warned:
            return
        _warned.add(key)
    _violation(record)


class _TrackedLock:
    """Order-witnessing wrapper around one threading Lock/RLock."""

    __slots__ = ("_inner", "name", "_reentrant")

    def __init__(self, inner, name, reentrant):
        self._inner = inner
        self.name = name
        self._reentrant = reentrant

    def acquire(self, blocking=True, timeout=-1):
        stack = _held_stack()
        # a blocking acquire with locks already held is an order edge;
        # trylocks and bounded waits cannot complete a deadlock cycle
        if blocking and (timeout is None or timeout < 0) and stack:
            site = _site()
            if not (self._reentrant
                    and any(e[0] is self for e in stack)):
                for entry in stack:
                    if entry[0] is not self:
                        _note_edge(entry, self, site)
        ok = self._inner.acquire(blocking, timeout)
        if ok:
            stack.append((self, _site()))
        return ok

    def release(self):
        stack = _held_stack()
        for i in range(len(stack) - 1, -1, -1):
            if stack[i][0] is self:
                del stack[i]
                break
        self._inner.release()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    def locked(self):
        locked = getattr(self._inner, "locked", None)
        return locked() if locked is not None else False

    def _is_owned(self):
        """Condition integration (``threading.Condition._is_owned``)."""
        inner = self._inner
        owned = getattr(inner, "_is_owned", None)
        if owned is not None:
            return owned()
        if inner.acquire(False):
            inner.release()
            return False
        return True

    def __repr__(self):
        return "<tracked %s %r>" % (
            "rlock" if self._reentrant else "lock", self.name)


# ---------------------------------------------------------------------------
# the constructor funnel
# ---------------------------------------------------------------------------

def make_lock(name):
    """A mutex; plain ``threading.Lock`` unless the witness is on."""
    if _MODE == "off":
        return threading.Lock()
    return _TrackedLock(threading.Lock(), name, reentrant=False)


def make_rlock(name):
    """A reentrant mutex (re-acquisition by the holder takes no edge)."""
    if _MODE == "off":
        return threading.RLock()
    return _TrackedLock(threading.RLock(), name, reentrant=True)


def make_condition(lock=None, name=None):
    """A condition variable over *lock* (or a fresh tracked lock).

    ``Condition.wait`` releases and re-acquires through the wrapper, so
    the held-stack stays truthful across waits."""
    if _MODE == "off":
        return threading.Condition(lock)
    if lock is None:
        lock = _TrackedLock(threading.Lock(), name or "<condition>",
                            reentrant=False)
    return threading.Condition(lock)


# ---------------------------------------------------------------------------
# introspection
# ---------------------------------------------------------------------------

def held_locks():
    """Names of tracked locks the calling thread holds right now."""
    return [e[0].name for e in getattr(_tls, "held", ())]


def note_blocking(site):
    """Report a blocking/delayed operation (chaos-stalled peer IO) that
    runs while tracked locks are held.  Warn-only: the chaos tier
    injects these stalls on purpose; the report is the product."""
    if _MODE == "off":
        return
    held = held_locks()
    if not held:
        return
    rec = {"site": site, "held": held}
    with _graph_lock:
        _blocked.append(rec)
        first = len(_blocked) == 1 or \
            all(b["site"] != site or b is rec for b in _blocked)
    try:
        from ..telemetry import flight as _flight
        _flight.record("lockcheck_blocked_io", site, held=",".join(held))
    except Exception:       # pragma: no cover
        pass
    if first:
        warnings.warn(
            "MXNET_LOCKCHECK: blocking peer IO at %s while holding %s"
            % (site, ", ".join(held)), RuntimeWarning, stacklevel=2)


def violations():
    with _graph_lock:
        return list(_violations)


def snapshot():
    """The recorded acquisition-order graph, JSON-shaped."""
    with _graph_lock:
        return {
            "mode": _MODE,
            "edges": [dict(rec) for _k, rec in sorted(_edges.items())],
            "violations": list(_violations),
            "blocked_io": list(_blocked),
            "cycle_free": not _violations,
        }
