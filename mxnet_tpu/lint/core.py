"""graftlint core: findings, suppressions, baseline, and the file scanner.

The reference framework kept TPU/async footguns out of user code with C++
compile-time checks and the dependency engine; this JAX rebuild has neither,
so the same class of mistakes (tracer leaks, retrace storms, global-PRNG
nondeterminism) only surfaces as slow or flaky runs.  graftlint moves those
checks to review time: an AST pass over the repo with a small rule registry
(``rules.py``), inline suppressions, and a checked-in baseline so legacy
findings do not block CI while new code is held to zero.

Design notes
------------
* A finding's identity is ``(rule, path, stripped source line)`` — NOT the
  line *number*, which rots on every unrelated edit above it.  The baseline
  stores counts per identity, so k findings with identical text on one file
  baseline as ``count: k`` and adding a (k+1)-th fires.
* Suppressions are source comments: ``# graftlint: disable=JG001`` (or
  ``disable=JG001,JG005`` / ``disable=all``) on the finding's line or alone
  on the line above it.
* The scanner is stdlib-only (``ast`` + ``tokenize``): importing the lint
  package must never drag jax in, because the CLI runs in CI and pre-commit
  contexts where initializing a backend is wasted seconds.
"""
from __future__ import annotations

import ast
import io
import json
import os
import re
import tokenize

__all__ = ["Finding", "SourceModule", "lint_source", "lint_sources",
           "lint_file", "lint_paths", "iter_python_files", "Baseline",
           "load_baseline", "default_baseline_path", "repo_root"]

# codes are comma-separated (spaces allowed around commas only): a
# justification written after the codes must not leak into the capture
_SUPPRESS_RE = re.compile(
    r"#\s*graftlint:\s*disable=((?:[A-Za-z0-9_]+(?:\s*,\s*)?)+)")


class Finding:
    """One rule violation at a source location."""

    __slots__ = ("rule", "path", "line", "col", "message", "snippet")

    def __init__(self, rule, path, line, col, message, snippet=""):
        self.rule = rule
        self.path = path
        self.line = line
        self.col = col
        self.message = message
        self.snippet = snippet

    @property
    def fingerprint(self):
        """Baseline identity: stable across reorderings of the file."""
        return (self.rule, self.path.replace(os.sep, "/"), self.snippet)

    def to_dict(self):
        return {"rule": self.rule, "path": self.path.replace(os.sep, "/"),
                "line": self.line, "col": self.col,
                "message": self.message, "snippet": self.snippet}

    def format_text(self):
        return "%s:%d:%d: %s %s" % (self.path, self.line, self.col,
                                    self.rule, self.message)

    def __repr__(self):
        return "Finding(%s, %s:%d)" % (self.rule, self.path, self.line)


class SourceModule:
    """Parsed module handed to every rule: AST (with parent links), source
    lines, and the per-line suppression table."""

    def __init__(self, path, source):
        self.path = path
        self.source = source
        self.lines = source.splitlines()
        self.tree = ast.parse(source, filename=path)
        for parent in ast.walk(self.tree):
            for child in ast.iter_child_nodes(parent):
                child._graftlint_parent = parent
        self.suppressions = _collect_suppressions(source)
        self._spread_over_statements()

    def _spread_over_statements(self):
        """A trailing suppression on ANY physical line of a multi-line
        statement covers the whole statement — findings anchor to the
        first line, the comment usually sits on the closing one."""
        spans = []
        for node in ast.walk(self.tree):
            # simple statements only: a compound stmt (def/if/for...)
            # spans its whole body and would over-suppress it
            if isinstance(node, ast.stmt) and not hasattr(node, "body") \
                    and getattr(node, "end_lineno", None) is not None \
                    and node.end_lineno > node.lineno:
                spans.append((node.lineno, node.end_lineno))
        if not spans:
            return
        for line, codes in list(self.suppressions.items()):
            for start, end in spans:
                if start <= line <= end:
                    for covered in range(start, end + 1):
                        self.suppressions.setdefault(
                            covered, set()).update(codes)

    def line_text(self, lineno):
        if 1 <= lineno <= len(self.lines):
            return self.lines[lineno - 1].strip()
        return ""

    def finding(self, rule, node, message):
        return Finding(rule, self.path, node.lineno, node.col_offset + 1,
                       message, self.line_text(node.lineno))

    def suppressed(self, finding):
        codes = self.suppressions.get(finding.line)
        if codes is None:
            return False
        return "all" in codes or finding.rule in codes


def parent(node):
    return getattr(node, "_graftlint_parent", None)


def _collect_suppressions(source):
    """line -> set of rule codes disabled on that line.

    A standalone suppression comment applies to the NEXT CODE line —
    skipping blank lines and further comments, so a justification comment
    may sit on either side of the directive; a trailing comment applies
    to its own line.
    """
    table = {}
    lines = source.splitlines()
    try:
        tokens = tokenize.generate_tokens(io.StringIO(source).readline)
        for tok in tokens:
            if tok.type != tokenize.COMMENT:
                continue
            m = _SUPPRESS_RE.search(tok.string)
            if not m:
                continue
            codes = {c.strip() for c in m.group(1).split(",") if c.strip()}
            line = tok.start[0]
            standalone = lines[line - 1].lstrip().startswith("#")
            if standalone:
                target = line + 1
                while target <= len(lines):
                    stripped = lines[target - 1].strip()
                    if stripped and not stripped.startswith("#"):
                        break
                    target += 1
                # also honor on its own line (harmless; no code there)
                table.setdefault(line, set()).update(codes)
            else:
                target = line
            table.setdefault(target, set()).update(codes)
    except tokenize.TokenError:
        pass
    return table


# ---------------------------------------------------------------------------
# scanning
# ---------------------------------------------------------------------------

_SKIP_DIRS = {"__pycache__", ".git", ".claude", "node_modules", "build",
              "dist", ".eggs"}


def iter_python_files(paths):
    """Expand files/directories into a sorted list of .py files."""
    out = []
    for p in paths:
        if os.path.isfile(p):
            if p.endswith(".py"):
                out.append(p)
        elif os.path.isdir(p):
            for dirpath, dirnames, filenames in os.walk(p):
                dirnames[:] = sorted(d for d in dirnames
                                     if d not in _SKIP_DIRS)
                for fname in sorted(filenames):
                    if fname.endswith(".py"):
                        out.append(os.path.join(dirpath, fname))
    return out


def _check_module(mod, select):
    """Run every (selected) rule over one parsed module."""
    from . import rules as _rules
    findings = []
    for code, rule in sorted(_rules.RULES.items()):
        if select is not None and code not in select:
            continue
        findings.extend(rule.check(mod))
    return [f for f in findings if not mod.suppressed(f)]


def _check_project(mods, select):
    """Run the rules over a set of modules linked as one project: when
    more than one module is in scope, cross-module `from mxnet_tpu.x
    import f` edges propagate hot-path and traced-ness between them
    before any rule runs (JG001/JG006 see through file boundaries)."""
    from . import rules as _rules
    if len(mods) > 1:
        _rules.link_project(mods)
    findings = []
    for mod in mods:
        findings.extend(_check_module(mod, select))
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return findings


def lint_source(source, path="<string>", select=None):
    """Run every (selected) rule over one source string."""
    findings = _check_module(SourceModule(path, source), select)
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return findings


def lint_sources(named_sources, select=None):
    """Lint several in-memory modules as ONE project — the cross-module
    call-graph propagation applies.  *named_sources*: [(path, source)]
    where the path's dotted form (``pkg/mod.py`` -> ``pkg.mod``) is the
    import identity other modules resolve against."""
    mods, findings = [], []
    for path, source in named_sources:
        try:
            mods.append(SourceModule(path, source))
        except SyntaxError as exc:
            findings.append(Finding("JG000", path, exc.lineno or 1, 1,
                                    "file does not parse: %s" % exc.msg))
    return findings + _check_project(mods, select)


def lint_file(path, select=None, rel_root=None):
    with open(path, encoding="utf-8") as f:
        source = f.read()
    rel = os.path.relpath(path, rel_root) if rel_root else path
    try:
        return lint_source(source, rel, select=select)
    except SyntaxError as exc:
        return [Finding("JG000", rel, exc.lineno or 1, 1,
                        "file does not parse: %s" % exc.msg)]


def lint_paths(paths, select=None, rel_root=None):
    mods, findings = [], []
    for path in iter_python_files(paths):
        with open(path, encoding="utf-8") as f:
            source = f.read()
        rel = os.path.relpath(path, rel_root) if rel_root else path
        rel = rel.replace(os.sep, "/")
        try:
            mods.append(SourceModule(rel, source))
        except SyntaxError as exc:
            findings.append(Finding("JG000", rel, exc.lineno or 1, 1,
                                    "file does not parse: %s" % exc.msg))
    return findings + _check_project(mods, select)


# ---------------------------------------------------------------------------
# baseline
# ---------------------------------------------------------------------------

def repo_root():
    """The directory holding the mxnet_tpu package (…/repo)."""
    return os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))


def default_baseline_path():
    return os.path.join(repo_root(), "LINT_BASELINE.json")


class Baseline:
    """Checked-in legacy findings: counts per finding fingerprint.

    ``apply`` splits current findings into (new, matched); whatever counts
    remain unconsumed afterwards are STALE entries — suppressions for code
    that no longer fires, which ``--check-baseline`` turns into an error so
    the baseline only ever shrinks.
    """

    def __init__(self, counts=None):
        self.counts = dict(counts or {})

    @classmethod
    def from_findings(cls, findings):
        counts = {}
        for f in findings:
            counts[f.fingerprint] = counts.get(f.fingerprint, 0) + 1
        return cls(counts)

    def apply(self, findings):
        remaining = dict(self.counts)
        new, matched = [], []
        for f in findings:
            if remaining.get(f.fingerprint, 0) > 0:
                remaining[f.fingerprint] -= 1
                matched.append(f)
            else:
                new.append(f)
        stale = {fp: n for fp, n in remaining.items() if n > 0}
        return new, matched, stale

    def restrict(self, paths=None, rules=None):
        """The sub-baseline covered by a scan scope.

        A partial scan (explicit file list, ``--select``) must only judge
        baseline entries it actually re-checked — everything else would
        read as stale (and a scoped ``--write-baseline`` would silently
        drop it).  *paths*: set of scanned repo-relative paths; *rules*:
        selected rule codes.  None means unrestricted.
        """
        kept = {}
        for (rule, path, snippet), n in self.counts.items():
            if paths is not None and path not in paths:
                continue
            if rules is not None and rule not in rules:
                continue
            kept[(rule, path, snippet)] = n
        return Baseline(kept)

    def merged_outside(self, paths=None, rules=None):
        """The complement of :meth:`restrict` — entries a scoped rewrite
        must preserve untouched."""
        scoped = self.restrict(paths, rules).counts
        return Baseline({fp: n for fp, n in self.counts.items()
                         if fp not in scoped})

    def to_json(self):
        entries = [{"rule": r, "path": p, "snippet": s, "count": n}
                   for (r, p, s), n in sorted(self.counts.items())]
        return {"version": 1, "entries": entries}

    @classmethod
    def from_json(cls, payload):
        counts = {}
        for e in payload.get("entries", ()):
            fp = (e["rule"], e["path"], e.get("snippet", ""))
            counts[fp] = counts.get(fp, 0) + int(e.get("count", 1))
        return cls(counts)

    def save(self, path):
        with open(path, "w", encoding="utf-8") as f:
            json.dump(self.to_json(), f, indent=1, sort_keys=True)
            f.write("\n")

    def __len__(self):
        return sum(self.counts.values())


def load_baseline(path):
    if not path or not os.path.exists(path):
        return Baseline()
    with open(path, encoding="utf-8") as f:
        return Baseline.from_json(json.load(f))
