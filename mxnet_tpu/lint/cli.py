"""graftlint CLI: ``python -m mxnet_tpu.lint`` / ``tools/graftlint.py``.

Two tiers share this front end, its output formats, and the baseline:

* AST tier (default): the JG rules over source files — stdlib-only.
* Trace tier (``--trace`` / ``tools/graftcheck.py``): the JX rules over
  the *lowered programs* of every owned jit entry point, AOT on CPU —
  imports jax and mxnet_tpu.

Exit codes: 0 clean (against the baseline), 1 findings (or stale baseline
entries under ``--check-baseline``), 2 usage error.
"""
from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys

from .core import (Baseline, default_baseline_path, iter_python_files,
                   lint_paths, load_baseline, repo_root)

# a diff touching any of these re-lints the whole concurrency tier: the
# JG009 order graph and JG010 blocking closures span module boundaries
import re as _re
_CONCURRENCY_TIER_RE = _re.compile(
    r"(^|/)(dist_ps\.py|engine\.py)$|(^|/)(serving|checkpoint)/")
_CONCURRENCY_TIER = (
    "mxnet_tpu/dist_ps.py",
    "mxnet_tpu/engine.py",
    "mxnet_tpu/serving",
    "mxnet_tpu/checkpoint",
    "mxnet_tpu/guardian",
    "mxnet_tpu/chaos",
    "mxnet_tpu/gluon/overlap.py",
)


def build_parser():
    p = argparse.ArgumentParser(
        prog="graftlint",
        description="TPU-footgun static analysis for mxnet_tpu "
                    "(rules JG001-JG006; see docs/LINT.md)")
    p.add_argument("paths", nargs="*", default=None,
                   help="files/directories to scan (default: mxnet_tpu/ "
                        "tools/ examples/)")
    p.add_argument("-f", "--format", choices=("text", "json"),
                   default="text", help="output format")
    p.add_argument("--select", default=None, metavar="JG001,JG002",
                   help="comma-separated rule codes to run (default: all)")
    p.add_argument("--baseline", default=None, metavar="PATH",
                   help="baseline file (default: <repo>/LINT_BASELINE.json)")
    p.add_argument("--no-baseline", action="store_true",
                   help="report every finding, ignoring the baseline")
    p.add_argument("--write-baseline", action="store_true",
                   help="write current findings as the new baseline and "
                        "exit 0")
    p.add_argument("--check-baseline", action="store_true",
                   help="fail if the baseline contains entries that no "
                        "longer fire (stale-suppression rot)")
    p.add_argument("--list-rules", action="store_true",
                   help="print the rule catalogue and exit")
    p.add_argument("--trace", action="store_true",
                   help="run the trace tier instead: lower every owned "
                        "XLA entry point AOT (CPU) and run the JX rules "
                        "over the jaxprs (imports jax; paths select entry "
                        "groups, e.g. 'executor kvstore')")
    p.add_argument("--diff", default=None, metavar="GIT_REF",
                   help="lint only .py files changed vs GIT_REF "
                        "(working tree included) — fast pre-commit mode; "
                        "under --trace, re-check only the entry groups "
                        "whose provider modules changed")
    p.add_argument("--no-memory", action="store_true",
                   help="--trace: skip the JX204 memory-budget pass "
                        "(no compiles; jaxpr rules only)")
    p.add_argument("--mem-baseline", default=None, metavar="PATH",
                   help="--trace: memory budget file (default: "
                        "<repo>/MEM_BASELINE.json)")
    p.add_argument("--write-mem-baseline", action="store_true",
                   help="--trace: measure every (selected) program and "
                        "write the budgets to the mem baseline, then "
                        "exit 0")
    p.add_argument("--memory-json", default=None, metavar="PATH",
                   help="--trace: write the per-program memory report "
                        "(bytes vs budget) as JSON for trace_report.py "
                        "--memory/--gate-memory")
    return p


def _changed_files(root, ref):
    """Repo-relative .py files changed between *ref* and the working
    tree — committed, staged, unstaged, AND untracked (a pre-commit run
    must see the brand-new file that was never ``git add``-ed) — or None
    on git failure."""
    try:
        diff = subprocess.run(
            ["git", "-C", root, "diff", "--name-only", ref, "--"],
            capture_output=True, text=True, timeout=30)
        untracked = subprocess.run(
            ["git", "-C", root, "ls-files", "--others",
             "--exclude-standard"],
            capture_output=True, text=True, timeout=30)
    except (OSError, subprocess.TimeoutExpired):
        return None
    if diff.returncode != 0 or untracked.returncode != 0:
        return None
    names = set(diff.stdout.splitlines()) | set(untracked.stdout.splitlines())
    return sorted(n.strip() for n in names if n.strip().endswith(".py"))


def main(argv=None):
    args = build_parser().parse_args(argv)

    if args.list_rules:
        from .rules import RULES
        for code, rule in sorted(RULES.items()):
            print("%s  %-24s %s" % (code, rule.name, rule.rationale))
        # the JX catalogue lives in tracecheck, which is import-light on
        # purpose (jax only loads when programs are actually traced)
        from .tracecheck import TRACE_RULES
        for code, rule in sorted(TRACE_RULES.items()):
            print("%s  %-24s %s" % (code, rule.name, rule.rationale))
        return 0

    select = None
    if args.select:
        select = {c.strip().upper() for c in args.select.split(",")
                  if c.strip()}

    root = repo_root()

    if args.trace:
        # the standalone launcher (tools/graftlint.py) loads this package
        # by file path, so the repo root is not on sys.path — but trace
        # providers import mxnet_tpu.* for real
        if root not in sys.path:
            sys.path.insert(0, root)
        from . import tracecheck
        entries = None
        if args.paths and args.diff is not None:
            print("graftcheck: give entry groups OR --diff, not both "
                  "(two scopes would silently intersect)",
                  file=sys.stderr)
            return 2
        if args.paths:
            known = {g for g, _m in tracecheck.ENTRY_POINTS}
            bad = sorted(set(args.paths) - known)
            if bad:
                print("graftcheck: unknown entry group(s): %s (known: %s)"
                      % (", ".join(bad), ", ".join(sorted(known))),
                      file=sys.stderr)
                return 2
            entries = set(args.paths)
        elif args.diff is not None:
            changed = _changed_files(root, args.diff)
            if changed is None:
                print("graftlint: git diff against %r failed" % args.diff,
                      file=sys.stderr)
                return 2
            entries = tracecheck.groups_for_paths(changed)
            if not entries:
                print("graftcheck: no changed trace providers vs %s"
                      % args.diff)
                return 0
            print("graftcheck: --diff %s -> entry group(s): %s"
                  % (args.diff, ", ".join(sorted(entries))),
                  file=sys.stderr)
        findings, names, mem_report = tracecheck.analyze_entry_points(
            entries=entries, select=select,
            memory=not args.no_memory,
            mem_baseline_path=args.mem_baseline)
        if args.write_mem_baseline:
            if mem_report is None:
                print("graftcheck: --write-mem-baseline needs the memory "
                      "pass (drop --no-memory / include JX204)",
                      file=sys.stderr)
                return 2
            records = [p for p in mem_report["programs"]]
            measured = {p["name"]: {k: p[k] for k in
                                    tracecheck.MEM_FIELDS
                                    + ("total_bytes", "specimens",
                                       "digest")}
                        for p in records}
            path = args.mem_baseline \
                or tracecheck.default_mem_baseline_path()
            prior = tracecheck.load_mem_baseline(path)
            tracecheck.save_mem_baseline(
                measured, path=path, prior=prior,
                scoped_names=set(measured) if entries is not None
                else None)
            print("graftcheck: wrote %d memory budget(s) to %s "
                  "(n_devices=%d)"
                  % (len(measured), os.path.relpath(path),
                     mem_report["n_devices"]))
            return 0
        if args.memory_json:
            if mem_report is None:
                print("graftcheck: --memory-json needs the memory pass "
                      "(drop --no-memory / include JX204)",
                      file=sys.stderr)
                return 2
            with open(args.memory_json, "w", encoding="utf-8") as f:
                json.dump(mem_report, f, indent=1, sort_keys=True)
                f.write("\n")
        scanned = {"trace://%s" % n for n in names} \
            | {f.path for f in findings}
        # the full-run staleness sweep covers entries whose program was
        # renamed away — but a JX000 means some provider DIDN'T run, and
        # sweeping then would drop that group's entries un-re-checked
        full_trace = entries is None \
            and not any(f.rule == "JX000" for f in findings)
        distinct = sorted(set(names))
        print("graftcheck: analyzed %d owned program(s) (%d specimen "
              "trace(s)): %s"
              % (len(distinct), len(names), ", ".join(distinct)),
              file=sys.stderr)
        if args.check_baseline and mem_report is not None:
            # the memory-budget twin of LINT staleness: budgets for
            # programs that no longer exist rot exactly like stale
            # suppressions
            stale_mem = mem_report.get("stale_budgets") or []
            if stale_mem:
                print("graftcheck: %d stale memory budget(s) (program "
                      "gone) — re-run --write-mem-baseline: %s"
                      % (len(stale_mem), ", ".join(stale_mem)))
                return 1
    else:
        paths = args.paths or [
            p for p in (os.path.join(repo_root(), d)
                        for d in ("mxnet_tpu", "tools", "examples"))
            if os.path.isdir(p)]
        # validate the scan roots BEFORE --diff filtering: a typo'd root
        # must stay a usage error, not "no changed files" + exit 0
        for p in paths:
            if not os.path.exists(p):
                print("graftlint: no such path: %s" % p, file=sys.stderr)
                return 2
        if args.diff is not None:
            changed = _changed_files(root, args.diff)
            if changed is None:
                print("graftlint: git diff against %r failed" % args.diff,
                      file=sys.stderr)
                return 2
            roots = [os.path.relpath(p, root).replace(os.sep, "/")
                     for p in paths]
            paths = [os.path.join(root, rel) for rel in changed
                     if os.path.exists(os.path.join(root, rel))
                     and any(rel == r or rel.startswith(r.rstrip("/") + "/")
                             for r in roots)]
            if not paths:
                print("graftlint: no changed Python files vs %s"
                      % args.diff)
                return 0
            # the lock graph is a WHOLE-TIER property: a diff touching
            # any threaded module re-lints the full concurrency tier, or
            # a new acquisition edge in the changed file would be judged
            # against a lock graph that was never linked
            if any(_CONCURRENCY_TIER_RE.search(
                    os.path.relpath(p, root).replace(os.sep, "/"))
                    for p in paths):
                tier = [os.path.join(root, rel) for rel in
                        _CONCURRENCY_TIER if
                        os.path.exists(os.path.join(root, rel))]
                known = set(paths)
                paths.extend(p for p in tier if p not in known)

        files = iter_python_files(paths)
        if not files:
            # scanning nothing must not read as lint-passing (a mis-wired
            # CI hook pointing at a .pyc or an emptied directory)
            print("graftlint: no Python files under %s" % ", ".join(paths),
                  file=sys.stderr)
            return 2
        findings = lint_paths(files, select=select, rel_root=root)

        # the scan scope: baseline entries outside it were NOT re-checked,
        # so they must be neither judged stale nor dropped by
        # --write-baseline.  Entries whose file no longer exists can never
        # fire again — they are in scope (stale / rewritten away) always.
        scanned = {os.path.relpath(p, root).replace(os.sep, "/")
                   for p in files}
        full_trace = False

    baseline_path = args.baseline or default_baseline_path()

    def scope_of(baseline):
        extra = set()
        for (_r, path, _s) in baseline.counts:
            if path.startswith("trace://"):
                # trace-tier entries are only re-checked by a FULL --trace
                # run; an AST run must not judge them stale (and a scoped
                # trace run only re-checked its own groups)
                if full_trace:
                    extra.add(path)
            elif not args.trace \
                    and not os.path.exists(os.path.join(root, path)):
                extra.add(path)
        return scanned | extra

    if args.write_baseline:
        prior = load_baseline(baseline_path)
        keep = prior.merged_outside(scope_of(prior), select)
        merged = Baseline.from_findings(findings)
        merged.counts.update(keep.counts)
        merged.save(baseline_path)
        print("graftlint: wrote %d finding(s) to %s (%d out-of-scope "
              "entr%s preserved)"
              % (len(findings), os.path.relpath(baseline_path), len(keep),
                 "y" if len(keep) == 1 else "ies"))
        return 0

    full_baseline = Baseline() if args.no_baseline \
        else load_baseline(baseline_path)
    baseline = full_baseline.restrict(scope_of(full_baseline), select)
    new, matched, stale = baseline.apply(findings)

    if args.check_baseline:
        if stale:
            print("graftlint: %d stale baseline entr%s (no longer fire) — "
                  "remove them or re-run --write-baseline:"
                  % (len(stale), "y" if len(stale) == 1 else "ies"))
            for (rule, path, snippet), n in sorted(stale.items()):
                print("  %s %s (x%d): %s" % (rule, path, n, snippet))
            return 1
        print("graftlint: baseline is tight (%d entr%s, all still fire)"
              % (len(baseline), "y" if len(baseline) == 1 else "ies"))
        return 0

    if args.format == "json":
        payload = {"new": [f.to_dict() for f in new],
                   "baselined": len(matched),
                   "stale_baseline": [
                       {"rule": r, "path": p, "snippet": s, "count": n}
                       for (r, p, s), n in sorted(stale.items())]}
        print(json.dumps(payload, indent=1, sort_keys=True))
    else:
        for f in new:
            print(f.format_text())
        if new:
            print("graftlint: %d new finding(s) (%d baselined)"
                  % (len(new), len(matched)))
        else:
            print("graftlint: clean (%d baselined finding(s))"
                  % len(matched))
        if stale:
            print("graftlint: note: %d stale baseline entr%s — run "
                  "--check-baseline for details"
                  % (len(stale), "y" if len(stale) == 1 else "ies"))
    return 1 if new else 0
