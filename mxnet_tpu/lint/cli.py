"""graftlint CLI: ``python -m mxnet_tpu.lint`` / ``tools/graftlint.py``.

Exit codes: 0 clean (against the baseline), 1 findings (or stale baseline
entries under ``--check-baseline``), 2 usage error.
"""
from __future__ import annotations

import argparse
import json
import os
import sys

from .core import (Baseline, default_baseline_path, iter_python_files,
                   lint_paths, load_baseline, repo_root)


def build_parser():
    p = argparse.ArgumentParser(
        prog="graftlint",
        description="TPU-footgun static analysis for mxnet_tpu "
                    "(rules JG001-JG006; see docs/LINT.md)")
    p.add_argument("paths", nargs="*", default=None,
                   help="files/directories to scan (default: mxnet_tpu/ "
                        "tools/ examples/)")
    p.add_argument("-f", "--format", choices=("text", "json"),
                   default="text", help="output format")
    p.add_argument("--select", default=None, metavar="JG001,JG002",
                   help="comma-separated rule codes to run (default: all)")
    p.add_argument("--baseline", default=None, metavar="PATH",
                   help="baseline file (default: <repo>/LINT_BASELINE.json)")
    p.add_argument("--no-baseline", action="store_true",
                   help="report every finding, ignoring the baseline")
    p.add_argument("--write-baseline", action="store_true",
                   help="write current findings as the new baseline and "
                        "exit 0")
    p.add_argument("--check-baseline", action="store_true",
                   help="fail if the baseline contains entries that no "
                        "longer fire (stale-suppression rot)")
    p.add_argument("--list-rules", action="store_true",
                   help="print the rule catalogue and exit")
    return p


def main(argv=None):
    args = build_parser().parse_args(argv)

    if args.list_rules:
        from .rules import RULES
        for code, rule in sorted(RULES.items()):
            print("%s  %-24s %s" % (code, rule.name, rule.rationale))
        return 0

    select = None
    if args.select:
        select = {c.strip().upper() for c in args.select.split(",")
                  if c.strip()}

    paths = args.paths or [
        p for p in (os.path.join(repo_root(), d)
                    for d in ("mxnet_tpu", "tools", "examples"))
        if os.path.isdir(p)]
    for p in paths:
        if not os.path.exists(p):
            print("graftlint: no such path: %s" % p, file=sys.stderr)
            return 2

    root = repo_root()
    files = iter_python_files(paths)
    if not files:
        # scanning nothing must not read as lint-passing (a mis-wired CI
        # hook pointing at a .pyc or an emptied directory)
        print("graftlint: no Python files under %s" % ", ".join(paths),
              file=sys.stderr)
        return 2
    findings = lint_paths(files, select=select, rel_root=root)

    # the scan scope: baseline entries outside it were NOT re-checked, so
    # they must be neither judged stale nor dropped by --write-baseline.
    # Entries whose file no longer exists can never fire again — they are
    # in scope (and therefore stale / rewritten away) on every run.
    scanned = {os.path.relpath(p, root).replace(os.sep, "/")
               for p in files}

    baseline_path = args.baseline or default_baseline_path()

    def scope_of(baseline):
        return scanned | {path for (_r, path, _s) in baseline.counts
                          if not os.path.exists(os.path.join(root, path))}

    if args.write_baseline:
        prior = load_baseline(baseline_path)
        keep = prior.merged_outside(scope_of(prior), select)
        merged = Baseline.from_findings(findings)
        merged.counts.update(keep.counts)
        merged.save(baseline_path)
        print("graftlint: wrote %d finding(s) to %s (%d out-of-scope "
              "entr%s preserved)"
              % (len(findings), os.path.relpath(baseline_path), len(keep),
                 "y" if len(keep) == 1 else "ies"))
        return 0

    full_baseline = Baseline() if args.no_baseline \
        else load_baseline(baseline_path)
    baseline = full_baseline.restrict(scope_of(full_baseline), select)
    new, matched, stale = baseline.apply(findings)

    if args.check_baseline:
        if stale:
            print("graftlint: %d stale baseline entr%s (no longer fire) — "
                  "remove them or re-run --write-baseline:"
                  % (len(stale), "y" if len(stale) == 1 else "ies"))
            for (rule, path, snippet), n in sorted(stale.items()):
                print("  %s %s (x%d): %s" % (rule, path, n, snippet))
            return 1
        print("graftlint: baseline is tight (%d entr%s, all still fire)"
              % (len(baseline), "y" if len(baseline) == 1 else "ies"))
        return 0

    if args.format == "json":
        payload = {"new": [f.to_dict() for f in new],
                   "baselined": len(matched),
                   "stale_baseline": [
                       {"rule": r, "path": p, "snippet": s, "count": n}
                       for (r, p, s), n in sorted(stale.items())]}
        print(json.dumps(payload, indent=1, sort_keys=True))
    else:
        for f in new:
            print(f.format_text())
        if new:
            print("graftlint: %d new finding(s) (%d baselined)"
                  % (len(new), len(matched)))
        else:
            print("graftlint: clean (%d baselined finding(s))"
                  % len(matched))
        if stale:
            print("graftlint: note: %d stale baseline entr%s — run "
                  "--check-baseline for details"
                  % (len(stale), "y" if len(stale) == 1 else "ies"))
    return 1 if new else 0
