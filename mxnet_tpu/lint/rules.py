"""graftlint rules: the TPU/JAX footgun catalogue (JG001-JG006).

Each rule is a small AST check registered in ``RULES``.  They share one
per-module analysis (:class:`ModuleFacts`) that resolves import aliases to
dotted names (``np.random.uniform`` -> ``numpy.random.uniform``), finds every
``jax.jit`` call/decorator, links jitted callables back to their function
defs, and builds a same-module call graph for hot-path propagation.

The rules are deliberately heuristic — a lint pass that is right about the
expensive mistakes and wrong occasionally is worth far more than a sound
analysis that never ships.  False positives have two escape hatches: inline
``# graftlint: disable=JG00x`` comments and the checked-in baseline.

Rule catalogue (rationale in docs/LINT.md):

JG001 host-sync-under-trace   .asnumpy()/.item()/bool()/int()/float()/
                              np.asarray on values inside a jit-traced
                              function: bakes constants or crashes with an
                              opaque TracerArrayConversionError at runtime.
JG002 naked-jit               a jax.jit entry point not wrapped in
                              telemetry.watch_jit: invisible to the PR-2
                              retrace watchdog, so its retrace storms burn
                              compile time silently.
JG003 retrace-hazard          jitted callable parameters whose defaults are
                              Python strings/bools/dicts/lists and are not
                              declared static: every distinct value (or any
                              unhashable) retraces or crashes.
JG004 donation-after-use      a buffer passed at a donated argnum is read
                              after the call: XLA may have already reused
                              its memory (garbage reads on TPU).
JG005 global-PRNG             np.random.* / random.* module-state draws
                              instead of seeded mxnet_tpu.random: seed()
                              cannot make runs reproducible and threads
                              race the hidden global state.
JG006 env-read-in-hot-path    os.environ reads inside step/update/push/...
                              call paths or loops: a getenv per step is a
                              dict lookup + string parse on the hot path;
                              use the module-level cached-bool pattern.
JG007 unbounded-blocking-call `.recv(...)` / queue-ish `.get()` with no
                              timeout in the dist/engine/serving tier: a
                              dead or silent peer turns the call into a
                              hang.  Pass a deadline — or an explicit
                              ``timeout=None`` documenting a deliberate
                              unbounded wait.
JG008 shard-map-outside-      direct jax shard_map use (import, alias,
      substrate               or attribute) anywhere but parallel/
                              mesh.py: the substrate exists because
                              jax's shard_map API drifts; one module
                              absorbs the drift, everyone else routes
                              through mesh.shard_map (ISSUE 16's grep
                              test, promoted to a rule).
"""
from __future__ import annotations

import ast
import os
import re

from .core import parent

__all__ = ["RULES", "Rule", "register", "ModuleFacts", "HOT_NAME_RE",
           "link_project"]

RULES = {}


class Rule:
    __slots__ = ("code", "name", "rationale", "_check")

    def __init__(self, code, name, rationale, check):
        self.code, self.name, self.rationale = code, name, rationale
        self._check = check

    def check(self, mod):
        facts = _facts(mod)
        return list(self._check(mod, facts))


def register(code, name, rationale):
    def deco(fn):
        RULES[code] = Rule(code, name, rationale, fn)
        return fn
    return deco


# ---------------------------------------------------------------------------
# shared per-module analysis
# ---------------------------------------------------------------------------

def _facts(mod):
    cached = getattr(mod, "_graftlint_facts", None)
    if cached is None:
        cached = mod._graftlint_facts = ModuleFacts(mod)
    return cached


class ModuleFacts:
    """Everything the rules need, computed once per module."""

    def __init__(self, mod):
        self.mod = mod
        self.aliases = {}        # local name -> dotted origin
        self._collect_imports()
        self.calls = [n for n in ast.walk(mod.tree)
                      if isinstance(n, ast.Call)]
        self.funcdefs = [n for n in ast.walk(mod.tree)
                         if isinstance(n, (ast.FunctionDef,
                                           ast.AsyncFunctionDef))]
        self.jit_calls = []      # ast.Call nodes that are jax.jit(...)
        self.jit_decorated = []  # (funcdef, decorator node)
        self._collect_jits()
        self.traced_defs = self._traced_defs()

    # -- imports ------------------------------------------------------------

    def _collect_imports(self):
        for node in ast.walk(self.mod.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    self.aliases[a.asname or a.name.split(".")[0]] = a.name
            elif isinstance(node, ast.ImportFrom):
                # relative imports get a leading "." so in-repo modules
                # (e.g. `from . import random`) never collide with stdlib
                base = ("." * node.level) + (node.module or "")
                for a in node.names:
                    origin = (base + "." + a.name) if base else a.name
                    self.aliases[a.asname or a.name] = origin

    def qualname(self, node):
        """Dotted origin of a Name/Attribute expression, or None.

        ``np.random.uniform`` -> "numpy.random.uniform" given
        ``import numpy as np``; unknown bases resolve to their local
        spelling so heuristic suffix checks still work.
        """
        parts = []
        while isinstance(node, ast.Attribute):
            parts.append(node.attr)
            node = node.value
        if not isinstance(node, ast.Name):
            return None
        base = self.aliases.get(node.id, node.id)
        parts.append(base)
        return ".".join(reversed(parts))

    # -- jit discovery ------------------------------------------------------

    def _is_jit_name(self, qual):
        return qual in ("jax.jit", "jax.api.jit") or \
            (qual is not None and qual.endswith(".jit")
             and qual.startswith("jax"))

    def is_jit_call(self, call):
        qual = self.qualname(call.func)
        if self._is_jit_name(qual):
            return True
        # functools.partial(jax.jit, ...) used as a factory
        if qual in ("functools.partial", "partial") and call.args:
            return self._is_jit_name(self.qualname(call.args[0]))
        return False

    def is_watch_jit_call(self, call):
        qual = self.qualname(call.func)
        return qual is not None and qual.split(".")[-1] == "watch_jit"

    def _collect_jits(self):
        for call in self.calls:
            if self.is_jit_call(call):
                self.jit_calls.append(call)
        for fd in self.funcdefs:
            for dec in fd.decorator_list:
                if isinstance(dec, ast.Call):
                    if self.is_jit_call(dec):
                        self.jit_decorated.append((fd, dec))
                else:
                    if self._is_jit_name(self.qualname(dec)):
                        self.jit_decorated.append((fd, dec))

    def jit_target_def(self, call):
        """The FunctionDef/Lambda a jax.jit call traces, if resolvable.

        Name lookup is scope-aware: ``jax.jit(step)`` inside a builder
        resolves to the ``step`` nested in that builder, not to a
        same-named method elsewhere in the module.
        """
        args = call.args
        if self.qualname(call.func) in ("functools.partial", "partial"):
            args = args[1:]
        if not args:
            return None
        target = args[0]
        if isinstance(target, ast.Lambda):
            return target
        if not isinstance(target, ast.Name):
            return None
        candidates = [fd for fd in self.funcdefs if fd.name == target.id]
        if not candidates:
            return None
        encl = self.enclosing_function(call)
        for fd in candidates:       # same enclosing function wins
            p = parent(fd)
            while p is not None:
                if p is encl:
                    return fd
                if isinstance(p, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.Lambda)):
                    break
                p = parent(p)
        for fd in candidates:       # else a module/class-level def
            if self.enclosing_function(fd) is None:
                return fd
        return candidates[0]

    def _traced_defs(self):
        """Function defs whose bodies execute under a jax trace: jitted
        defs, jit-decorated defs, and defs lexically nested inside one."""
        traced = set()
        for call in self.jit_calls:
            fd = self.jit_target_def(call)
            if fd is not None:
                traced.add(fd)
        for fd, _dec in self.jit_decorated:
            traced.add(fd)
        # nested defs trace with their parent
        grew = True
        while grew:
            grew = False
            for fd in self.funcdefs:
                if fd in traced:
                    continue
                p = parent(fd)
                while p is not None:
                    if p in traced:
                        traced.add(fd)
                        grew = True
                        break
                    p = parent(p)
        return traced

    def enclosing_function(self, node):
        p = parent(node)
        while p is not None:
            if isinstance(p, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.Lambda)):
                return p
            p = parent(p)
        return None

    def enclosing_statement(self, node):
        stmt = node
        p = parent(stmt)
        while p is not None and not isinstance(stmt, ast.stmt):
            stmt = p
            p = parent(stmt)
        return stmt if isinstance(stmt, ast.stmt) else None


def _static_argspec(call):
    """(static_argnums set, static_argnames set) from a jit call's literal
    keywords; non-literal specs resolve to None (= unknown, don't flag)."""
    nums, names = set(), set()
    for kw in call.keywords:
        if kw.arg == "static_argnums":
            vals = _literal_ints(kw.value)
            if vals is None:
                return None, None
            nums.update(vals)
        elif kw.arg == "static_argnames":
            vals = _literal_strs(kw.value)
            if vals is None:
                return None, None
            names.update(vals)
    return nums, names


def _literal_ints(node):
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return {node.value}
    if isinstance(node, (ast.Tuple, ast.List)):
        out = set()
        for elt in node.elts:
            if isinstance(elt, ast.Constant) and isinstance(elt.value, int):
                out.add(elt.value)
            else:
                return None
        return out
    return None


def _literal_strs(node):
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return {node.value}
    if isinstance(node, (ast.Tuple, ast.List)):
        out = set()
        for elt in node.elts:
            if isinstance(elt, ast.Constant) and isinstance(elt.value, str):
                out.add(elt.value)
            else:
                return None
        return out
    return None


# ---------------------------------------------------------------------------
# cross-module project linking
# ---------------------------------------------------------------------------
#
# The same-module call graph misses the common refactor where step() lives
# in one file and its helper in another: `from mxnet_tpu.kvstore import f`
# severs hot-path propagation at the file boundary.  link_project() runs
# once per multi-file scan, resolves import edges BETWEEN the scanned
# modules, computes global hot/traced fixpoints over (module, FunctionDef)
# nodes, and annotates each SourceModule with the defs forced hot (JG006)
# or traced (JG001) from outside.  Seeds and annotations are def-precise
# (a jitted inner `def step` must not smear traced-ness onto an unrelated
# same-named eager method); only call RESOLUTION is by name — a call edge
# lands on every same-named def in the target module, because the import
# surface carries no def identity.

def _module_dotted(path):
    """``mxnet_tpu/gluon/trainer.py`` -> ``mxnet_tpu.gluon.trainer``;
    ``pkg/__init__.py`` -> ``pkg``.  None for non-.py paths."""
    if not path.endswith(".py"):
        return None
    parts = path[:-3].replace("\\", "/").split("/")
    if parts and parts[-1] == "__init__":
        parts = parts[:-1]
    return ".".join(p for p in parts if p) or None


def _import_targets(mod, modname):
    """local name -> dotted-target parts, with relative imports resolved
    against *modname*'s package (AST levels, not the alias-string
    encoding, which cannot distinguish `from . import x` from `from ..x
    import f`)."""
    # an __init__.py IS its package: its dotted name (``pkg``, the
    # ``__init__`` segment already stripped) is the base one dot resolves
    # against; for a plain module the base is the containing package
    if mod.path.replace("\\", "/").endswith("/__init__.py"):
        package = modname.split(".")
    else:
        package = modname.split(".")[:-1]
    targets = {}
    for node in ast.walk(mod.tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                if a.asname:
                    targets[a.asname] = a.name.split(".")
                else:
                    head = a.name.split(".")[0]
                    targets[head] = [head]
        elif isinstance(node, ast.ImportFrom):
            if node.level:
                if node.level - 1 > len(package):
                    continue
                base = package[:len(package) - (node.level - 1)]
            else:
                base = []
            if node.module:
                base = base + node.module.split(".")
            for a in node.names:
                targets[a.asname or a.name] = base + [a.name]
    return targets


def _resolve_call_target(func, imports, defs, modname, index):
    """(module, funcname) a call lands in, if it is a def in a scanned
    module — via a bare same-module name, an imported name, or a dotted
    module alias chain."""
    parts = []
    node = func
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if not isinstance(node, ast.Name):
        return None
    parts.reverse()
    base = imports.get(node.id)
    if base is None:
        if not parts and node.id in defs.get(modname, ()):
            return (modname, node.id)
        return None
    full = base + parts
    for cut in range(len(full) - 1, 0, -1):
        m = ".".join(full[:cut])
        if m in index:
            fn = full[cut]
            return (m, fn) if fn in defs.get(m, ()) else None
    return None


def _fixpoint(seeds, edges):
    reached = set(seeds)
    frontier = list(seeds)
    while frontier:
        node = frontier.pop()
        for nxt in edges.get(node, ()):
            if nxt not in reached:
                reached.add(nxt)
                frontier.append(nxt)
    return reached


def link_project(mods):
    """Annotate each SourceModule in *mods* with ``external_hot`` /
    ``external_traced``: the FunctionDef nodes on a hot path or under a
    jax trace once cross-module edges are followed.  Rules consult the
    annotations lazily, so linking must run before any rule does."""
    # the concurrency tier (JG009-011) links the lock graph over the
    # same module set — before the <2-module early return, because its
    # rules consume the project annotation even for small scans
    lockcheck.link_lock_project(mods)
    index = {}
    for mod in mods:
        name = _module_dotted(mod.path)
        if name:
            index[name] = mod
    if len(index) < 2:
        return
    defs = {}
    for m, mod in index.items():
        by_name = {}
        for fd in _facts(mod).funcdefs:
            by_name.setdefault(fd.name, []).append(fd)
        defs[m] = by_name
    edges, hot_seeds, traced_seeds = {}, set(), set()
    for modname, mod in index.items():
        facts = _facts(mod)
        imports = _import_targets(mod, modname)
        for fd in facts.funcdefs:
            node = (modname, fd)
            if HOT_NAME_RE.search(fd.name):
                hot_seeds.add(node)
            if fd in facts.traced_defs:
                traced_seeds.add(node)
            outs = edges.setdefault(node, set())
            for sub in ast.walk(fd):
                if isinstance(sub, ast.Call):
                    tgt = _resolve_call_target(sub.func, imports, defs,
                                               modname, index)
                    if tgt is None:
                        continue
                    m, f = tgt
                    outs.update((m, tfd) for tfd in defs[m].get(f, ())
                                if (m, tfd) != node)
    hot = _fixpoint(hot_seeds, edges)
    traced = _fixpoint(traced_seeds, edges)
    for modname, mod in index.items():
        mod.external_hot = {fd for m, fd in hot if m == modname}
        mod.external_traced = {fd for m, fd in traced if m == modname}


def _project_traced_defs(mod, facts):
    """Defs under a trace once project links are considered: the local
    (def-precise) analysis plus any def the project fixpoint reached."""
    traced = set(facts.traced_defs)
    traced.update(getattr(mod, "external_traced", None) or ())
    return traced


# ---------------------------------------------------------------------------
# JG001 host-sync-under-trace
# ---------------------------------------------------------------------------

_HOST_SYNC_METHODS = {"asnumpy", "asscalar", "item", "tolist",
                      "block_until_ready", "wait_to_read"}
_HOST_SYNC_BUILTINS = {"bool", "int", "float"}
_SHAPEY_RE = re.compile(r"\.(shape|ndim|size|dtype)\b|len\(")


def _walk_own_body(fd):
    """Walk a function's nodes WITHOUT descending into nested defs (those
    are traced defs in their own right and are visited separately)."""
    stack = list(fd.body) if not isinstance(fd, ast.Lambda) else [fd.body]
    while stack:
        node = stack.pop()
        yield node
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            continue          # nested def: its body is its own traced walk
        stack.extend(ast.iter_child_nodes(node))


@register("JG001", "host-sync-under-trace",
          "host materialization inside a jit trace bakes constants into "
          "the compiled program or crashes with a tracer error")
def _jg001(mod, facts):
    for fd in _project_traced_defs(mod, facts):
        for node in _walk_own_body(fd):
            if not isinstance(node, ast.Call):
                continue
            msg = _jg001_call(mod, facts, node)
            if msg:
                name = getattr(fd, "name", "<lambda>")
                yield mod.finding("JG001", node, msg % name)


def _jg001_call(mod, facts, call):
    func = call.func
    if isinstance(func, ast.Attribute):
        if func.attr in _HOST_SYNC_METHODS and not call.args \
                and not call.keywords:
            return ("'.%s()' inside jit-traced function '%%s' forces a "
                    "host sync (or leaks a tracer)" % func.attr)
        qual = facts.qualname(func)
        if qual in ("numpy.asarray", "numpy.array") and call.args:
            arg = call.args[0]
            if isinstance(arg, (ast.Name, ast.Attribute, ast.Call)):
                return ("'np.%s(...)' on a traced value inside '%%s' "
                        "materializes to host" % func.attr)
    elif isinstance(func, ast.Name) and func.id in _HOST_SYNC_BUILTINS \
            and func.id not in facts.aliases and len(call.args) == 1:
        arg = call.args[0]
        if isinstance(arg, ast.Constant):
            return None
        src = ast.get_source_segment(mod.source, arg) or ""
        if _SHAPEY_RE.search(src):
            return None           # int(x.shape[0]) etc. is static under jit
        if isinstance(arg, (ast.Name, ast.Attribute, ast.Call,
                            ast.Subscript)):
            return ("'%s(...)' on a traced value inside '%%s' forces a "
                    "concrete host value" % func.id)
    return None


# ---------------------------------------------------------------------------
# JG002 naked-jit
# ---------------------------------------------------------------------------

@register("JG002", "naked-jit",
          "a jit entry point the retrace watchdog cannot see: wrap it in "
          "telemetry.watch_jit(jax.jit(fn), name)")
def _jg002(mod, facts):
    for call in facts.jit_calls:
        p = parent(call)
        if isinstance(p, ast.Call) and facts.is_watch_jit_call(p) \
                and p.args and p.args[0] is call:
            continue
        yield mod.finding(
            "JG002", call,
            "naked jax.jit: wrap in telemetry.watch_jit(jax.jit(...), "
            "'<name>') so retrace storms are booked")
    for fd, dec in facts.jit_decorated:
        yield mod.finding(
            "JG002", dec,
            "@jax.jit on '%s' bypasses the retrace watchdog: build with "
            "telemetry.watch_jit(jax.jit(%s), '%s') instead"
            % (fd.name, fd.name, fd.name))


# ---------------------------------------------------------------------------
# JG003 retrace-hazard
# ---------------------------------------------------------------------------

_HAZARD_TYPES = {str: "str", bool: "bool"}


@register("JG003", "retrace-hazard",
          "non-array parameters of a jitted callable retrace per distinct "
          "value (str/bool) or crash as unhashable (dict/list) unless "
          "declared static")
def _jg003(mod, facts):
    for call in facts.jit_calls:
        fd = facts.jit_target_def(call)
        if fd is None or isinstance(fd, ast.Lambda):
            continue
        nums, names = _static_argspec(call)
        if nums is None:
            continue              # non-literal static spec: trust the author
        args = fd.args
        params = list(args.posonlyargs) + list(args.args)
        defaults = list(args.defaults)
        # defaults right-align to positional params; kw-only params carry
        # a parallel (possibly None-holed) kw_defaults list
        dstart = len(params) - len(defaults)
        hazards = []
        for i, p in enumerate(params):
            if i in nums or i < dstart:
                continue
            hazards.append((p, defaults[i - dstart]))
        for p, default in zip(args.kwonlyargs, args.kw_defaults):
            if default is not None:
                hazards.append((p, default))
        for p, default in hazards:
            if p.arg in names or p.arg in ("self", "cls"):
                continue
            hazard = _default_hazard(default)
            if hazard:
                yield mod.finding(
                    "JG003", default,
                    "parameter '%s' of jitted '%s' defaults to a %s; each "
                    "distinct value retraces (or is unhashable) — declare "
                    "it in static_argnames or pass it traced"
                    % (p.arg, fd.name, hazard))


def _default_hazard(node):
    if isinstance(node, ast.Constant) and type(node.value) in _HAZARD_TYPES:
        return _HAZARD_TYPES[type(node.value)]
    if isinstance(node, ast.Dict):
        return "dict"
    if isinstance(node, (ast.List, ast.Set)):
        return "list/set"
    return None


# ---------------------------------------------------------------------------
# JG004 donation-after-use
# ---------------------------------------------------------------------------

@register("JG004", "donation-after-use",
          "a donated input buffer is read after the call; XLA may already "
          "have reused its memory")
def _jg004(mod, facts):
    donated = _donated_callables(facts)
    if not donated:
        return
    for call in facts.calls:
        key = _callee_key(call.func)
        if key is None or key not in donated:
            continue
        argnums = donated[key]
        for i in sorted(argnums):
            if i >= len(call.args):
                continue
            arg = call.args[i]
            if not isinstance(arg, ast.Name):
                continue
            use = _read_after(mod, facts, call, arg.id)
            if use is not None:
                yield mod.finding(
                    "JG004", use,
                    "'%s' was donated at argnum %d of '%s' on line %d and "
                    "is read afterwards; its buffer may be reused by XLA "
                    "— rebind it from the call's result or drop the "
                    "donation" % (arg.id, i, key, call.lineno))


def _rebinds_param(fd, name):
    args = fd.args
    names = [a.arg for a in (list(args.posonlyargs) + list(args.args)
                             + list(args.kwonlyargs))]
    for special in (args.vararg, args.kwarg):
        if special is not None:
            names.append(special.arg)
    return name in names


def _walk_skip_rebinding_defs(scope, name):
    """Walk *scope* but skip nested defs whose parameter list rebinds
    *name* — their 'name' is a fresh binding, not the donated buffer.
    Closures that capture *name* ARE walked (a plausible real use)."""
    stack = [scope]
    while stack:
        node = stack.pop()
        if node is not scope and \
                isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.Lambda)) and _rebinds_param(node, name):
            continue
        yield node
        stack.extend(ast.iter_child_nodes(node))


def _donated_callables(facts):
    """name -> donated argnums, for `x = [watch_jit(]jax.jit(f,
    donate_argnums=...)[)]` assignments (plain and self-attribute)."""
    out = {}
    for call in facts.jit_calls:
        nums = None
        for kw in call.keywords:
            if kw.arg == "donate_argnums":
                nums = _literal_ints(kw.value)
        if not nums:
            continue
        # climb through a watch_jit wrapper to the assignment
        node = call
        p = parent(node)
        if isinstance(p, ast.Call) and facts.is_watch_jit_call(p):
            node, p = p, parent(p)
        if isinstance(p, ast.Assign):
            for tgt in p.targets:
                key = _callee_key(tgt)
                if key:
                    out[key] = nums
    return out


def _callee_key(node):
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        return node.attr          # self._step_fn and obj._step_fn unify
    return None


def _read_after(mod, facts, call, name):
    """First Load of *name* after *call* in its enclosing scope, unless a
    Store rebinds it first.  Stores that are targets of the statement
    containing the call (``x = fn(x)``) count as immediately-after."""
    scope = facts.enclosing_function(call) or mod.tree
    call_stmt = facts.enclosing_statement(call)
    if isinstance(call_stmt, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
        targets = call_stmt.targets if isinstance(call_stmt, ast.Assign) \
            else [call_stmt.target]
        for tgt in targets:
            for sub in ast.walk(tgt):
                if isinstance(sub, ast.Name) and sub.id == name:
                    return None   # rebound from the result: the idiom
    end = (call.end_lineno, call.end_col_offset)
    events = []
    for node in _walk_skip_rebinding_defs(scope, name):
        if isinstance(node, ast.Name) and node.id == name:
            pos = (node.lineno, node.col_offset)
            if pos > end:
                events.append((pos, node))
    for _pos, node in sorted(events, key=lambda e: e[0]):
        if isinstance(node.ctx, ast.Store):
            return None
        if isinstance(node.ctx, ast.Load):
            return node
    return None


# ---------------------------------------------------------------------------
# JG005 global-PRNG
# ---------------------------------------------------------------------------

_NP_RANDOM_OK = {"RandomState", "default_rng", "Generator", "SeedSequence",
                 "BitGenerator", "PCG64", "Philox", "MT19937", "get_state",
                 "set_state"}
_STDLIB_RANDOM_STATEFUL = {
    "seed", "random", "randint", "randrange", "shuffle", "choice",
    "choices", "sample", "uniform", "normalvariate", "gauss",
    "betavariate", "expovariate", "triangular", "getrandbits",
    "lognormvariate", "vonmisesvariate", "paretovariate",
    "weibullvariate", "sample"}


@register("JG005", "global-PRNG",
          "module-state RNG draws are invisible to mxnet_tpu.random.seed "
          "and race across threads; use random.host_rng() / next_key()")
def _jg005(mod, facts):
    for call in facts.calls:
        qual = facts.qualname(call.func)
        if qual is None:
            continue
        if qual.startswith("numpy.random."):
            attr = qual.rsplit(".", 1)[-1]
            if attr not in _NP_RANDOM_OK:
                yield mod.finding(
                    "JG005", call,
                    "np.random.%s uses hidden module state; draw from "
                    "mxnet_tpu.random.host_rng() (numpy host draws) or "
                    "next_key() (traced) so mx.random.seed governs it"
                    % attr)
        elif qual.startswith("random.") and qual.count(".") == 1:
            attr = qual.rsplit(".", 1)[-1]
            if attr in _STDLIB_RANDOM_STATEFUL:
                yield mod.finding(
                    "JG005", call,
                    "stdlib random.%s uses hidden module state; use "
                    "mxnet_tpu.random.host_rng() so mx.random.seed "
                    "governs it" % attr)


# ---------------------------------------------------------------------------
# JG006 env-read-in-hot-path
# ---------------------------------------------------------------------------

HOT_NAME_RE = re.compile(
    r"(^|_)(step|update|forward|backward|push|pull|invoke|reduce|next|"
    r"sample|dispatch|train|fit)(_|$)|^__call__$|^__next__$|^__iter__$")

_CACHED_DECORATORS = {"lru_cache", "cache", "cached_property", "functools"}


@register("JG006", "env-read-in-hot-path",
          "os.environ reads on step/update/push paths re-parse strings "
          "every iteration; hoist into a module-level cached value with an "
          "explicit refresh hook (the cached-bool pattern)")
def _jg006(mod, facts):
    hot = _hot_functions(facts)
    for node in ast.walk(mod.tree):
        env = _env_read(facts, node)
        if env is None:
            continue
        fd = facts.enclosing_function(node)
        in_hot = fd is not None and fd in hot and not _is_cached(fd)
        in_loop = _inside_loop(node)
        if not (in_hot or in_loop):
            continue
        where = ("hot-path function '%s'" % fd.name) if in_hot \
            else "a loop body"
        yield mod.finding(
            "JG006", node,
            "%s read inside %s; cache it at module level (cached-bool "
            "pattern) and re-read only via an explicit refresh"
            % (env, where))


def _env_read(facts, node):
    if isinstance(node, ast.Call):
        qual = facts.qualname(node.func)
        if qual in ("os.environ.get", "os.getenv"):
            return qual
    if isinstance(node, ast.Subscript):
        qual = facts.qualname(node.value)
        if qual == "os.environ":
            return "os.environ[...]"
    return None


def _is_cached(fd):
    if isinstance(fd, ast.Lambda):
        return False
    for dec in fd.decorator_list:
        names = {n.attr if isinstance(n, ast.Attribute)
                 else getattr(n, "id", None)
                 for n in ast.walk(dec)}
        if names & _CACHED_DECORATORS:
            return True
    return False


def _inside_loop(node):
    p = parent(node)
    while p is not None:
        if isinstance(p, (ast.For, ast.While, ast.AsyncFor)):
            return True
        if isinstance(p, (ast.FunctionDef, ast.AsyncFunctionDef,
                          ast.Lambda)):
            return False          # a def inside a loop runs later, cold
        p = parent(p)
    return False


# ---------------------------------------------------------------------------
# JG007 unbounded-blocking-call
# ---------------------------------------------------------------------------
#
# Scoped to the modules that talk to peers or schedule work across
# threads — the places where "blocks forever" means "a dead peer hangs
# the whole job" (dist_ps.py, engine.py, serving/).  The fix is either a
# real deadline or an EXPLICIT ``timeout=None`` keyword: the latter
# reads as "I mean forever" and self-documents the deliberate waits
# (a server waiting on its clients, a rendezvous waiting on the roster).

_JG007_SCOPE_RE = re.compile(
    r"(^|/)mxnet_tpu/(dist_ps|engine)\.py$|(^|/)mxnet_tpu/serving/")

_QUEUEISH_RE = re.compile(r"(^|_)(q|queue|inbox|mailbox)$", re.IGNORECASE)


@register("JG007", "unbounded-blocking-call",
          "a recv()/queue.get() with no timeout blocks forever on a dead "
          "or silent peer; pass a deadline, or an explicit timeout=None "
          "to document a deliberate unbounded wait")
def _jg007(mod, facts):
    if not _JG007_SCOPE_RE.search(mod.path.replace(os.sep, "/")):
        return
    for call in facts.calls:
        func = call.func
        if not isinstance(func, ast.Attribute):
            continue
        kwnames = {kw.arg for kw in call.keywords}
        if func.attr == "recv":
            if "timeout" in kwnames:
                continue          # bounded, or explicit timeout=None
            yield mod.finding(
                "JG007", call,
                "'.recv(...)' without a timeout blocks forever on a "
                "silent peer; pass timeout=<deadline> (or an explicit "
                "timeout=None where waiting forever is the contract)")
        elif func.attr == "get":
            # queue-shaped receivers only: dict .get(key, default) takes
            # positional args, Queue.get() does not
            if call.args or "timeout" in kwnames or "block" in kwnames:
                continue
            recv_name = func.value
            base = recv_name.attr if isinstance(recv_name, ast.Attribute) \
                else getattr(recv_name, "id", None)
            if base is None or not _QUEUEISH_RE.search(base):
                continue
            yield mod.finding(
                "JG007", call,
                "'%s.get()' without a timeout blocks forever when the "
                "producer dies; pass timeout= (or block=False) — or an "
                "explicit timeout=None for a deliberate wait" % base)


# ---------------------------------------------------------------------------
# JG008 shard-map-outside-substrate
# ---------------------------------------------------------------------------
#
# ISSUE 16 put every SPMD program on one mesh substrate
# (mxnet_tpu/parallel/mesh.py) precisely because jax's shard_map surface
# drifts between releases — the 15 seed failures were exactly this.  The
# single-substrate invariant was a grep test
# (test_mesh.py::test_no_shard_map_outside_the_substrate); this is its
# promotion to a real rule: alias-resolved (``from jax.experimental
# import shard_map as sm`` does not hide it), suppression-capable, and
# scoped to everything EXCEPT the substrate module itself.

_JG008_EXEMPT_RE = re.compile(r"(^|/)mxnet_tpu/parallel/mesh\.py$")


def _jg008_is_jax_shard_map(qual):
    if qual is None or not qual.startswith("jax."):
        return False
    return qual == "jax.shard_map" \
        or qual.startswith("jax.experimental.shard_map") \
        or qual.endswith(".shard_map")


@register("JG008", "shard-map-outside-substrate",
          "direct jax shard_map use outside parallel/mesh.py: the one "
          "place allowed to track jax's drifting shard_map API is the "
          "substrate module — route through "
          "mxnet_tpu.parallel.mesh.shard_map")
def _jg008(mod, facts):
    if _JG008_EXEMPT_RE.search(mod.path.replace(os.sep, "/")):
        return
    seen_lines = set()

    def fire(node, what):
        if node.lineno in seen_lines:
            return None
        seen_lines.add(node.lineno)
        return mod.finding(
            "JG008", node,
            "%s reaches jax's shard_map surface directly — only "
            "parallel/mesh.py (the substrate) may; use "
            "mxnet_tpu.parallel.mesh.shard_map so API drift is "
            "absorbed in one module" % what)

    for node in ast.walk(mod.tree):
        f = None
        if isinstance(node, ast.ImportFrom) and node.level == 0:
            module = node.module or ""
            if module.startswith("jax") and (
                    "shard_map" in module.split(".")
                    or any(a.name == "shard_map" for a in node.names)):
                f = fire(node, "import of '%s'" % module)
        elif isinstance(node, ast.Import):
            for a in node.names:
                if a.name.startswith("jax") \
                        and "shard_map" in a.name.split("."):
                    f = fire(node, "import of '%s'" % a.name)
                    break
        elif isinstance(node, (ast.Attribute, ast.Name)):
            # outermost expression of each attribute chain only; chains
            # resolve through the alias table, so `sm.shard_map(...)`
            # after `from jax.experimental import shard_map as sm` is
            # still caught
            if not isinstance(parent(node), ast.Attribute):
                qual = facts.qualname(node)
                if _jg008_is_jax_shard_map(qual):
                    f = fire(node, "'%s'" % qual)
        if f is not None:
            yield f


def _hot_functions(facts):
    """Hot seed = hot-looking name, or a def the cross-module project
    link marked hot (its caller's step path runs through another file);
    propagate hotness down the same-module call graph (a helper called
    from step() is on the step path)."""
    external = getattr(facts.mod, "external_hot", None) or ()
    by_name = {}
    for fd in facts.funcdefs:
        by_name.setdefault(fd.name, []).append(fd)
    calls_from = {}
    for fd in facts.funcdefs:
        callees = set()
        for node in ast.walk(fd):
            if isinstance(node, ast.Call):
                key = _callee_key(node.func)
                if key and key in by_name:
                    callees.add(key)
        calls_from[fd] = callees
    hot = {fd for fd in facts.funcdefs
           if HOT_NAME_RE.search(fd.name) or fd in external}
    grew = True
    while grew:
        grew = False
        for fd in list(hot):
            for callee in calls_from.get(fd, ()):
                for target in by_name.get(callee, ()):
                    if target not in hot:
                        hot.add(target)
                        grew = True
    return hot


# registered last: lockcheck imports the registry above, so the import
# must come after every name it needs is bound (no circularity — the
# tail import only runs once this module body is otherwise complete)
from . import lockcheck  # noqa: E402,F401
