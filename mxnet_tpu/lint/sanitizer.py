"""Runtime sanitizer: turn the worst TPU footguns into loud errors.

The static pass (``rules.py``) catches what it can see; this module catches
the same hazards at runtime, where there are no false positives:

* **Tracer-leak / host-sync-under-trace** (runtime JG001):
  ``NDArray.asnumpy`` — the single funnel every host materialization goes
  through (``__array__``, ``asscalar``, ``item``, ``__bool__``, ``__int__``,
  ``__float__``) — calls :func:`check_host_sync`.  Under an active JAX
  trace it raises (or warns) with the offending user frame: either the
  value IS a tracer (jax would die anyway, with a far worse message) or it
  is concrete and would silently bake into the compiled program as a
  constant — the nastier bug, because it "works" until the constant goes
  stale.

* **Engine ordering** (a lightweight happens-before checker):
  :func:`guard_task` wraps tasks pushed onto the host dependency engine and
  validates the declared read/write contract as they execute — no two
  writers of one var concurrently, writes land in push order, and no
  reader overlaps a writer.  This is how the reference's threaded engine
  bugs (mis-declared ``const_vars``/``mutable_vars``) surface as errors
  instead of corrupted checkpoints.

Gating: ``MXNET_SANITIZE=1`` raises, ``MXNET_SANITIZE=warn`` warns once per
site, unset/0 is a single module-bool check on the hot path.  Import-light:
jax is only touched once a check actually runs.
"""
from __future__ import annotations

import contextlib
import logging
import os
import threading
import traceback

__all__ = ["SanitizerError", "enabled", "mode", "configure",
           "refresh_from_env", "check_host_sync", "allow_host_sync",
           "guard_task", "engine_checker_enabled"]

_LOG = logging.getLogger("mxnet_tpu.sanitizer")


class SanitizerError(RuntimeError):
    """A TPU footgun caught at runtime with MXNET_SANITIZE=1."""


def _env_mode():
    raw = os.environ.get("MXNET_SANITIZE", "0").strip().lower()
    if raw in ("1", "true", "on", "yes", "raise"):
        return "raise"
    if raw == "warn":
        return "warn"
    return "off"


_MODE = _env_mode()


def enabled():
    return _MODE != "off"


def mode():
    return _MODE


def configure(mode=None):
    """Programmatic override: 'off' | 'warn' | 'raise' (tests, notebooks)."""
    global _MODE
    if mode is not None:
        if mode not in ("off", "warn", "raise"):
            raise ValueError("sanitizer mode must be off/warn/raise, got %r"
                             % (mode,))
        _MODE = mode
        with _warn_lock:
            _warned_sites.clear()     # re-arm once-per-site warnings


def refresh_from_env():
    global _MODE
    _MODE = _env_mode()
    with _warn_lock:
        _warned_sites.clear()


_warn_lock = threading.Lock()
_warned_sites = set()


def _violation(message, site=None):
    try:
        from .. import telemetry as _tel
        _tel.bump("sanitizer_violations")
        # the flight ring keeps the last violations for post-mortems:
        # in warn mode the log line scrolls away, the ring does not
        _tel.flight.record("sanitizer", message[:300],
                           site=str(site) if site is not None else None)
    except Exception:
        pass
    if _MODE == "raise":
        raise SanitizerError(message)
    if site is not None:
        # warn mode logs once per site: a sync inside a training-step
        # trace would otherwise flood the log once per step
        with _warn_lock:
            if site in _warned_sites:
                return
            _warned_sites.add(site)
    _LOG.warning("MXNET_SANITIZE: %s", message)


def _user_frame():
    """The first stack frame outside mxnet_tpu — where the footgun lives."""
    pkg = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    for frame in reversed(traceback.extract_stack()):
        if not frame.filename.startswith(pkg):
            return "%s:%d in %s: %s" % (frame.filename, frame.lineno,
                                        frame.name, frame.line or "")
    return "<inside mxnet_tpu>"


# ---------------------------------------------------------------------------
# tracer-leak / host-sync-under-trace
# ---------------------------------------------------------------------------

_sync_tls = threading.local()


@contextlib.contextmanager
def allow_host_sync():
    """Suppress the *sync-under-trace* check on this thread.

    For framework code whose host materialization is deliberate and
    observation-only — ``monitor.Monitor._render`` formatting its stat
    values while a user's trace happens to be open on the same thread.
    The value is concrete and never flows back into traced math, so the
    "baked constant" hazard the check guards against cannot occur; a
    genuine TRACER leak still raises (a tracer escaping into a print is
    a real bug regardless of who formats it)."""
    depth = getattr(_sync_tls, "depth", 0)
    _sync_tls.depth = depth + 1
    try:
        yield
    finally:
        _sync_tls.depth = depth


def check_host_sync(data, what="asnumpy"):
    """Validate one host materialization.  Called from NDArray.asnumpy;
    off mode returns after a single module-bool check."""
    if _MODE == "off":
        return
    import jax
    try:
        is_tracer = isinstance(data, jax.core.Tracer)
        tracing = not jax.core.trace_state_clean()
    except Exception:       # pragma: no cover - jax internals moved
        return
    if tracing and not is_tracer and getattr(_sync_tls, "depth", 0):
        return              # an allow_host_sync() scope: deliberate read
    if is_tracer:
        site = _user_frame()
        _violation(
            "tracer leak: NDArray.%s() on a value that is being traced by "
            "jax.jit/grad — the array escaped the traced function into "
            "host code.  Thread it through the function's return value "
            "instead.  Site: %s" % (what, site), site=("leak", site))
    elif tracing:
        site = _user_frame()
        _violation(
            "host sync under trace: NDArray.%s() called while a jax trace "
            "is active; the concrete value will be baked into the "
            "compiled program as a constant and silently go stale on "
            "later calls.  Site: %s" % (what, site), site=("sync", site))


# ---------------------------------------------------------------------------
# engine happens-before checker
# ---------------------------------------------------------------------------

def engine_checker_enabled():
    return _MODE != "off"


def push_scope(engine):
    """Lock held across ticket issuance AND the native enqueue, so the
    sanitizer's write tickets cannot interleave differently from the
    engine's own push order under concurrent pushers.  A no-op context
    when the checker is off."""
    if _MODE == "off":
        return contextlib.nullcontext()
    return _hb_state(engine).push_lock


class _VarState:
    __slots__ = ("readers", "writer", "pushed", "landed", "cancelled",
                 "forget")

    def __init__(self):
        self.readers = 0       # concurrent readers executing now
        self.writer = False    # a writer executing now
        self.pushed = 0        # write tickets issued (push order)
        self.landed = 0        # writes completed
        self.cancelled = set()  # tickets whose task will never execute
        self.forget = False    # delete_variable'd: drop once drained

    @property
    def drained(self):
        return (self.landed == self.pushed and not self.writer
                and self.readers == 0)

    def advance(self):
        """Skip landed past tickets abandoned before execution (a push
        that raised after taking its ticket)."""
        while self.landed in self.cancelled:
            self.cancelled.discard(self.landed)
            self.landed += 1


class _HBState:
    """Per-engine happens-before ledger (attached lazily to the engine)."""

    def __init__(self):
        self.lock = threading.Lock()
        self.push_lock = threading.RLock()
        self.vars = {}

    def var(self, v):
        st = self.vars.get(v)
        if st is None:
            st = self.vars[v] = _VarState()
        return st


def _hb_state(engine):
    st = getattr(engine, "_graftlint_hb", None)
    if st is None:
        st = engine._graftlint_hb = _HBState()
    return st


def forget_var(engine, var):
    """Drop a deleted engine variable's ledger entry (bounds the ledger
    over long runs with variable churn).

    Deletion mirrors the engine's own semantics: it only takes effect
    once every pending task on the var has drained — an eager pop while
    a queued write still holds a ticket would recreate the state at
    landed=0 and misreport that write as out of push order.
    """
    hb = getattr(engine, "_graftlint_hb", None)
    if hb is not None:
        with hb.lock:
            st = hb.vars.get(int(var))
            if st is None:
                return
            if st.drained:
                hb.vars.pop(int(var), None)
            else:
                st.forget = True     # reaped by the last draining task


def guard_task(engine, fn, const_vars, mutable_vars):
    """Wrap an engine task so the declared dependency contract is asserted
    while it runs.

    Invariants checked at execution time (the engine's scheduling is the
    thing under test, so violations mean mis-declared deps or a scheduler
    bug):

    * writes to one var execute in push order (each task takes a ticket
      per mutable var at push time and must be the next to land);
    * no two writers of one var run concurrently;
    * no reader of a var runs while a writer of it runs.
    """
    hb = _hb_state(engine)
    # mirror the engine's DeduplicateVarHandle: repeated handles are one
    # dependency, and a var both read and written counts as written
    mv = tuple(dict.fromkeys(int(v) for v in mutable_vars))
    cv = tuple(v for v in dict.fromkeys(int(v) for v in const_vars)
               if v not in set(mv))
    tickets = {}
    with hb.lock:
        for v in mv:
            st = hb.var(v)
            tickets[v] = st.pushed
            st.pushed += 1

    def guarded():
        problems = []
        with hb.lock:
            for v in mv:
                st = hb.var(v)
                st.advance()
                if st.writer:
                    problems.append("two writers of engine var %d running "
                                    "concurrently" % v)
                if st.readers:
                    problems.append("writer of engine var %d overlaps %d "
                                    "reader(s)" % (v, st.readers))
                if st.landed != tickets[v]:
                    problems.append(
                        "write %d to engine var %d executing out of push "
                        "order (expected write %d next)"
                        % (tickets[v], v, st.landed))
                st.writer = True
            for v in cv:
                st = hb.var(v)
                if st.writer and v not in mv:
                    problems.append("reader of engine var %d overlaps a "
                                    "writer" % v)
                st.readers += 1
        try:
            if problems:
                # site key excludes ticket numbers: one mis-declared task
                # re-pushed every step must warn once, not flood the log
                _violation("engine ordering: " + "; ".join(problems),
                           site=("engine",) + tuple(sorted(set(mv)
                                                           | set(cv))))
            return fn()
        finally:
            with hb.lock:
                for v in mv:
                    st = hb.var(v)
                    st.writer = False
                    st.landed += 1
                    st.advance()
                for v in cv:
                    hb.var(v).readers -= 1
                for v in set(mv) | set(cv):
                    st = hb.vars.get(v)
                    if st is not None and st.forget and st.drained:
                        hb.vars.pop(v, None)

    def cancel():
        """Roll back the tickets of a push that will never execute (the
        native enqueue raised) so later writes don't read as reordered."""
        with hb.lock:
            for v, t in tickets.items():
                st = hb.var(v)
                st.cancelled.add(t)
                st.advance()
                if st.forget and st.drained:
                    hb.vars.pop(v, None)

    guarded.cancel = cancel
    return guarded
