"""graftlint: TPU-footgun static analysis + runtime sanitizer.

Static pass (stdlib-only, safe for CI/pre-commit):

    python -m mxnet_tpu.lint mxnet_tpu/            # scan vs baseline
    python -m mxnet_tpu.lint --list-rules          # rule catalogue
    tools/graftlint.py --check-baseline            # stale-suppression rot

Runtime sanitizer (``mxnet_tpu.lint.sanitizer``): ``MXNET_SANITIZE=1``
turns tracer leaks / host-syncs-under-trace and engine-ordering violations
into hard errors with the offending user frame; ``=warn`` logs instead.

Trace tier (``mxnet_tpu.lint.tracecheck``): ``--trace`` /
``tools/graftcheck.py`` lowers every owned jit entry point AOT on CPU
from ShapeDtypeStruct specimens and walks the jaxprs with the JX rules;
``MXNET_TRACECHECK=1`` runs the same rules (plus the JX105
retrace-explainer) on every ``watch_jit`` compile event at runtime.

Rules: JG001 host-sync-under-trace, JG002 naked-jit, JG003 retrace-hazard,
JG004 donation-after-use, JG005 global-PRNG, JG006 env-read-in-hot-path;
JX101 baked-constant, JX102 dtype-widening, JX103 host-callback, JX104
donation-waste, JX105 retrace-explainer.  Docs: docs/LINT.md.

The analyzer halves (``core``/``rules``) load lazily (PEP 562): the
runtime imports ``lint.sanitizer`` on every ``import mxnet_tpu``, and that
path must not pay for the ast/tokenize machinery it never uses.
"""

_CORE_EXPORTS = ("Baseline", "Finding", "default_baseline_path",
                 "iter_python_files", "lint_file", "lint_paths",
                 "lint_source", "lint_sources", "load_baseline",
                 "repo_root")

__all__ = list(_CORE_EXPORTS) + ["RULES", "TRACE_RULES"]


def __getattr__(name):
    if name in _CORE_EXPORTS:
        from . import core
        return getattr(core, name)
    if name == "RULES":
        from .rules import RULES
        return RULES
    if name == "TRACE_RULES":
        from .tracecheck import TRACE_RULES
        return TRACE_RULES
    raise AttributeError("module %r has no attribute %r"
                         % (__name__, name))
