"""Predict-only inference API.

Reference analogue: the amalgamation build's C predict API
(``include/mxnet/c_predict_api.h`` / ``src/c_api/c_predict_api.cc`` —
MXPredCreate / MXPredSetInput / MXPredForward / MXPredGetOutput): a
minimal deployment surface that loads a ``-symbol.json`` + ``.params``
checkpoint and runs forward passes, nothing else.

TPU-native: the whole graph compiles to one jitted XLA program at
``Predictor`` creation; repeated ``forward`` calls reuse it.

    pred = Predictor.load("model-prefix", epoch=3,
                          input_shapes={"data": (1, 3, 224, 224)})
    probs = pred.forward(data=batch)[0]
"""
from __future__ import annotations

import numpy as np

from . import ndarray as nd
from .base import MXNetError
from .context import cpu

__all__ = ["Predictor"]


class Predictor(object):
    """A bound inference-only executor over a saved checkpoint."""

    def __init__(self, symbol, arg_params, aux_params, input_shapes,
                 ctx=None):
        ctx = ctx or cpu()
        self._ctx = ctx
        self._input_names = list(input_shapes)
        # kept for the serving tier: bucket-padded AOT variants re-infer
        # batch-dependent arg shapes from the symbol (serving/program.py)
        self._symbol = symbol
        self._input_shapes = {n: tuple(s) for n, s in input_shapes.items()}
        args = {}
        shapes = dict(input_shapes)
        arg_shapes, _, aux_shapes = symbol.infer_shape(**shapes)
        for name, shape in zip(symbol.list_arguments(), arg_shapes):
            if name in input_shapes:
                args[name] = nd.zeros(input_shapes[name], ctx=ctx)
            elif name in arg_params:
                args[name] = arg_params[name].as_in_context(ctx)
            elif name.endswith("_label") and shape is not None:
                # loss-head labels (softmax_label etc., the reference's
                # `<head>_label` naming convention) are unused at
                # inference: zero-bind them like Module.predict does.
                # Anything else missing is a real checkpoint defect.
                args[name] = nd.zeros(shape, ctx=ctx)
            else:
                raise MXNetError("checkpoint is missing parameter %r" % name)
        auxs = {}
        for name, shape in zip(symbol.list_auxiliary_states(), aux_shapes):
            if name not in aux_params:
                raise MXNetError("checkpoint is missing aux state %r" % name)
            auxs[name] = aux_params[name].as_in_context(ctx)
        self._exe = symbol.bind(ctx, args, aux_states=auxs, grad_req="null")
        self.output_names = symbol.list_outputs()

    @classmethod
    def load(cls, prefix, epoch, input_shapes, ctx=None):
        """Build a predictor from ``prefix-symbol.json`` +
        ``prefix-{epoch:04d}.params`` (ref MXPredCreate)."""
        from .model import load_checkpoint
        symbol, arg_params, aux_params = load_checkpoint(prefix, epoch)
        return cls(symbol, arg_params, aux_params, input_shapes, ctx=ctx)

    def set_input(self, **inputs):
        """Load input arrays by name (ref MXPredSetInput)."""
        for name, value in inputs.items():
            if name not in self._input_names:
                raise MXNetError("unknown input %r (have %s)"
                                 % (name, self._input_names))
            arr = value if isinstance(value, nd.NDArray) \
                else nd.array(np.asarray(value, np.float32))
            arr.copyto(self._exe.arg_dict[name])

    def forward(self, **inputs):
        """Set inputs (optional) and run inference; returns the output
        list (ref MXPredForward + MXPredGetOutput)."""
        if inputs:
            self.set_input(**inputs)
        return self._exe.forward(is_train=False)

    def get_output(self, index=0):
        if self._exe.outputs is None:
            raise MXNetError("run forward() first")
        return self._exe.outputs[index]


def _tracecheck_predictor():
    """Specimen Predictor for graftcheck: a tiny MLP with a loss head, so
    the zero-bound ``*_label`` path is part of the traced program exactly
    as a real checkpoint binds it.  Params are zeros — nothing is
    executed, only shapes/dtypes matter."""
    from . import ndarray as nd_mod
    from . import symbol as S
    data = S.Variable("data")
    net = S.FullyConnected(data, num_hidden=8, name="pt_fc1")
    net = S.Activation(net, act_type="relu")
    net = S.FullyConnected(net, num_hidden=4, name="pt_fc2")
    net = S.SoftmaxOutput(net, name="softmax")
    input_shapes = {"data": (2, 16)}
    arg_shapes, _, aux_shapes = net.infer_shape(**input_shapes)
    arg_params = {
        name: nd_mod.zeros(shape)
        for name, shape in zip(net.list_arguments(), arg_shapes)
        if name not in input_shapes and not name.endswith("_label")}
    aux_params = {
        name: nd_mod.zeros(shape)
        for name, shape in zip(net.list_auxiliary_states(), aux_shapes)}
    return Predictor(net, arg_params, aux_params, input_shapes)


def tracecheck_programs():
    """AOT specimen for graftcheck: the predictor's eval program through
    the Predictor construction path (checkpoint-shaped params, zero-bound
    loss labels) — the one owned jit surface the executor specimens do
    not exercise."""
    import jax

    from . import random as _random
    pred = _tracecheck_predictor()
    ex = pred._exe
    key = _random.next_key()
    spec = lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype)  # noqa: E731
    arg_specs = [spec(ex.arg_dict[n]) for n in ex.arg_names]
    aux_specs = [spec(ex.aux_dict[n]) for n in ex.aux_names]
    key_spec = jax.ShapeDtypeStruct(key.shape, key.dtype)
    return [("predictor_forward", ex._eval_jit,
             (arg_specs, aux_specs, key_spec), {})]


class _EmbeddedPredictor(object):
    """Byte-oriented shim behind the native C predict API
    (``native/predict_api.cc`` — ref ``include/mxnet/c_predict_api.h``).

    The C side traffics only in raw buffers: inputs arrive as float32
    bytes, outputs leave as float32 bytes plus a shape tuple, so the
    embedding layer never needs the numpy C API.
    """

    def __init__(self, symbol_json, param_bytes, input_names, input_shapes,
                 dev_type=1, dev_id=0):
        from . import context, symbol as sym_mod
        from .model import split_saved_params
        from .ndarray import utils as nd_utils
        symbol = sym_mod.load_json(symbol_json)
        arg_params, aux_params = split_saved_params(
            nd_utils.load_from_bytes(param_bytes))
        if dev_type >= 2 and context.num_tpus():
            ctx = context.tpu(dev_id)
        else:
            ctx = context.cpu(dev_id)
        shapes = {n: tuple(int(x) for x in s)
                  for n, s in zip(input_names, input_shapes)}
        self._pred = Predictor(symbol, arg_params, aux_params, shapes,
                               ctx=ctx)
        self._shapes = shapes
        self._inputs = {}
        self._outputs = []

    def set_input(self, key, raw):
        if key not in self._shapes:
            raise MXNetError("unknown input %r" % key)
        arr = np.frombuffer(raw, dtype=np.float32).reshape(
            self._shapes[key]).copy()
        self._inputs[key] = arr

    def forward(self):
        outs = self._pred.forward(**self._inputs)
        self._outputs = [np.ascontiguousarray(o.asnumpy(),
                                              dtype=np.float32)
                         for o in outs]

    def num_outputs(self):
        return len(self._outputs)

    def get_output_shape(self, index):
        return tuple(int(s) for s in self._outputs[index].shape)

    def get_output_bytes(self, index):
        return self._outputs[index].tobytes()
