"""Predict-only inference API.

Reference analogue: the amalgamation build's C predict API
(``include/mxnet/c_predict_api.h`` / ``src/c_api/c_predict_api.cc`` —
MXPredCreate / MXPredSetInput / MXPredForward / MXPredGetOutput): a
minimal deployment surface that loads a ``-symbol.json`` + ``.params``
checkpoint and runs forward passes, nothing else.

TPU-native: the whole graph compiles to one jitted XLA program at
``Predictor`` creation; repeated ``forward`` calls reuse it.

    pred = Predictor.load("model-prefix", epoch=3,
                          input_shapes={"data": (1, 3, 224, 224)})
    probs = pred.forward(data=batch)[0]
"""
from __future__ import annotations

import numpy as np

from . import ndarray as nd
from .base import MXNetError
from .context import cpu

__all__ = ["Predictor"]


class Predictor(object):
    """A bound inference-only executor over a saved checkpoint."""

    def __init__(self, symbol, arg_params, aux_params, input_shapes,
                 ctx=None):
        ctx = ctx or cpu()
        self._ctx = ctx
        self._input_names = list(input_shapes)
        args = {}
        shapes = dict(input_shapes)
        arg_shapes, _, aux_shapes = symbol.infer_shape(**shapes)
        for name, shape in zip(symbol.list_arguments(), arg_shapes):
            if name in input_shapes:
                args[name] = nd.zeros(input_shapes[name], ctx=ctx)
            elif name in arg_params:
                args[name] = arg_params[name].as_in_context(ctx)
            else:
                raise MXNetError("checkpoint is missing parameter %r" % name)
        auxs = {}
        for name, shape in zip(symbol.list_auxiliary_states(), aux_shapes):
            if name not in aux_params:
                raise MXNetError("checkpoint is missing aux state %r" % name)
            auxs[name] = aux_params[name].as_in_context(ctx)
        self._exe = symbol.bind(ctx, args, aux_states=auxs, grad_req="null")
        self.output_names = symbol.list_outputs()

    @classmethod
    def load(cls, prefix, epoch, input_shapes, ctx=None):
        """Build a predictor from ``prefix-symbol.json`` +
        ``prefix-{epoch:04d}.params`` (ref MXPredCreate)."""
        from .model import load_checkpoint
        symbol, arg_params, aux_params = load_checkpoint(prefix, epoch)
        return cls(symbol, arg_params, aux_params, input_shapes, ctx=ctx)

    def set_input(self, **inputs):
        """Load input arrays by name (ref MXPredSetInput)."""
        for name, value in inputs.items():
            if name not in self._input_names:
                raise MXNetError("unknown input %r (have %s)"
                                 % (name, self._input_names))
            arr = value if isinstance(value, nd.NDArray) \
                else nd.array(np.asarray(value, np.float32))
            arr.copyto(self._exe.arg_dict[name])

    def forward(self, **inputs):
        """Set inputs (optional) and run inference; returns the output
        list (ref MXPredForward + MXPredGetOutput)."""
        if inputs:
            self.set_input(**inputs)
        return self._exe.forward(is_train=False)

    def get_output(self, index=0):
        if self._exe.outputs is None:
            raise MXNetError("run forward() first")
        return self._exe.outputs[index]
