"""Preemption-safe training checkpoints: async, sharded, atomic, elastic.

Reference capability surface: MXNet's ``kvstore.save_optimizer_states``
/ ``model.load_checkpoint`` (PAPER.md layers 3/7), rebuilt for a
production TPU fleet where the scheduler WILL SIGTERM the job:

* **Async sharded snapshots.**  ``CheckpointManager.save()`` copies
  params, per-slot optimizer state (the fused-trainer state tree plus
  its update counts), the data-iterator cursor, the RNG state, and the
  telemetry step clock device→host ON THE CALLER (so the snapshot is a
  consistent cut of one step, immune to later donated-buffer rebinds),
  then hands the host tree to a background writer thread.  Optimizer
  state is written as one shard per replica (``reshard.py`` layout);
  every shard carries a CRC32 in ``manifest.json``; the commit is
  write-to-tmp + ``os.rename`` like ``telemetry/flight.py``; transient
  write failures retry with exponential backoff; retention keeps the
  newest ``MXNET_CKPT_KEEP`` complete checkpoints.
* **Preemption path.**  ``install_preemption_handler()`` chains a
  SIGTERM handler in front of the flight recorder's: the signal only
  *requests* a final synchronous checkpoint, which the next step
  boundary (``hooks.note_step_boundary`` — called by ``Trainer.step``
  and the module fit loop) writes before re-raising into the previous
  handler (flight dump + death by SIGTERM, so exit status still says
  "killed").  A grace timer (``MXNET_CKPT_GRACE_SECS``) guarantees the
  process dies even when no boundary ever arrives — wedged collective,
  stuck ``engine.push`` — without touching any lock the interrupted
  thread may hold.
* **Elastic resume.**  ``restore()`` walks checkpoints newest-first,
  validates sizes + checksums against the manifest, falls back to the
  previous complete checkpoint on any corruption (never crashes), and
  tolerates a changed replica count by streaming the saved shards into
  the current layout (see ``reshard.py``).  Restoring cursor + RNG makes
  the post-resume loss trajectory bitwise-identical on CPU.
"""
from __future__ import annotations

import json
import os
import pickle
import queue
import shutil
import signal
import threading
import time
import zlib

import numpy as np

from .. import chaos as _chaos
from .. import ndarray as nd
from .. import random as _random
from .. import telemetry as _tel
from ..ndarray import NDArray
from ..telemetry import flight as _flight
from . import hooks, reshard

__all__ = ["CheckpointManager", "install_preemption_handler"]

_MANIFEST = "manifest.json"
_CKPT_PREFIX = "ckpt-"


def _env_int(name, default):
    try:
        return int(os.environ.get(name, default))
    except ValueError:
        return default


def _env_float(name, default):
    try:
        return float(os.environ.get(name, default))
    except ValueError:
        return default


# ---------------------------------------------------------------------------
# host-tree conversion: NDArray-structured state <-> pure numpy trees
# ---------------------------------------------------------------------------

def _tree_to_np(tree):
    """Optimizer state tree -> numpy tree (the device→host cut)."""
    if tree is None:
        return None
    if isinstance(tree, NDArray):
        return np.asarray(tree.asnumpy())
    if isinstance(tree, (list, tuple)):
        return tuple(_tree_to_np(t) for t in tree)
    raise TypeError("unsupported optimizer state leaf %r" % type(tree))


def _np_to_state(tree, ctx):
    """Numpy tree -> NDArray state tree on *ctx* (None = default ctx).

    Large leaves upload through the chunked device-put
    (``parallel.collective``, arXiv 2112.01075): an elastic restore
    streams each leaf onto its device in bounded chunks instead of
    staging a second full host copy beside the target buffer."""
    if tree is None:
        return None
    if isinstance(tree, np.ndarray):
        from ..parallel import collective as _coll
        if tree.nbytes > _coll.chunk_bytes():
            from ..context import current_context
            dev = (ctx or current_context()).jax_device
            return NDArray(_coll.chunked_device_put(tree, dev), ctx=ctx)
        return nd.array(tree, ctx=ctx, dtype=tree.dtype)
    return tuple(_np_to_state(t, ctx) for t in tree)


class CheckpointManager:
    """Snapshot/restore a training run; one instance per run.

    Exactly one of *trainer* (``gluon.Trainer``) or *module*
    (``module.BaseModule`` after ``init_optimizer``) supplies the
    params + optimizer state; *data_iter* (anything implementing the
    ``DataIter`` checkpoint-state protocol) is optional but required for
    bitwise-resumable input pipelines.

    Constructing the manager registers it with ``checkpoint.hooks`` so
    the training loops' step-boundary notifications reach it.  Call
    :meth:`close` when the run is over: it drains pending writes, stops
    the writer thread, detaches the hooks, and restores the previous
    SIGTERM handler — a merely superseded manager (a newer one
    registered) keeps its thread and references alive until closed.
    """

    def __init__(self, directory, trainer=None, module=None, data_iter=None,
                 every_steps=None, keep=None, num_shards=None,
                 retries=None):
        if (trainer is None) == (module is None):
            raise ValueError("pass exactly one of trainer= or module=")
        self._dir = os.path.abspath(directory)
        os.makedirs(self._dir, exist_ok=True)
        self._trainer = trainer
        self._module = module
        self._data_iter = data_iter
        self._every_steps = int(every_steps
                                if every_steps is not None
                                else _env_int("MXNET_CKPT_EVERY_STEPS", 0))
        self._keep = max(1, int(keep if keep is not None
                                else _env_int("MXNET_CKPT_KEEP", 3)))
        if num_shards is None:
            num_shards = _env_int("MXNET_CKPT_SHARDS", 0)
        # an explicit shard count (argument or env) is pinned; otherwise
        # the count tracks the trainer's live ZeRO-1 layout (below),
        # reverting to this auto default when ZeRO deactivates
        self._n_shards_explicit = bool(num_shards)
        if not num_shards:
            import jax
            num_shards = max(1, jax.local_device_count())
        self._n_shards = self._auto_shards = max(1, int(num_shards))
        self._retries = max(1, int(retries if retries is not None
                                   else _env_int("MXNET_CKPT_RETRIES", 3)))
        self._grace_secs = _env_float("MXNET_CKPT_GRACE_SECS", 30.0)

        self._step = 0
        self._epoch = None
        self._batch = None
        self.last_committed_step = None
        self.last_error = None
        self._last_enqueued = None
        self._active_tmp = None
        # the guardian's rollback target: survives restarts via a marker
        # file so a resumed run keeps its known-good anchor
        self._pinned_step = self._load_pin()

        self._preempt_at = None
        self._final_done = False
        self._grace_timer = None
        self._sigterm_installed = False
        self._prev_sigterm = None

        self._queue = queue.Queue(maxsize=2)   # backpressure bounds host mem
        self._writer = threading.Thread(target=self._writer_loop,
                                        name="mxnet-ckpt-writer",
                                        daemon=True)
        self._writer.start()
        hooks.register(self)

    # -- lifecycle ---------------------------------------------------------

    def close(self):
        """Drain pending writes, stop the writer thread, detach from the
        step-boundary hooks, and give SIGTERM back to the previous
        handler (a closed manager would otherwise pin its
        trainer/module — and swallow preemption signals its boundaries
        can no longer honor — for the process lifetime)."""
        self.wait()
        hooks.unregister(self)
        if self._writer.is_alive():
            self._queue.put(None)        # writer-loop stop sentinel
            self._writer.join(timeout=10.0)
        if self._sigterm_installed:
            try:
                signal.signal(signal.SIGTERM, self._prev_sigterm)
                self._sigterm_installed = False
            except (ValueError, OSError):
                pass                     # not the main thread: leave it
        # a pending preemption dies with the manager: the armed grace
        # timer would otherwise os._exit a process that moved on to
        # post-run work after detaching
        self._final_done = True
        if self._grace_timer is not None:
            self._grace_timer.cancel()
            # the signal/timer side is lock-free BY DESIGN (_on_sigterm
            # runs in signal context where taking locks can deadlock);
            # both fields are single-word writes and every reader
            # tolerates either ordering
            self._grace_timer = None      # graftlint: disable=JG011
        self._preempt_at = None           # graftlint: disable=JG011

    def wait(self):
        """Block until every enqueued snapshot has been committed (or
        exhausted its retries)."""
        self._queue.join()

    @property
    def step(self):
        return self._step

    # -- last-good pinning (the guardian's rollback anchor) ----------------

    _PIN_FILE = "last_good.json"

    def _load_pin(self):
        try:
            with open(os.path.join(self._dir, self._PIN_FILE)) as fh:
                return int(json.load(fh)["step"])
        except Exception:
            return None

    @property
    def last_good_step(self):
        """The pinned known-good checkpoint step, or None."""
        return self._pinned_step

    def pin_last_good(self, step=None):
        """Mark checkpoint *step* (default: the newest committed one) as
        known-good: retention never evicts it, and the guardian's
        auto-rollback targets it.  Persisted as an atomic marker file so
        the pin survives a restart.  Returns the pinned step or None."""
        if step is None:
            step = self.last_committed_step
        if step is None:
            return None
        step = int(step)
        self._pinned_step = step
        tmp = os.path.join(self._dir, self._PIN_FILE + ".tmp-%d"
                           % os.getpid())
        try:
            with open(tmp, "w") as fh:
                json.dump({"step": step}, fh)
                fh.flush()
                os.fsync(fh.fileno())
            os.rename(tmp, os.path.join(self._dir, self._PIN_FILE))
        except OSError:
            # the in-memory pin still protects this process's retention;
            # only restart persistence degrades.  Remove the torn tmp —
            # the _retain sweep only handles directories.
            try:
                os.unlink(tmp)
            except OSError:
                pass
        _tel.set_gauge("checkpoint_pinned_step", step)
        _flight.record("checkpoint", "pin-last-good", step=step)
        return step

    # -- snapshot capture (caller thread: the device→host cut) -------------

    def _capture(self, step, reason):
        if self._trainer is not None:
            params, optim, state = self._capture_trainer()
            # MXNET_ZERO: one checkpoint shard per update replica, so
            # each shard file is written from state that already lives
            # on that replica (the reshard.py round-robin layout on
            # device AND on disk — no gather-to-save).  An explicit
            # shard count stays pinned.
            plan = getattr(self._trainer, "_zero_plan", None)
            if not self._n_shards_explicit:
                self._n_shards = max(1, int(plan.n)) if plan is not None \
                    else self._auto_shards
        else:
            params, optim, state = self._capture_module()
        state["reason"] = reason
        state["epoch"] = self._epoch
        state["batch"] = self._batch
        if self._data_iter is not None:
            get = getattr(self._data_iter, "get_checkpoint_state", None)
            state["iterator"] = get() if get is not None else None
        state["rng"] = _random.get_state()
        state["telemetry_steps"] = _flight.step_count()
        return {"step": int(step), "n_shards": self._n_shards,
                "params": params, "optim": optim, "state": state}

    def _capture_trainer(self):
        t = self._trainer
        params = {"%d:%s" % (slot, p.name): p.data().asnumpy()
                  for slot, p in enumerate(t._params)}
        optim = {slot: _tree_to_np(st)
                 for slot, st in t._updater.states.items()}
        opt = t._optimizer
        state = {"kind": "trainer",
                 "index_update_count": {int(k): int(v) for k, v in
                                        opt._index_update_count.items()},
                 "num_update": int(opt.num_update)}
        kv = t._kvstore
        if kv is not None:
            state["kvstore_updater"] = kv.get_checkpoint_state()
        return params, optim, state

    def _capture_module(self):
        m = self._module
        arg, aux = m.get_params()
        params = {"arg:%s" % k: v.asnumpy() for k, v in arg.items()}
        params.update({"aux:%s" % k: v.asnumpy() for k, v in aux.items()})
        optim, counts, num_update = {}, {}, 0
        upd = getattr(m, "_updater", None)
        if upd is not None:
            optim = {slot: _tree_to_np(st) for slot, st in
                     upd.states.items()}
        opt = getattr(m, "_optimizer", None)
        if opt is not None:
            counts = {k: int(v) for k, v in
                      opt._index_update_count.items()}
            num_update = int(opt.num_update)
        state = {"kind": "module", "index_update_count": counts,
                 "num_update": num_update}
        kv = getattr(m, "_kvstore", None)
        if kv is not None:
            state["kvstore_updater"] = kv.get_checkpoint_state()
        return params, optim, state

    # -- save --------------------------------------------------------------

    def save(self, step=None, sync=False, reason="periodic"):
        """Snapshot now; serialize + commit on the background writer.

        ``sync=True`` blocks until the commit (or its final retry)
        finishes and returns whether *step* is on disk.  Saving the same
        step twice is a no-op (the periodic trigger and an explicit
        ``maybe_save`` may both fire on one boundary).
        """
        if step is None:
            step = self._step
        else:
            step = int(step)
            self._step = max(self._step, step)
        if self._last_enqueued == step:
            if sync:                     # already queued: wait it out
                self._queue.join()
                return self.last_committed_step == step
            return True
        snap = self._capture(step, reason)
        # racing the writer's failure-path reset (_write_with_retry) is
        # benign: worst case one extra re-save of an already-landed step
        self._last_enqueued = step        # graftlint: disable=JG011
        self._queue.put(snap)
        if sync:
            self._queue.join()
            return self.last_committed_step is not None \
                and self.last_committed_step >= step
        return True

    def maybe_save(self, step=None):
        """Periodic trigger: save iff ``every_steps`` divides *step*."""
        if step is not None:
            self._step = max(self._step, int(step))
        if self._every_steps and self._step \
                and self._step % self._every_steps == 0:
            return self.save(self._step)
        return False

    # -- background writer -------------------------------------------------

    def _writer_loop(self):
        while True:
            snap = self._queue.get()
            if snap is None:          # close() stop sentinel
                self._queue.task_done()
                return
            try:
                self._write_with_retry(snap)
            finally:
                self._queue.task_done()

    def _write_with_retry(self, snap):
        delay = 0.1
        for attempt in range(self._retries):
            try:
                self._commit(snap)
                self.last_error = None
                return True
            except Exception as exc:   # transient IO: retry with backoff
                self.last_error = repr(exc)
                self._cleanup_tmp()
                if attempt + 1 < self._retries:
                    _tel.bump("checkpoint_write_retries")
                    time.sleep(delay)
                    delay *= 2
        _flight.record("checkpoint", "write-failed", step=snap["step"],
                       error=self.last_error)
        # un-dedupe: a later explicit save(step) must re-attempt this
        # step instead of no-op'ing against a write that never landed
        if self._last_enqueued == snap["step"]:
            self._last_enqueued = None
        return False

    def _put_file(self, tmp, name, obj, files):
        if _chaos.active():       # per-file IO seam: `fail` faults land
            act = _chaos.decide("ckpt.io")   # in the retry-with-backoff
            if act is not None:              # path like real disk flakes
                _chaos.apply_inline(act)
        blob = pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL)
        with open(os.path.join(tmp, name), "wb") as fh:
            fh.write(blob)
            fh.flush()
            os.fsync(fh.fileno())
        files[name] = {"bytes": len(blob),
                       "crc32": zlib.crc32(blob) & 0xFFFFFFFF}

    def _commit(self, snap):
        """One atomic checkpoint: shards + manifest into a tmp dir, then
        a same-filesystem rename (the ``flight.py`` torn-read rule)."""
        t0 = time.monotonic()
        step = snap["step"]
        final = os.path.join(self._dir, "%s%010d" % (_CKPT_PREFIX, step))
        tmp = final + ".tmp-%d" % os.getpid()
        self._active_tmp = tmp
        if os.path.isdir(tmp):
            shutil.rmtree(tmp)
        os.makedirs(tmp)
        files = {}
        self._put_file(tmp, "params.pkl", snap["params"], files)
        shards = reshard.shard_states(snap["optim"], snap["n_shards"])
        for k, payload in enumerate(shards):
            self._put_file(tmp, "optim-%05d-of-%05d.pkl" % (k, len(shards)),
                           payload, files)
        self._put_file(tmp, "state.pkl", snap["state"], files)
        manifest = {"version": 1, "step": step,
                    "n_shards": snap["n_shards"],
                    "created_unix": time.time(),
                    "files": files, "complete": True}
        mpath = os.path.join(tmp, _MANIFEST)
        with open(mpath, "w") as fh:
            json.dump(manifest, fh, indent=1)
            fh.flush()
            os.fsync(fh.fileno())
        if os.path.isdir(final):       # re-save of the same step
            shutil.rmtree(final)
        os.rename(tmp, final)
        self._active_tmp = None
        self.last_committed_step = step
        total = sum(f["bytes"] for f in files.values())
        _tel.bump("checkpoint_saves")
        _tel.set_gauge("checkpoint_last_step", step)
        _tel.set_gauge("checkpoint_bytes", total)
        _tel.set_gauge("checkpoint_write_seconds",
                       time.monotonic() - t0)
        _flight.record("checkpoint", "commit", step=step, bytes=total,
                       shards=len(shards), reason=snap["state"]["reason"])
        self._retain()

    def _cleanup_tmp(self):
        tmp, self._active_tmp = self._active_tmp, None
        if tmp and os.path.isdir(tmp):
            shutil.rmtree(tmp, ignore_errors=True)

    def _retain(self):
        """Keep the newest ``keep`` complete checkpoints — plus the
        ``last_good``-pinned one, whatever its age: evicting the only
        verified-healthy state would turn the guardian's rollback into a
        no-op exactly when a sick run needs it.  Sweep the rest and any
        abandoned tmp dirs (not the one mid-write)."""
        complete = [(s, p) for s, p, m in self._list_checkpoints()
                    if m is not None and m.get("complete")]
        for step, path in complete[self._keep:]:
            if step == self._pinned_step:
                continue
            shutil.rmtree(path, ignore_errors=True)
        for name in os.listdir(self._dir):
            path = os.path.join(self._dir, name)
            if ".tmp-" in name and path != self._active_tmp \
                    and os.path.isdir(path):
                shutil.rmtree(path, ignore_errors=True)

    def discard_newer_than(self, step):
        """Evict every checkpoint newer than *step* (the guardian's
        rollback epilogue): after a rollback those checkpoints are the
        abandoned, unverified timeline — leaving them on disk means a
        restart's newest-first ``restore()`` would resume exactly the
        state the rollback fled.  Returns the discarded steps."""
        step = int(step)
        # drain the async writer first: an in-flight snapshot for a
        # newer (poisoned) step committing AFTER the sweep would
        # resurrect the abandoned timeline — and then get pinned by the
        # next clean step's last-good advance
        self.wait()
        discarded = []
        for ckpt_step, path, _manifest in self._list_checkpoints():
            if ckpt_step > step:
                shutil.rmtree(path, ignore_errors=True)
                discarded.append(ckpt_step)
        if discarded:
            if self._last_enqueued is not None \
                    and self._last_enqueued > step:
                self._last_enqueued = None     # re-saves must re-attempt
            if self.last_committed_step is not None \
                    and self.last_committed_step > step:
                # not racing _commit: self.wait() above drained the
                # writer queue, so the writer thread is parked in get()
                self.last_committed_step = step  # graftlint: disable=JG011
            _flight.record("checkpoint", "discard-newer", than=step,
                           discarded=discarded)
        return discarded

    # -- restore -----------------------------------------------------------

    def _list_checkpoints(self):
        """[(step, path, manifest-or-None)] newest first."""
        entries = []
        try:
            names = os.listdir(self._dir)
        except OSError:
            return entries
        for name in names:
            if not name.startswith(_CKPT_PREFIX) or ".tmp-" in name:
                continue
            path = os.path.join(self._dir, name)
            if not os.path.isdir(path):
                continue
            try:
                step = int(name[len(_CKPT_PREFIX):])
            except ValueError:
                continue
            manifest = None
            try:
                with open(os.path.join(path, _MANIFEST)) as fh:
                    manifest = json.load(fh)
            except Exception:
                pass
            entries.append((step, path, manifest))
        entries.sort(key=lambda e: e[0], reverse=True)
        return entries

    def _read_verified(self, path, manifest, name):
        meta = manifest["files"][name]
        with open(os.path.join(path, name), "rb") as fh:
            blob = fh.read()
        if len(blob) != meta["bytes"] \
                or (zlib.crc32(blob) & 0xFFFFFFFF) != meta["crc32"]:
            raise IOError("shard %s failed checksum" % name)
        return pickle.loads(blob)

    def _load(self, path, manifest):
        """Validated payload of one checkpoint dir; raises on any
        missing/corrupt shard.  Optimizer shards are streamed one file
        at a time into the merged dict — the elastic-restore half of the
        ``reshard`` layout: the saved shard count never has to match the
        current one."""
        if not manifest or not manifest.get("complete"):
            raise IOError("manifest missing or incomplete")
        names = set(manifest["files"])
        for name in names:
            if not os.path.exists(os.path.join(path, name)):
                raise IOError("shard %s missing" % name)
        params = self._read_verified(path, manifest, "params.pkl")
        state = self._read_verified(path, manifest, "state.pkl")
        optim = {}
        for name in sorted(n for n in names if n.startswith("optim-")):
            reshard.merge_into(optim,
                               self._read_verified(path, manifest, name))
        return {"step": int(manifest["step"]),
                "saved_shards": int(manifest.get("n_shards", 1)),
                "params": params, "optim": optim, "state": state}

    def restore(self, step=None):
        """Load a checkpoint into the trainer/module, iterator, and RNG.

        Default: the newest complete-and-valid one.  With ``step=`` the
        TARGETED checkpoint is tried first even when newer ones exist
        (the guardian's rollback: newer checkpoints are exactly the
        unverified ones).  A corrupt or missing target falls back —
        non-fatally — to the remaining checkpoints: older ones first
        (newest-first among them), then the newer group oldest-first as
        the last resort (closest to the last verified state).  Returns
        the restored step, or None when nothing restorable exists.
        """
        entries = self._list_checkpoints()       # newest first
        if step is not None:
            step = int(step)
            target = [e for e in entries if e[0] == step]
            older = [e for e in entries if e[0] < step]
            # the last-resort newer group goes OLDEST-first: when a
            # corrupt pin forces us into unverified territory, the
            # checkpoint closest to the last verified state is the
            # least-bad choice — newest-first would land on the one
            # furthest into the abandoned timeline
            newer = [e for e in entries if e[0] > step][::-1]
            entries = target + older + newer
        for step, path, manifest in entries:
            try:
                payload = self._load(path, manifest)
                self._apply(payload)
            except Exception as exc:
                _tel.bump("checkpoint_restore_fallbacks")
                _flight.record("checkpoint", "restore-fallback",
                               step=step, error=repr(exc)[:300])
                continue
            self._step = step
            self.last_committed_step = step
            self._last_enqueued = step      # don't re-save what we loaded
            if payload["saved_shards"] != self._n_shards:
                moves = reshard.redistribution_plan(
                    payload["optim"].keys(), payload["saved_shards"],
                    self._n_shards)
                _flight.record("checkpoint", "reshard",
                               from_shards=payload["saved_shards"],
                               to_shards=self._n_shards, moves=len(moves))
            _tel.bump("checkpoint_restores")
            _tel.set_gauge("checkpoint_last_step", step)
            return step
        return None

    def _apply(self, payload):
        state = payload["state"]
        if state["kind"] == "trainer":
            if self._trainer is None:
                raise ValueError("trainer checkpoint but manager wraps "
                                 "a module")
            self._apply_trainer(payload)
        else:
            if self._module is None:
                raise ValueError("module checkpoint but manager wraps "
                                 "a trainer")
            self._apply_module(payload)
        self._epoch = state.get("epoch")
        self._batch = state.get("batch")
        # cursor/RNG/clock phase: NON-fatal.  The model state above
        # applied cleanly, so the checkpoint is good — an incompatible
        # iterator state (the user swapped iterator types across the
        # restart) must not trigger a fallback to an older checkpoint
        # that would fail the same way on top of already-applied params.
        # The run resumes with restored weights and a restarted stream.
        try:
            if self._data_iter is not None \
                    and state.get("iterator") is not None:
                self._data_iter.set_checkpoint_state(state["iterator"])
            if state.get("rng") is not None:
                _random.set_state(state["rng"])
        except Exception as exc:
            _flight.record("checkpoint", "cursor-restore-skipped",
                           error=repr(exc)[:300])
        _flight.restore_progress(int(state.get("telemetry_steps") or 0))

    def _apply_trainer(self, payload):
        t = self._trainer
        by_slot = {}
        for key, arr in payload["params"].items():
            slot_s, _, name = key.partition(":")
            by_slot[int(slot_s)] = (name, arr)
        # validate EVERY slot before mutating ANY: a rejected checkpoint
        # must leave the live trainer untouched so the fallback to an
        # older checkpoint (or to a fresh start) sees unpoisoned params
        for slot, p in enumerate(t._params):
            ent = by_slot.get(slot)
            if ent is None:
                continue
            name, arr = ent
            if p.shape is not None and all(s > 0 for s in p.shape) \
                    and tuple(p.shape) != arr.shape:
                # slot is the binding contract; a shape clash means a
                # different model → fall back to an older checkpoint
                raise ValueError(
                    "checkpoint slot %d (%s) has shape %s, trainer "
                    "parameter %s expects %s"
                    % (slot, name, arr.shape, p.name, p.shape))
        # params first: set_data finishes deferred initialization (a
        # fresh model that never ran forward), which _init_kvstore needs
        for slot, p in enumerate(t._params):
            ent = by_slot.get(slot)
            if ent is None:
                continue
            _, arr = ent
            ctx = p._data.context if p._data is not None else None
            p.set_data(nd.array(arr, ctx=ctx, dtype=arr.dtype))
        if not t._kv_initialized:
            t._init_kvstore()
        upd = t._updater
        upd.states = {}
        for slot, tree in payload["optim"].items():
            ctx = t._params[slot].data().context \
                if 0 <= slot < len(t._params) else None
            upd.states[slot] = _np_to_state(tree, ctx)
        upd.states_synced = dict.fromkeys(upd.states, True)
        self._apply_counts(t._optimizer, payload["state"])
        self._apply_kvstore(t._kvstore, payload["state"])

    def _apply_module(self, payload):
        m = self._module
        arg = {k[4:]: nd.array(v, dtype=v.dtype)
               for k, v in payload["params"].items()
               if k.startswith("arg:")}
        aux = {k[4:]: nd.array(v, dtype=v.dtype)
               for k, v in payload["params"].items()
               if k.startswith("aux:")}
        m.set_params(arg, aux, allow_missing=False, force_init=True)
        upd = getattr(m, "_updater", None)
        if upd is not None and payload["optim"]:
            upd.states = {slot: _np_to_state(tree, None)
                          for slot, tree in payload["optim"].items()}
            upd.states_synced = dict.fromkeys(upd.states, True)
        opt = getattr(m, "_optimizer", None)
        if opt is not None:
            self._apply_counts(opt, payload["state"])
        self._apply_kvstore(getattr(m, "_kvstore", None),
                            payload["state"])

    @staticmethod
    def _apply_kvstore(kv, state):
        """Restore the server-side updater blob — non-fatally: params
        and updater state are already applied, so a kvstore mismatch
        (no updater installed yet, dist store) degrades with a flight
        event instead of poisoning the fallback path."""
        blob = state.get("kvstore_updater")
        if blob is None or kv is None:
            return
        try:
            kv.set_checkpoint_state(blob)
        except Exception as exc:
            _flight.record("checkpoint", "kvstore-restore-skipped",
                           error=repr(exc)[:300])

    @staticmethod
    def _apply_counts(opt, state):
        """Restore the fused-trainer step cache: per-slot update counts
        feed ``hyper['t']`` (Adam bias correction etc.) — losing them
        breaks bitwise resume.  Keys are preserved as saved: int slots
        on the trainer path, param-name strings on the module
        update_on_kvstore path."""
        counts = state.get("index_update_count") or {}
        opt._index_update_count = {k: int(v) for k, v in counts.items()}
        opt.num_update = int(state.get("num_update") or 0)

    # -- preemption path ---------------------------------------------------

    def install_preemption_handler(self):
        """Chain a SIGTERM handler in FRONT of whatever is installed
        (normally the flight recorder's).  Main thread only, idempotent.
        """
        if threading.current_thread() is not threading.main_thread():
            raise RuntimeError("signal handlers install on the main "
                               "thread only")
        if self._sigterm_installed:
            return
        self._prev_sigterm = signal.getsignal(signal.SIGTERM)
        signal.signal(signal.SIGTERM, self._on_sigterm)
        self._sigterm_installed = True

    def preempt_pending(self):
        return self._preempt_at is not None

    def _arm_grace_timer(self):
        """(Re-)start the hang-free deadline: cancel any running timer,
        arm a fresh daemon Timer on ``_grace_expired`` (no-op when the
        window is 0 = wait indefinitely)."""
        if self._grace_timer is not None:
            self._grace_timer.cancel()
            self._grace_timer = None
        if self._grace_secs > 0:
            t = threading.Timer(self._grace_secs, self._grace_expired)
            t.daemon = True
            t.start()
            self._grace_timer = t

    def _on_sigterm(self, signum, frame):
        """Signal context: set the flag, arm the grace timer, return.
        No locks, no allocation-heavy work — the interrupted main thread
        may be mid-``engine.push`` holding arbitrary locks."""
        if self._preempt_at is not None:    # second SIGTERM: stop waiting
            self._chain_sigterm()
            return
        self._preempt_at = time.monotonic()
        _flight.record("signal", "SIGTERM-checkpoint",
                       grace_s=self._grace_secs)
        self._arm_grace_timer()

    def _grace_expired(self):
        """The grace window ran out — either no step boundary arrived
        (wedged collective / stuck engine push) or the final save
        itself exceeded its re-armed window (wedged disk).  Die
        hang-free: flight dump with bounded lock acquires, then a hard
        exit — NEVER a synchronous checkpoint from here, the training
        state is mid-step and the main thread may hold the locks we'd
        need."""
        if self._final_done:
            return
        _flight.record("checkpoint", "grace-expired",
                       waited_s=self._grace_secs)
        try:
            _flight.dump("preempt:grace-expired")
        except Exception:
            pass
        os._exit(128 + int(signal.SIGTERM))

    def _on_step_boundary(self, epoch=None, batch=None):
        """The hooks.note_step_boundary target: one completed optimizer
        step.  Ordinary steps advance the counter and maybe fire the
        periodic async save; with a preemption pending this is the safe
        point — final synchronous checkpoint, then re-raise."""
        self._step += 1
        if epoch is not None:
            self._epoch = epoch
        if batch is not None:
            self._batch = batch
        if self._preempt_at is not None:
            # a boundary DID arrive inside the window: the original
            # timer's remainder must not hard-kill the final save
            # mid-commit.  Re-arm a fresh full window over the save
            # itself so a wedged writer still can't hang preemption.
            self._arm_grace_timer()
            try:
                self.save(self._step, sync=True, reason="sigterm")
            except Exception:
                pass                     # dying matters more than saving
            self._final_done = True
            if self._grace_timer is not None:
                self._grace_timer.cancel()
            self._chain_sigterm()
            return
        self.maybe_save()

    def _chain_sigterm(self):
        """Re-raise into the previous handler: the flight recorder dumps
        and re-kills so the exit status still says SIGTERM; a default
        disposition is restored and re-raised directly.  Either way this
        never returns to the training loop."""
        prev = self._prev_sigterm
        try:
            if callable(prev):
                prev(signal.SIGTERM, None)
            else:
                signal.signal(signal.SIGTERM, signal.SIG_DFL)
                os.kill(os.getpid(), signal.SIGTERM)
        except Exception:
            pass
        os._exit(128 + int(signal.SIGTERM))

    # -- introspection -----------------------------------------------------

    def describe(self):
        """JSON-shaped view for the ``/checkpoints`` endpoint."""
        entries = []
        for step, path, manifest in self._list_checkpoints():
            ent = {"step": step, "path": path,
                   "complete": bool(manifest and manifest.get("complete"))}
            if manifest:
                ent["n_shards"] = manifest.get("n_shards")
                ent["bytes"] = sum(f.get("bytes", 0) for f in
                                   manifest.get("files", {}).values())
                ent["created_unix"] = manifest.get("created_unix")
            entries.append(ent)
        return {"directory": self._dir,
                "step": self._step,
                "last_committed_step": self.last_committed_step,
                "last_good_step": self._pinned_step,
                "every_steps": self._every_steps,
                "n_shards": self._n_shards,
                "keep": self._keep,
                "preempt_pending": self.preempt_pending(),
                "last_error": self.last_error,
                "checkpoints": entries}


def install_preemption_handler(manager=None):
    """Install the SIGTERM-to-final-checkpoint handler for *manager*
    (default: the hooks-registered one)."""
    manager = manager if manager is not None else hooks.active()
    if manager is None:
        raise ValueError("no active CheckpointManager to install for")
    manager.install_preemption_handler()
    return manager
