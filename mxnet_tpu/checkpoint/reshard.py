"""Elastic shard layout for per-replica optimizer state.

ZeRO-style weight-update sharding ("Automatic Cross-Replica Sharding of
Weight Update in Data-Parallel Training", PAPERS.md arxiv 2004.13336)
makes optimizer state per-replica: replica ``k`` of ``n`` owns the
update of its slot subset, so a checkpoint must write ``n`` optimizer
shards — and a restore onto ``m != n`` replicas must *redistribute*
them.

The redistribution here follows the portable-collectives playbook
("Memory-efficient array redistribution through portable collective
communication", PAPERS.md arxiv 2112.01075) at host/file granularity
instead of chip granularity: the transfer is decomposed into per-shard
chunks that are streamed one file at a time and re-keyed into the target
layout, so no step of a restore ever materializes more than one source
shard beyond the state being accumulated — never an all-gathered
``n``-shard blob followed by an ``m``-way split.

The device-side promotion of this schedule lives in
``mxnet_tpu/parallel/collective.py``: ``redistribution_schedule`` is the
same decomposition at element/chunk granularity, and
``chunked_reduce_scatter`` / ``chunked_all_gather`` / ``redistribute``
execute it on device — kvstore buckets, the ZeRO-1 weight all-gather,
and the elastic-restore placement all stream through it.  This module
stays the *file*-granularity half (which shard file holds which slot).

Slot→shard assignment is round-robin over the *sorted* slot ids.  That
keeps the layout a pure function of (slots, n_shards) — every writer and
every reader derives the same plan with no layout metadata beyond
``n_shards`` in the manifest — and keeps shard payload sizes balanced
for the common case of interleaved large/small parameters.
"""
from __future__ import annotations

__all__ = ["assign_slots", "shard_states", "merge_into",
           "redistribution_plan"]


def assign_slots(slots, n_shards):
    """Round-robin shard assignment: ``[[slots of shard 0], ...]``.

    Deterministic in (slots, n_shards): slot ids are sorted first, so
    dict iteration order of the caller never changes the layout.
    """
    n_shards = max(1, int(n_shards))
    shards = [[] for _ in range(n_shards)]
    for i, slot in enumerate(sorted(slots)):
        shards[i % n_shards].append(slot)
    return shards


def shard_states(states, n_shards):
    """Partition a ``{slot: state-tree}`` dict into per-replica payload
    dicts, one per shard (empty shards are kept — the manifest's shard
    count IS the device count of the saving job)."""
    return [{slot: states[slot] for slot in shard}
            for shard in assign_slots(states.keys(), n_shards)]


def merge_into(acc, shard_payload):
    """Fold one loaded shard into the accumulating ``{slot: tree}`` dict
    (the streaming half of the redistribution: callers load shard files
    one at a time and release each before the next).  Duplicate slots
    across shards mean a corrupt layout and raise."""
    for slot, tree in shard_payload.items():
        if slot in acc:
            raise ValueError("slot %r appears in two optimizer shards "
                             "(corrupt shard layout)" % (slot,))
        acc[slot] = tree
    return acc


def redistribution_plan(slots, n_from, n_to):
    """Chunk moves for an ``n_from`` → ``n_to`` replica-count change:
    ``[(slot, src_shard, dst_shard), ...]`` with no-op moves elided.

    Purely descriptive on a single host (the restore path merges and
    re-buckets via :func:`assign_slots`), but it is also the exact
    per-chunk transfer schedule a multi-host restore would execute, and
    tests pin the invariant that every slot lands in exactly one target
    shard.
    """
    src = {}
    for shard_idx, members in enumerate(assign_slots(slots, n_from)):
        for slot in members:
            src[slot] = shard_idx
    moves = []
    for shard_idx, members in enumerate(assign_slots(slots, n_to)):
        for slot in members:
            if src[slot] != shard_idx:
                moves.append((slot, src[slot], shard_idx))
    return moves
