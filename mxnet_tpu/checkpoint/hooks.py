"""Step-boundary hooks: the training loops' one-line checkpoint contract.

``gluon.Trainer.step`` and the ``module`` fit loop call
:func:`note_step_boundary` after every completed optimizer step.  A step
boundary is the ONLY place training state is consistent enough to
snapshot (params, optimizer slots, and the data cursor all agree on the
same step), so it is where the active :class:`~.manager.CheckpointManager`

* advances its internal step counter,
* fires a periodic async snapshot (``every_steps``), and
* honors a pending SIGTERM by writing the final synchronous checkpoint
  and then re-raising the signal (the preemption path).

This module deliberately imports NOTHING: the training hot paths pay one
global read when no manager is registered, and there is no import cycle
between ``gluon``/``module`` and the checkpoint package.
"""
from __future__ import annotations

__all__ = ["register", "unregister", "active", "note_step_boundary"]

_manager = None


def register(manager):
    """Make *manager* the process's active checkpoint manager (one at a
    time; the latest registration wins, like signal handlers)."""
    global _manager
    _manager = manager


def unregister(manager):
    """Remove *manager* if it is still the active one."""
    global _manager
    if _manager is manager:
        _manager = None


def active():
    """The registered CheckpointManager, or None."""
    return _manager


def note_step_boundary(epoch=None, batch=None):
    """Called by training loops after each completed optimizer step."""
    m = _manager
    if m is not None:
        m._on_step_boundary(epoch=epoch, batch=batch)
