"""Preemption-safe training: async sharded checkpoints + elastic resume.

The robustness layer of the training stack (ROADMAP item 5; reference
capability: MXNet's ``kvstore.save_optimizer_states`` /
``model.load_checkpoint``, PAPER.md layers 3/7):

    mgr = checkpoint.CheckpointManager(dir, trainer=trainer,
                                       data_iter=it, every_steps=50)
    start = mgr.restore() or 0            # elastic: shard count may differ
    checkpoint.install_preemption_handler(mgr)
    for step in range(start, n_steps):
        ... forward / backward ...
        trainer.step(batch_size)          # boundaries auto-save + honor
                                          # a pending SIGTERM

See :mod:`.manager` for the full contract, :mod:`.reshard` for the
elastic shard layout, :mod:`.hooks` for the training-loop integration,
and docs/CHECKPOINT.md for formats and failure modes.  The live view is
``GET /checkpoints`` on the introspection server.
"""
from __future__ import annotations

from . import hooks, reshard                     # noqa: F401
from .manager import CheckpointManager, install_preemption_handler

__all__ = ["CheckpointManager", "install_preemption_handler",
           "http_view", "hooks", "reshard"]


def http_view():
    """The ``/checkpoints`` introspection payload: the active manager's
    description, or an inactive stub."""
    manager = hooks.active()
    if manager is None:
        return {"active": False, "checkpoints": []}
    view = manager.describe()
    view["active"] = True
    return view
