"""Model helpers: kvstore plumbing, checkpointing, legacy FeedForward.

Parity surface: reference ``python/mxnet/model.py`` (967 LoC):
``_create_kvstore`` :57 (update_on_kvstore decision), ``_initialize_kvstore``
:96, ``_update_params_on_kvstore`` :105, ``_update_params`` :117,
``save_checkpoint``/``load_checkpoint``, ``FeedForward``.
"""
from __future__ import annotations

import logging

import numpy as np

from .base import MXNetError
from . import ndarray as nd
from . import symbol as sym
from . import kvstore as kvs
from . import optimizer as opt
from . import metric as metric_mod
from .context import cpu, current_context

__all__ = ["_create_kvstore", "_initialize_kvstore",
           "_update_params_on_kvstore", "_update_params", "save_checkpoint",
           "load_checkpoint", "FeedForward", "BatchEndParam"]


class BatchEndParam:
    """Bundle handed to batch-end callbacks (ref model.py namedtuple)."""

    def __init__(self, epoch, nbatch, eval_metric, locals=None):
        self.epoch, self.nbatch = epoch, nbatch
        self.eval_metric, self.locals = eval_metric, locals


def _create_kvstore(kvstore, num_device, arg_params):
    """Resolve the kvstore spec and the update-placement decision
    (ref model.py:57): single-device non-dist runs skip the store entirely;
    'local' moves updates onto workers when the largest param exceeds 16M
    elements (server-side optimizer would serialise on that key)."""
    if kvstore is None or isinstance(kvstore, kvs.KVStore):
        kv = kvstore
        update_on_kvstore = kv is not None
    elif isinstance(kvstore, str):
        if num_device == 1 and "dist" not in kvstore:
            return None, False
        kv = kvs.create(kvstore)
        update_on_kvstore = True
        if kvstore == "local":
            biggest = max(np.prod(p.shape) for p in arg_params.values())
            update_on_kvstore = biggest <= 1024 * 1024 * 16
    else:
        raise TypeError("kvstore must be KVStore, str or None")
    return kv, update_on_kvstore


def _initialize_kvstore(kvstore, param_arrays, arg_params, param_names,
                        update_on_kvstore):
    """Register every parameter with the store (ref model.py:96); in
    update-on-kvstore mode also broadcast the initial values out."""
    for slot, (name, devs) in enumerate(zip(param_names, param_arrays)):
        kvstore.init(name, arg_params[name])
        if update_on_kvstore:
            kvstore.pull(name, devs, priority=-slot)


def _update_params_on_kvstore(param_arrays, grad_arrays, kvstore,
                              param_names):
    """Optimizer-on-server step (ref model.py:105): push the gradient,
    pull the freshly-updated weight back to every device."""
    for slot, (weights, grads) in enumerate(zip(param_arrays, grad_arrays)):
        if grads is None or grads[0] is None:
            continue
        kvstore.push(param_names[slot], grads, priority=-slot)
        kvstore.pull(param_names[slot], weights, priority=-slot)


def _update_params(param_arrays, grad_arrays, updater, num_device,
                   kvstore=None, param_names=None):
    """Optimizer-on-worker step (ref model.py:117): optionally reduce the
    gradient through the store, then run the local Updater per device."""
    for slot, (weights, grads) in enumerate(zip(param_arrays, grad_arrays)):
        if grads is None or grads[0] is None:
            continue
        if kvstore:
            kvstore.push(param_names[slot], grads, priority=-slot)
            kvstore.pull(param_names[slot], grads, priority=-slot)
        for dev, (w, g) in enumerate(zip(weights, grads)):
            updater(slot * num_device + dev, g, w)


def save_checkpoint(prefix, epoch, symbol, arg_params, aux_params):
    """Save ``prefix-symbol.json`` + ``prefix-####.params`` (reference
    model.py save_checkpoint; format per §5.4)."""
    if symbol is not None:
        symbol.save("%s-symbol.json" % prefix)
    blob = {}
    for tag, group in (("arg:", arg_params), ("aux:", aux_params)):
        for name, arr in group.items():
            blob[tag + name] = arr
    param_name = "%s-%04d.params" % (prefix, epoch)
    nd.save(param_name, blob)
    logging.info('Saved checkpoint to "%s"', param_name)


def split_saved_params(loaded):
    """Split a loaded ``.params`` dict into (arg_params, aux_params) by
    the ``arg:``/``aux:`` key prefixes; unprefixed keys are dropped.
    Shared by :func:`load_checkpoint` and the C predict API shim."""
    from .base import MXNetError
    if not isinstance(loaded, dict):
        raise MXNetError(
            "params file contains unnamed arrays; expected the "
            "arg:/aux:-keyed dict written by save_checkpoint")
    arg_params, aux_params = {}, {}
    groups = {"arg": arg_params, "aux": aux_params}
    for key, val in loaded.items():
        kind, _, name = key.partition(":")
        if kind in groups:
            groups[kind][name] = val
    return arg_params, aux_params


def load_checkpoint(prefix, epoch):
    """Load a checkpoint saved by save_checkpoint."""
    symbol = sym.load("%s-symbol.json" % prefix)
    arg_params, aux_params = split_saved_params(
        nd.load("%s-%04d.params" % (prefix, epoch)))
    return symbol, arg_params, aux_params


class FeedForward:
    """Legacy training API (reference model.py FeedForward) — a thin shim
    over Module, kept for example-source compatibility."""

    def __init__(self, symbol, ctx=None, num_epoch=None, epoch_size=None,
                 optimizer="sgd", initializer=None, numpy_batch_size=128,
                 arg_params=None, aux_params=None, allow_extra_params=False,
                 begin_epoch=0, **kwargs):
        from .initializer import Uniform
        self.symbol = symbol
        self.ctx = ctx if isinstance(ctx, (list, tuple)) else [ctx or cpu()]
        self.num_epoch = num_epoch
        self.epoch_size = epoch_size
        self.optimizer = optimizer
        self.initializer = initializer or Uniform(0.01)
        self.numpy_batch_size = numpy_batch_size
        self.arg_params = arg_params
        self.aux_params = aux_params
        self.allow_extra_params = allow_extra_params
        self.begin_epoch = begin_epoch
        self.kwargs = kwargs
        self._module = None

    def _get_module(self, data_iter):
        from .module import Module
        label_names = [d.name if hasattr(d, "name") else d[0]
                       for d in (data_iter.provide_label or [])]
        if not label_names:
            # predict-mode iter carries no labels; label args are still
            # graph inputs, not params (they'd break set_params otherwise)
            label_names = [n for n in self.symbol.list_arguments()
                           if n.endswith("_label")]
        data_names = [d.name if hasattr(d, "name") else d[0]
                      for d in data_iter.provide_data]
        mod = Module(self.symbol, data_names=data_names,
                     label_names=label_names, context=self.ctx)
        return mod

    def fit(self, X, y=None, eval_data=None, eval_metric="acc",
            epoch_end_callback=None, batch_end_callback=None, kvstore="local",
            logger=None, work_load_list=None, monitor=None, **kwargs):
        train_data = self._prepare_data(X, y)
        mod = self._get_module(train_data)
        mod.fit(train_data, eval_data=eval_data, eval_metric=eval_metric,
                epoch_end_callback=epoch_end_callback,
                batch_end_callback=batch_end_callback, kvstore=kvstore,
                optimizer=self.optimizer,
                optimizer_params=dict(self.kwargs),
                initializer=self.initializer,
                arg_params=self.arg_params, aux_params=self.aux_params,
                begin_epoch=self.begin_epoch,
                num_epoch=self.num_epoch or 1)
        self._module = mod
        self.arg_params, self.aux_params = mod.get_params()
        return self

    def _prepare_data(self, X, y=None):
        from .io import NDArrayIter, DataIter
        if isinstance(X, DataIter):
            return X
        return NDArrayIter(X, y, batch_size=self.numpy_batch_size,
                           shuffle=True)

    def _ensure_module(self, data_iter):
        """Bind a predict-mode module from loaded params when fit() never
        ran (the FeedForward.load → predict path)."""
        if self._module is not None and self._module.binded:
            return self._module
        mod = self._get_module(data_iter)
        mod.bind(data_shapes=data_iter.provide_data,
                 label_shapes=data_iter.provide_label or None,
                 for_training=False)
        if self.arg_params is not None:
            mod.set_params(self.arg_params, self.aux_params or {},
                           allow_missing=False)
        else:
            mod.init_params(initializer=self.initializer)
            self.arg_params, self.aux_params = mod.get_params()
        self._module = mod
        return mod

    def predict(self, X, num_batch=None, return_data=False, reset=True):
        data = self._prepare_data(X)
        outs = self._ensure_module(data).predict(data, num_batch=num_batch)
        return outs.asnumpy() if hasattr(outs, "asnumpy") else outs

    def score(self, X, eval_metric="acc", num_batch=None, **kwargs):
        data = self._prepare_data(X)
        res = self._ensure_module(data).score(data, eval_metric,
                                              num_batch=num_batch)
        return res[0][1]

    def save(self, prefix, epoch=None):
        if epoch is None:
            epoch = self.num_epoch or 0
        save_checkpoint(prefix, epoch, self.symbol, self.arg_params,
                        self.aux_params)

    @staticmethod
    def load(prefix, epoch, ctx=None, **kwargs):
        symbol, arg_params, aux_params = load_checkpoint(prefix, epoch)
        return FeedForward(symbol, ctx=ctx, arg_params=arg_params,
                           aux_params=aux_params, begin_epoch=epoch, **kwargs)

    @staticmethod
    def create(symbol, X, y=None, ctx=None, num_epoch=None, epoch_size=None,
               optimizer="sgd", initializer=None, eval_data=None,
               eval_metric="acc", epoch_end_callback=None,
               batch_end_callback=None, kvstore="local", logger=None,
               work_load_list=None, **kwargs):
        model = FeedForward(symbol, ctx=ctx, num_epoch=num_epoch,
                            epoch_size=epoch_size, optimizer=optimizer,
                            initializer=initializer, **kwargs)
        model.fit(X, y, eval_data=eval_data, eval_metric=eval_metric,
                  epoch_end_callback=epoch_end_callback,
                  batch_end_callback=batch_end_callback, kvstore=kvstore,
                  logger=logger, work_load_list=work_load_list)
        return model
