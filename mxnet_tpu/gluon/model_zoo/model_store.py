"""Pretrained-weight store (reference model_zoo/model_store.py).

The reference downloads ``.params`` files from S3 keyed by sha1
(``MXNET_GLUON_REPO`` env).  This build has zero network egress:
``get_model_file`` only resolves files already present in the local
cache directory (same layout/naming as the reference), so pretrained
checkpoints copied in by the user work identically.
"""
from __future__ import annotations

import os

__all__ = ["get_model_file", "purge"]


def get_model_file(name, root=os.path.join("~", ".mxnet", "models")):
    """Return the path of a locally cached pretrained model file.

    Search order: *root* (the reference's ``~/.mxnet/models`` cache),
    then ``MXNET_GLUON_REPO`` interpreted as a local directory (the
    reference uses that env var as its download base URL; a zero-egress
    build treats it as a published-weights directory), then the
    in-repo ``zoo/`` directory of shipped artifacts.
    """
    file_name = "{name}.params".format(name=name)
    repo_zoo = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "..", "..", "..", "zoo")
    candidates = [os.path.expanduser(root)]
    env_repo = os.environ.get("MXNET_GLUON_REPO")
    if env_repo and os.path.isdir(os.path.expanduser(env_repo)):
        candidates.append(os.path.expanduser(env_repo))
    candidates.append(os.path.normpath(repo_zoo))
    for cand in candidates:
        file_path = os.path.join(cand, file_name)
        if os.path.exists(file_path):
            return file_path
    raise FileNotFoundError(
        "Pretrained model file %s is not found in any of %s and this "
        "build has no network egress. Copy the .params file into the "
        "cache directory (MXNet model zoo format) to use "
        "pretrained=True." % (file_name, candidates))


def purge(root=os.path.join("~", ".mxnet", "models")):
    root = os.path.expanduser(root)
    if not os.path.isdir(root):
        return
    files = os.listdir(root)
    for f in files:
        if f.endswith(".params"):
            os.remove(os.path.join(root, f))
