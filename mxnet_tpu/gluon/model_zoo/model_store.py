"""Pretrained-weight store (reference model_zoo/model_store.py).

The reference downloads ``.params`` files from S3 keyed by sha1
(``MXNET_GLUON_REPO`` env).  This build has zero network egress:
``get_model_file`` only resolves files already present in the local
cache directory (same layout/naming as the reference), so pretrained
checkpoints copied in by the user work identically.
"""
from __future__ import annotations

import os

__all__ = ["get_model_file", "purge"]


def get_model_file(name, root=os.path.join("~", ".mxnet", "models")):
    """Return the path of a locally cached pretrained model file."""
    file_name = "{name}".format(name=name)
    root = os.path.expanduser(root)
    file_path = os.path.join(root, file_name + ".params")
    if os.path.exists(file_path):
        return file_path
    raise FileNotFoundError(
        "Pretrained model file %s is not found in %s and this build has "
        "no network egress. Copy the .params file into the cache "
        "directory (MXNet model zoo format) to use pretrained=True."
        % (file_name + ".params", root))


def purge(root=os.path.join("~", ".mxnet", "models")):
    root = os.path.expanduser(root)
    if not os.path.isdir(root):
        return
    files = os.listdir(root)
    for f in files:
        if f.endswith(".params"):
            os.remove(os.path.join(root, f))
