"""Inception V3.

API parity with the reference model zoo
(``python/mxnet/gluon/model_zoo/vision/inception.py:140``). Independent
construction: branches are described by explicit kwargs dicts (one per
conv unit) instead of positional tuples, and the five module types share
one parallel-concat container.
"""
from __future__ import annotations

from ....context import cpu
from ...block import HybridBlock
from ... import nn

__all__ = ["Inception3", "inception_v3"]


def _unit(channels, kernel, stride=1, pad=0):
    """conv-BN-relu unit with the inception BN epsilon."""
    seq = nn.HybridSequential(prefix="")
    seq.add(nn.Conv2D(channels, kernel_size=kernel, strides=stride,
                      padding=pad, use_bias=False))
    seq.add(nn.BatchNorm(epsilon=0.001))
    seq.add(nn.Activation("relu"))
    return seq


def _branch(pool=None, *convs):
    """A sequential branch: optional pool head, then conv units
    (each described by a kwargs dict for :func:`_unit`)."""
    seq = nn.HybridSequential(prefix="")
    if pool == "avg":
        seq.add(nn.AvgPool2D(pool_size=3, strides=1, padding=1))
    elif pool == "max":
        seq.add(nn.MaxPool2D(pool_size=3, strides=2))
    for kw in convs:
        seq.add(_unit(**kw))
    return seq


class _Parallel(HybridBlock):
    """Feed the same input to every child; concat outputs on channels
    (reference uses gluon.contrib HybridConcurrent)."""

    def add(self, block):
        self.register_child(block)

    def hybrid_forward(self, F, x):
        return F.concat(*[child(x) for child in self._children], dim=1)


def _parallel(prefix, *branches):
    box = _Parallel(prefix=prefix)
    with box.name_scope():
        for b in branches:
            box.add(b)
    return box


def _module_a(pool_features, prefix):
    return _parallel(
        prefix,
        _branch(None, dict(channels=64, kernel=1)),
        _branch(None, dict(channels=48, kernel=1),
                dict(channels=64, kernel=5, pad=2)),
        _branch(None, dict(channels=64, kernel=1),
                dict(channels=96, kernel=3, pad=1),
                dict(channels=96, kernel=3, pad=1)),
        _branch("avg", dict(channels=pool_features, kernel=1)))


def _module_b(prefix):
    return _parallel(
        prefix,
        _branch(None, dict(channels=384, kernel=3, stride=2)),
        _branch(None, dict(channels=64, kernel=1),
                dict(channels=96, kernel=3, pad=1),
                dict(channels=96, kernel=3, stride=2)),
        _branch("max"))


def _module_c(width, prefix):
    row = dict(kernel=(1, 7), pad=(0, 3))
    col = dict(kernel=(7, 1), pad=(3, 0))
    return _parallel(
        prefix,
        _branch(None, dict(channels=192, kernel=1)),
        _branch(None, dict(channels=width, kernel=1),
                dict(channels=width, **row),
                dict(channels=192, **col)),
        _branch(None, dict(channels=width, kernel=1),
                dict(channels=width, **col),
                dict(channels=width, **row),
                dict(channels=width, **col),
                dict(channels=192, **row)),
        _branch("avg", dict(channels=192, kernel=1)))


def _module_d(prefix):
    return _parallel(
        prefix,
        _branch(None, dict(channels=192, kernel=1),
                dict(channels=320, kernel=3, stride=2)),
        _branch(None, dict(channels=192, kernel=1),
                dict(channels=192, kernel=(1, 7), pad=(0, 3)),
                dict(channels=192, kernel=(7, 1), pad=(3, 0)),
                dict(channels=192, kernel=3, stride=2)),
        _branch("max"))


class _Fork13(HybridBlock):
    """1x3 / 3x1 conv pair over the same input, channel-concatenated."""

    def __init__(self, channels, **kwargs):
        super().__init__(**kwargs)
        self.row = _branch(None, dict(channels=channels, kernel=(1, 3),
                                      pad=(0, 1)))
        self.col = _branch(None, dict(channels=channels, kernel=(3, 1),
                                      pad=(1, 0)))

    def hybrid_forward(self, F, x):
        return F.concat(self.row(x), self.col(x), dim=1)


def _module_e(prefix):
    stem2 = nn.HybridSequential(prefix="")
    stem2.add(_unit(384, 1))
    stem2.add(_Fork13(384))
    stem3 = nn.HybridSequential(prefix="")
    stem3.add(_unit(448, 1))
    stem3.add(_unit(384, 3, pad=1))
    stem3.add(_Fork13(384))
    return _parallel(
        prefix,
        _branch(None, dict(channels=320, kernel=1)),
        stem2, stem3,
        _branch("avg", dict(channels=192, kernel=1)))


class Inception3(HybridBlock):
    r"""Inception v3 trunk (ref inception.py:140)."""

    def __init__(self, classes=1000, **kwargs):
        super().__init__(**kwargs)
        with self.name_scope():
            f = nn.HybridSequential(prefix="")
            f.add(_unit(32, 3, stride=2))
            f.add(_unit(32, 3))
            f.add(_unit(64, 3, pad=1))
            f.add(nn.MaxPool2D(pool_size=3, strides=2))
            f.add(_unit(80, 1))
            f.add(_unit(192, 3))
            f.add(nn.MaxPool2D(pool_size=3, strides=2))
            for i, pool_features in enumerate((32, 64, 64)):
                f.add(_module_a(pool_features, "A%d_" % (i + 1)))
            f.add(_module_b("B_"))
            for i, width in enumerate((128, 160, 160, 192)):
                f.add(_module_c(width, "C%d_" % (i + 1)))
            f.add(_module_d("D_"))
            f.add(_module_e("E1_"))
            f.add(_module_e("E2_"))
            f.add(nn.AvgPool2D(pool_size=8))
            f.add(nn.Dropout(0.5))
            self.features = f
            self.output = nn.Dense(classes)

    def hybrid_forward(self, F, x):
        return self.output(self.features(x))


def inception_v3(pretrained=False, ctx=cpu(), **kwargs):
    net = Inception3(**kwargs)
    if pretrained:
        from ..model_store import get_model_file
        net.load_params(get_model_file("inceptionv3"), ctx=ctx)
    return net
