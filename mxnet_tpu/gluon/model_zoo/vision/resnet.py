"""ResNet V1 (post-activation) and V2 (pre-activation) families.

API parity with the reference model zoo
(``python/mxnet/gluon/model_zoo/vision/resnet.py``: ResNetV1/V2, the four
block types, ``get_resnet`` and the resnet{18..152}_v{1,2} constructors).
Independent design: both residual-block generations derive from shared
templates whose conv stacks come from spec tuples, the two trunk classes
share one ``_stack_stages`` helper, and the public constructors are
generated from the depth table.

This is the flagship benchmark model (BASELINE.md resnet-50): all compute
is conv/BN/relu, which XLA fuses and tiles onto the MXU; bfloat16-safe.
"""
from __future__ import annotations

from ....context import cpu
from ...block import HybridBlock
from ... import nn

__all__ = ["ResNetV1", "ResNetV2", "BasicBlockV1", "BasicBlockV2",
           "BottleneckV1", "BottleneckV2", "resnet18_v1", "resnet34_v1",
           "resnet50_v1", "resnet101_v1", "resnet152_v1", "resnet18_v2",
           "resnet34_v2", "resnet50_v2", "resnet101_v2", "resnet152_v2",
           "get_resnet"]


def _conv(channels, kernel, stride=1, pad=None, in_channels=0, bias=False):
    if pad is None:
        pad = kernel // 2
    return nn.Conv2D(channels, kernel_size=kernel, strides=stride,
                     padding=pad, use_bias=bias, in_channels=in_channels)


def _conv3x3(channels, stride, in_channels):
    return _conv(channels, 3, stride, 1, in_channels)


class _ResidualV1(HybridBlock):
    """V1 template: body(x) + shortcut, then relu. Subclasses define the
    body via ``conv_plan(channels, stride)`` → [(ch, kernel, stride), ...];
    BN follows every conv, relu all but the last."""

    def __init__(self, channels, stride, downsample=False, in_channels=0,
                 **kwargs):
        super().__init__(**kwargs)
        plan = self.conv_plan(channels, stride)
        self.body = nn.HybridSequential(prefix="")
        for pos, (ch, kernel, s) in enumerate(plan):
            # reference V1 keeps biases on the bottleneck 1x1 convs
            self.body.add(_conv(ch, kernel, s,
                                in_channels=in_channels if pos == 0 else 0,
                                bias=(kernel == 1)))
            self.body.add(nn.BatchNorm())
            if pos + 1 < len(plan):
                self.body.add(nn.Activation("relu"))
        if downsample:
            self.downsample = nn.HybridSequential(prefix="")
            self.downsample.add(_conv(channels, 1, stride, 0, in_channels))
            self.downsample.add(nn.BatchNorm())
        else:
            self.downsample = None

    def hybrid_forward(self, F, x):
        shortcut = x if self.downsample is None else self.downsample(x)
        return F.Activation(self.body(x) + shortcut, act_type="relu")


class BasicBlockV1(_ResidualV1):
    r"""Two 3x3 convs ("Deep Residual Learning", 18/34-layer nets)."""

    @staticmethod
    def conv_plan(channels, stride):
        return [(channels, 3, stride), (channels, 3, 1)]


class BottleneckV1(_ResidualV1):
    r"""1x1 → 3x3 → 1x1 bottleneck (50/101/152-layer nets)."""

    @staticmethod
    def conv_plan(channels, stride):
        return [(channels // 4, 1, stride), (channels // 4, 3, 1),
                (channels, 1, 1)]


class _ResidualV2(HybridBlock):
    """V2 template ("Identity Mappings"): BN-relu precedes each conv; the
    shortcut taps the pre-activated input when downsampling."""

    def __init__(self, channels, stride, downsample=False, in_channels=0,
                 **kwargs):
        super().__init__(**kwargs)
        plan = self.conv_plan(channels, stride)
        self._bns = []
        self._convs = []
        for pos, (ch, kernel, s) in enumerate(plan):
            bn = nn.BatchNorm()
            conv = _conv(ch, kernel, s,
                         in_channels=in_channels if pos == 0 else 0)
            setattr(self, "bn%d" % (pos + 1), bn)
            setattr(self, "conv%d" % (pos + 1), conv)
            self._bns.append(bn)
            self._convs.append(conv)
        self.downsample = _conv(channels, 1, stride, 0, in_channels) \
            if downsample else None

    def hybrid_forward(self, F, x):
        shortcut = x
        for pos, (bn, conv) in enumerate(zip(self._bns, self._convs)):
            x = F.Activation(bn(x), act_type="relu")
            if pos == 0 and self.downsample is not None:
                shortcut = self.downsample(x)
            x = conv(x)
        return x + shortcut


class BasicBlockV2(_ResidualV2):
    r"""Pre-activation basic block."""

    @staticmethod
    def conv_plan(channels, stride):
        return [(channels, 3, stride), (channels, 3, 1)]


class BottleneckV2(_ResidualV2):
    r"""Pre-activation bottleneck."""

    @staticmethod
    def conv_plan(channels, stride):
        return [(channels // 4, 1, 1), (channels // 4, 3, stride),
                (channels, 1, 1)]


def _stack_stages(features, block, layers, channels, make_prefix):
    """Append the four residual stages; returns the final channel count."""
    width_in = channels[0]
    for stage, count in enumerate(layers):
        width = channels[stage + 1]
        stride = 1 if stage == 0 else 2
        group = nn.HybridSequential(prefix=make_prefix(stage + 1))
        with group.name_scope():
            group.add(block(width, stride, width != width_in,
                            in_channels=width_in, prefix=""))
            for _ in range(count - 1):
                group.add(block(width, 1, False, in_channels=width,
                                prefix=""))
        features.add(group)
        width_in = width
    return width_in


def _stem(features, channels0, thumbnail):
    """7x7/pool ImageNet stem, or a bare 3x3 for 32x32 inputs."""
    if thumbnail:
        features.add(_conv3x3(channels0, 1, 0))
    else:
        features.add(nn.Conv2D(channels0, 7, 2, 3, use_bias=False))
        features.add(nn.BatchNorm())
        features.add(nn.Activation("relu"))
        features.add(nn.MaxPool2D(3, 2, 1))


class ResNetV1(HybridBlock):
    r"""Post-activation ResNet trunk (ref resnet.py:ResNetV1)."""

    def __init__(self, block, layers, channels, classes=1000,
                 thumbnail=False, **kwargs):
        super().__init__(**kwargs)
        if len(layers) != len(channels) - 1:
            raise ValueError("channels must have one more entry than layers")
        with self.name_scope():
            self.features = nn.HybridSequential(prefix="")
            _stem(self.features, channels[0], thumbnail)
            _stack_stages(self.features, block, layers, channels,
                          lambda i: "stage%d_" % i)
            self.features.add(nn.GlobalAvgPool2D())
            self.output = nn.Dense(classes, in_units=channels[-1])

    def hybrid_forward(self, F, x):
        return self.output(self.features(x))


class ResNetV2(HybridBlock):
    r"""Pre-activation ResNet trunk (ref resnet.py:ResNetV2): leading
    data BN, trailing BN-relu before pooling."""

    def __init__(self, block, layers, channels, classes=1000,
                 thumbnail=False, **kwargs):
        super().__init__(**kwargs)
        if len(layers) != len(channels) - 1:
            raise ValueError("channels must have one more entry than layers")
        with self.name_scope():
            self.features = nn.HybridSequential(prefix="")
            self.features.add(nn.BatchNorm(scale=False, center=False))
            _stem(self.features, channels[0], thumbnail)
            final = _stack_stages(self.features, block, layers, channels,
                                  lambda i: "stage%d_" % i)
            self.features.add(nn.BatchNorm())
            self.features.add(nn.Activation("relu"))
            self.features.add(nn.GlobalAvgPool2D())
            self.features.add(nn.Flatten())
            self.output = nn.Dense(classes, in_units=final)

    def hybrid_forward(self, F, x):
        return self.output(self.features(x))


# depth → (block kind, per-stage counts, per-stage channels)
resnet_spec = {
    18: ("basic_block", [2, 2, 2, 2], [64, 64, 128, 256, 512]),
    34: ("basic_block", [3, 4, 6, 3], [64, 64, 128, 256, 512]),
    50: ("bottle_neck", [3, 4, 6, 3], [64, 256, 512, 1024, 2048]),
    101: ("bottle_neck", [3, 4, 23, 3], [64, 256, 512, 1024, 2048]),
    152: ("bottle_neck", [3, 8, 36, 3], [64, 256, 512, 1024, 2048])}

resnet_net_versions = [ResNetV1, ResNetV2]
resnet_block_versions = [
    {"basic_block": BasicBlockV1, "bottle_neck": BottleneckV1},
    {"basic_block": BasicBlockV2, "bottle_neck": BottleneckV2}]


def get_resnet(version, num_layers, pretrained=False, ctx=cpu(), **kwargs):
    """Build a ResNet by (version, depth) (ref resnet.py:get_resnet)."""
    if num_layers not in resnet_spec:
        raise ValueError("Invalid number of layers: %d. Options are %s"
                         % (num_layers, sorted(resnet_spec)))
    if version not in (1, 2):
        raise ValueError("Invalid resnet version: %d. Options are 1 and 2."
                         % version)
    kind, layers, channels = resnet_spec[num_layers]
    trunk = resnet_net_versions[version - 1]
    block = resnet_block_versions[version - 1][kind]
    net = trunk(block, layers, channels, **kwargs)
    if pretrained:
        from ..model_store import get_model_file
        net.load_params(get_model_file("resnet%d_v%d"
                                       % (num_layers, version)), ctx=ctx)
    return net


def _make_constructor(version, depth):
    def ctor(**kwargs):
        return get_resnet(version, depth, **kwargs)
    ctor.__name__ = "resnet%d_v%d" % (depth, version)
    ctor.__doc__ = "ResNet-%d V%d constructor." % (depth, version)
    return ctor


for _v in (1, 2):
    for _d in sorted(resnet_spec):
        globals()["resnet%d_v%d" % (_d, _v)] = _make_constructor(_v, _d)
del _v, _d
