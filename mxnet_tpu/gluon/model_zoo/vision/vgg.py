"""VGG 11/13/16/19, plain and batch-normed.

API parity with the reference model zoo
(``python/mxnet/gluon/model_zoo/vision/vgg.py:34``); constructors are
generated from the depth table.
"""
from __future__ import annotations

from ....context import cpu
from ....initializer import Xavier
from ...block import HybridBlock
from ... import nn

__all__ = ["VGG", "vgg11", "vgg13", "vgg16", "vgg19", "vgg11_bn",
           "vgg13_bn", "vgg16_bn", "vgg19_bn", "get_vgg"]

_CONV_INIT = dict(weight_initializer=Xavier(rnd_type="gaussian",
                                            factor_type="out", magnitude=2),
                  bias_initializer="zeros")
_FC_INIT = dict(weight_initializer="normal", bias_initializer="zeros")


class VGG(HybridBlock):
    r"""Stacked 3x3-conv stages + two 4096-wide FC layers (ref vgg.py:34)."""

    def __init__(self, layers, filters, classes=1000, batch_norm=False,
                 **kwargs):
        super().__init__(**kwargs)
        if len(layers) != len(filters):
            raise ValueError("layers and filters must pair up")
        with self.name_scope():
            self.features = nn.HybridSequential(prefix="")
            for repeat, width in zip(layers, filters):
                self._add_stage(repeat, width, batch_norm)
            for _ in range(2):
                self.features.add(nn.Dense(4096, activation="relu",
                                           **_FC_INIT))
                self.features.add(nn.Dropout(rate=0.5))
            self.output = nn.Dense(classes, **_FC_INIT)

    def _add_stage(self, repeat, width, batch_norm):
        """One resolution stage: `repeat` convs then a stride-2 pool."""
        for _ in range(repeat):
            self.features.add(nn.Conv2D(width, kernel_size=3, padding=1,
                                        **_CONV_INIT))
            if batch_norm:
                self.features.add(nn.BatchNorm())
            self.features.add(nn.Activation("relu"))
        self.features.add(nn.MaxPool2D(strides=2))

    def hybrid_forward(self, F, x):
        return self.output(self.features(x))


vgg_spec = {11: ([1, 1, 2, 2, 2], [64, 128, 256, 512, 512]),
            13: ([2, 2, 2, 2, 2], [64, 128, 256, 512, 512]),
            16: ([2, 2, 3, 3, 3], [64, 128, 256, 512, 512]),
            19: ([2, 2, 4, 4, 4], [64, 128, 256, 512, 512])}


def get_vgg(num_layers, pretrained=False, ctx=cpu(), **kwargs):
    """Build a VGG by depth (ref vgg.py:get_vgg)."""
    layers, filters = vgg_spec[num_layers]
    net = VGG(layers, filters, **kwargs)
    if pretrained:
        from ..model_store import get_model_file
        suffix = "_bn" if kwargs.get("batch_norm") else ""
        net.load_params(get_model_file("vgg%d%s" % (num_layers, suffix)),
                        ctx=ctx)
    return net


def _make_constructor(depth, batch_norm):
    def ctor(**kwargs):
        if batch_norm:
            kwargs["batch_norm"] = True
        return get_vgg(depth, **kwargs)
    ctor.__name__ = "vgg%d%s" % (depth, "_bn" if batch_norm else "")
    ctor.__doc__ = "VGG-%d%s constructor." % (depth,
                                              " (BN)" if batch_norm else "")
    return ctor


for _d in sorted(vgg_spec):
    globals()["vgg%d" % _d] = _make_constructor(_d, False)
    globals()["vgg%d_bn" % _d] = _make_constructor(_d, True)
del _d
