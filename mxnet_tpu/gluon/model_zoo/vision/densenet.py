"""DenseNet-BC 121/161/169/201.

API parity with the reference model zoo
(``python/mxnet/gluon/model_zoo/vision/densenet.py:65``). The BN-relu-conv
motif is factored into one helper shared by dense layers and transitions;
constructors are generated from the depth table.
"""
from __future__ import annotations

from ....context import cpu
from ...block import HybridBlock
from ... import nn

__all__ = ["DenseNet", "densenet121", "densenet161", "densenet169",
           "densenet201"]


def _bn_relu_conv(seq, channels, kernel, padding=0):
    """Append the pre-activation conv motif to *seq*."""
    seq.add(nn.BatchNorm())
    seq.add(nn.Activation("relu"))
    seq.add(nn.Conv2D(channels, kernel_size=kernel, padding=padding,
                      use_bias=False))


class _GrowthUnit(HybridBlock):
    """One dense layer: 1x1 bottleneck → 3x3 conv, output concatenated
    onto the running feature map."""

    def __init__(self, growth_rate, bn_size, dropout, **kwargs):
        super().__init__(**kwargs)
        self.body = nn.HybridSequential(prefix="")
        _bn_relu_conv(self.body, bn_size * growth_rate, 1)
        _bn_relu_conv(self.body, growth_rate, 3, padding=1)
        if dropout:
            self.body.add(nn.Dropout(dropout))

    def hybrid_forward(self, F, x):
        return F.concat(x, self.body(x), dim=1)


def _dense_stage(count, bn_size, growth_rate, dropout, stage_index):
    stage = nn.HybridSequential(prefix="stage%d_" % stage_index)
    with stage.name_scope():
        for _ in range(count):
            stage.add(_GrowthUnit(growth_rate, bn_size, dropout))
    return stage


def _transition(channels):
    """Halve channels (1x1 conv) and resolution (2x2 avg pool)."""
    tr = nn.HybridSequential(prefix="")
    _bn_relu_conv(tr, channels, 1)
    tr.add(nn.AvgPool2D(pool_size=2, strides=2))
    return tr


class DenseNet(HybridBlock):
    r"""DenseNet-BC trunk (ref densenet.py:65)."""

    def __init__(self, num_init_features, growth_rate, block_config,
                 bn_size=4, dropout=0, classes=1000, **kwargs):
        super().__init__(**kwargs)
        with self.name_scope():
            self.features = nn.HybridSequential(prefix="")
            self.features.add(nn.Conv2D(num_init_features, kernel_size=7,
                                        strides=2, padding=3, use_bias=False))
            self.features.add(nn.BatchNorm())
            self.features.add(nn.Activation("relu"))
            self.features.add(nn.MaxPool2D(pool_size=3, strides=2, padding=1))

            width = num_init_features
            last = len(block_config) - 1
            for stage, count in enumerate(block_config):
                self.features.add(_dense_stage(count, bn_size, growth_rate,
                                               dropout, stage + 1))
                width += count * growth_rate
                if stage != last:
                    width //= 2
                    self.features.add(_transition(width))

            self.features.add(nn.BatchNorm())
            self.features.add(nn.Activation("relu"))
            self.features.add(nn.AvgPool2D(pool_size=7))
            self.features.add(nn.Flatten())
            self.output = nn.Dense(classes)

    def hybrid_forward(self, F, x):
        return self.output(self.features(x))


# depth → (stem width, growth rate, per-stage layer counts)
densenet_spec = {121: (64, 32, [6, 12, 24, 16]),
                 161: (96, 48, [6, 12, 36, 24]),
                 169: (64, 32, [6, 12, 32, 32]),
                 201: (64, 32, [6, 12, 48, 32])}


def get_densenet(num_layers, pretrained=False, ctx=cpu(), **kwargs):
    stem, growth, stages = densenet_spec[num_layers]
    net = DenseNet(stem, growth, stages, **kwargs)
    if pretrained:
        from ..model_store import get_model_file
        net.load_params(get_model_file("densenet%d" % num_layers), ctx=ctx)
    return net


def _make_constructor(depth):
    def ctor(**kwargs):
        return get_densenet(depth, **kwargs)
    ctor.__name__ = "densenet%d" % depth
    ctor.__doc__ = "DenseNet-%d constructor." % depth
    return ctor


for _d in sorted(densenet_spec):
    globals()["densenet%d" % _d] = _make_constructor(_d)
del _d
