"""AlexNet ("One weird trick" variant).

API parity with the reference model zoo
(``python/mxnet/gluon/model_zoo/vision/alexnet.py:33``); the feature
extractor is built from a conv-spec table rather than inline adds.
"""
from __future__ import annotations

from ....context import cpu
from ...block import HybridBlock
from ... import nn

__all__ = ["AlexNet", "alexnet"]

# (channels, kernel, stride, pad, max-pool-after?)
_CONV_PLAN = [
    (64, 11, 4, 2, True),
    (192, 5, 1, 2, True),
    (384, 3, 1, 1, False),
    (256, 3, 1, 1, False),
    (256, 3, 1, 1, True),
]


class AlexNet(HybridBlock):
    r"""AlexNet: 5 conv stages + 2 dropout-regularised FC layers."""

    def __init__(self, classes=1000, **kwargs):
        super().__init__(**kwargs)
        with self.name_scope():
            self.features = nn.HybridSequential(prefix="")
            with self.features.name_scope():
                for ch, k, s, p, pool in _CONV_PLAN:
                    self.features.add(nn.Conv2D(ch, kernel_size=k, strides=s,
                                                padding=p, activation="relu"))
                    if pool:
                        self.features.add(nn.MaxPool2D(pool_size=3,
                                                       strides=2))
                self.features.add(nn.Flatten())
            self.classifier = nn.HybridSequential(prefix="")
            with self.classifier.name_scope():
                for _ in range(2):
                    self.classifier.add(nn.Dense(4096, activation="relu"))
                    self.classifier.add(nn.Dropout(0.5))
                self.classifier.add(nn.Dense(classes))

    def hybrid_forward(self, F, x):
        return self.classifier(self.features(x))


def alexnet(pretrained=False, ctx=cpu(), **kwargs):
    """Constructor; ``pretrained`` loads zoo weights."""
    net = AlexNet(**kwargs)
    if pretrained:
        from ..model_store import get_model_file
        net.load_params(get_model_file("alexnet"), ctx=ctx)
    return net
