"""MobileNet v1 with width multipliers 0.25/0.5/0.75/1.0.

API parity with the reference model zoo
(``python/mxnet/gluon/model_zoo/vision/mobilenet.py:33``); the depthwise-
separable stack is a single (out-channels, stride) plan list.

TPU note: depthwise conv = grouped Convolution with num_group == channels;
XLA lowers it to a feature-group-count convolution on the MXU.
"""
from __future__ import annotations

from ....context import cpu
from ...block import HybridBlock
from ... import nn

__all__ = ["MobileNet", "mobilenet1_0", "mobilenet0_75", "mobilenet0_5",
           "mobilenet0_25", "get_mobilenet"]

# (pointwise output channels, depthwise stride) for the 13 separable blocks
_SEPARABLE_PLAN = [
    (64, 1), (128, 2), (128, 1), (256, 2), (256, 1), (512, 2),
    (512, 1), (512, 1), (512, 1), (512, 1), (512, 1), (1024, 2), (1024, 1),
]


def _conv_bn_relu(seq, channels, kernel=1, stride=1, pad=0, groups=1):
    seq.add(nn.Conv2D(channels, kernel, stride, pad, groups=groups,
                      use_bias=False))
    seq.add(nn.BatchNorm(scale=True))
    seq.add(nn.Activation("relu"))


class MobileNet(HybridBlock):
    r"""Depthwise-separable trunk (ref mobilenet.py:33)."""

    def __init__(self, multiplier=1.0, classes=1000, **kwargs):
        super().__init__(**kwargs)
        scale = lambda ch: int(ch * multiplier)
        with self.name_scope():
            self.features = nn.HybridSequential(prefix="")
            with self.features.name_scope():
                _conv_bn_relu(self.features, scale(32), kernel=3, stride=2,
                              pad=1)
                width = scale(32)
                for out_ch, stride in _SEPARABLE_PLAN:
                    # depthwise 3x3 at current width, then pointwise 1x1
                    _conv_bn_relu(self.features, width, kernel=3,
                                  stride=stride, pad=1, groups=width)
                    width = scale(out_ch)
                    _conv_bn_relu(self.features, width)
                self.features.add(nn.GlobalAvgPool2D())
                self.features.add(nn.Flatten())
            self.output = nn.Dense(classes)

    def hybrid_forward(self, F, x):
        return self.output(self.features(x))


def get_mobilenet(multiplier, pretrained=False, ctx=cpu(), **kwargs):
    net = MobileNet(multiplier, **kwargs)
    if pretrained:
        from ..model_store import get_model_file
        tag = "%.2f" % multiplier
        if tag.endswith("0") and tag != "0.00":
            tag = tag[:-1]
        net.load_params(get_model_file("mobilenet%s" % tag), ctx=ctx)
    return net


def _make_constructor(multiplier, suffix):
    def ctor(**kwargs):
        return get_mobilenet(multiplier, **kwargs)
    ctor.__name__ = "mobilenet%s" % suffix
    ctor.__doc__ = "MobileNet with width multiplier %s." % multiplier
    return ctor


mobilenet1_0 = _make_constructor(1.0, "1_0")
mobilenet0_75 = _make_constructor(0.75, "0_75")
mobilenet0_5 = _make_constructor(0.5, "0_5")
mobilenet0_25 = _make_constructor(0.25, "0_25")
