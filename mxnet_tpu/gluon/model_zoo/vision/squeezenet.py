"""SqueezeNet 1.0 / 1.1.

API parity with the reference model zoo
(``python/mxnet/gluon/model_zoo/vision/squeezenet.py:60``); the feature
stack is driven by a per-version plan list where "P" marks a pool and a
tuple marks a fire module.
"""
from __future__ import annotations

from ....context import cpu
from ...block import HybridBlock
from ... import nn

__all__ = ["SqueezeNet", "squeezenet1_0", "squeezenet1_1"]


def _relu_conv(channels, kernel, padding=0):
    seq = nn.HybridSequential(prefix="")
    seq.add(nn.Conv2D(channels, kernel, padding=padding))
    seq.add(nn.Activation("relu"))
    return seq


class _Fire(HybridBlock):
    """Squeeze 1x1 → parallel 1x1/3x3 expands, channel-concatenated."""

    def __init__(self, squeeze, expand1, expand3, **kwargs):
        super().__init__(**kwargs)
        self.squeeze = _relu_conv(squeeze, 1)
        self.left = _relu_conv(expand1, 1)
        self.right = _relu_conv(expand3, 3, 1)

    def hybrid_forward(self, F, x):
        x = self.squeeze(x)
        return F.concat(self.left(x), self.right(x), dim=1)


def _pool():
    return nn.MaxPool2D(pool_size=3, strides=2, ceil_mode=True)


# Per-version plans after the stem conv: "P" = pool, tuple = fire module.
_PLANS = {
    "1.0": ["P", (16, 64, 64), (16, 64, 64), (32, 128, 128), "P",
            (32, 128, 128), (48, 192, 192), (48, 192, 192), (64, 256, 256),
            "P", (64, 256, 256)],
    "1.1": ["P", (16, 64, 64), (16, 64, 64), "P", (32, 128, 128),
            (32, 128, 128), "P", (48, 192, 192), (48, 192, 192),
            (64, 256, 256), (64, 256, 256)],
}
_STEMS = {"1.0": (96, 7), "1.1": (64, 3)}


class SqueezeNet(HybridBlock):
    r"""SqueezeNet (ref squeezenet.py:60): fire modules + conv classifier."""

    def __init__(self, version, classes=1000, **kwargs):
        super().__init__(**kwargs)
        if version not in _PLANS:
            raise ValueError("Unsupported SqueezeNet version %s: "
                             "1.0 or 1.1 expected" % version)
        stem_ch, stem_k = _STEMS[version]
        with self.name_scope():
            self.features = nn.HybridSequential(prefix="")
            self.features.add(nn.Conv2D(stem_ch, kernel_size=stem_k,
                                        strides=2))
            self.features.add(nn.Activation("relu"))
            for item in _PLANS[version]:
                self.features.add(_pool() if item == "P" else _Fire(*item))
            self.features.add(nn.Dropout(0.5))
            self.output = nn.HybridSequential(prefix="")
            self.output.add(nn.Conv2D(classes, kernel_size=1))
            self.output.add(nn.Activation("relu"))
            self.output.add(nn.GlobalAvgPool2D())
            self.output.add(nn.Flatten())

    def hybrid_forward(self, F, x):
        return self.output(self.features(x))


def get_squeezenet(version, pretrained=False, ctx=cpu(), **kwargs):
    net = SqueezeNet(version, **kwargs)
    if pretrained:
        from ..model_store import get_model_file
        net.load_params(get_model_file("squeezenet%s" % version), ctx=ctx)
    return net


def squeezenet1_0(**kwargs):
    return get_squeezenet("1.0", **kwargs)


def squeezenet1_1(**kwargs):
    return get_squeezenet("1.1", **kwargs)
