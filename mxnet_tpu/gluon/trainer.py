"""Gluon Trainer: applies an Optimizer to a set of Parameters.

Parity surface: reference ``python/mxnet/gluon/trainer.py:27`` —
``_init_kvstore`` (:102), ``step(batch_size)`` (:148: per-param
kvstore.push(grad) then pull; or local Updater :181-192), stale-grad
detection, ``save_states/load_states`` (:194-227).

TPU-native: a single device holds one logical copy of each parameter
(sharded/replicated by jax), so the push/pull data movement of the
reference collapses to running the fused optimizer update op; with a
'tpu'/'dist' kvstore the gradient is psum'd over the mesh first.
"""
from __future__ import annotations

from .. import optimizer as opt
from .. import kvstore as kvs
from .parameter import ParameterDict, Parameter

__all__ = ["Trainer"]


class Trainer(object):
    def __init__(self, params, optimizer, optimizer_params=None,
                 kvstore="device"):
        if isinstance(params, (dict, ParameterDict)):
            params = list(params.values())
        if not isinstance(params, (list, tuple)):
            raise ValueError(
                "First argument must be a list or dict of Parameters, "
                "got %s." % type(params))
        self._params = []
        for param in params:
            if not isinstance(param, Parameter):
                raise ValueError(
                    "First argument must be a list or dict of Parameters, "
                    "got list of %s." % type(param))
            self._params.append(param)

        optimizer_params = optimizer_params if optimizer_params else {}
        self._scale = optimizer_params.get("rescale_grad", 1.0)
        self._init_optimizer(optimizer, optimizer_params)
        self._kv_initialized = False
        self._kvstore_type = kvstore
        self._kvstore = None
        self._update_on_kvstore = None

    def _init_optimizer(self, optimizer, optimizer_params):
        param_dict = {i: param for i, param in enumerate(self._params)}
        if isinstance(optimizer, opt.Optimizer):
            assert not optimizer_params, \
                "optimizer_params must be None if optimizer is an " \
                "Optimizer instance"
            self._optimizer = optimizer
            self._optimizer.param_dict = param_dict
        else:
            self._optimizer = opt.create(optimizer, param_dict=param_dict,
                                         **optimizer_params)
        self._updaters = [opt.get_updater(self._optimizer)]

    def _init_kvstore(self):
        if self._kvstore_type:
            kv = kvs.create(self._kvstore_type) \
                if isinstance(self._kvstore_type, str) else self._kvstore_type
            self._kvstore = kv
            self._update_on_kvstore = False
            for i, param in enumerate(self._params):
                if param.grad_req != "null":
                    kv.init(i, param.data())
        else:
            self._kvstore = None
            self._update_on_kvstore = False
        self._kv_initialized = True

    @property
    def learning_rate(self):
        return self._optimizer.learning_rate

    def set_learning_rate(self, lr):
        self._optimizer.set_learning_rate(lr)

    def step(self, batch_size, ignore_stale_grad=False):
        """Make one parameter update step (reference trainer.py:148)."""
        if not self._kv_initialized:
            self._init_kvstore()
        self._optimizer.rescale_grad = self._scale / batch_size

        for i, param in enumerate(self._params):
            if param.grad_req == "null":
                continue
            grad = param.grad()
            if self._kvstore is not None:
                # push grad, pull reduced grad (update locally)
                self._kvstore.push(i, [grad])
                self._kvstore.pull(i, out=[grad])
            self._updaters[0](i, grad, param.data())

    def save_states(self, fname):
        assert self._optimizer is not None
        with open(fname, "wb") as f:
            f.write(self._updaters[0].get_states())

    def load_states(self, fname):
        if not self._kv_initialized:
            self._init_kvstore()
        with open(fname, "rb") as f:
            states = f.read()
        self._updaters[0].set_states(states)
        self._optimizer = self._updaters[0].optimizer
