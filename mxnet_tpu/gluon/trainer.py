"""Gluon Trainer: one optimizer step over a parameter set.

API parity with the reference ``python/mxnet/gluon/trainer.py:27``
(``_init_kvstore`` :102, ``step`` :148-192, ``save_states``/``load_states``
:194-227), built independently around a flat slot list.

TPU-native: one device holds one logical copy of each parameter (jax shards
or replicates it), so the reference's per-device push/pull traffic reduces
to the fused optimizer update; a 'tpu'/'dist' kvstore psums the gradient
over the mesh before the update.
"""
from __future__ import annotations

import pickle

from .. import chaos as _chaos
from .. import kvstore as kvs
from .. import model_stats as _mstats
from .. import optimizer as opt
from .. import telemetry as _tel
from ..checkpoint import hooks as _ckpt_hooks
from ..guardian import core as _guard
from . import overlap as _overlap
from .fused_trainer import (ensure_unsharded, fused_trainer_enabled,
                            run_fused_step)
from .parameter import Parameter, ParameterDict

__all__ = ["Trainer"]


def _flatten_params(params):
    """Accept ParameterDict / dict / list; return a validated flat list."""
    if isinstance(params, (dict, ParameterDict)):
        params = list(params.values())
    if not isinstance(params, (list, tuple)):
        raise ValueError("First argument must be a list or dict of "
                         "Parameters, got %s." % type(params))
    for p in params:
        if not isinstance(p, Parameter):
            raise ValueError("First argument must be a list or dict of "
                             "Parameters, got list of %s." % type(p))
    return list(params)


class Trainer(object):
    """Couples Parameters with an Optimizer and (optionally) a kvstore.

    Each parameter occupies one integer slot: the slot indexes the kvstore
    key and the Updater state entry alike.
    """

    def __init__(self, params, optimizer, optimizer_params=None,
                 kvstore="device"):
        self._params = _flatten_params(params)
        hyper = dict(optimizer_params or {})
        self._scale = hyper.get("rescale_grad", 1.0)
        self._optimizer = self._make_optimizer(optimizer, hyper)
        self._updater = opt.get_updater(self._optimizer)
        self._kvstore_spec = kvstore
        self._kvstore = None
        self._kv_initialized = False

    def _make_optimizer(self, optimizer, hyper):
        slots = dict(enumerate(self._params))
        if isinstance(optimizer, opt.Optimizer):
            if hyper:
                raise ValueError("optimizer_params must be None when an "
                                 "Optimizer instance is given")
            optimizer.param_dict = slots
            return optimizer
        return opt.create(optimizer, param_dict=slots, **hyper)

    def _init_kvstore(self):
        """Lazily create the kvstore and register every trainable slot."""
        spec = self._kvstore_spec
        if spec:
            store = kvs.create(spec) if isinstance(spec, str) else spec
            for slot, param in enumerate(self._params):
                if param.grad_req != "null":
                    store.init(slot, param.data())
            self._kvstore = store
        self._kv_initialized = True

    learning_rate = property(lambda self: self._optimizer.learning_rate)

    def set_learning_rate(self, lr):
        self._optimizer.set_learning_rate(lr)

    def step(self, batch_size, ignore_stale_grad=False):
        """Gradient-reduce (via kvstore) then update each parameter
        (ref trainer.py:148). *batch_size* normalises the gradient.

        With ``ignore_stale_grad=True`` slots whose gradient was not
        freshly written by backward since the last step are skipped;
        otherwise a stale gradient raises (reference trainer.py:148
        semantics — it usually means the model used only a subset of its
        Parameters this iteration).

        Default path (``MXNET_FUSED_TRAINER`` unset/1): bucketed
        gradient all-reduce + ONE jitted, donated whole-model optimizer
        program (gluon/fused_trainer.py).  ``MXNET_FUSED_TRAINER=0``
        falls back to the per-slot loop, which is also the
        bitwise-equality oracle in tests.

        ``MXNET_ZERO=1`` additionally shards the weight update ZeRO-1
        style across ``MXNET_ZERO_SHARDS`` local devices (docs/ZERO.md):
        optimizer state persists 1/N per device, the kvstore leg becomes
        a bucketed reduce-scatter, and the one step program all-gathers
        updated weights — bitwise-identical to the replicated paths.

        With a :class:`~mxnet_tpu.guardian.TrainingGuardian` installed
        the step additionally computes a finite-health verdict inside
        the update program, suppresses the update on NaN/Inf, and folds
        the guardian's loss scale into the traced rescale (see
        docs/GUARDIAN.md); a skipped step does not notify the
        checkpoint step boundary.

        ``MXNET_OVERLAP`` (default on): each step arms a bucket-ready
        overlap session for the next iteration — backward dispatches
        every gradient bucket's kvstore reduce as an engine task the
        moment the bucket's gradients exist, and this step *drains*
        the in-flight buckets instead of launching the round itself
        (``gluon/overlap.py``; ``MXNET_OVERLAP=0`` is the bitwise
        oracle).  The guardian verdict is unaffected: it is computed
        inside the one update program, which only runs after every
        bucket has landed.
        """
        if not self._kv_initialized:
            self._init_kvstore()
        self._optimizer.rescale_grad = float(self._scale) / batch_size
        guard = _guard.current()
        if guard is not None:
            # fold the inverse loss scale into the traced rescale scalar:
            # scaled gradients un-scale inside the update program, and a
            # scale change (halve/double) never retraces
            self._optimizer.rescale_grad = guard.apply_rescale(
                self._optimizer.rescale_grad)

        slots = []
        for slot, param in enumerate(self._params):
            if param.grad_req == "null":
                continue
            if not param._fresh_grad:
                if not ignore_stale_grad:
                    raise UserWarning(
                        "Gradient of Parameter `%s` has not been updated "
                        "by backward since last `step`. This could mean "
                        "a bug in your model that made it only use a "
                        "subset of the Parameters for this iteration. If "
                        "you are intentionally only using a subset, call "
                        "step with ignore_stale_grad=True to suppress "
                        "this warning and skip updating of Parameters "
                        "with stale gradient" % param.name)
                continue
            slots.append((slot, param))

        skipped = False
        if slots:
            # step-boundary span: kvstore buckets and the optimizer
            # program nest inside it; memory watermarks, the XLA cost
            # window (step_model_flops/step_mfu), and the engine-backlog
            # gauge resolve at its exit (telemetry on only)
            with _tel.span("trainer_step", cat="step", hist="step_time_us",
                           memory=True,
                           args={"slots": len(slots),
                                 "batch_size": batch_size}):
                if fused_trainer_enabled() \
                        and self._optimizer.supports_fused():
                    skipped = run_fused_step(self, slots)
                else:
                    skipped = self._loop_step(slots)
        for _, param in slots:
            param._fresh_grad = False
        # step boundary: params/optimizer/iterator agree on one step —
        # the active CheckpointManager snapshots here and honors a
        # pending SIGTERM (one global read when no manager is installed).
        # A guardian-skipped step is NOT a completed optimizer step:
        # nothing advanced, so nothing to snapshot.
        if not skipped:
            _ckpt_hooks.note_step_boundary()
        # arm comm/compute overlap for the NEXT iteration: the coming
        # backward will dispatch each gradient bucket's reduce as soon
        # as the bucket is ready (no-op when MXNET_OVERLAP=0, no
        # kvstore, or the step won't take the fused path)
        if slots:
            _overlap.maybe_arm(self, slots)

    def _loop_step(self, slots):
        """Per-slot fallback: one kvstore round + one eager Updater
        dispatch per parameter (O(n_params) program calls).

        With a guardian installed this grows the IDENTICAL guard the
        fused path folds in: reduce everything first, one finiteness
        verdict over the reduced gradients (+ recorded loss), then
        either every per-slot update or none — the bitwise oracle covers
        the skip machinery too.  Returns True when the step was skipped.
        """
        guard = _guard.current()
        # an armed overlap session belongs to the fused path: its
        # results target the bucketed round, not this per-slot loop
        _overlap.abandon_session(self)
        # state left mesh-sharded by an earlier ZeRO step must come home
        # before eager per-slot dispatch mixes devices
        ensure_unsharded(self, slots)
        # MXNET_MODEL_STATS on the oracle path: snapshot the pre-update
        # weights now; one extra watched `model_stats` program computes
        # the identical stats block the fused paths emit as a side-output
        # (due steps only — the update math is untouched either way)
        stats_due = _mstats.recorder().note_step() \
            if _mstats.enabled() else False
        old_raw = [param.data()._data for _, param in slots] \
            if stats_due else None
        if _chaos.active():          # the same grad seam, once per step
            raws = _chaos.poison_grads(
                [param.grad()._data for _, param in slots])
            for (_, param), raw in zip(slots, raws):
                if raw is not param.grad()._data:
                    param.grad()._set_data(raw)
        if guard is None:
            for slot, param in slots:
                grad = param.grad()
                if self._kvstore is not None:
                    # all-reduce the gradient across workers, update
                    # locally
                    with _tel.span("kvstore_push_pull", cat="kvstore"):
                        self._kvstore.push(slot, [grad])
                        self._kvstore.pull(slot, out=[grad])
                with _tel.span("optimizer_update", cat="program"):
                    self._updater(slot, grad, param.data())
            self._record_loop_stats(slots, old_raw, None)
            return False
        if self._kvstore is not None:
            for slot, param in slots:
                grad = param.grad()
                with _tel.span("kvstore_push_pull", cat="kvstore"):
                    self._kvstore.push(slot, [grad])
                    self._kvstore.pull(slot, out=[grad])
        loss_raw = guard.take_loss_raw()
        finite = guard.grads_finite(
            [param.grad()._data for _, param in slots], loss_raw)
        if finite:
            for slot, param in slots:
                with _tel.span("optimizer_update", cat="program"):
                    self._updater(slot, param.grad(), param.data())
        self._record_loop_stats(slots, old_raw, loss_raw)
        return guard.after_step(finite)

    def _record_loop_stats(self, slots, old_raw, loss_raw):
        """The oracle path's model-stats leg: one extra watched
        ``model_stats`` program over (old weights, reduced grads, new
        weights) — a skipped guardian step records update_ratio 0 over
        its nonfinite grads, exactly what the fused side-output yields
        through its ``jnp.where`` passthrough."""
        if old_raw is None:
            return
        grads_raw = [param.grad()._data for _, param in slots]
        new_raw = [param.data()._data for _, param in slots]
        _tel.bump("xla_program_calls")     # the oracle's one extra program
        block = _mstats.stats_program()(old_raw, grads_raw, new_raw,
                                        loss_raw)
        _mstats.recorder().record_block(
            [param.name for _, param in slots], block,
            loss_raw is not None)

    def save_states(self, fname):
        """Serialise optimizer state (moments etc.) to *fname*.

        Writes the Updater's per-slot state trees AND the fused-trainer
        step cache — the per-slot update counts that feed ``hyper['t']``
        into the fused program (Adam/Nadam bias correction).  The legacy
        format serialized only the ``_updater`` states, so a
        save→load→step round-trip silently reset ``t`` and diverged from
        an uninterrupted run.
        """
        if self._optimizer is None:
            raise AssertionError("trainer has no optimizer")
        payload = {
            "__mxnet_trainer_states__": 2,
            "updater": self._updater.get_states(),
            "index_update_count":
                {int(k): int(v) for k, v in
                 self._optimizer._index_update_count.items()},
            "num_update": int(self._optimizer.num_update),
        }
        with open(fname, "wb") as fh:
            fh.write(pickle.dumps(payload))

    def load_states(self, fname):
        """Restore state written by :meth:`save_states` (either format:
        the versioned dict, or a legacy raw Updater blob)."""
        if not self._kv_initialized:
            self._init_kvstore()
        with open(fname, "rb") as fh:
            raw = fh.read()
        payload = pickle.loads(raw)
        if isinstance(payload, dict) \
                and "__mxnet_trainer_states__" in payload:
            self._updater.set_states(payload["updater"])
            self._optimizer = self._updater.optimizer
            self._optimizer._index_update_count = \
                dict(payload["index_update_count"])
            self._optimizer.num_update = int(payload["num_update"])
        else:
            # legacy blob: reuse the decoded payload — a second
            # set_states(raw) would re-materialize every state NDArray
            self._updater.set_states_payload(payload)
            self._optimizer = self._updater.optimizer
