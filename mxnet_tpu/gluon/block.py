"""Gluon Block / HybridBlock / SymbolBlock.

Parity surface: reference ``python/mxnet/gluon/block.py`` — ``Block`` (:33,
eager container + ``_BlockScope`` param management :120), ``HybridBlock``
(:305, ``hybridize`` traces ``hybrid_forward`` into a CachedOp
:364-417), ``SymbolBlock`` (:497).

TPU-native redesign: the reference's CachedOp (``src/imperative/
cached_op.cc``) builds an NNVM graph once and replays it through the
dependency engine.  Here hybridize compiles ``hybrid_forward`` into ONE XLA
program with ``jax.jit``: the traced function is pure
``(param_values, inputs, rng_key) -> (outputs, updated_aux)``; jax caches
specializations per input shape/dtype exactly like CachedOp's
shape-specialized plans (``cached_op.cc:175``).  Under ``autograd.record``
the whole jitted program lands on the tape as a single node via ``jax.vjp``
— the direct analogue of ``_CachedOp``'s fused backward
(``cached_op.cc:385``).
"""
from __future__ import annotations

import itertools as _itertools
import re
import threading

import numpy as np
import jax

from ..base import MXNetError
from ..context import Context, current_context
from .. import ndarray as nd
from ..ndarray.ndarray import NDArray, _wrap
from .. import symbol as _sym
from ..symbol import Symbol
from .. import autograd
from .. import random as _random
from .. import telemetry as _tel
from .parameter import Parameter, ParameterDict, DeferredInitializationError

__all__ = ["Block", "HybridBlock", "SymbolBlock"]


class _BlockScope(object):
    """Name-manager for Block construction (reference block.py:33)."""
    _current = threading.local()

    def __init__(self, block):
        self._block = block
        self._counter = {}
        self._old_scope = None

    @staticmethod
    def create(prefix, params, hint):
        """Create prefix and params for new Block."""
        current = getattr(_BlockScope._current, "value", None)
        if current is None:
            if prefix is None:
                from .. import name as _name
                prefix = _name.current().get(None, hint) + "_"
            if params is None:
                params = ParameterDict(prefix)
            else:
                params = ParameterDict(params.prefix, params)
            return prefix, params
        if prefix is None:
            count = current._counter.get(hint, 0)
            prefix = "%s%d_" % (hint, count)
            current._counter[hint] = count + 1
        if params is None:
            parent = current._block.params
            params = ParameterDict(parent.prefix + prefix, parent._shared)
        else:
            params = ParameterDict(params.prefix, params)
        return current._block.prefix + prefix, params

    def __enter__(self):
        self._old_scope = getattr(_BlockScope._current, "value", None)
        _BlockScope._current.value = self
        return self

    def __exit__(self, ptype, value, trace):
        _BlockScope._current.value = self._old_scope


def _flatten(args):
    """Flatten nested lists/tuples of NDArrays/Symbols; return flat list
    + fmt tree."""
    if args is None:
        return [], None
    if not isinstance(args, (list, tuple)):
        return [args], int(0)
    flat, fmts = [], []
    for a in args:
        f, fmt = _flatten(a)
        flat.extend(f)
        fmts.append(fmt)
    return flat, fmts


def _regroup(flat, fmt):
    if fmt is None:
        return None, flat
    if isinstance(fmt, int):
        return flat[0], flat[1:]
    ret = []
    for f in fmt:
        r, flat = _regroup(flat, f)
        ret.append(r)
    return ret, flat


class Block(object):
    """Base class for all neural network layers and models.

    Reference: ``gluon/block.py:33``.  Children assigned as attributes are
    registered automatically; ``collect_params`` walks the tree.
    """

    def __init__(self, prefix=None, params=None):
        self._empty_prefix = prefix == ""
        self._prefix, self._params = _BlockScope.create(
            prefix, params, self._alias())
        self._name = self._prefix[:-1] if self._prefix.endswith("_") \
            else self._prefix
        self._scope = _BlockScope(self)
        self._children = []

    def __repr__(self):
        s = "{name}(\n{modstr}\n)"
        modstr = "\n".join("  ({key}): {block}".format(
            key=i, block=_indent(repr(b), 2))
            for i, b in enumerate(self._children))
        return s.format(name=self.__class__.__name__, modstr=modstr)

    def __setattr__(self, name, value):
        if isinstance(value, Block):
            self.register_child(value)
        super(Block, self).__setattr__(name, value)

    def _alias(self):
        return self.__class__.__name__.lower()

    @property
    def prefix(self):
        return self._prefix

    @property
    def name(self):
        return self._name

    def name_scope(self):
        return self._scope

    @property
    def params(self):
        return self._params

    def collect_params(self):
        """Return a ParameterDict of this block's and children's params."""
        ret = ParameterDict(self._params.prefix)
        ret.update(self.params)
        for child in self._children:
            ret.update(child.collect_params())
        return ret

    def save_params(self, filename):
        self.collect_params().save(filename, strip_prefix=self.prefix)

    def load_params(self, filename, ctx=None, allow_missing=False,
                    ignore_extra=False):
        self.collect_params().load(filename, ctx, allow_missing,
                                   ignore_extra, self.prefix)

    def register_child(self, block):
        self._children.append(block)

    def initialize(self, init=None, ctx=None, verbose=False,
                   force_reinit=False):
        if init is None:
            from .. import initializer
            init = initializer.Uniform()
        self.collect_params().initialize(init, ctx, verbose,
                                         force_reinit=force_reinit)

    def hybridize(self, active=True):
        for child in self._children:
            child.hybridize(active)

    def cast(self, dtype):
        for child in self._children:
            child.cast(dtype)
        for _, param in self.params.items():
            param.cast(dtype)

    def __call__(self, *args):
        return self.forward(*args)

    def forward(self, *args):
        raise NotImplementedError


def _indent(s_, num_spaces):
    lines = s_.split("\n")
    if len(lines) == 1:
        return s_
    first = lines.pop(0)
    return first + "\n" + "\n".join(" " * num_spaces + line
                                    for line in lines)


class HybridBlock(Block):
    """A Block that can be traced into one compiled XLA program.

    Reference: ``gluon/block.py:305``.  Subclasses implement
    ``hybrid_forward(F, x, *, weight=..., bias=...)`` written against
    ``F = mxnet_tpu.ndarray`` or ``F = mxnet_tpu.symbol``.
    """

    def __init__(self, prefix=None, params=None):
        super(HybridBlock, self).__init__(prefix, params)
        self._active = False
        self._cached_op = None
        self._reg_params = {}

    def __setattr__(self, name, value):
        super(HybridBlock, self).__setattr__(name, value)
        if isinstance(value, Parameter):
            assert name not in self._reg_params or \
                self._reg_params[name] is value, \
                "Overriding Parameter attribute %s is not allowed." % name
            self._reg_params[name] = value

    def register_child(self, block):
        if not isinstance(block, HybridBlock):
            raise ValueError(
                "Children of HybridBlock must also be HybridBlock, but %s "
                "has type %s." % (str(block), str(type(block))))
        super(HybridBlock, self).register_child(block)
        self._cached_op = None

    def hybridize(self, active=True):
        self._active = active
        self._cached_op = None
        super(HybridBlock, self).hybridize(active)

    def cast(self, dtype):
        self._cached_op = None
        super(HybridBlock, self).cast(dtype)

    # -- deferred shape inference -----------------------------------------
    def infer_shape(self, *args):
        """Infer deferred parameter shapes by symbolic tracing
        (reference block.py:417)."""
        params = {p.name: p for p in self.collect_params().values()}
        flat_args, in_fmt = _flatten(list(args))
        flat_vars = [_sym.var("data%d" % i) for i in range(len(flat_args))]
        arg_tree, _ = _regroup(list(flat_vars), in_fmt)
        pkw = {name: p.var() for name, p in self._reg_params.items()}
        with autograd.pause():
            out = self.hybrid_forward(_sym, *arg_tree, **pkw)
        flat_out, _ = _flatten(out)
        out = flat_out[0] if len(flat_out) == 1 else _sym.Group(flat_out)
        shape_kw = {"data%d" % i: a.shape for i, a in enumerate(flat_args)}
        arg_shapes, _, aux_shapes = out.infer_shape_partial(**shape_kw)
        arg_names = out.list_arguments()
        aux_names = out.list_auxiliary_states()
        for name, shape in list(zip(arg_names, arg_shapes)) + \
                list(zip(aux_names, aux_shapes)):
            if name in params and shape is not None:
                params[name]._set_shape_if_deferred(shape)

    def _finish_deferred(self, *args):
        self.infer_shape(*args)
        for p in self.collect_params().values():
            p._finish_deferred_init()

    # -- execution ---------------------------------------------------------
    def __call__(self, *args):
        return self.forward(*args)

    def forward(self, x, *args):
        if isinstance(x, NDArray):
            try:
                if self._active:
                    return self._call_cached_op(x, *args)
                params = {k: p.data() for k, p in self._reg_params.items()}
            except DeferredInitializationError:
                self._finish_deferred(x, *args)
                if self._active:
                    return self._call_cached_op(x, *args)
                params = {k: p.data() for k, p in self._reg_params.items()}
            return self.hybrid_forward(nd, x, *args, **params)
        if not isinstance(x, Symbol):
            raise ValueError(
                "HybridBlock input must be NDArray or Symbol, got %s"
                % type(x))
        pkw = {k: p.var() for k, p in self._reg_params.items()}
        return self.hybrid_forward(_sym, x, *args, **pkw)

    def hybrid_forward(self, F, x, *args, **kwargs):
        raise NotImplementedError

    # -- CachedOp (jit) path ----------------------------------------------
    def _build_cached_op(self):
        pd = self.collect_params()
        grad_params = [(n, p) for n, p in pd.items()
                       if p.grad_req != "null"]
        aux_params = [(n, p) for n, p in pd.items() if p.grad_req == "null"]
        self._cached_op = _CachedOp(self, [n for n, _ in grad_params],
                                    [n for n, _ in aux_params])
        self._cached_graph_params = (grad_params, aux_params)

    def _call_cached_op(self, *args):
        if self._cached_op is None:
            # trigger deferred init before tracing
            for p in self.collect_params().values():
                if p._deferred_init:
                    raise DeferredInitializationError(
                        "Parameter %s not initialized" % p.name)
                p._check_and_get()
            self._build_cached_op()
        return self._cached_op(*args)


_CACHED_OP_SEQ = _itertools.count()


class _CachedOp(object):
    """jit-compiled replay of a HybridBlock (reference cached_op.cc).

    The pure function is ``(grad_param_vals, aux_vals, input_vals, key)
    -> (flat_outputs, new_aux_vals)``; aux updates (BatchNorm moving
    stats) come back as explicit outputs and are written to the aux
    parameters after each call — the functional equivalent of the
    reference's in-place aux mutation.
    """

    def __init__(self, block, grad_names, aux_names):
        self._block = block
        self._grad_names = grad_names
        self._aux_names = aux_names
        pd = {p.name: p for p in block.collect_params().values()}
        self._pd = pd
        self._grad_params = [pd[n] for n in grad_names]
        self._aux_params = [pd[n] for n in aux_names]
        self._jit = {}   # train_mode -> jitted fn
        # watchdog identity: per-instance, so unrelated blocks (including
        # prefix="" ones) never aggregate into a phantom retrace storm
        self._watch_name = "gluon_cached_op:%s" % (
            block.prefix or "%s#%d" % (type(block).__name__,
                                       next(_CACHED_OP_SEQ)))
        self._fmt = None
        self._in_fmt = None

    def _pure(self, train_mode):
        block = self._block
        grad_names, aux_names = self._grad_names, self._aux_names

        def fn(grad_vals, aux_vals, in_vals, key):
            pd = self._pd
            handles = {}
            for name, v in list(zip(grad_names, grad_vals)) + \
                    list(zip(aux_names, aux_vals)):
                handles[name] = _wrap(v)
            saved = {}
            for name, h in handles.items():
                p = pd[name]
                saved[name] = p._data
                p._data = h
            try:
                with autograd.pause(train_mode=train_mode), \
                        _random.key_scope(key):
                    flat = [_wrap(v) for v in in_vals]
                    ins, _ = _regroup(list(flat), self._in_fmt)
                    out = block.hybrid_forward_dispatch(ins)
                    flat, fmt = _flatten(out)
                    self._fmt = fmt
                    out_vals = tuple(o._data for o in flat)
                    new_aux = tuple(handles[n]._data for n in aux_names)
            finally:
                for name, old in saved.items():
                    pd[name]._data = old
            return out_vals, new_aux
        return fn

    def _jitted(self, train):
        """The compiled replay program for *train* mode, built on first
        use (shared by ``__call__`` and the graftcheck AOT driver, so the
        trace tier analyzes the exact program this op ships)."""
        if train not in self._jit:
            pure = self._pure(train)
            from ..base import mirror_enabled
            if mirror_enabled():
                # MXNET_BACKWARD_DO_MIRROR (ref graph_executor.cc:281-304):
                # rematerialise forward activations in backward instead of
                # keeping them live — jax.checkpoint is the XLA-native form
                pure = jax.checkpoint(pure)
            self._jit[train] = _tel.watch_jit(jax.jit(pure),
                                              self._watch_name)
        return self._jit[train]

    def __call__(self, *args):
        grad_params = self._grad_params
        aux_params = self._aux_params
        grad_vals = tuple(p._data._data for p in grad_params)
        aux_vals = tuple(p._data._data for p in aux_params)
        flat_in, in_fmt = _flatten(list(args))
        self._in_fmt = in_fmt
        in_vals = tuple(x._data for x in flat_in)
        key = _random.next_key()
        train = autograd.is_training()
        recording = autograd.is_recording()

        jitted = self._jitted(train)

        if recording:
            def diff_fn(gvals, ivals):
                return jitted(gvals, aux_vals, ivals, key)
            (out_vals, new_aux), vjp_fn = jax.vjp(
                diff_fn, grad_vals, in_vals)

            def tape_vjp(out_grads):
                zeros_aux = tuple(jax.numpy.zeros_like(a) for a in new_aux)
                d_g, d_in = vjp_fn((tuple(out_grads), zeros_aux))
                return list(d_g) + list(d_in)
            inputs = [p._data for p in grad_params] + flat_in
            diff_idx = list(range(len(inputs)))
            outputs = [_wrap(v) for v in out_vals]
            node = autograd.TapeNode(None, {}, inputs, outputs, diff_idx,
                                     vjp_fn=tape_vjp)
            for o in outputs:
                o._tape_node = node
            autograd.append_node(node)
        else:
            out_vals, new_aux = jitted(grad_vals, aux_vals, in_vals, key)
            outputs = [_wrap(v) for v in out_vals]

        for p, v in zip(aux_params, new_aux):
            p._data._set_data(v)
        out, _ = _regroup(outputs, self._fmt)
        return out


def tracecheck_programs():
    """AOT specimens for graftcheck: the hybridized-block replay program
    (``gluon_cached_op``), built through the same ``_CachedOp._jitted``
    path ``__call__`` uses.  A tiny Dense block stands in; its weight
    buffers exist (initialize allocates) but the program is only traced,
    never executed."""
    from . import nn
    net = nn.Dense(8, in_units=16)
    net.initialize()
    net._build_cached_op()
    co = net._cached_op
    x = nd.zeros((4, 16))
    _flat, co._in_fmt = _flatten([x])
    jitted = co._jitted(False)
    grad_vals = tuple(p._data._data for p in co._grad_params)
    aux_vals = tuple(p._data._data for p in co._aux_params)
    key = _random.next_key()
    return [("gluon_cached_op", jitted,
             (grad_vals, aux_vals, (x._data,), key), {})]


def _hybrid_forward_dispatch(self, ins):
    params = {k: p.data() for k, p in self._reg_params.items()}
    ndin = ins
    # children called inside hybrid_forward go through their own forward();
    # inside a trace they take the eager path (params already concrete or
    # tracer-bound via the handle swap in _CachedOp._pure).
    return self.hybrid_forward(nd, *ndin, **params)


HybridBlock.hybrid_forward_dispatch = _hybrid_forward_dispatch


class SymbolBlock(HybridBlock):
    """Construct a block from a Symbol (reference block.py:497)."""

    def __init__(self, outputs, inputs, params=None):
        super(SymbolBlock, self).__init__(prefix=None, params=params)
        self._prefix = ""
        self._params = ParameterDict("", params)
        if isinstance(inputs, Symbol):
            inputs = [inputs]
        if isinstance(outputs, (list, tuple)) and len(outputs) == 1:
            outputs = outputs[0]
        if isinstance(outputs, (list, tuple)):
            out = _sym.Group(outputs)
        else:
            out = outputs
        input_names = set(i.name for i in inputs)
        for name in out.list_arguments():
            if name not in input_names:
                self.params.get(name, allow_deferred_init=True)
        for name in out.list_auxiliary_states():
            self.params.get(name, grad_req="null",
                            allow_deferred_init=True)
        self._out = out
        self._input_names = [i.name for i in inputs]

    def forward(self, x, *args):
        if isinstance(x, NDArray):
            arg_dict = {self._input_names[0]: x}
            for n, a in zip(self._input_names[1:], args):
                arg_dict[n] = a
            aux_dict = {}
            aux_names = set(self._out.list_auxiliary_states())
            for name, p in self.params.items():
                (aux_dict if name in aux_names else arg_dict)[name] = p.data()
            ex = self._out.bind(x.context, arg_dict, grad_req="null",
                                aux_states=aux_dict)
            outs = ex.forward(is_train=autograd.is_training())
            return outs[0] if len(outs) == 1 else outs
        raise NotImplementedError(
            "SymbolBlock symbolic forward not supported")

    def hybrid_forward(self, F, x, *args, **kwargs):
        raise NotImplementedError
