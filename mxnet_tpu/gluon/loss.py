"""Gluon loss blocks.

API parity with the reference ``python/mxnet/gluon/loss.py`` (L1/L2, sigmoid
BCE, softmax CE, KL divergence, CTC, Huber/hinge family). Independent design:
pointwise losses share a ``_PointwiseLoss`` template — subclasses provide
only the per-element residual term; label reshaping, sample weighting, and
the mean-over-non-batch-axes reduction live in one place.
"""
from __future__ import annotations

from .block import HybridBlock

__all__ = ["Loss", "L1Loss", "L2Loss", "SigmoidBinaryCrossEntropyLoss",
           "SigmoidBCELoss", "SoftmaxCrossEntropyLoss", "SoftmaxCELoss",
           "KLDivLoss", "CTCLoss", "HuberLoss", "HingeLoss",
           "SquaredHingeLoss"]


def _apply_weighting(F, loss, weight=None, sample_weight=None):
    """Scale *loss* by a per-sample array and/or a scalar (ref loss.py:31)."""
    if weight is not None:
        if not isinstance(weight, (int, float)):
            raise TypeError("weight must be a number")
        loss = weight * loss
    if sample_weight is not None:
        loss = F.broadcast_mul(loss, sample_weight)
    return loss


def _reshape_like(F, x, y):
    return x.reshape(y.shape)


class Loss(HybridBlock):
    """Loss base: remembers the scalar weight and batch axis (ref loss.py:49)."""

    def __init__(self, weight, batch_axis, **kwargs):
        super().__init__(**kwargs)
        self._weight, self._batch_axis = weight, batch_axis

    def __repr__(self):
        return "%s(batch_axis=%s, w=%s)" % (
            type(self).__name__, self._batch_axis, self._weight)

    def hybrid_forward(self, F, x, *args, **kwargs):
        raise NotImplementedError

    def _finish(self, F, loss, sample_weight):
        """Common tail: weighting then mean over every non-batch axis."""
        loss = _apply_weighting(F, loss, self._weight, sample_weight)
        return F.mean(loss, axis=self._batch_axis, exclude=True)


class _PointwiseLoss(Loss):
    """Template for losses of the form mean(residual(pred, label))."""

    def __init__(self, weight=None, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)

    def hybrid_forward(self, F, pred, label, sample_weight=None):
        label = _reshape_like(F, label, pred)
        return self._finish(F, self._residual(F, pred, label), sample_weight)

    def _residual(self, F, pred, label):
        raise NotImplementedError


class L2Loss(_PointwiseLoss):
    r"""``0.5 * w * (pred - label)^2`` (ref loss.py:82)."""

    def __init__(self, weight=1., batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)

    def _residual(self, F, pred, label):
        # fold the 1/2 into the residual so _finish applies weight as-is
        return F.square(pred - label) * 0.5


class L1Loss(_PointwiseLoss):
    r"""``w * |pred - label|`` (ref loss.py:120)."""

    def _residual(self, F, pred, label):
        return F.abs(pred - label)


class HuberLoss(_PointwiseLoss):
    r"""Smoothed L1: quadratic inside ``rho``, linear outside."""

    def __init__(self, rho=1, weight=None, batch_axis=0, **kwargs):
        super().__init__(weight=weight, batch_axis=batch_axis, **kwargs)
        self._rho = rho

    def _residual(self, F, pred, label):
        err = F.abs(pred - label)
        return F.where(err > self._rho,
                       err - 0.5 * self._rho,
                       (0.5 / self._rho) * F.square(err))


class HingeLoss(_PointwiseLoss):
    r"""``max(0, margin - pred * label)`` with labels in {-1, 1}."""

    def __init__(self, margin=1, weight=None, batch_axis=0, **kwargs):
        super().__init__(weight=weight, batch_axis=batch_axis, **kwargs)
        self._margin = margin

    def _residual(self, F, pred, label):
        return F.relu(self._margin - pred * label)


class SquaredHingeLoss(HingeLoss):
    r"""``max(0, margin - pred * label)^2``."""

    def _residual(self, F, pred, label):
        return F.square(super()._residual(F, pred, label))


class SigmoidBinaryCrossEntropyLoss(_PointwiseLoss):
    r"""BCE over logits (default) or probabilities (ref loss.py:157)."""

    def __init__(self, from_sigmoid=False, weight=None, batch_axis=0,
                 **kwargs):
        super().__init__(weight=weight, batch_axis=batch_axis, **kwargs)
        self._from_sigmoid = from_sigmoid

    def _residual(self, F, pred, label):
        if self._from_sigmoid:
            tiny = 1e-12
            return -(label * F.log(pred + tiny)
                     + (1. - label) * F.log(1. - pred + tiny))
        # numerically stable logits form:
        #   max(x, 0) - x*z + log1p(exp(-|x|))
        return (F.relu(pred) - pred * label
                + F.Activation(-F.abs(pred), act_type="softrelu"))


SigmoidBCELoss = SigmoidBinaryCrossEntropyLoss


class SoftmaxCrossEntropyLoss(Loss):
    r"""log-softmax + negative likelihood in one block (ref loss.py:224).

    ``sparse_label`` picks the target-class log-prob; otherwise the label is
    a dense distribution over classes.
    """

    def __init__(self, axis=-1, sparse_label=True, from_logits=False,
                 weight=None, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._axis, self._sparse_label = axis, sparse_label
        self._from_logits = from_logits

    def hybrid_forward(self, F, pred, label, sample_weight=None):
        logp = pred if self._from_logits \
            else F.log_softmax(pred, axis=self._axis)
        if self._sparse_label:
            nll = -F.pick(logp, label, axis=self._axis, keepdims=True)
        else:
            dist = _reshape_like(F, label, logp)
            nll = -F.sum(logp * dist, axis=self._axis, keepdims=True)
        return self._finish(F, nll, sample_weight)


SoftmaxCELoss = SoftmaxCrossEntropyLoss


class KLDivLoss(Loss):
    r"""``sum label * (log label - log pred)`` (ref loss.py:291)."""

    def __init__(self, from_logits=True, axis=-1, weight=None,
                 batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._from_logits, self._axis = from_logits, axis

    def hybrid_forward(self, F, pred, label, sample_weight=None):
        logp = pred if self._from_logits \
            else F.log_softmax(pred, axis=self._axis)
        div = label * (F.log(label + 1e-12) - logp)
        return self._finish(F, div, sample_weight)


class CTCLoss(Loss):
    r"""Connectionist Temporal Classification (ref loss.py:334).

    Lowers to the ``_contrib_CTCLoss`` op — a lax.scan alpha-recursion on
    TPU. Layouts: pred NTC/TNC, label NT/TN.
    """

    def __init__(self, layout="NTC", label_layout="NT", weight=None,
                 **kwargs):
        if layout not in ("NTC", "TNC"):
            raise ValueError("pred layout must be 'NTC' or 'TNC'")
        if label_layout not in ("NT", "TN"):
            raise ValueError("label layout must be 'NT' or 'TN'")
        self._layout, self._label_layout = layout, label_layout
        super().__init__(weight, label_layout.index("N"), **kwargs)

    def hybrid_forward(self, F, pred, label, pred_lengths=None,
                       label_lengths=None, sample_weight=None):
        if self._layout == "NTC":                 # op wants time-major
            pred = F.swapaxes(pred, 0, 1)
        if self._batch_axis == 1:                 # label likewise
            label = F.swapaxes(label, 0, 1)
        operands, flags = [pred, label], {}
        if pred_lengths is not None:
            operands.append(pred_lengths)
            flags["use_data_lengths"] = True
        if label_lengths is not None:
            operands.append(label_lengths)
            flags["use_label_lengths"] = True
        loss = F.contrib.CTCLoss(*operands, **flags)
        return _apply_weighting(F, loss, self._weight, sample_weight)
