"""Gluon losses.

Parity surface: reference ``python/mxnet/gluon/loss.py`` — L1Loss, L2Loss,
SigmoidBinaryCrossEntropyLoss, SoftmaxCrossEntropyLoss, KLDivLoss,
CTCLoss, plus the weighting helpers (_apply_weighting).
"""
from __future__ import annotations

from .block import HybridBlock

__all__ = ["Loss", "L1Loss", "L2Loss", "SigmoidBinaryCrossEntropyLoss",
           "SigmoidBCELoss", "SoftmaxCrossEntropyLoss", "SoftmaxCELoss",
           "KLDivLoss", "CTCLoss", "HuberLoss", "HingeLoss",
           "SquaredHingeLoss"]


def _apply_weighting(F, loss, weight=None, sample_weight=None):
    """Apply weighting to loss (reference loss.py:31)."""
    if sample_weight is not None:
        loss = F.broadcast_mul(loss, sample_weight)
    if weight is not None:
        assert isinstance(weight, (float, int)), "weight must be a number"
        loss = loss * weight
    return loss


def _reshape_like(F, x, y):
    return x.reshape(y.shape) if F is not None else x.reshape(y.shape)


class Loss(HybridBlock):
    """Base class for losses (reference loss.py:49)."""

    def __init__(self, weight, batch_axis, **kwargs):
        super(Loss, self).__init__(**kwargs)
        self._weight = weight
        self._batch_axis = batch_axis

    def __repr__(self):
        return "{name}(batch_axis={_batch_axis}, w={_weight})".format(
            name=self.__class__.__name__, **self.__dict__)

    def hybrid_forward(self, F, x, *args, **kwargs):
        raise NotImplementedError


class L2Loss(Loss):
    r"""``L = 0.5 * w * (pred - label)^2`` (reference loss.py:82)."""

    def __init__(self, weight=1., batch_axis=0, **kwargs):
        super(L2Loss, self).__init__(weight, batch_axis, **kwargs)

    def hybrid_forward(self, F, pred, label, sample_weight=None):
        label = _reshape_like(F, label, pred)
        loss = F.square(pred - label)
        loss = _apply_weighting(F, loss, self._weight / 2, sample_weight)
        return F.mean(loss, axis=self._batch_axis, exclude=True)


class L1Loss(Loss):
    r"""``L = w * |pred - label|`` (reference loss.py:120)."""

    def __init__(self, weight=None, batch_axis=0, **kwargs):
        super(L1Loss, self).__init__(weight, batch_axis, **kwargs)

    def hybrid_forward(self, F, pred, label, sample_weight=None):
        label = _reshape_like(F, label, pred)
        loss = F.abs(pred - label)
        loss = _apply_weighting(F, loss, self._weight, sample_weight)
        return F.mean(loss, axis=self._batch_axis, exclude=True)


class SigmoidBinaryCrossEntropyLoss(Loss):
    r"""BCE with optional logits input (reference loss.py:157)."""

    def __init__(self, from_sigmoid=False, weight=None, batch_axis=0,
                 **kwargs):
        super(SigmoidBinaryCrossEntropyLoss, self).__init__(
            weight, batch_axis, **kwargs)
        self._from_sigmoid = from_sigmoid

    def hybrid_forward(self, F, pred, label, sample_weight=None):
        label = _reshape_like(F, label, pred)
        if not self._from_sigmoid:
            # stable log-sum-exp form: max(x,0) - x*z + log(1+exp(-|x|))
            loss = F.relu(pred) - pred * label + \
                F.Activation(-F.abs(pred), act_type="softrelu")
        else:
            eps = 1e-12
            loss = -(F.log(pred + eps) * label +
                     F.log(1. - pred + eps) * (1. - label))
        loss = _apply_weighting(F, loss, self._weight, sample_weight)
        return F.mean(loss, axis=self._batch_axis, exclude=True)


SigmoidBCELoss = SigmoidBinaryCrossEntropyLoss


class SoftmaxCrossEntropyLoss(Loss):
    r"""Softmax + CE fused (reference loss.py:224)."""

    def __init__(self, axis=-1, sparse_label=True, from_logits=False,
                 weight=None, batch_axis=0, **kwargs):
        super(SoftmaxCrossEntropyLoss, self).__init__(
            weight, batch_axis, **kwargs)
        self._axis = axis
        self._sparse_label = sparse_label
        self._from_logits = from_logits

    def hybrid_forward(self, F, pred, label, sample_weight=None):
        if not self._from_logits:
            pred = F.log_softmax(pred, axis=self._axis)
        if self._sparse_label:
            loss = -F.pick(pred, label, axis=self._axis, keepdims=True)
        else:
            label = _reshape_like(F, label, pred)
            loss = -F.sum(pred * label, axis=self._axis, keepdims=True)
        loss = _apply_weighting(F, loss, self._weight, sample_weight)
        return F.mean(loss, axis=self._batch_axis, exclude=True)


SoftmaxCELoss = SoftmaxCrossEntropyLoss


class KLDivLoss(Loss):
    r"""Kullback-Leibler divergence (reference loss.py:291)."""

    def __init__(self, from_logits=True, axis=-1, weight=None,
                 batch_axis=0, **kwargs):
        super(KLDivLoss, self).__init__(weight, batch_axis, **kwargs)
        self._from_logits = from_logits
        self._axis = axis

    def hybrid_forward(self, F, pred, label, sample_weight=None):
        if not self._from_logits:
            pred = F.log_softmax(pred, axis=self._axis)
        loss = label * (F.log(label + 1e-12) - pred)
        loss = _apply_weighting(F, loss, self._weight, sample_weight)
        return F.mean(loss, axis=self._batch_axis, exclude=True)


class CTCLoss(Loss):
    r"""Connectionist Temporal Classification loss (reference loss.py:334;
    lowers to the _contrib_CTCLoss op — a lax.scan forward-alpha
    recursion on TPU)."""

    def __init__(self, layout="NTC", label_layout="NT", weight=None,
                 **kwargs):
        assert layout in ["NTC", "TNC"], \
            "Only 'NTC' and 'TNC' layouts for pred are supported."
        assert label_layout in ["NT", "TN"], \
            "Only 'NT' and 'TN' layouts for label are supported."
        self._layout = layout
        self._label_layout = label_layout
        batch_axis = label_layout.find("N")
        super(CTCLoss, self).__init__(weight, batch_axis, **kwargs)

    def hybrid_forward(self, F, pred, label, pred_lengths=None,
                       label_lengths=None, sample_weight=None):
        if self._layout == "NTC":
            pred = F.swapaxes(pred, 0, 1)
        if self._batch_axis == 1:
            label = F.swapaxes(label, 0, 1)
        args = [pred, label]
        kwargs = {}
        if pred_lengths is not None:
            args.append(pred_lengths)
            kwargs["use_data_lengths"] = True
        if label_lengths is not None:
            args.append(label_lengths)
            kwargs["use_label_lengths"] = True
        loss = F.contrib.CTCLoss(*args, **kwargs)
        return _apply_weighting(F, loss, self._weight, sample_weight)


class HuberLoss(Loss):
    r"""Smoothed L1 loss."""

    def __init__(self, rho=1, weight=None, batch_axis=0, **kwargs):
        super(HuberLoss, self).__init__(weight, batch_axis, **kwargs)
        self._rho = rho

    def hybrid_forward(self, F, pred, label, sample_weight=None):
        label = _reshape_like(F, label, pred)
        loss = F.abs(pred - label)
        loss = F.where(loss > self._rho,
                       loss - 0.5 * self._rho,
                       (0.5 / self._rho) * F.square(loss))
        loss = _apply_weighting(F, loss, self._weight, sample_weight)
        return F.mean(loss, axis=self._batch_axis, exclude=True)


class HingeLoss(Loss):
    r"""``L = max(0, margin - pred * label)``."""

    def __init__(self, margin=1, weight=None, batch_axis=0, **kwargs):
        super(HingeLoss, self).__init__(weight, batch_axis, **kwargs)
        self._margin = margin

    def hybrid_forward(self, F, pred, label, sample_weight=None):
        label = _reshape_like(F, label, pred)
        loss = F.relu(self._margin - pred * label)
        loss = _apply_weighting(F, loss, self._weight, sample_weight)
        return F.mean(loss, axis=self._batch_axis, exclude=True)


class SquaredHingeLoss(Loss):
    r"""``L = max(0, margin - pred * label)^2``."""

    def __init__(self, margin=1, weight=None, batch_axis=0, **kwargs):
        super(SquaredHingeLoss, self).__init__(weight, batch_axis, **kwargs)
        self._margin = margin

    def hybrid_forward(self, F, pred, label, sample_weight=None):
        label = _reshape_like(F, label, pred)
        loss = F.square(F.relu(self._margin - pred * label))
        loss = _apply_weighting(F, loss, self._weight, sample_weight)
        return F.mean(loss, axis=self._batch_axis, exclude=True)
