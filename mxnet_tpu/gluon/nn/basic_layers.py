"""Gluon basic NN layers.

Parity surface: reference ``python/mxnet/gluon/nn/basic_layers.py:29-462``
(Sequential, HybridSequential, Dense, Activation, Dropout, BatchNorm,
LeakyReLU, Embedding, Flatten).  All compute lowers to the shared op
registry (XLA-fused under hybridize).
"""
from __future__ import annotations

from ..block import Block, HybridBlock
from ... import initializer

__all__ = ["Sequential", "HybridSequential", "Dense", "Activation",
           "Dropout", "BatchNorm", "LeakyReLU", "Embedding", "Flatten",
           "InstanceNorm", "LayerNorm", "Lambda", "HybridLambda"]


class Sequential(Block):
    """Stacks Blocks sequentially (reference basic_layers.py:29)."""

    def __init__(self, prefix=None, params=None):
        super(Sequential, self).__init__(prefix=prefix, params=params)

    def add(self, *blocks):
        for block in blocks:
            self.register_child(block)

    def forward(self, x):
        for block in self._children:
            x = block(x)
        return x

    def __len__(self):
        return len(self._children)

    def __getitem__(self, i):
        return self._children[i]


class HybridSequential(HybridBlock):
    """Stacks HybridBlocks sequentially (reference basic_layers.py:53)."""

    def __init__(self, prefix=None, params=None):
        super(HybridSequential, self).__init__(prefix=prefix, params=params)

    def add(self, *blocks):
        for block in blocks:
            self.register_child(block)

    def hybrid_forward(self, F, x):
        for block in self._children:
            x = block(x)
        return x

    def __len__(self):
        return len(self._children)

    def __getitem__(self, i):
        return self._children[i]


class Dense(HybridBlock):
    """Fully-connected layer: ``out = act(dot(x, W.T) + b)``
    (reference basic_layers.py:77; lowers to the FullyConnected op →
    one MXU matmul)."""

    def __init__(self, units, activation=None, use_bias=True,
                 flatten=True, weight_initializer=None,
                 bias_initializer="zeros", in_units=0, **kwargs):
        super(Dense, self).__init__(**kwargs)
        self._flatten = flatten
        with self.name_scope():
            self._units = units
            self._in_units = in_units
            self.weight = self.params.get(
                "weight", shape=(units, in_units),
                init=weight_initializer, allow_deferred_init=True)
            if use_bias:
                self.bias = self.params.get(
                    "bias", shape=(units,), init=bias_initializer,
                    allow_deferred_init=True)
            else:
                self.bias = None
            if activation is not None:
                self.act = Activation(activation, prefix=activation + "_")
            else:
                self.act = None

    def hybrid_forward(self, F, x, weight, bias=None):
        if bias is None:
            act = F.FullyConnected(x, weight, no_bias=True,
                                   num_hidden=self._units,
                                   flatten=self._flatten)
        else:
            act = F.FullyConnected(x, weight, bias,
                                   num_hidden=self._units,
                                   flatten=self._flatten)
        if self.act is not None:
            act = self.act(act)
        return act

    def __repr__(self):
        s = "{name}({layout}, {act})"
        shape = self.weight.shape
        return s.format(name=self.__class__.__name__,
                        act=self.act if self.act else "linear",
                        layout="{0} -> {1}".format(
                            shape[1] if shape[1] else None, shape[0]))


class Activation(HybridBlock):
    """Applies an activation ('relu','sigmoid','tanh','softrelu')
    (reference basic_layers.py:160)."""

    def __init__(self, activation, **kwargs):
        self._act_type = activation
        super(Activation, self).__init__(**kwargs)

    def _alias(self):
        return self._act_type

    def hybrid_forward(self, F, x):
        return F.Activation(x, act_type=self._act_type)

    def __repr__(self):
        return "{name}({act})".format(
            name=self.__class__.__name__, act=self._act_type)


class Dropout(HybridBlock):
    """Dropout (reference basic_layers.py:187); active only under
    ``autograd.train_mode``, RNG threaded jit-safely."""

    def __init__(self, rate, **kwargs):
        super(Dropout, self).__init__(**kwargs)
        self._rate = rate

    def hybrid_forward(self, F, x):
        return F.Dropout(x, p=self._rate)

    def __repr__(self):
        return "{name}(p = {_rate})".format(
            name=self.__class__.__name__, **self.__dict__)


class BatchNorm(HybridBlock):
    """Batch normalization (reference basic_layers.py:224).  The moving
    stats are aux parameters updated functionally (explicit extra outputs
    of the BatchNorm op) — jit-safe on TPU."""

    def __init__(self, axis=1, momentum=0.9, epsilon=1e-5, center=True,
                 scale=True, use_global_stats=False,
                 beta_initializer="zeros", gamma_initializer="ones",
                 running_mean_initializer="zeros",
                 running_variance_initializer="ones", in_channels=0,
                 **kwargs):
        super(BatchNorm, self).__init__(**kwargs)
        self._kwargs = {"axis": axis, "eps": epsilon, "momentum": momentum,
                        "fix_gamma": not scale,
                        "use_global_stats": use_global_stats}
        self._axis = axis
        if in_channels != 0:
            self.in_channels = in_channels
        self.gamma = self.params.get(
            "gamma", grad_req="write" if scale else "null",
            shape=(in_channels,), init=gamma_initializer,
            allow_deferred_init=True)
        self.beta = self.params.get(
            "beta", grad_req="write" if center else "null",
            shape=(in_channels,), init=beta_initializer,
            allow_deferred_init=True)
        self.running_mean = self.params.get(
            "running_mean", grad_req="null", shape=(in_channels,),
            init=running_mean_initializer, allow_deferred_init=True,
            differentiable=False)
        self.running_var = self.params.get(
            "running_var", grad_req="null", shape=(in_channels,),
            init=running_variance_initializer, allow_deferred_init=True,
            differentiable=False)

    def hybrid_forward(self, F, x, gamma, beta, running_mean, running_var):
        return F.BatchNorm(x, gamma, beta, running_mean, running_var,
                           **self._kwargs)

    def __repr__(self):
        in_channels = self.gamma.shape[0]
        return "{name}({content}, in_channels={in_channels})".format(
            name=self.__class__.__name__, in_channels=in_channels,
            content=", ".join("=".join([k, str(v)])
                              for k, v in self._kwargs.items()))


class LeakyReLU(HybridBlock):
    """Leaky ReLU (reference basic_layers.py:288)."""

    def __init__(self, alpha, **kwargs):
        super(LeakyReLU, self).__init__(**kwargs)
        self._alpha = alpha

    def hybrid_forward(self, F, x):
        return F.LeakyReLU(x, act_type="leaky", slope=self._alpha)

    def __repr__(self):
        return "{name}({alpha})".format(
            name=self.__class__.__name__, alpha=self._alpha)


class Embedding(HybridBlock):
    """Index → dense vector lookup (reference basic_layers.py:315)."""

    def __init__(self, input_dim, output_dim, dtype="float32",
                 weight_initializer=None, **kwargs):
        super(Embedding, self).__init__(**kwargs)
        self._kwargs = {"input_dim": input_dim, "output_dim": output_dim,
                        "dtype": dtype}
        self.weight = self.params.get(
            "weight", shape=(input_dim, output_dim),
            init=weight_initializer, allow_deferred_init=True)

    def hybrid_forward(self, F, x, weight):
        return F.Embedding(x, weight, **self._kwargs)

    def __repr__(self):
        return "{name}({input_dim} -> {output_dim}, {dtype})".format(
            name=self.__class__.__name__, **self._kwargs)


class Flatten(HybridBlock):
    """Flattens to 2D (reference basic_layers.py:355)."""

    def hybrid_forward(self, F, x):
        return F.Flatten(x)

    def __repr__(self):
        return self.__class__.__name__


class InstanceNorm(HybridBlock):
    """Instance normalization (reference basic_layers.py has it in later
    revs; op parity with InstanceNorm operator)."""

    def __init__(self, epsilon=1e-5, center=True, scale=False,
                 beta_initializer="zeros", gamma_initializer="ones",
                 in_channels=0, **kwargs):
        super(InstanceNorm, self).__init__(**kwargs)
        self._kwargs = {"eps": epsilon}
        self.gamma = self.params.get(
            "gamma", grad_req="write" if scale else "null",
            shape=(in_channels,), init=gamma_initializer,
            allow_deferred_init=True)
        self.beta = self.params.get(
            "beta", grad_req="write" if center else "null",
            shape=(in_channels,), init=beta_initializer,
            allow_deferred_init=True)

    def hybrid_forward(self, F, x, gamma, beta):
        return F.InstanceNorm(x, gamma, beta, **self._kwargs)


class LayerNorm(HybridBlock):
    """Layer normalization over the last axis."""

    def __init__(self, axis=-1, epsilon=1e-5, center=True, scale=True,
                 beta_initializer="zeros", gamma_initializer="ones",
                 in_channels=0, **kwargs):
        super(LayerNorm, self).__init__(**kwargs)
        self._kwargs = {"axis": axis, "eps": epsilon}
        self.gamma = self.params.get(
            "gamma", grad_req="write" if scale else "null",
            shape=(in_channels,), init=gamma_initializer,
            allow_deferred_init=True)
        self.beta = self.params.get(
            "beta", grad_req="write" if center else "null",
            shape=(in_channels,), init=beta_initializer,
            allow_deferred_init=True)

    def hybrid_forward(self, F, x, gamma, beta):
        return F.LayerNorm(x, gamma, beta, **self._kwargs)


class Lambda(Block):
    """Wraps a function as a Block."""

    def __init__(self, function, prefix=None):
        super(Lambda, self).__init__(prefix=prefix)
        if isinstance(function, str):
            from ... import ndarray as nd
            assert hasattr(nd, function), \
                "Function name %s is not found in ndarray." % function
            self._func_impl = getattr(nd, function)
        else:
            self._func_impl = function

    def forward(self, *args):
        return self._func_impl(*args)


class HybridLambda(HybridBlock):
    """Wraps a function as a HybridBlock."""

    def __init__(self, function, prefix=None):
        super(HybridLambda, self).__init__(prefix=prefix)
        if isinstance(function, str):
            from ... import ndarray as nd
            from ... import symbol as sym
            assert hasattr(nd, function) and hasattr(sym, function), \
                "Function name %s is not found in ndarray/symbol." % function
            self._func_name = function
            self._func_impl = None
        else:
            self._func_impl = function
            self._func_name = None

    def hybrid_forward(self, F, x, *args):
        if self._func_name is not None:
            return getattr(F, self._func_name)(x, *args)
        return self._func_impl(F, x, *args)
