"""Gluon convolution / pooling layers.

Parity surface: reference ``python/mxnet/gluon/nn/conv_layers.py:40-780``
(Conv1D/2D/3D, Conv1D/2D/3DTranspose, Max/Avg pooling 1-3D, global
variants).  All lower to the Convolution/Deconvolution/Pooling ops —
XLA conv_general_dilated on the MXU.
"""
from __future__ import annotations

from ..block import HybridBlock

__all__ = ["Conv1D", "Conv2D", "Conv3D",
           "Conv1DTranspose", "Conv2DTranspose", "Conv3DTranspose",
           "MaxPool1D", "MaxPool2D", "MaxPool3D",
           "AvgPool1D", "AvgPool2D", "AvgPool3D",
           "GlobalMaxPool1D", "GlobalMaxPool2D", "GlobalMaxPool3D",
           "GlobalAvgPool1D", "GlobalAvgPool2D", "GlobalAvgPool3D"]


def _to_tuple(x, n):
    if isinstance(x, int):
        return (x,) * n
    assert len(x) == n
    return tuple(x)


class _Conv(HybridBlock):
    """Base conv layer (reference conv_layers.py:40)."""

    def __init__(self, channels, kernel_size, strides, padding, dilation,
                 groups, layout, in_channels=0, activation=None,
                 use_bias=True, weight_initializer=None,
                 bias_initializer="zeros", op_name="Convolution",
                 adj=None, **kwargs):
        super(_Conv, self).__init__(**kwargs)
        with self.name_scope():
            self._channels = channels
            self._in_channels = in_channels
            dim = len(kernel_size)
            self._op_name = op_name
            self._kwargs = {
                "kernel": kernel_size, "stride": strides,
                "dilate": dilation, "pad": padding,
                "num_filter": channels, "num_group": groups,
                "no_bias": not use_bias, "layout": layout}
            if adj is not None:
                self._kwargs["adj"] = adj

            # canonical NCHW-family weight shape: conv (O, I/g, *k);
            # deconv (I, O/g, *k) — _ConvTranspose patches after super()
            if op_name == "Convolution":
                wshape = [channels,
                          in_channels // groups if in_channels else 0] + \
                    list(kernel_size)
            else:
                wshape = [in_channels,
                          channels // groups] + list(kernel_size)
            self.weight = self.params.get(
                "weight", shape=tuple(wshape), init=weight_initializer,
                allow_deferred_init=True)
            if use_bias:
                self.bias = self.params.get(
                    "bias", shape=(channels,), init=bias_initializer,
                    allow_deferred_init=True)
            else:
                self.bias = None
            if activation is not None:
                from .basic_layers import Activation
                self.act = Activation(activation, prefix=activation + "_")
            else:
                self.act = None

    def hybrid_forward(self, F, x, weight, bias=None):
        op = getattr(F, self._op_name)
        if bias is None:
            act = op(x, weight, **self._kwargs)
        else:
            act = op(x, weight, bias, **self._kwargs)
        if self.act is not None:
            act = self.act(act)
        return act

    def __repr__(self):
        s = "{name}({mapping}, kernel_size={kernel}, stride={stride})"
        shape = self.weight.shape
        return s.format(name=self.__class__.__name__,
                        mapping="{0} -> {1}".format(
                            shape[1] if shape[1] else None, shape[0]),
                        **self._kwargs)


class Conv1D(_Conv):
    def __init__(self, channels, kernel_size, strides=1, padding=0,
                 dilation=1, groups=1, layout="NCW", activation=None,
                 use_bias=True, weight_initializer=None,
                 bias_initializer="zeros", in_channels=0, **kwargs):
        kernel_size = _to_tuple(kernel_size, 1)
        strides = _to_tuple(strides, 1)
        padding = _to_tuple(padding, 1)
        dilation = _to_tuple(dilation, 1)
        super(Conv1D, self).__init__(
            channels, kernel_size, strides, padding, dilation, groups,
            layout, in_channels, activation, use_bias, weight_initializer,
            bias_initializer, **kwargs)


class Conv2D(_Conv):
    def __init__(self, channels, kernel_size, strides=(1, 1),
                 padding=(0, 0), dilation=(1, 1), groups=1, layout="NCHW",
                 activation=None, use_bias=True, weight_initializer=None,
                 bias_initializer="zeros", in_channels=0, **kwargs):
        kernel_size = _to_tuple(kernel_size, 2)
        strides = _to_tuple(strides, 2)
        padding = _to_tuple(padding, 2)
        dilation = _to_tuple(dilation, 2)
        super(Conv2D, self).__init__(
            channels, kernel_size, strides, padding, dilation, groups,
            layout, in_channels, activation, use_bias, weight_initializer,
            bias_initializer, **kwargs)


class Conv3D(_Conv):
    def __init__(self, channels, kernel_size, strides=(1, 1, 1),
                 padding=(0, 0, 0), dilation=(1, 1, 1), groups=1,
                 layout="NCDHW", activation=None, use_bias=True,
                 weight_initializer=None, bias_initializer="zeros",
                 in_channels=0, **kwargs):
        kernel_size = _to_tuple(kernel_size, 3)
        strides = _to_tuple(strides, 3)
        padding = _to_tuple(padding, 3)
        dilation = _to_tuple(dilation, 3)
        super(Conv3D, self).__init__(
            channels, kernel_size, strides, padding, dilation, groups,
            layout, in_channels, activation, use_bias, weight_initializer,
            bias_initializer, **kwargs)


class _ConvTranspose(_Conv):
    def __init__(self, channels, kernel_size, strides, padding,
                 output_padding, dilation, groups, layout, in_channels,
                 activation, use_bias, weight_initializer,
                 bias_initializer, **kwargs):
        super(_ConvTranspose, self).__init__(
            channels, kernel_size, strides, padding, dilation, groups,
            layout, in_channels, activation, use_bias, weight_initializer,
            bias_initializer, op_name="Deconvolution",
            adj=output_padding, **kwargs)
        # Deconvolution weight is (in_channels, channels/groups, *k)
        dim = len(kernel_size)
        wshape = [in_channels, channels // groups] + list(kernel_size)
        if in_channels == 0:
            wshape[0] = 0
        self.weight.shape = tuple(wshape)


class Conv1DTranspose(_ConvTranspose):
    def __init__(self, channels, kernel_size, strides=1, padding=0,
                 output_padding=0, dilation=1, groups=1, layout="NCW",
                 activation=None, use_bias=True, weight_initializer=None,
                 bias_initializer="zeros", in_channels=0, **kwargs):
        super(Conv1DTranspose, self).__init__(
            channels, _to_tuple(kernel_size, 1), _to_tuple(strides, 1),
            _to_tuple(padding, 1), _to_tuple(output_padding, 1),
            _to_tuple(dilation, 1), groups, layout, in_channels,
            activation, use_bias, weight_initializer, bias_initializer,
            **kwargs)


class Conv2DTranspose(_ConvTranspose):
    def __init__(self, channels, kernel_size, strides=(1, 1),
                 padding=(0, 0), output_padding=(0, 0), dilation=(1, 1),
                 groups=1, layout="NCHW", activation=None, use_bias=True,
                 weight_initializer=None, bias_initializer="zeros",
                 in_channels=0, **kwargs):
        super(Conv2DTranspose, self).__init__(
            channels, _to_tuple(kernel_size, 2), _to_tuple(strides, 2),
            _to_tuple(padding, 2), _to_tuple(output_padding, 2),
            _to_tuple(dilation, 2), groups, layout, in_channels,
            activation, use_bias, weight_initializer, bias_initializer,
            **kwargs)


class Conv3DTranspose(_ConvTranspose):
    def __init__(self, channels, kernel_size, strides=(1, 1, 1),
                 padding=(0, 0, 0), output_padding=(0, 0, 0),
                 dilation=(1, 1, 1), groups=1, layout="NCDHW",
                 activation=None, use_bias=True, weight_initializer=None,
                 bias_initializer="zeros", in_channels=0, **kwargs):
        super(Conv3DTranspose, self).__init__(
            channels, _to_tuple(kernel_size, 3), _to_tuple(strides, 3),
            _to_tuple(padding, 3), _to_tuple(output_padding, 3),
            _to_tuple(dilation, 3), groups, layout, in_channels,
            activation, use_bias, weight_initializer, bias_initializer,
            **kwargs)


class _Pooling(HybridBlock):
    """Base pooling (reference conv_layers.py:600)."""

    def __init__(self, pool_size, strides, padding, ceil_mode=False,
                 global_pool=False, pool_type="max", **kwargs):
        super(_Pooling, self).__init__(**kwargs)
        if strides is None:
            strides = pool_size
        self._kwargs = {
            "kernel": pool_size, "stride": strides, "pad": padding,
            "global_pool": global_pool, "pool_type": pool_type,
            "pooling_convention": "full" if ceil_mode else "valid"}

    def _alias(self):
        return "pool"

    def hybrid_forward(self, F, x):
        return F.Pooling(x, **self._kwargs)

    def __repr__(self):
        return "{name}(size={kernel}, stride={stride}, padding={pad}" \
            ")".format(name=self.__class__.__name__, **self._kwargs)


class MaxPool1D(_Pooling):
    def __init__(self, pool_size=2, strides=None, padding=0, layout="NCW",
                 ceil_mode=False, **kwargs):
        assert layout == "NCW"
        super(MaxPool1D, self).__init__(
            _to_tuple(pool_size, 1),
            _to_tuple(strides, 1) if strides is not None else None,
            _to_tuple(padding, 1), ceil_mode, False, "max", **kwargs)


class MaxPool2D(_Pooling):
    def __init__(self, pool_size=(2, 2), strides=None, padding=0,
                 layout="NCHW", ceil_mode=False, **kwargs):
        assert layout == "NCHW"
        super(MaxPool2D, self).__init__(
            _to_tuple(pool_size, 2),
            _to_tuple(strides, 2) if strides is not None else None,
            _to_tuple(padding, 2), ceil_mode, False, "max", **kwargs)


class MaxPool3D(_Pooling):
    def __init__(self, pool_size=(2, 2, 2), strides=None, padding=0,
                 layout="NCDHW", ceil_mode=False, **kwargs):
        assert layout == "NCDHW"
        super(MaxPool3D, self).__init__(
            _to_tuple(pool_size, 3),
            _to_tuple(strides, 3) if strides is not None else None,
            _to_tuple(padding, 3), ceil_mode, False, "max", **kwargs)


class AvgPool1D(_Pooling):
    def __init__(self, pool_size=2, strides=None, padding=0, layout="NCW",
                 ceil_mode=False, **kwargs):
        assert layout == "NCW"
        super(AvgPool1D, self).__init__(
            _to_tuple(pool_size, 1),
            _to_tuple(strides, 1) if strides is not None else None,
            _to_tuple(padding, 1), ceil_mode, False, "avg", **kwargs)


class AvgPool2D(_Pooling):
    def __init__(self, pool_size=(2, 2), strides=None, padding=0,
                 layout="NCHW", ceil_mode=False, **kwargs):
        assert layout == "NCHW"
        super(AvgPool2D, self).__init__(
            _to_tuple(pool_size, 2),
            _to_tuple(strides, 2) if strides is not None else None,
            _to_tuple(padding, 2), ceil_mode, False, "avg", **kwargs)


class AvgPool3D(_Pooling):
    def __init__(self, pool_size=(2, 2, 2), strides=None, padding=0,
                 layout="NCDHW", ceil_mode=False, **kwargs):
        assert layout == "NCDHW"
        super(AvgPool3D, self).__init__(
            _to_tuple(pool_size, 3),
            _to_tuple(strides, 3) if strides is not None else None,
            _to_tuple(padding, 3), ceil_mode, False, "avg", **kwargs)


class GlobalMaxPool1D(_Pooling):
    def __init__(self, layout="NCW", **kwargs):
        super(GlobalMaxPool1D, self).__init__(
            (1,), None, (0,), True, True, "max", **kwargs)


class GlobalMaxPool2D(_Pooling):
    def __init__(self, layout="NCHW", **kwargs):
        super(GlobalMaxPool2D, self).__init__(
            (1, 1), None, (0, 0), True, True, "max", **kwargs)


class GlobalMaxPool3D(_Pooling):
    def __init__(self, layout="NCDHW", **kwargs):
        super(GlobalMaxPool3D, self).__init__(
            (1, 1, 1), None, (0, 0, 0), True, True, "max", **kwargs)


class GlobalAvgPool1D(_Pooling):
    def __init__(self, layout="NCW", **kwargs):
        super(GlobalAvgPool1D, self).__init__(
            (1,), None, (0,), True, True, "avg", **kwargs)


class GlobalAvgPool2D(_Pooling):
    def __init__(self, layout="NCHW", **kwargs):
        super(GlobalAvgPool2D, self).__init__(
            (1, 1), None, (0, 0), True, True, "avg", **kwargs)


class GlobalAvgPool3D(_Pooling):
    def __init__(self, layout="NCDHW", **kwargs):
        super(GlobalAvgPool3D, self).__init__(
            (1, 1, 1), None, (0, 0, 0), True, True, "avg", **kwargs)
