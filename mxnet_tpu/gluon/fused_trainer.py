"""Fused Gluon Trainer step: the whole weight update as ONE XLA program.

The per-slot ``Trainer.step`` loop issues one kvstore push/pull (a
separate reduce per slot) plus one eager ``Updater`` dispatch per slot —
O(n_params) XLA program calls per step (~160 for ResNet-50).  The fused
path collapses that to

    O(n_buckets) bucketed gradient all-reduce programs   (kvstore.py)
  + 1 jitted, donated whole-model optimizer program

        (param_list, grad_list, opt_state_list, hyper)
            -> (new_params, new_opt_states)

mirroring ``module/cached_step.py``'s donated train step and the
reference's fused ``optimizer_op.cc`` kernels ("Automatic Cross-Replica
Sharding of Weight Update in Data-Parallel Training", PAPERS.md).

Hyper-parameters — per-slot lr/wd (scheduler and multipliers resolved
host-side each step), the update counts ``t``, and ``rescale_grad`` —
enter as *traced* scalars: changing the lr schedule or the batch size
never retraces.  Compiled steps are cached in ``_STEP_CACHE`` keyed on
(optimizer class, its static scalar hypers, param shapes/dtypes, opt
state tree structure), so two Trainers over identical models share one
program.

Parameter and state buffers are donated on device backends: XLA updates
weights in place in HBM; the Trainer rebinds the original NDArray
handles (``Parameter._rebind_data``) so every holder observes the new
buffers.  Gradients are NOT donated — ``grad_req='add'`` accumulation
reads them on the next backward.

Opt out with ``MXNET_FUSED_TRAINER=0`` (the per-slot loop stays the
bitwise-equality oracle in tests/test_fused_trainer.py).

ZeRO-1 sharded mode (``MXNET_ZERO=1``, docs/ZERO.md): the SAME one
donated program additionally carries cross-replica weight-update
sharding (arXiv 2004.13336 via ``parallel/zero.py``): optimizer state
persists sharded 1/N per local device, gradients are reduce-scattered
in (``kvstore.reduce_scatter_all`` or a direct sharded placement),
each replica updates only its rows, and the updated weights all-gather
back out — bitwise-identical to the replicated path, still exactly one
XLA program per step, guardian verdict folded in unchanged.
"""
from __future__ import annotations

import os
import weakref

import jax
import jax.numpy as jnp
import numpy as np

from .. import chaos as _chaos
from .. import model_stats as _mstats
from .. import profiler as _prof
from .. import random as _random
from .. import telemetry as _tel
from . import overlap as _overlap
from ..guardian import core as _guard
from ..guardian import health as _health
from ..ndarray import NDArray
from ..optimizer import _state_raw, _state_writeback, static_hypers

__all__ = ["fused_trainer_enabled", "fused_step_fn", "run_fused_step",
           "zero_enabled", "zero_num_shards"]


def _env_enabled():
    return os.environ.get("MXNET_FUSED_TRAINER", "1").strip().lower() \
        not in ("0", "false", "off", "no")


def _env_zero():
    return os.environ.get("MXNET_ZERO", "0").strip().lower() \
        in ("1", "true", "on", "yes")


def _env_zero_shards():
    try:
        return max(0, int(os.environ.get("MXNET_ZERO_SHARDS", "0")))
    except ValueError:
        return 0


# cached at import (the JG006 cached-value pattern): Trainer.step consults
# these once per step and must not re-parse the environment each time
_ENABLED = _env_enabled()
_ZERO = _env_zero()
_ZERO_SHARDS = _env_zero_shards()


def refresh_from_env():
    """Re-read MXNET_FUSED_TRAINER / MXNET_ZERO / MXNET_ZERO_SHARDS
    (tests / late configuration)."""
    global _ENABLED, _ZERO, _ZERO_SHARDS
    _ENABLED = _env_enabled()
    _ZERO = _env_zero()
    _ZERO_SHARDS = _env_zero_shards()


def fused_trainer_enabled():
    return _ENABLED


def zero_enabled():
    """Whether MXNET_ZERO asked for the sharded weight update."""
    return _ZERO


def zero_num_shards():
    """Replica count for the sharded update: MXNET_ZERO_SHARDS, clamped
    to the local device count; 0/unset means every local device."""
    n_local = jax.local_device_count()
    return min(_ZERO_SHARDS, n_local) if _ZERO_SHARDS else n_local


_STEP_CACHE = {}      # signature -> (weakref to optimizer, jitted step)
_TRACECHECK_KEEPALIVE = []    # graftcheck specimen optimizers (see below)


class _ZeroPlan:
    """The ZeRO-1 layout for one Trainer: a 1-D ``zero`` mesh of N local
    devices plus per-shape update shardings (``parallel/zero.py``).

    The plan owns the persistent placement of the optimizer state — each
    weight-shaped state leaf whose leading dim divides N lives sharded
    P('zero') across the mesh; scalar/odd leaves stay replicated — and
    the per-step placement of params/grads entering the one program.
    Slot→checkpoint-shard assignment is untouched: the round-robin
    ``checkpoint/reshard.py`` layout the CheckpointManager already
    writes, so snapshotting sharded state never gathers on device.
    """

    axis = "zero"

    def __init__(self, n_shards):
        from ..parallel import zero as z
        self._z = z
        self.mesh = z.zero1_axis_mesh(n_shards, self.axis)
        self.n = int(self.mesh.shape[self.axis])
        from ..parallel import mesh as mesh_mod
        self.replicated = mesh_mod.replicated(self.mesh)
        self._upd_cache = {}           # weight shape -> sharding or None
        self._bytes = None             # (per_device, replicated) cache

    def update_sharding(self, shape):
        shape = tuple(shape)
        if shape not in self._upd_cache:
            self._upd_cache[shape] = self._z.update_sharding(
                self.mesh, shape, self.axis)
        return self._upd_cache[shape]

    def grad_shardings(self, shapes):
        """Per-slot placement for incoming gradients: the update
        sharding (the reduce-scatter target) or replicated."""
        return [self.update_sharding(s) or self.replicated for s in shapes]

    def place_replicated(self, arrs):
        """Broadcast host/device arrays onto the mesh (the weights'
        entry leg; pure data movement, no XLA program)."""
        if not arrs:
            return list(arrs)
        return list(jax.device_put(list(arrs), self.replicated))

    def scatter_grads(self, raw_grads, shapes):
        """Direct reduce-scatter placement for the no-kvstore path: each
        device receives only its rows of each divisible gradient."""
        if not raw_grads:
            return list(raw_grads)
        return list(jax.device_put(list(raw_grads),
                                   self.grad_shardings(shapes)))

    @staticmethod
    def _state_nds(state):
        """Flatten one slot's NDArray state tree to its NDArray leaves."""
        if state is None:
            return []
        if isinstance(state, NDArray):
            return [state]
        out = []
        for s in state:
            out.extend(_ZeroPlan._state_nds(s))
        return out

    def place_states(self, slots, updater):
        """Ensure every state leaf sits at its planned sharding; leaves
        arriving from a checkpoint restore / load_states (plain host or
        single-device arrays) are re-placed — through the chunked
        redistribution path (``parallel.collective.redistribute``,
        arXiv 2112.01075) so an elastic restore onto a changed shard
        count streams instead of staging full per-device copies."""
        from ..parallel import collective as _coll
        moved = False
        for slot, p in slots:
            wshape = tuple(p.data().shape)
            upd = self.update_sharding(wshape)
            for leaf in self._state_nds(updater.states.get(slot)):
                want = self._z.shard_state_tree_spec(
                    leaf.shape, wshape, upd, self.replicated)
                if getattr(leaf._data, "sharding", None) != want:
                    leaf._set_data(_coll.redistribute(leaf._data, want))
                    moved = True
        if moved:
            self._bytes = None
        return moved

    def unplace_states(self, slots, updater):
        """Pull sharded state back to each weight's own device (the exit
        path when MXNET_ZERO is flipped off mid-run) — the chunked
        all-gather: each leaf streams home shard by shard instead of
        materializing beside a full gathered staging copy."""
        from jax.sharding import SingleDeviceSharding
        from ..parallel import collective as _coll
        for slot, p in slots:
            dev = p.data().context.jax_device
            home = SingleDeviceSharding(dev)
            for leaf in self._state_nds(updater.states.get(slot)):
                if getattr(leaf._data, "sharding", None) != home:
                    leaf._set_data(_coll.gather_home(leaf._data, dev))
        self._bytes = None

    def local_view(self, arr, jax_device):
        """The single-device view of a replicated program output on
        *jax_device* — no copy when the shard buffer already lives
        there; a weight whose home device is outside the zero mesh gets
        a chunked transfer back (``collective.gather_home``) so it
        never silently migrates and never stages a second full copy."""
        from ..parallel import collective as _coll
        for s in arr.addressable_shards:
            if s.device == jax_device:
                return s.data
        return _coll.gather_home(arr, jax_device)

    def state_byte_gauges(self, slots, updater):
        """(per_device, replicated) optimizer-state bytes under this
        layout — the ``zero_optimizer_bytes_*`` gauges' arithmetic."""
        if self._bytes is None:
            leaves = []
            for slot, p in slots:
                wshape = tuple(p.data().shape)
                upd = self.update_sharding(wshape)
                for leaf in self._state_nds(updater.states.get(slot)):
                    sharded = upd is not None \
                        and tuple(leaf.shape) == wshape
                    leaves.append((leaf.shape, leaf.dtype, sharded))
            self._bytes = self._z.state_bytes(leaves, self.n)
        return self._bytes


def _deactivate_zero(trainer, slots):
    """De-shard a trainer that previously ran the ZeRO path: pull the
    state home, drop the plan, and zero the gauges (their declared
    contract is '0/absent when replicated')."""
    plan = getattr(trainer, "_zero_plan", None)
    if plan is None:
        return
    plan.unplace_states(slots, trainer._updater)
    trainer._zero_plan = None
    _tel.set_gauge("zero_shards", 0)
    _tel.set_gauge("zero_optimizer_bytes_per_device", 0)
    _tel.set_gauge("zero_optimizer_bytes_replicated", 0)


def ensure_unsharded(trainer, slots):
    """Entry hook for the NON-fused paths (the ``MXNET_FUSED_TRAINER=0``
    oracle loop, non-fusable optimizers): a trainer whose state was left
    mesh-sharded by an earlier ZeRO step must be de-sharded before any
    eager per-slot update touches it — the eager dispatch would
    otherwise mix single-device grads with mesh-committed state."""
    _deactivate_zero(trainer, slots)


def _zero_plan(trainer, slots):
    """The trainer's active ZeRO plan, or None.  Builds/rebuilds on an
    env change (shard count or enable flip) and migrates the optimizer
    state's placement accordingly."""
    if not zero_enabled():
        _deactivate_zero(trainer, slots)   # flipped off mid-run
        return None
    plan = getattr(trainer, "_zero_plan", None)
    n = zero_num_shards()
    if plan is None or plan.n != n:
        plan = trainer._zero_plan = _ZeroPlan(n)
    return plan


def _signature(opt, params_raw, states_raw, donate, guarded, zero=None,
               stats=False):
    leaves, treedef = jax.tree_util.tree_flatten(states_raw)
    return (type(opt), static_hypers(opt),
            tuple((tuple(w.shape), str(w.dtype)) for w in params_raw),
            # placement is part of jax's own jit cache key: fold it in so
            # a same-shape model on a different device/sharding gets its
            # own entry instead of a retrace of someone else's closure
            tuple(str(getattr(w, "sharding", None)) for w in params_raw),
            str(treedef),
            tuple((tuple(l.shape), str(l.dtype)) for l in leaves),
            bool(donate), bool(guarded),
            None if zero is None else ("zero", zero.n),
            bool(stats))


def fused_step_fn(opt, params_raw, states_raw, donate, guarded=False,
                  zero=None, stats=False):
    """The jitted whole-model step for this (optimizer, model) signature,
    compiled once per signature process-wide.

    The compiled step closes over *an* optimizer instance, but only via a
    weakref: the signature pins every attribute the trace reads, so any
    same-signature instance produces the same program — and a cached
    entry whose original optimizer died is rebuilt around the caller's
    live one instead of pinning the dead model's parameters forever.

    With ``guarded=True`` (a :class:`~mxnet_tpu.guardian.TrainingGuardian`
    is installed) the SAME program additionally computes an
    all-grads-finite scalar — plus the finiteness of ``hyper['loss']``
    when the loop recorded one — and suppresses the whole update via
    ``jnp.where`` on a nonfinite verdict: old params/states pass through
    the donated buffers, the verdict rides out as a third output.  One
    extra reduction in an existing program; never a second XLA launch,
    never a host callback (graftcheck-proven on the
    ``fused_trainer_step_guarded`` specimen).

    With ``zero`` (a :class:`_ZeroPlan`) the SAME program carries the
    ZeRO-1 placement: per-slot sharding constraints make the XLA
    partitioner reduce-scatter each divisible gradient, run the
    identical update math on 1/N of the rows per replica against the
    persistently sharded state, and all-gather the updated weights back
    to replicated outputs.  Guarding composes unchanged — the verdict
    reduces over the sharded gradients (same truth value) and the
    ``jnp.where`` pass-through keeps each replica's state rows.

    With ``stats=True`` (``MXNET_MODEL_STATS``) the SAME program emits
    the model-health side-output (``model_stats.stats_block``): one
    stacked f32 block of per-slot grad-norm²/weight-norm²/update-ratio/
    grad-absmax (+ a loss row when the loop recorded one) as a final
    output.  Its inputs pass through an ``optimization_barrier`` so the
    stat reductions compile as their own fusion islands — the update
    clusters keep the exact codegen of the stats-off program, and
    training stays bitwise-identical (the ZeRO bitwise trick, reused).
    Still one XLA launch, still no host callback (graftcheck-proven on
    the ``*_stats`` specimens).
    """
    sig = _signature(opt, params_raw, states_raw, donate, guarded, zero,
                     stats)
    # prune entries whose owning optimizer died (their compiled programs
    # would otherwise pin memory forever)
    for dead in [k for k, (r, _) in _STEP_CACHE.items() if r() is None]:
        del _STEP_CACHE[dead]
    entry = _STEP_CACHE.get(sig)
    if entry is not None:
        owner = entry[0]()
        # the closure's owner must still match the signature it was
        # compiled under — a mid-training hyper mutation on the owner
        # would otherwise leak into a retrace of this entry
        if owner is not None and static_hypers(owner) == sig[1]:
            return entry[1]

    opt_ref = weakref.ref(opt)
    if zero is not None:
        zero_upd = [zero.update_sharding(tuple(w.shape))
                    for w in params_raw]
        zero_rep = zero.replicated
        wshapes = [tuple(w.shape) for w in params_raw]

    def step(params, grads, states, hyper):
        o = opt_ref()
        if o is None:       # only reachable on a retrace after death
            raise RuntimeError("fused step optimizer was collected")
        wsc = jax.lax.with_sharding_constraint
        states_in = states
        if zero is not None:
            # reduce-scatter point: each replica keeps only its rows of
            # each divisible gradient/weight before the update runs
            grads = [g if s is None else wsc(g, s)
                     for g, s in zip(grads, zero_upd)]
            p_in = [p if s is None else wsc(p, s)
                    for p, s in zip(params, zero_upd)]
            # isolate each slot's update into its own fusion island:
            # XLA's cross-slot loop fusion emits different vector code
            # for shard-shaped buffers than for the full arrays, which
            # costs 1-ulp drift vs the replicated program.  A per-slot
            # barrier (identity — no arithmetic) makes each update
            # compile exactly like its standalone per-slot program, the
            # same bits the MXNET_FUSED_TRAINER=0 oracle produces.
            iso_p, iso_g, iso_s = [], [], []
            for p_i, g_i, s_i in zip(p_in, grads, states):
                p_i, g_i, s_i = jax.lax.optimization_barrier(
                    (p_i, g_i, s_i))
                iso_p.append(p_i)
                iso_g.append(g_i)
                iso_s.append(s_i)
            p_in, grads, states_in = iso_p, iso_g, iso_s
        else:
            p_in = params
        finite = None
        if guarded:
            finite = _health.all_finite(grads)
            if "loss" in hyper:        # dict structure: static per trace
                finite = jnp.logical_and(
                    finite, jnp.all(jnp.isfinite(hyper["loss"])))
        new_params, new_states = o.fused_update_step(p_in, grads,
                                                     states_in, hyper)
        if zero is not None:
            # seal the islands: downstream select/constraint ops are
            # arithmetic-free, but without this barrier they could fuse
            # back INTO the update clusters and re-open codegen drift
            new_params = list(jax.lax.optimization_barrier(
                tuple(new_params)))
        if guarded:
            # nonfinite ⇒ the donated buffers keep their old values: the
            # poisoned batch costs one skipped step, not a retrace and
            # not a host round-trip
            new_params = [jnp.where(finite, n, p)
                          for n, p in zip(new_params, params)]
            new_states = jax.tree_util.tree_map(
                lambda n, p: jnp.where(finite, n, p), new_states, states)
        if zero is not None:
            # all-gather leg, pinned LAST so the partitioner cannot
            # re-shard the final outputs past it: updated weights come
            # back replicated; state rows stay on their replica
            new_params = [wsc(nw, zero_rep) for nw in new_params]
            new_states = [
                ns if s is None else jax.tree_util.tree_map(
                    lambda x, s=s, w=w: wsc(x, s)
                    if tuple(x.shape) == w else x, ns)
                for ns, s, w in zip(new_states, zero_upd, wshapes)]
        out = [new_params, new_states]
        if guarded:
            out.append(finite)
        if stats:
            # the model-health side-output, LAST: barrier'd inputs keep
            # the stat reductions out of the update clusters, so the
            # update math compiles (and rounds) exactly as without stats
            s_old, s_g, s_new = jax.lax.optimization_barrier(
                (tuple(params), tuple(grads), tuple(new_params)))
            out.append(_mstats.stats_block(s_old, s_g, s_new,
                                           hyper.get("loss")))
        return tuple(out)

    # params + states donated: the update happens in place in HBM
    name = "fused_trainer_step" + ("_zero1" if zero is not None else "") \
        + ("_guarded" if guarded else "") + ("_stats" if stats else "")
    fn = _tel.watch_jit(jax.jit(step, donate_argnums=(0, 2) if donate else ()),
                        name)
    _STEP_CACHE[sig] = (opt_ref, fn)
    return fn


def tracecheck_programs():
    """AOT specimens for graftcheck: the donated whole-model fused step
    over a tiny two-slot model (momentum SGD — weight AND slot state
    paths exercised), built through the same ``fused_step_fn`` cache the
    Trainer uses, with the device-backend donation layout."""
    from .. import ndarray as nd
    from ..optimizer import SGD
    opt = SGD(momentum=0.9, learning_rate=0.05)
    # the compiled step holds the optimizer only via weakref: keep the
    # specimen alive past this call or the driver's trace would observe
    # a collected owner
    _TRACECHECK_KEEPALIVE[:] = [opt]
    params_nd = [nd.zeros((32, 16)), nd.zeros((32,))]
    states_raw = [_state_raw(opt.create_state(i, w))
                  for i, w in enumerate(params_nd)]
    params_raw = [w._data for w in params_nd]
    hyper = {"lr": np.zeros(2, np.float32), "wd": np.zeros(2, np.float32),
             "t": np.ones(2, np.int32), "rescale": np.float32(1.0)}
    fn = fused_step_fn(opt, params_raw, states_raw, donate=True)
    # the guardian variant: same donated layout + the folded finite-
    # health verdict and a recorded loss scalar — graftcheck proves the
    # guard adds no host callback and no dtype widening
    guarded_hyper = dict(hyper, loss=np.float32(0.0))
    guarded = fused_step_fn(opt, params_raw, states_raw, donate=True,
                            guarded=True)
    # the ZeRO-1 variants: same donated layout with the sharded-update
    # placement over a zero mesh (2 shards where the host offers >1
    # device, degenerate 1 otherwise) — graftcheck proves the collective
    # sandwich adds no host callback, no dtype widening, and keeps the
    # donation clean
    zero = _ZeroPlan(min(2, jax.local_device_count()))
    zparams = zero.place_replicated(params_raw)
    zgrads = zero.scatter_grads(params_raw,
                                [w.shape for w in params_raw])
    zstates = [None if s is None else jax.device_put(
        s, zero.update_sharding(tuple(w.shape)) or zero.replicated)
        for s, w in zip(states_raw, params_raw)]
    zfn = fused_step_fn(opt, zparams, zstates, donate=True, zero=zero)
    zguarded = fused_step_fn(opt, zparams, zstates, donate=True,
                             guarded=True, zero=zero)
    # the MXNET_MODEL_STATS variants: same donated layouts with the
    # stacked health side-output — graftcheck proves the stats math adds
    # no host callback (JX103) and no f64 widening (JX102) to any path
    sfn = fused_step_fn(opt, params_raw, states_raw, donate=True,
                        stats=True)
    sguarded = fused_step_fn(opt, params_raw, states_raw, donate=True,
                             guarded=True, stats=True)
    zsfn = fused_step_fn(opt, zparams, zstates, donate=True, zero=zero,
                         stats=True)
    zsguarded = fused_step_fn(opt, zparams, zstates, donate=True,
                              guarded=True, zero=zero, stats=True)
    return [("fused_trainer_step", fn,
             (params_raw, params_raw, states_raw, hyper), {}),
            ("fused_trainer_step_guarded", guarded,
             (params_raw, params_raw, states_raw, guarded_hyper), {}),
            ("fused_trainer_step_zero1", zfn,
             (zparams, zgrads, zstates, hyper), {}),
            ("fused_trainer_step_zero1_guarded", zguarded,
             (zparams, zgrads, zstates, guarded_hyper), {}),
            ("fused_trainer_step_stats", sfn,
             (params_raw, params_raw, states_raw, hyper), {}),
            ("fused_trainer_step_guarded_stats", sguarded,
             (params_raw, params_raw, states_raw, guarded_hyper), {}),
            ("fused_trainer_step_zero1_stats", zsfn,
             (zparams, zgrads, zstates, hyper), {}),
            ("fused_trainer_step_zero1_guarded_stats", zsguarded,
             (zparams, zgrads, zstates, guarded_hyper), {})]


def run_fused_step(trainer, slots):
    """Execute one fused step for *slots* ([(slot_idx, Parameter)]).

    Keeps the Updater/optimizer bookkeeping (state layout, update
    counts, lr/wd resolution) identical to the per-slot loop so
    ``save_states``/``load_states`` round-trip unchanged and results are
    bitwise equal.

    Returns True when an installed guardian's verdict suppressed the
    update (the caller must then NOT notify the step boundary — a
    skipped step is not a completed optimizer step).
    """
    opt, updater = trainer._optimizer, trainer._updater
    guard = _guard.current()
    grads = [p.grad() for _, p in slots]
    plan = _zero_plan(trainer, slots)
    wshapes = [tuple(p.data().shape) for _, p in slots]
    session = _overlap.take_session(trainer)

    if trainer._kvstore is not None:
        raw_grads = None
        if session is not None:
            # overlap drain: the per-bucket rounds were dispatched
            # under backward as each bucket's gradients landed — this
            # waits out whatever is still in flight (the EXPOSED part
            # of the collective; the rest was hidden) and surfaces any
            # in-flight failure (PeerLost) before anything touches
            # params
            with _tel.span("kvstore_push_pull", cat="kvstore",
                           args={"overlap_drain": True}):
                raw_grads = session.drain(trainer._kvstore,
                                          [s for s, _ in slots], plan)
        if raw_grads is None and plan is not None:
            # the reduce-scatter leg: the bucketed reduction lands each
            # divisible gradient already sharded over the zero mesh (the
            # per-slot grad buffers are NOT rewritten — the sharded
            # arrays are consumed by the one step program)
            with _tel.span("kvstore_push_pull", cat="kvstore"):
                reduced = trainer._kvstore.reduce_scatter_all(
                    [s for s, _ in slots], [[g] for g in grads],
                    plan.grad_shardings(wshapes))
            raw_grads = [r._data for r in reduced]
        elif raw_grads is None:
            with _tel.span("kvstore_push_pull", cat="kvstore"):
                reduced = trainer._kvstore.push_pull_all(
                    [s for s, _ in slots], [[g] for g in grads])
            # per-slot grad buffers observe the reduced value, like
            # pull(out=g)
            for g, r in zip(grads, reduced):
                if r is not g:
                    g._set_data(r._data)
            raw_grads = [r._data for r in reduced]
    else:
        if session is not None:      # nothing to overlap without a store
            session.discard()
        raw_grads = [g._data for g in grads]
        if plan is not None:
            raw_grads = plan.scatter_grads(raw_grads, wshapes)
    if _chaos.active():
        # grad seam, once per BUCKET per step, keyed by bucket id: the
        # same decisions in the same canonical order whether the
        # buckets were reduced under backward or synchronously
        raw_grads = _overlap.poison_by_bucket(
            raw_grads, _overlap.bucket_plan(grads))

    # state + hyper bookkeeping, per slot, exactly like Updater/update()
    count_snapshot = None
    if guard is not None:
        # the undo token: a skipped step must not advance hyper['t']
        count_snapshot = opt._snapshot_update_counts(
            [s for s, _ in slots])
    for slot, p in slots:
        if slot not in updater.states:
            updater.states[slot] = opt.create_state(slot, p.data())
            updater.states_synced[slot] = True
        opt._update_count(slot)
    hyper = {"lr": np.asarray([opt._get_lr(s) for s, _ in slots],
                              np.float32),
             "wd": np.asarray([opt._get_wd(s) for s, _ in slots],
                              np.float32),
             "t": np.asarray([opt._index_update_count[s]
                              for s, _ in slots], np.int32),
             "rescale": np.float32(opt.rescale_grad)}
    rng_snapshot = None
    if getattr(opt, "needs_rng", False):
        if guard is not None:
            # a skipped step must not consume from the key stream, or a
            # retried batch draws different noise than the clean run
            rng_snapshot = _random.get_state()
        _prof.bump("xla_program_calls")            # the key split
        hyper["key"] = jax.random.split(_random.next_key(), len(slots))
    loss_raw = guard.take_loss_raw() if guard is not None else None
    if loss_raw is not None:
        hyper["loss"] = loss_raw

    params_raw = [p._raw_data() for _, p in slots]
    if plan is not None:
        # every program input must live on the zero mesh: weights (and
        # the loss/keys) enter replicated — data movement only, the
        # devices already share the reduced gradient rows and the
        # persistently sharded state
        plan.place_states(slots, updater)
        params_raw = plan.place_replicated(params_raw)
        if loss_raw is not None:
            hyper["loss"] = jax.device_put(hyper["loss"], plan.replicated)
        if "key" in hyper:
            hyper["key"] = jax.device_put(hyper["key"], plan.replicated)
        per_dev, rep_bytes = plan.state_byte_gauges(slots, updater)
        _tel.set_gauge("zero_shards", plan.n)
        _tel.set_gauge("zero_optimizer_bytes_per_device", per_dev)
        _tel.set_gauge("zero_optimizer_bytes_replicated", rep_bytes)
    states_raw = [_state_raw(updater.states[s]) for s, _ in slots]
    donate = slots and slots[0][1].data().context.device_type != "cpu"
    # model stats ride as a side-output of the SAME program: the flag is
    # part of the signature (one retrace when first enabled), the
    # interval is not — only the host fetch below is rationed by it
    stats_on = _mstats.enabled()
    stats_due = _mstats.recorder().note_step() if stats_on else False
    fn = fused_step_fn(opt, params_raw, states_raw, donate,
                       guarded=guard is not None, zero=plan,
                       stats=stats_on)
    trainer._fused_step_jit = fn                   # introspection / tests

    _prof.bump("xla_program_calls")
    _prof.bump("trainer_fused_step")
    if plan is not None:
        _prof.bump("trainer_zero_step")
    with _tel.span("fused_optimizer_step", cat="program"):
        outs = fn(params_raw, raw_grads, states_raw, hyper)
    new_params, new_states = outs[0], outs[1]
    verdict = outs[2] if guard is not None else None
    if stats_due:
        # the only host cost of recording: one read of an output the
        # program produced anyway (a guarded step pays this sync for the
        # verdict regardless)
        _mstats.recorder().record_block([p.name for _, p in slots],
                                        outs[-1], "loss" in hyper)

    # ALWAYS rebind: on a donate backend the inputs were consumed, and on
    # a skipped step the outputs carry the old values through jnp.where
    for (slot, p), nw, ns in zip(slots, new_params, new_states):
        if plan is not None:
            # the all-gathered weight is replicated over the mesh: keep
            # the shard already on this weight's OWN device (a view, not
            # a copy) so the eager forward/backward path is untouched
            nw = plan.local_view(nw, p.data().context.jax_device)
        p._rebind_data(nw)                         # donation-safe rebind
        _state_writeback(updater.states[slot], ns)

    if guard is None:
        return False
    # the one cost of guarding: reading the verdict scalar waits for the
    # step program (the same read dynamic loss scaling needs anyway to
    # steer the next step's scale).  The VERDICT itself was free — no
    # callback, no second program — but a guarded step does not overlap
    # with the next batch's host work the way an unguarded one can.
    finite = bool(np.asarray(verdict))
    if not finite:
        opt._revert_update_counts(count_snapshot)
        if rng_snapshot is not None:
            _random.set_state(rng_snapshot)
    return guard.after_step(finite)
