"""Fused Gluon Trainer step: the whole weight update as ONE XLA program.

The per-slot ``Trainer.step`` loop issues one kvstore push/pull (a
separate reduce per slot) plus one eager ``Updater`` dispatch per slot —
O(n_params) XLA program calls per step (~160 for ResNet-50).  The fused
path collapses that to

    O(n_buckets) bucketed gradient all-reduce programs   (kvstore.py)
  + 1 jitted, donated whole-model optimizer program

        (param_list, grad_list, opt_state_list, hyper)
            -> (new_params, new_opt_states)

mirroring ``module/cached_step.py``'s donated train step and the
reference's fused ``optimizer_op.cc`` kernels ("Automatic Cross-Replica
Sharding of Weight Update in Data-Parallel Training", PAPERS.md).

Hyper-parameters — per-slot lr/wd (scheduler and multipliers resolved
host-side each step), the update counts ``t``, and ``rescale_grad`` —
enter as *traced* scalars: changing the lr schedule or the batch size
never retraces.  Compiled steps are cached in ``_STEP_CACHE`` keyed on
(optimizer class, its static scalar hypers, param shapes/dtypes, opt
state tree structure), so two Trainers over identical models share one
program.

Parameter and state buffers are donated on device backends: XLA updates
weights in place in HBM; the Trainer rebinds the original NDArray
handles (``Parameter._rebind_data``) so every holder observes the new
buffers.  Gradients are NOT donated — ``grad_req='add'`` accumulation
reads them on the next backward.

Opt out with ``MXNET_FUSED_TRAINER=0`` (the per-slot loop stays the
bitwise-equality oracle in tests/test_fused_trainer.py).
"""
from __future__ import annotations

import os
import weakref

import jax
import jax.numpy as jnp
import numpy as np

from .. import chaos as _chaos
from .. import profiler as _prof
from .. import random as _random
from .. import telemetry as _tel
from ..guardian import core as _guard
from ..guardian import health as _health
from ..optimizer import _state_raw, _state_writeback, static_hypers

__all__ = ["fused_trainer_enabled", "fused_step_fn", "run_fused_step"]


def _env_enabled():
    return os.environ.get("MXNET_FUSED_TRAINER", "1").strip().lower() \
        not in ("0", "false", "off", "no")


# cached at import (the JG006 cached-value pattern): Trainer.step consults
# this once per step and must not re-parse the environment each time
_ENABLED = _env_enabled()


def refresh_from_env():
    """Re-read MXNET_FUSED_TRAINER (tests / late configuration)."""
    global _ENABLED
    _ENABLED = _env_enabled()


def fused_trainer_enabled():
    return _ENABLED


_STEP_CACHE = {}      # signature -> (weakref to optimizer, jitted step)
_TRACECHECK_KEEPALIVE = []    # graftcheck specimen optimizers (see below)


def _signature(opt, params_raw, states_raw, donate, guarded):
    leaves, treedef = jax.tree_util.tree_flatten(states_raw)
    return (type(opt), static_hypers(opt),
            tuple((tuple(w.shape), str(w.dtype)) for w in params_raw),
            # placement is part of jax's own jit cache key: fold it in so
            # a same-shape model on a different device/sharding gets its
            # own entry instead of a retrace of someone else's closure
            tuple(str(getattr(w, "sharding", None)) for w in params_raw),
            str(treedef),
            tuple((tuple(l.shape), str(l.dtype)) for l in leaves),
            bool(donate), bool(guarded))


def fused_step_fn(opt, params_raw, states_raw, donate, guarded=False):
    """The jitted whole-model step for this (optimizer, model) signature,
    compiled once per signature process-wide.

    The compiled step closes over *an* optimizer instance, but only via a
    weakref: the signature pins every attribute the trace reads, so any
    same-signature instance produces the same program — and a cached
    entry whose original optimizer died is rebuilt around the caller's
    live one instead of pinning the dead model's parameters forever.

    With ``guarded=True`` (a :class:`~mxnet_tpu.guardian.TrainingGuardian`
    is installed) the SAME program additionally computes an
    all-grads-finite scalar — plus the finiteness of ``hyper['loss']``
    when the loop recorded one — and suppresses the whole update via
    ``jnp.where`` on a nonfinite verdict: old params/states pass through
    the donated buffers, the verdict rides out as a third output.  One
    extra reduction in an existing program; never a second XLA launch,
    never a host callback (graftcheck-proven on the
    ``fused_trainer_step_guarded`` specimen).
    """
    sig = _signature(opt, params_raw, states_raw, donate, guarded)
    # prune entries whose owning optimizer died (their compiled programs
    # would otherwise pin memory forever)
    for dead in [k for k, (r, _) in _STEP_CACHE.items() if r() is None]:
        del _STEP_CACHE[dead]
    entry = _STEP_CACHE.get(sig)
    if entry is not None:
        owner = entry[0]()
        # the closure's owner must still match the signature it was
        # compiled under — a mid-training hyper mutation on the owner
        # would otherwise leak into a retrace of this entry
        if owner is not None and static_hypers(owner) == sig[1]:
            return entry[1]

    opt_ref = weakref.ref(opt)

    def step(params, grads, states, hyper):
        o = opt_ref()
        if o is None:       # only reachable on a retrace after death
            raise RuntimeError("fused step optimizer was collected")
        if not guarded:
            return o.fused_update_step(params, grads, states, hyper)
        finite = _health.all_finite(grads)
        if "loss" in hyper:            # dict structure: static per trace
            finite = jnp.logical_and(
                finite, jnp.all(jnp.isfinite(hyper["loss"])))
        new_params, new_states = o.fused_update_step(params, grads,
                                                     states, hyper)
        # nonfinite ⇒ the donated buffers keep their old values: the
        # poisoned batch costs one skipped step, not a retrace and not
        # a host round-trip
        new_params = [jnp.where(finite, n, p)
                      for n, p in zip(new_params, params)]
        new_states = jax.tree_util.tree_map(
            lambda n, p: jnp.where(finite, n, p), new_states, states)
        return new_params, new_states, finite

    # params + states donated: the update happens in place in HBM
    name = "fused_trainer_step_guarded" if guarded else "fused_trainer_step"
    fn = _tel.watch_jit(jax.jit(step, donate_argnums=(0, 2) if donate else ()),
                        name)
    _STEP_CACHE[sig] = (opt_ref, fn)
    return fn


def tracecheck_programs():
    """AOT specimens for graftcheck: the donated whole-model fused step
    over a tiny two-slot model (momentum SGD — weight AND slot state
    paths exercised), built through the same ``fused_step_fn`` cache the
    Trainer uses, with the device-backend donation layout."""
    from .. import ndarray as nd
    from ..optimizer import SGD
    opt = SGD(momentum=0.9, learning_rate=0.05)
    # the compiled step holds the optimizer only via weakref: keep the
    # specimen alive past this call or the driver's trace would observe
    # a collected owner
    _TRACECHECK_KEEPALIVE[:] = [opt]
    params_nd = [nd.zeros((32, 16)), nd.zeros((32,))]
    states_raw = [_state_raw(opt.create_state(i, w))
                  for i, w in enumerate(params_nd)]
    params_raw = [w._data for w in params_nd]
    hyper = {"lr": np.zeros(2, np.float32), "wd": np.zeros(2, np.float32),
             "t": np.ones(2, np.int32), "rescale": np.float32(1.0)}
    fn = fused_step_fn(opt, params_raw, states_raw, donate=True)
    # the guardian variant: same donated layout + the folded finite-
    # health verdict and a recorded loss scalar — graftcheck proves the
    # guard adds no host callback and no dtype widening
    guarded_hyper = dict(hyper, loss=np.float32(0.0))
    guarded = fused_step_fn(opt, params_raw, states_raw, donate=True,
                            guarded=True)
    return [("fused_trainer_step", fn,
             (params_raw, params_raw, states_raw, hyper), {}),
            ("fused_trainer_step_guarded", guarded,
             (params_raw, params_raw, states_raw, guarded_hyper), {})]


def run_fused_step(trainer, slots):
    """Execute one fused step for *slots* ([(slot_idx, Parameter)]).

    Keeps the Updater/optimizer bookkeeping (state layout, update
    counts, lr/wd resolution) identical to the per-slot loop so
    ``save_states``/``load_states`` round-trip unchanged and results are
    bitwise equal.

    Returns True when an installed guardian's verdict suppressed the
    update (the caller must then NOT notify the step boundary — a
    skipped step is not a completed optimizer step).
    """
    opt, updater = trainer._optimizer, trainer._updater
    guard = _guard.current()
    grads = [p.grad() for _, p in slots]

    if trainer._kvstore is not None:
        with _tel.span("kvstore_push_pull", cat="kvstore"):
            reduced = trainer._kvstore.push_pull_all(
                [s for s, _ in slots], [[g] for g in grads])
        # per-slot grad buffers observe the reduced value, like pull(out=g)
        for g, r in zip(grads, reduced):
            if r is not g:
                g._set_data(r._data)
        raw_grads = [r._data for r in reduced]
    else:
        raw_grads = [g._data for g in grads]
    if _chaos.active():              # grad seam: `nan` poisons a bucket
        raw_grads = _chaos.poison_grads(raw_grads)

    # state + hyper bookkeeping, per slot, exactly like Updater/update()
    count_snapshot = None
    if guard is not None:
        # the undo token: a skipped step must not advance hyper['t']
        count_snapshot = opt._snapshot_update_counts(
            [s for s, _ in slots])
    for slot, p in slots:
        if slot not in updater.states:
            updater.states[slot] = opt.create_state(slot, p.data())
            updater.states_synced[slot] = True
        opt._update_count(slot)
    hyper = {"lr": np.asarray([opt._get_lr(s) for s, _ in slots],
                              np.float32),
             "wd": np.asarray([opt._get_wd(s) for s, _ in slots],
                              np.float32),
             "t": np.asarray([opt._index_update_count[s]
                              for s, _ in slots], np.int32),
             "rescale": np.float32(opt.rescale_grad)}
    rng_snapshot = None
    if getattr(opt, "needs_rng", False):
        if guard is not None:
            # a skipped step must not consume from the key stream, or a
            # retried batch draws different noise than the clean run
            rng_snapshot = _random.get_state()
        _prof.bump("xla_program_calls")            # the key split
        hyper["key"] = jax.random.split(_random.next_key(), len(slots))
    loss_raw = guard.take_loss_raw() if guard is not None else None
    if loss_raw is not None:
        hyper["loss"] = loss_raw

    params_raw = [p._raw_data() for _, p in slots]
    states_raw = [_state_raw(updater.states[s]) for s, _ in slots]
    donate = slots and slots[0][1].data().context.device_type != "cpu"
    fn = fused_step_fn(opt, params_raw, states_raw, donate,
                       guarded=guard is not None)
    trainer._fused_step_jit = fn                   # introspection / tests

    _prof.bump("xla_program_calls")
    _prof.bump("trainer_fused_step")
    with _tel.span("fused_optimizer_step", cat="program"):
        if guard is not None:
            new_params, new_states, verdict = fn(params_raw, raw_grads,
                                                 states_raw, hyper)
        else:
            new_params, new_states = fn(params_raw, raw_grads,
                                        states_raw, hyper)

    # ALWAYS rebind: on a donate backend the inputs were consumed, and on
    # a skipped step the outputs carry the old values through jnp.where
    for (slot, p), nw, ns in zip(slots, new_params, new_states):
        p._rebind_data(nw)                         # donation-safe rebind
        _state_writeback(updater.states[slot], ns)

    if guard is None:
        return False
    # the one cost of guarding: reading the verdict scalar waits for the
    # step program (the same read dynamic loss scaling needs anyway to
    # steer the next step's scale).  The VERDICT itself was free — no
    # callback, no second program — but a guarded step does not overlap
    # with the next batch's host work the way an unguarded one can.
    finite = bool(np.asarray(verdict))
    if not finite:
        opt._revert_update_counts(count_snapshot)
        if rng_snapshot is not None:
            _random.set_state(rng_snapshot)
    return guard.after_step(finite)
