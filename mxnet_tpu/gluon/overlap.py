"""Comm/compute overlap: bucket-ready gradient reduction under backward.

The fused ``Trainer.step`` used to run every kvstore collective as one
post-hoc phase after backward returned, so communication time was pure
added wall clock (ROADMAP item 2).  This module makes gradient
reduction start the moment a bucket's gradients exist, DDP-style:

1. At the end of each ``Trainer.step`` the trainer **arms** an
   :class:`OverlapSession` for the next iteration: the trainable slots
   are grouped into the same dtype-homogeneous ≤4 MiB buckets the
   kvstore itself plans (``kvstore._plan_buckets`` on identical metas,
   so bucket membership — and the dist wire's ``__bucket__<digest>``
   keys — match the non-overlapped round exactly).
2. ``autograd._backward_impl`` finalizes each parameter's gradient the
   moment its last consumer is processed and fires the grad-ready hook
   (the ``grad.bucket`` seam PR 9 carved).  When a bucket's last
   gradient lands, the session dispatches that bucket's kvstore round —
   ``push_pull_all`` (or ``reduce_scatter_all`` under ``MXNET_ZERO``) —
   as an **engine task** while the tape sweep is still computing
   earlier layers.
3. ``run_fused_step`` **drains** the session instead of launching the
   round itself: it waits out the in-flight buckets, measures how much
   collective wall time was hidden under backward vs exposed in the
   step, and feeds the reduced gradients straight into the one fused
   update program.

Ordering: backward produces gradients in roughly reverse slot order
(output layers first), so buckets are *launched* in descending bucket
index — a bucket becomes launchable only once every higher-indexed
bucket has been dispatched.  The launch order is therefore a pure
function of the bucket plan, identical on every rank: concurrent
same-key pushes can never interleave across ranks into the dist_sync
deadlock, and chaos decisions stay deterministic (bucket-keyed
counters, see :mod:`mxnet_tpu.chaos`).  Bucket tasks serialize on one
engine lane variable, so the transport sees at most one in-flight
bucket round per trainer — each protected by the PR-8 per-RPC
deadlines: a dead peer mid-overlap surfaces as a structured
``PeerLost`` at drain (engine errors re-raise at the wait point), never
a hang, and the params are untouched because the fused update program
only runs after a clean drain.

Bitwise: bucket membership, per-key payloads, and per-element summation
order are identical to the non-overlapped round — only *when* each
bucket's round runs changes.  ``MXNET_OVERLAP=0`` disables arming
entirely and is the equality oracle in tests (the same trick as
``MXNET_FUSED_TRAINER=0``).

Anything the armed plan cannot honor — a changed slot set, a gradient
re-written after its bucket dispatched (double backward), a flipped
ZeRO plan, stale slots — discards the session and falls back to the
synchronous round: ``overlap_fallbacks`` counts those, correctness
never depends on the fast path.
"""
from __future__ import annotations

import os
import threading

from .. import chaos as _chaos
from .. import engine as _engine
from ..lint import lockwitness as _lockwitness
from .. import profiler as _prof
from .. import telemetry as _tel
from ..telemetry import flight as _flight

__all__ = ["overlap_enabled", "refresh_from_env", "OverlapSession",
           "maybe_arm", "take_session", "abandon_session",
           "bucket_plan", "poison_by_bucket", "last_step_stats"]


def _env_enabled():
    return os.environ.get("MXNET_OVERLAP", "1").strip().lower() \
        not in ("0", "false", "off", "no")


# cached at import (the JG006 pattern): consulted once per Trainer.step
_ENABLED = _env_enabled()


def refresh_from_env():
    """Re-read MXNET_OVERLAP (tests / late configuration)."""
    global _ENABLED
    _ENABLED = _env_enabled()


def overlap_enabled():
    return _ENABLED


def _now_us():
    from ..telemetry import core as _core
    return _core.now_us()


# ---------------------------------------------------------------------------
# the canonical bucket plan (shared with the non-overlapped chaos seam)
# ---------------------------------------------------------------------------

def bucket_plan(grads):
    """Group slot positions into the canonical gradient buckets:
    ``kvstore._plan_buckets`` over the same (dtype, nbytes) metas the
    kvstore and the dist ``_bucket_layout`` derive — one shared plan, so
    overlapped per-bucket rounds reduce exactly the buckets the
    monolithic round would, and chaos bucket ids mean the same thing on
    every path.  Returns ``[[slot positions of bucket 0], ...]``."""
    from .. import kvstore as kvs
    metas = [(str(g.dtype), g.size * g.dtype.itemsize) for g in grads]
    return kvs._plan_buckets(metas)


def poison_by_bucket(raw_grads, plan):
    """The per-bucket ``grad.bucket`` chaos seam, bucket-id keyed: one
    decision per bucket per step, in ascending bucket order at a
    deterministic point (post-reduce, pre-update) — identical calls
    whether the buckets were reduced under backward or synchronously.
    A ``nan`` fault poisons the FIRST gradient of its bucket."""
    out = list(raw_grads)
    for bidx, positions in enumerate(plan):
        sub = [out[p] for p in positions]
        res = _chaos.poison_grads(sub, key=bidx)
        if res is not sub:
            for p, r in zip(positions, res):
                out[p] = r
    return out


# ---------------------------------------------------------------------------
# the session
# ---------------------------------------------------------------------------

# id(param data NDArray) -> (weakref to session, position); the autograd
# hook does ONE dict lookup per finalized gradient.  The session is held
# WEAKLY: a trainer dropped with its final session still armed (every
# step ends with maybe_arm) must not pin model-sized params/grads in a
# module global forever — when the trainer dies, the session dies, and
# its entries are swept lazily here and at the next arm.
_WATCH = {}
_WATCH_LOCK = _lockwitness.make_lock("overlap._WATCH_LOCK")
_PREV_HOOK = None
_HOOK_ON = False

_LAST_STATS = None          # the most recent drained step's overlap stats


def _grad_ready_hook(data_nd):
    entry = _WATCH.get(id(data_nd))
    if entry is not None:
        session = entry[0]()
        if session is None:          # owner died armed: sweep the entry
            with _WATCH_LOCK:
                if _WATCH.get(id(data_nd)) is entry:
                    del _WATCH[id(data_nd)]
                _hook_sync()
            return
        session._on_ready(entry[1], data_nd)


def _sweep_dead_watch():
    """Drop entries whose session was garbage-collected (called under
    _WATCH_LOCK)."""
    dead = [k for k, e in _WATCH.items() if e[0]() is None]
    for k in dead:
        del _WATCH[k]


def _hook_sync():
    """Install/remove the autograd hook to track watch-map emptiness."""
    global _PREV_HOOK, _HOOK_ON
    from .. import autograd as _ag
    if _WATCH and not _HOOK_ON:
        _PREV_HOOK = _ag.set_grad_ready_hook(_grad_ready_hook)
        _HOOK_ON = True
    elif not _WATCH and _HOOK_ON:
        _ag.set_grad_ready_hook(_PREV_HOOK)
        _PREV_HOOK = None
        _HOOK_ON = False


class _Bucket:
    __slots__ = ("idx", "positions", "waiting", "launched", "result",
                 "error", "t0_us", "t1_us", "thread")

    def __init__(self, idx, positions):
        self.idx = idx
        self.positions = list(positions)
        self.waiting = set(positions)
        self.launched = False
        self.result = None
        self.error = None
        self.t0_us = self.t1_us = 0.0
        self.thread = None


class OverlapSession:
    """One armed iteration: buckets waiting for their gradients, then
    in-flight on the engine lane, then drained by ``run_fused_step``."""

    def __init__(self, trainer, slots, kvstore, zero_plan):
        self.slot_ids = [s for s, _ in slots]
        self.params = [p for _, p in slots]
        self.grads = [p.grad() for _, p in slots]
        self.kvstore = kvstore
        self.zero_plan = zero_plan
        if zero_plan is not None:
            self.shardings = zero_plan.grad_shardings(
                [tuple(p.data().shape) for _, p in slots])
        else:
            self.shardings = None
        self.plan = bucket_plan(self.grads)
        self.buckets = [_Bucket(i, ps) for i, ps in enumerate(self.plan)]
        self.dirty = False
        self._dispatched = 0
        self._next_launch = len(self.buckets) - 1   # descending launches
        self._lock = _lockwitness.make_lock("OverlapSession._lock")
        self._notify_thread = None
        self._eng = _engine.engine()
        self._lane = self._eng.new_variable()
        self._closed = False
        import weakref
        ref = weakref.ref(self)
        with _WATCH_LOCK:
            _sweep_dead_watch()
            for pos, p in enumerate(self.params):
                _WATCH[id(p.data())] = (ref, pos)
            _hook_sync()

    # -- grad-ready side (backward thread) ---------------------------------

    def _on_ready(self, pos, data_nd):
        if self.params[pos].data() is not data_nd:
            return            # stale id-reuse of a dead trainer's buffer
        launch = []
        with self._lock:
            if self._closed:
                return
            if self._notify_thread is None:
                self._notify_thread = threading.get_ident()
            for b in self.buckets:
                if pos in b.waiting:
                    b.waiting.discard(pos)
                    break
            else:
                # a gradient re-written after its bucket was counted:
                # the dispatched reduce may have consumed a superseded
                # value — discard the whole session at drain
                self.dirty = True
                return
            while self._next_launch >= 0 \
                    and not self.buckets[self._next_launch].waiting:
                b = self.buckets[self._next_launch]
                b.launched = True
                launch.append(b)
                self._next_launch -= 1
        for b in launch:
            self._launch(b)

    def _launch(self, b):
        _prof.bump("overlap_bucket_dispatches")
        try:
            self._eng.push(lambda b=b: self._reduce_bucket(b),
                           mutable_vars=(self._lane,),
                           tag="overlap_bucket_%d" % b.idx)
        except Exception:
            # an un-pushable task must not break backward; the drain
            # notices the missing result and falls back synchronously
            with self._lock:
                self.dirty = True

    def _reduce_bucket(self, b):
        """The engine task: this bucket's kvstore round (PR-8 deadlines
        bound every RPC inside — a dead peer raises structured
        ``PeerLost`` here and re-raises at the drain wait point)."""
        b.t0_us = _now_us()
        b.thread = threading.get_ident()
        keys = [self.slot_ids[p] for p in b.positions]
        vals = [[self.grads[p]] for p in b.positions]
        with _tel.span("kvstore_push_pull", cat="kvstore",
                       args={"bucket": b.idx, "overlap": True}):
            if self.shardings is None:
                b.result = self.kvstore.push_pull_all(keys, vals)
            else:
                b.result = self.kvstore.reduce_scatter_all(
                    keys, vals,
                    [self.shardings[p] for p in b.positions])
        b.t1_us = _now_us()

    # -- drain side (Trainer.step) -----------------------------------------

    def _deactivate(self):
        with _WATCH_LOCK:
            for p in self.params:
                try:
                    key = id(p.data())
                except Exception:
                    continue
                entry = _WATCH.get(key)
                if entry is not None and entry[0]() is self:
                    del _WATCH[key]
            _sweep_dead_watch()
            _hook_sync()
        with self._lock:
            self._closed = True

    def _release_lane(self):
        lane, self._lane = self._lane, None
        if lane is not None:
            self._eng.delete_variable(lane)

    def drain(self, kvstore, slot_ids, zero_plan):
        """Collect the overlapped results for this step, or None when
        the armed plan cannot serve it (the caller then runs the
        synchronous round).  Raises what a bucket task raised — e.g. a
        structured ``PeerLost`` from a dead peer — with the params
        untouched and nothing half-reduced escaping: results are only
        returned when EVERY bucket landed cleanly."""
        global _LAST_STATS
        self._deactivate()
        usable = (not self.dirty
                  and kvstore is self.kvstore
                  and zero_plan is self.zero_plan
                  and slot_ids == self.slot_ids
                  and all(b.launched for b in self.buckets))
        if not usable:
            dispatched = any(b.launched for b in self.buckets)
            self.discard()
            _prof.bump("overlap_fallbacks")
            self._refuse_dist_refallback(dispatched)
            return None
        t_drain = _now_us()
        try:
            self._eng.wait_for_var(self._lane)
        finally:
            self._release_lane()
        exposed_us = _now_us() - t_drain
        if any(b.result is None for b in self.buckets):
            # a task died without raising here (error already consumed
            # by an earlier wait point): fall back, don't guess
            _prof.bump("overlap_fallbacks")
            self._refuse_dist_refallback(True)
            return None
        reduced = [None] * len(self.slot_ids)
        for b in self.buckets:
            for p, r in zip(b.positions, b.result):
                reduced[p] = r
        if self.shardings is None:
            # per-slot grad buffers observe the reduced value, exactly
            # like the synchronous round's pull(out=g) contract
            for g, r in zip(self.grads, reduced):
                if r is not g:
                    g._set_data(r._data)
        busy = sum(b.t1_us - b.t0_us for b in self.buckets)
        off_busy = sum(b.t1_us - b.t0_us for b in self.buckets
                       if b.thread != self._notify_thread)
        inline_busy = busy - off_busy
        hidden_us = max(0.0, off_busy - exposed_us)
        stats = {"buckets": len(self.buckets),
                 "collective_busy_us": round(busy, 1),
                 "hidden_us": round(hidden_us, 1),
                 "exposed_us": round(exposed_us + inline_busy, 1)}
        _LAST_STATS = stats
        _prof.bump("overlap_steps")
        _tel.set_gauge("overlap_hidden_us", stats["hidden_us"])
        _tel.set_gauge("overlap_exposed_us", stats["exposed_us"])
        _tel.device.note_overlap(stats["hidden_us"], stats["exposed_us"])
        return [r._data for r in reduced]

    def _refuse_dist_refallback(self, dispatched):
        """On a DIST kvstore, a synchronous re-run after this session
        already pushed bucket frames would advance this rank's per-key
        push timestamps one ahead of every other rank — the server
        would then silently aggregate mismatched steps forever.  Local
        stores re-reduce harmlessly; dist must fail LOUDLY instead
        (the rank-asymmetric causes — a failed engine push, a consumed
        task error — are unrecoverable in-band; symmetric causes can
        rerun with MXNET_OVERLAP=0)."""
        from .. import kvstore as kvs
        from ..base import MXNetError
        if dispatched and isinstance(self.kvstore, kvs.KVStoreDist):
            raise MXNetError(
                "overlap session cannot fall back to the synchronous "
                "round on a dist kvstore after bucket pushes reached "
                "the wire (per-key push timestamps would desync across "
                "ranks); restart the step pattern with MXNET_OVERLAP=0")

    def discard(self):
        """Abandon the session: wait out in-flight bucket tasks (their
        results are dropped; a task error is logged, not raised — a
        synchronous retry on a LOCAL store resurfaces anything real)
        and release the lane."""
        self._deactivate()
        try:
            if self._lane is not None:
                self._eng.wait_for_var(self._lane)
        except Exception as exc:    # noqa: BLE001
            _flight.record("overlap", "abandoned-bucket-error",
                           error=repr(exc)[:300])
        finally:
            self._release_lane()


def maybe_arm(trainer, slots):
    """Arm an overlap session for the NEXT iteration, when the next
    step can actually use it: overlap on, fused path on, a kvstore
    without server-side update semantics, dense gradients.  Called at
    the end of every ``Trainer.step``."""
    from . import fused_trainer as _ft
    old = getattr(trainer, "_overlap_session", None)
    if old is not None:
        old.discard()
        trainer._overlap_session = None
    if not _ENABLED:
        return None
    kv = trainer._kvstore
    if kv is None or not _ft.fused_trainer_enabled() \
            or not trainer._optimizer.supports_fused():
        return None
    if kv._updater is not None or kv._optimizer is not None:
        return None                 # update_on_kvstore: per-key path
    if any(getattr(p.grad(), "stype", "default") != "default"
           for _, p in slots):
        return None                 # sparse rows don't map onto buckets
    zero_plan = getattr(trainer, "_zero_plan", None) \
        if _ft.zero_enabled() else None
    session = OverlapSession(trainer, slots, kv, zero_plan)
    trainer._overlap_session = session
    return session


def take_session(trainer):
    """Claim (and detach) the trainer's armed session, if any."""
    session = getattr(trainer, "_overlap_session", None)
    trainer._overlap_session = None
    return session


def abandon_session(trainer):
    """Discard an armed session without using it (the per-slot oracle
    loop, a de-fused optimizer, trainer teardown)."""
    session = getattr(trainer, "_overlap_session", None)
    if session is not None:
        trainer._overlap_session = None
        session.discard()


def last_step_stats():
    """The most recent drained step's overlap stats (the MULTICHIP
    dryrun's reporting hook), or None."""
    return _LAST_STATS
