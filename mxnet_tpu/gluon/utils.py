"""Gluon utilities (reference python/mxnet/gluon/utils.py).

``split_and_load`` in the reference scatters a batch over a ctx list; on
TPU a batch is one sharded jax.Array, so the returned "slices" are views —
the parity API survives for code written against it.
"""
from __future__ import annotations

import numpy as np

from .. import ndarray as nd
from .. import profiler as _prof
from .. import telemetry as _tel

__all__ = ["split_data", "split_and_load", "clip_global_norm"]


def split_data(data, num_slice, batch_axis=0, even_split=True):
    """Split an NDArray into num_slice slices along batch_axis."""
    size = data.shape[batch_axis]
    if size < num_slice:
        raise ValueError(
            "Too many slices for data with shape %s. Arguments are "
            "num_slice=%d and batch_axis=%d." % (
                str(data.shape), num_slice, batch_axis))
    if even_split and size % num_slice != 0:
        raise ValueError(
            "data with shape %s cannot be evenly split into %d slices "
            "along axis %d. Use a batch size that's multiple of %d or set "
            "even_split=False to allow uneven partitioning of data."
            % (str(data.shape), num_slice, batch_axis, num_slice))
    step = size // num_slice
    if not even_split:
        slices = [
            nd.slice_axis(data, batch_axis, i * step,
                          (i + 1) * step if i < num_slice - 1 else size)
            for i in range(num_slice)]
    else:
        slices = [nd.slice_axis(data, batch_axis, i * step, (i + 1) * step)
                  for i in range(num_slice)]
    return slices


def split_and_load(data, ctx_list, batch_axis=0, even_split=True):
    """Split data into len(ctx_list) slices and load each to one ctx."""
    if not isinstance(data, nd.NDArray):
        data = nd.array(data, ctx=ctx_list[0])
    if len(ctx_list) == 1:
        return [data.as_in_context(ctx_list[0])]
    slices = split_data(data, len(ctx_list), batch_axis, even_split)
    return [s.as_in_context(ctx) for s, ctx in zip(slices, ctx_list)]


# one watched jit per donation mode; jax keys its own cache on the array
# layout, so each (shapes, dtypes) gradient set compiles once and every
# later step is a single program call (the old implementation dispatched
# one dot product per array AND host-synced the norm before deciding the
# scale — O(n) programs + a blocking round-trip per clip).  On device
# backends the input buffers are donated (the caller rebinds the outputs,
# so XLA rescales in place in HBM); CPU skips donation like the fused
# trainer does.
_CLIP_JITS = {}


def _clip_program(donate):
    fn = _CLIP_JITS.get(donate)
    if fn is None:
        import jax
        import jax.numpy as jnp
        from ..guardian import health as _health

        def _clip(raws, max_norm):
            norm = _health.global_norm(raws)
            # the guardian's finiteness verdict, not a private isfinite
            # pass: nonfinite gradients leave the arrays untouched (the
            # guardian will skip the step) and report the nonfinite norm
            finite = _health.all_finite(raws)
            scale = max_norm / (norm + 1e-8)
            apply = jnp.logical_and(finite, scale < 1.0)
            scale = jnp.where(apply, scale, jnp.ones_like(scale))
            return [r * scale.astype(r.dtype) for r in raws], norm
        fn = _CLIP_JITS[donate] = _tel.watch_jit(
            jax.jit(_clip, donate_argnums=(0,) if donate else ()),
            "clip_global_norm")
    return fn


def clip_global_norm(arrays, max_norm):
    """Rescale arrays so that the sum of their 2-norm is at most
    *max_norm*; returns the pre-clip global norm.

    Norm, scale decision, and rescale all run in ONE watched jitted
    program — the only host sync is the returned float, after the
    program is already in flight.  Nonfinite inputs are never scaled
    (``mxnet_tpu.guardian.health`` verdict in-program): the garbage
    stays visible to the guardian instead of being smeared by a NaN
    scale factor.
    """
    assert len(arrays) > 0
    _prof.bump("xla_program_calls")
    donate = arrays[0].context.device_type != "cpu"
    new_raws, norm = _clip_program(donate)([a._data for a in arrays],
                                           np.float32(max_norm))
    for arr, raw in zip(arrays, new_raws):
        arr._set_data(raw)
    return float(np.asarray(norm))


def tracecheck_programs():
    """AOT specimens for graftcheck: the fused norm+scale clip program
    over a two-array gradient layout."""
    raws = [nd.zeros((8, 4))._data, nd.zeros((16,))._data]
    return [("clip_global_norm", _clip_program(donate=True),
             (raws, np.float32(1.0)), {})]
