"""Gluon Parameter / ParameterDict.

Parity surface: reference ``python/mxnet/gluon/parameter.py`` (Parameter
:43 — deferred init, grad_req, data/grad accessors :251,348-399;
ParameterDict :416 — prefixed registry with get/update/initialize/save/load).

TPU-native redesign: the reference keeps one copy of every parameter per
device context and reduces gradients across them (``_check_and_get`` over
``_data`` lists).  On TPU, replication and sharding are properties of one
``jax.Array`` over a mesh, so a Parameter owns exactly one NDArray; the
``list_data``/``list_grad`` API survives as views of that single logical
value (length == len(ctx list) for API parity, same buffer).
"""
from __future__ import annotations

from collections import OrderedDict

import numpy as np

from ..base import MXNetError
from ..context import Context, cpu, current_context
from .. import ndarray as nd
from .. import initializer
from .. import symbol as _sym
from .. import autograd

__all__ = ["DeferredInitializationError", "Parameter", "ParameterDict"]


class DeferredInitializationError(MXNetError):
    """Raised when a parameter's value is requested before shape is known."""


def _shape_known(shape):
    return shape is not None and all(s > 0 for s in shape)


class Parameter(object):
    """A Container holding parameters (weights) of Blocks.

    Reference: ``gluon/parameter.py:43``.  ``grad_req`` in
    {'write','add','null'}; shape dims of 0 mean "infer on first forward"
    (deferred initialization).
    """

    def __init__(self, name, grad_req="write", shape=None, dtype=np.float32,
                 lr_mult=1.0, wd_mult=1.0, init=None,
                 allow_deferred_init=False, differentiable=True):
        self.name = name
        self.shape = tuple(shape) if shape is not None else None
        self.dtype = dtype
        self.lr_mult = lr_mult
        self.wd_mult = wd_mult
        self.init = init
        self.allow_deferred_init = allow_deferred_init
        if not differentiable:
            grad_req = "null"
        self._grad_req = grad_req
        self._data = None
        self._grad = None
        self._ctx_list = None
        self._deferred_init = ()

    def __repr__(self):
        return "Parameter %s (shape=%s, dtype=%s)" % (
            self.name, self.shape, self.dtype)

    # -- grad_req ----------------------------------------------------------
    @property
    def grad_req(self):
        return self._grad_req

    @grad_req.setter
    def grad_req(self, req):
        if req not in ("write", "add", "null"):
            raise ValueError("invalid grad_req %s" % req)
        if self._grad_req == req:
            return
        self._grad_req = req
        if req == "null":
            self._grad = None
            if self._data is not None:
                self._data._grad = None
                self._data._marked = False
        elif self._data is not None:
            self._init_grad()

    # -- initialization ----------------------------------------------------
    def initialize(self, init=None, ctx=None, default_init=None,
                   force_reinit=False):
        """Initialize parameter data (reference parameter.py:251)."""
        if default_init is None:
            default_init = initializer.Uniform()
        if self._data is not None and not force_reinit:
            return
        if ctx is None:
            ctx = [current_context()]
        if isinstance(ctx, Context):
            ctx = [ctx]
        self._ctx_list = list(ctx)
        if not _shape_known(self.shape):
            if self.allow_deferred_init:
                self._deferred_init = (init, ctx, default_init)
                return
            raise ValueError(
                "Cannot initialize Parameter %s because it has invalid "
                "shape %s." % (self.name, self.shape))
        self._finish_init(init, ctx, default_init)

    def _finish_init(self, init, ctx, default_init):
        self._deferred_init = ()
        data = nd.zeros(self.shape, ctx=ctx[0], dtype=self.dtype)
        initializer.create(init or self.init or default_init)(
            initializer.InitDesc(self.name), data)
        self._data = data
        if self._grad_req != "null":
            self._init_grad()

    def _init_grad(self):
        self._grad = nd.zeros(self.shape, ctx=self._data.context,
                              dtype=self._data.dtype)
        autograd.mark_variables([self._data], [self._grad],
                                grad_reqs=self._grad_req)

    def _finish_deferred_init(self):
        if not self._deferred_init:
            return
        if not _shape_known(self.shape):
            raise DeferredInitializationError(
                "Parameter %s has unknown shape %s" % (self.name, self.shape))
        init, ctx, default_init = self._deferred_init
        self._finish_init(init, ctx, default_init)

    def _set_shape_if_deferred(self, shape):
        """Fill in inferred dims (0 → concrete) during deferred init."""
        if self.shape is None:
            self.shape = tuple(shape)
            return
        new = []
        for old, got in zip(self.shape, shape):
            if old > 0 and got > 0 and old != got:
                raise MXNetError(
                    "inferred shape %s incompatible with declared %s for %s"
                    % (shape, self.shape, self.name))
            new.append(old if old > 0 else got)
        self.shape = tuple(new)

    # -- stale-grad tracking (reference parameter.py _fresh_grad) ----------
    @property
    def _fresh_grad(self):
        """True iff backward wrote this parameter's gradient since the
        last ``Trainer.step`` (reference trainer.py:148 staleness)."""
        return bool(self._data is not None
                    and getattr(self._data, "_fresh_grad", False))

    @_fresh_grad.setter
    def _fresh_grad(self, value):
        if self._data is not None:
            self._data._fresh_grad = bool(value)

    # -- raw-buffer access (fused Trainer step) ----------------------------
    def _raw_data(self):
        """The underlying jax array of the weight — what a donated XLA
        program consumes."""
        return self._check_and_get("data")._data

    def _raw_grad(self):
        return self.grad()._data

    def _rebind_data(self, jarr):
        """In-place rebind of the weight handle to a new buffer.

        Every holder of this Parameter shares the one NDArray handle, so
        rebinding here is what makes buffer donation safe: after the
        fused step donates the old weight buffer to XLA, all views
        observe the new buffer through the same handle.
        """
        self._check_and_get("data")._set_data(jarr)

    # -- accessors ---------------------------------------------------------
    def _check_and_get(self, what="data"):
        if self._data is None:
            if self._deferred_init:
                raise DeferredInitializationError(
                    "Parameter %s has not been initialized yet because "
                    "initialization was deferred. Actual initialization "
                    "happens during the first forward pass." % self.name)
            raise RuntimeError(
                "Parameter %s has not been initialized. You should "
                "initialize parameters with Block.collect_params()."
                "initialize(...) before use." % self.name)
        return self._data if what == "data" else self._grad

    def data(self, ctx=None):
        return self._check_and_get("data")

    def list_data(self):
        d = self._check_and_get("data")
        return [d] * max(1, len(self._ctx_list or [None]))

    def grad(self, ctx=None):
        g = self._check_and_get("grad")
        if g is None:
            raise RuntimeError(
                "Cannot get gradient array for Parameter %s because "
                "grad_req='null'" % self.name)
        return g

    def list_grad(self):
        g = self.grad()
        return [g] * max(1, len(self._ctx_list or [None]))

    def list_ctx(self):
        if self._data is None and not self._deferred_init:
            raise RuntimeError("Parameter %s has not been initialized"
                               % self.name)
        return list(self._ctx_list or [current_context()])

    def set_data(self, data):
        """Set this parameter's value everywhere (finishes deferred or
        uninitialized params from the data, reference _load_init)."""
        if self._data is None:
            self._set_shape_if_deferred(data.shape)
            if self._deferred_init:
                init, ctx, default_init = self._deferred_init
                self._finish_init(init, ctx, default_init)
            else:
                ctx = self._ctx_list or [current_context()]
                self._finish_init(initializer.Zero(), ctx,
                                  initializer.Zero())
        if not isinstance(data, nd.NDArray):
            data = nd.array(data, dtype=self.dtype)
        self._data._set_data(data._data.astype(self._data.dtype))

    def zero_grad(self):
        if self._grad is not None:
            self._grad[:] = 0

    def reset_ctx(self, ctx):
        if isinstance(ctx, Context):
            ctx = [ctx]
        self._ctx_list = list(ctx)
        if self._data is not None:
            self._data._set_data(self._data.as_in_context(ctx[0])._data)

    def cast(self, dtype):
        self.dtype = dtype
        if self._data is not None:
            with autograd.pause():
                self._data._set_data(self._data._data.astype(
                    np.dtype(dtype) if not isinstance(dtype, str)
                    else dtype))
            if self._grad is not None:
                self._grad._set_data(self._grad._data.astype(
                    self._data.dtype))

    def var(self):
        """A symbol representing this parameter (reference :399)."""
        shape = self.shape if _shape_known(self.shape) else None
        return _sym.var(self.name, shape=shape, dtype=self.dtype,
                        lr_mult=self.lr_mult, wd_mult=self.wd_mult,
                        init=self.init)


class ParameterDict(object):
    """A dictionary managing Parameters with a common prefix.

    Reference: ``gluon/parameter.py:416``.
    """

    def __init__(self, prefix="", shared=None):
        self._prefix = prefix
        self._params = OrderedDict()
        self._shared = shared

    def __repr__(self):
        s = "%s(\n" % (self._prefix + " " if self._prefix else "")
        s += "\n".join("  " + repr(p) for p in self._params.values())
        return s + "\n)"

    def __getitem__(self, key):
        return self._params[key]

    def __iter__(self):
        return iter(self._params)

    def __len__(self):
        return len(self._params)

    def __contains__(self, key):
        return key in self._params

    def items(self):
        return self._params.items()

    def keys(self):
        return self._params.keys()

    def values(self):
        return self._params.values()

    @property
    def prefix(self):
        return self._prefix

    def _get_impl(self, name):
        if name in self._params:
            return self._params[name]
        if self._shared is not None and name in self._shared._params:
            self._params[name] = self._shared._params[name]
            return self._params[name]
        return None

    def get(self, name, **kwargs):
        """Retrieve or create a Parameter named ``prefix+name``."""
        name = self._prefix + name
        param = self._get_impl(name)
        if param is None:
            param = Parameter(name, **kwargs)
            self._params[name] = param
        else:
            for k, v in kwargs.items():
                if hasattr(param, k) and getattr(param, k) is not None:
                    existing = getattr(param, k)
                    if k == "shape" and v is not None and existing is not None:
                        # merge partial shapes
                        if len(v) == len(existing):
                            merged = tuple(
                                e if e > 0 else n
                                for e, n in zip(existing, v))
                            param.shape = merged
                            continue
                    if v is not None and v != existing:
                        raise AssertionError(
                            "Cannot retrieve Parameter %s because desired "
                            "attribute %s does not match stored: %s vs %s"
                            % (name, k, v, existing))
                elif v is not None:
                    setattr(param, k, v)
        return param

    def update(self, other):
        for k, v in other.items():
            if k in self._params and self._params[k] is not v:
                raise ValueError(
                    "Cannot update self with other because they have "
                    "different Parameters with the same name %s" % k)
            self._params[k] = v

    def initialize(self, init=None, ctx=None, verbose=False,
                   force_reinit=False):
        if init is None:
            init = initializer.Uniform()
        for _, v in self.items():
            v.initialize(None, ctx, init, force_reinit=force_reinit)

    def zero_grad(self):
        for v in self.values():
            v.zero_grad()

    def reset_ctx(self, ctx):
        for v in self.values():
            v.reset_ctx(ctx)

    def setattr(self, name, value):
        for v in self.values():
            setattr(v, name, value)

    def save(self, filename, strip_prefix=""):
        arg_dict = {}
        for param in self.values():
            weight = param.data()
            if not param.name.startswith(strip_prefix):
                raise ValueError(
                    "Prefix %s is to be striped before saving, but "
                    "Parameter %s does not start with it"
                    % (strip_prefix, param.name))
            arg_dict[param.name[len(strip_prefix):]] = weight
        nd.save(filename, arg_dict)

    def load(self, filename, ctx=None, allow_missing=False,
             ignore_extra=False, restore_prefix=""):
        loaded = nd.load(filename)
        arg_dict = {restore_prefix + k.split(":", 1)[-1]: v
                    for k, v in loaded.items()}
        if not allow_missing:
            for name in self.keys():
                if name not in arg_dict:
                    raise IOError(
                        "Parameter %s is missing in file %s"
                        % (name, filename))
        for name in arg_dict:
            if name not in self._params:
                if not ignore_extra:
                    raise IOError(
                        "Parameter %s loaded from file %s is not present "
                        "in ParameterDict" % (name, filename))
                continue
            self[name].set_data(arg_dict[name])
