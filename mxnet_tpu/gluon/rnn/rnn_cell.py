"""Gluon recurrent cells.

API parity with the reference ``python/mxnet/gluon/rnn/rnn_cell.py``
(RecurrentCell protocol with unroll/begin_state, RNN/LSTM/GRU cells,
Sequential/Dropout/Zoneout/Residual/Bidirectional wrappers). Independent
design: the three gated cells derive from one ``_GatedCell`` template that
owns parameter allocation and the fused i2h/h2h projections; each concrete
cell contributes only its gate count and the state-transition math.

TPU note: ``unroll`` builds a python-unrolled graph (length is static
under jit, so XLA fuses it); the fused ``rnn_layer`` variants use
``lax.scan`` and are the fast path for long sequences.
"""
from __future__ import annotations

from ... import ndarray as nd
from ..block import Block, HybridBlock

__all__ = ["RecurrentCell", "HybridRecurrentCell", "RNNCell", "LSTMCell",
           "GRUCell", "SequentialRNNCell", "DropoutCell", "ZoneoutCell",
           "ResidualCell", "BidirectionalCell"]


def _stack_state_info(cells, batch_size):
    infos = []
    for c in cells:
        infos += c.state_info(batch_size)
    return infos


def _stack_begin_state(cells, **kwargs):
    states = []
    for c in cells:
        states += c.begin_state(**kwargs)
    return states


def _as_step_list(length, inputs, layout):
    """Split a merged [*, T, *] tensor (or pass through a list) into
    per-timestep tensors; returns (steps, time_axis, batch_size)."""
    t_axis = layout.find("T")
    n_axis = layout.find("N")
    if isinstance(inputs, (list, tuple)):
        return list(inputs), t_axis, inputs[0].shape[0 if n_axis == 0 else
                                                     n_axis - 1]
    batch_size = inputs.shape[n_axis]
    steps = nd.SliceChannel(inputs, axis=t_axis,
                            num_outputs=inputs.shape[t_axis],
                            squeeze_axis=1)
    if not isinstance(steps, (list, tuple)):
        steps = [steps]
    return list(steps), t_axis, batch_size


def _merge_steps(outputs, t_axis):
    """Stack per-step outputs back into one tensor along the time axis."""
    expanded = [nd.expand_dims(o, axis=t_axis) for o in outputs]
    return nd.concat(*expanded, dim=t_axis)


class RecurrentCell(Block):
    """Recurrent-cell protocol (ref rnn_cell.py:81): step via __call__,
    whole sequences via :meth:`unroll`, states via :meth:`begin_state`."""

    def __init__(self, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._modified = False
        self.reset()

    def reset(self):
        self._init_counter = -1
        self._counter = -1

    def state_info(self, batch_size=0):
        raise NotImplementedError

    def begin_state(self, batch_size=0, func=None, **kwargs):
        """Allocate initial states per :meth:`state_info`
        (ref rnn_cell.py:129)."""
        if self._modified:
            raise AssertionError(
                "After applying modifier cells the base cell cannot be "
                "called directly. Call the modifier cell instead.")
        make = func if func is not None else nd.zeros
        states = []
        for info in self.state_info(batch_size):
            self._init_counter += 1
            spec = dict(info or {})
            spec.pop("__layout__", None)
            spec.update(kwargs)
            states.append(make(**spec))
        return states

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None):
        """Step the cell ``length`` times (ref rnn_cell.py:177)."""
        self.reset()
        steps, t_axis, batch_size = _as_step_list(length, inputs, layout)
        states = begin_state if begin_state is not None \
            else self.begin_state(batch_size=batch_size)
        outputs = []
        for x in steps[:length]:
            out, states = self(x, states)
            outputs.append(out)
        if merge_outputs:
            return _merge_steps(outputs, t_axis), states
        return outputs, states

    def _get_activation(self, F, inputs, activation, **kwargs):
        if isinstance(activation, str):
            return F.Activation(inputs, act_type=activation, **kwargs)
        return activation(inputs, **kwargs)

    def forward(self, inputs, states):
        self._counter += 1
        return super().forward(inputs, states)


class HybridRecurrentCell(RecurrentCell, HybridBlock):
    """RecurrentCell whose step is a hybrid_forward (ref rnn_cell.py:270)."""

    def forward(self, inputs, states):
        self._counter += 1
        return HybridBlock.forward(self, inputs, states)

    def hybrid_forward(self, F, x, *args, **kwargs):
        raise NotImplementedError


class _GatedCell(HybridRecurrentCell):
    """Shared template for RNN/LSTM/GRU cells.

    Owns the four parameter tensors (i2h/h2h × weight/bias), sized by the
    subclass's ``num_gates``, and computes the fused input/hidden
    projections; subclasses implement ``_transition``.
    """

    num_gates = 1

    def __init__(self, hidden_size, i2h_weight_initializer=None,
                 h2h_weight_initializer=None,
                 i2h_bias_initializer="zeros", h2h_bias_initializer="zeros",
                 input_size=0, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._hidden_size = hidden_size
        self._input_size = input_size
        wide = self.num_gates * hidden_size
        for tag, shape, init in (
                ("i2h_weight", (wide, input_size), i2h_weight_initializer),
                ("h2h_weight", (wide, hidden_size), h2h_weight_initializer),
                ("i2h_bias", (wide,), i2h_bias_initializer),
                ("h2h_bias", (wide,), h2h_bias_initializer)):
            setattr(self, tag, self.params.get(tag, shape=shape, init=init,
                                               allow_deferred_init=True))

    def state_info(self, batch_size=0):
        one = {"shape": (batch_size, self._hidden_size), "__layout__": "NC"}
        return [dict(one) for _ in range(self.num_states)]

    num_states = 1

    def hybrid_forward(self, F, inputs, states, i2h_weight, h2h_weight,
                       i2h_bias, h2h_bias):
        wide = self.num_gates * self._hidden_size
        i2h = F.FullyConnected(inputs, i2h_weight, i2h_bias, num_hidden=wide)
        h2h = F.FullyConnected(states[0], h2h_weight, h2h_bias,
                               num_hidden=wide)
        return self._transition(F, i2h, h2h, states)

    def _transition(self, F, i2h, h2h, states):
        raise NotImplementedError


class RNNCell(_GatedCell):
    """Elman cell: ``h' = act(W_i x + b_i + W_h h + b_h)``
    (ref rnn_cell.py:290)."""

    num_gates = 1

    def __init__(self, hidden_size, activation="tanh", **kwargs):
        super().__init__(hidden_size, **kwargs)
        self._activation = activation

    def _alias(self):
        return "rnn"

    def _transition(self, F, i2h, h2h, states):
        out = self._get_activation(F, i2h + h2h, self._activation)
        return out, [out]


class LSTMCell(_GatedCell):
    """LSTM cell (ref rnn_cell.py:374); packed gate order i,f,g,o matches
    the fused RNN op layout."""

    num_gates = 4
    num_states = 2

    def _alias(self):
        return "lstm"

    def _transition(self, F, i2h, h2h, states):
        pre = i2h + h2h
        gi, gf, gc, go = F.SliceChannel(pre, num_outputs=4)
        i = F.Activation(gi, act_type="sigmoid")
        f = F.Activation(gf, act_type="sigmoid")
        c_tilde = F.Activation(gc, act_type="tanh")
        o = F.Activation(go, act_type="sigmoid")
        c = f * states[1] + i * c_tilde
        h = o * F.Activation(c, act_type="tanh")
        return h, [h, c]


class GRUCell(_GatedCell):
    """GRU cell (ref rnn_cell.py:460); packed gate order r,z,n."""

    num_gates = 3

    def _alias(self):
        return "gru"

    def _transition(self, F, i2h, h2h, states):
        prev = states[0]
        ir, iz, in_ = F.SliceChannel(i2h, num_outputs=3)
        hr, hz, hn = F.SliceChannel(h2h, num_outputs=3)
        r = F.Activation(ir + hr, act_type="sigmoid")
        z = F.Activation(iz + hz, act_type="sigmoid")
        candidate = F.Activation(in_ + r * hn, act_type="tanh")
        h = (1. - z) * candidate + z * prev
        return h, [h]


class SequentialRNNCell(RecurrentCell):
    """Vertically stacked cells (ref rnn_cell.py:543); states of the
    children are concatenated in order."""

    def add(self, cell):
        self.register_child(cell)

    def state_info(self, batch_size=0):
        return _stack_state_info(self._children, batch_size)

    def begin_state(self, **kwargs):
        if self._modified:
            raise AssertionError("call the modifier cell instead")
        return _stack_begin_state(self._children, **kwargs)

    def _split_states(self, states):
        """Yield (cell, its slice of the flat state list)."""
        at = 0
        for cell in self._children:
            width = len(cell.state_info())
            yield cell, states[at:at + width]
            at += width

    def __call__(self, inputs, states):
        self._counter += 1
        collected = []
        for cell, sub in self._split_states(states):
            inputs, sub = cell(inputs, sub)
            collected += sub
        return inputs, collected

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None):
        self.reset()
        steps, _, batch_size = _as_step_list(length, inputs, layout)
        if begin_state is None:
            begin_state = self.begin_state(batch_size=batch_size)
        seq = steps
        collected = []
        last = len(self._children) - 1
        for pos, (cell, sub) in enumerate(self._split_states(begin_state)):
            seq, sub = cell.unroll(
                length, inputs=seq, begin_state=sub, layout=layout,
                merge_outputs=merge_outputs if pos == last else None)
            collected += sub
        return seq, collected

    def __getitem__(self, i):
        return self._children[i]

    def __len__(self):
        return len(self._children)

    def forward(self, *args):
        raise NotImplementedError


class ModifierCell(HybridRecurrentCell):
    """Wraps a base cell, sharing its parameters (ref rnn_cell.py:637)."""

    def __init__(self, base_cell):
        if base_cell._modified:
            raise AssertionError("Cell %s is already modified."
                                 % base_cell.name)
        base_cell._modified = True
        super().__init__(prefix=base_cell.prefix + "_", params=None)
        self.base_cell = base_cell

    @property
    def params(self):
        return self.base_cell.params

    def state_info(self, batch_size=0):
        return self.base_cell.state_info(batch_size)

    def begin_state(self, func=None, **kwargs):
        if self._modified:
            raise AssertionError("call the outermost modifier cell")
        self.base_cell._modified = False
        try:
            if func is not None:
                kwargs["func"] = func
            return self.base_cell.begin_state(**kwargs)
        finally:
            self.base_cell._modified = True

    def hybrid_forward(self, F, inputs, states):
        raise NotImplementedError


class DropoutCell(HybridRecurrentCell):
    """Stateless input-dropout pseudo-cell (ref rnn_cell.py:594)."""

    def __init__(self, rate, prefix=None, params=None):
        super().__init__(prefix, params)
        if not isinstance(rate, (int, float)):
            raise TypeError("rate must be a number")
        self.rate = rate

    def state_info(self, batch_size=0):
        return []

    def _alias(self):
        return "dropout"

    def hybrid_forward(self, F, inputs, states):
        if self.rate > 0:
            inputs = F.Dropout(inputs, p=self.rate)
        return inputs, states


class ZoneoutCell(ModifierCell):
    """Zoneout regularisation over the base cell (ref rnn_cell.py:701):
    randomly keep previous outputs/states in place of new ones."""

    def __init__(self, base_cell, zoneout_outputs=0., zoneout_states=0.):
        if isinstance(base_cell, BidirectionalCell):
            raise TypeError(
                "BidirectionalCell doesn't support zoneout. "
                "Please add ZoneoutCell to the cells underneath instead.")
        super().__init__(base_cell)
        self.zoneout_outputs = zoneout_outputs
        self.zoneout_states = zoneout_states
        self._prev_output = None

    def _alias(self):
        return "zoneout"

    def reset(self):
        super().reset()
        self._prev_output = None

    def hybrid_forward(self, F, inputs, states):
        new_out, new_states = self.base_cell(inputs, states)

        def keep_mask(p, like):
            return F.Dropout(F.ones_like(like), p=p)

        prior = self._prev_output
        if prior is None:
            prior = F.zeros_like(new_out)
        out = new_out if self.zoneout_outputs == 0. else \
            F.where(keep_mask(self.zoneout_outputs, new_out), new_out, prior)
        if self.zoneout_states != 0.:
            new_states = [F.where(keep_mask(self.zoneout_states, ns), ns, os)
                          for ns, os in zip(new_states, states)]
        self._prev_output = out
        return out, new_states


class ResidualCell(ModifierCell):
    """output = base_cell(input) + input (ref rnn_cell.py:764)."""

    def hybrid_forward(self, F, inputs, states):
        out, states = self.base_cell(inputs, states)
        return out + inputs, states

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None):
        self.reset()
        self.base_cell._modified = False
        try:
            outputs, states = self.base_cell.unroll(
                length, inputs=inputs, begin_state=begin_state,
                layout=layout, merge_outputs=merge_outputs)
        finally:
            self.base_cell._modified = True
        steps, t_axis, _ = _as_step_list(length, inputs, layout)
        if isinstance(outputs, (list, tuple)):
            outputs = [o + x for o, x in zip(outputs, steps)]
        else:
            outputs = outputs + _merge_steps(steps, t_axis)
        return outputs, states


class BidirectionalCell(HybridRecurrentCell):
    """Forward + reversed cell over the sequence, outputs concatenated
    (ref rnn_cell.py:825). Only ``unroll`` makes sense here."""

    def __init__(self, l_cell, r_cell, output_prefix="bi_"):
        super().__init__(prefix="", params=None)
        self.register_child(l_cell)
        self.register_child(r_cell)
        self._output_prefix = output_prefix

    def __call__(self, inputs, states):
        raise NotImplementedError(
            "Bidirectional cannot be stepped. Please use unroll")

    def state_info(self, batch_size=0):
        return _stack_state_info(self._children, batch_size)

    def begin_state(self, **kwargs):
        if self._modified:
            raise AssertionError("call the modifier cell instead")
        return _stack_begin_state(self._children, **kwargs)

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None):
        self.reset()
        steps, t_axis, batch_size = _as_step_list(length, inputs, layout)
        if begin_state is None:
            begin_state = self.begin_state(batch_size=batch_size)
        fwd_cell, bwd_cell = self._children
        split = len(fwd_cell.state_info())
        fwd_out, fwd_states = fwd_cell.unroll(
            length, inputs=steps, begin_state=begin_state[:split],
            layout=layout, merge_outputs=False)
        bwd_out, bwd_states = bwd_cell.unroll(
            length, inputs=steps[::-1], begin_state=begin_state[split:],
            layout=layout, merge_outputs=False)
        joined = [nd.concat(f, b, dim=1)
                  for f, b in zip(fwd_out, bwd_out[::-1])]
        if merge_outputs:
            return _merge_steps(joined, t_axis), fwd_states + bwd_states
        return joined, fwd_states + bwd_states
