"""Gluon recurrent cells.

Parity surface: reference ``python/mxnet/gluon/rnn/rnn_cell.py`` —
RecurrentCell (unroll/begin_state), RNNCell, LSTMCell, GRUCell,
SequentialRNNCell, DropoutCell, ZoneoutCell, ResidualCell,
BidirectionalCell.

TPU note: ``unroll`` here builds the python-unrolled graph (length is
static under jit, so XLA still fuses it); the fused ``rnn_layer``
variants use ``lax.scan`` and are the fast path.
"""
from __future__ import annotations

from ..block import Block, HybridBlock
from ... import ndarray as nd

__all__ = ["RecurrentCell", "HybridRecurrentCell", "RNNCell", "LSTMCell",
           "GRUCell", "SequentialRNNCell", "DropoutCell", "ZoneoutCell",
           "ResidualCell", "BidirectionalCell"]


def _cells_state_info(cells, batch_size):
    return sum([c.state_info(batch_size) for c in cells], [])


def _cells_begin_state(cells, **kwargs):
    return sum([c.begin_state(**kwargs) for c in cells], [])


def _get_begin_state(cell, F, begin_state, inputs, batch_size):
    if begin_state is None:
        begin_state = cell.begin_state(batch_size=batch_size)
    return begin_state


def _format_sequence(length, inputs, layout, merge, in_layout=None):
    """Normalize inputs to a list of per-step tensors or a merged tensor."""
    assert inputs is not None
    axis = layout.find("T")
    batch_axis = layout.find("N")
    if isinstance(inputs, (list, tuple)):
        in_axis = in_layout.find("T") if in_layout else axis
        if merge is True:
            inputs = [nd.expand_dims(i, axis=in_axis) for i in inputs]
            inputs = nd.concat(*inputs, dim=in_axis)
            seq = inputs
            batch_size = seq.shape[batch_axis]
            return seq, axis, batch_size
        batch_size = inputs[0].shape[0 if layout.find("N") == 0 else
                                     batch_axis]
        return list(inputs), axis, inputs[0].shape[batch_axis - 1
                                                   if batch_axis > axis
                                                   else batch_axis]
    batch_size = inputs.shape[batch_axis]
    if merge is False:
        outs = nd.SliceChannel(inputs, axis=axis,
                               num_outputs=inputs.shape[axis],
                               squeeze_axis=1)
        if not isinstance(outs, (list, tuple)):
            outs = [outs]
        return list(outs), axis, batch_size
    return inputs, axis, batch_size


class RecurrentCell(Block):
    """Abstract base class for RNN cells (reference rnn_cell.py:81)."""

    def __init__(self, prefix=None, params=None):
        super(RecurrentCell, self).__init__(prefix=prefix, params=params)
        self._modified = False
        self.reset()

    def reset(self):
        self._init_counter = -1
        self._counter = -1

    def state_info(self, batch_size=0):
        raise NotImplementedError

    def begin_state(self, batch_size=0, func=None, **kwargs):
        """Initial states for this cell (reference rnn_cell.py:129)."""
        assert not self._modified, \
            "After applying modifier cells the base cell cannot be called " \
            "directly. Call the modifier cell instead."
        if func is None:
            func = nd.zeros
        states = []
        for info in self.state_info(batch_size):
            self._init_counter += 1
            info = dict(info or {})
            info.pop("__layout__", None)
            info.update(kwargs)
            states.append(func(**info))
        return states

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None):
        """Unroll the cell for ``length`` steps (reference rnn_cell.py:177)."""
        self.reset()
        inputs, axis, batch_size = _format_sequence(
            length, inputs, layout, False)
        begin_state = _get_begin_state(self, nd, begin_state, inputs,
                                       batch_size)
        states = begin_state
        outputs = []
        for i in range(length):
            output, states = self(inputs[i], states)
            outputs.append(output)
        if merge_outputs:
            outputs = [nd.expand_dims(o, axis=axis) for o in outputs]
            outputs = nd.concat(*outputs, dim=axis)
        return outputs, states

    def _get_activation(self, F, inputs, activation, **kwargs):
        if isinstance(activation, str):
            return F.Activation(inputs, act_type=activation, **kwargs)
        return activation(inputs, **kwargs)

    def forward(self, inputs, states):
        self._counter += 1
        return super(RecurrentCell, self).forward(inputs, states)


class HybridRecurrentCell(RecurrentCell, HybridBlock):
    """RecurrentCell with hybrid_forward (reference rnn_cell.py:270)."""

    def __init__(self, prefix=None, params=None):
        super(HybridRecurrentCell, self).__init__(prefix=prefix,
                                                  params=params)

    def forward(self, inputs, states):
        self._counter += 1
        return HybridBlock.forward(self, inputs, states)

    def hybrid_forward(self, F, x, *args, **kwargs):
        raise NotImplementedError


class RNNCell(HybridRecurrentCell):
    """Elman RNN cell: ``h' = act(W_i x + b_i + W_h h + b_h)``
    (reference rnn_cell.py:290)."""

    def __init__(self, hidden_size, activation="tanh",
                 i2h_weight_initializer=None, h2h_weight_initializer=None,
                 i2h_bias_initializer="zeros", h2h_bias_initializer="zeros",
                 input_size=0, prefix=None, params=None):
        super(RNNCell, self).__init__(prefix=prefix, params=params)
        self._hidden_size = hidden_size
        self._activation = activation
        self._input_size = input_size
        self.i2h_weight = self.params.get(
            "i2h_weight", shape=(hidden_size, input_size),
            init=i2h_weight_initializer, allow_deferred_init=True)
        self.h2h_weight = self.params.get(
            "h2h_weight", shape=(hidden_size, hidden_size),
            init=h2h_weight_initializer, allow_deferred_init=True)
        self.i2h_bias = self.params.get(
            "i2h_bias", shape=(hidden_size,),
            init=i2h_bias_initializer, allow_deferred_init=True)
        self.h2h_bias = self.params.get(
            "h2h_bias", shape=(hidden_size,),
            init=h2h_bias_initializer, allow_deferred_init=True)

    def state_info(self, batch_size=0):
        return [{"shape": (batch_size, self._hidden_size),
                 "__layout__": "NC"}]

    def _alias(self):
        return "rnn"

    def hybrid_forward(self, F, inputs, states, i2h_weight, h2h_weight,
                       i2h_bias, h2h_bias):
        i2h = F.FullyConnected(inputs, i2h_weight, i2h_bias,
                               num_hidden=self._hidden_size)
        h2h = F.FullyConnected(states[0], h2h_weight, h2h_bias,
                               num_hidden=self._hidden_size)
        output = self._get_activation(F, i2h + h2h, self._activation)
        return output, [output]


class LSTMCell(HybridRecurrentCell):
    """LSTM cell (reference rnn_cell.py:374); gate order i,f,g,o matches
    the fused RNN op's packed layout."""

    def __init__(self, hidden_size, i2h_weight_initializer=None,
                 h2h_weight_initializer=None,
                 i2h_bias_initializer="zeros", h2h_bias_initializer="zeros",
                 input_size=0, prefix=None, params=None):
        super(LSTMCell, self).__init__(prefix=prefix, params=params)
        self._hidden_size = hidden_size
        self._input_size = input_size
        self.i2h_weight = self.params.get(
            "i2h_weight", shape=(4 * hidden_size, input_size),
            init=i2h_weight_initializer, allow_deferred_init=True)
        self.h2h_weight = self.params.get(
            "h2h_weight", shape=(4 * hidden_size, hidden_size),
            init=h2h_weight_initializer, allow_deferred_init=True)
        self.i2h_bias = self.params.get(
            "i2h_bias", shape=(4 * hidden_size,),
            init=i2h_bias_initializer, allow_deferred_init=True)
        self.h2h_bias = self.params.get(
            "h2h_bias", shape=(4 * hidden_size,),
            init=h2h_bias_initializer, allow_deferred_init=True)

    def state_info(self, batch_size=0):
        return [{"shape": (batch_size, self._hidden_size),
                 "__layout__": "NC"},
                {"shape": (batch_size, self._hidden_size),
                 "__layout__": "NC"}]

    def _alias(self):
        return "lstm"

    def hybrid_forward(self, F, inputs, states, i2h_weight, h2h_weight,
                       i2h_bias, h2h_bias):
        i2h = F.FullyConnected(inputs, i2h_weight, i2h_bias,
                               num_hidden=4 * self._hidden_size)
        h2h = F.FullyConnected(states[0], h2h_weight, h2h_bias,
                               num_hidden=4 * self._hidden_size)
        gates = i2h + h2h
        slices = F.SliceChannel(gates, num_outputs=4)
        in_gate = F.Activation(slices[0], act_type="sigmoid")
        forget_gate = F.Activation(slices[1], act_type="sigmoid")
        in_transform = F.Activation(slices[2], act_type="tanh")
        out_gate = F.Activation(slices[3], act_type="sigmoid")
        next_c = forget_gate * states[1] + in_gate * in_transform
        next_h = out_gate * F.Activation(next_c, act_type="tanh")
        return next_h, [next_h, next_c]


class GRUCell(HybridRecurrentCell):
    """GRU cell (reference rnn_cell.py:460); gate order r,z,n."""

    def __init__(self, hidden_size, i2h_weight_initializer=None,
                 h2h_weight_initializer=None,
                 i2h_bias_initializer="zeros", h2h_bias_initializer="zeros",
                 input_size=0, prefix=None, params=None):
        super(GRUCell, self).__init__(prefix=prefix, params=params)
        self._hidden_size = hidden_size
        self._input_size = input_size
        self.i2h_weight = self.params.get(
            "i2h_weight", shape=(3 * hidden_size, input_size),
            init=i2h_weight_initializer, allow_deferred_init=True)
        self.h2h_weight = self.params.get(
            "h2h_weight", shape=(3 * hidden_size, hidden_size),
            init=h2h_weight_initializer, allow_deferred_init=True)
        self.i2h_bias = self.params.get(
            "i2h_bias", shape=(3 * hidden_size,),
            init=i2h_bias_initializer, allow_deferred_init=True)
        self.h2h_bias = self.params.get(
            "h2h_bias", shape=(3 * hidden_size,),
            init=h2h_bias_initializer, allow_deferred_init=True)

    def state_info(self, batch_size=0):
        return [{"shape": (batch_size, self._hidden_size),
                 "__layout__": "NC"}]

    def _alias(self):
        return "gru"

    def hybrid_forward(self, F, inputs, states, i2h_weight, h2h_weight,
                       i2h_bias, h2h_bias):
        prev_h = states[0]
        i2h = F.FullyConnected(inputs, i2h_weight, i2h_bias,
                               num_hidden=3 * self._hidden_size)
        h2h = F.FullyConnected(prev_h, h2h_weight, h2h_bias,
                               num_hidden=3 * self._hidden_size)
        i2h_r, i2h_z, i2h_n = F.SliceChannel(i2h, num_outputs=3)
        h2h_r, h2h_z, h2h_n = F.SliceChannel(h2h, num_outputs=3)
        reset_gate = F.Activation(i2h_r + h2h_r, act_type="sigmoid")
        update_gate = F.Activation(i2h_z + h2h_z, act_type="sigmoid")
        next_h_tmp = F.Activation(i2h_n + reset_gate * h2h_n,
                                  act_type="tanh")
        next_h = (1. - update_gate) * next_h_tmp + update_gate * prev_h
        return next_h, [next_h]


class SequentialRNNCell(RecurrentCell):
    """Stacks multiple cells (reference rnn_cell.py:543)."""

    def __init__(self, prefix=None, params=None):
        super(SequentialRNNCell, self).__init__(prefix=prefix, params=params)

    def add(self, cell):
        self.register_child(cell)

    def state_info(self, batch_size=0):
        return _cells_state_info(self._children, batch_size)

    def begin_state(self, **kwargs):
        assert not self._modified
        return _cells_begin_state(self._children, **kwargs)

    def __call__(self, inputs, states):
        self._counter += 1
        next_states = []
        p = 0
        for cell in self._children:
            n = len(cell.state_info())
            state = states[p:p + n]
            p += n
            inputs, state = cell(inputs, state)
            next_states.extend(state)
        return inputs, next_states

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None):
        self.reset()
        inputs, _, batch_size = _format_sequence(length, inputs, layout,
                                                 None)
        num_cells = len(self._children)
        begin_state = _get_begin_state(self, nd, begin_state, inputs,
                                       batch_size)
        p = 0
        next_states = []
        for i, cell in enumerate(self._children):
            n = len(cell.state_info())
            states = begin_state[p:p + n]
            p += n
            inputs, states = cell.unroll(
                length, inputs=inputs, begin_state=states, layout=layout,
                merge_outputs=None if i < num_cells - 1 else merge_outputs)
            next_states.extend(states)
        return inputs, next_states

    def __getitem__(self, i):
        return self._children[i]

    def __len__(self):
        return len(self._children)

    def forward(self, *args):
        raise NotImplementedError


class ModifierCell(HybridRecurrentCell):
    """Base class for cells that wrap another cell
    (reference rnn_cell.py:637)."""

    def __init__(self, base_cell):
        assert not base_cell._modified, \
            "Cell %s is already modified." % base_cell.name
        base_cell._modified = True
        super(ModifierCell, self).__init__(prefix=base_cell.prefix + "_",
                                           params=None)
        self.base_cell = base_cell

    @property
    def params(self):
        return self.base_cell.params

    def state_info(self, batch_size=0):
        return self.base_cell.state_info(batch_size)

    def begin_state(self, func=None, **kwargs):
        assert not self._modified
        self.base_cell._modified = False
        begin = self.base_cell.begin_state(func=func, **kwargs) \
            if func is not None else self.base_cell.begin_state(**kwargs)
        self.base_cell._modified = True
        return begin

    def hybrid_forward(self, F, inputs, states):
        raise NotImplementedError


class DropoutCell(HybridRecurrentCell):
    """Applies dropout on input (reference rnn_cell.py:594)."""

    def __init__(self, rate, prefix=None, params=None):
        super(DropoutCell, self).__init__(prefix, params)
        assert isinstance(rate, (int, float))
        self.rate = rate

    def state_info(self, batch_size=0):
        return []

    def _alias(self):
        return "dropout"

    def hybrid_forward(self, F, inputs, states):
        if self.rate > 0:
            inputs = F.Dropout(inputs, p=self.rate)
        return inputs, states


class ZoneoutCell(ModifierCell):
    """Applies Zoneout on base cell (reference rnn_cell.py:701)."""

    def __init__(self, base_cell, zoneout_outputs=0., zoneout_states=0.):
        assert not isinstance(base_cell, BidirectionalCell), \
            "BidirectionalCell doesn't support zoneout. " \
            "Please add ZoneoutCell to the cells underneath instead."
        super(ZoneoutCell, self).__init__(base_cell)
        self.zoneout_outputs = zoneout_outputs
        self.zoneout_states = zoneout_states
        self._prev_output = None

    def _alias(self):
        return "zoneout"

    def reset(self):
        super(ZoneoutCell, self).reset()
        self._prev_output = None

    def hybrid_forward(self, F, inputs, states):
        cell, p_outputs, p_states = (self.base_cell, self.zoneout_outputs,
                                     self.zoneout_states)
        next_output, next_states = cell(inputs, states)
        mask = lambda p, like: F.Dropout(F.ones_like(like), p=p)
        prev_output = self._prev_output
        if prev_output is None:
            prev_output = F.zeros_like(next_output)
        output = (F.where(mask(p_outputs, next_output), next_output,
                          prev_output)
                  if p_outputs != 0. else next_output)
        new_states = ([F.where(mask(p_states, new_s), new_s, old_s)
                       for new_s, old_s in zip(next_states, states)]
                      if p_states != 0. else next_states)
        self._prev_output = output
        return output, new_states


class ResidualCell(ModifierCell):
    """Adds residual connection (reference rnn_cell.py:764)."""

    def hybrid_forward(self, F, inputs, states):
        output, states = self.base_cell(inputs, states)
        output = output + inputs
        return output, states

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None):
        self.reset()
        self.base_cell._modified = False
        outputs, states = self.base_cell.unroll(
            length, inputs=inputs, begin_state=begin_state, layout=layout,
            merge_outputs=merge_outputs)
        self.base_cell._modified = True
        if isinstance(outputs, (list, tuple)):
            inputs, _, _ = _format_sequence(length, inputs, layout, False)
            outputs = [o + i for o, i in zip(outputs, inputs)]
        else:
            inputs, _, _ = _format_sequence(length, inputs, layout, True)
            outputs = outputs + inputs
        return outputs, states


class BidirectionalCell(HybridRecurrentCell):
    """Runs two cells over the sequence in both directions
    (reference rnn_cell.py:825)."""

    def __init__(self, l_cell, r_cell, output_prefix="bi_"):
        super(BidirectionalCell, self).__init__(prefix="", params=None)
        self.register_child(l_cell)
        self.register_child(r_cell)
        self._output_prefix = output_prefix

    def __call__(self, inputs, states):
        raise NotImplementedError(
            "Bidirectional cannot be stepped. Please use unroll")

    def state_info(self, batch_size=0):
        return _cells_state_info(self._children, batch_size)

    def begin_state(self, **kwargs):
        assert not self._modified
        return _cells_begin_state(self._children, **kwargs)

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None):
        self.reset()
        inputs, axis, batch_size = _format_sequence(length, inputs, layout,
                                                    False)
        begin_state = _get_begin_state(self, nd, begin_state, inputs,
                                       batch_size)
        states = begin_state
        l_cell, r_cell = self._children
        l_outputs, l_states = l_cell.unroll(
            length, inputs=inputs,
            begin_state=states[:len(l_cell.state_info())],
            layout=layout, merge_outputs=False)
        r_outputs, r_states = r_cell.unroll(
            length, inputs=list(reversed(inputs)),
            begin_state=states[len(l_cell.state_info()):],
            layout=layout, merge_outputs=False)
        outputs = [nd.concat(l_o, r_o, dim=1)
                   for l_o, r_o in zip(l_outputs, reversed(r_outputs))]
        if merge_outputs:
            outputs = [nd.expand_dims(o, axis=axis) for o in outputs]
            outputs = nd.concat(*outputs, dim=axis)
        states = l_states + r_states
        return outputs, states
