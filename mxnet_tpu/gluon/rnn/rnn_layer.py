"""Gluon fused RNN layers (RNN / LSTM / GRU).

Parity surface: reference ``python/mxnet/gluon/rnn/rnn_layer.py`` —
``_RNNLayer`` holding per-layer/direction i2h/h2h weights, forwarding
through the fused ``RNN`` op (reference ``src/operator/rnn-inl.h:44``,
cuDNN at ``cudnn_rnn-inl.h``).

TPU-native: the fused op is a ``lax.scan`` over time with the gate matmuls
batched per step (MXU-friendly); weights are packed into the same flat
layout the reference uses, so checkpoints round-trip.
"""
from __future__ import annotations

from ..block import HybridBlock
from ... import ndarray as nd
from ...ops.nn import rnn_param_size, _gates

__all__ = ["RNN", "LSTM", "GRU"]


class _RNNLayer(HybridBlock):
    """Base fused RNN layer (reference rnn_layer.py:33)."""

    def __init__(self, hidden_size, num_layers, layout, dropout,
                 bidirectional, input_size, i2h_weight_initializer,
                 h2h_weight_initializer, i2h_bias_initializer,
                 h2h_bias_initializer, mode, **kwargs):
        super(_RNNLayer, self).__init__(**kwargs)
        assert layout in ("TNC", "NTC"), \
            "Invalid layout %s; must be one of ['TNC' or 'NTC']" % layout
        self._hidden_size = hidden_size
        self._num_layers = num_layers
        self._mode = mode
        self._layout = layout
        self._dropout = dropout
        self._dir = 2 if bidirectional else 1
        self._input_size = input_size
        self._i2h_weight_initializer = i2h_weight_initializer
        self._h2h_weight_initializer = h2h_weight_initializer
        self._i2h_bias_initializer = i2h_bias_initializer
        self._h2h_bias_initializer = h2h_bias_initializer

        self._gates = _gates(mode)
        ng, ni, nh = self._gates, input_size, hidden_size
        for i in range(num_layers):
            for j in (["l", "r"] if self._dir == 2 else ["l"]):
                self._register_param(
                    "{}{}_i2h_weight".format(j, i), (ng * nh, ni),
                    i2h_weight_initializer)
                self._register_param(
                    "{}{}_h2h_weight".format(j, i), (ng * nh, nh),
                    h2h_weight_initializer)
                self._register_param(
                    "{}{}_i2h_bias".format(j, i), (ng * nh,),
                    i2h_bias_initializer)
                self._register_param(
                    "{}{}_h2h_bias".format(j, i), (ng * nh,),
                    h2h_bias_initializer)
            ni = nh * self._dir

    def _register_param(self, name, shape, init):
        p = self.params.get(name, shape=shape, init=init,
                            allow_deferred_init=True)
        setattr(self, name, p)
        return p

    def __repr__(self):
        s = "{name}({mapping}, {_layout}"
        if self._num_layers != 1:
            s += ", num_layers={_num_layers}"
        if self._dropout != 0:
            s += ", dropout={_dropout}"
        if self._dir == 2:
            s += ", bidirectional"
        s += ")"
        shape = self.l0_i2h_weight.shape
        mapping = "{0} -> {1}".format(
            shape[1] if shape[1] else None, shape[0] // self._gates)
        return s.format(name=self.__class__.__name__, mapping=mapping,
                        **self.__dict__)

    def state_info(self, batch_size=0):
        raise NotImplementedError

    def begin_state(self, batch_size=0, func=None, **kwargs):
        if func is None:
            func = nd.zeros
        states = []
        for i, info in enumerate(self.state_info(batch_size)):
            info = dict(info)
            info.pop("__layout__", None)
            info.update(kwargs)
            states.append(func(**info))
        return states

    def _collect_ordered_params(self):
        """Pack parameters in the fused op's flat layout
        (per layer, per dir: i2h_W, h2h_W, i2h_b, h2h_b)."""
        flat = []
        for i in range(self._num_layers):
            for j in (["l", "r"] if self._dir == 2 else ["l"]):
                for t in ["i2h_weight", "h2h_weight", "i2h_bias",
                          "h2h_bias"]:
                    p = getattr(self, "{}{}_{}".format(j, i, t))
                    flat.append(p.data().reshape((-1,)))
        return nd.concat(*flat, dim=0)

    def forward(self, inputs, states=None):
        batch_size = inputs.shape[self._layout.find("N")]
        # finish deferred init: layer-0 i2h shape depends on input channels
        in_size = inputs.shape[2]
        for j in (["l", "r"] if self._dir == 2 else ["l"]):
            p = getattr(self, "%s0_i2h_weight" % j)
            if p._data is None:
                p._set_shape_if_deferred((self._gates * self._hidden_size,
                                          in_size))
        for param in self.collect_params().values():
            param._finish_deferred_init()
        skip_states = states is None
        if skip_states:
            states = self.begin_state(batch_size, ctx=inputs.context)
        if isinstance(states, nd.NDArray):
            states = [states]
        for state, info in zip(states, self.state_info(batch_size)):
            if state.shape != info["shape"]:
                raise ValueError(
                    "Invalid recurrent state shape. Expecting %s, got %s."
                    % (str(info["shape"]), str(state.shape)))
        out = self._forward_kernel(inputs, states)
        # out is (output, states)
        return out[0] if skip_states else out

    def _forward_kernel(self, inputs, states):
        if self._layout == "NTC":
            inputs = nd.swapaxes(inputs, 0, 1)
        params = self._collect_ordered_params()
        rnn_args = [inputs, params] + states
        outs = nd.RNN(*rnn_args, state_size=self._hidden_size,
                      num_layers=self._num_layers,
                      bidirectional=self._dir == 2,
                      p=self._dropout, state_outputs=True,
                      mode=self._mode)
        if not isinstance(outs, (list, tuple)):
            outs = [outs]
        output = outs[0]
        if self._layout == "NTC":
            output = nd.swapaxes(output, 0, 1)
        return output, list(outs[1:])

    def hybrid_forward(self, F, x, *args, **kwargs):
        raise NotImplementedError


class RNN(_RNNLayer):
    """Multi-layer Elman RNN with tanh/relu (reference rnn_layer.py:244)."""

    def __init__(self, hidden_size, num_layers=1, activation="relu",
                 layout="TNC", dropout=0, bidirectional=False,
                 i2h_weight_initializer=None, h2h_weight_initializer=None,
                 i2h_bias_initializer="zeros", h2h_bias_initializer="zeros",
                 input_size=0, **kwargs):
        super(RNN, self).__init__(
            hidden_size, num_layers, layout, dropout, bidirectional,
            input_size, i2h_weight_initializer, h2h_weight_initializer,
            i2h_bias_initializer, h2h_bias_initializer,
            "rnn_" + activation, **kwargs)

    def state_info(self, batch_size=0):
        return [{"shape": (self._num_layers * self._dir, batch_size,
                           self._hidden_size), "__layout__": "LNC"}]


class LSTM(_RNNLayer):
    """Multi-layer LSTM (reference rnn_layer.py:318)."""

    def __init__(self, hidden_size, num_layers=1, layout="TNC", dropout=0,
                 bidirectional=False, input_size=0,
                 i2h_weight_initializer=None, h2h_weight_initializer=None,
                 i2h_bias_initializer="zeros", h2h_bias_initializer="zeros",
                 **kwargs):
        super(LSTM, self).__init__(
            hidden_size, num_layers, layout, dropout, bidirectional,
            input_size, i2h_weight_initializer, h2h_weight_initializer,
            i2h_bias_initializer, h2h_bias_initializer, "lstm", **kwargs)

    def state_info(self, batch_size=0):
        return [{"shape": (self._num_layers * self._dir, batch_size,
                           self._hidden_size), "__layout__": "LNC"},
                {"shape": (self._num_layers * self._dir, batch_size,
                           self._hidden_size), "__layout__": "LNC"}]


class GRU(_RNNLayer):
    """Multi-layer GRU (reference rnn_layer.py:397)."""

    def __init__(self, hidden_size, num_layers=1, layout="TNC", dropout=0,
                 bidirectional=False, input_size=0,
                 i2h_weight_initializer=None, h2h_weight_initializer=None,
                 i2h_bias_initializer="zeros", h2h_bias_initializer="zeros",
                 **kwargs):
        super(GRU, self).__init__(
            hidden_size, num_layers, layout, dropout, bidirectional,
            input_size, i2h_weight_initializer, h2h_weight_initializer,
            i2h_bias_initializer, h2h_bias_initializer, "gru", **kwargs)

    def state_info(self, batch_size=0):
        return [{"shape": (self._num_layers * self._dir, batch_size,
                           self._hidden_size), "__layout__": "LNC"}]
