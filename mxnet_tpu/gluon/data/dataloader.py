"""Gluon DataLoader (reference python/mxnet/gluon/data/dataloader.py:41).

TPU note: batches feed one device/mesh; host-side batchification stacks
numpy then uploads once per batch (minimizing host↔device transfers).
An optional background-thread prefetcher hides host latency (the
reference's PrefetchingIter doctrine, SURVEY §3.5).
"""
from __future__ import annotations

import threading
import queue as _queue

import numpy as np

from ... import ndarray as nd
from .sampler import SequentialSampler, RandomSampler, BatchSampler
from . import sampler as _sampler

__all__ = ["DataLoader"]


def default_batchify_fn(data):
    """Stack a list of samples into a batch."""
    if isinstance(data[0], nd.NDArray):
        return nd.stack(*data)
    if isinstance(data[0], tuple):
        data = zip(*data)
        return [default_batchify_fn(i) for i in data]
    data = np.asarray(data)
    return nd.array(data, dtype=data.dtype)


class DataLoader(object):
    """Loads data from a Dataset and returns mini-batches."""

    def __init__(self, dataset, batch_size=None, shuffle=False,
                 sampler=None, last_batch=None, batch_sampler=None,
                 batchify_fn=None, num_workers=0):
        self._dataset = dataset
        if batch_sampler is None:
            if batch_size is None:
                raise ValueError(
                    "batch_size must be specified unless batch_sampler "
                    "is specified")
            if sampler is None:
                if shuffle:
                    sampler = RandomSampler(len(dataset))
                else:
                    sampler = SequentialSampler(len(dataset))
            elif shuffle:
                raise ValueError(
                    "shuffle must not be specified if sampler is "
                    "specified")
            batch_sampler = BatchSampler(
                sampler, batch_size, last_batch if last_batch else "keep")
        elif batch_size is not None or shuffle or sampler is not None or \
                last_batch is not None:
            raise ValueError(
                "batch_size, shuffle, sampler and last_batch must not be "
                "specified if batch_sampler is specified.")
        self._batch_sampler = batch_sampler
        self._batchify_fn = batchify_fn or default_batchify_fn
        self._num_workers = num_workers  # prefetch depth (thread-based)

    def __iter__(self):
        if self._num_workers == 0:
            for batch in self._batch_sampler:
                yield self._batchify_fn(
                    [self._dataset[idx] for idx in batch])
            return
        # background-thread prefetch pipeline
        q = _queue.Queue(maxsize=max(2, self._num_workers))
        sentinel = object()

        def worker():
            try:
                for batch in self._batch_sampler:
                    q.put(self._batchify_fn(
                        [self._dataset[idx] for idx in batch]))
                q.put(sentinel)
            except BaseException as exc:  # propagate to the consumer
                q.put(exc)

        t = threading.Thread(target=worker, daemon=True)
        t.start()
        while True:
            item = q.get()
            if item is sentinel:
                break
            if isinstance(item, BaseException):
                raise item
            yield item

    def __len__(self):
        return len(self._batch_sampler)
