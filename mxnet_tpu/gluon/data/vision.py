"""Gluon vision datasets (reference python/mxnet/gluon/data/vision.py:73-291).

This build runs with zero network egress: if the canonical dataset files
exist under ``root`` they are parsed (same formats as the reference —
MNIST idx files, CIFAR binary batches); otherwise a deterministic
synthetic fixture with the right shapes/classes is generated so training
integration tests stay runnable hermetically.
"""
from __future__ import annotations

import gzip
import os
import struct

import numpy as np

from .dataset import _DownloadedDataset, RecordFileDataset
from ... import ndarray as nd
from ... import image as _image_mod
from ... import recordio

__all__ = ["MNIST", "FashionMNIST", "CIFAR10", "CIFAR100",
           "ImageRecordDataset", "ImageFolderDataset"]


def _synthetic(num, shape, num_classes, seed):
    rng = np.random.RandomState(seed)
    data = rng.randint(0, 256, size=(num,) + shape).astype(np.uint8)
    label = rng.randint(0, num_classes, size=(num,)).astype(np.int32)
    return data, label


class MNIST(_DownloadedDataset):
    """MNIST (reference vision.py:73).  Reads idx-ubyte files if present
    under root, else synthesizes a small fixture."""

    _train_files = ("train-images-idx3-ubyte.gz",
                    "train-labels-idx1-ubyte.gz")
    _test_files = ("t10k-images-idx3-ubyte.gz",
                   "t10k-labels-idx1-ubyte.gz")
    _synth_num = 1024

    def __init__(self, root=os.path.join("~", ".mxnet", "datasets",
                                         "mnist"),
                 train=True, transform=None):
        super(MNIST, self).__init__(root, train, transform)

    def _get_data(self):
        files = self._train_files if self._train else self._test_files
        data_file = os.path.join(self._root, files[0])
        label_file = os.path.join(self._root, files[1])
        if os.path.isfile(data_file) and os.path.isfile(label_file):
            with gzip.open(label_file, "rb") as fin:
                struct.unpack(">II", fin.read(8))
                label = np.frombuffer(fin.read(), dtype=np.uint8) \
                    .astype(np.int32)
            with gzip.open(data_file, "rb") as fin:
                struct.unpack(">IIII", fin.read(16))
                data = np.frombuffer(fin.read(), dtype=np.uint8)
                data = data.reshape(len(label), 28, 28, 1)
        else:
            data, label = _synthetic(self._synth_num, (28, 28, 1), 10,
                                     42 if self._train else 43)
        self._label = label
        self._data = nd.array(data, dtype=np.uint8)

    def __getitem__(self, idx):
        data = self._data[idx].astype(np.float32)
        if self._transform is not None:
            return self._transform(data, self._label[idx])
        return data, self._label[idx]


class FashionMNIST(MNIST):
    """FashionMNIST (reference vision.py:120); same file format."""

    def __init__(self, root=os.path.join("~", ".mxnet", "datasets",
                                         "fashion-mnist"),
                 train=True, transform=None):
        super(FashionMNIST, self).__init__(root, train, transform)


class CIFAR10(_DownloadedDataset):
    """CIFAR10 (reference vision.py:154).  Reads the binary batch files
    if present, else synthesizes."""

    _synth_num = 1024
    _num_classes = 10

    def __init__(self, root=os.path.join("~", ".mxnet", "datasets",
                                         "cifar10"),
                 train=True, transform=None):
        super(CIFAR10, self).__init__(root, train, transform)

    def _read_batch(self, filename):
        with open(filename, "rb") as fin:
            raw = np.frombuffer(fin.read(), dtype=np.uint8)
        rec = raw.reshape(-1, 3072 + 1)
        return rec[:, 1:].reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1), \
            rec[:, 0].astype(np.int32)

    def _get_data(self):
        if self._train:
            files = ["data_batch_%d.bin" % i for i in range(1, 6)]
        else:
            files = ["test_batch.bin"]
        paths = [os.path.join(self._root, f) for f in files]
        if all(os.path.isfile(p) for p in paths):
            parts = [self._read_batch(p) for p in paths]
            data = np.concatenate([p[0] for p in parts])
            label = np.concatenate([p[1] for p in parts])
        else:
            data, label = _synthetic(self._synth_num, (32, 32, 3),
                                     self._num_classes,
                                     44 if self._train else 45)
        self._data = nd.array(data, dtype=np.uint8)
        self._label = label

    def __getitem__(self, idx):
        data = self._data[idx].astype(np.float32)
        if self._transform is not None:
            return self._transform(data, self._label[idx])
        return data, self._label[idx]


class CIFAR100(CIFAR10):
    """CIFAR100 (reference vision.py:195)."""

    _num_classes = 100

    def __init__(self, root=os.path.join("~", ".mxnet", "datasets",
                                         "cifar100"),
                 fine_label=False, train=True, transform=None):
        self._fine_label = fine_label
        super(CIFAR100, self).__init__(root, train, transform)

    def _get_data(self):
        files = ["train.bin"] if self._train else ["test.bin"]
        paths = [os.path.join(self._root, f) for f in files]
        if all(os.path.isfile(p) for p in paths):
            with open(paths[0], "rb") as fin:
                raw = np.frombuffer(fin.read(), dtype=np.uint8)
            rec = raw.reshape(-1, 3072 + 2)
            data = rec[:, 2:].reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1)
            label = rec[:, 1 if self._fine_label else 0].astype(np.int32)
        else:
            data, label = _synthetic(
                self._synth_num, (32, 32, 3),
                100 if self._fine_label else 20,
                46 if self._train else 47)
        self._data = nd.array(data, dtype=np.uint8)
        self._label = label


class ImageRecordDataset(RecordFileDataset):
    """Images packed in a RecordIO file (reference vision.py:240)."""

    def __init__(self, filename, flag=1, transform=None):
        super(ImageRecordDataset, self).__init__(filename)
        self._flag = flag
        self._transform = transform

    def __getitem__(self, idx):
        record = super(ImageRecordDataset, self).__getitem__(idx)
        header, img = recordio.unpack(record)
        img = _image_mod.imdecode(img, self._flag)
        label = header.label
        if self._transform is not None:
            return self._transform(img, label)
        return img, label


class ImageFolderDataset(_DownloadedDataset):
    """A dataset of images arranged in class folders
    (reference vision.py:273)."""

    def __init__(self, root, flag=1, transform=None):
        self._flag = flag
        self._exts = [".jpg", ".jpeg", ".png"]
        # note: bypasses _DownloadedDataset synthesis - folder must exist
        self._root = os.path.expanduser(root)
        self._transform = transform
        self._list_images(self._root)

    def _list_images(self, root):
        self.synsets = []
        self.items = []
        for folder in sorted(os.listdir(root)):
            path = os.path.join(root, folder)
            if not os.path.isdir(path):
                continue
            label = len(self.synsets)
            self.synsets.append(folder)
            for filename in sorted(os.listdir(path)):
                filename = os.path.join(path, filename)
                ext = os.path.splitext(filename)[1]
                if ext.lower() not in self._exts:
                    continue
                self.items.append((filename, float(label)))

    def __getitem__(self, idx):
        with open(self.items[idx][0], "rb") as f:
            img = _image_mod.imdecode(f.read(), self._flag)
        label = self.items[idx][1]
        if self._transform is not None:
            return self._transform(img, label)
        return img, label

    def __len__(self):
        return len(self.items)
