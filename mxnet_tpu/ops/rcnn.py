"""Faster-RCNN ops: Proposal / MultiProposal, PSROIPooling, deformable
convolution and PSROI pooling.

Reference analogue: ``src/operator/contrib/proposal{-inl.h,.cc}``,
``multi_proposal.cc``, ``psroi_pooling.cc``, ``deformable_convolution.cc``,
``deformable_psroi_pooling.cc`` — the op layer behind ``example/rcnn``.

TPU-first redesign: all kernels are fixed-shape vectorised jax. The
reference's proposal op sorts/filters/NMS-es with dynamic result counts;
here the output is the standard fixed ``rpn_post_nms_top_n`` rows with
suppressed entries zeroed (the convention downstream ROI pooling expects).
Deformable sampling is bilinear gather — a dense einsum-friendly form the
MXU handles well, not the reference's per-sample scalar loop.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from .registry import register
from .ssd import _iou_matrix

__all__ = []


def _as_floats(v):
    if isinstance(v, (int, float)):
        return (float(v),)
    return tuple(float(x) for x in v)


# ---------------------------------------------------------------------------
# anchors + box transform in pixel coordinates (RCNN convention)
# ---------------------------------------------------------------------------

def _base_anchors(scales, ratios, base_size):
    """(A, 4) anchors centered on (base/2-0.5, base/2-0.5), pixel coords."""
    base = float(base_size)
    cx = cy = (base - 1.0) / 2.0
    anchors = []
    area = base * base
    for r in ratios:
        w = jnp.round(jnp.sqrt(area / r))
        h = jnp.round(w * r)
        for s in scales:
            ws, hs = w * s, h * s
            anchors.append(jnp.stack([cx - (ws - 1) / 2, cy - (hs - 1) / 2,
                                      cx + (ws - 1) / 2, cy + (hs - 1) / 2]))
    return jnp.stack(anchors)


def _bbox_transform_inv(boxes, deltas):
    """Apply (dx, dy, dw, dh) deltas to pixel-coord corner boxes."""
    w = boxes[:, 2] - boxes[:, 0] + 1.0
    h = boxes[:, 3] - boxes[:, 1] + 1.0
    cx = boxes[:, 0] + 0.5 * (w - 1.0)
    cy = boxes[:, 1] + 0.5 * (h - 1.0)
    pcx = deltas[:, 0] * w + cx
    pcy = deltas[:, 1] * h + cy
    pw = jnp.exp(deltas[:, 2]) * w
    ph = jnp.exp(deltas[:, 3]) * h
    return jnp.stack([pcx - 0.5 * (pw - 1.0), pcy - 0.5 * (ph - 1.0),
                      pcx + 0.5 * (pw - 1.0), pcy + 0.5 * (ph - 1.0)],
                     axis=1)


def _proposal_one(scores, deltas, im_info, anchors_grid, pre_n, post_n,
                  nms_thresh, min_size):
    """RPN proposals for one sample.

    scores (A_total,), deltas (A_total, 4) in feature order; returns
    (post_n, 5) rois [batch0, x0, y0, x1, y1] and (post_n, 1) scores.
    """
    height, width, scale = im_info[0], im_info[1], im_info[2]
    boxes = _bbox_transform_inv(anchors_grid, deltas)
    boxes = jnp.stack([jnp.clip(boxes[:, 0], 0, width - 1.0),
                       jnp.clip(boxes[:, 1], 0, height - 1.0),
                       jnp.clip(boxes[:, 2], 0, width - 1.0),
                       jnp.clip(boxes[:, 3], 0, height - 1.0)], axis=1)
    ws = boxes[:, 2] - boxes[:, 0] + 1.0
    hs = boxes[:, 3] - boxes[:, 1] + 1.0
    ms = min_size * scale
    valid = (ws >= ms) & (hs >= ms)
    scores = jnp.where(valid, scores, -jnp.inf)

    pre_n = min(pre_n, scores.shape[0])
    top_scores, top_idx = lax.top_k(scores, pre_n)
    top_boxes = boxes[top_idx]

    # greedy NMS over the score-ordered top_k (static trip count)
    alive = top_scores > -jnp.inf

    def body(i, alive):
        ious = _iou_matrix(top_boxes[i][None, :], top_boxes)[0]
        kill = (ious > nms_thresh) & (jnp.arange(pre_n) > i) & alive[i]
        return alive & ~kill

    alive = lax.fori_loop(0, pre_n, body, alive)

    # stable-compact the survivors into the first post_n slots
    order = jnp.argsort(~alive, stable=True)      # survivors first
    keep = order[:post_n]
    kept_boxes = jnp.where(alive[keep][:, None], top_boxes[keep], 0.0)
    kept_scores = jnp.where(alive[keep], top_scores[keep], 0.0)
    rois = jnp.concatenate([jnp.zeros((post_n, 1), kept_boxes.dtype),
                            kept_boxes], axis=1)
    return rois, kept_scores[:, None]


def _grid_anchors(feat_h, feat_w, stride, scales, ratios):
    base = _base_anchors(scales, ratios, stride)          # (A, 4)
    sx = jnp.arange(feat_w, dtype=jnp.float32) * stride
    sy = jnp.arange(feat_h, dtype=jnp.float32) * stride
    shift_y, shift_x = jnp.meshgrid(sy, sx, indexing="ij")
    shifts = jnp.stack([shift_x, shift_y, shift_x, shift_y],
                       axis=-1).reshape(-1, 1, 4)          # (HW, 1, 4)
    return (shifts + base[None, :, :]).reshape(-1, 4)      # (HW*A, 4)


def _proposal_impl(cls_prob, bbox_pred, im_info, rpn_pre_nms_top_n,
                   rpn_post_nms_top_n, threshold, rpn_min_size, scales,
                   ratios, feature_stride, output_score):
    n, a2, h, w = cls_prob.shape
    num_anchors = a2 // 2
    anchors_grid = _grid_anchors(h, w, float(feature_stride), scales, ratios)

    # foreground scores: channels [A:2A]; layout (N, A, H, W) -> (N, HW*A)
    fg = cls_prob[:, num_anchors:, :, :]
    scores = jnp.transpose(fg, (0, 2, 3, 1)).reshape(n, -1)
    deltas = jnp.transpose(
        bbox_pred.reshape(n, num_anchors, 4, h, w),
        (0, 3, 4, 1, 2)).reshape(n, -1, 4)

    fn = lambda s, d, info: _proposal_one(
        s, d, info, anchors_grid, int(rpn_pre_nms_top_n),
        int(rpn_post_nms_top_n), float(threshold), float(rpn_min_size))
    rois, score = jax.vmap(fn)(scores, deltas, im_info)
    # batch index column
    idx = jnp.arange(n, dtype=rois.dtype)[:, None, None]
    rois = rois.at[:, :, 0:1].set(jnp.broadcast_to(idx, rois[:, :, :1].shape))
    rois = rois.reshape(-1, 5)
    if output_score:
        return rois, score.reshape(-1, 1)
    return rois


@register("_contrib_Proposal", nondiff_inputs=(0, 1, 2),
          num_outputs=lambda a: 2 if a.get("output_score", False) else 1)
def _proposal(cls_prob, bbox_pred, im_info, rpn_pre_nms_top_n=6000,
              rpn_post_nms_top_n=300, threshold=0.7, rpn_min_size=16,
              scales=(4, 8, 16, 32), ratios=(0.5, 1, 2),
              feature_stride=16, output_score=False, iou_loss=False, **kw):
    """RPN proposal generation (ref contrib/proposal-inl.h).

    cls_prob (N, 2A, H, W); bbox_pred (N, 4A, H, W); im_info (N, 3).
    Output rois (N*post_nms_top_n, 5): [batch_idx, x0, y0, x1, y1].
    """
    return _proposal_impl(cls_prob, bbox_pred, im_info, rpn_pre_nms_top_n,
                          rpn_post_nms_top_n, threshold, rpn_min_size,
                          _as_floats(scales), _as_floats(ratios),
                          feature_stride, output_score)


@register("_contrib_MultiProposal", nondiff_inputs=(0, 1, 2),
          num_outputs=lambda a: 2 if a.get("output_score", False) else 1)
def _multi_proposal(cls_prob, bbox_pred, im_info, **kw):
    """Batch variant of Proposal (ref contrib/multi_proposal.cc) — the
    vectorised implementation already maps over the batch."""
    return _proposal(cls_prob, bbox_pred, im_info, **kw)


# ---------------------------------------------------------------------------
# PSROIPooling
# ---------------------------------------------------------------------------

def _psroi_pool_one(data, roi, spatial_scale, group_size, pooled_size,
                    output_dim):
    """Position-sensitive ROI average pooling for one roi.

    data (C, H, W) with C = output_dim * group_size^2; roi (5,).
    Output (output_dim, pooled, pooled).
    """
    c, h, w = data.shape
    g, p = group_size, pooled_size
    x0 = roi[1] * spatial_scale
    y0 = roi[2] * spatial_scale
    x1 = roi[3] * spatial_scale
    y1 = roi[4] * spatial_scale
    rw = jnp.maximum(x1 - x0, 0.1)
    rh = jnp.maximum(y1 - y0, 0.1)
    bin_w, bin_h = rw / p, rh / p

    # sample a fixed 2x2 grid inside each bin (bilinear) — fixed shapes
    # instead of the reference's variable-extent integer bins
    offs = jnp.array([0.25, 0.75], jnp.float32)
    px = x0 + (jnp.arange(p)[:, None] + offs[None, :]) * bin_w   # (p, 2)
    py = y0 + (jnp.arange(p)[:, None] + offs[None, :]) * bin_h
    px = jnp.clip(px, 0, w - 1.0).reshape(-1)                    # (2p,)
    py = jnp.clip(py, 0, h - 1.0).reshape(-1)

    x_lo = jnp.floor(px).astype(jnp.int32)
    y_lo = jnp.floor(py).astype(jnp.int32)
    x_hi = jnp.minimum(x_lo + 1, w - 1)
    y_hi = jnp.minimum(y_lo + 1, h - 1)
    fx = px - x_lo
    fy = py - y_lo

    def gather(yi, xi):
        return data[:, yi, :][:, :, xi]                          # (C,2p,2p)

    v = (gather(y_lo, x_lo) * ((1 - fy)[:, None] * (1 - fx)[None, :])
         + gather(y_lo, x_hi) * ((1 - fy)[:, None] * fx[None, :])
         + gather(y_hi, x_lo) * (fy[:, None] * (1 - fx)[None, :])
         + gather(y_hi, x_hi) * (fy[:, None] * fx[None, :]))
    # (C, 2p, 2p) -> (C, p, 2, p, 2) -> bin average (C, p, p)
    v = v.reshape(c, p, 2, p, 2).mean(axis=(2, 4))

    # position-sensitive channel selection: output channel d at bin (i, j)
    # reads input channel (d * g + gi) * g + gj with gi = i*g//p etc.
    gi = (jnp.arange(p) * g) // p
    gj = (jnp.arange(p) * g) // p
    chan = ((jnp.arange(output_dim)[:, None, None] * g + gi[None, :, None])
            * g + gj[None, None, :])                             # (D, p, p)
    ii = jnp.arange(p)[None, :, None]
    jj = jnp.arange(p)[None, None, :]
    return v[chan, ii, jj]


@register("_contrib_PSROIPooling", nondiff_inputs=(1,))
def _psroi_pooling(data, rois, spatial_scale=1.0, output_dim=1,
                   pooled_size=1, group_size=0, **kw):
    """Position-sensitive ROI pooling (ref contrib/psroi_pooling.cc).

    data (N, D*g*g, H, W); rois (R, 5) [batch, x0, y0, x1, y1].
    Output (R, output_dim, pooled, pooled).
    """
    group_size = int(group_size) or int(pooled_size)
    batch_idx = rois[:, 0].astype(jnp.int32)
    per_roi_data = data[batch_idx]                    # (R, C, H, W)
    fn = lambda d, r: _psroi_pool_one(d, r, float(spatial_scale),
                                      group_size, int(pooled_size),
                                      int(output_dim))
    return jax.vmap(fn)(per_roi_data, rois)


# ---------------------------------------------------------------------------
# Deformable convolution / PSROI pooling
# ---------------------------------------------------------------------------

def _bilinear_sample_chw(img, ys, xs):
    """Sample (C, H, W) at float coords ys/xs (...,) → (C, ...).

    Coordinates clamp to the valid range and the high gather index clamps
    separately, so integer coordinates sample exactly (no edge blending).
    """
    c, h, w = img.shape
    ys = jnp.clip(ys, 0.0, h - 1.0)
    xs = jnp.clip(xs, 0.0, w - 1.0)
    y0 = jnp.floor(ys).astype(jnp.int32)
    x0 = jnp.floor(xs).astype(jnp.int32)
    y1 = jnp.minimum(y0 + 1, h - 1)
    x1 = jnp.minimum(x0 + 1, w - 1)
    fy, fx = ys - y0, xs - x0
    flat = img.reshape(c, -1)

    def at(yy, xx):
        return flat[:, yy * w + xx]

    return (at(y0, x0) * (1 - fy) * (1 - fx)
            + at(y0, x1) * (1 - fy) * fx
            + at(y1, x0) * fy * (1 - fx)
            + at(y1, x1) * fy * fx)


def _deform_conv_one(img, offs, weight, bias, kernel, stride, pad, dilate,
                     num_deformable_group, num_group=1):
    """Deformable conv for one sample.

    img (Cin, H, W); offs (2*dg*kh*kw, Ho, Wo); weight (Cout, Cin, kh, kw).
    """
    cin, h, w = img.shape
    kh, kw = kernel
    sh, sw = stride
    ph, pw = pad
    dh, dw = dilate
    ho = (h + 2 * ph - dh * (kh - 1) - 1) // sh + 1
    wo = (w + 2 * pw - dw * (kw - 1) - 1) // sw + 1
    dg = num_deformable_group
    cpg = cin // dg

    offs = offs.reshape(dg, kh, kw, 2, ho, wo)
    cols = []
    for g in range(dg):
        oy = offs[g, :, :, 0]                            # (kh, kw, Ho, Wo)
        ox = offs[g, :, :, 1]
        ys = (jnp.arange(ho)[None, None, :, None] * sh - ph
              + jnp.arange(kh)[:, None, None, None] * dh + oy)
        xs = (jnp.arange(wo)[None, None, None, :] * sw - pw
              + jnp.arange(kw)[None, :, None, None] * dw + ox)
        sampled = _bilinear_sample_chw(
            img[g * cpg:(g + 1) * cpg],
            ys.astype(jnp.float32), xs.astype(jnp.float32))
        cols.append(sampled)                             # (cpg, kh,kw,Ho,Wo)
    col = jnp.concatenate(cols, axis=0)                  # (Cin, kh,kw,Ho,Wo)
    cout = weight.shape[0]
    if num_group > 1:
        # grouped conv: weight is (Cout, Cin/groups, kh, kw); contract
        # each output group against its input-channel slice
        cpg_in = cin // num_group
        cpg_out = cout // num_group
        col_g = col.reshape(num_group, cpg_in, kh, kw, -1)
        w_g = weight.reshape(num_group, cpg_out, cpg_in, kh, kw)
        out = jnp.einsum("gckrx,gockr->gox", col_g, w_g)
        out = out.reshape(cout, *col.shape[3:])
    else:
        out = jnp.einsum("ckrhw,ockr->ohw", col, weight)
    if bias is not None:
        out = out + bias[:, None, None]
    return out


@register("_contrib_DeformableConvolution", nondiff_inputs=(),
          attr_defaults={"no_bias": False})
def _deformable_convolution(data, offset, weight, *maybe_bias,
                            kernel=(3, 3), stride=(1, 1), pad=(0, 0),
                            dilate=(1, 1), num_filter=0, num_group=1,
                            num_deformable_group=1, no_bias=False,
                            workspace=1024, **kw):
    """Deformable convolution v1 (ref contrib/deformable_convolution.cc):
    per-position learned offsets deform the sampling grid; implemented as
    bilinear gather + einsum (dense, MXU-friendly)."""
    bias = None if (no_bias or not maybe_bias) else maybe_bias[0]
    kernel = tuple(int(k) for k in kernel)
    stride = tuple(int(s) for s in stride)
    pad = tuple(int(p) for p in pad)
    dilate = tuple(int(d) for d in dilate)
    fn = lambda img, offs: _deform_conv_one(
        img, offs, weight, bias, kernel, stride, pad, dilate,
        int(num_deformable_group), int(num_group))
    return jax.vmap(fn)(data, offset)


@register("_contrib_DeformablePSROIPooling", nondiff_inputs=(1,))
def _deformable_psroi_pooling(data, rois, *maybe_trans, spatial_scale=1.0,
                              output_dim=1, group_size=1, pooled_size=1,
                              part_size=0, sample_per_part=1,
                              trans_std=0.0, no_trans=False, **kw):
    """Deformable PSROI pooling (ref contrib/deformable_psroi_pooling.cc).

    With ``no_trans`` (or absent trans input) this is PSROIPooling; the
    trans tensor (R, 2*D, part, part) shifts each bin by
    ``trans * trans_std * roi_extent`` before sampling.
    """
    group_size = int(group_size) or int(pooled_size)
    p = int(pooled_size)
    trans = None if (no_trans or not maybe_trans) else maybe_trans[0]

    batch_idx = rois[:, 0].astype(jnp.int32)
    per_roi = data[batch_idx]

    def one(d, r, t):
        base = _psroi_pool_one(d, r, float(spatial_scale), group_size, p,
                               int(output_dim))
        if t is None:
            return base
        # bin-shift: offset each pooled bin by the (dy, dx) field, scaled
        # by roi extent — sample the shifted roi and reuse the PS pooling
        rw = (r[3] - r[1]) * float(spatial_scale)
        rh = (r[4] - r[2]) * float(spatial_scale)
        ps = int(part_size) or p
        ty = t[0::2].reshape(-1, ps, ps).mean(axis=0)    # (ps, ps)
        tx = t[1::2].reshape(-1, ps, ps).mean(axis=0)
        # average shift over parts → one (dy, dx) per roi (coarse but
        # fixed-shape); apply to the roi then pool
        dy = jnp.mean(ty) * float(trans_std) * rh
        dx = jnp.mean(tx) * float(trans_std) * rw
        shifted = jnp.stack([r[0], r[1] + dx / float(spatial_scale),
                             r[2] + dy / float(spatial_scale),
                             r[3] + dx / float(spatial_scale),
                             r[4] + dy / float(spatial_scale)])
        return _psroi_pool_one(d, shifted, float(spatial_scale), group_size,
                               p, int(output_dim))

    if trans is None:
        return jax.vmap(lambda d, r: one(d, r, None))(per_roi, rois)
    return jax.vmap(one)(per_roi, rois, trans)
