"""Reduction / broadcasting-shape ops.

Reference analogue: ``src/operator/tensor/broadcast_reduce_op_{value,index}.cc``
(SURVEY §2.2 — sum/mean/prod/nansum/nanprod/max/min/argmax/argmin/norm/
broadcast_axis/broadcast_to).  MXNet reduce attrs kept: ``axis`` (None = all),
``keepdims``, ``exclude`` (reduce over the complement of ``axis``).
"""
from __future__ import annotations

import jax.numpy as jnp

from .registry import register


def _norm_axis(axis, ndim, exclude=False):
    if axis is None:
        ax = tuple(range(ndim))
    elif isinstance(axis, int):
        ax = (axis % ndim,)
    else:
        ax = tuple(a % ndim for a in axis)
    if exclude:
        ax = tuple(i for i in range(ndim) if i not in ax)
    return ax


def _mk_reduce(fn):
    def red(x, axis=None, keepdims=False, exclude=False, **kw):
        ax = _norm_axis(axis, x.ndim, exclude)
        return fn(x, axis=ax, keepdims=bool(keepdims))
    return red


for _n, _fn in {
    "sum": jnp.sum, "mean": jnp.mean, "prod": jnp.prod,
    "nansum": jnp.nansum, "nanprod": jnp.nanprod,
    "max": jnp.max, "min": jnp.min,
}.items():
    register(_n, aliases=["%s_axis" % _n] if _n in ("sum", "max", "min") else [])(
        _mk_reduce(_fn))


def _mk_arg_reduce(fn):
    def red(x, axis=None, keepdims=False, **kw):
        if axis is None:
            out = fn(x.reshape(-1), axis=0)
            if keepdims:
                out = out.reshape((1,) * x.ndim)
            return out.astype(x.dtype)
        out = fn(x, axis=int(axis))
        if keepdims:
            out = jnp.expand_dims(out, int(axis))
        return out.astype(x.dtype)
    return red


register("argmax")(_mk_arg_reduce(jnp.argmax))
register("argmin")(_mk_arg_reduce(jnp.argmin))


@register("argmax_channel")
def _argmax_channel(x, **kw):
    return jnp.argmax(x, axis=1).astype(x.dtype)


@register("norm")
def _norm(x, ord=2, axis=None, keepdims=False, **kw):
    if axis is None:
        return jnp.sqrt(jnp.sum(jnp.square(x))).reshape((1,))
    ax = axis if isinstance(axis, int) else tuple(axis)
    if ord == 1:
        return jnp.sum(jnp.abs(x), axis=ax, keepdims=bool(keepdims))
    return jnp.sqrt(jnp.sum(jnp.square(x), axis=ax, keepdims=bool(keepdims)))


@register("_square_sum")
def _square_sum(x, axis=None, keepdims=False, **kw):
    ax = _norm_axis(axis, x.ndim)
    return jnp.sum(jnp.square(x), axis=ax, keepdims=bool(keepdims))


@register("broadcast_axis", aliases=["broadcast_axes"])
def _broadcast_axis(x, axis=(), size=(), **kw):
    if isinstance(axis, int):
        axis, size = (axis,), (size,)
    shape = list(x.shape)
    for a, s in zip(axis, size):
        shape[a] = s
    return jnp.broadcast_to(x, tuple(shape))


@register("broadcast_to")
def _broadcast_to(x, shape=(), **kw):
    # mxnet allows 0 meaning "keep this dim"
    tgt = tuple(x.shape[i] if s == 0 else s for i, s in enumerate(shape))
    return jnp.broadcast_to(x, tgt)


@register("broadcast_like", nondiff_inputs=(1,))
def _broadcast_like(x, like, **kw):
    return jnp.broadcast_to(x, like.shape)
