"""The ``Custom`` operator: user-defined Python ops inside the graph.

Reference analogue: ``src/operator/custom/custom.cc:49-250`` (the C++
Custom op that trampolines into Python callbacks registered through
``python/mxnet/operator.py``'s CustomOpProp table). TPU-first redesign:
the user's numpy ``forward``/``backward`` run on the *host* behind
``jax.pure_callback`` — so a Custom op can sit anywhere in a jitted or
differentiated XLA program, with shapes/dtypes resolved at trace time
from the prop's ``infer_shape``/``infer_type``. Users who want the op to
run *on-chip* should instead register a pure-jax/Pallas function via
``mxnet_tpu.ops.register`` (see ops/pallas_kernels.py for the pattern).

The user-facing classes (CustomOp / CustomOpProp / register) live in
``mxnet_tpu/operator.py``; this module holds the prop registry and the
graph-op plumbing so the op exists before the nd/sym namespaces are
generated.
"""
from __future__ import annotations

import numpy as np
import jax

from ..base import MXNetError
from .registry import register

# op_type -> CustomOpProp subclass
CUSTOM_PROP_REGISTRY = {}


def register_prop(reg_name, prop_cls):
    CUSTOM_PROP_REGISTRY[reg_name] = prop_cls


def _instantiate(attrs):
    """Build the user's CustomOpProp from the op attrs (kwargs arrive as
    strings, matching the reference contract)."""
    spec = {k: v for k, v in attrs.items() if k != "op_type"}
    op_type = attrs.get("op_type")
    if not op_type:
        raise MXNetError("Custom op requires op_type=")
    if op_type not in CUSTOM_PROP_REGISTRY:
        raise MXNetError("Custom op type %r is not registered "
                         "(use mxnet_tpu.operator.register)" % op_type)
    return CUSTOM_PROP_REGISTRY[op_type](**{k: str(v)
                                            for k, v in spec.items()})


def _resolve(prop, arrays):
    """Shapes/dtypes of args, outputs, aux from the prop's inference."""
    n_args = len(prop.list_arguments())
    in_shapes = [list(a.shape) for a in arrays[:n_args]]
    shaped = prop.infer_shape(in_shapes)
    arg_shapes, out_shapes = shaped[0], shaped[1]
    aux_shapes = shaped[2] if len(shaped) > 2 else []
    in_types = [np.dtype(a.dtype) for a in arrays[:n_args]]
    typed = prop.infer_type(in_types)
    out_types = typed[1]
    aux_types = typed[2] if len(typed) > 2 else []
    return (n_args, arg_shapes, out_shapes, aux_shapes,
            in_types, out_types, aux_types)


class HostArray(object):
    """numpy-backed NDArray stand-in handed to CustomOp callbacks.

    The callbacks run on XLA's callback thread while the enclosing program
    is still in flight; dispatching device ops from there can deadlock the
    runtime, so user code sees a pure-host array (the reference's
    numpy-ops contract: read via ``asnumpy()``, write via ``assign``/
    slicing). Anything jax stays out of the callback.
    """

    def __init__(self, buf):
        self._np = np.asarray(buf)

    # ---- NDArray-surface the numpy-ops examples rely on ----
    @property
    def shape(self):
        return self._np.shape

    @property
    def dtype(self):
        return self._np.dtype

    @property
    def size(self):
        return self._np.size

    @property
    def ndim(self):
        return self._np.ndim

    def asnumpy(self):
        return self._np

    def __array__(self, dtype=None):
        return self._np if dtype is None else self._np.astype(dtype)

    def copy(self):
        return HostArray(self._np.copy())

    def astype(self, dtype):
        return HostArray(self._np.astype(dtype))

    def reshape(self, *shape):
        if len(shape) == 1 and isinstance(shape[0], (tuple, list)):
            shape = tuple(shape[0])
        return HostArray(self._np.reshape(shape))

    def __getitem__(self, key):
        return HostArray(self._np[key])

    def __setitem__(self, key, value):
        if hasattr(value, "asnumpy"):
            value = value.asnumpy()
        self._np[key] = value

    def __repr__(self):
        return "HostArray(%r)" % (self._np,)

    def _binary(self, other, fn):
        if hasattr(other, "asnumpy"):
            other = other.asnumpy()
        return HostArray(fn(self._np, other))

    def __add__(self, o):
        return self._binary(o, np.add)

    __radd__ = __add__

    def __sub__(self, o):
        return self._binary(o, np.subtract)

    def __rsub__(self, o):
        return self._binary(o, lambda a, b: b - a)

    def __mul__(self, o):
        return self._binary(o, np.multiply)

    __rmul__ = __mul__

    def __truediv__(self, o):
        return self._binary(o, np.divide)

    def __neg__(self):
        return HostArray(-self._np)


def _nd_wrap_list(host_arrays):
    """numpy buffers → HostArray shims for user callbacks (jax-free)."""
    return [HostArray(np.asarray(a)) for a in host_arrays]


def _n_outputs(attrs):
    return len(_instantiate(attrs).list_outputs())


def _custom_forward(*arrays, train_mode=False, **attrs):
    prop = _instantiate(attrs)
    if prop.list_auxiliary_states():
        import warnings
        warnings.warn(
            "Custom op %r declares auxiliary states; they are passed to the "
            "callbacks read-only — in-place aux mutation does not propagate "
            "back to the graph on the TPU build" % attrs.get("op_type"),
            stacklevel=2)
    (n_args, _arg_shapes, out_shapes, _aux_shapes,
     in_types, out_types, _aux_types) = _resolve(prop, arrays)
    result_spec = tuple(
        jax.ShapeDtypeStruct(tuple(s), np.dtype(t))
        for s, t in zip(out_shapes, out_types))

    def host_forward(*host_arrays):
        ins = _nd_wrap_list(host_arrays[:n_args])
        auxs = _nd_wrap_list(host_arrays[n_args:])
        outs = [HostArray(np.zeros(tuple(s), dtype=np.dtype(t)))
                for s, t in zip(out_shapes, out_types)]
        op = prop.create_operator(None, [list(a.shape) for a in ins],
                                  [a.dtype for a in ins])
        op.forward(is_train=train_mode, req=["write"] * len(outs),
                   in_data=ins, out_data=outs, aux=auxs)
        return tuple(np.asarray(o.asnumpy(), dtype=np.dtype(t))
                     for o, t in zip(outs, out_types))

    out = jax.pure_callback(host_forward, result_spec, *arrays,
                            vmap_method="sequential")
    return out if len(result_spec) > 1 else (out[0]
                                             if isinstance(out, (tuple, list))
                                             else out)


def _custom_backward(gout, arrs, out, attrs):
    prop = _instantiate(attrs)
    n_args = len(prop.list_arguments())
    grad_spec = tuple(jax.ShapeDtypeStruct(a.shape, a.dtype)
                      for a in arrs[:n_args])
    n_out = len(out)

    def host_backward(*flat):
        grads_in = _nd_wrap_list(flat[:n_out])            # out_grad
        ins = _nd_wrap_list(flat[n_out:n_out + n_args])   # in_data
        auxs = _nd_wrap_list(flat[n_out + n_args:n_out + len(arrs)])
        outs = _nd_wrap_list(flat[n_out + len(arrs):])    # out_data
        igrads = [HostArray(np.zeros(a.shape, dtype=a.dtype))
                  for a in ins]
        op = prop.create_operator(None, [list(a.shape) for a in ins],
                                  [a.dtype for a in ins])
        op.backward(req=["write"] * len(igrads), out_grad=grads_in,
                    in_data=ins, out_data=outs, in_grad=igrads, aux=auxs)
        return tuple(np.asarray(g.asnumpy()) for g in igrads)

    grads = jax.pure_callback(host_backward, grad_spec, *gout, *arrs, *out,
                              vmap_method="sequential")
    if not isinstance(grads, (tuple, list)):
        grads = (grads,)
    # auxiliary-state inputs receive zero gradient
    import jax.numpy as jnp
    aux_zero = tuple(jnp.zeros_like(a) for a in arrs[n_args:])
    return tuple(grads) + aux_zero


register("Custom", num_outputs=_n_outputs, takes_mode=True,
         custom_vjp=_custom_backward)(_custom_forward)
