"""Operator corpus: importing this package populates the registry."""
from .registry import Op, register, get_op, list_ops, OP_REGISTRY  # noqa: F401
from . import elemwise  # noqa: F401
from . import reduce  # noqa: F401
from . import matrix  # noqa: F401
from . import nn  # noqa: F401
from . import random_ops  # noqa: F401
from . import optim_ops  # noqa: F401
from . import contrib  # noqa: F401
from . import custom  # noqa: F401
from . import ssd  # noqa: F401
from . import rcnn  # noqa: F401
