"""contrib ops: CTC loss, quantization, FFT, count_sketch.

Parity surface: reference ``src/operator/contrib/`` — ``ctc_loss.cc``
(warp-ctc style CTC), ``quantize.cc``/``dequantize.cc``, ``fft.cc``/
``ifft.cc``, ``count_sketch.cc``.

TPU-native: CTC is the classic forward-alpha dynamic program expressed as
``lax.scan`` over time (compiler-friendly control flow; no host sync),
vmapped over the batch.  FFT maps to jnp.fft; quantize to scaled casts.
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from .registry import register

NEG_INF = -1e30


def _ctc_single(logp, ext, ext_valid, T_len, S_len):
    """CTC -log p(label|data) for one sequence.

    logp: (T, C) log-softmax scores; ext: (S,) extended label seq
    (blank interleaved); ext_valid: (S,) bool; T_len, S_len: actual lengths.
    """
    T, C = logp.shape
    S = ext.shape[0]
    idx = jnp.arange(S)
    # allowed skip transition s-2 -> s: s odd (a label) and ext[s]!=ext[s-2]
    prev2 = jnp.where(idx >= 2, ext[jnp.maximum(idx - 2, 0)], -1)
    can_skip = (idx % 2 == 1) & (ext != prev2) & (idx >= 2)

    alpha0 = jnp.full((S,), NEG_INF)
    alpha0 = alpha0.at[0].set(logp[0, ext[0]])
    alpha0 = jnp.where((idx == 1) & (S_len > 1),
                       alpha0.at[1].set(logp[0, ext[1]]), alpha0)

    def step(alpha, t):
        a_prev1 = jnp.concatenate([jnp.array([NEG_INF]), alpha[:-1]])
        a_prev2 = jnp.concatenate([jnp.full((2,), NEG_INF), alpha[:-2]])
        a_prev2 = jnp.where(can_skip, a_prev2, NEG_INF)
        stacked = jnp.stack([alpha, a_prev1, a_prev2])
        merged = jax.nn.logsumexp(stacked, axis=0)
        new = merged + logp[t, ext]
        new = jnp.where(ext_valid, new, NEG_INF)
        # freeze after the true sequence length (supports data_lengths)
        new = jnp.where(t < T_len, new, alpha)
        return new, None

    alpha, _ = lax.scan(step, alpha0, jnp.arange(1, T))
    last = alpha[jnp.maximum(S_len - 1, 0)]
    last2 = jnp.where(S_len >= 2, alpha[jnp.maximum(S_len - 2, 0)], NEG_INF)
    ll = jax.nn.logsumexp(jnp.stack([last, last2]))
    return -ll


@register("_contrib_CTCLoss", aliases=["ctc_loss", "CTCLoss"],
          num_outputs=2, num_visible_outputs=1, nondiff_inputs=(1, 2, 3))
def _ctc_loss(data, label, *opt, use_data_lengths=False,
              use_label_lengths=False, blank_label="first", **kw):
    """data: (T, N, C) activations; label: (N, L) padded labels."""
    opt = list(opt)
    data_lengths = opt.pop(0) if use_data_lengths else None
    label_lengths = opt.pop(0) if use_label_lengths else None
    T, N, C = data.shape
    L = label.shape[1]
    logp = jax.nn.log_softmax(data.astype(jnp.float32), axis=-1)

    lab = label.astype(jnp.int32)
    if blank_label == "first":
        blank = 0
        # real labels are 1..C-1; padding value 0
        if label_lengths is None:
            lab_len = jnp.sum((lab != 0).astype(jnp.int32), axis=1)
        else:
            lab_len = label_lengths.astype(jnp.int32)
    else:
        blank = C - 1
        if label_lengths is None:
            lab_len = jnp.sum((lab != -1).astype(jnp.int32), axis=1)
        else:
            lab_len = label_lengths.astype(jnp.int32)
    d_len = (data_lengths.astype(jnp.int32) if data_lengths is not None
             else jnp.full((N,), T, jnp.int32))

    S = 2 * L + 1
    sidx = jnp.arange(S)

    def extend(labels_n, len_n):
        lab_pos = (sidx - 1) // 2
        ext = jnp.where(sidx % 2 == 1,
                        labels_n[jnp.clip(lab_pos, 0, L - 1)], blank)
        valid = sidx < 2 * len_n + 1
        return ext, valid, 2 * len_n + 1

    def one(logp_n, labels_n, dl, ll):
        ext, valid, s_len = extend(labels_n, ll)
        return _ctc_single(logp_n, ext, valid, dl, s_len)

    logp_bn = jnp.transpose(logp, (1, 0, 2))  # (N, T, C)
    loss = jax.vmap(one)(logp_bn, lab, d_len, lab_len)
    return loss.astype(data.dtype), jnp.zeros_like(data)


@register("_contrib_quantize", num_outputs=3, nondiff_inputs=(0, 1, 2))
def _quantize(data, min_range, max_range, out_type="uint8", **kw):
    if out_type == "uint8":
        qmin, qmax, qdt = 0.0, 255.0, jnp.uint8
    else:  # int8
        qmin, qmax, qdt = -127.0, 127.0, jnp.int8
    mn = min_range.reshape(())
    mx_ = max_range.reshape(())
    scale = (qmax - qmin) / (mx_ - mn)
    q = jnp.clip(jnp.round((data - mn) * scale + qmin), qmin, qmax)
    return q.astype(qdt), mn.reshape((1,)), mx_.reshape((1,))


@register("_contrib_dequantize", nondiff_inputs=(0, 1, 2))
def _dequantize(data, min_range, max_range, out_type="float32", **kw):
    if data.dtype == jnp.uint8:
        qmin, qmax = 0.0, 255.0
    else:
        qmin, qmax = -127.0, 127.0
    mn = min_range.reshape(())
    mx_ = max_range.reshape(())
    scale = (mx_ - mn) / (qmax - qmin)
    return ((data.astype(jnp.float32) - qmin) * scale + mn).astype(
        np.dtype(out_type))


@register("_contrib_fft")
def _fft(data, compute_size=128, **kw):
    """Reference fft.cc: output interleaves real/imag along last dim."""
    out = jnp.fft.fft(data.astype(jnp.complex64), axis=-1)
    inter = jnp.stack([out.real, out.imag], axis=-1)
    return inter.reshape(data.shape[:-1] + (data.shape[-1] * 2,)).astype(
        jnp.float32)


@register("_contrib_ifft")
def _ifft(data, compute_size=128, **kw):
    n = data.shape[-1] // 2
    pairs = data.reshape(data.shape[:-1] + (n, 2))
    comp = pairs[..., 0] + 1j * pairs[..., 1]
    out = jnp.fft.ifft(comp, axis=-1) * n  # reference does not normalize
    return out.real.astype(jnp.float32)


@register("_contrib_count_sketch", nondiff_inputs=(1, 2))
def _count_sketch(data, h, s, out_dim=0, processing_batch_size=32, **kw):
    """Count sketch projection (reference count_sketch.cc)."""
    n, d = data.shape
    hh = h.reshape(-1).astype(jnp.int32)[:d]
    ss = s.reshape(-1)[:d]
    signed = data * ss[None, :]
    out = jnp.zeros((n, int(out_dim)), data.dtype)
    return out.at[:, hh].add(signed)
