"""Fused optimizer-update ops.

Reference analogue: ``src/operator/optimizer_op.cc`` — sgd_update,
sgd_mom_update, mp_* (multi-precision), adam_update, rmsprop_update,
rmspropalex_update, ftrl_update (SURVEY §2.2).  Optimizers run *as ops* so the
whole update fuses into one XLA program (reference runs them as engine ops for
async overlap; here fusion gives the same effect).

Semantics match the reference kernels: rescale_grad, clip_gradient, wd applied
to the *rescaled, clipped* gradient (``optimizer_op-inl.h``).
"""
from __future__ import annotations

import jax.numpy as jnp

from .registry import register


def _prep_grad(grad, rescale_grad, clip_gradient):
    g = grad * rescale_grad
    if clip_gradient is not None and clip_gradient > 0:
        g = jnp.clip(g, -clip_gradient, clip_gradient)
    return g


@register("sgd_update", nondiff_inputs=(0, 1))
def _sgd_update(weight, grad, lr=0.01, wd=0.0, rescale_grad=1.0,
                clip_gradient=-1.0, lazy_update=True, **kw):
    g = _prep_grad(grad, rescale_grad, clip_gradient)
    return weight - lr * (g + wd * weight)


@register("sgd_mom_update", nondiff_inputs=(0, 1, 2), num_outputs=2,
          num_visible_outputs=1, aux_updates={2: 1})
def _sgd_mom_update(weight, grad, mom, lr=0.01, momentum=0.0, wd=0.0,
                    rescale_grad=1.0, clip_gradient=-1.0, lazy_update=True, **kw):
    g = _prep_grad(grad, rescale_grad, clip_gradient)
    new_mom = momentum * mom - lr * (g + wd * weight)
    return weight + new_mom, new_mom


@register("mp_sgd_update", nondiff_inputs=(0, 1, 2), num_outputs=2,
          num_visible_outputs=1, aux_updates={2: 1})
def _mp_sgd_update(weight, grad, weight32, lr=0.01, wd=0.0, rescale_grad=1.0,
                   clip_gradient=-1.0, **kw):
    g = _prep_grad(grad.astype(jnp.float32), rescale_grad, clip_gradient)
    new_w32 = weight32 - lr * (g + wd * weight32)
    return new_w32.astype(weight.dtype), new_w32


@register("mp_sgd_mom_update", nondiff_inputs=(0, 1, 2, 3), num_outputs=3,
          num_visible_outputs=1, aux_updates={2: 1, 3: 2})
def _mp_sgd_mom_update(weight, grad, mom, weight32, lr=0.01, momentum=0.0,
                       wd=0.0, rescale_grad=1.0, clip_gradient=-1.0, **kw):
    g = _prep_grad(grad.astype(jnp.float32), rescale_grad, clip_gradient)
    new_mom = momentum * mom - lr * (g + wd * weight32)
    new_w32 = weight32 + new_mom
    return new_w32.astype(weight.dtype), new_mom, new_w32


@register("adam_update", nondiff_inputs=(0, 1, 2, 3), num_outputs=3,
          num_visible_outputs=1, aux_updates={2: 1, 3: 2})
def _adam_update(weight, grad, mean, var, lr=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, wd=0.0, rescale_grad=1.0, clip_gradient=-1.0,
                 lazy_update=True, **kw):
    g = _prep_grad(grad, rescale_grad, clip_gradient) + wd * weight
    new_mean = beta1 * mean + (1 - beta1) * g
    new_var = beta2 * var + (1 - beta2) * jnp.square(g)
    return (weight - lr * new_mean / (jnp.sqrt(new_var) + epsilon),
            new_mean, new_var)


@register("rmsprop_update", nondiff_inputs=(0, 1, 2), num_outputs=2,
          num_visible_outputs=1, aux_updates={2: 1})
def _rmsprop_update(weight, grad, n, lr=0.001, gamma1=0.9, epsilon=1e-8,
                    wd=0.0, rescale_grad=1.0, clip_gradient=-1.0,
                    clip_weights=-1.0, **kw):
    g = _prep_grad(grad, rescale_grad, clip_gradient) + wd * weight
    new_n = gamma1 * n + (1 - gamma1) * jnp.square(g)
    w = weight - lr * g / jnp.sqrt(new_n + epsilon)
    if clip_weights is not None and clip_weights > 0:
        w = jnp.clip(w, -clip_weights, clip_weights)
    return w, new_n


@register("rmspropalex_update", nondiff_inputs=(0, 1, 2, 3, 4), num_outputs=4,
          num_visible_outputs=1, aux_updates={2: 1, 3: 2, 4: 3})
def _rmspropalex_update(weight, grad, n, g_, delta, lr=0.001, gamma1=0.95,
                        gamma2=0.9, epsilon=1e-8, wd=0.0, rescale_grad=1.0,
                        clip_gradient=-1.0, clip_weights=-1.0, **kw):
    grd = _prep_grad(grad, rescale_grad, clip_gradient) + wd * weight
    new_n = gamma1 * n + (1 - gamma1) * jnp.square(grd)
    new_g = gamma1 * g_ + (1 - gamma1) * grd
    new_delta = gamma2 * delta - lr * grd / jnp.sqrt(new_n - jnp.square(new_g) + epsilon)
    w = weight + new_delta
    if clip_weights is not None and clip_weights > 0:
        w = jnp.clip(w, -clip_weights, clip_weights)
    return w, new_n, new_g, new_delta


@register("ftrl_update", nondiff_inputs=(0, 1, 2, 3), num_outputs=3,
          num_visible_outputs=1, aux_updates={2: 1, 3: 2})
def _ftrl_update(weight, grad, z, n, lr=0.1, lamda1=0.01, beta=1.0, wd=0.0,
                 rescale_grad=1.0, clip_gradient=-1.0, **kw):
    g = _prep_grad(grad, rescale_grad, clip_gradient)
    new_n = n + jnp.square(g)
    sigma = (jnp.sqrt(new_n) - jnp.sqrt(n)) / lr
    new_z = z + g - sigma * weight
    w = jnp.where(
        jnp.abs(new_z) <= lamda1,
        jnp.zeros_like(weight),
        -(new_z - jnp.sign(new_z) * lamda1)
        / ((beta + jnp.sqrt(new_n)) / lr + wd))
    return w, new_z, new_n


@register("signsgd_update", nondiff_inputs=(0, 1))
def _signsgd_update(weight, grad, lr=0.01, wd=0.0, rescale_grad=1.0,
                    clip_gradient=-1.0, **kw):
    g = _prep_grad(grad, rescale_grad, clip_gradient)
    return weight - lr * (jnp.sign(g) + wd * weight)


@register("signum_update", nondiff_inputs=(0, 1, 2), num_outputs=2,
          num_visible_outputs=1, aux_updates={2: 1})
def _signum_update(weight, grad, mom, lr=0.01, momentum=0.0, wd=0.0,
                   rescale_grad=1.0, clip_gradient=-1.0, wd_lh=0.0, **kw):
    g = _prep_grad(grad, rescale_grad, clip_gradient)
    new_mom = momentum * mom - (1 - momentum) * g
    return weight - lr * (jnp.sign(-new_mom) + wd * weight), new_mom
