"""Shape / layout / linear-algebra / indexing ops.

Reference analogue: ``src/operator/tensor/matrix_op.cc`` (reshape, transpose,
slice, flip, ...), ``dot-inl.h`` (dot/batch_dot), ``indexing_op.cc``
(take/Embedding/one_hot/gather_nd/scatter_nd), ``ordering_op.cc``
(sort/argsort/topk), ``init_op.cc`` (zeros/ones/arange), ``la_op.cc`` (linalg).

TPU notes: ``dot`` lowers to ``lax.dot_general`` (MXU); ``take``/gather are
XLA gathers; dynamic output shapes are avoided throughout (topk's k is an
attr, so shapes stay static under jit).
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from .registry import register
from ..base import dtype_np


# --- reshape family ---------------------------------------------------------
@register("Reshape", aliases=["reshape"])
def _reshape(x, shape=None, reverse=False, target_shape=None, keep_highest=False, **kw):
    if shape is None and target_shape is not None:  # legacy attr
        return x.reshape(tuple(target_shape))
    src = list(x.shape)
    if reverse:
        src = src[::-1]
        shape = tuple(shape)[::-1]
    out = []
    src_i = 0
    infer_idx = None
    i = 0
    shape = tuple(shape)
    while i < len(shape):
        s = shape[i]
        if s > 0:
            out.append(s)
            src_i += 1
        elif s == 0:  # copy dim
            out.append(src[src_i])
            src_i += 1
        elif s == -1:  # infer
            infer_idx = len(out)
            out.append(1)
            src_i += 1
        elif s == -2:  # copy all remaining
            out.extend(src[src_i:])
            src_i = len(src)
        elif s == -3:  # merge two dims
            out.append(src[src_i] * src[src_i + 1])
            src_i += 2
        elif s == -4:  # split dim into next two shape values
            a, b = shape[i + 1], shape[i + 2]
            d = src[src_i]
            if a == -1:
                a = d // b
            if b == -1:
                b = d // a
            out.extend([a, b])
            src_i += 1
            i += 2
        i += 1
    if infer_idx is not None:
        known = int(np.prod([d for j, d in enumerate(out) if j != infer_idx]))
        out[infer_idx] = int(np.prod(x.shape)) // max(known, 1)
    if reverse:
        out = out[::-1]
    return x.reshape(tuple(out))


@register("Flatten", aliases=["flatten"])
def _flatten(x, **kw):
    return x.reshape((x.shape[0], -1))


@register("expand_dims")
def _expand_dims(x, axis=0, **kw):
    return jnp.expand_dims(x, axis)


@register("transpose")
def _transpose(x, axes=None, **kw):
    if axes is None or axes == ():
        axes = tuple(reversed(range(x.ndim)))
    return jnp.transpose(x, axes)


@register("SwapAxis", aliases=["swapaxes"])
def _swapaxes(x, dim1=0, dim2=0, **kw):
    return jnp.swapaxes(x, dim1, dim2)


@register("reshape_like", nondiff_inputs=(1,))
def _reshape_like(x, like, **kw):
    return x.reshape(like.shape)


@register("Concat", aliases=["concat"])
def _concat(*args, dim=1, num_args=None, **kw):
    return jnp.concatenate(args, axis=dim)


@register("stack")
def _stack(*args, axis=0, num_args=None, **kw):
    return jnp.stack(args, axis=axis)


def _split_outputs(attrs):
    return int(attrs.get("num_outputs", 1))


@register("SliceChannel", aliases=["split"], num_outputs=_split_outputs)
def _split(x, num_outputs=1, axis=1, squeeze_axis=False, **kw):
    parts = jnp.split(x, num_outputs, axis=axis)
    if squeeze_axis:
        parts = [p.squeeze(axis=axis) for p in parts]
    return tuple(parts)


@register("_internal_getitem")
def _internal_getitem(x, key=None, **kw):
    """Eager ``x[key]`` as a registered op so NDArray.__getitem__ lands
    on the autograd tape (the key travels as a live attr — slices /
    index arrays — and is never stringified; eager-only by design)."""
    return x[key]


@register("slice", aliases=["crop"])
def _slice(x, begin=(), end=(), step=None, **kw):
    idx = []
    step = step or (None,) * len(begin)
    for b, e, s in zip(begin, end, step):
        idx.append(builtins_slice(b, e, s))
    return x[tuple(idx)]


def builtins_slice(b, e, s):
    return slice(b, e, s)


@register("slice_axis")
def _slice_axis(x, axis=0, begin=0, end=None, **kw):
    idx = [slice(None)] * x.ndim
    idx[axis] = slice(begin, end)
    return x[tuple(idx)]


@register("slice_like", nondiff_inputs=(1,))
def _slice_like(x, like, axes=(), **kw):
    shape = list(x.shape)
    axes = axes or range(x.ndim)
    for a in axes:
        shape[a] = like.shape[a]
    return x[tuple(slice(0, s) for s in shape)]


@register("reverse", aliases=["flip"])
def _reverse(x, axis=(), **kw):
    ax = (axis,) if isinstance(axis, int) else tuple(axis)
    return jnp.flip(x, axis=ax)


@register("tile")
def _tile(x, reps=(), **kw):
    return jnp.tile(x, tuple(reps))


@register("repeat")
def _repeat(x, repeats=1, axis=None, **kw):
    return jnp.repeat(x, repeats, axis=axis)


@register("Pad", aliases=["pad"])
def _pad(x, mode="constant", pad_width=(), constant_value=0.0, **kw):
    pw = [(pad_width[2 * i], pad_width[2 * i + 1]) for i in range(len(pad_width) // 2)]
    if mode == "constant":
        return jnp.pad(x, pw, mode="constant", constant_values=constant_value)
    if mode == "edge":
        return jnp.pad(x, pw, mode="edge")
    if mode == "reflect":
        return jnp.pad(x, pw, mode="reflect")
    raise ValueError("unknown pad mode %s" % mode)


# --- dot / linalg -----------------------------------------------------------
@register("dot", aliases=["_sparse_dot"])
def _dot(a, b, transpose_a=False, transpose_b=False, forward_stype=None, **kw):
    if transpose_a:
        a = jnp.swapaxes(a, -1, -2) if a.ndim > 1 else a
    if transpose_b:
        b = jnp.swapaxes(b, -1, -2) if b.ndim > 1 else b
    # mxnet dot on >2d: contracts last axis of a with first axis of b
    return jnp.tensordot(a, b, axes=([a.ndim - 1], [0]))


@register("batch_dot")
def _batch_dot(a, b, transpose_a=False, transpose_b=False, **kw):
    if transpose_a:
        a = jnp.swapaxes(a, -1, -2)
    if transpose_b:
        b = jnp.swapaxes(b, -1, -2)
    return jnp.matmul(a, b)


def _reg_linalg():
    register("_linalg_gemm2", aliases=["linalg_gemm2"])(
        lambda a, b, transpose_a=False, transpose_b=False, alpha=1.0, axis=-2, **kw:
        alpha * jnp.matmul(jnp.swapaxes(a, -1, -2) if transpose_a else a,
                           jnp.swapaxes(b, -1, -2) if transpose_b else b))

    def gemm(a, b, c, transpose_a=False, transpose_b=False, alpha=1.0, beta=1.0, **kw):
        return (alpha * jnp.matmul(jnp.swapaxes(a, -1, -2) if transpose_a else a,
                                   jnp.swapaxes(b, -1, -2) if transpose_b else b)
                + beta * c)
    register("_linalg_gemm", aliases=["linalg_gemm"])(gemm)
    register("_linalg_potrf", aliases=["linalg_potrf"])(
        lambda a, **kw: jnp.linalg.cholesky(a))

    def potri(a, **kw):
        l = jnp.linalg.cholesky(a) if False else a  # input is already potrf output
        linv = jax.scipy.linalg.solve_triangular(
            a, jnp.broadcast_to(jnp.eye(a.shape[-1], dtype=a.dtype), a.shape), lower=True)
        return jnp.matmul(jnp.swapaxes(linv, -1, -2), linv)
    register("_linalg_potri", aliases=["linalg_potri"])(potri)

    def trsm(a, b, transpose=False, rightside=False, alpha=1.0, lower=True, **kw):
        sol = jax.scipy.linalg.solve_triangular
        if rightside:
            # solve X A = alpha B  ->  A^T X^T = alpha B^T
            x = sol(jnp.swapaxes(a, -1, -2), jnp.swapaxes(alpha * b, -1, -2),
                    lower=not lower, trans=1 if transpose else 0)
            return jnp.swapaxes(x, -1, -2)
        return sol(a, alpha * b, lower=lower, trans=1 if transpose else 0)
    register("_linalg_trsm", aliases=["linalg_trsm"])(trsm)

    def trmm(a, b, transpose=False, rightside=False, alpha=1.0, lower=True, **kw):
        at = jnp.swapaxes(a, -1, -2) if transpose else a
        return alpha * (jnp.matmul(b, at) if rightside else jnp.matmul(at, b))
    register("_linalg_trmm", aliases=["linalg_trmm"])(trmm)
    register("_linalg_sumlogdiag", aliases=["linalg_sumlogdiag"])(
        lambda a, **kw: jnp.sum(jnp.log(jnp.diagonal(a, axis1=-2, axis2=-1)), axis=-1))
    register("_linalg_syrk", aliases=["linalg_syrk"])(
        lambda a, transpose=False, alpha=1.0, **kw:
        alpha * (jnp.matmul(jnp.swapaxes(a, -1, -2), a) if transpose
                 else jnp.matmul(a, jnp.swapaxes(a, -1, -2))))

    def syevd(a, **kw):
        w, v = jnp.linalg.eigh(a)
        return jnp.swapaxes(v, -1, -2), w
    register("_linalg_syevd", aliases=["linalg_syevd"], num_outputs=2)(syevd)

    def gelqf(a, **kw):
        q, r = jnp.linalg.qr(jnp.swapaxes(a, -1, -2))
        return jnp.swapaxes(r, -1, -2), jnp.swapaxes(q, -1, -2)
    register("_linalg_gelqf", aliases=["linalg_gelqf"], num_outputs=2)(gelqf)


_reg_linalg()


# --- indexing ---------------------------------------------------------------
@register("take", nondiff_inputs=(1,))
def _take(a, indices, axis=0, mode="clip", **kw):
    idx = indices.astype(jnp.int32)
    return jnp.take(a, idx, axis=axis, mode=mode if mode != "raise" else "clip")


@register("batch_take", nondiff_inputs=(1,))
def _batch_take(a, indices, **kw):
    return jnp.take_along_axis(a, indices.astype(jnp.int32)[:, None], axis=1)[:, 0]


@register("pick", nondiff_inputs=(1,))
def _pick(x, index, axis=-1, keepdims=False, mode="clip", **kw):
    idx = jnp.expand_dims(index.astype(jnp.int32), axis if axis >= 0 else x.ndim + axis)
    out = jnp.take_along_axis(x, idx, axis=axis)
    if not keepdims:
        out = jnp.squeeze(out, axis=axis)
    return out


@register("Embedding", nondiff_inputs=(0,))
def _embedding(data, weight, input_dim=None, output_dim=None, dtype="float32",
               sparse_grad=False, **kw):
    return jnp.take(weight, data.astype(jnp.int32), axis=0)


@register("one_hot")
def _one_hot(indices, depth=1, on_value=1.0, off_value=0.0, dtype="float32", **kw):
    oh = jax.nn.one_hot(indices.astype(jnp.int32), int(depth), dtype=dtype_np(dtype))
    return oh * on_value + (1 - oh) * off_value


@register("gather_nd", nondiff_inputs=(1,))
def _gather_nd(data, indices, **kw):
    idx = tuple(indices[i].astype(jnp.int32) for i in range(indices.shape[0]))
    return data[idx]


@register("scatter_nd", nondiff_inputs=(1,))
def _scatter_nd(data, indices, shape=(), **kw):
    out = jnp.zeros(tuple(shape), dtype=data.dtype)
    idx = tuple(indices[i].astype(jnp.int32) for i in range(indices.shape[0]))
    return out.at[idx].set(data)


@register("_scatter_set_nd", nondiff_inputs=(1,))
def _scatter_set_nd(lhs, indices, rhs, shape=(), **kw):
    idx = tuple(indices[i].astype(jnp.int32) for i in range(indices.shape[0]))
    return lhs.at[idx].set(rhs)


@register("sparse_retain", aliases=["_sparse_retain"], nondiff_inputs=(1,))
def _sparse_retain_dense(data, indices, **kw):
    mask = jnp.zeros((data.shape[0],), dtype=bool).at[indices.astype(jnp.int32)].set(True)
    return jnp.where(mask.reshape((-1,) + (1,) * (data.ndim - 1)), data, 0)


# --- ordering ---------------------------------------------------------------
@register("sort")
def _sort(x, axis=-1, is_ascend=True, **kw):
    out = jnp.sort(x, axis=axis if axis is not None else None)
    if not is_ascend:
        out = jnp.flip(out, axis=axis)
    return out


@register("argsort")
def _argsort(x, axis=-1, is_ascend=True, dtype="float32", **kw):
    out = jnp.argsort(x, axis=axis)
    if not is_ascend:
        out = jnp.flip(out, axis=axis)
    return out.astype(dtype_np(dtype))


def _topk_nout(attrs):
    rt = attrs.get("ret_typ", "indices")
    return 2 if rt == "both" else 1


@register("topk", num_outputs=_topk_nout)
def _topk(x, axis=-1, k=1, ret_typ="indices", is_ascend=False, dtype="float32", **kw):
    axis = x.ndim - 1 if axis is None else axis % x.ndim
    xs = jnp.moveaxis(x, axis, -1)
    vals, idx = lax.top_k(-xs if is_ascend else xs, int(k))
    if is_ascend:
        vals = -vals
    vals = jnp.moveaxis(vals, -1, axis)
    idx = jnp.moveaxis(idx, -1, axis)
    if ret_typ == "value":
        return vals
    if ret_typ == "indices":
        return idx.astype(dtype_np(dtype))
    if ret_typ == "mask":
        m = jnp.zeros(xs.shape, x.dtype)
        m = m.at[..., :].set(0)
        oh = jax.nn.one_hot(idx if idx.ndim else idx, xs.shape[-1], dtype=x.dtype)
        mask = jnp.moveaxis(oh.sum(axis=-2), -1, axis)
        return mask
    return vals, idx.astype(dtype_np(dtype))


# --- creation (reference: init_op.cc) --------------------------------------
@register("_zeros", aliases=["zeros_like_dummy"], no_inputs=True)
def _zeros(shape=(), dtype="float32", ctx=None, **kw):
    return jnp.zeros(tuple(shape) if not isinstance(shape, int) else (shape,),
                     dtype=dtype_np(dtype))


@register("_ones", no_inputs=True)
def _ones(shape=(), dtype="float32", ctx=None, **kw):
    return jnp.ones(tuple(shape) if not isinstance(shape, int) else (shape,),
                    dtype=dtype_np(dtype))


@register("_full", no_inputs=True)
def _full(shape=(), dtype="float32", value=0.0, ctx=None, **kw):
    return jnp.full(tuple(shape) if not isinstance(shape, int) else (shape,),
                    value, dtype=dtype_np(dtype))


@register("_arange", no_inputs=True)
def _arange(start=0, stop=None, step=1.0, repeat=1, dtype="float32", ctx=None,
            infer_range=False, **kw):
    out = jnp.arange(start, stop, step, dtype=dtype_np(dtype))
    if repeat > 1:
        out = jnp.repeat(out, repeat)
    return out


@register("_eye", no_inputs=True)
def _eye(N=0, M=0, k=0, dtype="float32", ctx=None, **kw):
    return jnp.eye(int(N), int(M) if M else None, k=int(k), dtype=dtype_np(dtype))


@register("zeros_like")
def _zeros_like(x, **kw):
    return jnp.zeros_like(x)


@register("ones_like")
def _ones_like(x, **kw):
    return jnp.ones_like(x)


@register("diag")
def _diag(x, k=0, **kw):
    return jnp.diag(x, k=int(k))


# --- control-flow-ish (reference: control_flow_op.cc handled by `where`) ----
@register("cast_storage", aliases=["_sparse_cast_storage"])
def _cast_storage(x, stype=None, **kw):
    # dense backing for all stypes; the NDArray wrapper re-tags the stype.
    return x


def _region(shape, begin, end, step=None):
    """Slice objects for the reference begin/end(/step) attr convention."""
    begin = tuple(begin)
    end = tuple(end)
    step = tuple(step) if step else (None,) * len(begin)
    out = []
    for i in range(len(shape)):
        b = begin[i] if i < len(begin) else None
        e = end[i] if i < len(end) else None
        st = step[i] if i < len(step) else None
        out.append(slice(b, e, st if st not in (0,) else None))
    return tuple(out)


@register("_slice_assign", aliases=["_crop_assign"], nondiff_inputs=())
def _slice_assign(lhs, rhs, begin=(), end=(), step=(), **kw):
    """Write rhs into lhs[begin:end:step] (ref tensor/matrix_op.cc
    _slice_assign): returns the updated array (functional in-place)."""
    return lhs.at[_region(lhs.shape, begin, end, step)].set(rhs)


@register("_crop_assign_scalar", nondiff_inputs=())
def _crop_assign_scalar(data, scalar=0.0, begin=(), end=(), **kw):
    """Fill data[begin:end] with a scalar (ref _crop_assign_scalar)."""
    return data.at[_region(data.shape, begin, end)].set(scalar)


def _no_gradient_bwd(gout, arrs, out, attrs):
    return (jnp.zeros_like(arrs[0]),)


@register("_NoGradient", custom_vjp=_no_gradient_bwd)
def _no_gradient(data, **kw):
    """Identity whose gradient is defined as zero (ref _NoGradient node —
    distinct from BlockGrad only in how the reference graph passes used it)."""
    return data


@register("_CrossDeviceCopy")
def _cross_device_copy(data, **kw):
    """Explicit device-boundary copy node (ref PlaceDevice inserts these,
    graph_executor.cc:403). Placement on this build is handled by the
    executor's group2ctx walk / shardings, so the op itself is identity."""
    return data


def _kl_sparse_bwd(gout, arrs, out, attrs):
    data = arrs[0]
    target = float(attrs.get("sparseness_target", 0.1))
    penalty = float(attrs.get("penalty", 0.001))
    momentum = float(attrs.get("momentum", 0.9))  # noqa: F841 (API parity)
    # mean activation per unit over the batch axis
    rho_hat = jnp.clip(jnp.mean(data, axis=0, keepdims=True), 1e-6, 1 - 1e-6)
    kl_grad = (-target / rho_hat + (1.0 - target) / (1.0 - rho_hat)) \
        / data.shape[0]
    return (gout[0] + penalty * kl_grad,)


@register("IdentityAttachKLSparseReg", custom_vjp=_kl_sparse_bwd)
def _identity_attach_kl_sparse_reg(data, sparseness_target=0.1,
                                   penalty=0.001, momentum=0.9, **kw):
    """Identity forward; backward adds the KL sparseness penalty gradient
    (ref src/operator/regression_output... identity_attach_KL_sparse_reg:
    drives mean activations toward sparseness_target)."""
    return data
