"""SSD detection ops: MultiBoxPrior / MultiBoxTarget / MultiBoxDetection.

Reference analogue: ``src/operator/contrib/multibox_prior{-inl.h,.cc}``,
``multibox_target-inl.h``, ``multibox_detection-inl.h`` — the op trio
behind ``example/ssd`` (BASELINE workload #5).

TPU-first redesign: the reference kernels are per-anchor scalar loops with
data-dependent control flow; here everything is fixed-shape vectorised
jax — IoU matrices, argmax matching, and mask arithmetic — so the whole
detector head jits into one XLA program. NMS and bipartite matching use
``lax`` loops with static trip counts.

Boxes are corner-format (xmin, ymin, xmax, ymax), normalised to [0, 1].
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from .registry import register

__all__ = []


def _as_floats(v):
    if isinstance(v, (int, float)):
        return (float(v),)
    return tuple(float(x) for x in v)


# ---------------------------------------------------------------------------
# MultiBoxPrior
# ---------------------------------------------------------------------------

@register("_contrib_MultiBoxPrior", nondiff_inputs=(0,))
def _multibox_prior(data, sizes=(1.0,), ratios=(1.0,), clip=False,
                    steps=(-1.0, -1.0), offsets=(0.5, 0.5), **kw):
    """Anchor boxes for every feature-map cell (ref multibox_prior-inl.h).

    data: (N, C, H, W). Output: (1, H*W*A, 4) with
    A = len(sizes) + len(ratios) - 1 — sizes[k] each paired with
    ratios[0], plus sizes[0] with every extra ratio.
    """
    sizes = _as_floats(sizes)
    ratios = _as_floats(ratios)
    h, w = data.shape[2], data.shape[3]
    # reference param order is (step_y, step_x) / (offset_y, offset_x)
    step_y = float(steps[0]) if steps[0] > 0 else 1.0 / h
    step_x = float(steps[1]) if steps[1] > 0 else 1.0 / w

    cy = (jnp.arange(h, dtype=jnp.float32) + float(offsets[0])) * step_y
    cx = (jnp.arange(w, dtype=jnp.float32) + float(offsets[1])) * step_x
    cy, cx = jnp.meshgrid(cy, cx, indexing="ij")        # (H, W)

    # per-anchor half extents
    half_w, half_h = [], []
    r0 = jnp.sqrt(jnp.float32(ratios[0]))
    for s in sizes:
        half_w.append(s * r0 / 2.0)
        half_h.append(s / r0 / 2.0)
    for r in ratios[1:]:
        rs = jnp.sqrt(jnp.float32(r))
        half_w.append(sizes[0] * rs / 2.0)
        half_h.append(sizes[0] / rs / 2.0)
    half_w = jnp.stack([jnp.asarray(v, jnp.float32) for v in half_w])   # (A,)
    half_h = jnp.stack([jnp.asarray(v, jnp.float32) for v in half_h])

    boxes = jnp.stack([
        cx[..., None] - half_w, cy[..., None] - half_h,
        cx[..., None] + half_w, cy[..., None] + half_h], axis=-1)
    boxes = boxes.reshape(1, h * w * half_w.shape[0], 4)
    if clip:
        boxes = jnp.clip(boxes, 0.0, 1.0)
    return boxes.astype(data.dtype)


# ---------------------------------------------------------------------------
# Shared geometry
# ---------------------------------------------------------------------------

def _iou_matrix(anchors, gt_boxes):
    """IoU between (A, 4) anchors and (G, 4) boxes → (A, G)."""
    ax0, ay0, ax1, ay1 = jnp.split(anchors, 4, axis=-1)      # (A, 1)
    gx0, gy0, gx1, gy1 = [g[None, :, 0] for g in
                          jnp.split(gt_boxes, 4, axis=-1)]   # (1, G)
    ix0 = jnp.maximum(ax0, gx0)
    iy0 = jnp.maximum(ay0, gy0)
    ix1 = jnp.minimum(ax1, gx1)
    iy1 = jnp.minimum(ay1, gy1)
    inter = jnp.clip(ix1 - ix0, 0) * jnp.clip(iy1 - iy0, 0)
    area_a = jnp.clip(ax1 - ax0, 0) * jnp.clip(ay1 - ay0, 0)
    area_g = jnp.clip(gx1 - gx0, 0) * jnp.clip(gy1 - gy0, 0)
    union = area_a + area_g - inter
    return jnp.where(union > 0, inter / union, 0.0)


def _encode_offsets(anchors, matched_gt, variances):
    """Corner boxes → (dx, dy, dw, dh) regression targets."""
    aw = anchors[:, 2] - anchors[:, 0]
    ah = anchors[:, 3] - anchors[:, 1]
    acx = (anchors[:, 0] + anchors[:, 2]) / 2
    acy = (anchors[:, 1] + anchors[:, 3]) / 2
    gw = matched_gt[:, 2] - matched_gt[:, 0]
    gh = matched_gt[:, 3] - matched_gt[:, 1]
    gcx = (matched_gt[:, 0] + matched_gt[:, 2]) / 2
    gcy = (matched_gt[:, 1] + matched_gt[:, 3]) / 2
    eps = 1e-8
    dx = (gcx - acx) / jnp.maximum(aw, eps) / variances[0]
    dy = (gcy - acy) / jnp.maximum(ah, eps) / variances[1]
    dw = jnp.log(jnp.maximum(gw, eps) / jnp.maximum(aw, eps)) / variances[2]
    dh = jnp.log(jnp.maximum(gh, eps) / jnp.maximum(ah, eps)) / variances[3]
    return jnp.stack([dx, dy, dw, dh], axis=-1)


def _decode_offsets(anchors, deltas, variances):
    """Inverse of :func:`_encode_offsets` → corner boxes."""
    aw = anchors[:, 2] - anchors[:, 0]
    ah = anchors[:, 3] - anchors[:, 1]
    acx = (anchors[:, 0] + anchors[:, 2]) / 2
    acy = (anchors[:, 1] + anchors[:, 3]) / 2
    cx = deltas[:, 0] * variances[0] * aw + acx
    cy = deltas[:, 1] * variances[1] * ah + acy
    w = jnp.exp(deltas[:, 2] * variances[2]) * aw
    h = jnp.exp(deltas[:, 3] * variances[3]) * ah
    return jnp.stack([cx - w / 2, cy - h / 2, cx + w / 2, cy + h / 2],
                     axis=-1)


# ---------------------------------------------------------------------------
# MultiBoxTarget
# ---------------------------------------------------------------------------

def _match_anchors(ious, valid_gt, overlap_threshold):
    """SSD matching: every valid gt claims its best anchor (bipartite,
    greedy by IoU), then anchors with IoU >= threshold join in.

    Returns (match: (A,) int32 gt index or -1, matched_iou: (A,))."""
    n_anchor, n_gt = ious.shape
    ious = jnp.where(valid_gt[None, :], ious, -1.0)

    # stage 2 first: threshold matches to each anchor's best gt
    best_gt = jnp.argmax(ious, axis=1)
    best_iou = jnp.take_along_axis(ious, best_gt[:, None], axis=1)[:, 0]
    match = jnp.where(best_iou >= overlap_threshold, best_gt, -1)

    # stage 1 overrides: greedy bipartite — iterate gts, each claims the
    # globally-best unclaimed anchor (static trip count = n_gt)
    def claim(carry, _):
        match, pool = carry
        flat = jnp.argmax(pool)
        a_idx, g_idx = flat // n_gt, flat % n_gt
        good = pool[a_idx, g_idx] > 0
        match = jnp.where(good, match.at[a_idx].set(g_idx), match)
        pool = jnp.where(good,
                         pool.at[a_idx, :].set(-1.0).at[:, g_idx].set(-1.0),
                         pool)
        return (match, pool), None

    (match, _), _ = lax.scan(claim, (match, ious), None, length=n_gt)
    matched_iou = jnp.where(match >= 0,
                            ious[jnp.arange(n_anchor),
                                 jnp.clip(match, 0, n_gt - 1)], 0.0)
    return match, matched_iou


def _target_one(anchors, label, cls_pred_t, overlap_threshold, ignore_label,
                negative_mining_ratio, negative_mining_thresh, variances):
    """Targets for one sample. label: (G, 5) [cls, x0, y0, x1, y1],
    cls < 0 marks padding rows."""
    gt_cls = label[:, 0]
    gt_boxes = label[:, 1:5]
    valid = gt_cls >= 0

    ious = _iou_matrix(anchors, gt_boxes)
    best_iou_any = jnp.max(jnp.where(valid[None, :], ious, 0.0), axis=1)
    match, _ = _match_anchors(ious, valid, overlap_threshold)
    is_fg = match >= 0
    safe_match = jnp.clip(match, 0, label.shape[0] - 1)

    cls_target = jnp.where(is_fg, gt_cls[safe_match] + 1.0, 0.0)
    loc = _encode_offsets(anchors, gt_boxes[safe_match], variances)
    loc_target = jnp.where(is_fg[:, None], loc, 0.0).reshape(-1)
    loc_mask = jnp.where(is_fg[:, None],
                         jnp.ones_like(loc), 0.0).reshape(-1)

    if negative_mining_ratio > 0:
        # hard negative mining by background confidence deficit
        # cls_pred_t: (num_classes+1, A) scores; negatives where max
        # non-background prob is high are "hard"
        bg_scores = cls_pred_t[0]
        # near-positives (IoU above the mining threshold) are excluded
        # from the negative pool, per the reference semantics
        neg_mask = ~is_fg & (best_iou_any < negative_mining_thresh)
        hardness = jnp.where(neg_mask, -bg_scores, -jnp.inf)
        n_fg = jnp.sum(is_fg)
        quota = jnp.maximum((negative_mining_ratio * n_fg).astype(jnp.int32),
                            1)
        order = jnp.argsort(-hardness)
        rank = jnp.zeros_like(order).at[order].set(
            jnp.arange(order.shape[0]))
        keep_neg = neg_mask & (rank < quota)
        cls_target = jnp.where(is_fg, cls_target,
                               jnp.where(keep_neg, 0.0,
                                         float(ignore_label)))
    return loc_target, loc_mask, cls_target


@register("_contrib_MultiBoxTarget", num_outputs=3,
          nondiff_inputs=(0, 1, 2))
def _multibox_target(anchor, label, cls_pred, overlap_threshold=0.5,
                     ignore_label=-1.0, negative_mining_ratio=-1.0,
                     negative_mining_thresh=0.5,
                     minimum_negative_samples=0,
                     variances=(0.1, 0.1, 0.2, 0.2), **kw):
    """Anchor-to-ground-truth matching (ref multibox_target-inl.h).

    anchor (1, A, 4); label (N, G, 5); cls_pred (N, num_cls+1, A).
    Outputs: loc_target (N, 4A), loc_mask (N, 4A), cls_target (N, A).
    """
    variances = _as_floats(variances)
    anchors = anchor.reshape(-1, 4)

    fn = lambda lbl, cp: _target_one(
        anchors, lbl, cp, float(overlap_threshold), float(ignore_label),
        float(negative_mining_ratio), float(negative_mining_thresh),
        variances)
    loc_t, loc_m, cls_t = jax.vmap(fn)(label, cls_pred)
    return (loc_t.astype(anchor.dtype), loc_m.astype(anchor.dtype),
            cls_t.astype(anchor.dtype))


# ---------------------------------------------------------------------------
# MultiBoxDetection
# ---------------------------------------------------------------------------

def _nms_one(dets, nms_threshold, force_suppress, topk):
    """Greedy NMS over (A, 6) [cls, score, x0, y0, x1, y1]; suppressed
    rows get cls = -1. Static trip count = topk."""
    n = dets.shape[0]
    order = jnp.argsort(-dets[:, 1])
    dets = dets[order]
    boxes = dets[:, 2:6]
    cls = dets[:, 0]
    alive = cls >= 0

    def body(i, alive):
        keep_i = alive[i]
        ious = _iou_matrix(boxes[i][None, :], boxes)[0]      # (A,)
        same_cls = (cls == cls[i]) | bool(force_suppress)
        kill = (ious > nms_threshold) & same_cls & \
            (jnp.arange(n) > i) & keep_i
        return alive & ~kill

    alive = lax.fori_loop(0, min(topk, n) if topk > 0 else n, body, alive)
    out = jnp.where(alive[:, None], dets,
                    dets.at[:, 0].set(-1.0)[:, :])
    out = out.at[:, 0].set(jnp.where(alive, dets[:, 0], -1.0))
    return out


def _detect_one(cls_prob_t, loc_pred, anchors, threshold, background_id,
                nms_threshold, force_suppress, variances, nms_topk, clip):
    """One sample: cls_prob_t (num_cls+1, A), loc_pred (4A,)."""
    boxes = _decode_offsets(anchors, loc_pred.reshape(-1, 4), variances)
    if clip:
        boxes = jnp.clip(boxes, 0.0, 1.0)
    scores = cls_prob_t                                   # (C+1, A)
    # best non-background class per anchor
    masked = scores.at[background_id].set(-jnp.inf)
    best_cls = jnp.argmax(masked, axis=0)                 # (A,)
    best_score = jnp.max(masked, axis=0)
    keep = best_score > threshold
    cls_id = jnp.where(keep, best_cls.astype(jnp.float32) - 1.0, -1.0)
    score = jnp.where(keep, best_score, 0.0)
    dets = jnp.concatenate([cls_id[:, None], score[:, None], boxes], axis=1)
    return _nms_one(dets, nms_threshold, force_suppress,
                    nms_topk if nms_topk > 0 else dets.shape[0])


@register("_contrib_MultiBoxDetection", nondiff_inputs=(0, 1, 2))
def _multibox_detection(cls_prob, loc_pred, anchor, clip=True,
                        threshold=0.01, background_id=0, nms_threshold=0.5,
                        force_suppress=False,
                        variances=(0.1, 0.1, 0.2, 0.2), nms_topk=-1, **kw):
    """Decode + per-class NMS (ref multibox_detection-inl.h).

    cls_prob (N, C+1, A); loc_pred (N, 4A); anchor (1, A, 4).
    Output (N, A, 6): [class_id, score, x0, y0, x1, y1], -1 class = void.
    """
    variances = _as_floats(variances)
    anchors = anchor.reshape(-1, 4)
    fn = lambda cp, lp: _detect_one(
        cp, lp, anchors, float(threshold), int(background_id),
        float(nms_threshold), bool(force_suppress), variances,
        int(nms_topk), bool(clip))
    return jax.vmap(fn)(cls_prob, loc_pred).astype(cls_prob.dtype)
