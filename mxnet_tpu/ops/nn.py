"""Neural-network layer ops (the legacy OperatorProperty corpus, TPU-native).

Reference analogue: ``src/operator/{convolution,pooling,batch_norm,activation,
dropout,fully_connected,softmax_output,rnn,...}-inl.h`` (SURVEY §2.2 "NN
layers").  Re-design notes:

- Convolution/Deconvolution lower to ``lax.conv_general_dilated`` (MXU path);
  there is no im2col, no cuDNN algo registry — XLA autotunes tiling.
- Pooling is ``lax.reduce_window``.
- BatchNorm is a pure function returning updated moving stats as extra
  outputs (``aux_updates``) instead of mutating aux buffers in a kernel.
- Dropout takes an explicit PRNG key (``needs_rng``) so it is jit-safe.
- The fused RNN op is a ``lax.scan`` over time — the XLA-native equivalent of
  cuDNN's fused RNN (``cudnn_rnn-inl.h``).
- Loss-layer ops (SoftmaxOutput & regression outputs) keep MXNet's *semantic*
  gradients via ``custom_vjp`` (backward ignores head-grad and uses labels,
  reference ``softmax_output-inl.h``).

Layout: NCHW / TNC defaults, matching the reference's Python API surface.
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from .registry import register
from ..base import dtype_np


def _tup(v, n=None):
    if isinstance(v, int):
        v = (v,) * (n or 1)
    return tuple(v)


# --- FullyConnected ---------------------------------------------------------
@register("FullyConnected")
def _fully_connected(data, weight, *maybe_bias, num_hidden=None, no_bias=False,
                     flatten=True, **kw):
    x = data.reshape((data.shape[0], -1)) if flatten else data
    out = jnp.dot(x, weight.T)
    if not no_bias and maybe_bias:
        out = out + maybe_bias[0]
    return out


# --- Convolution family -----------------------------------------------------
def _conv_dims(kernel):
    nd = len(kernel)
    spat = "DHW"[3 - nd:]
    return ("NC" + spat, "OI" + spat, "NC" + spat)


@register("Convolution", aliases=["Convolution_v1"])
def _convolution(data, weight, *maybe_bias, kernel=(), stride=(), dilate=(),
                 pad=(), num_filter=1, num_group=1, no_bias=False,
                 workspace=1024, cudnn_tune=None, cudnn_off=False, layout=None, **kw):
    nd = len(kernel)
    stride = _tup(stride or (1,) * nd, nd)
    dilate = _tup(dilate or (1,) * nd, nd)
    pad = _tup(pad or (0,) * nd, nd)
    # bf16 in/out: the MXU accumulates partial products in f32 regardless
    # and rounds once at the output, so no preferred_element_type override
    # (which would make the conv transpose rule see an f32 cotangent
    # against bf16 operands and fail under AD)
    out = lax.conv_general_dilated(
        data, weight, window_strides=stride,
        padding=[(p, p) for p in pad],
        rhs_dilation=dilate, feature_group_count=int(num_group),
        dimension_numbers=_conv_dims(kernel))
    if not no_bias and maybe_bias:
        b = maybe_bias[0].reshape((1, -1) + (1,) * nd)
        out = out + b
    return out


@register("Deconvolution")
def _deconvolution(data, weight, *maybe_bias, kernel=(), stride=(), dilate=(),
                   pad=(), adj=(), target_shape=(), num_filter=1, num_group=1,
                   no_bias=True, workspace=512, cudnn_tune=None, cudnn_off=False,
                   layout=None, **kw):
    nd = len(kernel)
    stride = _tup(stride or (1,) * nd, nd)
    pad = _tup(pad or (0,) * nd, nd)
    adj = _tup(adj or (0,) * nd, nd)
    g = int(num_group)
    c = weight.shape[0]
    f = weight.shape[1] * g
    # weight (C, F/g, *k) -> (F, C/g, *k), spatially flipped
    w = weight.reshape((g, c // g, f // g) + tuple(kernel))
    w = jnp.swapaxes(w, 1, 2).reshape((f, c // g) + tuple(kernel))
    w = jnp.flip(w, axis=tuple(range(2, 2 + nd)))
    padding = [(k - 1 - p, k - 1 - p + a) for k, p, a in zip(kernel, pad, adj)]
    out = lax.conv_general_dilated(
        data, w, window_strides=(1,) * nd, padding=padding,
        lhs_dilation=stride, feature_group_count=g,
        dimension_numbers=_conv_dims(kernel))
    if not no_bias and maybe_bias:
        out = out + maybe_bias[0].reshape((1, -1) + (1,) * nd)
    return out


# --- Pooling ----------------------------------------------------------------
@register("Pooling", aliases=["Pooling_v1"])
def _pooling(data, kernel=(), pool_type="max", global_pool=False, stride=(),
             pad=(), pooling_convention="valid", count_include_pad=True,
             cudnn_off=False, p_value=2, layout=None, **kw):
    nd = data.ndim - 2
    if global_pool:
        kernel = data.shape[2:]
        stride = (1,) * nd
        pad = (0,) * nd
    kernel = _tup(kernel, nd)
    stride = _tup(stride or (1,) * nd, nd)
    pad = _tup(pad or (0,) * nd, nd)
    window = (1, 1) + kernel
    strides = (1, 1) + stride
    if pooling_convention == "full":
        # ceil-mode: pad on the high side so the last partial window counts
        extra = []
        for i in range(nd):
            inp = data.shape[2 + i]
            out_sz = int(np.ceil((inp + 2 * pad[i] - kernel[i]) / stride[i])) + 1
            need = (out_sz - 1) * stride[i] + kernel[i] - inp - 2 * pad[i]
            extra.append(max(0, need))
        padding = ((0, 0), (0, 0)) + tuple((p, p + e) for p, e in zip(pad, extra))
    else:
        padding = ((0, 0), (0, 0)) + tuple((p, p) for p in pad)
    if pool_type == "max":
        init = (-np.inf if jnp.issubdtype(data.dtype, jnp.floating)
                else np.iinfo(np.dtype(data.dtype)).min)
        return lax.reduce_window(data, init, lax.max, window, strides, padding)
    if pool_type in ("avg", "sum"):
        s = lax.reduce_window(data, 0.0 if jnp.issubdtype(data.dtype, jnp.floating) else 0,
                              lax.add, window, strides, padding)
        if pool_type == "sum":
            return s
        if count_include_pad:
            return s / float(np.prod(kernel))
        ones = jnp.ones_like(data)
        cnt = lax.reduce_window(ones, 0.0, lax.add, window, strides, padding)
        return s / cnt
    if pool_type == "lp":
        p = float(p_value)
        s = lax.reduce_window(jnp.abs(data) ** p, 0.0, lax.add,
                              window, strides, padding)
        return s ** (1.0 / p)
    raise ValueError("unknown pool_type %s" % pool_type)


@register("UpSampling")
def _upsampling(*args, scale=1, sample_type="nearest", num_filter=0,
                multi_input_mode="concat", num_args=1, workspace=512, **kw):
    data = args[0]
    s = int(scale)
    if sample_type == "nearest":
        outs = []
        for a in args:
            o = jnp.repeat(jnp.repeat(a, s, axis=2), s, axis=3)
            outs.append(o)
        if len(outs) == 1:
            return outs[0]
        if multi_input_mode == "sum":
            return sum(outs)
        return jnp.concatenate(outs, axis=1)
    # bilinear: args = (data, weight) in reference; use jax.image.resize
    n, c, h, w = data.shape
    return jax.image.resize(data, (n, c, h * s, w * s), method="bilinear")


# --- BatchNorm --------------------------------------------------------------
@register("BatchNorm", aliases=["BatchNorm_v1", "CuDNNBatchNorm"],
          num_outputs=3, num_visible_outputs=1,
          nondiff_inputs=(3, 4), aux_updates={3: 1, 4: 2}, takes_mode=True)
def _batch_norm(data, gamma, beta, moving_mean, moving_var, eps=1e-3,
                momentum=0.9, fix_gamma=True, use_global_stats=False,
                output_mean_var=False, axis=1, cudnn_off=False,
                train_mode=False, **kw):
    ax = axis % data.ndim
    red = tuple(i for i in range(data.ndim) if i != ax)
    shape = [1] * data.ndim
    shape[ax] = data.shape[ax]
    g = jnp.ones_like(gamma) if fix_gamma else gamma
    if train_mode and not use_global_stats:
        mean = jnp.mean(data, axis=red)
        var = jnp.var(data, axis=red)
        new_mm = moving_mean * momentum + mean * (1 - momentum)
        new_mv = moving_var * momentum + var * (1 - momentum)
    else:
        mean, var = moving_mean, moving_var
        new_mm, new_mv = moving_mean, moving_var
    inv = lax.rsqrt(var + eps)
    out = (data - mean.reshape(shape)) * inv.reshape(shape) * g.reshape(shape) \
        + beta.reshape(shape)
    return out, new_mm, new_mv


@register("InstanceNorm")
def _instance_norm(data, gamma, beta, eps=1e-3, **kw):
    red = tuple(range(2, data.ndim))
    mean = jnp.mean(data, axis=red, keepdims=True)
    var = jnp.var(data, axis=red, keepdims=True)
    shape = (1, -1) + (1,) * (data.ndim - 2)
    return ((data - mean) * lax.rsqrt(var + eps)) * gamma.reshape(shape) \
        + beta.reshape(shape)


@register("LayerNorm")
def _layer_norm(data, gamma, beta, axis=-1, eps=1e-5, output_mean_var=False, **kw):
    mean = jnp.mean(data, axis=axis, keepdims=True)
    var = jnp.var(data, axis=axis, keepdims=True)
    out = (data - mean) * lax.rsqrt(var + eps)
    shape = [1] * data.ndim
    shape[axis % data.ndim] = data.shape[axis % data.ndim]
    return out * gamma.reshape(shape) + beta.reshape(shape)


@register("L2Normalization")
def _l2_normalization(data, eps=1e-10, mode="instance", **kw):
    if mode == "instance":
        red = tuple(range(1, data.ndim))
        n = jnp.sqrt(jnp.sum(jnp.square(data), axis=red, keepdims=True) + eps)
    elif mode == "channel":
        n = jnp.sqrt(jnp.sum(jnp.square(data), axis=1, keepdims=True) + eps)
    else:  # spatial
        red = tuple(range(2, data.ndim))
        n = jnp.sqrt(jnp.sum(jnp.square(data), axis=red, keepdims=True) + eps)
    return data / n


@register("LRN")
def _lrn(data, alpha=1e-4, beta=0.75, knorm=2.0, nsize=5, **kw):
    sq = jnp.square(data)
    half = int(nsize) // 2
    padded = jnp.pad(sq, ((0, 0), (half, half), (0, 0), (0, 0)))
    windows = sum(padded[:, i:i + data.shape[1]] for i in range(int(nsize)))
    return data / jnp.power(knorm + (alpha / nsize) * windows, beta)


# --- Activations ------------------------------------------------------------
@register("Activation")
def _activation(data, act_type="relu", **kw):
    if act_type == "relu":
        return jnp.maximum(data, 0)
    if act_type == "sigmoid":
        return jax.nn.sigmoid(data)
    if act_type == "tanh":
        return jnp.tanh(data)
    if act_type == "softrelu":
        return jax.nn.softplus(data)
    if act_type == "softsign":
        return jax.nn.soft_sign(data)
    raise ValueError("unknown act_type %s" % act_type)


@register("LeakyReLU", needs_rng=True, takes_mode=True)
def _leaky_relu(data, *maybe_gamma, act_type="leaky", slope=0.25,
                lower_bound=0.125, upper_bound=0.334, rng=None,
                train_mode=False, **kw):
    if act_type == "leaky":
        return jnp.where(data >= 0, data, slope * data)
    if act_type == "elu":
        return jnp.where(data >= 0, data, slope * (jnp.exp(data) - 1))
    if act_type == "selu":
        return 1.0507009873554805 * jax.nn.elu(data, 1.6732632423543772)
    if act_type == "prelu":
        gamma = maybe_gamma[0]
        shape = [1] * data.ndim
        if gamma.size > 1 and data.ndim > 1:
            shape[1] = gamma.size
        return jnp.where(data >= 0, data, gamma.reshape(shape) * data)
    if act_type == "rrelu":
        if train_mode and rng is not None:
            lo, hi = float(lower_bound), float(upper_bound)
            r = jax.random.uniform(rng, data.shape, data.dtype, lo, hi)
            return jnp.where(data >= 0, data, r * data)
        s = (float(lower_bound) + float(upper_bound)) / 2.0
        return jnp.where(data >= 0, data, s * data)
    raise ValueError("unknown act_type %s" % act_type)


@register("SoftmaxActivation")
def _softmax_activation(data, mode="instance", **kw):
    if mode == "channel":
        return jax.nn.softmax(data, axis=1)
    return jax.nn.softmax(data.reshape(data.shape[0], -1), axis=-1).reshape(data.shape)


# --- Dropout ----------------------------------------------------------------
@register("Dropout", needs_rng=True, takes_mode=True)
def _dropout(data, p=0.5, mode="training", axes=(), rng=None,
              train_mode=False, cudnn_off=False, **kw):
    if (not train_mode and mode != "always") or p <= 0 or rng is None:
        return data
    shape = list(data.shape)
    for a in axes or ():
        shape[a] = 1
    keep = 1.0 - p
    mask = jax.random.bernoulli(rng, keep, tuple(shape)).astype(data.dtype) / keep
    return data * mask


# --- Loss-layer ops with semantic gradients ---------------------------------
def _softmax_fwd(data, multi_output=False, preserve_shape=False, temperature=None):
    if multi_output:
        return jax.nn.softmax(data, axis=1)
    if preserve_shape:
        return jax.nn.softmax(data, axis=-1)
    return jax.nn.softmax(data.reshape(data.shape[0], -1), axis=-1).reshape(data.shape)


def _softmax_output_bwd(out_grads, inputs, outputs, attrs):
    data, label = inputs[0], inputs[1]
    out = outputs[0]
    grad_scale = attrs.get("grad_scale", 1.0)
    ignore_label = attrs.get("ignore_label", -1.0)
    use_ignore = attrs.get("use_ignore", False)
    multi_output = attrs.get("multi_output", False)
    normalization = attrs.get("normalization", "null")
    smooth_alpha = attrs.get("smooth_alpha", 0.0)
    if multi_output:
        # data (N, C, ...) label (N, ...)
        c = data.shape[1]
        lab = label.astype(jnp.int32)
        oh = jnp.moveaxis(jax.nn.one_hot(lab, c, dtype=data.dtype), -1, 1)
        if smooth_alpha:
            oh = oh * (1 - smooth_alpha) + smooth_alpha / (c - 1) * (1 - oh)
        grad = out - oh
        valid = jnp.ones(lab.shape, data.dtype)
        if use_ignore:
            valid = (lab != int(ignore_label)).astype(data.dtype)
            grad = grad * valid[:, None]
        norm = 1.0
        if normalization == "valid":
            norm = jnp.maximum(jnp.sum(valid), 1.0)
        elif normalization == "batch":
            norm = float(data.shape[0])
        return (grad * (grad_scale / norm), jnp.zeros_like(label))
    if label.ndim == data.ndim:  # one-hot/dense label
        grad = out - label
        norm = float(data.shape[0]) if normalization == "batch" else 1.0
        return (grad * (grad_scale / norm), jnp.zeros_like(label))
    c = data.shape[-1]
    lab = label.astype(jnp.int32)
    oh = jax.nn.one_hot(lab, c, dtype=data.dtype)
    if smooth_alpha:
        oh = oh * (1 - smooth_alpha) + smooth_alpha / (c - 1) * (1 - oh)
    grad = out - oh
    valid = jnp.ones(lab.shape, data.dtype)
    if use_ignore:
        valid = (lab != int(ignore_label)).astype(data.dtype)
        grad = grad * valid[..., None]
    norm = 1.0
    if normalization == "valid":
        norm = jnp.maximum(jnp.sum(valid), 1.0)
    elif normalization == "batch":
        norm = float(data.shape[0])
    return (grad * (grad_scale / norm), jnp.zeros_like(label))


@register("SoftmaxOutput", aliases=["Softmax"], nondiff_inputs=(1,),
          custom_vjp=_softmax_output_bwd)
def _softmax_output(data, label, grad_scale=1.0, ignore_label=-1.0,
                    multi_output=False, use_ignore=False, preserve_shape=False,
                    normalization="null", out_grad=False, smooth_alpha=0.0, **kw):
    return _softmax_fwd(data, multi_output, preserve_shape)


def _linreg_bwd(out_grads, inputs, outputs, attrs):
    data, label = inputs
    gs = attrs.get("grad_scale", 1.0)
    return ((outputs[0] - label.reshape(data.shape)) * gs, jnp.zeros_like(label))


@register("LinearRegressionOutput", nondiff_inputs=(1,), custom_vjp=_linreg_bwd)
def _lin_reg_output(data, label, grad_scale=1.0, **kw):
    return data


def _maereg_bwd(out_grads, inputs, outputs, attrs):
    data, label = inputs
    gs = attrs.get("grad_scale", 1.0)
    return (jnp.sign(data - label.reshape(data.shape)) * gs, jnp.zeros_like(label))


@register("MAERegressionOutput", nondiff_inputs=(1,), custom_vjp=_maereg_bwd)
def _mae_reg_output(data, label, grad_scale=1.0, **kw):
    return data


def _logreg_bwd(out_grads, inputs, outputs, attrs):
    data, label = inputs
    gs = attrs.get("grad_scale", 1.0)
    return ((outputs[0] - label.reshape(data.shape)) * gs, jnp.zeros_like(label))


@register("LogisticRegressionOutput", nondiff_inputs=(1,), custom_vjp=_logreg_bwd)
def _log_reg_output(data, label, grad_scale=1.0, **kw):
    return jax.nn.sigmoid(data)


def _svm_bwd(out_grads, inputs, outputs, attrs):
    data, label = inputs
    margin = attrs.get("margin", 1.0)
    reg = attrs.get("regularization_coefficient", 1.0)
    use_linear = attrs.get("use_linear", False)
    c = data.shape[-1]
    lab = label.astype(jnp.int32)
    oh = jax.nn.one_hot(lab, c, dtype=data.dtype)
    score_y = jnp.take_along_axis(data, lab[..., None], axis=-1)
    viol = (margin - (score_y - data)) > 0
    viol = viol.astype(data.dtype) * (1 - oh)
    if use_linear:
        grad = reg * (viol - oh * jnp.sum(viol, axis=-1, keepdims=True))
    else:
        dist = (margin - (score_y - data)) * (1 - oh)
        grad = reg * 2 * jnp.maximum(dist, 0)
        grad = grad - oh * jnp.sum(grad, axis=-1, keepdims=True)
    return (grad, jnp.zeros_like(label))


@register("SVMOutput", nondiff_inputs=(1,), custom_vjp=_svm_bwd)
def _svm_output(data, label, margin=1.0, regularization_coefficient=1.0,
                use_linear=False, **kw):
    return data


@register("softmax_cross_entropy", nondiff_inputs=(1,))
def _softmax_cross_entropy(data, label, **kw):
    logp = jax.nn.log_softmax(data, axis=-1)
    lab = label.astype(jnp.int32)
    return -jnp.sum(jnp.take_along_axis(logp, lab[..., None], axis=-1))


@register("MakeLoss", custom_vjp=lambda og, i, o, a:
          (jnp.ones_like(i[0]) * a.get("grad_scale", 1.0),))
def _make_loss_layer(data, grad_scale=1.0, valid_thresh=0.0,
                     normalization="null", **kw):
    if normalization == "batch":
        return data / data.shape[0]
    if normalization == "valid":
        valid = jnp.sum((data > valid_thresh).astype(data.dtype))
        return data / jnp.maximum(valid, 1.0)
    return data


# --- Sequence ops -----------------------------------------------------------
@register("SequenceMask")
def _sequence_mask(data, *maybe_len, use_sequence_length=False, value=0.0,
                   axis=0, **kw):
    if not use_sequence_length or not maybe_len:
        return data
    seq_len = maybe_len[0]
    t = data.shape[axis]
    pos = jnp.arange(t)
    if axis == 0:
        mask = pos[:, None] < seq_len[None, :].astype(pos.dtype)
        mask = mask.reshape(mask.shape + (1,) * (data.ndim - 2))
    else:
        mask = pos[None, :] < seq_len[:, None].astype(pos.dtype)
        mask = mask.reshape(mask.shape + (1,) * (data.ndim - 2))
    return jnp.where(mask, data, jnp.asarray(value, data.dtype))


@register("SequenceLast", nondiff_inputs=(1,))
def _sequence_last(data, *maybe_len, use_sequence_length=False, axis=0, **kw):
    if not use_sequence_length or not maybe_len:
        return jnp.take(data, data.shape[axis] - 1, axis=axis)
    seq_len = maybe_len[0].astype(jnp.int32) - 1
    if axis == 0:
        return data[seq_len, jnp.arange(data.shape[1])]
    return data[jnp.arange(data.shape[0]), seq_len]


@register("SequenceReverse")
def _sequence_reverse(data, *maybe_len, use_sequence_length=False, axis=0, **kw):
    if not use_sequence_length or not maybe_len:
        return jnp.flip(data, axis=0)
    seq_len = maybe_len[0].astype(jnp.int32)
    t = data.shape[0]
    pos = jnp.arange(t)[:, None]
    rev = seq_len[None, :] - 1 - pos
    idx = jnp.where(rev >= 0, rev, pos)
    return jnp.take_along_axis(
        data, idx.reshape(idx.shape + (1,) * (data.ndim - 2)).astype(jnp.int32), axis=0)


# --- Fused RNN (lax.scan; the XLA-native cuDNN-RNN equivalent) --------------
def _gates(mode):
    return {"rnn_relu": 1, "rnn_tanh": 1, "lstm": 4, "gru": 3}[mode]


def rnn_param_size(num_layers, input_size, state_size, mode, bidirectional=False):
    """Total packed parameter count; layout documented in _rnn_unpack."""
    g = _gates(mode)
    d = 2 if bidirectional else 1
    size = 0
    for layer in range(num_layers):
        in_sz = input_size if layer == 0 else state_size * d
        size += d * (g * state_size * in_sz + g * state_size * state_size
                     + 2 * g * state_size)
    return size


def _rnn_unpack(params, num_layers, input_size, state_size, mode, bidirectional):
    """Packed layout: per layer, per direction: i2h_W (G*H, in), h2h_W (G*H, H),
    i2h_b (G*H), h2h_b (G*H).  Gate order: LSTM i,f,g,o; GRU r,z,n."""
    g = _gates(mode)
    d = 2 if bidirectional else 1
    h = state_size
    off = 0
    layers = []
    for layer in range(num_layers):
        in_sz = input_size if layer == 0 else h * d
        dirs = []
        for _ in range(d):
            wi = params[off:off + g * h * in_sz].reshape(g * h, in_sz); off += g * h * in_sz
            wh = params[off:off + g * h * h].reshape(g * h, h); off += g * h * h
            bi = params[off:off + g * h]; off += g * h
            bh = params[off:off + g * h]; off += g * h
            dirs.append((wi, wh, bi, bh))
        layers.append(dirs)
    return layers


def _rnn_cell_step(mode, h):
    def step(carry, x_t, wi, wh, bi, bh):
        if mode in ("rnn_relu", "rnn_tanh"):
            hp = carry[0]
            pre = x_t @ wi.T + bi + hp @ wh.T + bh
            hn = jnp.maximum(pre, 0) if mode == "rnn_relu" else jnp.tanh(pre)
            return (hn,), hn
        if mode == "lstm":
            hp, cp = carry
            pre = x_t @ wi.T + bi + hp @ wh.T + bh
            i, f, gg, o = jnp.split(pre, 4, axis=-1)
            i, f, o = jax.nn.sigmoid(i), jax.nn.sigmoid(f), jax.nn.sigmoid(o)
            gg = jnp.tanh(gg)
            cn = f * cp + i * gg
            hn = o * jnp.tanh(cn)
            return (hn, cn), hn
        # gru
        hp = carry[0]
        xi = x_t @ wi.T + bi
        hh = hp @ wh.T + bh
        xr, xz, xn = jnp.split(xi, 3, axis=-1)
        hr, hz, hn_ = jnp.split(hh, 3, axis=-1)
        r = jax.nn.sigmoid(xr + hr)
        z = jax.nn.sigmoid(xz + hz)
        n = jnp.tanh(xn + r * hn_)
        hn = (1 - z) * n + z * hp
        return (hn,), hn
    return step


def _rnn_nout(attrs):
    if not attrs.get("state_outputs", False):
        return 1
    return 3 if attrs.get("mode", "lstm") == "lstm" else 2


@register("RNN", num_outputs=_rnn_nout, needs_rng=True, takes_mode=True)
def _rnn(data, parameters, state, *maybe_cell, state_size=0, num_layers=1,
         bidirectional=False, mode="lstm", p=0.0, state_outputs=False,
         rng=None, train_mode=False, lstm_state_clip_min=None,
         lstm_state_clip_max=None, projection_size=None, **kw):
    """Fused multi-layer RNN. data: (T, N, C); state: (L*D, N, H)."""
    t, n, input_size = data.shape
    h = int(state_size)
    d = 2 if bidirectional else 1
    is_lstm = mode == "lstm"
    cell0 = maybe_cell[0] if is_lstm and maybe_cell else None
    layers = _rnn_unpack(parameters, int(num_layers), input_size, h, mode,
                         bidirectional)
    step = _rnn_cell_step(mode, h)
    x = data
    out_h, out_c = [], []
    for li, dirs in enumerate(layers):
        dir_outs = []
        for di, (wi, wh, bi, bh) in enumerate(dirs):
            idx = li * d + di
            h0 = state[idx]
            carry = (h0, cell0[idx]) if is_lstm else (h0,)
            seq = jnp.flip(x, axis=0) if di == 1 else x

            def scan_fn(c, x_t, wi=wi, wh=wh, bi=bi, bh=bh):
                return step(c, x_t, wi, wh, bi, bh)
            carry, ys = lax.scan(scan_fn, carry, seq)
            if di == 1:
                ys = jnp.flip(ys, axis=0)
            dir_outs.append(ys)
            out_h.append(carry[0])
            if is_lstm:
                out_c.append(carry[1])
        x = dir_outs[0] if d == 1 else jnp.concatenate(dir_outs, axis=-1)
        if p > 0 and train_mode and rng is not None and li < len(layers) - 1:
            rng, sub = jax.random.split(rng)
            keep = 1.0 - p
            x = x * jax.random.bernoulli(sub, keep, x.shape).astype(x.dtype) / keep
    outs = [x]
    if state_outputs:
        outs.append(jnp.stack(out_h, axis=0))
        if is_lstm:
            outs.append(jnp.stack(out_c, axis=0))
    return tuple(outs) if len(outs) > 1 else outs[0]


# --- Spatial/geometry ops ---------------------------------------------------
@register("GridGenerator")
def _grid_generator(data, transform_type="affine", target_shape=(0, 0), **kw):
    h, w = int(target_shape[0]), int(target_shape[1])
    if transform_type == "affine":
        n = data.shape[0]
        theta = data.reshape(n, 2, 3)
        ys = jnp.linspace(-1, 1, h)
        xs = jnp.linspace(-1, 1, w)
        gx, gy = jnp.meshgrid(xs, ys)
        ones = jnp.ones_like(gx)
        grid = jnp.stack([gx, gy, ones], axis=0).reshape(3, -1)
        out = jnp.einsum("nij,jk->nik", theta, grid.astype(data.dtype))
        return out.reshape(n, 2, h, w)
    return data  # warp type: data is already the flow grid


def _bilinear_sample(data, grid):
    """data (N,C,H,W), grid (N,2,Ho,Wo) in [-1,1] (x, y)."""
    n, c, h, w = data.shape
    gx = (grid[:, 0] + 1) * (w - 1) / 2
    gy = (grid[:, 1] + 1) * (h - 1) / 2
    x0 = jnp.floor(gx); y0 = jnp.floor(gy)
    x1 = x0 + 1; y1 = y0 + 1
    wx1 = gx - x0; wy1 = gy - y0
    wx0 = 1 - wx1; wy0 = 1 - wy1

    def gather(yy, xx):
        yi = jnp.clip(yy, 0, h - 1).astype(jnp.int32)
        xi = jnp.clip(xx, 0, w - 1).astype(jnp.int32)
        valid = ((yy >= 0) & (yy <= h - 1) & (xx >= 0) & (xx <= w - 1))
        batch = jnp.arange(n).reshape(n, 1, 1)
        vals = data[batch, :, yi, xi]  # (N,Ho,Wo,C)
        vals = jnp.moveaxis(vals, -1, 1)
        return vals * valid[:, None].astype(data.dtype)

    out = (gather(y0, x0) * (wy0 * wx0)[:, None]
           + gather(y0, x1) * (wy0 * wx1)[:, None]
           + gather(y1, x0) * (wy1 * wx0)[:, None]
           + gather(y1, x1) * (wy1 * wx1)[:, None])
    return out


@register("BilinearSampler")
def _bilinear_sampler(data, grid, cudnn_off=False, **kw):
    return _bilinear_sample(data, grid)


@register("SpatialTransformer")
def _spatial_transformer(data, loc, target_shape=(0, 0),
                         transform_type="affine", sampler_type="bilinear",
                         cudnn_off=False, **kw):
    grid = _grid_generator(loc, transform_type, target_shape)
    return _bilinear_sample(data, grid)


@register("Crop", nondiff_inputs=(1,))
def _crop_op(*args, offset=(0, 0), h_w=(0, 0), center_crop=False,
             num_args=1, **kw):
    data = args[0]
    if len(args) > 1:
        th, tw = args[1].shape[2], args[1].shape[3]
    else:
        th, tw = int(h_w[0]), int(h_w[1])
    if center_crop:
        oy = (data.shape[2] - th) // 2
        ox = (data.shape[3] - tw) // 2
    else:
        oy, ox = int(offset[0]), int(offset[1])
    return data[:, :, oy:oy + th, ox:ox + tw]


@register("ROIPooling", nondiff_inputs=(1,))
def _roi_pooling(data, rois, pooled_size=(1, 1), spatial_scale=1.0, **kw):
    """ROI max pooling via per-bin masked max (XLA-friendly, no dynamic shapes)."""
    n, c, h, w = data.shape
    ph, pw = int(pooled_size[0]), int(pooled_size[1])

    ys = jnp.arange(h, dtype=data.dtype)
    xs = jnp.arange(w, dtype=data.dtype)

    def one_roi(roi):
        b = roi[0].astype(jnp.int32)
        x1 = jnp.round(roi[1] * spatial_scale)
        y1 = jnp.round(roi[2] * spatial_scale)
        x2 = jnp.round(roi[3] * spatial_scale)
        y2 = jnp.round(roi[4] * spatial_scale)
        rh = jnp.maximum(y2 - y1 + 1, 1.0)
        rw = jnp.maximum(x2 - x1 + 1, 1.0)
        bh, bw = rh / ph, rw / pw
        img = data[b]  # (C,H,W)

        def bin_val(i, j):
            ys0 = y1 + jnp.floor(i * bh)
            ys1 = y1 + jnp.ceil((i + 1) * bh)
            xs0 = x1 + jnp.floor(j * bw)
            xs1 = x1 + jnp.ceil((j + 1) * bw)
            ymask = (ys >= ys0) & (ys < jnp.maximum(ys1, ys0 + 1)) & (ys <= y2)
            xmask = (xs >= xs0) & (xs < jnp.maximum(xs1, xs0 + 1)) & (xs <= x2)
            mask = ymask[:, None] & xmask[None, :]
            masked = jnp.where(mask[None], img, -jnp.inf)
            v = jnp.max(masked, axis=(1, 2))
            return jnp.where(jnp.isfinite(v), v, 0.0)

        ii, jj = jnp.meshgrid(jnp.arange(ph, dtype=data.dtype),
                              jnp.arange(pw, dtype=data.dtype), indexing="ij")
        vals = jax.vmap(jax.vmap(bin_val))(ii, jj)  # (ph,pw,C)
        return jnp.moveaxis(vals, -1, 0)

    return jax.vmap(one_roi)(rois)


@register("Correlation")
def _correlation(data1, data2, kernel_size=1, max_displacement=1, stride1=1,
                 stride2=1, pad_size=0, is_multiply=True, **kw):
    n, c, h, w = data1.shape
    pad = int(pad_size)
    d1 = jnp.pad(data1, ((0, 0), (0, 0), (pad, pad), (pad, pad)))
    d2 = jnp.pad(data2, ((0, 0), (0, 0), (pad, pad), (pad, pad)))
    md = int(max_displacement)
    s2 = int(stride2)
    disps = range(-md, md + 1, s2)
    outs = []
    hh, ww = d1.shape[2], d1.shape[3]
    for dy in disps:
        for dx in disps:
            shifted = jnp.roll(d2, (-dy, -dx), axis=(2, 3))
            if is_multiply:
                prod = jnp.mean(d1 * shifted, axis=1)
            else:
                prod = jnp.mean(jnp.abs(d1 - shifted), axis=1)
            outs.append(prod)
    out = jnp.stack(outs, axis=1)
    return out[:, :, pad:hh - pad, pad:ww - pad]
