"""Elementwise operator corpus (unary / binary / scalar / comparison).

Reference analogue: ``src/operator/tensor/elemwise_unary_op_*.cc``,
``elemwise_binary_op*.cc``, ``*_scalar_op*.cc``, ``mshadow_op.h`` functor zoo
(SURVEY §2.2).  On TPU every one of these is a single XLA HLO that fuses into
neighbours, so the whole file is just jnp lambdas behind the registry.

MXNet name conventions preserved: ``elemwise_add``/``_plus``/``broadcast_add``
all exist; scalar variants take attr ``scalar``; reverse variants ``_r*``.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.scipy import special as jsp_special

from .registry import register, Op, OP_REGISTRY

_f = jnp.asarray


def _reg_unary(name, fn, aliases=()):
    register(name, aliases=aliases)(lambda x, **kw: fn(x))


# --- unary math (reference: elemwise_unary_op_basic.cc, _trig.cc) -----------
_UNARY = {
    "abs": jnp.abs,
    "arccos": jnp.arccos, "arccosh": jnp.arccosh,
    "arcsin": jnp.arcsin, "arcsinh": jnp.arcsinh,
    "arctan": jnp.arctan, "arctanh": jnp.arctanh,
    "cbrt": jnp.cbrt, "ceil": jnp.ceil,
    "cos": jnp.cos, "cosh": jnp.cosh,
    "degrees": jnp.degrees, "exp": jnp.exp, "expm1": jnp.expm1,
    "fix": jnp.trunc, "floor": jnp.floor,
    "gamma": lambda x: jnp.exp(jsp_special.gammaln(x)),
    "gammaln": jsp_special.gammaln,
    "log": jnp.log, "log10": jnp.log10, "log1p": jnp.log1p, "log2": jnp.log2,
    "negative": jnp.negative,
    "radians": jnp.radians,
    "rcbrt": lambda x: 1.0 / jnp.cbrt(x),
    "reciprocal": lambda x: 1.0 / x,
    "relu": lambda x: jnp.maximum(x, 0),
    "rint": jnp.rint, "round": jnp.round,
    "rsqrt": lambda x: 1.0 / jnp.sqrt(x),
    "sigmoid": jax.nn.sigmoid,
    "sign": jnp.sign, "sin": jnp.sin, "sinh": jnp.sinh,
    "sqrt": jnp.sqrt, "square": jnp.square,
    "tan": jnp.tan, "tanh": jnp.tanh, "trunc": jnp.trunc,
    "erf": jax.lax.erf,
    "erfinv": jax.lax.erf_inv,
    "softsign": jax.nn.soft_sign,
}
for _name, _fn in _UNARY.items():
    _reg_unary(_name, _fn)

register("_copy", aliases=["identity"])(lambda x, **kw: x)


def _block_grad_bwd(out_grads, inputs, outputs, attrs):
    return (jnp.zeros_like(inputs[0]),)


register("BlockGrad", aliases=["stop_gradient"], custom_vjp=_block_grad_bwd)(
    lambda x, **kw: jax.lax.stop_gradient(x))


def _make_loss_bwd(out_grads, inputs, outputs, attrs):
    # reference make_loss: gradient is ones (the output *is* the loss)
    return (jnp.ones_like(inputs[0]) * attrs.get("grad_scale", 1.0),)


register("make_loss", custom_vjp=_make_loss_bwd)(lambda x, **kw: x)


@register("smooth_l1")
def _smooth_l1(x, scalar=1.0, **kw):
    s2 = scalar * scalar
    absx = jnp.abs(x)
    return jnp.where(absx < 1.0 / s2, 0.5 * s2 * x * x, absx - 0.5 / s2)


@register("softmax")
def _softmax(x, axis=-1, temperature=None, **kw):
    if temperature is not None and temperature != 1.0:
        x = x / temperature
    return jax.nn.softmax(x, axis=axis)


@register("log_softmax")
def _log_softmax(x, axis=-1, temperature=None, **kw):
    if temperature is not None and temperature != 1.0:
        x = x / temperature
    return jax.nn.log_softmax(x, axis=axis)


@register("softmin")
def _softmin(x, axis=-1, **kw):
    return jax.nn.softmax(-x, axis=axis)


@register("Cast", aliases=["cast"])
def _cast(x, dtype="float32", **kw):
    from ..base import dtype_np
    return x.astype(dtype_np(dtype))


@register("clip")
def _clip(x, a_min=None, a_max=None, **kw):
    return jnp.clip(x, a_min, a_max)


# --- binary elemwise + broadcast (reference: elemwise_binary_op_basic.cc,
# broadcast ops in elemwise_binary_broadcast_op_*.cc) ------------------------
_BINARY = {
    "add": jnp.add, "sub": jnp.subtract, "mul": jnp.multiply,
    "div": jnp.divide, "mod": jnp.mod, "power": jnp.power,
    "maximum": jnp.maximum, "minimum": jnp.minimum, "hypot": jnp.hypot,
}
_CMP = {
    "equal": jnp.equal, "not_equal": jnp.not_equal,
    "greater": jnp.greater, "greater_equal": jnp.greater_equal,
    "lesser": jnp.less, "lesser_equal": jnp.less_equal,
    "logical_and": jnp.logical_and, "logical_or": jnp.logical_or,
    "logical_xor": jnp.logical_xor,
}
_OLD_NAMES = {"add": "_plus", "sub": "_minus", "mul": "_mul", "div": "_div"}


def _mk_binary(fn, as_dtype=False):
    if as_dtype:
        return lambda a, b, **kw: fn(a, b).astype(a.dtype)
    return lambda a, b, **kw: fn(a, b)


for _n, _fn in _BINARY.items():
    _b = _mk_binary(_fn)
    aliases = ["broadcast_%s" % _n, "_%s" % _n]
    if _n in _OLD_NAMES:
        aliases.append(_OLD_NAMES[_n])
    if _n in ("maximum", "minimum", "hypot"):
        aliases.append(_n)  # public numpy-style names
    register("elemwise_%s" % _n, aliases=aliases)(_b)

for _n, _fn in _CMP.items():
    _b = _mk_binary(_fn, as_dtype=True)
    register("_%s" % _n, aliases=["broadcast_%s" % _n])(_b)

register("_grad_add")(_mk_binary(jnp.add))


def _bwd_div_out_zero(out_grads, inputs, outputs, attrs):
    raise NotImplementedError


# scalar variants (reference: elemwise_binary_scalar_op_*.cc)
_SCALAR = {
    "_plus_scalar": lambda x, s: x + s,
    "_minus_scalar": lambda x, s: x - s,
    "_rminus_scalar": lambda x, s: s - x,
    "_mul_scalar": lambda x, s: x * s,
    "_div_scalar": lambda x, s: x / s,
    "_rdiv_scalar": lambda x, s: s / x,
    "_mod_scalar": lambda x, s: jnp.mod(x, s),
    "_rmod_scalar": lambda x, s: jnp.mod(s, x),
    "_power_scalar": lambda x, s: jnp.power(x, s),
    "_rpower_scalar": lambda x, s: jnp.power(s, x),
    "_maximum_scalar": lambda x, s: jnp.maximum(x, s),
    "_minimum_scalar": lambda x, s: jnp.minimum(x, s),
    "_hypot_scalar": lambda x, s: jnp.hypot(x, _f(s).astype(x.dtype)),
    "_equal_scalar": lambda x, s: (x == s).astype(x.dtype),
    "_not_equal_scalar": lambda x, s: (x != s).astype(x.dtype),
    "_greater_scalar": lambda x, s: (x > s).astype(x.dtype),
    "_greater_equal_scalar": lambda x, s: (x >= s).astype(x.dtype),
    "_lesser_scalar": lambda x, s: (x < s).astype(x.dtype),
    "_lesser_equal_scalar": lambda x, s: (x <= s).astype(x.dtype),
    "_logical_and_scalar": lambda x, s: jnp.logical_and(x, s).astype(x.dtype),
    "_logical_or_scalar": lambda x, s: jnp.logical_or(x, s).astype(x.dtype),
    "_logical_xor_scalar": lambda x, s: jnp.logical_xor(x, s).astype(x.dtype),
}


def _mk_scalar(fn):
    return lambda x, scalar=0.0, **kw: fn(x, scalar)


for _n, _fn in _SCALAR.items():
    register(_n)(_mk_scalar(_fn))

register("_scatter_plus_scalar")(_mk_scalar(lambda x, s: x + s))
register("_scatter_minus_scalar")(_mk_scalar(lambda x, s: x - s))
register("_scatter_elemwise_div")(_mk_binary(jnp.divide))


@register("add_n", aliases=["ElementWiseSum", "_sparse_add_n"])
def _add_n(*args, num_args=None, **kw):
    out = args[0]
    for a in args[1:]:
        out = out + a
    return out


@register("elemwise_sum")
def _elemwise_sum(*args, num_args=None, **kw):
    return _add_n(*args)


@register("_identity_with_attr_like_rhs", nondiff_inputs=(1,))
def _id_attr_like(lhs, rhs, **kw):
    return lhs


@register("where", nondiff_inputs=(0,))
def _where(cond, x, y, **kw):
    return jnp.where(cond.astype(bool), x, y)
