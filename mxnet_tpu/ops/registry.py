"""Operator registry: one pure-JAX function per op, shared by every frontend.

Reference analogue: the NNVM ``Op`` registry plus its typed attributes
(``include/mxnet/op_attr_types.h:184-261`` — FCompute/FGradient/
FInferStorageType) and the dmlc parameter reflection system
(``DMLC_DECLARE_PARAMETER``, e.g. ConvolutionParam at
``src/operator/convolution-inl.h:56``).

TPU-first redesign: an op is a *pure function* ``fn(*jax_arrays, **attrs)``
returning one or more jax arrays.  There are no per-device kernels, no
FCompute/FComputeEx split, and no storage-type dispatch — XLA compiles and
fuses everything.  Gradients come from ``jax.vjp`` over the same function
(replacing hand-written FGradient registrations), except where the reference
defines a *semantic* gradient that differs from the mathematical one
(SoftmaxOutput, MakeLoss, BlockGrad ...), which declare ``custom_vjp``.

Attributes serialize to strings for symbol-JSON parity
(reference symbols store every param stringified).
"""
from __future__ import annotations

import ast

import numpy as np

from ..base import MXNetError

__all__ = ["Op", "register", "get_op", "list_ops", "OP_REGISTRY",
           "parse_attr_string", "attr_to_string"]

OP_REGISTRY = {}


def parse_attr_string(v):
    """Parse a stringified attr back to a python value (symbol JSON parity)."""
    if not isinstance(v, str):
        return v
    s = v.strip()
    low = s.lower()
    if low in ("true", "false"):
        return low == "true"
    if low in ("none", "null"):
        return None
    try:
        return ast.literal_eval(s)
    except (ValueError, SyntaxError):
        return v


def attr_to_string(v):
    if isinstance(v, str):
        return v
    if isinstance(v, (bool, int, float, type(None))):
        return str(v)
    if isinstance(v, (tuple, list)):
        if len(v) == 1:  # "(64,)" — "(64)" would parse back as an int
            return "(%s,)" % v[0]
        return "(" + ", ".join(str(x) for x in v) + ")"
    if isinstance(v, np.dtype):
        return v.name
    return str(v)


class Op:
    """A registered operator.

    Parameters
    ----------
    name : canonical op name (reference-compatible, e.g. ``Convolution``).
    fn : pure function ``(*arrays, **attrs) -> array | tuple``.  If
        ``takes_mode``, it receives ``train_mode=<bool>``; if ``needs_rng`` it
        receives ``rng=<jax PRNG key>``.  Both are trace-safe (static bool /
        traced key), which is what makes the whole graph jittable.
    num_outputs : int or callable(attrs) -> int.
    num_visible_outputs : outputs exposed to the user (reference: BatchNorm
        registers 3 outputs, 1 visible).
    nondiff_inputs : input positions excluded from autograd (labels, aux
        state) — reference analogue: DeclareBackwardDependency pruning.
    aux_updates : {aux_input_pos: output_pos} — outputs that are *new values
        of auxiliary state* (BatchNorm moving stats).  Eager mode writes them
        back into the aux NDArray; the executor updates its aux dict; they are
        never differentiated.
    custom_vjp : optional ``(attrs) -> (fwd_fn, bwd_fn)``-style override; here
        simply a function ``bwd(out_grads, inputs, outputs, attrs) ->
        input_grads`` used instead of jax.vjp (semantic gradients).
    """

    def __init__(self, name, fn, num_outputs=1, num_visible_outputs=None,
                 nondiff_inputs=(), aux_updates=None, takes_mode=False,
                 needs_rng=False, custom_vjp=None, attr_defaults=None,
                 no_inputs=False):
        self.name = name
        self.fn = fn
        self.num_outputs = num_outputs
        self.num_visible_outputs = num_visible_outputs
        self.nondiff_inputs = tuple(nondiff_inputs)
        self.aux_updates = dict(aux_updates or {})
        self.takes_mode = takes_mode
        self.needs_rng = needs_rng
        self.custom_vjp = custom_vjp
        self.attr_defaults = dict(attr_defaults or {})
        self.no_inputs = no_inputs  # creation ops (zeros, ones, arange, random)

    def n_outputs(self, attrs):
        if callable(self.num_outputs):
            return self.num_outputs(attrs)
        return self.num_outputs

    def n_visible_outputs(self, attrs):
        if self.num_visible_outputs is None:
            n = self.n_outputs(attrs)
            return n - len(self.aux_updates)
        if callable(self.num_visible_outputs):
            return self.num_visible_outputs(attrs)
        return self.num_visible_outputs

    def apply(self, inputs, attrs, train_mode=False, rng=None):
        """Run the pure function; always returns a tuple of jax arrays."""
        kw = dict(attrs)
        if self.takes_mode:
            kw["train_mode"] = train_mode
        if self.needs_rng:
            kw["rng"] = rng
        out = self.fn(*inputs, **kw)
        if isinstance(out, (tuple, list)):
            return tuple(out)
        return (out,)

    def traceable(self, attrs, train_mode=False, rng=None):
        """Return a jax-traceable callable ``f(*arrays) -> tuple`` with attrs
        closed over, honoring ``custom_vjp`` under jax transforms (the
        executor-path analogue of the eager tape's semantic gradients)."""
        import jax as _jax

        if self.custom_vjp is None:
            def plain(*arrs):
                return self.apply(arrs, attrs, train_mode=train_mode, rng=rng)
            return plain

        bwd_rule = self.custom_vjp

        @_jax.custom_vjp
        def f(*arrs):
            return self.apply(arrs, attrs, train_mode=train_mode, rng=rng)

        def fwd(*arrs):
            out = self.apply(arrs, attrs, train_mode=train_mode, rng=rng)
            return out, (arrs, out)

        def bwd(res, gout):
            arrs, out = res
            grads = bwd_rule(gout, arrs, out, attrs)
            return tuple(grads)

        f.defvjp(fwd, bwd)
        return f

    def param_table(self):
        """Typed parameter reflection (the dmlc-Parameter analogue,
        ref DMLC_DECLARE_PARAMETER / SURVEY §5.6): [(name, type, default)]
        derived from the kernel signature."""
        import inspect
        rows = []
        try:
            sig = inspect.signature(self.fn)
        except (TypeError, ValueError):
            return rows
        for p in sig.parameters.values():
            if p.default is inspect.Parameter.empty:
                continue
            if p.name in ("train_mode", "rng") or p.kind == p.VAR_KEYWORD:
                continue
            default = self.attr_defaults.get(p.name, p.default)
            rows.append((p.name, type(default).__name__, default))
        return rows

    def describe(self):
        """Human-readable op description with its parameter table."""
        lines = ["Operator %s" % self.name]
        doc = (self.fn.__doc__ or "").strip()
        if doc:
            lines.append(doc)
        rows = self.param_table()
        if rows:
            lines.append("")
            lines.append("Parameters")
            lines.append("----------")
            for name, tname, default in rows:
                lines.append("%s : %s, default %r" % (name, tname, default))
        return "\n".join(lines)

    def __repr__(self):
        return "Op(%s)" % self.name


def register(name, aliases=(), **kwargs):
    """Decorator: register a pure function as operator ``name``."""
    def deco(fn):
        op = Op(name, fn, **kwargs)
        OP_REGISTRY[name] = op
        for a in aliases:
            OP_REGISTRY[a] = op
        return fn
    return deco


def get_op(name):
    if name not in OP_REGISTRY:
        raise MXNetError("Operator %s is not registered (have %d ops)"
                         % (name, len(OP_REGISTRY)))
    return OP_REGISTRY[name]


def list_ops():
    return sorted(OP_REGISTRY)
