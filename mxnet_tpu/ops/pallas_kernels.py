"""Pallas TPU kernels for the hot ops.

Reference analogue: the RTC/custom-kernel surface (``src/common/rtc.cc``,
NVRTC runtime CUDA compilation; SURVEY §2.1 "RTC") — on TPU, user-authored
kernels are Pallas.  This module holds the framework's built-in kernels:

- ``flash_attention``: tiled online-softmax attention.  Grid is
  (batch·heads, q blocks, k blocks); the k dimension is the innermost
  (sequential) grid axis, so each program sees ONE [block_k, D] K/V tile in
  VMEM while fp32 accumulators persist in scratch across k steps — true
  streaming, O(block·D) VMEM regardless of sequence length.  Causal
  programs whose whole K tile is masked skip compute via ``pl.when``.
  Differentiable via ``jax.custom_vjp``; the backward recomputes scores in
  q-row chunks (O(chunk·S) memory, not O(S²)).

On non-TPU backends the kernels run in Pallas interpret mode (tests) or
callers fall back to the jnp reference (``parallel/ring_attention.py``'s
``local_attention``).
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp

try:
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu  # noqa: F401 (probe)
    HAS_PALLAS = True
except Exception:  # pragma: no cover
    HAS_PALLAS = False

__all__ = ["flash_attention", "HAS_PALLAS"]

_NEG = -1e30
_LANES = 128  # m/l scratch is lane-replicated to satisfy TPU tiling


def _attn_kernel(q_ref, k_ref, v_ref, o_ref, acc_ref, m_ref, l_ref, *,
                 block_q, block_k, causal, sm_scale, seq_len):
    """One (bh, qi, ki) program. Scratch (acc/m/l) carries across ki —
    the innermost grid axis is sequential on TPU."""
    qi = pl.program_id(1)
    ki = pl.program_id(2)
    num_k = pl.num_programs(2)

    @pl.when(ki == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, _NEG)
        l_ref[:] = jnp.zeros_like(l_ref)

    # causal: skip K tiles strictly in the future of this q block
    live = True
    if causal:
        live = (qi + 1) * block_q - 1 >= ki * block_k

    @pl.when(live)
    def _step():
        q = q_ref[:].astype(jnp.float32) * sm_scale
        kb = k_ref[:].astype(jnp.float32)
        vb = v_ref[:].astype(jnp.float32)
        s = jax.lax.dot_general(q, kb, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        k_pos = ki * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1)
        valid = k_pos < seq_len          # mask the padded K tail
        if causal:
            q_pos = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            valid = jnp.logical_and(valid, q_pos >= k_pos)
        s = jnp.where(valid, s, _NEG)

        m_prev = m_ref[:, 0]
        blk_max = jnp.max(s, axis=-1)
        m_new = jnp.maximum(m_prev, blk_max)
        corr = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new[:, None])
        l_new = l_ref[:, 0] * corr + jnp.sum(p, axis=-1)
        acc_ref[:] = acc_ref[:] * corr[:, None] + jax.lax.dot_general(
            p, vb, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_ref[:] = jnp.broadcast_to(m_new[:, None], m_ref.shape)
        l_ref[:] = jnp.broadcast_to(l_new[:, None], l_ref.shape)

    @pl.when(ki == num_k - 1)
    def _finalize():
        o_ref[:] = (acc_ref[:] /
                    jnp.maximum(l_ref[:, 0], 1e-30)[:, None]
                    ).astype(o_ref.dtype)


def _flash_fwd_impl(q, k, v, causal, sm_scale, block_q, block_k, interpret):
    b, h, s, d = q.shape
    scale = sm_scale if sm_scale is not None else 1.0 / math.sqrt(d)
    bq = min(block_q, s)
    bk = min(block_k, s)
    qf = q.reshape(b * h, s, d)
    kf = k.reshape(b * h, s, d)
    vf = v.reshape(b * h, s, d)
    # pad K/V to a block multiple: an out-of-bounds block index CLAMPS,
    # silently shifting the tail tile — padded keys are masked by seq_len
    s_pad = ((s + bk - 1) // bk) * bk
    if s_pad != s:
        pad = [(0, 0), (0, s_pad - s), (0, 0)]
        kf = jnp.pad(kf, pad)
        vf = jnp.pad(vf, pad)
    kernel = functools.partial(_attn_kernel, block_q=bq, block_k=bk,
                               causal=causal, sm_scale=scale, seq_len=s)
    out = pl.pallas_call(
        kernel,
        grid=(b * h, pl.cdiv(s, bq), s_pad // bk),
        in_specs=[
            pl.BlockSpec((None, bq, d), lambda bh, i, t: (bh, i, 0)),
            pl.BlockSpec((None, bk, d), lambda bh, i, t: (bh, t, 0)),
            pl.BlockSpec((None, bk, d), lambda bh, i, t: (bh, t, 0)),
        ],
        out_specs=pl.BlockSpec((None, bq, d), lambda bh, i, t: (bh, i, 0)),
        out_shape=jax.ShapeDtypeStruct((b * h, s, d), q.dtype),
        scratch_shapes=[
            pltpu.VMEM((bq, d), jnp.float32),
            pltpu.VMEM((bq, _LANES), jnp.float32),
            pltpu.VMEM((bq, _LANES), jnp.float32),
        ],
        interpret=interpret,
    )(qf, kf, vf)
    return out.reshape(b, h, s, d)


def _chunked_attn_grads(q, k, v, do, causal, sm_scale, chunk=512):
    """Recompute backward in q-row chunks: memory O(chunk·S) per step
    instead of materializing the full S×S score/softmax matrices."""
    b, h, s, d = q.shape
    scale = sm_scale if sm_scale is not None else 1.0 / math.sqrt(d)
    c = min(chunk, s)
    n = (s + c - 1) // c
    s_pad = n * c
    f32 = jnp.float32

    def padq(x):
        if s_pad != s:
            x = jnp.pad(x, [(0, 0), (0, 0), (0, s_pad - s), (0, 0)])
        return x.astype(f32).reshape(b, h, n, c, d).transpose(2, 0, 1, 3, 4)

    qs, dos = padq(q), padq(do)
    kf = k.astype(f32)
    vf = v.astype(f32)
    k_pos = jnp.arange(s)

    def body(carry, inp):
        dk_acc, dv_acc, i = carry
        q_c, do_c = inp
        s_c = jnp.einsum("bhqd,bhkd->bhqk", q_c, kf) * scale
        q_pos = i * c + jnp.arange(c)
        valid = (q_pos[:, None] < s)
        if causal:
            valid = jnp.logical_and(valid, q_pos[:, None] >= k_pos[None, :])
        s_c = jnp.where(valid, s_c, _NEG)
        p = jax.nn.softmax(s_c, axis=-1)
        dv_acc = dv_acc + jnp.einsum("bhqk,bhqd->bhkd", p, do_c)
        dp = jnp.einsum("bhqd,bhkd->bhqk", do_c, vf)
        ds = p * (dp - jnp.sum(dp * p, axis=-1, keepdims=True))
        ds = jnp.where(valid, ds, 0.0)
        dq_c = jnp.einsum("bhqk,bhkd->bhqd", ds, kf) * scale
        dk_acc = dk_acc + jnp.einsum("bhqk,bhqd->bhkd", ds, q_c) * scale
        return (dk_acc, dv_acc, i + 1), dq_c

    zeros = jnp.zeros((b, h, s, d), f32)
    (dk, dv, _), dq_chunks = jax.lax.scan(
        body, (zeros, zeros, jnp.int32(0)), (qs, dos))
    dq = dq_chunks.transpose(1, 2, 0, 3, 4).reshape(b, h, s_pad, d)[:, :, :s]
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def flash_attention(q, k, v, causal=False, sm_scale=None, block_q=128,
                    block_k=128, interpret=False):
    """Tiled flash attention: q, k, v [B, H, S, D] -> [B, H, S, D].

    Pallas streaming forward (K/V tiles via the sequential grid axis,
    causal tile skipping); q-chunked recompute backward.
    ``interpret=True`` runs the kernel in the Pallas interpreter (CPU
    tests).  Shard batch/head dims with ``shard_map`` before calling —
    pallas_call is opaque to GSPMD.
    """
    return _flash_fwd_impl(q, k, v, causal, sm_scale, block_q, block_k,
                           interpret)


def _fwd(q, k, v, causal, sm_scale, block_q, block_k, interpret):
    out = _flash_fwd_impl(q, k, v, causal, sm_scale, block_q, block_k,
                          interpret)
    return out, (q, k, v)


def _bwd(causal, sm_scale, block_q, block_k, interpret, res, do):
    q, k, v = res
    return _chunked_attn_grads(q, k, v, do, causal, sm_scale)


flash_attention.defvjp(_fwd, _bwd)
