"""Random sampling ops (counter-based JAX PRNG behind MXNet's sampling API).

Reference analogue: ``src/operator/random/sample_op.cc`` (``_random_*`` shape-
parameterized samplers and ``_sample_*`` tensor-parameterized variants,
SURVEY appendix A) backed by a per-device parallel RNG resource
(``ResourceRequest::kRandom``).  TPU-native: every sampler is a pure function
of an explicit threefry key (``needs_rng``), so sampling is reproducible,
jit-safe, and shardable — the "RNG resource" is just key-splitting.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .registry import register
from ..base import dtype_np


def _shape(shape):
    if isinstance(shape, int):
        return (shape,)
    return tuple(shape or ())


def _reg_random(name, fn):
    register(name, aliases=["random_" + name.split("_random_")[-1]]
             if name.startswith("_random_") else [], needs_rng=True,
             no_inputs=True)(fn)


@register("_random_uniform", aliases=["uniform", "random_uniform"],
          needs_rng=True, no_inputs=True)
def _random_uniform(low=0.0, high=1.0, shape=(), dtype="float32", ctx=None,
                    rng=None, **kw):
    return jax.random.uniform(rng, _shape(shape), dtype_np(dtype), low, high)


@register("_random_normal", aliases=["normal", "random_normal"],
          needs_rng=True, no_inputs=True)
def _random_normal(loc=0.0, scale=1.0, shape=(), dtype="float32", ctx=None,
                   rng=None, **kw):
    return loc + scale * jax.random.normal(rng, _shape(shape), dtype_np(dtype))


@register("_random_gamma", aliases=["random_gamma"], needs_rng=True, no_inputs=True)
def _random_gamma(alpha=1.0, beta=1.0, shape=(), dtype="float32", ctx=None,
                  rng=None, **kw):
    return jax.random.gamma(rng, alpha, _shape(shape), dtype_np(dtype)) * beta


@register("_random_exponential", aliases=["random_exponential"], needs_rng=True,
          no_inputs=True)
def _random_exponential(lam=1.0, shape=(), dtype="float32", ctx=None, rng=None, **kw):
    return jax.random.exponential(rng, _shape(shape), dtype_np(dtype)) / lam


@register("_random_poisson", aliases=["random_poisson"], needs_rng=True,
          no_inputs=True)
def _random_poisson(lam=1.0, shape=(), dtype="float32", ctx=None, rng=None, **kw):
    return jax.random.poisson(rng, lam, _shape(shape)).astype(dtype_np(dtype))


@register("_random_negative_binomial", aliases=["random_negative_binomial"],
          needs_rng=True, no_inputs=True)
def _random_negbin(k=1, p=1.0, shape=(), dtype="float32", ctx=None, rng=None, **kw):
    k1, k2 = jax.random.split(rng)
    lam = jax.random.gamma(k1, float(k), _shape(shape)) * (1 - p) / p
    return jax.random.poisson(k2, lam, _shape(shape)).astype(dtype_np(dtype))


@register("_random_generalized_negative_binomial",
          aliases=["random_generalized_negative_binomial"], needs_rng=True,
          no_inputs=True)
def _random_gnegbin(mu=1.0, alpha=1.0, shape=(), dtype="float32", ctx=None,
                    rng=None, **kw):
    k1, k2 = jax.random.split(rng)
    if alpha == 0:
        return jax.random.poisson(k1, mu, _shape(shape)).astype(dtype_np(dtype))
    r = 1.0 / alpha
    p = r / (r + mu)
    lam = jax.random.gamma(k1, r, _shape(shape)) * (1 - p) / p
    return jax.random.poisson(k2, lam, _shape(shape)).astype(dtype_np(dtype))


@register("_random_randint", aliases=["random_randint"], needs_rng=True,
          no_inputs=True)
def _random_randint(low=0, high=1, shape=(), dtype="int32", ctx=None, rng=None, **kw):
    return jax.random.randint(rng, _shape(shape), int(low), int(high)).astype(
        dtype_np(dtype))


@register("_sample_multinomial", aliases=["sample_multinomial"], needs_rng=True,
          nondiff_inputs=(0,))
def _sample_multinomial(data, shape=(), get_prob=False, dtype="int32",
                        rng=None, **kw):
    n = int(jnp.prod(jnp.array(_shape(shape)))) if shape else 1
    logits = jnp.log(jnp.maximum(data, 1e-37))
    out_shape = data.shape[:-1] + (_shape(shape) or (1,))[0:len(_shape(shape)) or 1]
    samp = jax.random.categorical(rng, logits, axis=-1,
                                  shape=(_shape(shape) or (1,)) + data.shape[:-1])
    samp = jnp.moveaxis(samp, 0, -1)
    if not shape:
        samp = samp[..., 0]
    samp = samp.astype(dtype_np(dtype))
    if get_prob:
        logp = jax.nn.log_softmax(logits, axis=-1)
        lp = jnp.take_along_axis(
            logp, samp.reshape(data.shape[:-1] + (-1,)).astype(jnp.int32), axis=-1)
        return samp, lp.reshape(samp.shape)
    return samp


# tensor-parameterized samplers: _sample_uniform(low_arr, high_arr, shape=s)
def _mk_tensor_sampler(sampler):
    def fn(*params, shape=(), dtype="float32", rng=None, **kw):
        s = _shape(shape)
        def one(key, *p):
            return sampler(key, s, dtype_np(dtype), *p)
        n = params[0].shape[0] if params[0].ndim else 1
        keys = jax.random.split(rng, n)
        flat = [p.reshape(n) if p.ndim else p.reshape(1) for p in params]
        out = jax.vmap(one)(keys, *flat)
        return out.reshape(params[0].shape + s)
    return fn


register("_sample_uniform", aliases=["sample_uniform"], needs_rng=True,
         nondiff_inputs=(0, 1))(
    _mk_tensor_sampler(lambda k, s, d, lo, hi: jax.random.uniform(k, s, d, lo, hi)))
register("_sample_normal", aliases=["sample_normal"], needs_rng=True,
         nondiff_inputs=(0, 1))(
    _mk_tensor_sampler(lambda k, s, d, mu, sig: mu + sig * jax.random.normal(k, s, d)))
register("_sample_gamma", aliases=["sample_gamma"], needs_rng=True,
         nondiff_inputs=(0, 1))(
    _mk_tensor_sampler(lambda k, s, d, a, b: jax.random.gamma(k, a, s, d) * b))
register("_sample_exponential", aliases=["sample_exponential"], needs_rng=True,
         nondiff_inputs=(0,))(
    _mk_tensor_sampler(lambda k, s, d, lam: jax.random.exponential(k, s, d) / lam))
register("_sample_poisson", aliases=["sample_poisson"], needs_rng=True,
         nondiff_inputs=(0,))(
    _mk_tensor_sampler(lambda k, s, d, lam: jax.random.poisson(k, lam, s).astype(d)))


@register("shuffle", aliases=["_shuffle"], needs_rng=True)
def _shuffle(data, rng=None, **kw):
    return jax.random.permutation(rng, data, axis=0)
