"""Global random state: counter-based threefry keys behind ``mx.random.seed``.

Reference analogue: the per-device parallel RNG resource
(``src/resource.cc``, ``ResourceRequest::kRandom``) seeded by
``mx.random.seed`` (``python/mxnet/random.py``).  TPU-native: one root key +
a split counter; every sampling op consumes a fresh subkey, so eager sampling
is reproducible given a seed, and jitted graphs thread keys explicitly.
"""
from __future__ import annotations

import threading

import jax
import numpy as np

__all__ = ["seed", "next_key", "current_seed", "key_scope", "host_rng",
           "get_state", "set_state"]

_lock = threading.Lock()
_seed = 0
_key = None  # lazily created: backend init must not run at import time
_host_rng = None  # np.random.Generator once seeded (host-side draws)
_scope = threading.local()  # per-thread key override stack (jit tracing)


def seed(seed_state, ctx="all"):
    """Seed the global RNG (reference: mx.random.seed)."""
    global _key, _seed, _host_rng
    with _lock:
        _seed = int(seed_state)
        _key = jax.random.PRNGKey(_seed)
        _host_rng = np.random.default_rng(_seed)


def host_rng():
    """The numpy RNG for host-side draws (initializers, shuffles).

    After ``mx.random.seed(n)`` this is a dedicated
    ``np.random.default_rng(n)`` Generator, so host randomness is governed
    by the framework seed instead of numpy's hidden module state (and
    never races third-party ``np.random`` users).  Before any ``seed()``
    call it falls back to the legacy ``np.random`` module so unseeded
    behavior is unchanged.  Both expose the same draw API surface used
    here (``uniform``/``normal``/``shuffle``/``permutation``).
    """
    return _host_rng if _host_rng is not None else np.random


def next_key():
    stack = getattr(_scope, "stack", None)
    if stack:
        # inside a key_scope (jit trace): split the scoped key so traced
        # programs thread randomness explicitly (may be a tracer)
        stack[-1], sub = jax.random.split(stack[-1])
        return sub
    global _key
    with _lock:
        if _key is None:
            _key = jax.random.PRNGKey(_seed)
        _key, sub = jax.random.split(_key)
        return sub


class key_scope:
    """Thread randomness from an explicit key (used while jit-tracing)."""

    def __init__(self, key):
        self._key = key

    def __enter__(self):
        if not hasattr(_scope, "stack"):
            _scope.stack = []
        _scope.stack.append(self._key)
        return self

    def __exit__(self, *exc):
        _scope.stack.pop()


def current_seed():
    return _seed


def get_state():
    """Full RNG state as a host-side picklable dict (checkpointing).

    Captures the root jax key (as numpy), the seeded host Generator's
    bit-generator state, and — when :func:`seed` was never called — the
    legacy ``np.random`` module state, so a restored run replays the
    exact draw sequence (shuffles, initializers, key splits) either way.
    """
    with _lock:
        return {
            "seed": _seed,
            "key": None if _key is None else np.asarray(_key),
            "host": None if _host_rng is None
            else _host_rng.bit_generator.state,
            "host_legacy": np.random.get_state() if _host_rng is None
            else None,
        }


def set_state(state):
    """Restore a :func:`get_state` snapshot (checkpoint resume)."""
    global _seed, _key, _host_rng
    with _lock:
        _seed = int(state["seed"])
        _key = None if state["key"] is None \
            else jax.numpy.asarray(np.asarray(state["key"]))
        if state.get("host") is not None:
            _host_rng = np.random.default_rng(_seed)
            _host_rng.bit_generator.state = state["host"]
        else:
            _host_rng = None
            if state.get("host_legacy") is not None:
                np.random.set_state(state["host_legacy"])
