"""Global random state: counter-based threefry keys behind ``mx.random.seed``.

Reference analogue: the per-device parallel RNG resource
(``src/resource.cc``, ``ResourceRequest::kRandom``) seeded by
``mx.random.seed`` (``python/mxnet/random.py``).  TPU-native: one root key +
a split counter; every sampling op consumes a fresh subkey, so eager sampling
is reproducible given a seed, and jitted graphs thread keys explicitly.
"""
from __future__ import annotations

import threading

import jax

__all__ = ["seed", "next_key", "current_seed"]

_lock = threading.Lock()
_seed = 0
_key = None  # lazily created: backend init must not run at import time


def seed(seed_state, ctx="all"):
    """Seed the global RNG (reference: mx.random.seed)."""
    global _key, _seed
    with _lock:
        _seed = int(seed_state)
        _key = jax.random.PRNGKey(_seed)


def next_key():
    global _key
    with _lock:
        if _key is None:
            _key = jax.random.PRNGKey(_seed)
        _key, sub = jax.random.split(_key)
        return sub


def current_seed():
    return _seed
