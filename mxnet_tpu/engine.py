"""Engine facade: observable async semantics over XLA/PJRT dispatch.

Reference analogue: the threaded dependency engine
(``include/mxnet/engine.h:95-280``, ``src/engine/threaded_engine.cc``) whose
*observable* contract is: ops issue asynchronously; ``WaitForVar`` blocks
until pending writes land; ``WaitForAll`` drains everything; writes to one
buffer serialize, reads run in parallel (SURVEY §3.3).

On TPU the entire scheduler is XLA/PJRT: jax dispatch is already async, jax
arrays are immutable (so write-serialization is by construction — each
mutation produces a new buffer), and ``block_until_ready`` is WaitForVar.
This facade keeps the API (and the NaiveEngine-style ``--sync_dispatch``
debug mode, reference ``MXNET_ENGINE_TYPE=NaiveEngine``) for parity tests.
"""
from __future__ import annotations

import os

import jax

__all__ = ["wait_for_var", "wait_for_all", "push", "is_sync_dispatch",
           "set_sync_dispatch"]

_SYNC = os.environ.get("MXNET_ENGINE_TYPE", "") == "NaiveEngine"


def is_sync_dispatch():
    return _SYNC


def set_sync_dispatch(flag):
    """Debug mode: force synchronous execution after every op (the
    NaiveEngine idea — crashes surface with a usable backtrace)."""
    global _SYNC
    _SYNC = bool(flag)


def wait_for_var(arr):
    """Block until all pending computation producing ``arr`` is done."""
    jax.block_until_ready(arr)


def wait_for_all():
    """Engine::WaitForAll — drain every outstanding computation."""
    # PJRT has no global barrier; sync all live committed arrays is
    # unnecessary — an empty device sync per backend suffices.
    for dev in jax.devices():
        try:
            jax.device_put(0, dev).block_until_ready()
        except Exception:  # pragma: no cover
            pass


def push(fn, *args, **kwargs):
    """Run a function 'on the engine' (async by construction under jax)."""
    out = fn(*args, **kwargs)
    if _SYNC:
        jax.block_until_ready(out)
    return out
