"""Engine: dependency-scheduled host tasks + observable async semantics
over XLA/PJRT dispatch.

Reference analogue: the threaded dependency engine
(``include/mxnet/engine.h:95-280``, ``src/engine/threaded_engine.cc``) whose
observable contract is: ops issue asynchronously; ``WaitForVar`` blocks
until pending writes land; ``WaitForAll`` drains everything; writes to one
buffer serialize in push order, reads run in parallel (SURVEY §3.3).

TPU-native split of responsibilities:

* **Device side** — XLA/PJRT *is* the engine: jax dispatch is already
  async, jax arrays are immutable (write-serialization by construction —
  each mutation rebinds to a new buffer) and ``block_until_ready`` is
  WaitForVar.  ``wait_for_var``/``wait_for_all``/``push`` below keep that
  facade, including the NaiveEngine-style sync-dispatch debug mode
  (reference ``MXNET_ENGINE_TYPE=NaiveEngine``).

* **Host side** — the reference also routes IO, checkpoint, and kvstore
  transport through the engine.  ``ThreadedEngine`` below is a real native
  scheduler (C++ worker pool + per-variable dependency queues,
  ``native/engine.cc`` via ctypes) with the same protocol: tasks declare
  ``const_vars`` (reads) and ``mutable_vars`` (writes); the engine
  guarantees serialized writes and parallel reads per variable.

Env vars (docs/env_var.md): ``MXNET_ENGINE_TYPE=NaiveEngine`` forces
synchronous execution everywhere (usable backtraces);
``MXNET_CPU_WORKER_NTHREADS`` sizes the native worker pool.
"""
from __future__ import annotations

import atexit
import ctypes
import itertools
import logging
import os
import threading
import weakref

import jax
import numpy as np

from . import chaos as _chaos
from .lint import lockwitness as _lockwitness
from .lint import sanitizer as _san
from .telemetry import flight as _flight

__all__ = ["wait_for_var", "wait_for_all", "push", "is_sync_dispatch",
           "set_sync_dispatch", "ThreadedEngine", "engine"]

_SYNC = os.environ.get("MXNET_ENGINE_TYPE", "") == "NaiveEngine"


def is_sync_dispatch():
    return _SYNC


def set_sync_dispatch(flag):
    """Debug mode: force synchronous execution after every op (the
    NaiveEngine idea — crashes surface with a usable backtrace)."""
    global _SYNC
    _SYNC = bool(flag)
    eng = _SINGLETON
    if eng is not None:
        eng.set_sync(flag)


# ---------------------------------------------------------------------------
# Device-side facade (XLA/PJRT is the scheduler)
# ---------------------------------------------------------------------------

def wait_for_var(arr):
    """Block until all pending computation producing ``arr`` is done.

    Accepts a jax/NDArray value (PJRT future) or an ``int`` variable
    handle from :meth:`ThreadedEngine.new_variable`.
    """
    if isinstance(arr, (int, np.integer)) and not isinstance(arr, bool):
        engine().wait_for_var(int(arr))
        return
    jax.block_until_ready(arr)


def wait_for_all():
    """Engine::WaitForAll — drain every outstanding computation."""
    eng = _SINGLETON
    if eng is not None:
        eng.wait_for_all()
    # PJRT has no global barrier; an empty device sync per backend
    # suffices for the device side.
    for dev in jax.devices():
        try:
            jax.device_put(0, dev).block_until_ready()
        except Exception:  # pragma: no cover
            pass


def push(fn, *args, **kwargs):
    """Run a function 'on the engine' (async by construction under jax)."""
    out = fn(*args, **kwargs)
    if _SYNC:
        jax.block_until_ready(out)
    return out


# ---------------------------------------------------------------------------
# Host-side native engine
# ---------------------------------------------------------------------------

# One immortal ctypes trampoline shared by every task: the C side receives
# (trampoline, key) and the key resolves to the Python callable at run
# time.  This avoids per-task CFUNCTYPE closures entirely — nothing to
# keep alive per task, nothing to free while a C stack frame might still
# reference it.
_TASKS_LOCK = _lockwitness.make_lock("engine._TASKS_LOCK")
_LIVE_TASKS = {}          # key -> (engine, callable)
_KEY_SEQ = itertools.count(1)
_TRAMPOLINE = None        # created on first native engine


def _make_trampoline(fn_type):
    global _TRAMPOLINE
    if _TRAMPOLINE is None:
        def _run(arg):
            key = int(arg or 0)
            with _TASKS_LOCK:
                entry = _LIVE_TASKS.pop(key, None)
            if entry is None:     # pragma: no cover - defensive
                return
            eng, fn = entry
            eng._run_inline(fn)
        _TRAMPOLINE = fn_type(_run)
    return _TRAMPOLINE


class _EngineCore:
    """Owner of one native engine handle.  Holds no reference back to the
    Python-facing ``ThreadedEngine``, so it can serve as the
    ``weakref.finalize`` callback target: ``close()`` and the finalizer
    both funnel into the idempotent shutdown paths below, and every
    native call claims the handle through :meth:`enter`/:meth:`exit`
    so shutdown can wait out (or exclude) concurrent callers.
    """

    def __init__(self, nat, h):
        self.nat = nat
        self.h = h
        self.lock = _lockwitness.make_lock("_EngineCore.lock")
        self.idle = _lockwitness.make_condition(self.lock,
                                                "_EngineCore.idle")
        self.inflight = 0

    def enter(self):
        """Claim the handle for one native call; None once shut down."""
        with self.lock:
            if self.h is None:
                return None
            self.inflight += 1
            return self.h

    def exit(self):
        with self.lock:
            self.inflight -= 1
            if self.inflight == 0:
                self.idle.notify_all()

    def shutdown_sync(self):
        """Drain and free, waiting out concurrent native calls.  Must not
        run on one of the engine's own worker threads."""
        with self.lock:
            if self.h is None:
                return
            h, self.h = self.h, None     # new calls now see 'closed'
            while self.inflight:
                self.idle.wait()
        self.nat.MXEngineWaitForAll(h)
        self.nat.MXEngineFree(h)

    def shutdown_async(self):
        """Free via a detached native deleter — for GC on a non-main
        thread, possibly one of this engine's own workers mid-task,
        where a synchronous drain would self-deadlock.  No inflight wait
        is needed: GC implies the engine was unreachable, so no API call
        can be concurrently holding the handle."""
        with self.lock:
            if self.h is None:
                return
            h, self.h = self.h, None
        self.nat.MXEngineFreeAsync(h)


def _finalize_core(core):
    """weakref.finalize callback (GC of a dropped engine, or weakref's
    atexit hook for engines still alive at interpreter exit)."""
    import sys
    if sys.is_finalizing():     # pragma: no cover - teardown path
        # Too late to run trampolines; let the OS reclaim at exit.
        return
    if threading.current_thread() is threading.main_thread():
        # The main thread can never be an engine worker: safe to drain.
        # This covers the weakref-atexit path, where a detached deleter
        # would race process teardown.
        core.shutdown_sync()
    else:
        core.shutdown_async()


class ThreadedEngine:
    """Host-task scheduler with the reference engine's dependency protocol.

    Backed by ``native/engine.cc`` (C++ worker pool, per-variable FIFO
    dependency queues).  When the native library is unavailable the same
    API degrades to synchronous inline execution — the observable
    contract (completion order per variable) is preserved, only the
    parallelism is lost.
    """

    def __init__(self, num_workers=None, sync=None):
        from ._native import engine as nat
        if num_workers is None:
            num_workers = int(os.environ.get(
                "MXNET_CPU_WORKER_NTHREADS",
                str(min(8, os.cpu_count() or 1))))
        if sync is None:
            sync = _SYNC
        self._nat = nat.lib()
        self._errors = []
        self._pyvar_seq = itertools.count(1)
        if self._nat is not None:
            h = self._nat.MXEngineCreate(int(num_workers), 1 if sync else 0)
            self._core = _EngineCore(self._nat, h)
            self._trampoline = _make_trampoline(nat.TASK_FN)
            # GC safety net: a dropped instance still drains and frees
            # its C++ engine (and worker threads) instead of leaking
            # them — and before interpreter teardown, so no trampoline
            # fires into a finalizing Python.
            self._finalizer = weakref.finalize(self, _finalize_core,
                                               self._core)
        else:
            self._core = None

    # -- variables ---------------------------------------------------------

    def new_variable(self):
        """A scheduling variable (an ``int`` handle)."""
        h = self._enter_native()
        if h is None:
            return next(self._pyvar_seq)
        try:
            return int(self._nat.MXEngineNewVariable(h))
        finally:
            self._exit_native()

    def delete_variable(self, var):
        """GC the variable once every pending task touching it completes."""
        _san.forget_var(self, var)
        h = self._enter_native()
        if h is not None:
            try:
                self._nat.MXEngineDeleteVariable(h, int(var))
            finally:
                self._exit_native()

    # -- tasks -------------------------------------------------------------

    def push(self, fn, const_vars=(), mutable_vars=(), priority=0,
             tag=None):
        """Schedule ``fn()`` after its dependencies resolve.

        ``const_vars`` are read-dependencies (may run concurrently with
        other readers); ``mutable_vars`` are write-dependencies
        (serialized in push order per variable).  Exceptions raised by
        ``fn`` are captured and re-raised at the next wait point.
        *tag* names the task in the flight ring (callers pushing
        lambdas — e.g. the serving batcher — would otherwise all read
        as ``<lambda>`` in a post-mortem).

        Under ``MXNET_SANITIZE`` every task is wrapped in a happens-before
        checker that asserts the declared contract as it executes (writes
        land in push order, writers exclusive, readers never overlap a
        writer) — mis-declared deps surface as errors at the next wait
        point instead of corrupted data.  The checker's write tickets and
        the native enqueue happen under one push scope so concurrent
        pushers cannot interleave ticket order against engine order.
        """
        if _flight.enabled():     # opted-out path stays one bool check
            _flight.record("engine_push",
                           tag or getattr(fn, "__qualname__", None)
                           or getattr(fn, "__name__", repr(type(fn))),
                           reads=len(const_vars), writes=len(mutable_vars))
        if _chaos.active():       # decided HERE (deterministic push
            act = _chaos.decide("engine.task")   # order), applied in-task
            if act is not None:
                fn = _chaos.chaos_task(fn, act)
        with _san.push_scope(self):
            if _san.engine_checker_enabled():
                fn = _san.guard_task(self, fn, const_vars, mutable_vars)
            self._push_raw(fn, const_vars, mutable_vars, priority)

    def _push_raw(self, fn, const_vars, mutable_vars, priority):
        if self._core is None:
            self._run_inline(fn)
            return

        key = next(_KEY_SEQ)
        with _TASKS_LOCK:
            _LIVE_TASKS[key] = (self, fn)
        h = self._enter_native()
        if h is None:                        # closed concurrently
            with _TASKS_LOCK:
                _LIVE_TASKS.pop(key, None)
            # Degrade like the no-native fallback: the task still runs.
            self._run_inline(fn)
            return
        try:
            cv = (ctypes.c_int64 * max(1, len(const_vars)))(*const_vars)
            mv = (ctypes.c_int64 * max(1, len(mutable_vars)))(*mutable_vars)
            self._nat.MXEnginePushAsync(
                h, self._trampoline, ctypes.c_void_p(key),
                cv, len(const_vars), mv, len(mutable_vars), int(priority))
        except BaseException:
            # never handed to the engine: the registry entry would leak,
            # and the happens-before ticket must be rolled back or every
            # later write to these vars reads as out-of-order
            with _TASKS_LOCK:
                _LIVE_TASKS.pop(key, None)
            getattr(fn, "cancel", lambda: None)()
            raise
        finally:
            self._exit_native()

    def _run_inline(self, fn):
        """Run a task on the calling thread, capturing its exception for
        the next wait point (shared by the trampoline and fallbacks)."""
        try:
            fn()
        except BaseException as e:      # noqa: BLE001
            with _TASKS_LOCK:
                self._errors.append(e)

    # -- synchronization ---------------------------------------------------

    def _enter_native(self):
        """Claim the handle for one native call; None when unavailable."""
        return None if self._core is None else self._core.enter()

    def _exit_native(self):
        self._core.exit()

    def _raise_pending(self):
        with _TASKS_LOCK:
            if not self._errors:
                return
            err, rest = self._errors[0], self._errors[1:]
            self._errors.clear()
        # surface the FIRST failure; chain the rest via __context__ so no
        # async task error is silently discarded when several fail between
        # wait points (e.g. two async checkpoint writes)
        node = err
        for extra in rest:
            logging.getLogger(__name__).error(
                "additional async engine task failure: %r", extra)
            node.__context__ = extra
            node = extra
        raise err

    def wait_for_var(self, var):
        """Block until every write pushed on ``var`` so far has landed."""
        h = self._enter_native()
        if h is not None:
            try:
                self._nat.MXEngineWaitForVar(h, int(var))
            finally:
                self._exit_native()
        self._raise_pending()

    def wait_for_all(self):
        h = self._enter_native()
        if h is not None:
            try:
                self._nat.MXEngineWaitForAll(h)
            finally:
                self._exit_native()
        self._raise_pending()

    def num_pending(self):
        h = self._enter_native()
        if h is None:
            return 0
        try:
            return int(self._nat.MXEnginePendingTasks(h))
        finally:
            self._exit_native()

    def set_sync(self, flag):
        h = self._enter_native()
        if h is not None:
            try:
                self._nat.MXEngineSetSync(h, 1 if flag else 0)
            finally:
                self._exit_native()

    def close(self):
        """Drain and free the native engine (waits out concurrent calls).
        Idempotent; safe against a finalizer that already fired."""
        if self._core is not None:
            self._core.shutdown_sync()

    @property
    def native(self):
        """True when backed by the C++ scheduler (not the sync fallback)."""
        return self._core is not None


_SINGLETON = None
_SINGLETON_LOCK = _lockwitness.make_lock("engine._SINGLETON_LOCK")


def engine():
    """The process-wide host-task engine (created on first use)."""
    global _SINGLETON
    if _SINGLETON is None:
        with _SINGLETON_LOCK:
            if _SINGLETON is None:
                _SINGLETON = ThreadedEngine()
    return _SINGLETON


@atexit.register
def _shutdown():  # pragma: no cover - interpreter teardown
    global _SINGLETON
    if _SINGLETON is not None:
        try:
            _SINGLETON.close()
        except Exception:
            pass
        # Raising at atexit is useless, but swallowing task failures
        # (e.g. a final async checkpoint hitting a full disk) silently
        # is worse: surface them in the log.
        with _TASKS_LOCK:
            errors, _SINGLETON._errors = list(_SINGLETON._errors), []
        for err in errors:
            import logging
            logging.getLogger("mxnet_tpu").error(
                "host-engine task failed before exit: %r", err)
        _SINGLETON = None
