"""Weight initializers.

API parity with the reference ``python/mxnet/initializer.py:34-676``
(InitDesc, pattern-dispatch Initializer protocol, the Zero…FusedRNN zoo,
Load/Mixed). Independent design: name-suffix dispatch is table-driven, and
structured initializers (Bilinear) are vectorised numpy rather than loops.
"""
from __future__ import annotations

import json
import re

import numpy as np

from .base import Registry, MXNetError
from . import ndarray as nd
from . import random as _random

__all__ = ["InitDesc", "Initializer", "register", "create", "Zero", "One",
           "Constant", "Uniform", "Normal", "Orthogonal", "Xavier",
           "MSRAPrelu", "Bilinear", "LSTMBias", "Load", "Mixed", "init"]

_REG = Registry("initializer")


class InitDesc(str):
    """Parameter name enriched with symbol attrs + the global initializer."""

    def __new__(cls, name, attrs=None, global_init=None):
        self = super().__new__(cls, name)
        self.attrs = attrs or {}
        self.global_init = global_init
        return self


# (name suffix → handler method) dispatch table, checked in order.
_SUFFIX_DISPATCH = (
    (("weight",), "_init_weight"),
    (("bias",), "_init_bias"),
    (("gamma",), "_init_gamma"),
    (("beta",), "_init_beta"),
    (("moving_mean", "running_mean", "moving_inv_var", "moving_avg",
      "min", "max"), "_init_zero"),
    (("moving_var", "running_var"), "_init_one"),
)


class Initializer:
    """Base initializer implementing the reference dispatch protocol:
    an ``__init__`` attr on the variable wins, else the name suffix picks
    the handler (weight/bias/gamma/beta/aux-stat)."""

    def __init__(self, **kwargs):
        self._kwargs = kwargs
        self._verbose, self._print_func = False, None

    def set_verbosity(self, verbose=False, print_func=None):
        self._verbose, self._print_func = verbose, print_func
        return self

    def dumps(self):
        return json.dumps([type(self).__name__.lower(), self._kwargs])

    def __call__(self, desc, arr):
        if not isinstance(desc, InitDesc):
            desc = InitDesc(desc)
        if desc.global_init is None:
            desc.global_init = self
        attr_init = desc.attrs.get("__init__", "")
        if attr_init:
            # variable-level override: serialized [class, kwargs] or a plain
            # registered name — the reference accepts both via create(init)
            # (ref python/mxnet/initializer.py:134).
            create(attr_init)._init_weight(desc, arr)
            return
        lowered = desc.lower()
        for suffixes, handler in _SUFFIX_DISPATCH:
            if lowered.endswith(suffixes):
                getattr(self, handler)(desc, arr)
                return
        self._init_default(desc, arr)

    def _init_zero(self, _, arr):
        arr[:] = 0.0

    def _init_one(self, _, arr):
        arr[:] = 1.0

    _init_bias = _init_zero
    _init_beta = _init_zero
    _init_gamma = _init_one

    def _init_weight(self, name, arr):
        raise NotImplementedError()

    def _init_default(self, name, arr):
        raise ValueError(
            'Unknown initialization pattern for %s. Default initialization '
            'is now limited to "weight", "bias", "gamma", and "beta". '
            'Please use mx.sym.Variable(init=mx.init.*) to set the '
            'initialization pattern' % name)

    def __eq__(self, other):
        return (type(self) is type(other)
                and self._kwargs == other._kwargs)

    __hash__ = object.__hash__


def register(klass):
    _REG.register(klass, klass.__name__)
    return klass


def create(name, **kwargs):
    """Name, JSON ``[class, kwargs]`` string, or instance → Initializer
    (name-or-JSON acceptance mirrors ref python/mxnet/initializer.py:134)."""
    if isinstance(name, Initializer) or callable(name) and not isinstance(name, (str, type)):
        return name
    if isinstance(name, str) and name.lstrip().startswith("["):
        cls_name, cls_kwargs = json.loads(name)
        return _REG.get(cls_name)(**cls_kwargs)
    return _REG.get(name)(**kwargs)


@register
class Zero(Initializer):
    def _init_weight(self, _, arr):
        arr[:] = 0.0
    _init_default = _init_weight


@register
class One(Initializer):
    def _init_weight(self, _, arr):
        arr[:] = 1.0
    _init_default = _init_weight


_REG.register(Zero, "zeros")
_REG.register(One, "ones")


@register
class Constant(Initializer):
    def __init__(self, value=0.0):
        super().__init__(value=value)
        self.value = value

    def _init_weight(self, _, arr):
        arr[:] = self.value
    _init_default = _init_weight


@register
class Uniform(Initializer):
    """U(-scale, scale)."""

    def __init__(self, scale=0.07):
        super().__init__(scale=scale)
        self.scale = scale

    def _init_weight(self, _, arr):
        arr[:] = _random.host_rng().uniform(-self.scale, self.scale,
                                            arr.shape)


@register
class Normal(Initializer):
    """N(0, sigma^2)."""

    def __init__(self, sigma=0.01):
        super().__init__(sigma=sigma)
        self.sigma = sigma

    def _init_weight(self, _, arr):
        arr[:] = _random.host_rng().normal(0, self.sigma, arr.shape)


@register
class Orthogonal(Initializer):
    """Scaled orthogonal matrix via SVD of a random (nout, nin) draw."""

    def __init__(self, scale=1.414, rand_type="uniform"):
        super().__init__(scale=scale, rand_type=rand_type)
        self.scale, self.rand_type = scale, rand_type

    def _init_weight(self, _, arr):
        rows = arr.shape[0]
        cols = int(np.prod(arr.shape[1:]))
        rng = _random.host_rng()
        draw = (rng.uniform(-1.0, 1.0, (rows, cols))
                if self.rand_type == "uniform"
                else rng.normal(0.0, 1.0, (rows, cols)))
        u, _s, v = np.linalg.svd(draw, full_matrices=False)
        basis = u if u.shape == draw.shape else v
        arr[:] = (self.scale * basis).reshape(arr.shape)


def _conv_fans(shape):
    """(fan_in, fan_out) with trailing spatial dims folded in."""
    spatial = np.prod(shape[2:]) if len(shape) > 2 else 1.0
    return shape[1] * spatial, shape[0] * spatial


@register
class Xavier(Initializer):
    """Glorot init: scale^2 = magnitude / factor(fan_in, fan_out)."""

    _FACTORS = {"avg": lambda fi, fo: (fi + fo) / 2.0,
                "in": lambda fi, fo: fi,
                "out": lambda fi, fo: fo}

    def __init__(self, rnd_type="uniform", factor_type="avg", magnitude=3):
        super().__init__(rnd_type=rnd_type, factor_type=factor_type,
                         magnitude=magnitude)
        self.rnd_type, self.factor_type = rnd_type, factor_type
        self.magnitude = float(magnitude)

    def _init_weight(self, name, arr):
        if len(arr.shape) < 2:
            raise ValueError("Xavier initializer cannot be applied to vector "
                             "%s. It requires at least 2D." % name)
        try:
            factor_fn = self._FACTORS[self.factor_type]
        except KeyError:
            raise ValueError("Incorrect factor type")
        sigma = np.sqrt(self.magnitude / factor_fn(*_conv_fans(arr.shape)))
        if self.rnd_type == "uniform":
            arr[:] = _random.host_rng().uniform(-sigma, sigma, arr.shape)
        elif self.rnd_type == "gaussian":
            arr[:] = _random.host_rng().normal(0, sigma, arr.shape)
        else:
            raise ValueError("Unknown random type")


@register
class MSRAPrelu(Xavier):
    """He init adjusted for PReLU slope."""

    def __init__(self, factor_type="avg", slope=0.25):
        super().__init__("gaussian", factor_type, 2.0 / (1 + slope ** 2))
        self._kwargs = {"factor_type": factor_type, "slope": slope}


@register
class Bilinear(Initializer):
    """Bilinear-upsampling kernel for Deconvolution (vectorised)."""

    def _init_weight(self, _, arr):
        shape = arr.shape
        f = np.ceil(shape[3] / 2.0)
        c = (2 * f - 1 - f % 2) / (2.0 * f)
        xs = np.arange(shape[3], dtype="float32")
        ys = np.arange(shape[2], dtype="float32")
        kernel = np.outer(1 - np.abs(ys / f - c), 1 - np.abs(xs / f - c))
        arr[:] = np.broadcast_to(kernel, shape).astype("float32")


@register
class LSTMBias(Initializer):
    """Zero bias except the forget gate (slot 2 of i,f,g,o)."""

    def __init__(self, forget_bias=1.0):
        super().__init__(forget_bias=forget_bias)
        self.forget_bias = forget_bias

    def _init_weight(self, desc, arr):
        per_gate = arr.shape[0] // 4
        host = np.zeros(arr.shape, dtype="float32")
        host[per_gate:2 * per_gate] = self.forget_bias
        arr[:] = host
    _init_default = _init_weight
    _init_bias = _init_weight


@register
class FusedRNN(Initializer):
    """Delegates to a wrapped initializer (fused-RNN param blob layout is
    flat on TPU, so no re-packing is needed)."""

    def __init__(self, init=None, state_size=None, num_layers=None, mode=None,
                 bidirectional=False, forget_bias=1.0):
        super().__init__()
        if isinstance(init, Initializer):
            self._init = init
        elif isinstance(init, str) and init:
            self._init = create(init)          # name or JSON form
        else:
            self._init = Uniform(0.1)

    def _init_weight(self, desc, arr):
        self._init._init_weight(desc, arr)
    _init_default = _init_weight


@register
class Load:
    """Copy parameters from a saved dict, else fall back to default_init."""

    def __init__(self, param, default_init=None, verbose=False):
        if isinstance(param, str):
            param = nd.load(param)
        self.param = {key.split(":", 1)[-1]: val
                      for key, val in param.items()}
        self.default_init = default_init
        self.verbose = verbose

    def __call__(self, name, arr):
        loaded = self.param.get(name)
        if loaded is not None:
            if tuple(loaded.shape) != tuple(arr.shape):
                raise MXNetError(
                    "Parameter %s cannot be initialized from loading. Shape "
                    "mismatch, target %s vs loaded %s"
                    % (name, arr.shape, loaded.shape))
            loaded.copyto(arr)
            return
        if self.default_init is None:
            raise MXNetError(
                "Cannot Initialize parameter %s. Not found in loaded "
                "param and no default initializer" % name)
        self.default_init(name, arr)


@register
class Mixed:
    """First-matching-regex dispatch over a list of initializers."""

    def __init__(self, patterns, initializers):
        if len(patterns) != len(initializers):
            raise ValueError("patterns and initializers must have same length")
        self.map = [(re.compile(p), i)
                    for p, i in zip(patterns, initializers)]

    def __call__(self, name, arr):
        for matcher, initializer in self.map:
            if matcher.match(name):
                initializer(name, arr)
                return
        raise ValueError(
            'Parameter name %s did not match any pattern. Consider adding a '
            '".*" pattern at the end with default Initializer.' % name)


class _InitModule:
    """``mx.init`` namespace shim."""
    Initializer, InitDesc = Initializer, InitDesc
    Zero, One, Constant = Zero, One, Constant
    Uniform, Normal, Orthogonal = Uniform, Normal, Orthogonal
    Xavier, MSRAPrelu, Bilinear = Xavier, MSRAPrelu, Bilinear
    LSTMBias, FusedRNN = LSTMBias, FusedRNN
    Load, Mixed = Load, Mixed


init = _InitModule()
