"""Weight initializers (parity: reference python/mxnet/initializer.py:34-676)."""
from __future__ import annotations

import json
import math
import re

import numpy as np

from .base import Registry, MXNetError
from . import ndarray as nd

__all__ = ["InitDesc", "Initializer", "register", "create", "Zero", "One",
           "Constant", "Uniform", "Normal", "Orthogonal", "Xavier",
           "MSRAPrelu", "Bilinear", "LSTMBias", "Load", "Mixed", "init"]

_REG = Registry("initializer")


class InitDesc(str):
    """Name + attrs descriptor passed to initializers."""
    def __new__(cls, name, attrs=None, global_init=None):
        ret = super().__new__(cls, name)
        ret.attrs = attrs or {}
        ret.global_init = global_init
        return ret


class Initializer:
    """Base initializer with the reference's pattern-dispatch protocol."""

    def __init__(self, **kwargs):
        self._kwargs = kwargs
        self._verbose = False
        self._print_func = None

    def set_verbosity(self, verbose=False, print_func=None):
        self._verbose = verbose
        self._print_func = print_func
        return self

    def dumps(self):
        return json.dumps([self.__class__.__name__.lower(), self._kwargs])

    def __call__(self, desc, arr):
        if not isinstance(desc, InitDesc):
            desc = InitDesc(desc)
        if desc.global_init is None:
            desc.global_init = self
        init = desc.attrs.get("__init__", "")
        if init:
            klass, kwargs = json.loads(init)
            create(klass, **kwargs)._init_weight(desc, arr)
            return
        name = desc.lower()
        if name.endswith("weight"):
            self._init_weight(desc, arr)
        elif name.endswith("bias"):
            self._init_bias(desc, arr)
        elif name.endswith("gamma"):
            self._init_gamma(desc, arr)
        elif name.endswith("beta"):
            self._init_beta(desc, arr)
        elif name.endswith("moving_mean") or name.endswith("running_mean"):
            self._init_zero(desc, arr)
        elif name.endswith("moving_var") or name.endswith("running_var"):
            self._init_one(desc, arr)
        elif name.endswith("moving_inv_var") or name.endswith("moving_avg"):
            self._init_zero(desc, arr)
        elif name.endswith("min") or name.endswith("max"):
            self._init_zero(desc, arr)
        else:
            self._init_default(desc, arr)

    def _init_zero(self, _, arr):
        arr[:] = 0.0

    def _init_one(self, _, arr):
        arr[:] = 1.0

    def _init_bias(self, _, arr):
        arr[:] = 0.0

    def _init_gamma(self, _, arr):
        arr[:] = 1.0

    def _init_beta(self, _, arr):
        arr[:] = 0.0

    def _init_weight(self, name, arr):
        raise NotImplementedError()

    def _init_default(self, name, arr):
        raise ValueError(
            "Unknown initialization pattern for %s. Default initialization "
            "is now limited to \"weight\", \"bias\", \"gamma\", and \"beta\". "
            "Please use mx.sym.Variable(init=mx.init.*) to set the "
            "initialization pattern" % name)

    def __eq__(self, other):
        return (isinstance(other, Initializer)
                and self.__class__ == other.__class__
                and self._kwargs == other._kwargs)

    __hash__ = object.__hash__


def register(klass):
    _REG.register(klass, klass.__name__)
    return klass


def create(name, **kwargs):
    if isinstance(name, Initializer):
        return name
    return _REG.get(name)(**kwargs)


@register
class Zero(Initializer):
    def _init_weight(self, _, arr):
        arr[:] = 0.0
    _init_default = _init_weight


_REG.register(Zero, "zeros")


@register
class One(Initializer):
    def _init_weight(self, _, arr):
        arr[:] = 1.0
    _init_default = _init_weight


_REG.register(One, "ones")


@register
class Constant(Initializer):
    def __init__(self, value=0.0):
        super().__init__(value=value)
        self.value = value

    def _init_weight(self, _, arr):
        arr[:] = self.value
    _init_default = _init_weight


@register
class Uniform(Initializer):
    def __init__(self, scale=0.07):
        super().__init__(scale=scale)
        self.scale = scale

    def _init_weight(self, _, arr):
        arr[:] = np.random.uniform(-self.scale, self.scale, arr.shape)


@register
class Normal(Initializer):
    def __init__(self, sigma=0.01):
        super().__init__(sigma=sigma)
        self.sigma = sigma

    def _init_weight(self, _, arr):
        arr[:] = np.random.normal(0, self.sigma, arr.shape)


@register
class Orthogonal(Initializer):
    def __init__(self, scale=1.414, rand_type="uniform"):
        super().__init__(scale=scale, rand_type=rand_type)
        self.scale = scale
        self.rand_type = rand_type

    def _init_weight(self, _, arr):
        nout = arr.shape[0]
        nin = int(np.prod(arr.shape[1:]))
        if self.rand_type == "uniform":
            tmp = np.random.uniform(-1.0, 1.0, (nout, nin))
        else:
            tmp = np.random.normal(0.0, 1.0, (nout, nin))
        u, _, v = np.linalg.svd(tmp, full_matrices=False)
        q = u if u.shape == tmp.shape else v
        arr[:] = (self.scale * q).reshape(arr.shape)


@register
class Xavier(Initializer):
    def __init__(self, rnd_type="uniform", factor_type="avg", magnitude=3):
        super().__init__(rnd_type=rnd_type, factor_type=factor_type,
                         magnitude=magnitude)
        self.rnd_type = rnd_type
        self.factor_type = factor_type
        self.magnitude = float(magnitude)

    def _init_weight(self, name, arr):
        shape = arr.shape
        hw_scale = 1.0
        if len(shape) < 2:
            raise ValueError("Xavier initializer cannot be applied to vector "
                             "%s. It requires at least 2D." % name)
        if len(shape) > 2:
            hw_scale = np.prod(shape[2:])
        fan_in, fan_out = shape[1] * hw_scale, shape[0] * hw_scale
        factor = 1.0
        if self.factor_type == "avg":
            factor = (fan_in + fan_out) / 2.0
        elif self.factor_type == "in":
            factor = fan_in
        elif self.factor_type == "out":
            factor = fan_out
        else:
            raise ValueError("Incorrect factor type")
        scale = np.sqrt(self.magnitude / factor)
        if self.rnd_type == "uniform":
            arr[:] = np.random.uniform(-scale, scale, arr.shape)
        elif self.rnd_type == "gaussian":
            arr[:] = np.random.normal(0, scale, arr.shape)
        else:
            raise ValueError("Unknown random type")


@register
class MSRAPrelu(Xavier):
    def __init__(self, factor_type="avg", slope=0.25):
        magnitude = 2.0 / (1 + slope ** 2)
        super().__init__("gaussian", factor_type, magnitude)
        self._kwargs = {"factor_type": factor_type, "slope": slope}


@register
class Bilinear(Initializer):
    def _init_weight(self, _, arr):
        weight = np.zeros(np.prod(arr.shape), dtype="float32")
        shape = arr.shape
        f = np.ceil(shape[3] / 2.0)
        c = (2 * f - 1 - f % 2) / (2.0 * f)
        for i in range(np.prod(shape)):
            x = i % shape[3]
            y = (i // shape[3]) % shape[2]
            weight[i] = (1 - abs(x / f - c)) * (1 - abs(y / f - c))
        arr[:] = weight.reshape(shape)


@register
class LSTMBias(Initializer):
    """Initialize forget-gate bias to a custom value, rest to 0."""

    def __init__(self, forget_bias=1.0):
        super().__init__(forget_bias=forget_bias)
        self.forget_bias = forget_bias

    def _init_weight(self, desc, arr):
        arr[:] = 0.0
        num_hidden = arr.shape[0] // 4
        a = arr.asnumpy()
        a[num_hidden:2 * num_hidden] = self.forget_bias  # gate order i,f,g,o
        arr[:] = a
    _init_default = _init_weight
    _init_bias = _init_weight


@register
class FusedRNN(Initializer):
    def __init__(self, init=None, state_size=None, num_layers=None, mode=None,
                 bidirectional=False, forget_bias=1.0):
        super().__init__()
        self._init = init if isinstance(init, Initializer) else (
            create(*json.loads(init)) if isinstance(init, str) and init else
            Uniform(0.1))

    def _init_weight(self, desc, arr):
        self._init._init_weight(desc, arr)
    _init_default = _init_weight


@register
class Load:
    """Initialize from a dict of arrays, fall back to default_init."""

    def __init__(self, param, default_init=None, verbose=False):
        if isinstance(param, str):
            param = nd.load(param)
        self.param = {k.split(":", 1)[-1]: v for k, v in param.items()}
        self.default_init = default_init
        self.verbose = verbose

    def __call__(self, name, arr):
        if name in self.param:
            if tuple(self.param[name].shape) != tuple(arr.shape):
                raise MXNetError(
                    "Parameter %s cannot be initialized from loading. Shape "
                    "mismatch, target %s vs loaded %s"
                    % (name, arr.shape, self.param[name].shape))
            self.param[name].copyto(arr)
        else:
            if self.default_init is None:
                raise MXNetError(
                    "Cannot Initialize parameter %s. Not found in loaded "
                    "param and no default initializer" % name)
            self.default_init(name, arr)


@register
class Mixed:
    """Pattern-matched mixed initializer."""

    def __init__(self, patterns, initializers):
        if len(patterns) != len(initializers):
            raise ValueError("patterns and initializers must have same length")
        self.map = list(zip([re.compile(p) for p in patterns], initializers))

    def __call__(self, name, arr):
        for prog, i in self.map:
            if prog.match(name):
                i(name, arr)
                return
        raise ValueError(
            "Parameter name %s did not match any pattern. Consider adding a "
            "\".*\" pattern at the end with default Initializer." % name)


class _InitModule:
    """`mx.init` namespace shim."""
    Zero = Zero
    One = One
    Constant = Constant
    Uniform = Uniform
    Normal = Normal
    Orthogonal = Orthogonal
    Xavier = Xavier
    MSRAPrelu = MSRAPrelu
    Bilinear = Bilinear
    LSTMBias = LSTMBias
    FusedRNN = FusedRNN
    Load = Load
    Mixed = Mixed
    Initializer = Initializer
    InitDesc = InitDesc


init = _InitModule()
