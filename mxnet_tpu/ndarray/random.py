"""``mx.nd.random`` namespace (reference python/mxnet/ndarray/random.py)."""
from __future__ import annotations

from .ndarray import invoke, NDArray
from ..ops.registry import get_op
from ..context import current_context

__all__ = ["uniform", "normal", "gamma", "exponential", "poisson",
           "negative_binomial", "generalized_negative_binomial",
           "multinomial", "randint", "shuffle"]


def _sample(op_shape, op_tensor, params, shape, dtype, ctx, out, kwargs):
    if any(isinstance(p, NDArray) for p in params):
        nd_params = [p for p in params if isinstance(p, NDArray)]
        attrs = dict(shape=shape, dtype=dtype or "float32", **kwargs)
        return invoke(get_op(op_tensor), nd_params, attrs, out=out)[0]
    attrs = dict(shape=shape if shape is not None else (1,),
                 dtype=dtype or "float32",
                 ctx=ctx or current_context(), **kwargs)
    return invoke(get_op(op_shape), [], attrs, out=out)[0]


def uniform(low=0, high=1, shape=None, dtype=None, ctx=None, out=None, **kw):
    if isinstance(low, NDArray) or isinstance(high, NDArray):
        return _sample(None, "_sample_uniform", [low, high], shape, dtype, ctx, out, {})
    return _sample("_random_uniform", None, [], shape, dtype, ctx, out,
                   dict(low=float(low), high=float(high)))


def normal(loc=0, scale=1, shape=None, dtype=None, ctx=None, out=None, **kw):
    if isinstance(loc, NDArray) or isinstance(scale, NDArray):
        return _sample(None, "_sample_normal", [loc, scale], shape, dtype, ctx, out, {})
    return _sample("_random_normal", None, [], shape, dtype, ctx, out,
                   dict(loc=float(loc), scale=float(scale)))


def gamma(alpha=1, beta=1, shape=None, dtype=None, ctx=None, out=None, **kw):
    if isinstance(alpha, NDArray) or isinstance(beta, NDArray):
        return _sample(None, "_sample_gamma", [alpha, beta], shape, dtype, ctx, out, {})
    return _sample("_random_gamma", None, [], shape, dtype, ctx, out,
                   dict(alpha=float(alpha), beta=float(beta)))


def exponential(scale=1, shape=None, dtype=None, ctx=None, out=None, **kw):
    if isinstance(scale, NDArray):
        return _sample(None, "_sample_exponential", [scale], shape, dtype, ctx, out, {})
    return _sample("_random_exponential", None, [], shape, dtype, ctx, out,
                   dict(lam=1.0 / float(scale)))


def poisson(lam=1, shape=None, dtype=None, ctx=None, out=None, **kw):
    if isinstance(lam, NDArray):
        return _sample(None, "_sample_poisson", [lam], shape, dtype, ctx, out, {})
    return _sample("_random_poisson", None, [], shape, dtype, ctx, out,
                   dict(lam=float(lam)))


def negative_binomial(k=1, p=1, shape=None, dtype=None, ctx=None, out=None, **kw):
    return _sample("_random_negative_binomial", None, [], shape, dtype, ctx,
                   out, dict(k=int(k), p=float(p)))


def generalized_negative_binomial(mu=1, alpha=1, shape=None, dtype=None,
                                  ctx=None, out=None, **kw):
    return _sample("_random_generalized_negative_binomial", None, [], shape,
                   dtype, ctx, out, dict(mu=float(mu), alpha=float(alpha)))


def multinomial(data, shape=None, get_prob=False, out=None, dtype="int32", **kw):
    attrs = dict(shape=shape or (), get_prob=get_prob, dtype=dtype)
    res = invoke(get_op("_sample_multinomial"), [data], attrs, out=out)
    return res if get_prob else res[0]


def randint(low, high, shape=None, dtype="int32", ctx=None, out=None, **kw):
    return _sample("_random_randint", None, [], shape, dtype, ctx, out,
                   dict(low=int(low), high=int(high)))


def shuffle(data, **kw):
    return invoke(get_op("shuffle"), [data], {})[0]
