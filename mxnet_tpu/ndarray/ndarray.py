"""NDArray: MXNet's async mutable tensor API over immutable jax.Arrays.

Parity surface: reference ``python/mxnet/ndarray/ndarray.py`` (NDArray class,
attach_grad :1691, backward :1733, wait_to_read :1360, asnumpy :1531) over
``include/mxnet/ndarray.h`` / ``src/ndarray/ndarray.cc``.

TPU-native redesign (SURVEY §7 "hard parts"): MXNet NDArrays mutate in place;
jax arrays are immutable.  ``NDArray`` is therefore a *handle* — a mutable
slot holding the current ``jax.Array`` — and every "mutation" rebinds the
slot.  This reproduces the reference's observable semantics exactly (the
dependency engine also never mutates concurrently: writes serialize per
buffer, §3.3) while staying functional underneath, which is what lets whole
training steps jit into one XLA program.

Async semantics come free: jax dispatch is asynchronous; ``wait_to_read`` is
``block_until_ready``; ``asnumpy`` is the only implicit sync point — same
latency-hiding contract as the reference engine (SURVEY §3.1 note).
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ..base import MXNetError, dtype_np, dtype_name
from ..context import Context, current_context, cpu
from .. import autograd as ag
from .. import profiler as _prof
from .. import random as _random
from .. import telemetry as _tel
from ..lint import sanitizer as _san
from ..ops.registry import get_op, Op

__all__ = ["NDArray", "array", "zeros", "ones", "empty", "full", "arange",
           "invoke", "waitall", "concatenate", "imperative_invoke", "_wrap",
           "moveaxis", "onehot_encode"]


class NDArray:
    """A mutable n-dimensional array handle on a device context."""
    __slots__ = ("_data", "_ctx", "_stype", "_grad", "_grad_req", "_marked",
                 "_fresh_grad", "_tape_node", "name", "__weakref__")
    # numpy scalar-priority so  np_scalar * NDArray  dispatches to us
    __array_priority__ = 1000.0

    def __init__(self, data, ctx=None, stype="default"):
        self._data = data
        self._ctx = ctx or current_context()
        self._stype = stype
        self._grad = None
        self._grad_req = "null"
        self._marked = False
        self._fresh_grad = False  # grad written by backward since last step
        self._tape_node = None
        self.name = None

    # -- core properties ---------------------------------------------------
    @property
    def shape(self):
        return tuple(self._data.shape)

    @property
    def dtype(self):
        return np.dtype(self._data.dtype)

    @property
    def size(self):
        return int(np.prod(self.shape)) if self.shape else 1

    @property
    def ndim(self):
        return self._data.ndim

    @property
    def context(self):
        return self._ctx

    ctx = context

    @property
    def stype(self):
        return self._stype

    @property
    def grad(self):
        return self._grad

    @property
    def T(self):
        if ag.is_recording():
            return invoke(get_op("transpose"), [self], {})[0]
        return _wrap(self._data.T, self._ctx)

    def _set_data(self, jarr):
        """Rebind the handle (the 'mutation' primitive)."""
        self._data = jarr
        return self

    # -- sync / host transfer ---------------------------------------------
    def wait_to_read(self):
        jax.block_until_ready(self._data)

    wait_to_write = wait_to_read

    def asnumpy(self):
        # every host materialization funnels through here (__array__,
        # asscalar/item, __bool__/__int__/__float__) — the one choke point
        # where MXNET_SANITIZE can catch tracer leaks / syncs-under-trace
        _san.check_host_sync(self._data)
        return np.asarray(self._data)

    def __array__(self, dtype=None):
        a = self.asnumpy()
        return a.astype(dtype) if dtype is not None else a

    def asscalar(self):
        if self.size != 1:
            raise ValueError("The current array is not a scalar")
        return self.asnumpy().reshape(-1)[0]

    def item(self):
        return self.asscalar()

    def __float__(self):
        return float(self.asscalar())

    def __int__(self):
        return int(self.asscalar())

    def __bool__(self):
        if self.size == 1:
            return bool(self.asscalar())
        raise ValueError("The truth value of an NDArray with multiple "
                         "elements is ambiguous.")

    def __len__(self):
        if not self.shape:
            raise TypeError("len() of unsized object")
        return self.shape[0]

    def __iter__(self):
        for i in range(len(self)):
            yield self[i]

    def __repr__(self):
        return "%s\n<NDArray %s @%s>" % (
            self.asnumpy(), "x".join(str(s) for s in self.shape), self._ctx)

    def __reduce__(self):
        return (_nd_unpickle, (self.asnumpy(), self._ctx.device_type,
                               self._ctx.device_id, self._stype))

    # -- conversion / copy -------------------------------------------------
    # (casts and copies record on the tape like the reference's Cast /
    # _copy ops — only detach() deliberately severs the graph)
    def astype(self, dtype, copy=True):
        if ag.is_recording():
            return invoke(get_op("Cast"), [self],
                          {"dtype": dtype_name(dtype)})[0]
        return _wrap(self._data.astype(dtype_np(dtype)), self._ctx)

    def copy(self):
        if ag.is_recording():
            return invoke(get_op("_copy"), [self], {})[0]
        return _wrap(jnp.array(self._data), self._ctx)

    def copyto(self, other):
        """Copy into another NDArray or to a Context (reference CopyFromTo)."""
        if isinstance(other, Context):
            dev = other.jax_device
            return NDArray(jax.device_put(self._data, dev), Context(other.device_type, other.device_id))
        if isinstance(other, NDArray):
            if other is self:
                return other
            dev = other._ctx.jax_device
            other._set_data(jax.device_put(self._data.astype(other.dtype), dev))
            return other
        raise TypeError("copyto does not support type " + str(type(other)))

    def as_in_context(self, context):
        if context == self._ctx:
            return self
        return self.copyto(context)

    def tostype(self, stype):
        from .sparse import cast_storage
        return cast_storage(self, stype)

    # -- autograd ----------------------------------------------------------
    def attach_grad(self, grad_req="write", stype=None):
        self._grad = _wrap(jnp.zeros_like(self._data), self._ctx)
        self._grad_req = grad_req
        self._marked = True

    def detach(self):
        out = _wrap(self._data, self._ctx)
        return out

    def backward(self, out_grad=None, retain_graph=False, train_mode=True):
        ag.backward([self], [out_grad] if out_grad is not None else None,
                    retain_graph=retain_graph, train_mode=train_mode)

    # -- shape ops (delegate to registry so they record on the tape) -------
    def reshape(self, *shape, **kwargs):
        if len(shape) == 1 and isinstance(shape[0], (tuple, list)):
            shape = tuple(shape[0])
        shape = kwargs.get("shape", shape)
        return invoke(get_op("Reshape"), [self], {"shape": tuple(shape)})[0]

    def reshape_like(self, other):
        return invoke(get_op("reshape_like"), [self, other], {})[0]

    def expand_dims(self, axis):
        return invoke(get_op("expand_dims"), [self], {"axis": axis})[0]

    def flatten(self):
        return invoke(get_op("Flatten"), [self], {})[0]

    def transpose(self, axes=None):
        return invoke(get_op("transpose"), [self], {"axes": axes})[0]

    def swapaxes(self, dim1, dim2):
        return invoke(get_op("SwapAxis"), [self], {"dim1": dim1, "dim2": dim2})[0]

    def split(self, num_outputs, axis=1, squeeze_axis=False):
        return invoke(get_op("SliceChannel"), [self],
                      {"num_outputs": num_outputs, "axis": axis,
                       "squeeze_axis": squeeze_axis})

    def slice(self, begin, end, step=None):
        return invoke(get_op("slice"), [self],
                      {"begin": begin, "end": end, "step": step})[0]

    def slice_axis(self, axis, begin, end):
        return invoke(get_op("slice_axis"), [self],
                      {"axis": axis, "begin": begin, "end": end})[0]

    def take(self, indices, axis=0, mode="clip"):
        return invoke(get_op("take"), [self, indices],
                      {"axis": axis, "mode": mode})[0]

    def pick(self, index, axis=-1, keepdims=False):
        return invoke(get_op("pick"), [self, index],
                      {"axis": axis, "keepdims": keepdims})[0]

    def one_hot(self, depth, **kw):
        return invoke(get_op("one_hot"), [self], dict(depth=depth, **kw))[0]

    def broadcast_to(self, shape):
        return invoke(get_op("broadcast_to"), [self], {"shape": tuple(shape)})[0]

    def broadcast_axes(self, axis, size):
        return invoke(get_op("broadcast_axis"), [self],
                      {"axis": axis, "size": size})[0]

    def tile(self, reps):
        return invoke(get_op("tile"), [self], {"reps": reps})[0]

    def repeat(self, repeats, axis=None):
        return invoke(get_op("repeat"), [self],
                      {"repeats": repeats, "axis": axis})[0]

    def pad(self, mode, pad_width, constant_value=0.0):
        return invoke(get_op("Pad"), [self],
                      {"mode": mode, "pad_width": pad_width,
                       "constant_value": constant_value})[0]

    def flip(self, axis):
        return invoke(get_op("reverse"), [self], {"axis": axis})[0]

    def clip(self, a_min, a_max):
        return invoke(get_op("clip"), [self], {"a_min": a_min, "a_max": a_max})[0]

    def abs(self):
        return invoke(get_op("abs"), [self], {})[0]

    def sign(self):
        return invoke(get_op("sign"), [self], {})[0]

    def sqrt(self):
        return invoke(get_op("sqrt"), [self], {})[0]

    def square(self):
        return invoke(get_op("square"), [self], {})[0]

    def exp(self):
        return invoke(get_op("exp"), [self], {})[0]

    def log(self):
        return invoke(get_op("log"), [self], {})[0]

    def sigmoid(self):
        return invoke(get_op("sigmoid"), [self], {})[0]

    def tanh(self):
        return invoke(get_op("tanh"), [self], {})[0]

    def relu(self):
        return invoke(get_op("relu"), [self], {})[0]

    def softmax(self, axis=-1):
        return invoke(get_op("softmax"), [self], {"axis": axis})[0]

    def log_softmax(self, axis=-1):
        return invoke(get_op("log_softmax"), [self], {"axis": axis})[0]

    def dot(self, other, **kw):
        return invoke(get_op("dot"), [self, other], kw)[0]

    # -- reductions --------------------------------------------------------
    def _reduce(self, opname, axis=None, keepdims=False, **kw):
        return invoke(get_op(opname), [self],
                      dict(axis=axis, keepdims=keepdims, **kw))[0]

    def sum(self, axis=None, keepdims=False):
        return self._reduce("sum", axis, keepdims)

    def mean(self, axis=None, keepdims=False):
        return self._reduce("mean", axis, keepdims)

    def prod(self, axis=None, keepdims=False):
        return self._reduce("prod", axis, keepdims)

    def max(self, axis=None, keepdims=False):
        return self._reduce("max", axis, keepdims)

    def min(self, axis=None, keepdims=False):
        return self._reduce("min", axis, keepdims)

    def nansum(self, axis=None, keepdims=False):
        return self._reduce("nansum", axis, keepdims)

    def nanprod(self, axis=None, keepdims=False):
        return self._reduce("nanprod", axis, keepdims)

    def argmax(self, axis=None, keepdims=False):
        return self._reduce("argmax", axis, keepdims)

    def argmin(self, axis=None, keepdims=False):
        return self._reduce("argmin", axis, keepdims)

    def norm(self, ord=2, axis=None, keepdims=False):
        return invoke(get_op("norm"), [self],
                      {"ord": ord, "axis": axis, "keepdims": keepdims})[0]

    def argsort(self, axis=-1, is_ascend=True):
        return invoke(get_op("argsort"), [self],
                      {"axis": axis, "is_ascend": is_ascend})[0]

    def sort(self, axis=-1, is_ascend=True):
        return invoke(get_op("sort"), [self],
                      {"axis": axis, "is_ascend": is_ascend})[0]

    def topk(self, axis=-1, k=1, ret_typ="indices", is_ascend=False):
        return invoke(get_op("topk"), [self],
                      {"axis": axis, "k": k, "ret_typ": ret_typ,
                       "is_ascend": is_ascend})

    # -- arithmetic --------------------------------------------------------
    def _binary(self, opname, other, reverse=False):
        if isinstance(other, NDArray):
            a, b = (other, self) if reverse else (self, other)
            return invoke(get_op(opname), [a, b], {})[0]
        if isinstance(other, (int, float, np.generic, bool)):
            scalar_map = {
                "elemwise_add": "_plus_scalar",
                "elemwise_sub": "_rminus_scalar" if reverse else "_minus_scalar",
                "elemwise_mul": "_mul_scalar",
                "elemwise_div": "_rdiv_scalar" if reverse else "_div_scalar",
                "elemwise_mod": "_rmod_scalar" if reverse else "_mod_scalar",
                "elemwise_power": "_rpower_scalar" if reverse else "_power_scalar",
                "elemwise_maximum": "_maximum_scalar",
                "elemwise_minimum": "_minimum_scalar",
                "_equal": "_equal_scalar", "_not_equal": "_not_equal_scalar",
                "_greater": "_lesser_scalar" if reverse else "_greater_scalar",
                "_greater_equal": "_lesser_equal_scalar" if reverse else "_greater_equal_scalar",
                "_lesser": "_greater_scalar" if reverse else "_lesser_scalar",
                "_lesser_equal": "_greater_equal_scalar" if reverse else "_lesser_equal_scalar",
            }
            return invoke(get_op(scalar_map[opname]), [self],
                          {"scalar": float(other)})[0]
        return NotImplemented

    def __add__(self, o): return self._binary("elemwise_add", o)
    def __radd__(self, o): return self._binary("elemwise_add", o, True)
    def __sub__(self, o): return self._binary("elemwise_sub", o)
    def __rsub__(self, o): return self._binary("elemwise_sub", o, True)
    def __mul__(self, o): return self._binary("elemwise_mul", o)
    def __rmul__(self, o): return self._binary("elemwise_mul", o, True)
    def __truediv__(self, o): return self._binary("elemwise_div", o)
    def __rtruediv__(self, o): return self._binary("elemwise_div", o, True)
    def __div__(self, o): return self._binary("elemwise_div", o)
    def __rdiv__(self, o): return self._binary("elemwise_div", o, True)
    def __mod__(self, o): return self._binary("elemwise_mod", o)
    def __rmod__(self, o): return self._binary("elemwise_mod", o, True)
    def __pow__(self, o): return self._binary("elemwise_power", o)
    def __rpow__(self, o): return self._binary("elemwise_power", o, True)
    def __matmul__(self, o): return self.dot(o)
    def __neg__(self): return invoke(get_op("negative"), [self], {})[0]
    def __abs__(self): return invoke(get_op("abs"), [self], {})[0]
    def __eq__(self, o):
        if o is None:
            return False
        return self._binary("_equal", o)
    def __ne__(self, o):
        if o is None:
            return True
        return self._binary("_not_equal", o)
    def __gt__(self, o): return self._binary("_greater", o)
    def __ge__(self, o): return self._binary("_greater_equal", o)
    def __lt__(self, o): return self._binary("_lesser", o)
    def __le__(self, o): return self._binary("_lesser_equal", o)
    __hash__ = object.__hash__

    def __iadd__(self, o):
        return self._set_data((self + o)._data)

    def __isub__(self, o):
        return self._set_data((self - o)._data)

    def __imul__(self, o):
        return self._set_data((self * o)._data)

    def __itruediv__(self, o):
        return self._set_data((self / o)._data)

    __idiv__ = __itruediv__

    # -- indexing ----------------------------------------------------------
    def _norm_key(self, key):
        if isinstance(key, NDArray):
            return key._data.astype(jnp.int32)
        if isinstance(key, tuple):
            return tuple(self._norm_key(k) if isinstance(k, NDArray) else k
                         for k in key)
        return key

    def __getitem__(self, key):
        key = self._norm_key(key)
        if ag.is_recording():
            # slicing must land on the tape or gradients through views
            # are silently dropped (x[:, t, :] inside autograd.record)
            return invoke(get_op("_internal_getitem"), [self],
                          {"key": key})[0]
        return _wrap(self._data[key], self._ctx)

    def __setitem__(self, key, value):
        key = self._norm_key(key)
        if isinstance(value, NDArray):
            v = value._data
        elif isinstance(value, (np.ndarray, list, tuple)):
            v = jnp.asarray(np.asarray(value, dtype=self.dtype))
        else:
            v = value
        self._set_data(self._data.at[key].set(v))


def _wrap(jarr, ctx=None):
    return NDArray(jarr, ctx or current_context())


def _nd_unpickle(npy, dev_type, dev_id, stype):
    out = array(npy, ctx=Context(dev_type, dev_id), dtype=npy.dtype)
    out._stype = stype
    return out


def _current_rng():
    return _random.next_key()


def invoke(op, inputs, attrs, out=None):
    """Execute a registered op eagerly; record on the autograd tape if needed.

    Reference analogue: MXImperativeInvokeEx → Imperative::Invoke
    (``src/imperative/imperative.cc:86``) and RecordOp (:182).
    """
    if not (_prof.is_running() or _tel.enabled()):   # the eager off path
        return _invoke(op, inputs, attrs, out)
    prof_all = _prof.is_running() and _prof._state["mode"] == "all"
    tel = _tel.enabled()
    if prof_all or tel:
        t0 = _tel.now_us()
        try:
            return _invoke(op, inputs, attrs, out)
        finally:
            dur = _tel.now_us() - t0
            if prof_all:
                _prof.record_op(op if isinstance(op, str) else op.name,
                                t0, dur)
            if tel:
                _tel.bump("eager_invocations")
                _tel.observe("eager_dispatch_us", dur)
    return _invoke(op, inputs, attrs, out)


def _invoke(op, inputs, attrs, out=None):
    if isinstance(op, str):
        op = get_op(op)
    attrs = dict(attrs)
    ctx = attrs.pop("ctx", None)
    if ctx is None:
        ctx = inputs[0]._ctx if inputs else current_context()
    elif not isinstance(ctx, Context):
        ctx = Context(ctx) if isinstance(ctx, str) else ctx
    attrs.pop("name", None)
    attrs.pop("dtype_np", None)

    jin = [x._data for x in inputs]
    rng = _current_rng() if op.needs_rng else None
    train = ag.is_training()

    recording = (ag.is_recording() and inputs
                 and not all(i in op.nondiff_inputs for i in range(len(inputs))))

    if recording:
        diff_idx = [i for i in range(len(inputs))
                    if i not in op.nondiff_inputs]
        if op.custom_vjp is not None:
            out_vals = op.apply(jin, attrs, train_mode=train, rng=rng)
            node_kw = dict(custom_bwd=op.custom_vjp, in_vals=tuple(jin),
                           out_vals=out_vals)
        else:
            def pure(*diff_vals):
                full = list(jin)
                for i, v in zip(diff_idx, diff_vals):
                    full[i] = v
                return op.apply(full, attrs, train_mode=train, rng=rng)
            out_vals, vjp_fn = jax.vjp(pure, *[jin[i] for i in diff_idx])
            node_kw = dict(vjp_fn=vjp_fn)
        outputs = [_wrap(v, ctx) for v in out_vals]
        node = ag.TapeNode(op, attrs, list(inputs), outputs, diff_idx,
                           **node_kw)
        for o in outputs:
            o._tape_node = node
        ag.append_node(node)
    else:
        out_vals = op.apply(jin, attrs, train_mode=train, rng=rng)
        outputs = [_wrap(v, ctx) for v in out_vals]

    # aux-state writeback (BatchNorm moving stats, optimizer state slots)
    for aux_in, out_idx in op.aux_updates.items():
        if aux_in < len(inputs):
            inputs[aux_in]._set_data(out_vals[out_idx])

    nvis = op.n_visible_outputs(attrs)
    visible = outputs[:nvis]
    if op.no_inputs and ctx is not None:
        for o in visible:
            o._ctx = ctx
            o._set_data(jax.device_put(o._data, ctx.jax_device))
    if out is not None:
        outs = out if isinstance(out, (list, tuple)) else [out]
        for dst, src in zip(outs, visible):
            dst._set_data(src._data.astype(dst.dtype))
        return list(outs)
    return visible


def imperative_invoke(op_name, *inputs, **attrs):
    """C-API-shaped entry (MXImperativeInvoke parity)."""
    out = attrs.pop("out", None)
    res = invoke(get_op(op_name), list(inputs), attrs, out=out)
    return res[0] if len(res) == 1 else res


# --- creation API -----------------------------------------------------------
def array(source_array, ctx=None, dtype=None):
    """Create an NDArray (reference semantics: dtype defaults to
    source.dtype for NDArray source, float32 otherwise)."""
    ctx = ctx or current_context()
    if isinstance(source_array, NDArray):
        dt = dtype_np(dtype) if dtype is not None else source_array.dtype
        return NDArray(jax.device_put(source_array._data.astype(dt),
                                      ctx.jax_device), ctx)
    arr = np.asarray(source_array)
    # reference semantics: default dtype is float32 unless source is NDArray
    dt = dtype_np(dtype) if dtype is not None else np.dtype(np.float32)
    return NDArray(jax.device_put(jnp.asarray(arr.astype(dt)), ctx.jax_device), ctx)


def zeros(shape, ctx=None, dtype=None, stype=None, **kw):
    ctx = ctx or current_context()
    return invoke(get_op("_zeros"), [],
                  {"shape": shape, "dtype": dtype or "float32", "ctx": ctx})[0]


def ones(shape, ctx=None, dtype=None, **kw):
    ctx = ctx or current_context()
    return invoke(get_op("_ones"), [],
                  {"shape": shape, "dtype": dtype or "float32", "ctx": ctx})[0]


def empty(shape, ctx=None, dtype=None):
    return zeros(shape, ctx, dtype)


def full(shape, val, ctx=None, dtype=None, out=None):
    return invoke(get_op("_full"), [],
                  {"shape": shape, "value": val, "dtype": dtype or "float32",
                   "ctx": ctx or current_context()}, out=out)[0]


def arange(start, stop=None, step=1.0, repeat=1, ctx=None, dtype=None,
           infer_range=False):
    return invoke(get_op("_arange"), [],
                  {"start": start, "stop": stop, "step": step,
                   "repeat": repeat, "dtype": dtype or "float32",
                   "ctx": ctx or current_context()})[0]


def eye(N, M=0, k=0, ctx=None, dtype=None):
    return invoke(get_op("_eye"), [],
                  {"N": N, "M": M, "k": k, "dtype": dtype or "float32",
                   "ctx": ctx or current_context()})[0]


def concatenate(arrays, axis=0, always_copy=True):
    return invoke(get_op("Concat"), list(arrays), {"dim": axis})[0]


def moveaxis(tensor, source, destination):
    axes = list(range(tensor.ndim))
    axes.remove(source % tensor.ndim)
    axes.insert(destination % tensor.ndim, source % tensor.ndim)
    return tensor.transpose(axes)


def onehot_encode(indices, out):
    depth = out.shape[1]
    return invoke(get_op("one_hot"), [indices], {"depth": depth}, out=out)[0]


def waitall():
    from .. import engine
    engine.wait_for_all()
