"""Sparse NDArray: row_sparse + csr (reference python/mxnet/ndarray/sparse.py).

Reference analogue: ``include/mxnet/ndarray.h:58-63`` storage types and the
FComputeEx sparse kernel path (SURVEY §2.1 NDArray row).

TPU-native design decision (SURVEY §7 hard-parts "Sparse parity"): XLA wants
static shapes, and TPU has no scatter-gather-friendly sparse format, so the
*backing store is dense* with sparse metadata materialized lazily on host.
The sparse classes preserve the reference API (``.indices``, ``.indptr``,
``.data``, ``tostype``, ``retain``) and its semantics (row-sparse gradients
for Embedding/dot, kvstore row_sparse push/pull), while every device compute
runs dense — which on TPU is usually *faster* than emulated scatter for the
model sizes the reference targets; the dense path is also exactly what the
reference's ``FComputeFallback`` does.
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ..base import MXNetError, dtype_np
from ..context import current_context
from .ndarray import NDArray, _wrap, array, invoke
from ..ops.registry import get_op

__all__ = ["BaseSparseNDArray", "RowSparseNDArray", "CSRNDArray",
           "row_sparse_array", "csr_matrix", "cast_storage", "zeros"]


class BaseSparseNDArray(NDArray):
    __slots__ = ("_idx_cache", "_val_cache")

    def __repr__(self):
        return "\n<%s %s @%s>" % (type(self).__name__,
                                  "x".join(str(s) for s in self.shape),
                                  self.context)

    def asscipy(self):
        import scipy.sparse as sp
        if self.stype == "csr":
            return sp.csr_matrix(self.asnumpy())
        raise MXNetError("asscipy only supported for csr")


class RowSparseNDArray(BaseSparseNDArray):
    """Row-sparse array: index + values metadata over a dense backing store.

    The SURVEY §7 design: device compute stays dense (XLA-friendly), but
    the sparse identity — which rows are active — is carried as explicit
    device arrays: constructors from (data, indices) seed the metadata,
    mutation drops it, and ``indices``/``data`` recompute on DEVICE
    (jnp mask/take) only when no metadata is cached. kvstore
    row_sparse_pull and the dist server's pull_rows ride the same gather
    path instead of materialising host copies.
    """
    __slots__ = ()

    def _set_data(self, jarr):
        # any mutation invalidates the sparse metadata
        self._idx_cache = None
        self._val_cache = None
        super()._set_data(jarr)

    def _seed_sparse(self, indices, values):
        self._idx_cache = jnp.asarray(indices, jnp.int64)
        self._val_cache = None if values is None else jnp.asarray(values)

    def _active_rows(self):
        if getattr(self, "_idx_cache", None) is not None:
            return self._idx_cache
        flat = self._data.reshape(self.shape[0], -1)
        mask = jnp.any(flat != 0, axis=1)           # device-side reduction
        rows = jnp.nonzero(mask)[0].astype(jnp.int64)
        self._idx_cache = rows
        return rows

    @property
    def indices(self):
        return _wrap(self._active_rows(), self.context)

    @property
    def data(self):
        if getattr(self, "_val_cache", None) is not None:
            return _wrap(self._val_cache, self.context)
        vals = jnp.take(self._data, self._active_rows(), axis=0)
        self._val_cache = vals
        return _wrap(vals, self.context)

    def tostype(self, stype):
        return cast_storage(self, stype)

    def retain(self, indices):
        return invoke(get_op("sparse_retain"), [self, indices], {})[0]


class CSRNDArray(BaseSparseNDArray):
    """Compressed sparse row matrix: (data, indices, indptr) metadata
    over a dense backing store, mirroring RowSparseNDArray's design —
    constructors seed the metadata, mutation drops it, and the parts
    recompute (scipy, host-side) only when no cache exists."""
    __slots__ = ()

    def _set_data(self, jarr):
        # any mutation invalidates the sparse metadata
        self._idx_cache = None
        super()._set_data(jarr)

    def _seed_csr(self, data, indices, indptr):
        # copies: np.asarray would alias caller buffers, letting later
        # external mutation desync metadata from the dense store
        self._idx_cache = (np.array(data),
                           np.array(indices, np.int64),
                           np.array(indptr, np.int64))

    def _csr_parts(self):
        if getattr(self, "_idx_cache", None) is None:
            import scipy.sparse as sp
            m = sp.csr_matrix(self.asnumpy())
            self._idx_cache = (m.data,
                               m.indices.astype(np.int64),
                               m.indptr.astype(np.int64))
        return self._idx_cache

    @property
    def indices(self):
        return array(self._csr_parts()[1], ctx=self.context,
                     dtype=np.int64)

    @property
    def indptr(self):
        return array(self._csr_parts()[2], ctx=self.context,
                     dtype=np.int64)

    @property
    def data(self):
        return array(self._csr_parts()[0], ctx=self.context,
                     dtype=self.dtype)

    def tostype(self, stype):
        return cast_storage(self, stype)


def _retag(arr, stype):
    cls = {"default": NDArray, "row_sparse": RowSparseNDArray,
           "csr": CSRNDArray}[stype]
    out = cls(arr._data, arr.context)
    out._stype = stype
    if stype != "default":
        out._idx_cache = None
        out._val_cache = None
    return out


def cast_storage(arr, stype):
    """Convert between storage types (reference cast_storage op)."""
    if stype == arr.stype:
        return arr
    return _retag(arr, stype)


def row_sparse_array(arg1, shape=None, ctx=None, dtype=None):
    """Create a RowSparseNDArray from (data, indices) or a dense source."""
    ctx = ctx or current_context()
    if isinstance(arg1, tuple) and len(arg1) == 2:
        data, indices = arg1
        data = np.asarray(data, dtype=dtype_np(dtype))
        indices = np.asarray(indices, dtype=np.int64).reshape(-1)
        if shape is None:
            nrows = int(indices.max()) + 1 if indices.size else 0
            shape = (nrows,) + tuple(data.shape[1:])
        dense = np.zeros(shape, dtype=data.dtype)
        if indices.size:
            dense[indices] = data
        out = array(dense, ctx=ctx, dtype=data.dtype)
        out = _retag(out, "row_sparse")
        out._seed_sparse(indices, data)
        return out
    if isinstance(arg1, NDArray):
        return cast_storage(arg1, "row_sparse")
    out = array(np.asarray(arg1, dtype=dtype_np(dtype)), ctx=ctx)
    return _retag(out, "row_sparse")


def csr_matrix(arg1, shape=None, ctx=None, dtype=None):
    """Create a CSRNDArray from (data, indices, indptr) or dense/scipy."""
    ctx = ctx or current_context()
    if isinstance(arg1, tuple) and len(arg1) == 3:
        data, indices, indptr = arg1
        data = np.asarray(data, dtype=dtype_np(dtype))
        indices = np.asarray(indices, dtype=np.int64)
        indptr = np.asarray(indptr, dtype=np.int64)
        if shape is None:
            ncols = int(indices.max()) + 1 if indices.size else 0
            shape = (len(indptr) - 1, ncols)
        dense = np.zeros(shape, dtype=data.dtype)
        rows = np.repeat(np.arange(shape[0]), np.diff(indptr))
        np.add.at(dense, (rows, indices), data)   # scipy duplicate-sum
        out = array(dense, ctx=ctx, dtype=data.dtype)
        out = _retag(out, "csr")
        # seed metadata only when it is canonical: no duplicate column per
        # row AND columns sorted within each row (strictly increasing flat
        # keys) — otherwise .indices/.data would depend on construction
        # history vs the scipy-recomputed (sorted) form after any mutation
        flat = rows * max(shape[1], 1) + indices
        if flat.size == 0 or bool(np.all(np.diff(flat) > 0)):
            out._seed_csr(data, indices, indptr)
        return out
    if isinstance(arg1, NDArray):
        return cast_storage(arg1, "csr")
    if hasattr(arg1, "toarray"):  # scipy sparse
        out = array(arg1.toarray(), ctx=ctx, dtype=dtype)
        return _retag(out, "csr")
    out = array(np.asarray(arg1), ctx=ctx, dtype=dtype)
    return _retag(out, "csr")


def zeros(stype, shape, ctx=None, dtype=None):
    from .ndarray import zeros as _dense_zeros
    out = _dense_zeros(shape, ctx=ctx, dtype=dtype)
    if stype == "default":
        return out
    return _retag(out, stype)
