"""``mx.nd.linalg`` namespace (reference la_op.cc LAPACK ops)."""
from __future__ import annotations

from .ndarray import invoke, NDArray
from ..ops.registry import get_op

__all__ = ["gemm", "gemm2", "potrf", "potri", "trsm", "trmm", "sumlogdiag",
           "syrk", "syevd", "gelqf"]


def _mk(opname):
    def f(*args, **kwargs):
        kwargs.pop("name", None)
        out = kwargs.pop("out", None)
        res = invoke(get_op(opname), [a for a in args if isinstance(a, NDArray)],
                     kwargs, out=out)
        return res[0] if len(res) == 1 else res
    f.__name__ = opname.replace("_linalg_", "")
    return f


gemm = _mk("_linalg_gemm")
gemm2 = _mk("_linalg_gemm2")
potrf = _mk("_linalg_potrf")
potri = _mk("_linalg_potri")
trsm = _mk("_linalg_trsm")
trmm = _mk("_linalg_trmm")
sumlogdiag = _mk("_linalg_sumlogdiag")
syrk = _mk("_linalg_syrk")
syevd = _mk("_linalg_syevd")
gelqf = _mk("_linalg_gelqf")
