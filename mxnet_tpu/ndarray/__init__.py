"""``mx.nd`` namespace: NDArray + generated operator functions.

Reference analogue: ``python/mxnet/ndarray/`` — the op functions there are
code-generated from the C op registry (``register.py`` + ``_internal.py``);
here they are generated from the Python op registry.  Public (non-underscore)
ops land in this namespace; every op (including ``_internal``-style names)
lands in ``mxnet_tpu.ndarray._internal``.
"""
from __future__ import annotations

import sys
import types

from .ndarray import (NDArray, array, zeros, ones, empty, full, arange, eye,
                      concatenate, moveaxis, onehot_encode, waitall, invoke,
                      imperative_invoke, _wrap)
from .utils import save, load, save_to_bytes, load_from_bytes
from ..ops.registry import OP_REGISTRY, get_op


def _scalar_attr_names(op):
    """Keyword parameter names of the op fn, in declaration order (for
    mapping scalar positional args, reference generated-op behaviour)."""
    import inspect
    try:
        sig = inspect.signature(op.fn)
    except (TypeError, ValueError):
        return []
    return [p.name for p in sig.parameters.values()
            if p.default is not inspect.Parameter.empty
            and p.name not in ("train_mode", "rng")]


def _make_op_func(name, op):
    scalar_names = None

    def op_func(*args, **kwargs):
        nonlocal scalar_names
        out = kwargs.pop("out", None)
        kwargs.pop("name", None)
        ndargs = []
        scalars = []
        for a in args:
            if isinstance(a, NDArray):
                ndargs.append(a)
            elif isinstance(a, (list, tuple)) and a and isinstance(a[0], NDArray):
                ndargs.extend(a)
            elif a is None:
                continue
            else:
                scalars.append(a)
        if scalars:
            # scalar positionals fill the op's attr params in order
            if scalar_names is None:
                scalar_names = _scalar_attr_names(op)
            free = [n for n in scalar_names if n not in kwargs]
            if len(scalars) > len(free):
                raise TypeError(
                    "operator %s got %d scalar positional args but only "
                    "has attr slots %s" % (name, len(scalars), free))
            for n, v in zip(free, scalars):
                kwargs[n] = v
        res = invoke(op, ndargs, kwargs, out=out)
        return res[0] if len(res) == 1 else res
    op_func.__name__ = name
    op_func.__doc__ = op.describe()
    return op_func


_internal = types.ModuleType(__name__ + "._internal")
_this = sys.modules[__name__]
for _name, _op in OP_REGISTRY.items():
    _fn = _make_op_func(_name, _op)
    setattr(_internal, _name, _fn)
    if not _name.startswith("_"):
        if not hasattr(_this, _name):
            setattr(_this, _name, _fn)
sys.modules[__name__ + "._internal"] = _internal

# mx.nd.contrib namespace: _contrib_* ops under their stripped names
contrib = types.ModuleType(__name__ + ".contrib")
for _name, _op in OP_REGISTRY.items():
    if _name.startswith("_contrib_"):
        setattr(contrib, _name[len("_contrib_"):],
                _make_op_func(_name, _op))
sys.modules[__name__ + ".contrib"] = contrib

from . import random  # noqa: E402,F401
from . import sparse  # noqa: E402,F401
from .sparse import csr_matrix, row_sparse_array  # noqa: E402
from . import linalg  # noqa: E402,F401

__all__ = ["NDArray", "array", "zeros", "ones", "empty", "full", "arange",
           "eye", "concatenate", "moveaxis", "onehot_encode", "waitall",
           "save", "load", "invoke", "imperative_invoke", "random", "sparse",
           "linalg", "csr_matrix", "row_sparse_array"]
