"""NDArray save/load in the reference's binary format.

Format parity with ``src/ndarray/ndarray.cc`` (Save at :826, list container
at :1022): files written here are bit-compatible with MXNet v0.12 ``.params``
/ ``mx.nd.save`` files for dense arrays, so reference checkpoints load and
vice versa.

Layout (little-endian):
  file   := uint64 0x112 (kMXAPINDArrayListMagic) | uint64 reserved
          | uint64 n | NDArray*n | uint64 nkeys | (uint64 len | bytes)*nkeys
  ndarray:= uint32 0xF993fac9 (NDARRAY_V2_MAGIC) | int32 stype(0=dense)
          | shape | int32 dev_type | int32 dev_id | int32 type_flag | raw data
  shape  := uint32 ndim | int64 dim[ndim]          (nnvm::TShape::Save)

Legacy records also load (reference LegacyLoad, ndarray.cc:892-937):
  V1     := uint32 0xF993fac8 | shape | context | type_flag | raw data
  V0     := uint32 ndim | uint32 dim[ndim] | context | type_flag | raw data
"""
from __future__ import annotations

import struct

import numpy as np

from ..base import MXNetError, CODE_TO_DTYPE, DTYPE_TO_CODE
from ..context import cpu
from .ndarray import NDArray, array

__all__ = ["save", "load", "save_to_bytes", "load_from_bytes"]

_LIST_MAGIC = 0x112
_V2_MAGIC = 0xF993FAC9
_V1_MAGIC = 0xF993FAC8


def _write_shape(buf, shape):
    buf.append(struct.pack("<I", len(shape)))
    if shape:
        buf.append(struct.pack("<%dq" % len(shape), *shape))


def _read_shape(mv, off):
    (ndim,) = struct.unpack_from("<I", mv, off)
    off += 4
    dims = struct.unpack_from("<%dq" % ndim, mv, off) if ndim else ()
    off += 8 * ndim
    return tuple(dims), off


def _save_one(buf, arr):
    if arr.stype != "default":
        arr = arr.tostype("default")
    buf.append(struct.pack("<I", _V2_MAGIC))
    buf.append(struct.pack("<i", 0))  # kDefaultStorage
    _write_shape(buf, arr.shape)
    buf.append(struct.pack("<ii", 1, 0))  # Context: cpu(0)
    npy = arr.asnumpy()
    code = DTYPE_TO_CODE[np.dtype(npy.dtype)]
    buf.append(struct.pack("<i", code))
    buf.append(np.ascontiguousarray(npy).tobytes())


def _load_legacy(mv, off, magic):
    """V1 / V0 NDArray records (reference NDArray::LegacyLoad,
    src/ndarray/ndarray.cc:908-937 over LegacyTShapeLoad :892).

    V1 (magic 0xF993FAC8): shape is the V2 TShape (uint32 ndim + int64
    dims). V0 has NO magic — the word already read IS ndim, followed by
    uint32 dims. Both then carry context, type_flag, raw data like V2.
    """
    if magic == _V1_MAGIC:
        shape, off = _read_shape(mv, off)
    else:
        ndim = magic
        if ndim > 32:  # not a plausible legacy ndim -> corrupt/unknown
            raise MXNetError("invalid NDArray save format: bad magic 0x%x"
                             % magic)
        shape = struct.unpack_from("<%dI" % ndim, mv, off) if ndim else ()
        off += 4 * ndim
        shape = tuple(int(d) for d in shape)
    if not shape:
        return array(np.zeros((0,), np.float32), ctx=cpu()), off
    return _read_body(mv, off, shape)


def _read_body(mv, off, shape):
    """context | type_flag | raw data — shared by every format version."""
    dev_type, dev_id = struct.unpack_from("<ii", mv, off)
    off += 8
    (type_flag,) = struct.unpack_from("<i", mv, off)
    off += 4
    dt = np.dtype(CODE_TO_DTYPE[type_flag])
    count = int(np.prod(shape)) if shape else 1
    nbytes = count * dt.itemsize
    data = np.frombuffer(mv, dtype=dt, count=count, offset=off).reshape(shape)
    off += nbytes
    return array(data, ctx=cpu(), dtype=dt), off


def _load_one(mv, off):
    (magic,) = struct.unpack_from("<I", mv, off)
    off += 4
    if magic != _V2_MAGIC:
        return _load_legacy(mv, off, magic)
    (stype,) = struct.unpack_from("<i", mv, off)
    off += 4
    if stype != 0:
        raise MXNetError("sparse NDArray load not supported yet")
    shape, off = _read_shape(mv, off)
    return _read_body(mv, off, shape)


def save_to_bytes(data):
    if isinstance(data, NDArray):
        data = [data]
    if isinstance(data, dict):
        keys = list(data.keys())
        arrays = [data[k] for k in keys]
    else:
        keys = []
        arrays = list(data)
    buf = [struct.pack("<QQ", _LIST_MAGIC, 0), struct.pack("<Q", len(arrays))]
    for a in arrays:
        _save_one(buf, a)
    buf.append(struct.pack("<Q", len(keys)))
    for k in keys:
        kb = k.encode("utf-8")
        buf.append(struct.pack("<Q", len(kb)))
        buf.append(kb)
    return b"".join(buf)


def save(fname, data):
    """Save list/dict of NDArrays (reference mx.nd.save). Scheme URIs
    (s3://, mem://, ...) dispatch through mxnet_tpu.stream — the dmlc
    Stream parity hook (ref include/mxnet/ndarray.h:340)."""
    from ..stream import open_stream
    with open_stream(fname, "wb") as f:
        f.write(save_to_bytes(data))


def load_from_bytes(raw):
    try:
        mv = memoryview(raw)
        header, _res = struct.unpack_from("<QQ", mv, 0)
        if header != _LIST_MAGIC:
            raise MXNetError("Invalid NDArray file format")
        (n,) = struct.unpack_from("<Q", mv, 16)
        off = 24
        arrays = []
        for _ in range(n):
            a, off = _load_one(mv, off)
            arrays.append(a)
        (nkeys,) = struct.unpack_from("<Q", mv, off)
        off += 8
        keys = []
        for _ in range(nkeys):
            (ln,) = struct.unpack_from("<Q", mv, off)
            off += 8
            keys.append(bytes(mv[off:off + ln]).decode("utf-8"))
            off += ln
    except MXNetError:
        raise
    except (struct.error, IndexError, KeyError, UnicodeDecodeError,
            ValueError, OverflowError) as exc:
        # truncated/garbage payloads must fail as a format error, not leak
        # struct internals to the caller
        raise MXNetError("Invalid NDArray file format: %s" % exc)
    if keys:
        return dict(zip(keys, arrays))
    return arrays


def load(fname):
    """Load list/dict of NDArrays (reference mx.nd.load). Scheme URIs
    dispatch through mxnet_tpu.stream (dmlc Stream parity)."""
    from ..stream import open_stream
    with open_stream(fname, "rb") as f:
        return load_from_bytes(f.read())
