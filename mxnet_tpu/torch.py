"""Torch interop bridge (reference python/mxnet/torch.py + plugin/torch).

The reference bridged Torch7 tensor functions into the op universe
(``TorchModule``/``TorchCriterion`` ops). Here the bridge is pytorch:
zero-copy-ish conversion between NDArray and ``torch.Tensor`` plus a
``pytorch_fn`` wrapper that runs any torch callable as a host op on
NDArrays. Gated on torch being importable (cpu torch ships in the
environment).
"""
from __future__ import annotations

import numpy as np

from . import ndarray as nd

__all__ = ["to_torch", "from_torch", "pytorch_fn"]


def _torch():
    try:
        import torch
        return torch
    except ImportError as exc:               # pragma: no cover
        raise ImportError("the torch bridge requires pytorch") from exc


def to_torch(arr):
    """NDArray → torch.Tensor (host copy)."""
    torch = _torch()
    return torch.from_numpy(np.ascontiguousarray(arr.asnumpy()))


def from_torch(tensor, ctx=None):
    """torch.Tensor → NDArray."""
    _torch()
    return nd.array(tensor.detach().cpu().numpy(), ctx=ctx)


def pytorch_fn(fn):
    """Wrap a torch callable so it consumes/produces NDArrays.

    >>> relu = pytorch_fn(torch.nn.functional.relu)
    >>> y = relu(x_ndarray)
    """
    def wrapped(*args, **kwargs):
        torch = _torch()
        conv = [to_torch(a) if isinstance(a, nd.NDArray) else a
                for a in args]
        out = fn(*conv, **kwargs)
        if isinstance(out, (list, tuple)):
            return [from_torch(o) if torch.is_tensor(o) else o for o in out]
        return from_torch(out) if torch.is_tensor(out) else out
    wrapped.__name__ = getattr(fn, "__name__", "pytorch_fn")
    return wrapped
