"""Network visualization: print_summary + plot_network.

Parity surface: reference ``python/mxnet/visualization.py`` (print_summary
:355 — layer table with shapes and parameter counts; plot_network —
graphviz rendering).  Works directly on the Symbol node graph.
"""
from __future__ import annotations

import json

from .symbol.symbol import Symbol, _topo

__all__ = ["print_summary", "plot_network"]


def _node_label(node):
    op = node.op.name if node.op is not None else "null"
    return op


def print_summary(symbol, shape=None, line_length=120, positions=None):
    """Print a layer-by-layer summary table (reference visualization.py:355).

    ``shape``: dict of input name -> shape, used to infer per-layer output
    shapes and parameter counts.
    """
    if not isinstance(symbol, Symbol):
        raise TypeError("symbol must be a Symbol")
    positions = positions or [0.44, 0.64, 0.74, 1.0]
    shape_dict = {}
    if shape is not None:
        internals = symbol.get_internals()
        arg_shapes, out_shapes, aux_shapes = internals.infer_shape(**shape)
        shape_dict = dict(zip(internals.list_outputs(), out_shapes))

    nodes = _topo(symbol._outputs)
    positions = [int(line_length * p) for p in positions]
    fields = ["Layer (type)", "Output Shape", "Param #", "Previous Layer"]

    def print_row(f, pos):
        line = ""
        for i, field in enumerate(f):
            line += str(field)
            line = line[:pos[i]]
            line += " " * (pos[i] - len(line))
        print(line)

    print("_" * line_length)
    print_row(fields, positions)
    print("=" * line_length)
    total_params = 0
    arg_names = set(symbol.list_arguments())
    data_names = {n for n in arg_names
                  if not n.endswith(("weight", "bias", "gamma", "beta"))}
    for node in nodes:
        if node.op is None:
            continue
        name = node.name
        out_name = node.output_name(0)
        out_shape = shape_dict.get(out_name)
        params = 0
        pre = []
        for src, _ in node.inputs:
            if src.op is None:
                if src.name not in data_names:
                    pshape = shape_dict.get(src.name + "_output") or \
                        _infer_arg_shape(symbol, src.name, shape)
                    if pshape:
                        n_el = 1
                        for s in pshape:
                            n_el *= s
                        params += n_el
            else:
                pre.append(src.name)
        total_params += params
        print_row(["%s(%s)" % (name, _node_label(node)),
                   str(out_shape) if out_shape else "",
                   str(params), ",".join(pre)], positions)
        print("_" * line_length)
    print("Total params: {}".format(total_params))
    print("_" * line_length)
    return total_params


def _infer_arg_shape(symbol, arg_name, shape):
    if shape is None:
        return None
    try:
        arg_shapes, _, _ = symbol.infer_shape_partial(**shape)
        names = symbol.list_arguments()
        if arg_name in names:
            return arg_shapes[names.index(arg_name)]
    except Exception:
        return None
    return None


def plot_network(symbol, title="plot", save_format="pdf", shape=None,
                 node_attrs=None, hide_weights=True):
    """Build a graphviz Digraph of the network (reference plot_network).

    Requires the ``graphviz`` package; raises ImportError with guidance
    otherwise (same behavior as the reference).
    """
    try:
        from graphviz import Digraph
    except ImportError:
        raise ImportError("Draw network requires graphviz library")
    if not isinstance(symbol, Symbol):
        raise TypeError("symbol must be a Symbol")
    node_attrs = node_attrs or {}

    shape_dict = {}
    if shape is not None:
        internals = symbol.get_internals()
        _, out_shapes, _ = internals.infer_shape(**shape)
        shape_dict = dict(zip(internals.list_outputs(), out_shapes))

    node_attr = {"shape": "box", "fixedsize": "true", "width": "1.3",
                 "height": "0.8034", "style": "filled"}
    node_attr.update(node_attrs)
    dot = Digraph(name=title, format=save_format)

    nodes = _topo(symbol._outputs)
    hidden = set()
    for node in nodes:
        name = node.name
        if node.op is None:
            if hide_weights and name.endswith(
                    ("weight", "bias", "gamma", "beta", "running_mean",
                     "running_var", "moving_mean", "moving_var")):
                hidden.add(id(node))
                continue
            dot.node(name=name, label=name, shape="oval",
                     fillcolor="#8dd3c7", style="filled")
        else:
            label = node.op.name
            if node.op.name in ("Convolution", "FullyConnected"):
                label = "%s\n%s" % (node.op.name,
                                    node.attrs.get("num_filter",
                                                   node.attrs.get(
                                                       "num_hidden", "")))
            dot.node(name=name, label=label, fillcolor="#fb8072",
                     **{k: v for k, v in node_attr.items()})
    for node in nodes:
        if node.op is None:
            continue
        for src, oi in node.inputs:
            if id(src) in hidden:
                continue
            label = ""
            out_name = src.output_name(oi) if src.op is not None else None
            if out_name and out_name in shape_dict:
                label = "x".join(str(s) for s in shape_dict[out_name][1:])
            dot.edge(tail_name=src.name, head_name=node.name, label=label)
    return dot
