"""Colorized logging (reference ``python/mxnet/log.py``)."""
from __future__ import annotations

import logging
import sys

__all__ = ["get_logger"]

CRITICAL = logging.CRITICAL
ERROR = logging.ERROR
WARNING = logging.WARNING
INFO = logging.INFO
DEBUG = logging.DEBUG
NOTSET = logging.NOTSET

PY3 = True


class _Formatter(logging.Formatter):
    """Per-level colored prefix when attached to a tty."""

    def __init__(self, colored=True):
        self.colored = colored
        super().__init__(datefmt="%m%d %H:%M:%S")

    def _color(self, level):
        return {logging.DEBUG: "\x1b[32m",       # green
                logging.INFO: "\x1b[34m",        # blue
                logging.WARNING: "\x1b[33m",     # yellow
                logging.ERROR: "\x1b[31m",       # red
                logging.CRITICAL: "\x1b[35m"}.get(level, "")

    def format(self, record):
        label = record.levelname[0]
        if self.colored:
            head = "%s%s%s" % (self._color(record.levelno), label,
                               "\x1b[0m")
        else:
            head = label
        self._style._fmt = head + "%(asctime)s %(process)d %(pathname)s:" \
            "%(lineno)d] %(message)s"
        return super().format(record)


def get_logger(name=None, filename=None, filemode=None, level=WARNING):
    """Get a logger with the mxnet formatting (reference log.py:getLogger)."""
    logger = logging.getLogger(name)
    if name is not None and not getattr(logger, "_init_done", False):
        logger._init_done = True
        if filename:
            mode = filemode if filemode else "a"
            hdlr = logging.FileHandler(filename, mode)
            hdlr.setFormatter(_Formatter(colored=False))
        else:
            hdlr = logging.StreamHandler(sys.stderr)
            hdlr.setFormatter(_Formatter(
                colored=getattr(sys.stderr, "isatty", lambda: False)()))
        logger.addHandler(hdlr)
    logger.setLevel(level)
    return logger
