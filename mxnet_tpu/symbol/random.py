"""``mx.sym.random`` namespace."""
from __future__ import annotations

from .symbol import Symbol

__all__ = ["uniform", "normal", "gamma", "exponential", "poisson"]


def _mk(opname, params):
    def f(*args, shape=None, dtype=None, **kw):
        attrs = dict(zip(params, args))
        attrs.update({k: v for k, v in kw.items() if not isinstance(v, Symbol)})
        attrs["shape"] = shape
        if dtype:
            attrs["dtype"] = str(dtype)
        return Symbol._from_op(opname, [], attrs, name=kw.get("name"))
    f.__name__ = opname
    return f


uniform = _mk("_random_uniform", ["low", "high"])
normal = _mk("_random_normal", ["loc", "scale"])
gamma = _mk("_random_gamma", ["alpha", "beta"])
exponential = _mk("_random_exponential", ["lam"])
poisson = _mk("_random_poisson", ["lam"])
