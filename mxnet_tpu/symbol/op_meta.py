"""Per-op metadata for symbolic composition.

Reference analogue: NNVM's ``FListInputNames``/``FListOutputNames`` op
attributes plus the bidirectional ``InferShape`` functions each operator
registers (``src/operator/*-inl.h``).  Here forward shape flow is free
(jax.eval_shape); this module supplies the two things jax cannot derive:
(1) canonical input/aux names so ``sym.Convolution(data=d, ...)``
auto-creates ``conv0_weight``/``conv0_bias`` variables, and (2) data→param
shape inference so ``simple_bind`` can allocate parameters from the data
shape alone (the workhorse behind Module).
"""
from __future__ import annotations

import numpy as np
import jax

from ..base import dtype_np

__all__ = ["op_input_names", "infer_param_shapes", "HINTS"]

# name hints for auto-naming (reference: lowercase op name)
HINTS = {
    "FullyConnected": "fullyconnected", "Convolution": "convolution",
    "Deconvolution": "deconvolution", "BatchNorm": "batchnorm",
    "Pooling": "pooling", "Activation": "activation", "Dropout": "dropout",
    "SoftmaxOutput": "softmaxoutput", "Embedding": "embedding", "RNN": "rnn",
    "Concat": "concat", "Flatten": "flatten", "Reshape": "reshape",
    "LeakyReLU": "leakyrelu", "elemwise_add": "_plus", "elemwise_sub": "_minus",
    "elemwise_mul": "_mul", "elemwise_div": "_div",
}


def _gates(mode):
    return {"rnn_relu": 1, "rnn_tanh": 1, "lstm": 4, "gru": 3}[mode]


def op_input_names(op, attrs):
    """Return (input_names, aux_names); aux_names are the trailing inputs."""
    name = op.name
    a = attrs
    if name in ("Convolution", "Convolution_v1", "Deconvolution"):
        base = ["data", "weight"]
        # reference defaults: Convolution no_bias=False, Deconvolution True
        if not a.get("no_bias", name == "Deconvolution"):
            base.append("bias")
        return base, []
    if name == "FullyConnected":
        return (["data", "weight"] if a.get("no_bias", False)
                else ["data", "weight", "bias"]), []
    if name in ("BatchNorm", "BatchNorm_v1", "CuDNNBatchNorm"):
        return ["data", "gamma", "beta"], ["moving_mean", "moving_var"]
    if name in ("InstanceNorm", "LayerNorm"):
        return ["data", "gamma", "beta"], []
    if name == "Embedding":
        return ["data", "weight"], []
    if name == "RNN":
        ins = ["data", "parameters", "state"]
        if a.get("mode", "lstm") == "lstm":
            ins.append("state_cell")
        return ins, []
    if name == "LeakyReLU":
        if a.get("act_type", "leaky") == "prelu":
            return ["data", "gamma"], []
        return ["data"], []
    if name in ("SoftmaxOutput", "Softmax", "LinearRegressionOutput",
                "LogisticRegressionOutput", "MAERegressionOutput",
                "SVMOutput", "softmax_cross_entropy"):
        return ["data", "label"], []
    if name in ("dot", "batch_dot") or name.startswith("elemwise_") \
            or name.startswith("broadcast_") or name in (
                "_plus", "_minus", "_mul", "_div", "_grad_add", "_maximum",
                "_minimum", "_power", "_mod", "_hypot"):
        return ["lhs", "rhs"], []
    if name in ("Concat", "add_n", "stack", "elemwise_sum", "ElementWiseSum",
                "UpSampling"):
        n = int(a.get("num_args", a.get("num_args", 1)) or 1)
        return ["arg%d" % i for i in range(n)], []
    if name == "where":
        return ["condition", "x", "y"], []
    if name == "ROIPooling":
        return ["data", "rois"], []
    if name in ("take", "batch_take", "gather_nd", "scatter_nd"):
        return ["a", "indices"], []
    if name in ("SequenceMask", "SequenceLast", "SequenceReverse"):
        if a.get("use_sequence_length", False):
            return ["data", "sequence_length"], []
        return ["data"], []
    if name in ("SpatialTransformer",):
        return ["data", "loc"], []
    if name in ("BilinearSampler",):
        return ["data", "grid"], []
    if name in ("Crop",):
        n = int(a.get("num_args", 1))
        return ["data"] + (["crop_like"] if n > 1 else []), []
    return ["data"], []


def infer_param_shapes(node, in_structs):
    """Given a node whose data input shape is known, infer missing
    parameter/aux input shapes.  Returns list aligned to inputs or None."""
    op = node.op
    a = node.attrs
    name = op.name
    if not in_structs or in_structs[0] is None:
        return None
    data = in_structs[0]
    dshape = tuple(data.shape)
    dt = data.dtype
    S = lambda sh: jax.ShapeDtypeStruct(tuple(sh), dt)
    out = [None] * len(in_structs)

    if name in ("Convolution", "Convolution_v1"):
        k = tuple(a.get("kernel", ()))
        nf = int(a.get("num_filter", 1))
        g = int(a.get("num_group", 1))
        out[1] = S((nf, dshape[1] // g) + k)
        if len(in_structs) > 2:
            out[2] = S((nf,))
    elif name == "Deconvolution":
        k = tuple(a.get("kernel", ()))
        nf = int(a.get("num_filter", 1))
        g = int(a.get("num_group", 1))
        out[1] = S((dshape[1], nf // g) + k)
        if len(in_structs) > 2:
            out[2] = S((nf,))
    elif name == "FullyConnected":
        nh = int(a.get("num_hidden", 1))
        flat = a.get("flatten", True)
        in_dim = int(np.prod(dshape[1:])) if flat else dshape[-1]
        out[1] = S((nh, in_dim))
        if len(in_structs) > 2:
            out[2] = S((nh,))
    elif name in ("BatchNorm", "BatchNorm_v1", "CuDNNBatchNorm"):
        ax = int(a.get("axis", 1)) % len(dshape)
        c = dshape[ax]
        for i in range(1, len(in_structs)):
            out[i] = S((c,))
    elif name in ("InstanceNorm",):
        c = dshape[1]
        out[1] = S((c,))
        out[2] = S((c,))
    elif name == "LayerNorm":
        ax = int(a.get("axis", -1)) % len(dshape)
        c = dshape[ax]
        out[1] = S((c,))
        out[2] = S((c,))
    elif name == "Embedding":
        out[1] = S((int(a.get("input_dim")), int(a.get("output_dim"))))
    elif name == "LeakyReLU" and a.get("act_type") == "prelu":
        out[1] = S((dshape[1],))
    elif name == "RNN":
        from ..ops.nn import rnn_param_size
        h = int(a.get("state_size"))
        L = int(a.get("num_layers", 1))
        bi = bool(a.get("bidirectional", False))
        d = 2 if bi else 1
        t, n, c = dshape
        out[1] = S((rnn_param_size(L, c, h, a.get("mode", "lstm"), bi),))
        out[2] = S((L * d, n, h))
        if len(in_structs) > 3:
            out[3] = S((L * d, n, h))
    elif name in ("SoftmaxOutput", "Softmax"):
        if a.get("multi_output", False):
            out[1] = S((dshape[0],) + dshape[2:])
        else:
            out[1] = S((dshape[0],))
    elif name in ("LinearRegressionOutput", "LogisticRegressionOutput",
                  "MAERegressionOutput"):
        out[1] = S(dshape)
    elif name == "SVMOutput":
        out[1] = S((dshape[0],))
    elif name == "softmax_cross_entropy":
        out[1] = S((dshape[0],))
    else:
        return None
    return out
