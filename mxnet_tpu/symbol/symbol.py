"""Symbol: the declarative graph IR.

Parity surface: reference ``python/mxnet/symbol/symbol.py`` (composition,
``infer_shape`` :1515-area, ``simple_bind``/``bind`` :1251+, JSON save/load)
over NNVM's graph (``src/c_api/c_api_symbolic.cc``).

TPU-native redesign: a Symbol is a lightweight Python DAG over the same op
registry the eager path uses.  There are no NNVM passes — shape/dtype
inference is ``jax.eval_shape`` over each op's pure function (the compiler's
own abstract evaluation, so inference can never diverge from execution), and
"compilation" (bind) lowers the whole graph into one jitted XLA program in
``executor.py`` (replacing GraphExecutor's memory planner / op scheduler,
which XLA subsumes).

JSON graph format is reference-compatible (nodes/arg_nodes/heads with
stringified attrs) so reference ``-symbol.json`` checkpoints round-trip.
"""
from __future__ import annotations

import json

import numpy as np
import jax

from ..base import MXNetError, dtype_np
from ..ops.registry import (OP_REGISTRY, get_op, parse_attr_string,
                            attr_to_string)
from .. import name as _name_mod
from .. import attribute as _attr_mod
from .op_meta import op_input_names, infer_param_shapes, HINTS

__all__ = ["Symbol", "var", "Variable", "Group", "load", "load_json",
           "zeros", "ones", "arange"]


class SymNode:
    """One graph node: an op application or a variable (op=None)."""
    __slots__ = ("op", "name", "attrs", "inputs", "is_aux", "_num_outputs")

    def __init__(self, op, name, attrs, inputs, is_aux=False):
        self.op = op            # Op or None for variables
        self.name = name
        self.attrs = attrs      # python-typed attrs
        self.inputs = inputs    # list[(SymNode, out_idx)]
        self.is_aux = is_aux
        self._num_outputs = None

    def num_outputs(self):
        if self.op is None:
            return 1
        if self._num_outputs is None:
            self._num_outputs = self.op.n_visible_outputs(self.attrs)
        return self._num_outputs

    def output_name(self, idx):
        if self.op is None:
            return self.name
        if self.num_outputs() == 1:
            return self.name + "_output"
        return "%s_output%d" % (self.name, idx)


def _topo(heads):
    """Post-order DFS over the graph of the given head nodes."""
    order, seen = [], set()

    def visit(node):
        if id(node) in seen:
            return
        seen.add(id(node))
        for inp, _ in node.inputs:
            visit(inp)
        order.append(node)

    for node, _ in heads:
        visit(node)
    return order


class Symbol:
    """Immutable handle over one or more graph outputs."""
    __slots__ = ("_outputs",)

    def __init__(self, outputs):
        self._outputs = list(outputs)  # list[(SymNode, out_idx)]

    # -- construction ------------------------------------------------------
    @staticmethod
    def _from_op(op_name, input_syms, attrs, name=None):
        op = get_op(op_name)
        hint = HINTS.get(op_name, op_name.lower().replace("_", ""))
        name = _name_mod.current().get(name, hint)
        str_attrs = {k: v for k, v in attrs.items() if v is not None}
        inputs = []
        for s in input_syms:
            if len(s._outputs) != 1:
                raise MXNetError(
                    "cannot compose op %s with a multi-output symbol; "
                    "select one output first" % op_name)
            inputs.append(s._outputs[0])
        node = SymNode(op, name, str_attrs, inputs)
        n = node.num_outputs()
        return Symbol([(node, i) for i in range(n)])

    @property
    def name(self):
        if len(self._outputs) == 1:
            return self._outputs[0][0].name
        return None

    # -- listing -----------------------------------------------------------
    def _arg_nodes(self):
        return [n for n in _topo(self._outputs) if n.op is None and not n.is_aux]

    def _aux_nodes(self):
        return [n for n in _topo(self._outputs) if n.op is None and n.is_aux]

    def list_arguments(self):
        return [n.name for n in self._arg_nodes()]

    def list_auxiliary_states(self):
        return [n.name for n in self._aux_nodes()]

    def list_outputs(self):
        return [n.output_name(i) for n, i in self._outputs]

    def list_inputs(self):
        return self.list_arguments() + self.list_auxiliary_states()

    @property
    def num_outputs(self):
        return len(self._outputs)

    def __len__(self):
        return len(self._outputs)

    # -- selection ---------------------------------------------------------
    def __getitem__(self, index):
        if isinstance(index, str):
            matches = [i for i, (n, oi) in enumerate(self._outputs)
                       if n.output_name(oi) == index or n.name == index]
            if not matches:
                raise ValueError("no output named %r in %s"
                                 % (index, self.list_outputs()))
            index = matches[0]
        if isinstance(index, slice):
            return Symbol(self._outputs[index])
        return Symbol([self._outputs[index]])

    def get_internals(self):
        outs = []
        for node in _topo(self._outputs):
            if node.op is None:
                outs.append((node, 0))
            else:
                for i in range(node.num_outputs()):
                    outs.append((node, i))
        return Symbol(outs)

    def get_children(self):
        nodes = {id(n): n for n, _ in self._outputs}
        kids = []
        for n, _ in self._outputs:
            kids.extend(n.inputs)
        return Symbol(kids) if kids else None

    # -- attrs -------------------------------------------------------------
    def attr(self, key):
        node = self._outputs[0][0]
        v = node.attrs.get("__" + key + "__", node.attrs.get(key))
        return attr_to_string(v) if v is not None else None

    def list_attr(self):
        node = self._outputs[0][0]
        return {k.strip("_"): attr_to_string(v) for k, v in node.attrs.items()}

    def attr_dict(self):
        """Per-node attrs, keys as stored — special attrs KEEP their
        dunder form (``__init__``/``__lr_mult__``/...): that is what the
        initializer's variable-override and the optimizer's multiplier
        lookups key on (reference symbol.py attr_dict contract)."""
        out = {}
        for node in _topo(self._outputs):
            if node.attrs:
                out[node.name] = {k: attr_to_string(v)
                                  for k, v in node.attrs.items()}
        return out

    def _set_attr(self, **kwargs):
        for node, _ in self._outputs:
            node.attrs.update(kwargs)

    # -- arithmetic --------------------------------------------------------
    def _binary(self, op_name, scalar_op, other, reverse=False):
        if isinstance(other, Symbol):
            a, b = (other, self) if reverse else (self, other)
            return Symbol._from_op(op_name, [a, b], {})
        if isinstance(other, (int, float, np.generic)):
            return Symbol._from_op(scalar_op, [self], {"scalar": float(other)})
        raise TypeError("unsupported operand %r" % (type(other),))

    def __add__(self, o): return self._binary("elemwise_add", "_plus_scalar", o)
    def __radd__(self, o): return self._binary("elemwise_add", "_plus_scalar", o, True)
    def __sub__(self, o):
        return self._binary("elemwise_sub", "_minus_scalar", o)
    def __rsub__(self, o):
        if isinstance(o, Symbol):
            return o.__sub__(self)
        return Symbol._from_op("_rminus_scalar", [self], {"scalar": float(o)})
    def __mul__(self, o): return self._binary("elemwise_mul", "_mul_scalar", o)
    def __rmul__(self, o): return self._binary("elemwise_mul", "_mul_scalar", o, True)
    def __truediv__(self, o): return self._binary("elemwise_div", "_div_scalar", o)
    def __rtruediv__(self, o):
        if isinstance(o, Symbol):
            return o.__truediv__(self)
        return Symbol._from_op("_rdiv_scalar", [self], {"scalar": float(o)})
    __div__ = __truediv__
    __rdiv__ = __rtruediv__
    def __pow__(self, o): return self._binary("elemwise_power", "_power_scalar", o)
    def __neg__(self): return Symbol._from_op("negative", [self], {})
    def __eq__(self, o):
        if isinstance(o, (Symbol, int, float, np.generic)):
            return self._binary("_equal", "_equal_scalar", o)
        return NotImplemented
    def __ne__(self, o):
        if isinstance(o, (Symbol, int, float, np.generic)):
            return self._binary("_not_equal", "_not_equal_scalar", o)
        return NotImplemented
    def __gt__(self, o): return self._binary("_greater", "_greater_scalar", o)
    def __ge__(self, o): return self._binary("_greater_equal", "_greater_equal_scalar", o)
    def __lt__(self, o): return self._binary("_lesser", "_lesser_scalar", o)
    def __le__(self, o): return self._binary("_lesser_equal", "_lesser_equal_scalar", o)
    __hash__ = object.__hash__

    def __copy__(self):
        return Symbol(list(self._outputs))

    def __deepcopy__(self, memo):
        return load_json(self.tojson())

    # -- convenience methods mirroring NDArray ----------------------------
    def reshape(self, *shape, **kw):
        if len(shape) == 1 and isinstance(shape[0], (tuple, list)):
            shape = tuple(shape[0])
        shape = kw.get("shape", shape)
        return Symbol._from_op("Reshape", [self], {"shape": tuple(shape)})

    def astype(self, dtype):
        return Symbol._from_op("Cast", [self], {"dtype": str(dtype)})

    def sum(self, axis=None, keepdims=False):
        return Symbol._from_op("sum", [self], {"axis": axis, "keepdims": keepdims})

    def mean(self, axis=None, keepdims=False):
        return Symbol._from_op("mean", [self], {"axis": axis, "keepdims": keepdims})

    def transpose(self, axes=None):
        return Symbol._from_op("transpose", [self], {"axes": axes})

    def flatten(self):
        return Symbol._from_op("Flatten", [self], {})

    def slice_axis(self, axis, begin, end):
        return Symbol._from_op("slice_axis", [self],
                               {"axis": axis, "begin": begin, "end": end})

    def softmax(self, axis=-1):
        return Symbol._from_op("softmax", [self], {"axis": axis})

    def __repr__(self):
        outs = self.list_outputs()
        return "<Symbol %s>" % (self.name if len(outs) == 1 else outs)

    # -- inference ---------------------------------------------------------
    def _infer(self, shape_kwargs=None, dtype_kwargs=None, partial=False):
        """Joint shape+dtype inference via jax.eval_shape per node.

        Returns (arg_structs, out_structs, aux_structs) — each a list of
        jax.ShapeDtypeStruct or None (unknown).
        """
        shape_kwargs = dict(shape_kwargs or {})
        dtype_kwargs = dict(dtype_kwargs or {})
        nodes = _topo(self._outputs)
        vals = {}  # id(node) -> list[ShapeDtypeStruct|None]
        var_struct = {}

        def struct_of(node):
            shape = shape_kwargs.get(node.name)
            if shape is None and "__shape__" in node.attrs:
                shape = node.attrs["__shape__"]
            if isinstance(shape, (int, np.integer)):
                # files written before the 1-tuple stringify fix stored
                # "(64)" which parses back as a bare int
                shape = (int(shape),)
            dtype = dtype_kwargs.get(node.name)
            if dtype is None:
                dtype = node.attrs.get("__dtype__", np.float32)
            if shape is None:
                return None
            return jax.ShapeDtypeStruct(tuple(shape), dtype_np(dtype))

        for node in nodes:
            if node.op is None:
                s = struct_of(node)
                vals[id(node)] = [s]
                var_struct[id(node)] = s

        for node in nodes:
            if node.op is None:
                continue
            in_structs = [vals[id(n)][oi] for n, oi in node.inputs]
            if any(s is None for s in in_structs):
                # try param-shape inference from known inputs (simple_bind)
                inferred = infer_param_shapes(node, in_structs)
                if inferred is not None:
                    for pos, st in enumerate(inferred):
                        if st is not None and in_structs[pos] is None:
                            in_structs[pos] = st
                            src, soi = node.inputs[pos]
                            if src.op is None:
                                vals[id(src)][soi] = st
                                var_struct[id(src)] = st
            if any(s is None for s in in_structs):
                if partial:
                    vals[id(node)] = [None] * node.num_outputs()
                    continue
                missing = [node.inputs[i][0].name
                           for i, s in enumerate(in_structs) if s is None]
                raise MXNetError(
                    "cannot infer shape for inputs %s of node %s; provide "
                    "their shapes" % (missing, node.name))
            fn = node.op.traceable(node.attrs, train_mode=False,
                                   rng=_dummy_key())
            try:
                out = jax.eval_shape(lambda *a: fn(*a), *in_structs)
            except Exception as e:
                raise MXNetError(
                    "shape inference failed at node %s (op %s): %s"
                    % (node.name, node.op.name, e))
            out = list(out) if isinstance(out, (tuple, list)) else [out]
            vals[id(node)] = out[:node.num_outputs()] + out[node.num_outputs():]

        args = [var_struct.get(id(n)) for n in self._arg_nodes()]
        auxs = [var_struct.get(id(n)) for n in self._aux_nodes()]
        outs = []
        for n, oi in self._outputs:
            v = vals.get(id(n))
            outs.append(v[oi] if v else None)
        return args, outs, auxs

    def infer_shape(self, *args, **kwargs):
        if args:
            kwargs = dict(zip(self.list_arguments(), args), **kwargs)
        kwargs = {k: v for k, v in kwargs.items() if v is not None}
        a, o, x = self._infer(shape_kwargs=kwargs)
        if any(s is None for s in a + o + x):
            return None, None, None
        return ([tuple(s.shape) for s in a], [tuple(s.shape) for s in o],
                [tuple(s.shape) for s in x])

    def infer_shape_partial(self, *args, **kwargs):
        if args:
            kwargs = dict(zip(self.list_arguments(), args), **kwargs)
        kwargs = {k: v for k, v in kwargs.items() if v is not None}
        a, o, x = self._infer(shape_kwargs=kwargs, partial=True)
        f = lambda s: tuple(s.shape) if s is not None else None
        return [f(s) for s in a], [f(s) for s in o], [f(s) for s in x]

    def infer_type(self, *args, **kwargs):
        """Shape-free dtype propagation (reference: nnvm InferType pass).

        Forward-propagates known dtypes through homogeneous ops and
        back-fills unknown variable dtypes from their consumers (the rule
        that makes conv/fc weights inherit the data dtype).
        """
        if args:
            kwargs = dict(zip(self.list_arguments(), args), **kwargs)
        nodes = _topo(self._outputs)
        dt = {}  # id(node) -> np.dtype or None
        for n in nodes:
            if n.op is None:
                d = kwargs.get(n.name, n.attrs.get("__dtype__"))
                dt[id(n)] = np.dtype(d) if d is not None else None
        for _ in range(2):  # fwd then (after back-fill) fwd again
            for n in nodes:
                if n.op is None:
                    continue
                if "dtype" in n.attrs and n.attrs["dtype"] is not None:
                    dt[id(n)] = dtype_np(n.attrs["dtype"])
                    continue
                known = [dt.get(id(s)) for s, _ in n.inputs]
                known = [k for k in known if k is not None]
                dt[id(n)] = known[0] if known else dt.get(id(n))
            # back-fill: unknown var inputs inherit their consumer's dtype
            for n in nodes:
                if n.op is None or dt.get(id(n)) is None:
                    continue
                for s, _ in n.inputs:
                    if s.op is None and dt.get(id(s)) is None:
                        dt[id(s)] = dt[id(n)]

        f = lambda node: dt.get(id(node)) or np.dtype(np.float32)
        return ([f(n) for n in self._arg_nodes()],
                [f(n) for n, _ in self._outputs],
                [f(n) for n in self._aux_nodes()])

    # -- serialization -----------------------------------------------------
    def tojson(self):
        nodes = _topo(self._outputs)
        nid = {id(n): i for i, n in enumerate(nodes)}
        jnodes = []
        for n in nodes:
            jnodes.append({
                "op": "null" if n.op is None else n.op.name,
                "name": n.name,
                "attrs": {k: attr_to_string(v) for k, v in n.attrs.items()},
                "inputs": [[nid[id(s)], oi, 0] for s, oi in n.inputs],
            })
        graph = {
            "nodes": jnodes,
            "arg_nodes": [i for i, n in enumerate(nodes) if n.op is None],
            "heads": [[nid[id(n)], oi, 0] for n, oi in self._outputs],
            "attrs": {"mxnet_version": ["int", 1200],
                      "framework": ["str", "mxnet_tpu"]},
        }
        return json.dumps(graph, indent=2)

    def save(self, fname):
        from ..stream import open_stream
        with open_stream(fname, "w") as f:
            f.write(self.tojson())

    def debug_str(self):
        """Human-readable graph dump (reference ``Symbol.debug_str`` —
        one line per node in topological order with op, name, and input
        wiring; SURVEY §5.5 graph introspection)."""
        nodes = _topo(self._outputs)
        nid = {id(n): i for i, n in enumerate(nodes)}
        lines = ["Symbol Outputs:"]
        for pos, (n, oi) in enumerate(self._outputs):
            lines.append("\toutput[%d]=%s(%d)"
                         % (pos, n.output_name(oi), nid[id(n)]))
        for n in nodes:
            if n.op is None:
                lines.append("Variable:%s" % n.name)
                continue
            attrs = ", ".join("%s=%s" % (k, attr_to_string(v))
                              for k, v in sorted(n.attrs.items()))
            lines.append("--------------------")
            lines.append("Op:%s, Name=%s%s"
                         % (n.op.name, n.name,
                            (" {%s}" % attrs) if attrs else ""))
            for k, (s, oi) in enumerate(n.inputs):
                lines.append("\targ[%d]=%s(%d)"
                             % (k, s.output_name(oi), nid[id(s)]))
        return "\n".join(lines) + "\n"

    # -- binding (implemented in executor.py) ------------------------------
    def bind(self, ctx, args, args_grad=None, grad_req="write",
             aux_states=None, group2ctx=None, shared_exec=None):
        from ..executor import Executor
        return Executor._bind(self, ctx, args, args_grad, grad_req,
                              aux_states, group2ctx)

    def simple_bind(self, ctx, grad_req="write", type_dict=None,
                    stype_dict=None, group2ctx=None, shared_arg_names=None,
                    shared_exec=None, shared_buffer=None, **kwargs):
        from ..executor import Executor
        return Executor._simple_bind(self, ctx, grad_req, type_dict,
                                     group2ctx, kwargs)

    def eval(self, ctx=None, **kwargs):
        from ..context import current_context
        ex = self.bind(ctx or current_context(), kwargs)
        return ex.forward()

    # gradient symbol (reference Symbol.gradient is rarely used; omitted)


def var(name, attr=None, shape=None, lr_mult=None, wd_mult=None, dtype=None,
        init=None, stype=None, **kwargs):
    """Create a variable symbol (reference mx.sym.var / Variable)."""
    if not isinstance(name, str):
        raise TypeError("Expect a string for variable name")
    attrs = _attr_mod.current().get(attr)
    attrs = {k: v for k, v in (attrs or {}).items()}
    if shape is not None:
        attrs["__shape__"] = tuple(shape)
    if dtype is not None:
        attrs["__dtype__"] = str(np.dtype(dtype))
    if lr_mult is not None:
        attrs["__lr_mult__"] = lr_mult
    if wd_mult is not None:
        attrs["__wd_mult__"] = wd_mult
    if init is not None:
        from ..initializer import Initializer
        attrs["__init__"] = init.dumps() if isinstance(init, Initializer) else str(init)
    if stype is not None:
        attrs["__storage_type__"] = stype
    attrs.update({k: attr_to_string(v) for k, v in kwargs.items()})
    return Symbol([(SymNode(None, name, attrs, []), 0)])


Variable = var


def Group(symbols):
    outs = []
    for s in symbols:
        outs.extend(s._outputs)
    return Symbol(outs)


def load_json(json_str):
    graph = json.loads(json_str)
    graph = _upgrade_json(graph)
    nodes = []
    aux_hint = set()
    # first pass: find aux inputs by walking op input-name metadata
    for jn in graph["nodes"]:
        node = SymNode(None if jn["op"] == "null" else get_op(jn["op"]),
                       jn["name"],
                       {k: parse_attr_string(v)
                        for k, v in _node_attrs(jn).items()},
                       [])
        nodes.append(node)
    for jn, node in zip(graph["nodes"], nodes):
        node.inputs = [(nodes[i], oi) for i, oi, *_ in jn["inputs"]]
        if node.op is not None:
            _, aux_names = op_input_names(node.op, node.attrs)
            n_in = len(node.inputs)
            n_aux = len(aux_names)
            for (src, _), pos in zip(node.inputs, range(n_in)):
                if pos >= n_in - n_aux and src.op is None:
                    src.is_aux = True
    heads = [(nodes[i], oi) for i, oi, *_ in graph["heads"]]
    return Symbol(heads)


def _node_attrs(jn):
    """Node attr dict across JSON generations: modern ``attrs``, 0.9-era
    ``attr``, pre-0.9 ``param`` (reference legacy_json_util.cc upgrades the
    same progression in place)."""
    return jn.get("attrs") or jn.get("attr") or jn.get("param") or {}


def _upgrade_json(graph):
    """Upgrade legacy symbol JSON in place (reference
    src/nnvm/legacy_json_util.cc:1-200, UpgradeJSON_* chain).

    Handled: (a) node attrs under ``attr``/``param`` keys (rewritten to
    ``attrs``); (b) pre-0.9 graphs where op params lived on the *op node*
    but variable metadata (init/lr_mult) was stored flat — moved to
    ``__key__`` form; (c) dropped long-gone bookkeeping attrs the modern
    parser rejects (``ctx_group``-era keys are kept, unknown ``mojo``-era
    parse blockers are not fatal because attrs parse lazily here).
    """
    version = 0
    g_attrs = graph.get("attrs") or {}
    if isinstance(g_attrs.get("mxnet_version"), (list, tuple)) \
            and len(g_attrs["mxnet_version"]) == 2:
        version = int(g_attrs["mxnet_version"][1])
    for jn in graph.get("nodes", []):
        attrs = _node_attrs(jn)
        if jn.get("op") == "null":
            # legacy variable nodes store their metadata flat; the modern
            # node model namespaces it (__shape__/__dtype__/... is what
            # _infer and the optimizer multiplier lookups read)
            for key in ("init", "lr_mult", "wd_mult", "dtype", "shape"):
                if key in attrs:
                    attrs["__%s__" % key] = attrs.pop(key)
        elif version < 900:
            # pre-0.9: *variable* metadata could be stranded on the
            # consuming op node — namespace it out of the op's kwargs
            # (reference UpgradeJSON_FixParsing:56-86). dtype/shape stay:
            # on an op node those are real parameters (e.g. Cast(dtype)).
            for key in ("init", "lr_mult", "wd_mult"):
                if key in attrs:
                    attrs["__%s__" % key] = attrs.pop(key)
        jn.pop("param", None)
        jn.pop("attr", None)
        jn["attrs"] = attrs
    return graph


def load(fname):
    from ..stream import open_stream
    with open_stream(fname, "r") as f:
        return load_json(f.read())


def _dummy_key():
    return jax.random.PRNGKey(0)


# --- creation symbols -------------------------------------------------------
def zeros(shape, dtype=None, **kwargs):
    return Symbol._from_op("_zeros", [],
                           {"shape": shape, "dtype": str(dtype or "float32")})


def ones(shape, dtype=None, **kwargs):
    return Symbol._from_op("_ones", [],
                           {"shape": shape, "dtype": str(dtype or "float32")})


def arange(start, stop=None, step=1.0, repeat=1, dtype=None, **kwargs):
    return Symbol._from_op("_arange", [],
                           {"start": start, "stop": stop, "step": step,
                            "repeat": repeat, "dtype": str(dtype or "float32")})
