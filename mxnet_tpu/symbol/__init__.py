"""``mx.sym`` namespace: Symbol + generated symbolic op functions.

Reference analogue: ``python/mxnet/symbol/`` generated op modules.  Symbolic
op functions accept Symbols positionally or by input-name kwargs
(``sym.Convolution(data=d, weight=w, ...)``) and auto-create variable nodes
for omitted parameter inputs — the behavior Module/simple_bind rely on.
"""
from __future__ import annotations

import sys
import types

from .symbol import (Symbol, SymNode, var, Variable, Group, load, load_json,
                     zeros, ones, arange)
from .op_meta import op_input_names, HINTS
from ..ops.registry import OP_REGISTRY
from .. import name as _name_mod


def _make_sym_func(name, op):
    def sym_func(*args, **kwargs):
        attr = kwargs.pop("attr", None)
        sym_name = kwargs.pop("name", None)
        # split symbol kwargs from attr kwargs
        sym_kwargs = {}
        for k in list(kwargs):
            if isinstance(kwargs[k], Symbol):
                sym_kwargs[k] = kwargs.pop(k)
        pos_syms = []
        rest_args = []
        for a in args:
            if isinstance(a, Symbol):
                pos_syms.append(a)
            elif isinstance(a, (list, tuple)) and a and isinstance(a[0], Symbol):
                pos_syms.extend(a)
            else:
                rest_args.append(a)
        if rest_args:
            raise TypeError("op %s: non-Symbol positional args not allowed; "
                            "pass attrs as keywords" % name)
        in_names, aux_names = op_input_names(op, kwargs)
        if name in ("Concat", "add_n", "stack", "elemwise_sum",
                    "ElementWiseSum", "UpSampling") and pos_syms:
            kwargs.setdefault("num_args", len(pos_syms))
            in_names = ["arg%d" % i for i in range(len(pos_syms))]
        all_names = in_names + aux_names
        # assemble inputs: positional fill first, then kwargs by name,
        # then auto-created variables
        hint = HINTS.get(name, name.lower().strip("_"))
        node_name = _name_mod.current().get(sym_name, hint)
        inputs = []
        pos_iter = iter(pos_syms)
        from .symbol import var as _var
        for i, iname in enumerate(all_names):
            if iname in sym_kwargs:
                inputs.append(sym_kwargs.pop(iname))
                continue
            s = next(pos_iter, None)
            if s is not None:
                inputs.append(s)
                continue
            # auto-create variable (aux flagged)
            v = _var("%s_%s" % (node_name, iname))
            if iname in aux_names:
                v._outputs[0][0].is_aux = True
            inputs.append(v)
        leftovers = list(pos_iter)
        if leftovers:
            inputs.extend(leftovers)
        if sym_kwargs:
            raise TypeError("op %s got unexpected symbol kwargs %s (inputs "
                            "are %s)" % (name, list(sym_kwargs), all_names))
        if attr:
            kwargs.update({"__%s__" % k: v for k, v in attr.items()})
        # mark trailing aux inputs via is_aux on their variable nodes
        for iname, s in zip(all_names, inputs):
            if iname in aux_names and s._outputs[0][0].op is None:
                s._outputs[0][0].is_aux = True
        return Symbol._from_op(name, inputs, kwargs, name=node_name)
    sym_func.__name__ = name
    return sym_func


_internal = types.ModuleType(__name__ + "._internal")
_this = sys.modules[__name__]
for _name, _op in OP_REGISTRY.items():
    _fn = _make_sym_func(_name, _op)
    setattr(_internal, _name, _fn)
    if not _name.startswith("_"):
        if not hasattr(_this, _name):
            setattr(_this, _name, _fn)
sys.modules[__name__ + "._internal"] = _internal

# mx.sym.contrib namespace: _contrib_* ops under their stripped names
contrib = types.ModuleType(__name__ + ".contrib")
for _name, _op in OP_REGISTRY.items():
    if _name.startswith("_contrib_"):
        setattr(contrib, _name[len("_contrib_"):],
                _make_sym_func(_name, _op))
sys.modules[__name__ + ".contrib"] = contrib

from . import random  # noqa: E402,F401

__all__ = ["Symbol", "var", "Variable", "Group", "load", "load_json",
           "zeros", "ones", "arange"]
