"""mxnet_tpu: a TPU-native framework with the capabilities of MXNet.

A ground-up JAX/XLA/Pallas re-design of the capability surface of Apache
MXNet v0.12 (reference: jermainewang/mxnet; see SURVEY.md at repo root for
the inventory this build targets).  Eager NDArray + autograd tape on one
side, Symbol/Executor compiling whole graphs to single XLA programs on the
other — the same dual paradigm ("mix symbolic and imperative") the reference
is built around, mapped onto jax eager vs jax.jit.
"""
from __future__ import annotations

__version__ = "0.1.0"

import os as _os

import jax as _jax
# MXNet supports float64/int64 tensors; jax defaults to 32-bit only.
_jax.config.update("jax_enable_x64", True)
# Mirror an env-pinned platform list into jax.config: plugin
# sitecustomize hooks (e.g. a tunneled TPU runtime) can otherwise race
# the env var and hang the first backend touch of a plain
# `JAX_PLATFORMS=cpu python script.py` run.
if _os.environ.get("JAX_PLATFORMS", "") not in ("", "axon"):
    _jax.config.update("jax_platforms", _os.environ["JAX_PLATFORMS"])

from .base import MXNetError
from .context import Context, cpu, gpu, tpu, cpu_pinned, current_context, num_gpus
from . import base
from . import engine
from . import random
from .random import seed
from . import ndarray
from . import ndarray as nd
from . import autograd
from . import attribute
from .attribute import AttrScope
from . import name
from .name import NameManager
from . import symbol
from . import symbol as sym
from .symbol import Symbol
from .executor import Executor
from . import initializer
from .initializer import init
from . import optimizer
from . import optimizer as opt
from . import metric
from . import operator
from . import pallas
from . import stream
from . import rnn
from . import contrib
from . import torch
from . import predict
from .predict import Predictor
from . import lr_scheduler
from . import callback
from . import io
from . import kvstore as kv
from . import kvstore
from . import model
from . import module
from . import module as mod
from .model import FeedForward
from . import recordio
from . import image
from . import gluon
from . import parallel
from . import checkpoint
# models, test_utils, and serving are opt-in imports (mxnet_tpu.models /
# mxnet_tpu.test_utils / mxnet_tpu.serving), keeping `import mxnet_tpu`
# lean like the reference; the serving tier (AOT predict programs +
# continuous batching, docs/SERVING.md) spins up threads and compiles
# programs, so it only loads when a process opts into being a server.
from . import telemetry
from . import profiler
from . import monitor
from .monitor import Monitor
from . import visualization
from . import visualization as viz
from . import log
