"""Multi-process distributed KVStore transport (scheduler / server / worker).

Reference counterpart: ps-lite + ``src/kvstore/kvstore_dist.h`` (worker,
ZPush/ZPull with big-array key sharding) and ``kvstore_dist_server.h``
(sync aggregation + ApplyUpdates), launched by ``tools/launch.py`` via the
dmlc tracker.  This rebuild keeps the *roles and semantics* — a scheduler
for rendezvous/barrier, S servers holding key shards, N workers pushing
gradients and pulling weights, sync mode aggregating all workers' pushes
before one optimizer step — over a dependency-free length-prefixed-pickle
TCP protocol instead of ZeroMQ.

On real multi-host TPU pods the training hot path does not go through this
transport at all: it is `pjit` + ``lax.psum`` over ICI/DCN (see
``parallel/sharded.py``).  This module exists so the reference's dist
kvstore API (``create('dist_sync')``, rank/num_workers/barrier,
optimizer-on-server) is a working, testable surface — the nightly
dist-invariant tests run against it with real local processes, the same
way the reference runs ps-lite over localhost.

Role selection uses the reference's env-var contract
(``DMLC_ROLE``, ``DMLC_PS_ROOT_URI``, ``DMLC_PS_ROOT_PORT``,
``DMLC_NUM_WORKER``, ``DMLC_NUM_SERVER``), so launch scripts written for
the reference port unchanged.
"""
from __future__ import annotations

import os
import pickle
import socket
import struct
import threading

import numpy as np

__all__ = ["role", "num_workers", "num_servers", "root_addr",
           "Conn", "ProtocolError", "Scheduler", "Server",
           "WorkerTransport", "run_scheduler", "run_server",
           "shard_ranges", "server_of_key", "BIGARRAY_BOUND"]

# Wire frame: magic + protocol version + payload length. The magic word
# rejects stray/rogue connections before any payload is parsed; the
# version word makes cross-version jobs fail loudly instead of
# corrupting state mid-training.
_MAGIC = b"MXPS"
_WIRE_VERSION = 1
_HDR = struct.Struct("<4sHQ")
_MAX_FRAME = 1 << 34          # 16 GiB: above any realistic shard


class ProtocolError(ConnectionError):
    """Peer spoke garbage: wrong magic/version, oversized frame, or a
    pickle payload outside the allowlist."""


# Payloads are numpy arrays + plain containers + framework classes
# (set_optimizer ships an mxnet_tpu.optimizer instance). Everything
# else — os.system et al. — is refused at find_class time, so one
# malformed/malicious peer cannot execute code in a training job.
_SAFE_BUILTINS = frozenset({
    "dict", "list", "tuple", "set", "frozenset", "str", "int", "float",
    "bool", "bytes", "bytearray", "complex", "slice", "range",
})


class _RestrictedUnpickler(pickle.Unpickler):
    def find_class(self, module, name):
        root = module.split(".", 1)[0]
        if root in ("numpy", "mxnet_tpu"):
            return super().find_class(module, name)
        if module == "builtins" and name in _SAFE_BUILTINS:
            return super().find_class(module, name)
        raise pickle.UnpicklingError(
            "disallowed pickle global %s.%s" % (module, name))


def _restricted_loads(blob):
    import io
    return _RestrictedUnpickler(io.BytesIO(blob)).load()


def BIGARRAY_BOUND():
    """Elements above which a key is range-sharded across all servers
    (reference: MXNET_KVSTORE_BIGARRAY_BOUND, kvstore_dist.h:60)."""
    # deliberate re-read: dist tests retune the bound between phases
    # graftlint: disable=JG006
    return int(os.environ.get("MXNET_KVSTORE_BIGARRAY_BOUND", 1 << 20))


def role():
    return os.environ.get("DMLC_ROLE", "worker")


def num_workers():
    return int(os.environ.get("DMLC_NUM_WORKER", 1))


def num_servers():
    return int(os.environ.get("DMLC_NUM_SERVER", 1))


def root_addr():
    return (os.environ.get("DMLC_PS_ROOT_URI", "127.0.0.1"),
            int(os.environ.get("DMLC_PS_ROOT_PORT", 9091)))


class Conn:
    """Blocking message channel: (magic, version, length) header +
    allowlist-restricted pickle payload."""

    def __init__(self, sock):
        self.sock = sock
        self._wlock = threading.Lock()

    @classmethod
    def connect(cls, addr, retries=100, delay=0.1):
        import time
        last = None
        for _ in range(retries):
            try:
                s = socket.create_connection(addr, timeout=60)
                s.settimeout(None)
                s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
                return cls(s)
            except OSError as exc:
                last = exc
                time.sleep(delay)
        raise ConnectionError("cannot reach %s:%d: %s" % (addr[0], addr[1], last))

    def send(self, msg):
        blob = pickle.dumps(msg, protocol=pickle.HIGHEST_PROTOCOL)
        with self._wlock:
            self.sock.sendall(
                _HDR.pack(_MAGIC, _WIRE_VERSION, len(blob)) + blob)

    def recv(self):
        magic, ver, n = _HDR.unpack(self._read(_HDR.size))
        if magic != _MAGIC:
            raise ProtocolError("bad frame magic %r" % (magic,))
        if ver != _WIRE_VERSION:
            raise ProtocolError(
                "peer speaks wire version %d, this process speaks %d"
                % (ver, _WIRE_VERSION))
        if n > _MAX_FRAME:
            raise ProtocolError("frame of %d bytes exceeds limit" % n)
        try:
            return _restricted_loads(self._read(n))
        except pickle.UnpicklingError as exc:
            raise ProtocolError(str(exc))
        except Exception as exc:   # truncated/garbage pickle bytes
            raise ProtocolError("undecodable payload: %r" % (exc,))

    def _read(self, n):
        buf = bytearray()
        while len(buf) < n:
            chunk = self.sock.recv(n - len(buf))
            if not chunk:
                raise ConnectionError("peer closed")
            buf.extend(chunk)
        return bytes(buf)

    def close(self):
        try:
            self.sock.close()
        except OSError:
            pass


# ---------------------------------------------------------------------------
# key → server placement
# ---------------------------------------------------------------------------

def _key_hash(key):
    """Stable across processes (unlike hash() under PYTHONHASHSEED)."""
    import zlib
    return zlib.adler32(str(key).encode())


def server_of_key(key, nserv):
    return _key_hash(key) % nserv


def shard_ranges(size, nserv):
    """Split [0, size) into nserv contiguous ranges (big-array mode)."""
    step = -(-size // nserv)
    return [(i * step, min((i + 1) * step, size)) for i in range(nserv)
            if i * step < size]


def placement(key, shape, nserv):
    """Return [(server_idx, (lo, hi))] over the *flattened* array.

    Small keys live whole on one server; arrays over BIGARRAY_BOUND are
    range-partitioned across every server so no single server bottlenecks
    on the fat embedding/fc weights (reference kvstore_dist.h:253-313).
    """
    size = int(np.prod(shape)) if shape else 1
    if size < BIGARRAY_BOUND() or nserv == 1:
        return [(server_of_key(key, nserv), (0, size))]
    return list(enumerate(shard_ranges(size, nserv)))


# ---------------------------------------------------------------------------
# Scheduler: rendezvous + barrier + shutdown fan-out
# ---------------------------------------------------------------------------

class Scheduler:
    """Assigns ranks, publishes the server address list, serves barriers.

    Lifecycle: all S servers and N workers connect and register; the
    scheduler replies with (rank, server_addrs).  Workers keep the
    connection for barrier()/finalize; when every worker has finalized,
    servers are told to shut down and the scheduler exits.
    """

    def __init__(self, nworkers, nservers, port=None):
        self.nworkers, self.nservers = nworkers, nservers
        self.lsock = socket.socket()
        self.lsock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self.lsock.bind(("", port or root_addr()[1]))
        self.lsock.listen(128)
        self.server_addrs = [None] * nservers
        self.server_conns = []
        self.worker_conns = {}
        self._lock = threading.Lock()
        self._registered = threading.Condition(self._lock)
        self._barrier_waiters = []
        self._barrier_gen = 0
        self._finalized = 0
        self._finalized_ranks = set()
        self.dead_workers = set()
        self._done = threading.Event()

    def run(self):
        # Accept until shutdown rather than counting to N connections: a
        # malformed/rogue connection must not consume a registration slot
        # and hang the whole job (it is dropped in _serve instead).
        self.lsock.settimeout(0.25)
        while not self._done.is_set():
            try:
                sock, _ = self.lsock.accept()
            except socket.timeout:
                continue
            except OSError:
                break
            threading.Thread(target=self._serve, args=(Conn(sock),),
                             daemon=True).start()
        for c in self.server_conns:
            try:
                c.send(("shutdown",))
            except (OSError, ConnectionError):
                pass
        self.lsock.close()

    def _serve(self, conn):
        try:
            msg = conn.recv()
            kind = msg[0]
            if kind not in ("reg_server", "reg_worker"):
                raise ProtocolError("first message must register a role")
        except (ConnectionError, TypeError, IndexError, KeyError):
            conn.close()   # rogue peer: drop without consuming a slot
            return
        with self._lock:
            if kind == "reg_server":
                rank = sum(a is not None for a in self.server_addrs)
                if rank >= self.nservers:
                    conn.close()   # over-registration
                    return
                self.server_addrs[rank] = msg[1]
                self.server_conns.append(conn)
            else:
                # honor the launcher's DMLC_WORKER_RANK when present so
                # worker i deterministically gets rank i
                hint = msg[1] if len(msg) > 1 else None
                if isinstance(hint, int) and 0 <= hint < self.nworkers \
                        and hint not in self.worker_conns:
                    rank = hint
                else:
                    try:
                        rank = next(i for i in range(self.nworkers)
                                    if i not in self.worker_conns)
                    except StopIteration:
                        conn.close()   # over-registration
                        return
                self.worker_conns[rank] = conn
            self._registered.notify_all()
            while (None in self.server_addrs
                   or len(self.worker_conns) < self.nworkers):
                self._registered.wait()
        conn.send(("ranked", rank, list(self.server_addrs)))
        if kind == "reg_server":
            return  # servers only hear "shutdown" from us
        while True:
            try:
                msg = conn.recv()
            except ConnectionError:
                # liveness surface (ref kvstore.h:328 get_num_dead_node):
                # a worker whose control connection dropped without
                # finalizing counts as dead
                with self._lock:
                    if rank in self.worker_conns \
                            and self.worker_conns[rank] is conn \
                            and rank not in getattr(self, "_finalized_ranks",
                                                    set()):
                        self.dead_workers.add(rank)
                break
            if msg[0] == "num_dead":
                with self._lock:
                    conn.send(("num_dead", len(self.dead_workers)))
                continue
            if msg[0] == "barrier":
                with self._lock:
                    gen = self._barrier_gen
                    self._barrier_waiters.append(conn)
                    if len(self._barrier_waiters) == self.nworkers:
                        for c in self._barrier_waiters:
                            c.send(("barrier_done",))
                        self._barrier_waiters = []
                        self._barrier_gen += 1
                        self._registered.notify_all()
                    else:
                        while self._barrier_gen == gen:
                            self._registered.wait()
                continue
            if msg[0] == "finalize":
                with self._lock:
                    if not hasattr(self, "_finalized_ranks"):
                        self._finalized_ranks = set()
                    self._finalized_ranks.add(rank)
                    self._finalized += 1
                    if self._finalized == self.nworkers:
                        self._done.set()
                conn.send(("bye",))
                break


# ---------------------------------------------------------------------------
# Server: shard store + sync aggregation + optimizer-on-server
# ---------------------------------------------------------------------------

class _PendingAgg:
    """Sync-mode merge buffer for one (key, timestamp)."""

    __slots__ = ("acc", "count", "rows")

    def __init__(self):
        self.acc = None
        self.count = 0
        self.rows = None  # row_sparse: set of pushed row ids


class Server:
    """Holds flat float shards; aggregates sync pushes; runs the updater.

    Push protocol (sync): each worker's push RPC blocks until all
    ``num_workers`` contributions for that (key, timestamp) have arrived
    and the update has been applied — this is the ordering guarantee the
    reference gets from engine dependencies + per-key server counters
    (kvstore_dist_server.h:164-210).
    """

    def __init__(self, nworkers):
        self.nworkers = nworkers
        self.store = {}        # key -> flat np array (this server's shard)
        self.shapes = {}       # key -> full shape (for updater reshape)
        self.ranges = {}       # key -> (lo, hi) of our shard
        self.pending = {}      # (key, ts) -> _PendingAgg
        self.updater = None
        self.sync = True
        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)

    def handle(self, msg):
        """Process one request; return the reply (or None)."""
        op = msg[0]
        if op == "init":
            _, key, flat, shape, rng = msg
            with self._lock:
                if key not in self.store:
                    self.store[key] = np.array(flat)
                    self.shapes[key] = tuple(shape)
                    self.ranges[key] = rng
                self._cv.notify_all()
            return ("ok",)
        if op == "push":
            return self._push(*msg[1:])
        if op == "pull":
            _, key = msg
            with self._lock:
                self._wait_key(key)
                return ("val", self.store[key])
        if op == "pull_rows":
            _, key, rows = msg
            with self._lock:
                self._wait_key(key)
                w = self.store[key].reshape(self.shapes[key])
                return ("val", w[np.asarray(rows, np.int64)])
        if op == "set_optimizer":
            from . import optimizer as opt
            optimizer = _restricted_loads(msg[1])
            with self._lock:
                self.updater = opt.get_updater(optimizer)
            return ("ok",)
        if op == "set_sync":
            with self._lock:
                self.sync = bool(msg[1])
            return ("ok",)
        raise ValueError("bad server op %r" % (op,))

    def _wait_key(self, key):
        while key not in self.store:
            self._cv.wait()

    def _push(self, key, ts, flat, rows):
        """flat: contribution to our shard (dense) or row-block (sparse)."""
        with self._lock:
            self._wait_key(key)
            if not self.sync:
                self._apply(key, np.array(flat), rows)
                return ("ok",)
            pend = self.pending.setdefault((key, ts), _PendingAgg())
            if rows is None:
                pend.acc = flat if pend.acc is None else pend.acc + flat
            else:
                # row-sparse: accumulate into a dense scratch of our shard
                if pend.acc is None:
                    pend.acc = np.zeros_like(self.store[key])
                w = pend.acc.reshape(self.shapes[key])
                w[np.asarray(rows, np.int64)] += flat
            pend.count += 1
            if pend.count == self.nworkers:
                self._apply(key, pend.acc, None)
                del self.pending[(key, ts)]
                self._cv.notify_all()
            else:
                while (key, ts) in self.pending:
                    self._cv.wait()
        return ("ok",)

    def _apply(self, key, agg, rows):
        """Aggregated gradient → updater (or overwrite, matching the
        reference server's no-updater CopyFromTo path)."""
        if rows is not None:  # async sparse push
            dense = np.zeros_like(self.store[key])
            dense.reshape(self.shapes[key])[np.asarray(rows, np.int64)] = agg
            agg = dense
        if self.updater is None:
            self.store[key] = np.asarray(agg, self.store[key].dtype).ravel()
            return
        from . import ndarray as _nd
        shape = self.shapes[key]
        lo, hi = self.ranges[key]
        full = lo == 0 and hi == int(np.prod(shape))
        wshape = shape if full else (hi - lo,)
        w = _nd.array(self.store[key].reshape(wshape), ctx=_cpu())
        g = _nd.array(np.asarray(agg, self.store[key].dtype).reshape(wshape),
                      ctx=_cpu())
        self.updater(_int_key(key), g, w)
        self.store[key] = w.asnumpy().astype(self.store[key].dtype).ravel()

    def serve_forever(self, lsock, stop):
        while not stop.is_set():
            try:
                lsock.settimeout(0.25)
                sock, _ = lsock.accept()
            except socket.timeout:
                continue
            except OSError:
                break
            conn = Conn(sock)
            threading.Thread(target=self._serve_conn, args=(conn,),
                             daemon=True).start()

    def _serve_conn(self, conn):
        while True:
            try:
                msg = conn.recv()
            except ConnectionError:
                return
            try:
                reply = self.handle(msg)
            except Exception:  # surface server bugs to the worker instead
                import traceback  # of hanging its blocking recv()
                reply = ("err", traceback.format_exc())
                with self._lock:      # unblock peers waiting on this key
                    self._cv.notify_all()
            if reply is not None:
                conn.send(reply)


def _cpu():
    from .context import cpu
    return cpu()


def _int_key(k):
    try:
        return int(k)
    except (TypeError, ValueError):
        return k


# ---------------------------------------------------------------------------
# role mains
# ---------------------------------------------------------------------------

def run_scheduler():
    Scheduler(num_workers(), num_servers()).run()


def run_server():
    lsock = socket.socket()
    lsock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    lsock.bind(("", 0))
    lsock.listen(128)
    my_addr = ("127.0.0.1", lsock.getsockname()[1])

    server = Server(num_workers())
    stop = threading.Event()
    t = threading.Thread(target=server.serve_forever, args=(lsock, stop),
                         daemon=True)
    t.start()

    sched = Conn.connect(root_addr())
    sched.send(("reg_server", my_addr))
    sched.recv()  # ("ranked", rank, addrs)
    # block until scheduler says shutdown
    try:
        msg = sched.recv()
    except ConnectionError:
        msg = ("shutdown",)
    assert msg[0] == "shutdown"
    stop.set()
    lsock.close()


def _check(reply):
    """Re-raise server-side failures shipped back as ('err', traceback)."""
    if isinstance(reply, tuple) and reply and reply[0] == "err":
        raise RuntimeError("kvstore server error:\n" + reply[1])
    return reply


class WorkerTransport:
    """Worker-side connections: one to the scheduler, one per server."""

    def __init__(self):
        self.sched = Conn.connect(root_addr())
        rank_hint = (os.environ.get("DMLC_WORKER_RANK")
                     or os.environ.get("OMPI_COMM_WORLD_RANK")
                     or os.environ.get("PMI_RANK"))
        self.sched.send(("reg_worker",
                         int(rank_hint) if rank_hint is not None else None))
        msg = self.sched.recv()
        assert msg[0] == "ranked"
        self.rank = msg[1]
        self.server_conns = [Conn.connect(tuple(a)) for a in msg[2]]
        self.nservers = len(self.server_conns)
        self._ts = {}     # key -> push timestamp counter
        self._lock = threading.Lock()

    # -- scheduler ops ------------------------------------------------------
    def barrier(self):
        self.sched.send(("barrier",))
        msg = self.sched.recv()
        assert msg[0] == "barrier_done"

    def num_dead_nodes(self):
        """Workers whose control link dropped without finalizing
        (ref kvstore.h:328 get_num_dead_node)."""
        self.sched.send(("num_dead",))
        msg = self.sched.recv()
        assert msg[0] == "num_dead"
        return int(msg[1])

    def finalize(self):
        try:
            self.sched.send(("finalize",))
            self.sched.recv()
        except (OSError, ConnectionError):
            pass
        for c in self.server_conns:
            c.close()
        self.sched.close()

    # -- kv ops -------------------------------------------------------------
    def init(self, key, arr):
        flat = np.asarray(arr).ravel()
        for sidx, (lo, hi) in placement(key, arr.shape, self.nservers):
            c = self.server_conns[sidx]
            c.send(("init", key, flat[lo:hi], arr.shape, (lo, hi)))
            _check(c.recv())

    def push(self, key, arr, rows=None):
        with self._lock:
            ts = self._ts[key] = self._ts.get(key, -1) + 1
        if rows is not None:
            sidx = server_of_key(key, self.nservers)
            c = self.server_conns[sidx]
            c.send(("push", key, ts, np.asarray(arr), np.asarray(rows)))
            _check(c.recv())
            return
        flat = np.asarray(arr).ravel()
        plc = placement(key, arr.shape, self.nservers)
        for sidx, (lo, hi) in plc:
            self.server_conns[sidx].send(("push", key, ts, flat[lo:hi], None))
        for sidx, _ in plc:
            _check(self.server_conns[sidx].recv())

    def pull(self, key, shape):
        plc = placement(key, shape, self.nservers)
        for sidx, _ in plc:
            self.server_conns[sidx].send(("pull", key))
        shards = [_check(self.server_conns[sidx].recv()) for sidx, _ in plc]
        out = np.empty(int(np.prod(shape)), shards[0][1].dtype)
        for (_, (lo, hi)), (tag, val) in zip(plc, shards):
            assert tag == "val"
            out[lo:hi] = val
        return out.reshape(shape)

    def pull_rows(self, key, shape, rows):
        sidx = server_of_key(key, self.nservers)
        c = self.server_conns[sidx]
        c.send(("pull_rows", key, np.asarray(rows, np.int64)))
        tag, val = _check(c.recv())
        assert tag == "val"
        return val

    def set_optimizer(self, optimizer):
        blob = pickle.dumps(optimizer, protocol=pickle.HIGHEST_PROTOCOL)
        for c in self.server_conns:
            c.send(("set_optimizer", blob))
        for c in self.server_conns:
            _check(c.recv())

    def set_sync(self, sync):
        for c in self.server_conns:
            c.send(("set_sync", sync))
        for c in self.server_conns:
            _check(c.recv())
