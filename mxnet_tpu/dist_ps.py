"""Multi-process distributed KVStore transport (scheduler / server / worker).

Reference counterpart: ps-lite + ``src/kvstore/kvstore_dist.h`` (worker,
ZPush/ZPull with big-array key sharding) and ``kvstore_dist_server.h``
(sync aggregation + ApplyUpdates), launched by ``tools/launch.py`` via the
dmlc tracker.  This rebuild keeps the *roles and semantics* — a scheduler
for rendezvous/barrier, S servers holding key shards, N workers pushing
gradients and pulling weights, sync mode aggregating all workers' pushes
before one optimizer step — over a dependency-free length-prefixed-pickle
TCP protocol instead of ZeroMQ.

On real multi-host TPU pods the training hot path does not go through this
transport at all: it is `pjit` + ``lax.psum`` over ICI/DCN (see
``parallel/sharded.py``).  This module exists so the reference's dist
kvstore API (``create('dist_sync')``, rank/num_workers/barrier,
optimizer-on-server) is a working, testable surface — the nightly
dist-invariant tests run against it with real local processes, the same
way the reference runs ps-lite over localhost.

Failure doctrine (docs/FAULT_TOLERANCE.md): a dead or silent peer must
surface as a structured :class:`PeerLost` within a bounded time, never as
a hang.  Every worker-side RPC recv carries a deadline
(``MXNET_PS_RPC_TIMEOUT_S``); idempotent RPCs (pull, rendezvous reads,
state snapshots) retry on a *fresh* connection with exponential backoff +
jitter; scheduler↔server/worker heartbeats feed dead-peer detection and
the introspection server's ``/peers`` view; and a worker can
:meth:`~WorkerTransport.refresh_servers` onto a restarted server whose
shard state is restored through the checkpoint-state protocol
(``get_state``/``set_state`` — the PR-7 ``kvstore`` analogue).  The
:mod:`mxnet_tpu.chaos` tier injects faults at ``Conn`` send/recv to prove
all of this under test.

Role selection uses the reference's env-var contract
(``DMLC_ROLE``, ``DMLC_PS_ROOT_URI``, ``DMLC_PS_ROOT_PORT``,
``DMLC_NUM_WORKER``, ``DMLC_NUM_SERVER``), so launch scripts written for
the reference port unchanged.
"""
from __future__ import annotations

import os
import pickle
import socket
import struct
import threading
import time
import weakref
from random import Random as _JitterRandom

import numpy as np

from . import chaos as _chaos
from .lint import lockwitness as _lockwitness
from .telemetry import core as _tel
from .telemetry import flight as _flight

__all__ = ["role", "num_workers", "num_servers", "root_addr",
           "Conn", "RpcListener", "ProtocolError", "PeerLost",
           "RPCTimeout",
           "Scheduler", "Server", "WorkerTransport",
           "run_scheduler", "run_server", "shard_ranges", "server_of_key",
           "BIGARRAY_BOUND", "peer_view", "fleet_view",
           "clock_offset_us", "dump_trace_artifacts", "refresh_gauges",
           "refresh_from_env"]

# Wire frame: magic + protocol version + payload length. The magic word
# rejects stray/rogue connections before any payload is parsed; the
# version word makes cross-version jobs fail loudly instead of
# corrupting state mid-training.
_MAGIC = b"MXPS"
_WIRE_VERSION = 1
_HDR = struct.Struct("<4sHQ")
_MAX_FRAME = 1 << 34          # 16 GiB: above any realistic shard


class ProtocolError(ConnectionError):
    """Peer spoke garbage: wrong magic/version, oversized frame, or a
    pickle payload outside the allowlist."""


class PeerLost(ConnectionError):
    """A dist peer died or went silent: the structured, catchable form
    of every transport failure — callers recover (reconnect/restore) or
    re-raise, but they never hang."""

    def __init__(self, message, role=None, rank=None, addr=None,
                 reason=None):
        super().__init__(message)
        self.role = role
        self.rank = rank
        self.addr = addr
        self.reason = reason


class RPCTimeout(PeerLost):
    """No (complete) reply within the RPC deadline."""

    def __init__(self, message, **kw):
        kw.setdefault("reason", "rpc-timeout")
        super().__init__(message, **kw)


# ---------------------------------------------------------------------------
# env knobs — cached at import (JG006 cached-value pattern; these sit on
# the push/pull hot path).  refresh_from_env() re-reads for tests.
# ---------------------------------------------------------------------------

def _env_float(name, default, minimum=0.0):
    try:
        return max(minimum, float(os.environ.get(name, default)))
    except ValueError:
        return default


def _env_int(name, default, minimum=0):
    try:
        return max(minimum, int(os.environ.get(name, default)))
    except ValueError:
        return default


def _read_env():
    timeout = _env_float("MXNET_PS_RPC_TIMEOUT_S", 60.0)
    heartbeat = _env_float("MXNET_PS_HEARTBEAT_S", 2.0)
    return {
        # 0 = unbounded (None): the pre-hardening behavior, opt-in only
        "rpc_timeout": timeout if timeout > 0 else None,
        "rpc_retries": _env_int("MXNET_PS_RPC_RETRIES", 3, minimum=1),
        "connect_retries": _env_int("MXNET_PS_CONNECT_RETRIES", 100,
                                    minimum=1),
        "connect_delay": _env_float("MXNET_PS_CONNECT_DELAY_S", 0.1),
        "heartbeat": heartbeat,
        # staleness is the LAST-resort tripwire (a truly silent peer on
        # a live socket); disconnects detect a dead process instantly.
        # Keep the window generous so CPU-starved-but-alive peers (cold
        # jax compiles, loaded CI hosts) are never falsely buried.
        "dead_after": _env_float("MXNET_PS_DEAD_AFTER_S",
                                 15.0 * heartbeat if heartbeat else 30.0),
        "barrier_timeout":
            _env_float("MXNET_PS_BARRIER_TIMEOUT_S", 600.0) or None,
        # distributed tracing: MXNET_TRACE_CONTEXT=0 keeps trace ids off
        # the wire even with telemetry on; MXNET_TRACE_DUMP_DIR makes
        # every role dump its Chrome trace (+ rank/clock metadata) there
        # at exit, the per-rank artifacts trace_report --fleet merges
        "trace_context":
            os.environ.get("MXNET_TRACE_CONTEXT", "1").strip().lower()
            not in ("0", "false", "off", "no"),
        "trace_dump_dir":
            os.environ.get("MXNET_TRACE_DUMP_DIR", "").strip() or None,
    }


_ENV = _read_env()


def _parse_rank_hint():
    """Launcher-provided rank hint, or None when no launcher set one
    (registration sends None so the scheduler assigns any free rank —
    0 would wrongly claim rank 0)."""
    hint = (os.environ.get("DMLC_WORKER_RANK")
            or os.environ.get("OMPI_COMM_WORLD_RANK")
            or os.environ.get("PMI_RANK"))
    try:
        return int(hint) if hint is not None else None
    except ValueError:
        return None


# role/rank identity for per-frame trace context: cached at import (the
# JG006 cached-value pattern — identity cannot change mid-process, and
# _wrap_traced sits on the send hot path)
_ROLE = os.environ.get("DMLC_ROLE", "worker")
_RANK_HINT = _parse_rank_hint()


def refresh_from_env():
    """Re-read every MXNET_PS_* knob (tests / late configuration)."""
    global _ENV, _ROLE, _RANK_HINT
    _ENV = _read_env()
    _ROLE = os.environ.get("DMLC_ROLE", "worker")
    _RANK_HINT = _parse_rank_hint()


# retry jitter: intentionally unseeded — it desynchronizes thundering
# herds and never affects numerics, so reproducibility doesn't want it
_jitter = _JitterRandom()


def BIGARRAY_BOUND():
    """Elements above which a key is range-sharded across all servers
    (reference: MXNET_KVSTORE_BIGARRAY_BOUND, kvstore_dist.h:60)."""
    # deliberate re-read: dist tests retune the bound between phases
    # graftlint: disable=JG006
    return int(os.environ.get("MXNET_KVSTORE_BIGARRAY_BOUND", 1 << 20))


def role():
    return os.environ.get("DMLC_ROLE", "worker")


def num_workers():
    return int(os.environ.get("DMLC_NUM_WORKER", 1))


def num_servers():
    return int(os.environ.get("DMLC_NUM_SERVER", 1))


def root_addr():
    return (os.environ.get("DMLC_PS_ROOT_URI", "127.0.0.1"),
            int(os.environ.get("DMLC_PS_ROOT_PORT", 9091)))


# Payloads are numpy arrays + plain containers + framework classes
# (set_optimizer ships an mxnet_tpu.optimizer instance). Everything
# else — os.system et al. — is refused at find_class time, so one
# malformed/malicious peer cannot execute code in a training job.
_SAFE_BUILTINS = frozenset({
    "dict", "list", "tuple", "set", "frozenset", "str", "int", "float",
    "bool", "bytes", "bytearray", "complex", "slice", "range",
})


class _RestrictedUnpickler(pickle.Unpickler):
    def find_class(self, module, name):
        root = module.split(".", 1)[0]
        if root in ("numpy", "mxnet_tpu"):
            return super().find_class(module, name)
        if module == "builtins" and name in _SAFE_BUILTINS:
            return super().find_class(module, name)
        raise pickle.UnpicklingError(
            "disallowed pickle global %s.%s" % (module, name))


def _restricted_loads(blob):
    import io
    return _RestrictedUnpickler(io.BytesIO(blob)).load()


_UNSET = object()


def _send_site(msg):
    """Chaos site for one outgoing frame: ``conn.send.<op>`` when the
    message is a tagged tuple, bare ``conn.send`` otherwise."""
    if isinstance(msg, tuple) and msg and isinstance(msg[0], str):
        return "conn.send." + msg[0]
    return "conn.send"


def _send_key(msg):
    """Chaos key for one outgoing frame: push frames count per kv key
    (bucket id), because the overlap tier dispatches bucket pushes in
    whatever order gradients become ready — a dispatch-order counter
    would make the same spec+seed hit different buckets with overlap on
    vs off.  Every other op keeps the sequential counter (their order
    IS the deterministic call order)."""
    if isinstance(msg, tuple) and len(msg) > 1 and msg[0] == "push" \
            and isinstance(msg[1], str):
        return msg[1]
    return None


def _msg_op(msg):
    if isinstance(msg, tuple) and msg and isinstance(msg[0], str):
        return msg[0]
    return "?"


# ---------------------------------------------------------------------------
# wire trace context
# ---------------------------------------------------------------------------
#
# When the sender's telemetry is tracing (and MXNET_TRACE_CONTEXT is not
# 0), every frame is wrapped  ("__tc__", (trace_id, span_id, send_clock,
# role, rank), payload)  and the send/recv pair lands in both ranks'
# Chrome traces as ``ps_send:<op>`` / ``ps_recv:<op>`` events sharing
# the span id — the joints trace_report --fleet draws flow arrows on.
# A receiver adopts the trace id into its context, so work a server does
# on behalf of a worker's step carries the step's trace id.  Receivers
# unwrap unconditionally (the SENDER decides whether to trace), so
# mixed-configuration jobs interoperate.

_TC_TAG = "__tc__"


def _wrap_traced(msg):
    if not (_ENV["trace_context"] and _tel.trace_active()):
        return msg
    trace_id = _tel.trace_context() or _tel.new_trace_id()
    span_id = _tel.new_span_id()
    ctx = (trace_id, span_id, _tel.now_us(), _ROLE, _my_rank())
    t0 = _tel.now_us()
    _tel.add_event("ps_send:%s" % _msg_op(msg), "rpc", t0, 1.0,
                   args={"trace_id": trace_id, "span_id": span_id})
    return (_TC_TAG, ctx, msg)


def _unwrap_traced(msg):
    if not (isinstance(msg, tuple) and len(msg) == 3
            and msg[0] == _TC_TAG):
        return msg
    ctx, payload = msg[1], msg[2]
    try:
        trace_id, span_id, send_clock, from_role, from_rank = ctx
    except (TypeError, ValueError):
        return payload
    _tel.set_trace_context(trace_id)
    if _tel.trace_active():
        _tel.add_event("ps_recv:%s" % _msg_op(payload), "rpc",
                       _tel.now_us(), 1.0,
                       args={"trace_id": trace_id,
                             "parent_span": span_id,
                             "send_clock_us": send_clock,
                             "from_role": from_role,
                             "from_rank": from_rank})
    return payload


class Conn:
    """Message channel: (magic, version, length) header + allowlist-
    restricted pickle payload.

    Deadlines: *timeout* (seconds) bounds every recv by default;
    ``recv(timeout=...)`` overrides per call, and an explicit
    ``timeout=None`` documents a deliberate unbounded wait (the JG007
    contract).  A timeout that interrupts a half-read frame poisons the
    connection — the stream is no longer aligned, so later recvs fail
    fast instead of decoding garbage.
    """

    def __init__(self, sock, timeout=None):
        self.sock = sock
        self._wlock = _lockwitness.make_lock("Conn._wlock")
        self._timeout = timeout
        self._broken = None
        try:
            sock.settimeout(timeout)
        except OSError:       # already-closed test socket: fail at use
            pass

    @classmethod
    def connect(cls, addr, retries=None, delay=None, timeout=_UNSET):
        """Dial with bounded retries (``MXNET_PS_CONNECT_RETRIES`` /
        ``MXNET_PS_CONNECT_DELAY_S``); the resulting connection keeps a
        bounded recv deadline (``MXNET_PS_RPC_TIMEOUT_S``) instead of
        reverting to blocking-forever."""
        env = _ENV
        if retries is None:
            retries = env["connect_retries"]
        if delay is None:
            delay = env["connect_delay"]
        if timeout is _UNSET:
            timeout = env["rpc_timeout"]
        last = None
        for _ in range(max(1, retries)):
            try:
                s = socket.create_connection(addr, timeout=60)
                if s.getsockname() == s.getpeername():
                    # TCP self-connect: dialing a port with no listener
                    # can "succeed" when the kernel picks the target
                    # port itself as our source port (likely on
                    # localhost right after that port's owner died —
                    # freed ports are preferentially reused).  Both
                    # ends are THIS socket, so any protocol exchange
                    # would read back its own frames; a dial-verify
                    # against a killed server's address would wrongly
                    # pass.  Never a real peer: fail the attempt.
                    s.close()
                    raise ConnectionError(
                        "self-connected to %s:%s (no listener on the "
                        "port)" % (addr[0], addr[1]))
                s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
                return cls(s, timeout=timeout)
            except OSError as exc:
                last = exc
                time.sleep(delay)
        raise ConnectionError(
            "cannot reach %s:%d after %d attempts: %r"
            % (addr[0], addr[1], max(1, retries), last)) from last

    def send(self, msg):
        blob = pickle.dumps(_wrap_traced(msg),
                            protocol=pickle.HIGHEST_PROTOCOL)
        if self._broken:
            raise ConnectionError(
                "connection poisoned (%s); reconnect before reuse"
                % self._broken)
        if _chaos.active():
            act = _chaos.decide(_send_site(msg), key=_send_key(msg))
            if act is not None:
                kind = act[0]
                if kind == "drop":
                    return                  # frame vanishes on the wire
                if kind in ("delay", "stall"):
                    # a lock held across this injected stall is exactly
                    # the wedge JG010 hunts — tell the witness
                    _lockwitness.note_blocking("conn.send(chaos-%s)"
                                               % kind)
                    time.sleep(act[1])
                elif kind == "close":
                    self.close()
                    raise ConnectionError(
                        "chaos: connection closed before send")
                elif kind == "garbage":
                    with self._wlock:
                        # _wlock IS the frame-write serializer: leaf
                        # lock, nothing ever nests under it
                        # graftlint: disable=JG010
                        self.sock.sendall(b"\xde\xad\xbe\xef" * 4)
                    return
                else:
                    _chaos.apply_inline(act)
        with self._wlock:
            # graftlint: disable=JG010 — leaf write lock, see above
            self.sock.sendall(
                _HDR.pack(_MAGIC, _WIRE_VERSION, len(blob)) + blob)

    def recv(self, timeout=_UNSET):
        """Receive one message.  *timeout* seconds (default: the
        connection's deadline); pass an explicit ``timeout=None`` only
        for documented-deliberate unbounded waits.  Raises
        :class:`RPCTimeout` on deadline, :class:`ProtocolError` on
        garbage, :class:`ConnectionError` on EOF."""
        if self._broken:
            raise ConnectionError(
                "connection poisoned (%s); reconnect before reuse"
                % self._broken)
        eff = self._timeout if timeout is _UNSET else timeout
        if _chaos.active():
            act = _chaos.decide("conn.recv")
            if act is not None:
                kind = act[0]
                if kind in ("delay", "stall"):
                    _lockwitness.note_blocking("conn.recv(chaos-%s)"
                                               % kind)
                    time.sleep(act[1])
                elif kind == "close":
                    self.close()            # the read below sees EOF
                else:
                    _chaos.apply_inline(act)
        consumed = [0]
        try:
            try:
                self.sock.settimeout(eff)
                hdr = self._read(_HDR.size, consumed)
                magic, ver, n = _HDR.unpack(hdr)
                if magic != _MAGIC:
                    raise ProtocolError("bad frame magic %r" % (magic,))
                if ver != _WIRE_VERSION:
                    raise ProtocolError(
                        "peer speaks wire version %d, this process "
                        "speaks %d" % (ver, _WIRE_VERSION))
                if n > _MAX_FRAME:
                    raise ProtocolError(
                        "frame of %d bytes exceeds limit" % n)
                blob = self._read(n, consumed)
            finally:
                try:
                    self.sock.settimeout(self._timeout)
                except OSError:
                    pass
        except socket.timeout as exc:
            mid = bool(consumed[0])
            if mid:       # half a frame read: stream alignment is gone
                self._broken = "mid-frame rpc timeout"
            _tel.bump("ps_rpc_timeouts")
            raise RPCTimeout(
                "no%s reply within %.1fs%s"
                % ("" if not mid else " complete", eff or 0.0,
                   " (mid-frame; connection poisoned)" if mid else "")
            ) from exc
        try:
            return _unwrap_traced(_restricted_loads(blob))
        except pickle.UnpicklingError as exc:
            raise ProtocolError(str(exc))
        except Exception as exc:   # truncated/garbage pickle bytes
            raise ProtocolError("undecodable payload: %r" % (exc,))

    def _read(self, n, consumed=None):
        buf = bytearray()
        while len(buf) < n:
            # bounded by the settimeout() in recv(): the one deliberate
            # raw-socket read funnel  # graftlint: disable=JG007
            chunk = self.sock.recv(n - len(buf))
            if not chunk:
                raise ConnectionError("peer closed")
            buf.extend(chunk)
            if consumed is not None:
                consumed[0] += len(chunk)
        return bytes(buf)

    def close(self):
        try:
            self.sock.close()
        except OSError:
            pass


def _accept_loop(lsock, stop, handler, make_conn=Conn):
    """The one accept/poll/stop discipline every wire role shares
    (:class:`RpcListener`, :meth:`Scheduler.run`,
    :meth:`Server.serve_forever`): poll ``accept`` on a bounded 0.25s
    timeout so ``stop`` never waits on a silent socket, end the loop on
    a socket error (the listener was closed under us), and hand each
    accepted connection to *handler* on a daemon thread that owns the
    conn's lifetime.  The caller keeps ownership of *lsock* — closing
    it (and any post-loop shutdown protocol) stays the caller's job."""
    lsock.settimeout(0.25)
    while not stop.is_set():
        try:
            sock, _ = lsock.accept()
        except socket.timeout:
            continue
        except OSError:
            break
        threading.Thread(target=handler, args=(make_conn(sock),),
                         daemon=True).start()


class RpcListener:
    """Bounded accept loop + per-connection handler threads — the
    :func:`_accept_loop` discipline plus socket setup/teardown, so new
    wire roles (the serving fleet router and its replicas) don't
    re-derive it.

    *handler(conn)* runs on a daemon thread per accepted connection and
    owns the conn's lifetime; the accept loop itself polls on a bounded
    timeout so :meth:`stop` never waits on a silent socket.
    """

    def __init__(self, handler, port=0, host="127.0.0.1", name="rpc",
                 conn_timeout=_UNSET):
        self._handler = handler
        self._conn_timeout = _ENV["rpc_timeout"] \
            if conn_timeout is _UNSET else conn_timeout
        self._stop = threading.Event()
        self.lsock = socket.socket()
        self.lsock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self.lsock.bind((host, port))
        self.lsock.listen(128)
        self.addr = (host, self.lsock.getsockname()[1])
        self._thread = threading.Thread(
            target=self._loop, name="mxps-listen-%s" % name, daemon=True)

    def start(self):
        self._thread.start()
        return self

    def _loop(self):
        _accept_loop(
            self.lsock, self._stop, self._serve,
            make_conn=lambda s: Conn(s, timeout=self._conn_timeout))
        try:
            self.lsock.close()
        except OSError:
            pass

    def _serve(self, conn):
        try:
            self._handler(conn)
        except (OSError, ConnectionError):
            pass                       # peer went away: its problem
        finally:
            conn.close()

    def stop(self):
        self._stop.set()
        try:
            self.lsock.close()         # unblock a pending accept
        except OSError:
            pass
        if self._thread.is_alive():
            self._thread.join(5.0)


# ---------------------------------------------------------------------------
# key → server placement
# ---------------------------------------------------------------------------

def _key_hash(key):
    """Stable across processes (unlike hash() under PYTHONHASHSEED)."""
    import zlib
    return zlib.adler32(str(key).encode())


def server_of_key(key, nserv):
    return _key_hash(key) % nserv


def shard_ranges(size, nserv):
    """Split [0, size) into nserv contiguous ranges (big-array mode)."""
    step = -(-size // nserv)
    return [(i * step, min((i + 1) * step, size)) for i in range(nserv)
            if i * step < size]


def placement(key, shape, nserv):
    """Return [(server_idx, (lo, hi))] over the *flattened* array.

    Small keys live whole on one server; arrays over BIGARRAY_BOUND are
    range-partitioned across every server so no single server bottlenecks
    on the fat embedding/fc weights (reference kvstore_dist.h:253-313).
    """
    size = int(np.prod(shape)) if shape else 1
    if size < BIGARRAY_BOUND() or nserv == 1:
        return [(server_of_key(key, nserv), (0, size))]
    return list(enumerate(shard_ranges(size, nserv)))


# ---------------------------------------------------------------------------
# local node registry + /peers view (observe-only, no network IO)
# ---------------------------------------------------------------------------

_NODES = {}               # (role, rank) -> zero-arg dict provider
_NODES_LOCK = _lockwitness.make_lock("dist_ps._NODES_LOCK")
_SCHEDULER_REF = None     # weakref to the in-process Scheduler, if any
_PEER_SNAPSHOT = None     # (unix_time, table) last fetched by a worker
_FLEET_SNAPSHOT = None    # (unix_time, table) last fetched by a worker
_MY_RANK = None           # rank of this process's primary (env) role
_CLOCK = [None, None]     # [offset_us, rtt_us] vs the scheduler's clock


def _register_node(role_name, rank, provider):
    global _MY_RANK
    with _NODES_LOCK:
        _NODES[(role_name, rank)] = provider
        if role_name == role():
            _MY_RANK = rank


def _my_rank():
    if _MY_RANK is not None:
        return _MY_RANK
    return _RANK_HINT if _RANK_HINT is not None else 0


def _set_peer_snapshot(table):
    global _PEER_SNAPSHOT
    _PEER_SNAPSHOT = (time.time(), table)


def _set_fleet_snapshot(table):
    global _FLEET_SNAPSHOT
    _FLEET_SNAPSHOT = (time.time(), table)


def _set_clock(offset_us, rtt_us):
    _CLOCK[0] = offset_us
    _CLOCK[1] = rtt_us
    _tel.set_gauge("ps_clock_offset_us", offset_us)
    _tel.set_gauge("ps_clock_rtt_us", rtt_us)


def clock_offset_us():
    """This rank's estimated trace-clock offset to the scheduler (None
    before the first heartbeat clock exchange; 0 on the scheduler)."""
    if _SCHEDULER_REF is not None and _SCHEDULER_REF() is not None:
        return 0.0
    return _CLOCK[0]


def _local_digest():
    """The compact telemetry digest a rank ships on fleet_sync: enough
    for the scheduler's /fleet view, small enough for a heartbeat."""
    gauge_names = ("step_device_us", "step_collective_us", "step_host_us",
                   "step_data_wait_us", "overlap_ratio", "step_rate_per_s",
                   "device_bytes_in_use", "engine_pending_tasks",
                   "serving_queue_depth")
    return {"pid": os.getpid(),
            "unix_time": time.time(),
            "steps": _flight.step_count(),
            "telemetry": _tel.enabled(),
            "counters": _tel.counters(),
            "gauges": {name: _tel.gauge(name) for name in gauge_names},
            "clock_offset_us": _CLOCK[0],
            "clock_rtt_us": _CLOCK[1]}


def peer_view():
    """Dist/peer health for the introspection server's ``/peers``.

    Observe-only by contract: reports this process's registered nodes,
    the live table when this process IS the scheduler, and otherwise the
    last scheduler snapshot the heartbeat thread cached — never a fresh
    network round trip from the HTTP handler.
    """
    with _NODES_LOCK:
        nodes = dict(_NODES)
    local = []
    for (role_name, rank), provider in sorted(nodes.items()):
        entry = {"role": role_name, "rank": rank}
        try:
            entry.update(provider() or {})
        except Exception:
            pass
        local.append(entry)
    out = {"role": role(), "local_nodes": local,
           "counters": {name: _tel.counter(name) for name in
                        ("ps_rpc_timeouts", "ps_rpc_retries",
                         "ps_peer_lost", "ps_reconnects",
                         "ps_heartbeats", "chaos_faults")}}
    sched = _SCHEDULER_REF() if _SCHEDULER_REF is not None else None
    if sched is not None:
        out["scheduler"] = sched.peer_table()
    snap = _PEER_SNAPSHOT
    if snap is not None:
        out["peers"] = dict(snap[1],
                            snapshot_age_s=round(time.time() - snap[0], 3))
    chaos_desc = _chaos.describe()
    if chaos_desc is not None:
        out["chaos"] = chaos_desc
    return out


def fleet_view():
    """Fleet-wide telemetry for the introspection server's ``/fleet``.

    Observe-only by contract (the /peers doctrine): the live digest
    table when this process IS the scheduler, otherwise the snapshot the
    heartbeat thread last cached — never a network round trip from the
    HTTP handler.
    """
    out = {"role": role(),
           "rank": _my_rank(),
           "clock_offset_us": clock_offset_us(),
           "clock_rtt_us": _CLOCK[1]}
    sched = _SCHEDULER_REF() if _SCHEDULER_REF is not None else None
    if sched is not None:
        out["fleet"] = sched.fleet_table()
        out["live"] = True
        return out
    snap = _FLEET_SNAPSHOT
    if snap is not None:
        out["fleet"] = dict(snap[1],
                            snapshot_age_s=round(time.time() - snap[0], 3))
    out["live"] = False
    return out


def dump_trace_artifacts(directory=None):
    """Write this rank's Chrome trace (+ rank/clock metadata) as
    ``trace_<role>_<rank>.json`` — the per-rank artifact
    ``trace_report --fleet`` merges into one clock-aligned timeline.

    *directory* defaults to ``MXNET_TRACE_DUMP_DIR``; returns the path,
    or None when no directory is configured.  Called automatically at
    role exit (scheduler/server mains, worker finalize) when the env
    knob is set; safe to call explicitly at any point.
    """
    directory = directory or _ENV["trace_dump_dir"]
    if not directory:
        return None
    payload = _tel.chrome_trace_payload()
    payload["rank_meta"] = {
        "role": role(), "rank": _my_rank(), "pid": os.getpid(),
        "clock_offset_us": clock_offset_us(),
        "clock_rtt_us": _CLOCK[1],
        "steps": _flight.step_count(),
        "unix_time": time.time()}
    os.makedirs(directory, exist_ok=True)
    path = os.path.join(directory,
                        "trace_%s_%s.json" % (role(), _my_rank()))
    tmp = "%s.tmp.%d" % (path, os.getpid())
    import json as _json
    with open(tmp, "w") as fh:
        _json.dump(payload, fh, default=repr)
    os.replace(tmp, path)
    return path


def refresh_gauges():
    """Feed the ``ps_dead_peers`` gauge (called by the introspection
    sampler through ``sys.modules`` — observe-only)."""
    table = None
    sched = _SCHEDULER_REF() if _SCHEDULER_REF is not None else None
    if sched is not None:
        table = sched.peer_table()
    elif _PEER_SNAPSHOT is not None:
        table = _PEER_SNAPSHOT[1]
    if table is None:
        return
    dead = sum(1 for group in ("workers", "servers")
               for info in table.get(group, {}).values()
               if info.get("dead"))
    _tel.set_gauge("ps_dead_peers", dead)


def _start_heartbeat(role_name, rank):
    """Daemon thread: a dedicated scheduler connection carrying periodic
    one-way ``heartbeat`` frames and, every few ticks, a ``fleet_sync``
    exchange — this rank's telemetry digest out; the peer table, the
    fleet digest table, and the scheduler's trace clock back.  The
    round-trip also estimates this rank's clock offset to the scheduler
    (RTT-midpoint: the scheduler stamped its clock mid-flight, so local
    time ``t0 + rtt/2`` corresponds to that stamp; error ≤ rtt/2).
    Returns a stop Event, or None when heartbeats are disabled."""
    env = _ENV
    if env["heartbeat"] <= 0:
        return None
    stop = threading.Event()

    def _loop():
        try:
            conn = Conn.connect(root_addr(), retries=20,
                                timeout=max(env["dead_after"], 5.0))
            conn.send(("hb_register", role_name, rank))
        except (OSError, ConnectionError):
            return                     # no scheduler: nothing to feed
        tick = 0
        while not stop.wait(env["heartbeat"]):
            tick += 1
            try:
                conn.send(("heartbeat",))
                _tel.bump("ps_heartbeats")
                if tick % 5 == 0:
                    t0 = _tel.now_us()
                    conn.send(("fleet_sync", _local_digest()))
                    reply = conn.recv(timeout=max(env["dead_after"], 5.0))
                    if reply and reply[0] == "fleet_sync":
                        rtt = _tel.now_us() - t0
                        _set_peer_snapshot(reply[1])
                        _set_fleet_snapshot(reply[2])
                        _set_clock(reply[3] - (t0 + rtt / 2.0), rtt)
                        _tel.bump("ps_fleet_syncs")
            except (OSError, ConnectionError):
                return                 # scheduler gone; RPCs will notice
        conn.close()

    threading.Thread(target=_loop, name="mxps-hb-%s-%s"
                     % (role_name, rank), daemon=True).start()
    return stop


# ---------------------------------------------------------------------------
# Scheduler: rendezvous + barrier + heartbeats + shutdown fan-out
# ---------------------------------------------------------------------------

class Scheduler:
    """Assigns ranks, publishes the server address list, serves barriers,
    and tracks peer liveness.

    Lifecycle: all S servers and N workers connect and register; the
    scheduler replies with (rank, server_addrs).  Workers keep the
    connection for barrier()/finalize; when every worker has finalized,
    servers are told to shut down and the scheduler exits.  Each peer
    additionally opens a heartbeat connection (``hb_register``); a peer
    whose heartbeats stop for ``MXNET_PS_DEAD_AFTER_S`` (or whose
    heartbeat link drops) is marked dead — dead workers fail any pending
    or future barrier *immediately* (``barrier_failed``), and a dead
    server's rank is handed to the next ``reg_server`` so a restarted
    server can take over its shard.
    """

    def __init__(self, nworkers, nservers, port=None):
        global _SCHEDULER_REF
        self.nworkers, self.nservers = nworkers, nservers
        self.lsock = socket.socket()
        self.lsock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self.lsock.bind(("", port or root_addr()[1]))
        self.lsock.listen(128)
        self.server_addrs = [None] * nservers
        self.server_conns = []
        self.worker_conns = {}
        self._lock = _lockwitness.make_lock("Scheduler._lock")
        self._registered = _lockwitness.make_condition(
            self._lock, "Scheduler._registered")
        self._barrier_waiters = []
        self._barrier_gen = 0
        self._finalized = 0
        self._finalized_ranks = set()
        self.dead_workers = set()
        self.dead_servers = set()
        self._hb = {}             # (role, rank) -> last monotonic
        self._fleet = {}          # (role, rank) -> (monotonic, digest)
        self._done = threading.Event()
        _SCHEDULER_REF = weakref.ref(self)
        _register_node("scheduler", 0, self._node_info)

    def _node_info(self):
        with self._lock:
            return {"nworkers": self.nworkers, "nservers": self.nservers,
                    "finalized": len(self._finalized_ranks),
                    "dead_workers": sorted(self.dead_workers),
                    "dead_servers": sorted(self.dead_servers)}

    def peer_table(self):
        """JSON-able liveness table (the /peers payload's core)."""
        now = time.monotonic()
        with self._lock:
            workers = {}
            for r in range(self.nworkers):
                seen = self._hb.get(("worker", r))
                workers[str(r)] = {
                    "last_heartbeat_age_s":
                        None if seen is None else round(now - seen, 3),
                    "registered": r in self.worker_conns,
                    "dead": r in self.dead_workers,
                    "finalized": r in self._finalized_ranks}
            servers = {}
            for r in range(self.nservers):
                seen = self._hb.get(("server", r))
                servers[str(r)] = {
                    "last_heartbeat_age_s":
                        None if seen is None else round(now - seen, 3),
                    "addr": self.server_addrs[r],
                    "dead": r in self.dead_servers}
            return {"nworkers": self.nworkers, "nservers": self.nservers,
                    "workers": workers, "servers": servers,
                    "barrier_waiters": len(self._barrier_waiters)}

    def fleet_table(self):
        """Aggregated per-rank telemetry digests (the /fleet payload's
        core): whatever each rank last shipped on its heartbeat link,
        plus this scheduler's own clock so readers can re-anchor."""
        now = time.monotonic()
        with self._lock:
            ranks = {"%s-%s" % key: dict(digest,
                                         digest_age_s=round(now - at, 3))
                     for key, (at, digest) in sorted(self._fleet.items())}
        return {"nworkers": self.nworkers, "nservers": self.nservers,
                "ranks": ranks,
                "scheduler": {"pid": os.getpid(),
                              "now_us": round(_tel.now_us(), 1),
                              "steps": _flight.step_count()}}

    def run(self):
        # Accept until shutdown rather than counting to N connections: a
        # malformed/rogue connection must not consume a registration slot
        # and hang the whole job (it is dropped in _serve instead).
        _accept_loop(self.lsock, self._done, self._serve)
        for c in self.server_conns:
            try:
                c.send(("shutdown",))
            except (OSError, ConnectionError):
                pass
        self.lsock.close()

    # -- liveness ----------------------------------------------------------

    def _mark_dead(self, role_name, rank, reason):
        """Book a dead peer; dead workers fail pending barriers at once
        (a barrier missing a dead member can never complete — waiting
        would be the exact hang this module exists to prevent)."""
        notify = []
        with self._lock:
            if role_name == "server":
                if rank in self.dead_servers:
                    return
                self.dead_servers.add(rank)
            else:
                if rank in self.dead_workers \
                        or rank in self._finalized_ranks:
                    return
                self.dead_workers.add(rank)
                if self._barrier_waiters:
                    notify, self._barrier_waiters = \
                        self._barrier_waiters, []
                    self._barrier_gen += 1
                    self._registered.notify_all()
                # with every remaining worker finalized or dead the job
                # can never finalize cleanly: release the servers.  NOT
                # on staleness though — a stale peer is revivable (GC /
                # cold-compile pause), and tearing the servers down
                # would make the revive meaningless.
                if reason != "heartbeat-stale" \
                        and len(self._finalized_ranks | self.dead_workers) \
                        == self.nworkers:
                    self._done.set()
            dead = sorted(self.dead_workers)
        _flight.record("peer_dead", "%s-%s" % (role_name, rank),
                       reason=reason)
        for c in notify:
            try:
                c.send(("barrier_failed", dead))
            except (OSError, ConnectionError):
                pass

    def _revive(self, role_name, rank):
        with self._lock:
            if role_name == "server":
                self.dead_servers.discard(rank)
            else:
                self.dead_workers.discard(rank)

    def _serve_heartbeats(self, conn, role_name, rank):
        """Per-peer heartbeat loop: stamp arrivals, declare staleness,
        answer ``peers`` snapshot requests on the same link."""
        key = (role_name, rank)
        with self._lock:
            self._hb[key] = time.monotonic()
        stale = False
        while not self._done.is_set():
            try:
                msg = conn.recv(timeout=max(_ENV["dead_after"], 0.05))
            except RPCTimeout:
                stale = True
                self._mark_dead(role_name, rank, "heartbeat-stale")
                continue
            except (OSError, ConnectionError):
                self._mark_dead(role_name, rank, "heartbeat-disconnect")
                return
            with self._lock:
                self._hb[key] = time.monotonic()
            if stale:           # a long GC pause, not a death: revive
                stale = False
                self._revive(role_name, rank)
            if msg and msg[0] == "peers":
                try:
                    conn.send(("peers", self.peer_table()))
                except (OSError, ConnectionError):
                    return
            elif msg and msg[0] == "fleet_sync":
                if len(msg) > 1 and isinstance(msg[1], dict):
                    with self._lock:
                        self._fleet[key] = (time.monotonic(), msg[1])
                try:
                    # the clock stamp goes LAST in the handler so the
                    # peer's rtt/2 midpoint brackets it as tightly as
                    # the transport allows
                    conn.send(("fleet_sync", self.peer_table(),
                               self.fleet_table(),
                               round(_tel.now_us(), 1)))
                except (OSError, ConnectionError):
                    return

    # -- registration + control --------------------------------------------

    def _serve(self, conn):
        try:
            # registration follows connect immediately; a silent socket
            # here is a rogue peer, not a straggler
            msg = conn.recv(timeout=max(_ENV["dead_after"] * 5, 30.0))
            kind = msg[0]
            if kind not in ("reg_server", "reg_worker", "hb_register"):
                raise ProtocolError("first message must register a role")
        except (ConnectionError, TypeError, IndexError, KeyError):
            conn.close()   # rogue peer: drop without consuming a slot
            return
        if kind == "hb_register":
            self._serve_heartbeats(conn, str(msg[1]), int(msg[2]))
            return
        with self._lock:
            if kind == "reg_server":
                if None in self.server_addrs:
                    rank = self.server_addrs.index(None)
                elif self.dead_servers:
                    # a restarted server takes over a dead rank's shard;
                    # the caller restores its state via set_state
                    rank = min(self.dead_servers)
                    self.dead_servers.discard(rank)
                    self._hb.pop(("server", rank), None)
                else:
                    conn.close()   # over-registration
                    return
                self.server_addrs[rank] = msg[1]
                self.server_conns.append(conn)
            else:
                # honor the launcher's DMLC_WORKER_RANK when present so
                # worker i deterministically gets rank i
                hint = msg[1] if len(msg) > 1 else None
                if isinstance(hint, int) and 0 <= hint < self.nworkers \
                        and hint not in self.worker_conns:
                    rank = hint
                else:
                    try:
                        rank = next(i for i in range(self.nworkers)
                                    if i not in self.worker_conns)
                    except StopIteration:
                        conn.close()   # over-registration
                        return
                self.worker_conns[rank] = conn
            self._registered.notify_all()
            while (None in self.server_addrs
                   or len(self.worker_conns) < self.nworkers):
                self._registered.wait()
        conn.send(("ranked", rank, list(self.server_addrs)))
        if kind == "reg_server":
            return  # servers only hear "shutdown" from us
        self._serve_worker(conn, rank)

    def _serve_worker(self, conn, rank):
        while True:
            try:
                # a worker between RPCs is legitimately quiet; liveness
                # is the heartbeat link's job, not this one's
                msg = conn.recv(timeout=None)
            except ConnectionError:
                # liveness surface (ref kvstore.h:328 get_num_dead_node):
                # a worker whose control connection dropped without
                # finalizing counts as dead
                with self._lock:
                    known = (rank in self.worker_conns
                             and self.worker_conns[rank] is conn
                             and rank not in self._finalized_ranks)
                if known:
                    self._mark_dead("worker", rank, "control-disconnect")
                break
            if msg[0] == "heartbeat":
                with self._lock:
                    self._hb[("worker", rank)] = time.monotonic()
                continue
            if msg[0] == "num_dead":
                # snapshot under the lock, write to the peer outside it:
                # a stalled reader must not wedge the scheduler table
                with self._lock:
                    reply = ("num_dead", len(self.dead_workers))
                conn.send(reply)
                continue
            if msg[0] == "servers":
                with self._lock:
                    reply = ("servers", list(self.server_addrs),
                             sorted(self.dead_servers))
                conn.send(reply)
                continue
            if msg[0] == "peers":
                conn.send(("peers", self.peer_table()))
                continue
            if msg[0] == "fleet":
                conn.send(("fleet", self.fleet_table()))
                continue
            if msg[0] == "barrier":
                fail = None
                done = []
                with self._lock:
                    departed = self.dead_workers | self._finalized_ranks
                    if departed:
                        # can never complete: refuse instead of wedging
                        # (finalized members are gone just as surely as
                        # dead ones — and a crashed worker's atexit
                        # still manages to send finalize, so "finalized"
                        # does NOT imply "exited cleanly after its last
                        # barrier")
                        fail = sorted(departed)
                    else:
                        gen = self._barrier_gen
                        self._barrier_waiters.append(conn)
                        if len(self._barrier_waiters) == self.nworkers:
                            # release outside the lock: one slow worker
                            # socket must not hold the whole table hostage
                            done = self._barrier_waiters
                            self._barrier_waiters = []
                            self._barrier_gen += 1
                            self._registered.notify_all()
                        else:
                            while self._barrier_gen == gen \
                                    and conn in self._barrier_waiters:
                                self._registered.wait()
                            # woken by _mark_dead's sweep: it already
                            # sent barrier_failed on this conn
                for c in done:
                    c.send(("barrier_done",))
                if fail is not None:
                    conn.send(("barrier_failed", fail))
                continue
            if msg[0] == "finalize":
                notify = []
                with self._lock:
                    self._finalized_ranks.add(rank)
                    self._finalized += 1
                    if self._barrier_waiters:
                        # a member just left for good: the pending
                        # barrier can never reach nworkers — fail it now
                        notify, self._barrier_waiters = \
                            self._barrier_waiters, []
                        self._barrier_gen += 1
                        self._registered.notify_all()
                    departed = sorted(self.dead_workers
                                      | self._finalized_ranks)
                    if len(self._finalized_ranks | self.dead_workers) \
                            == self.nworkers:
                        self._done.set()
                for c in notify:
                    try:
                        c.send(("barrier_failed", departed))
                    except (OSError, ConnectionError):
                        pass
                conn.send(("bye",))
                break


# ---------------------------------------------------------------------------
# Server: shard store + sync aggregation + optimizer-on-server
# ---------------------------------------------------------------------------

class _PendingAgg:
    """Sync-mode merge buffer for one (key, timestamp)."""

    __slots__ = ("acc", "count", "rows")

    def __init__(self):
        self.acc = None
        self.count = 0
        self.rows = None  # row_sparse: set of pushed row ids


class Server:
    """Holds flat float shards; aggregates sync pushes; runs the updater.

    Push protocol (sync): each worker's push RPC blocks until all
    ``num_workers`` contributions for that (key, timestamp) have arrived
    and the update has been applied — this is the ordering guarantee the
    reference gets from engine dependencies + per-key server counters
    (kvstore_dist_server.h:164-210).

    Checkpoint-state protocol (``get_state``/``set_state``): the whole
    shard store + updater state as one opaque blob, so a worker can
    snapshot every server into a PR-7 checkpoint and pour it back into a
    *restarted* server that re-registered into the dead rank's slot.
    ``set_state`` also clears the sync-mode pending buffers — restore is
    a rollback to a consistent cut, and half-aggregated rounds from
    before the failure must not leak into the resumed run.
    """

    def __init__(self, nworkers):
        self.nworkers = nworkers
        self.store = {}        # key -> flat np array (this server's shard)
        self.shapes = {}       # key -> full shape (for updater reshape)
        self.ranges = {}       # key -> (lo, hi) of our shard
        self.pending = {}      # (key, ts) -> _PendingAgg
        self.updater = None
        self.sync = True
        self._lock = _lockwitness.make_lock("Server._lock")
        self._cv = _lockwitness.make_condition(self._lock, "Server._cv")

    def handle(self, msg):
        """Process one request; return the reply (or None)."""
        op = msg[0]
        if op == "ping":
            # liveness probe (refresh_servers dial-verify): a reply
            # proves a live server PROCESS is behind the socket — a
            # bare TCP connect cannot (the kernel completes handshakes
            # into a killed process's not-yet-torn-down accept queue)
            return ("pong",)
        if op == "init":
            _, key, flat, shape, rng = msg
            with self._lock:
                if key not in self.store:
                    self.store[key] = np.array(flat)
                    self.shapes[key] = tuple(shape)
                    self.ranges[key] = rng
                self._cv.notify_all()
            return ("ok",)
        if op == "push":
            return self._push(*msg[1:])
        if op == "pull":
            _, key = msg
            with self._lock:
                self._wait_key(key)
                return ("val", self.store[key])
        if op == "pull_rows":
            _, key, rows = msg
            with self._lock:
                self._wait_key(key)
                w = self.store[key].reshape(self.shapes[key])
                return ("val", w[np.asarray(rows, np.int64)])
        if op == "set_optimizer":
            from . import optimizer as opt
            optimizer = _restricted_loads(msg[1])
            with self._lock:
                self.updater = opt.get_updater(optimizer)
            return ("ok",)
        if op == "set_sync":
            with self._lock:
                self.sync = bool(msg[1])
            return ("ok",)
        if op == "get_state":
            return ("state", self._get_state())
        if op == "set_state":
            self._set_state(msg[1])
            return ("ok",)
        raise ValueError("bad server op %r" % (op,))

    # -- checkpoint-state protocol -----------------------------------------

    def _get_state(self):
        with self._lock:
            payload = {
                "version": 1,
                "store": {k: np.array(v) for k, v in self.store.items()},
                "shapes": dict(self.shapes),
                "ranges": dict(self.ranges),
                "sync": self.sync,
                "updater": None, "index_update_count": None,
                "num_update": None,
            }
            if self.updater is not None:
                payload["updater"] = self.updater.get_states(
                    dump_optimizer=False)
                srv_opt = getattr(self.updater, "optimizer", None)
                if srv_opt is not None:
                    payload["index_update_count"] = \
                        dict(srv_opt._index_update_count)
                    payload["num_update"] = int(srv_opt.num_update)
        return pickle.dumps(payload, protocol=pickle.HIGHEST_PROTOCOL)

    def _set_state(self, blob):
        payload = _restricted_loads(blob)
        with self._lock:
            self.store = {k: np.array(v)
                          for k, v in payload["store"].items()}
            self.shapes = {k: tuple(s)
                           for k, s in payload["shapes"].items()}
            self.ranges = dict(payload["ranges"])
            self.sync = bool(payload.get("sync", True))
            self.pending.clear()
            if payload.get("updater") is not None \
                    and self.updater is not None:
                # the inner blob crossed the wire too: decode it through
                # the SAME allowlist — a raw pickle.loads here would be
                # the code-exec hole the restricted unpickler exists to
                # close
                self.updater.set_states_payload(
                    _restricted_loads(payload["updater"]))
                srv_opt = getattr(self.updater, "optimizer", None)
                if srv_opt is not None \
                        and payload.get("index_update_count") is not None:
                    srv_opt._index_update_count = \
                        dict(payload["index_update_count"])
                    srv_opt.num_update = int(payload["num_update"])
            self._cv.notify_all()

    def _wait_key(self, key):
        while key not in self.store:
            self._cv.wait()

    def _push(self, key, ts, flat, rows):
        """flat: contribution to our shard (dense) or row-block (sparse)."""
        with self._lock:
            self._wait_key(key)
            if not self.sync:
                self._apply(key, np.array(flat), rows)
                return ("ok",)
            pend = self.pending.setdefault((key, ts), _PendingAgg())
            if rows is None:
                pend.acc = flat if pend.acc is None else pend.acc + flat
            else:
                # row-sparse: accumulate into a dense scratch of our shard
                if pend.acc is None:
                    pend.acc = np.zeros_like(self.store[key])
                w = pend.acc.reshape(self.shapes[key])
                w[np.asarray(rows, np.int64)] += flat
            pend.count += 1
            if pend.count == self.nworkers:
                self._apply(key, pend.acc, None)
                del self.pending[(key, ts)]
                self._cv.notify_all()
            else:
                while (key, ts) in self.pending:
                    self._cv.wait()
        return ("ok",)

    def _apply(self, key, agg, rows):
        """Aggregated gradient → updater (or overwrite, matching the
        reference server's no-updater CopyFromTo path)."""
        if rows is not None:  # async sparse push
            dense = np.zeros_like(self.store[key])
            dense.reshape(self.shapes[key])[np.asarray(rows, np.int64)] = agg
            agg = dense
        if self.updater is None:
            self.store[key] = np.asarray(agg, self.store[key].dtype).ravel()
            return
        from . import ndarray as _nd
        shape = self.shapes[key]
        lo, hi = self.ranges[key]
        full = lo == 0 and hi == int(np.prod(shape))
        wshape = shape if full else (hi - lo,)
        w = _nd.array(self.store[key].reshape(wshape), ctx=_cpu())
        g = _nd.array(np.asarray(agg, self.store[key].dtype).reshape(wshape),
                      ctx=_cpu())
        self.updater(_int_key(key), g, w)
        self.store[key] = w.asnumpy().astype(self.store[key].dtype).ravel()

    def serve_forever(self, lsock, stop):
        _accept_loop(lsock, stop, self._serve_conn)

    def _serve_conn(self, conn):
        while True:
            try:
                # a server waits on its clients by design: explicit
                # unbounded recv (the JG007 annotation)
                msg = conn.recv(timeout=None)
            except ConnectionError:
                return
            try:
                reply = self.handle(msg)
            except Exception:  # surface server bugs to the worker instead
                import traceback  # of hanging its blocking recv()
                reply = ("err", traceback.format_exc())
                with self._lock:      # unblock peers waiting on this key
                    self._cv.notify_all()
            if reply is not None:
                conn.send(reply)


def _cpu():
    from .context import cpu
    return cpu()


def _int_key(k):
    try:
        return int(k)
    except (TypeError, ValueError):
        return k


# ---------------------------------------------------------------------------
# role mains
# ---------------------------------------------------------------------------

def run_scheduler():
    Scheduler(num_workers(), num_servers()).run()
    try:
        dump_trace_artifacts()
    except Exception:
        pass


def run_server():
    lsock = socket.socket()
    lsock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    lsock.bind(("", 0))
    lsock.listen(128)
    my_addr = ("127.0.0.1", lsock.getsockname()[1])

    server = Server(num_workers())
    stop = threading.Event()
    t = threading.Thread(target=server.serve_forever, args=(lsock, stop),
                         daemon=True)
    t.start()

    # Registration retries: a RESTARTED server can beat the scheduler's
    # dead-peer detection — every rank still looks alive, the scheduler
    # refuses the registration as over-registration (closes the conn),
    # and without a retry the replacement would crash here and leave the
    # job permanently short one server (the exact recovery the fleet
    # replicas also depend on).  Re-register on a fresh conn until the
    # scheduler hands out a rank or the bounded window closes.
    last = None
    for _ in range(max(1, _env_int("MXNET_PS_REREGISTER_RETRIES", 40))):
        sched = None
        try:
            sched = Conn.connect(root_addr())
            sched.send(("reg_server", my_addr))
            # rendezvous waits for the full roster — deliberately
            # unbounded (a straggler worker is not a failure; scheduler
            # death / an over-registration refusal is an EOF here)
            msg = sched.recv(timeout=None)  # ("ranked", rank, addrs)
            break
        except (OSError, ConnectionError) as exc:
            last = exc
            if sched is not None:
                sched.close()
            time.sleep(0.25)
    else:
        raise PeerLost(
            "scheduler refused server registration (no free or dead "
            "rank) and never freed one: %r" % (last,),
            role="scheduler", addr=root_addr(),
            reason="over-registration")
    rank = int(msg[1])
    _register_node("server", rank, lambda: {"keys": len(server.store),
                                            "addr": my_addr})
    hb_stop = _start_heartbeat("server", rank)
    # block until scheduler says shutdown (unbounded by design: an idle
    # server between jobs is healthy; scheduler death is an EOF)
    try:
        msg = sched.recv(timeout=None)
    except ConnectionError:
        msg = ("shutdown",)
    assert msg[0] == "shutdown"
    if hb_stop is not None:
        hb_stop.set()
    stop.set()
    lsock.close()
    try:
        dump_trace_artifacts()
    except Exception:
        pass


def _check(reply):
    """Re-raise server-side failures shipped back as ('err', traceback)."""
    if isinstance(reply, tuple) and reply and reply[0] == "err":
        raise RuntimeError("kvstore server error:\n" + reply[1])
    return reply


class WorkerTransport:
    """Worker-side connections: one to the scheduler, one per server.

    Every RPC recv is bounded by ``MXNET_PS_RPC_TIMEOUT_S``; a timeout
    or broken connection surfaces as :class:`PeerLost` naming the peer.
    Idempotent RPCs (pull, pull_rows, state snapshots, set_optimizer,
    set_sync, init) retry up to ``MXNET_PS_RPC_RETRIES`` times on a
    *fresh* connection (a late reply on the old socket must never
    desynchronize the request/reply stream) with exponential backoff +
    jitter.  Pushes never retry — re-aggregating one worker's
    contribution would corrupt the sync merge; their failures surface
    immediately and recovery goes through :meth:`refresh_servers` +
    the checkpoint-state restore.
    """

    def __init__(self):
        self.sched = Conn.connect(root_addr())
        # read fresh (not the import-time _RANK_HINT cache): transports
        # are constructed once, and tests set the env late
        self.sched.send(("reg_worker", _parse_rank_hint()))
        # rendezvous waits for the full roster: deliberately unbounded
        msg = self.sched.recv(timeout=None)
        assert msg[0] == "ranked"
        self.rank = msg[1]
        self.server_addrs = [tuple(a) for a in msg[2]]
        self.server_conns = [Conn.connect(a) for a in self.server_addrs]
        self.nservers = len(self.server_conns)
        self._ts = {}     # key -> push timestamp counter
        self._lock = _lockwitness.make_lock("WorkerTransport._lock")
        self._hb_stop = _start_heartbeat("worker", self.rank)
        _register_node("worker", self.rank,
                       lambda: {"nservers": self.nservers})

    # -- failure plumbing ---------------------------------------------------

    def _peer_lost(self, sidx, op, cause):
        _tel.bump("ps_peer_lost")
        addr = self.server_addrs[sidx]
        _flight.record("peer_lost", "server-%d" % sidx, op=op,
                       cause=repr(cause))
        if isinstance(cause, RPCTimeout):
            reason = "rpc-timeout"
        else:
            reason = "disconnected"
        return PeerLost(
            "server %d (%s:%s) lost during %r: %r"
            % (sidx, addr[0], addr[1], op, cause),
            role="server", rank=sidx, addr=addr, reason=reason)

    def _sched_lost(self, op, cause, reason="disconnected"):
        _tel.bump("ps_peer_lost")
        _flight.record("peer_lost", "scheduler", op=op, cause=repr(cause))
        return PeerLost("scheduler lost during %r: %r" % (op, cause),
                        role="scheduler", addr=root_addr(), reason=reason)

    def _reconnect_server(self, sidx):
        """Fresh connection to server *sidx* (drops any half-read or
        half-written stream state with the old socket)."""
        old = self.server_conns[sidx]
        conn = Conn.connect(self.server_addrs[sidx], retries=1, delay=0)
        self.server_conns[sidx] = conn
        old.close()
        _tel.bump("ps_reconnects")
        return conn

    def _server_rpc(self, sidx, msg, idempotent=False):
        """One request/reply round to server *sidx*.  See the class
        docstring for the retry/idempotency doctrine."""
        attempts = _ENV["rpc_retries"] if idempotent else 1
        delay = 0.05
        last = None
        for attempt in range(attempts):
            if attempt:
                _tel.bump("ps_rpc_retries")
                time.sleep(delay * (0.5 + _jitter.random()))
                delay *= 2
                try:
                    self._reconnect_server(sidx)
                except (OSError, ConnectionError) as exc:
                    last = exc
                    continue
            conn = self.server_conns[sidx]
            try:
                conn.send(msg)
                return _check(conn.recv(timeout=_ENV["rpc_timeout"]))
            except ProtocolError:
                raise                       # a bug, not a dead peer
            except (OSError, ConnectionError) as exc:
                last = exc
        raise self._peer_lost(sidx, msg[0], last) from last

    def _send_to(self, sidx, msg):
        try:
            self.server_conns[sidx].send(msg)
        except ProtocolError:
            raise
        except (OSError, ConnectionError) as exc:
            raise self._peer_lost(sidx, msg[0], exc) from exc

    def _recv_from(self, sidx, op):
        # 2x the base deadline: a push ack legitimately waits on OTHER
        # workers' contributions, and a peer absorbing one transient
        # fault (<= 1 deadline of stall + retry) must not cascade into
        # a spurious PeerLost here.  A dead server still fails instantly
        # (TCP reset) — the 2x bound is the acceptance contract for the
        # silent-peer case.
        eff = _ENV["rpc_timeout"]
        try:
            return _check(self.server_conns[sidx].recv(
                timeout=None if eff is None else 2.0 * eff))
        except ProtocolError:
            raise
        except (OSError, ConnectionError) as exc:
            raise self._peer_lost(sidx, op, exc) from exc

    # -- scheduler ops ------------------------------------------------------

    def _sched_rpc(self, msg):
        try:
            self.sched.send(msg)
            reply = self.sched.recv(timeout=_ENV["rpc_timeout"])
        except (OSError, ConnectionError) as exc:
            raise self._sched_lost(msg[0], exc) from exc
        return reply

    def barrier(self):
        """Block until every worker arrives — or raise :class:`PeerLost`
        when the scheduler declares a member dead (``barrier_failed``),
        the scheduler itself dies, or ``MXNET_PS_BARRIER_TIMEOUT_S``
        elapses.  A barrier that cannot complete never hangs."""
        try:
            self.sched.send(("barrier",))
        except (OSError, ConnectionError) as exc:
            raise self._sched_lost("barrier", exc) from exc
        limit = _ENV["barrier_timeout"]
        deadline = None if limit is None else time.monotonic() + limit
        while True:
            remaining = None if deadline is None \
                else max(0.05, deadline - time.monotonic())
            try:
                msg = self.sched.recv(timeout=remaining)
            except RPCTimeout as exc:
                raise self._sched_lost("barrier", exc,
                                       reason="barrier-timeout") from exc
            except (OSError, ConnectionError) as exc:
                raise self._sched_lost("barrier", exc) from exc
            if msg[0] == "barrier_done":
                return
            if msg[0] == "barrier_failed":
                _tel.bump("ps_peer_lost")
                raise PeerLost(
                    "barrier failed: worker(s) %s are dead" % (msg[1],),
                    role="worker", reason="dead-peers")

    def num_dead_nodes(self):
        """Workers whose control link dropped without finalizing
        (ref kvstore.h:328 get_num_dead_node)."""
        msg = self._sched_rpc(("num_dead",))
        assert msg[0] == "num_dead"
        return int(msg[1])

    def peer_health(self):
        """The scheduler's live peer table (also cached for /peers)."""
        msg = self._sched_rpc(("peers",))
        assert msg[0] == "peers"
        _set_peer_snapshot(msg[1])
        return msg[1]

    def fleet_health(self):
        """The scheduler's live fleet digest table (also cached for the
        /fleet endpoint — the deterministic, heartbeat-free way for a
        worker to refresh its fleet view)."""
        msg = self._sched_rpc(("fleet",))
        assert msg[0] == "fleet"
        _set_fleet_snapshot(msg[1])
        return msg[1]

    def refresh_servers(self, timeout=60.0):
        """Re-resolve the server address list and redial every server.

        Blocks (bounded by *timeout*) until the scheduler reports no
        dead server — i.e. a restarted server has re-registered into
        each dead rank — then replaces ALL server connections.  The
        caller is responsible for restoring shard state afterwards
        (``restore_server_state`` / the kvstore checkpoint protocol).
        """
        deadline = time.monotonic() + timeout
        last = None
        dead = []
        while True:
            msg = self._sched_rpc(("servers",))
            assert msg[0] == "servers"
            addrs = [None if a is None else tuple(a) for a in msg[1]]
            dead = list(msg[2])
            if not dead and all(a is not None for a in addrs):
                # DIAL-VERIFY before committing: right after a kill the
                # scheduler may not have noticed the death yet, so a
                # clean-looking list can still carry the dead server's
                # stale address — trusting it would leak a bare
                # ConnectionError out of the recovery path.  A bare
                # connect is NOT proof of life (the kernel completes
                # handshakes into a freshly-killed process's accept
                # queue for a brief teardown window, and self-connects
                # are rejected separately in Conn.connect), so each
                # verified conn must answer a ping round trip.
                conns, ok = [], True
                for a in addrs:
                    try:
                        conn = Conn.connect(a, retries=3, delay=0.05)
                        conn.send(("ping",))
                        reply = conn.recv(timeout=min(
                            _ENV["rpc_timeout"] or 5.0, 5.0))
                        if not (isinstance(reply, tuple) and reply
                                and reply[0] == "pong"):
                            raise ConnectionError(
                                "ping to %s:%s answered %r"
                                % (a[0], a[1], reply))
                        conns.append(conn)
                    except (OSError, ConnectionError) as exc:
                        last = exc
                        ok = False
                        break
                if ok:
                    for c in self.server_conns:
                        c.close()
                    self.server_addrs = addrs
                    self.server_conns = conns
                    _tel.bump("ps_reconnects")
                    _flight.record("peer_recovered", "servers",
                                   n=len(addrs))
                    return
                for c in conns:
                    c.close()
            if time.monotonic() > deadline:
                _tel.bump("ps_peer_lost")
                raise PeerLost(
                    "no (reachable) replacement for server(s) within "
                    "%.0fs (scheduler-reported dead: %s, last dial "
                    "error: %r)" % (timeout, dead, last), role="server",
                    reason="no-replacement")
            time.sleep(min(0.2, max(_ENV["heartbeat"], 0.05)))

    def reset_timestamps(self):
        """Zero the per-key push timestamps (recovery: after a server
        state restore cleared the pending buffers, every worker must
        restart from the same counter or sync merges mismatch)."""
        with self._lock:
            self._ts.clear()

    def finalize(self):
        # finalize FIRST, stop heartbeats AFTER the scheduler confirmed:
        # the scheduler treats a heartbeat-link drop from an unfinalized
        # rank as a death, so closing the hb conn before the finalize
        # frame is processed would race a clean exit into a spurious
        # dead-worker count (get_num_dead_node() != 0 on live peers)
        try:
            self.sched.send(("finalize",))
            self.sched.recv(timeout=_ENV["rpc_timeout"])
        except (OSError, ConnectionError):
            pass
        if self._hb_stop is not None:
            self._hb_stop.set()
        for c in self.server_conns:
            c.close()
        self.sched.close()
        try:      # MXNET_TRACE_DUMP_DIR: leave the --fleet artifact
            dump_trace_artifacts()
        except Exception:
            pass

    # -- kv ops -------------------------------------------------------------

    def init(self, key, arr):
        flat = np.asarray(arr).ravel()
        for sidx, (lo, hi) in placement(key, arr.shape, self.nservers):
            self._server_rpc(
                sidx, ("init", key, flat[lo:hi], arr.shape, (lo, hi)),
                idempotent=True)

    def push(self, key, arr, rows=None):
        with self._lock:
            ts = self._ts[key] = self._ts.get(key, -1) + 1
        if rows is not None:
            sidx = server_of_key(key, self.nservers)
            self._send_to(sidx, ("push", key, ts, np.asarray(arr),
                                 np.asarray(rows)))
            self._recv_from(sidx, "push")
            return
        flat = np.asarray(arr).ravel()
        plc = placement(key, arr.shape, self.nservers)
        for sidx, (lo, hi) in plc:
            self._send_to(sidx, ("push", key, ts, flat[lo:hi], None))
        for sidx, _ in plc:
            self._recv_from(sidx, "push")

    def pull(self, key, shape):
        # pipelined fast path: request every shard, THEN collect — a
        # key sharded over S servers pays ~max(RTT), not sum(RTT).
        # Any shard whose round fails falls back to the idempotent
        # retry machinery (fresh connection) for that server alone.
        plc = placement(key, shape, self.nservers)
        sent = set()
        for sidx, _ in plc:
            try:
                self.server_conns[sidx].send(("pull", key))
                sent.add(sidx)
            except (OSError, ConnectionError):
                pass                     # retried per-shard below
        shards = []
        for sidx, _ in plc:
            reply = None
            if sidx in sent:
                try:
                    reply = _check(self.server_conns[sidx].recv(
                        timeout=_ENV["rpc_timeout"]))
                except ProtocolError:
                    raise
                except (OSError, ConnectionError):
                    reply = None
            if reply is None:            # slow path: reconnect + retry
                reply = self._server_rpc(sidx, ("pull", key),
                                         idempotent=True)
            shards.append(reply)
        out = np.empty(int(np.prod(shape)), shards[0][1].dtype)
        for (_, (lo, hi)), (tag, val) in zip(plc, shards):
            assert tag == "val"
            out[lo:hi] = val
        return out.reshape(shape)

    def pull_rows(self, key, shape, rows):
        sidx = server_of_key(key, self.nservers)
        tag, val = self._server_rpc(
            sidx, ("pull_rows", key, np.asarray(rows, np.int64)),
            idempotent=True)
        assert tag == "val"
        return val

    def set_optimizer(self, optimizer):
        blob = pickle.dumps(optimizer, protocol=pickle.HIGHEST_PROTOCOL)
        for sidx in range(self.nservers):
            self._server_rpc(sidx, ("set_optimizer", blob),
                             idempotent=True)

    def set_sync(self, sync):
        for sidx in range(self.nservers):
            self._server_rpc(sidx, ("set_sync", sync), idempotent=True)

    # -- checkpoint-state protocol ------------------------------------------

    def server_state(self, sidx):
        """Opaque state blob of server *sidx* (store + updater state)."""
        tag, blob = self._server_rpc(sidx, ("get_state",),
                                     idempotent=True)
        assert tag == "state"
        return blob

    def restore_server_state(self, sidx, blob):
        """Pour a ``server_state`` blob back into server *sidx* (e.g. a
        restarted one); clears its sync-pending buffers."""
        self._server_rpc(sidx, ("set_state", blob), idempotent=True)
