"""RNN checkpoint helpers (ref python/mxnet/rnn/rnn.py).

The reference re-packs fused-cell weights on save/load
(``unpack_weights``/``pack_weights``); cells here keep the same hook so
the round-trip is cell-aware.
"""
from __future__ import annotations

from ..model import save_checkpoint, load_checkpoint

__all__ = ["save_rnn_checkpoint", "load_rnn_checkpoint", "do_rnn_checkpoint"]


def _cells_of(cells):
    return cells if isinstance(cells, (list, tuple)) else [cells]


def save_rnn_checkpoint(cells, prefix, epoch, symbol, arg_params, aux_params):
    """save_checkpoint with cell-unpacked weights (ref rnn.py:28)."""
    packed = dict(arg_params)
    for cell in _cells_of(cells):
        packed = cell.unpack_weights(packed)
    save_checkpoint(prefix, epoch, symbol, packed, aux_params)


def load_rnn_checkpoint(cells, prefix, epoch):
    """load_checkpoint, re-packing weights per cell (ref rnn.py:58)."""
    symbol, args, auxs = load_checkpoint(prefix, epoch)
    for cell in _cells_of(cells):
        args = cell.pack_weights(args)
    return symbol, args, auxs


def do_rnn_checkpoint(cells, prefix, period=1):
    """Epoch-end callback variant (ref rnn.py:86)."""
    period = max(1, int(period))

    def _callback(iter_no, sym=None, arg=None, aux=None):
        if (iter_no + 1) % period == 0:
            save_rnn_checkpoint(cells, prefix, iter_no + 1, sym, arg, aux)

    return _callback
