"""Symbolic RNN cells for the legacy ``mx.rnn`` API.

API parity with the reference ``python/mxnet/rnn/rnn_cell.py`` (BaseRNNCell
protocol, RNNParams, RNN/LSTM/GRU cells, FusedRNNCell over the fused RNN
op, Sequential/Bidirectional/Dropout/Residual/Zoneout wrappers). The gluon
cells (``gluon/rnn/rnn_cell.py``) are the eager/hybrid twins; these build
``Symbol`` graphs for BucketingModule workloads.

TPU notes: per-step unrolling is fine under jit (static length → XLA fuses
the whole sequence); FusedRNNCell lowers to the ``RNN`` op, whose
implementation is a ``lax.scan`` over packed parameters — the fast path for
long sequences.
"""
from __future__ import annotations

from .. import symbol as sym
from ..base import MXNetError

__all__ = ["RNNParams", "BaseRNNCell", "RNNCell", "LSTMCell", "GRUCell",
           "FusedRNNCell", "SequentialRNNCell", "BidirectionalCell",
           "DropoutCell", "ResidualCell", "ZoneoutCell"]


class RNNParams(object):
    """Container of shared symbol variables (ref rnn_cell.py:RNNParams)."""

    def __init__(self, prefix=""):
        self._prefix = prefix
        self._vars = {}

    def get(self, name, **kwargs):
        full = self._prefix + name
        if full not in self._vars:
            self._vars[full] = sym.var(full, **kwargs)
        return self._vars[full]


def _zero_state_like(step, width):
    """A (batch, width) zeros symbol whose batch dim follows *step*'s.

    Built from graph ops (sum-to-column × zero row) because symbol-time
    shapes don't know the batch size yet — the reference gets the same
    effect from 0-dim shape inference.
    """
    column = sym.sum(step * 0.0, axis=1, keepdims=True)     # (N, 1) zeros
    row = sym.zeros((1, width))
    return sym.broadcast_add(column, row)


def _slice_steps(inputs, length, layout):
    """Split a merged (N, T, C) / (T, N, C) symbol into per-step symbols."""
    if isinstance(inputs, (list, tuple)):
        return list(inputs)
    t_axis = layout.find("T")
    parts = sym.SliceChannel(inputs, num_outputs=length, axis=t_axis,
                             squeeze_axis=1)
    return [parts[i] for i in range(length)]


def _merge_steps(outputs, layout):
    t_axis = layout.find("T")
    expanded = [sym.expand_dims(o, axis=t_axis) for o in outputs]
    return sym.concat(*expanded, dim=t_axis)


class BaseRNNCell(object):
    """Symbolic recurrent-cell protocol (ref rnn_cell.py:BaseRNNCell)."""

    def __init__(self, prefix="", params=None):
        self._prefix = prefix
        self._own_params = params is None
        self.params = params if params is not None else RNNParams(prefix)
        self._modified = False
        self.reset()

    def reset(self):
        self._counter = -1
        self._init_counter = -1

    @property
    def state_info(self):
        raise NotImplementedError()

    @property
    def state_shape(self):
        return [info["shape"] for info in self.state_info]

    def begin_state(self, func=None, **kwargs):
        """Zero initial states; symbolic default derives batch-shaped zeros
        lazily inside unroll (func overrides)."""
        if self._modified:
            raise MXNetError("call begin_state on the outermost modifier")
        states = []
        for info in self.state_info:
            self._init_counter += 1
            if func is not None:
                spec = dict(info)
                spec.pop("__layout__", None)
                spec.update(kwargs)
                states.append(func(**spec))
            else:
                states.append(("__zeros__", info["shape"][-1]))
        return states

    def _materialize_states(self, states, step):
        """Resolve lazy ("__zeros__", width) placeholders against the first
        input step symbol."""
        out = []
        for s in states:
            if isinstance(s, tuple) and len(s) == 2 and s[0] == "__zeros__":
                out.append(_zero_state_like(step, s[1]))
            else:
                out.append(s)
        return out

    def __call__(self, inputs, states):
        raise NotImplementedError()

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None):
        """Build the length-step graph (ref rnn_cell.py:unroll)."""
        self.reset()
        steps = _slice_steps(inputs, length, layout)
        if begin_state is None:
            begin_state = self.begin_state()
        states = self._materialize_states(begin_state, steps[0])
        outputs = []
        for x in steps[:length]:
            out, states = self(x, states)
            outputs.append(out)
        if merge_outputs:
            return _merge_steps(outputs, layout), states
        return outputs, states

    def unpack_weights(self, args):
        return dict(args)

    def pack_weights(self, args):
        return dict(args)


class _GatedSymCell(BaseRNNCell):
    """Shared template for RNN/LSTM/GRU symbolic cells: owns the i2h/h2h
    parameter variables and the fused projections."""

    num_gates = 1
    num_states = 1

    def __init__(self, num_hidden, prefix=None, params=None):
        if prefix is None:
            prefix = "%s_" % self._alias()
        super().__init__(prefix, params)
        self._num_hidden = num_hidden
        for tag in ("i2h_weight", "i2h_bias", "h2h_weight", "h2h_bias"):
            setattr(self, "_" + tag, self.params.get(tag))

    @property
    def state_info(self):
        return [{"shape": (0, self._num_hidden), "__layout__": "NC"}
                for _ in range(self.num_states)]

    def __call__(self, inputs, states):
        self._counter += 1
        name = "%st%d_" % (self._prefix, self._counter)
        wide = self.num_gates * self._num_hidden
        i2h = sym.FullyConnected(inputs, self._i2h_weight, self._i2h_bias,
                                 num_hidden=wide, name=name + "i2h")
        h2h = sym.FullyConnected(states[0], self._h2h_weight, self._h2h_bias,
                                 num_hidden=wide, name=name + "h2h")
        return self._transition(i2h, h2h, states, name)

    def _transition(self, i2h, h2h, states, name):
        raise NotImplementedError()


class RNNCell(_GatedSymCell):
    """Elman cell (ref rnn_cell.py:RNNCell)."""

    num_gates = 1

    def __init__(self, num_hidden, activation="tanh", prefix=None,
                 params=None):
        super().__init__(num_hidden, prefix, params)
        self._activation = activation

    def _alias(self):
        return "rnn"

    def _transition(self, i2h, h2h, states, name):
        out = sym.Activation(i2h + h2h, act_type=self._activation,
                             name=name + "out")
        return out, [out]


class LSTMCell(_GatedSymCell):
    """LSTM cell, gate order i,f,g,o (ref rnn_cell.py:LSTMCell)."""

    num_gates = 4
    num_states = 2

    def __init__(self, num_hidden, prefix=None, params=None,
                 forget_bias=1.0):
        super().__init__(num_hidden, prefix, params)
        self._forget_bias = forget_bias

    def _alias(self):
        return "lstm"

    def _transition(self, i2h, h2h, states, name):
        pre = i2h + h2h
        gates = sym.SliceChannel(pre, num_outputs=4, name=name + "slice")
        i = sym.Activation(gates[0], act_type="sigmoid")
        f = sym.Activation(gates[1] + self._forget_bias, act_type="sigmoid")
        g = sym.Activation(gates[2], act_type="tanh")
        o = sym.Activation(gates[3], act_type="sigmoid")
        c = f * states[1] + i * g
        h = o * sym.Activation(c, act_type="tanh")
        return h, [h, c]


class GRUCell(_GatedSymCell):
    """GRU cell, gate order r,z,n (ref rnn_cell.py:GRUCell)."""

    num_gates = 3

    def _alias(self):
        return "gru"

    def _transition(self, i2h, h2h, states, name):
        ir, iz, in_ = [sym.SliceChannel(i2h, num_outputs=3)[k]
                       for k in range(3)]
        hr, hz, hn = [sym.SliceChannel(h2h, num_outputs=3)[k]
                      for k in range(3)]
        r = sym.Activation(ir + hr, act_type="sigmoid")
        z = sym.Activation(iz + hz, act_type="sigmoid")
        cand = sym.Activation(in_ + r * hn, act_type="tanh")
        out = (1.0 - z) * cand + z * states[0]
        return out, [out]


class FusedRNNCell(BaseRNNCell):
    """Whole-sequence fused cell over the ``RNN`` op (ref
    rnn_cell.py:FusedRNNCell; the op itself is a lax.scan —
    ``ops/nn.py:_rnn``). ``unroll`` consumes the merged sequence in one op
    call instead of per-step graphs."""

    def __init__(self, num_hidden, num_layers=1, mode="lstm",
                 bidirectional=False, dropout=0.0, get_next_state=False,
                 prefix=None, params=None):
        if prefix is None:
            prefix = "%s_" % mode
        super().__init__(prefix, params)
        self._num_hidden = num_hidden
        self._num_layers = num_layers
        self._mode = mode
        self._bidirectional = bidirectional
        self._dropout = dropout
        self._get_next_state = get_next_state
        self._param = self.params.get("parameters")

    def _alias(self):
        return self._mode

    @property
    def state_info(self):
        d = 2 if self._bidirectional else 1
        shape = (d * self._num_layers, 0, self._num_hidden)
        infos = [{"shape": shape, "__layout__": "LNC"}]
        if self._mode == "lstm":
            infos.append({"shape": shape, "__layout__": "LNC"})
        return infos

    def __call__(self, inputs, states):
        raise MXNetError("FusedRNNCell cannot be stepped; use unroll")

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None):
        self.reset()
        if isinstance(inputs, (list, tuple)):
            inputs = _merge_steps(list(inputs), layout)
        if layout == "NTC":                     # RNN op wants time-major
            inputs = sym.swapaxes(inputs, dim1=0, dim2=1)

        if begin_state is None:
            d = 2 if self._bidirectional else 1
            width = self._num_hidden
            anchor = sym.sum(inputs * 0.0, axis=[0, 2], keepdims=False)
            # anchor: (N,) zeros → (L*d, N, H) zeros
            state0 = sym.broadcast_add(
                sym.reshape(anchor, shape=(1, -1, 1)),
                sym.zeros((d * self._num_layers, 1, width)))
            states = [state0, state0] if self._mode == "lstm" else [state0]
        else:
            states = begin_state

        args = [inputs, self._param] + list(states)
        out = sym.RNN(*args, state_size=self._num_hidden,
                      num_layers=self._num_layers,
                      bidirectional=self._bidirectional, mode=self._mode,
                      p=self._dropout, state_outputs=self._get_next_state,
                      name=self._prefix + "rnn")
        if self._get_next_state:
            outputs = out[0]
            next_states = [out[i] for i in range(1, len(self.state_info) + 1)]
        else:
            outputs, next_states = out, []
        if layout == "NTC":
            outputs = sym.swapaxes(outputs, dim1=0, dim2=1)
        if merge_outputs is False:
            t_axis = layout.find("T")
            parts = sym.SliceChannel(outputs, num_outputs=length, axis=t_axis,
                                     squeeze_axis=1)
            outputs = [parts[i] for i in range(length)]
        return outputs, next_states

    def unfuse(self):
        """Equivalent stack of unfused cells (ref rnn_cell.py:unfuse)."""
        stack = SequentialRNNCell()
        make = {"rnn_relu": lambda p: RNNCell(self._num_hidden, "relu", p),
                "rnn_tanh": lambda p: RNNCell(self._num_hidden, "tanh", p),
                "lstm": lambda p: LSTMCell(self._num_hidden, p),
                "gru": lambda p: GRUCell(self._num_hidden, p)}[self._mode]
        for layer in range(self._num_layers):
            prefix = "%sl%d_" % (self._prefix, layer)
            if self._bidirectional:
                stack.add(BidirectionalCell(
                    make(prefix + "l_"), make(prefix + "r_")))
            else:
                stack.add(make(prefix))
            if self._dropout > 0 and layer != self._num_layers - 1:
                stack.add(DropoutCell(self._dropout,
                                      prefix="%s_dropout%d_" % (self._prefix,
                                                                layer)))
        return stack


class SequentialRNNCell(BaseRNNCell):
    """Vertical stack of cells (ref rnn_cell.py:SequentialRNNCell)."""

    def __init__(self, params=None):
        super().__init__(prefix="", params=params)
        self._cells = []

    def add(self, cell):
        self._cells.append(cell)

    @property
    def state_info(self):
        return [info for c in self._cells for info in c.state_info]

    def begin_state(self, **kwargs):
        if self._modified:
            raise MXNetError("call begin_state on the outermost modifier")
        return [s for c in self._cells for s in c.begin_state(**kwargs)]

    def _per_cell_states(self, states):
        at = 0
        for cell in self._cells:
            width = len(cell.state_info)
            yield cell, states[at:at + width]
            at += width

    def __call__(self, inputs, states):
        self._counter += 1
        collected = []
        states = self._materialize_states(states, inputs)
        for cell, sub in self._per_cell_states(states):
            inputs, sub = cell(inputs, sub)
            collected += sub
        return inputs, collected

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None):
        self.reset()
        if begin_state is None:
            begin_state = self.begin_state()
        seq = inputs
        collected = []
        last = len(self._cells) - 1
        for pos, (cell, sub) in enumerate(
                self._per_cell_states(begin_state)):
            seq, sub = cell.unroll(
                length, inputs=seq, begin_state=sub, layout=layout,
                merge_outputs=merge_outputs if pos == last else None)
            collected += sub
        return seq, collected


class BidirectionalCell(BaseRNNCell):
    """Two cells over opposite directions (ref rnn_cell.py:
    BidirectionalCell)."""

    def __init__(self, l_cell, r_cell, params=None, output_prefix="bi_"):
        super().__init__("", params)
        self._l_cell, self._r_cell = l_cell, r_cell
        self._output_prefix = output_prefix

    @property
    def state_info(self):
        return self._l_cell.state_info + self._r_cell.state_info

    def begin_state(self, **kwargs):
        return (self._l_cell.begin_state(**kwargs)
                + self._r_cell.begin_state(**kwargs))

    def __call__(self, inputs, states):
        raise MXNetError("BidirectionalCell cannot be stepped; use unroll")

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None):
        self.reset()
        steps = _slice_steps(inputs, length, layout)
        if begin_state is None:
            begin_state = self.begin_state()
        split = len(self._l_cell.state_info)
        fwd, fwd_states = self._l_cell.unroll(
            length, steps, begin_state[:split], layout, merge_outputs=False)
        bwd, bwd_states = self._r_cell.unroll(
            length, steps[::-1], begin_state[split:], layout,
            merge_outputs=False)
        joined = [sym.concat(f, b, dim=1,
                             name="%sout%d" % (self._output_prefix, t))
                  for t, (f, b) in enumerate(zip(fwd, bwd[::-1]))]
        if merge_outputs:
            return _merge_steps(joined, layout), fwd_states + bwd_states
        return joined, fwd_states + bwd_states


class DropoutCell(BaseRNNCell):
    """Stateless dropout pseudo-cell (ref rnn_cell.py:DropoutCell)."""

    def __init__(self, dropout, prefix="dropout_", params=None):
        super().__init__(prefix, params)
        self.dropout = dropout

    @property
    def state_info(self):
        return []

    def __call__(self, inputs, states):
        self._counter += 1
        if self.dropout > 0:
            inputs = sym.Dropout(inputs, p=self.dropout)
        return inputs, states


class ModifierCell(BaseRNNCell):
    """Wrap-and-share-params base (ref rnn_cell.py:ModifierCell)."""

    def __init__(self, base_cell):
        if base_cell._modified:
            raise MXNetError("cell is already modified")
        base_cell._modified = True
        super().__init__(base_cell._prefix + "mod_", params=base_cell.params)
        self.base_cell = base_cell

    @property
    def state_info(self):
        return self.base_cell.state_info

    def begin_state(self, **kwargs):
        self.base_cell._modified = False
        try:
            return self.base_cell.begin_state(**kwargs)
        finally:
            self.base_cell._modified = True


class ResidualCell(ModifierCell):
    """output = cell(input) + input (ref rnn_cell.py:ResidualCell)."""

    def __call__(self, inputs, states):
        out, states = self.base_cell(inputs, states)
        return out + inputs, states


class ZoneoutCell(ModifierCell):
    """Zoneout over outputs/states (ref rnn_cell.py:ZoneoutCell)."""

    def __init__(self, base_cell, zoneout_outputs=0.0, zoneout_states=0.0):
        super().__init__(base_cell)
        self.zoneout_outputs = zoneout_outputs
        self.zoneout_states = zoneout_states
        self._prev_output = None

    def reset(self):
        super().reset()
        self._prev_output = None

    def __call__(self, inputs, states):
        out, new_states = self.base_cell(inputs, states)

        def mixed(p, new, old):
            mask = sym.Dropout(sym.ones_like(new), p=p)
            return sym.where(mask, new, old)

        prior = self._prev_output if self._prev_output is not None \
            else sym.zeros_like(out)
        if self.zoneout_outputs > 0:
            out = mixed(self.zoneout_outputs, out, prior)
        if self.zoneout_states > 0:
            new_states = [mixed(self.zoneout_states, ns, os)
                          for ns, os in zip(new_states, states)]
        self._prev_output = out
        return out, new_states
