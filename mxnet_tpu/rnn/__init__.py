"""``mx.rnn``: symbolic RNN cells + bucketed sequence IO.

Parity surface: reference ``python/mxnet/rnn/`` (rnn_cell.py, io.py,
rnn.py checkpoint helpers) — the toolkit behind
``example/rnn/lstm_bucketing.py`` (BASELINE workload #3).
"""
from .rnn_cell import (RNNParams, BaseRNNCell, RNNCell, LSTMCell, GRUCell,
                       FusedRNNCell, SequentialRNNCell, BidirectionalCell,
                       DropoutCell, ResidualCell, ZoneoutCell)
from .io import BucketSentenceIter, encode_sentences
from .rnn import save_rnn_checkpoint, load_rnn_checkpoint, do_rnn_checkpoint

__all__ = ["RNNParams", "BaseRNNCell", "RNNCell", "LSTMCell", "GRUCell",
           "FusedRNNCell", "SequentialRNNCell", "BidirectionalCell",
           "DropoutCell", "ResidualCell", "ZoneoutCell",
           "BucketSentenceIter", "encode_sentences",
           "save_rnn_checkpoint", "load_rnn_checkpoint", "do_rnn_checkpoint"]
