"""Bucketed sequence iterators for symbolic RNN training.

API parity with the reference ``python/mxnet/rnn/io.py`` (BucketSentenceIter
+ encode_sentences) — the data side of the PTB lstm_bucketing workload
(SURVEY §5.7). TPU note: each bucket key is one static-shape jit
specialization, so a handful of buckets means a handful of cached XLA
programs (the bucketing doctrine the reference implements with per-bucket
executors).
"""
from __future__ import annotations

import bisect

import numpy as np

from .. import random as _random
from ..io import DataBatch, DataDesc, DataIter

__all__ = ["BucketSentenceIter", "encode_sentences"]


def encode_sentences(sentences, vocab=None, invalid_label=-1,
                     invalid_key="\n", start_label=0):
    """Map token sequences to integer ids, growing *vocab* as needed
    (ref rnn/io.py:encode_sentences)."""
    if vocab is None:
        vocab = {invalid_key: invalid_label}
    next_id = start_label
    taken = set(vocab.values())
    encoded = []
    for sent in sentences:
        row = []
        for word in sent:
            if word not in vocab:
                while next_id in taken:
                    next_id += 1
                vocab[word] = next_id
                taken.add(next_id)
            row.append(vocab[word])
        encoded.append(row)
    return encoded, vocab


def _default_buckets(sentences, count=5):
    """Pick bucket lengths from the sentence-length distribution."""
    lengths = sorted(len(s) for s in sentences if s)
    if not lengths:
        return []
    qs = sorted({lengths[min(len(lengths) - 1,
                             int(len(lengths) * q / count))]
                 for q in range(1, count + 1)})
    return qs


class BucketSentenceIter(DataIter):
    """Pads each sentence into the smallest bucket that fits and serves
    fixed-shape batches per bucket (ref rnn/io.py:BucketSentenceIter).

    Labels are the next-token shift of the data, padded with
    ``invalid_label``.
    """

    def __init__(self, sentences, batch_size, buckets=None, invalid_label=-1,
                 data_name="data", label_name="softmax_label", dtype="float32",
                 layout="NT"):
        super().__init__(batch_size)
        self.data_name, self.label_name = data_name, label_name
        self.dtype = dtype
        self.layout = layout
        self.invalid_label = invalid_label
        if buckets is None:
            buckets = _default_buckets(sentences)
        self.buckets = sorted(buckets)

        # per-bucket padded data matrices
        per_bucket = [[] for _ in self.buckets]
        discarded = 0
        for sent in sentences:
            if not sent:
                continue
            slot = bisect.bisect_left(self.buckets, len(sent))
            if slot == len(self.buckets):
                discarded += 1
                continue
            padded = np.full(self.buckets[slot], invalid_label,
                             dtype=self.dtype)
            padded[:len(sent)] = sent
            per_bucket[slot].append(padded)
        if discarded:
            import logging
            logging.warning("discarded %d sentences longer than the largest "
                            "bucket", discarded)
        self.data = [np.asarray(rows, dtype=self.dtype) if rows
                     else np.zeros((0, b), dtype=self.dtype)
                     for rows, b in zip(per_bucket, self.buckets)]

        self.batch_size = batch_size
        self.default_bucket_key = max(self.buckets)
        self._plan = []          # (bucket_idx, row_offset) per batch
        self._order = None
        self.major_axis = layout.find("N")
        self.provide_data = [DataDesc(
            data_name, self._shape_for(self.default_bucket_key),
            layout=layout)]
        self.provide_label = [DataDesc(
            label_name, self._shape_for(self.default_bucket_key),
            layout=layout)]
        self.idx = None
        self.reset()

    def _shape_for(self, seq_len):
        if self.major_axis == 0:
            return (self.batch_size, seq_len)
        return (seq_len, self.batch_size)

    def reset(self):
        # both shuffles draw from the framework RNG so mx.random.seed
        # makes epoch order reproducible (JG005)
        rng = _random.host_rng()
        self._plan = []
        for b, rows in enumerate(self.data):
            rng.shuffle(rows)               # row order within bucket
            for start in range(0, len(rows) - self.batch_size + 1,
                               self.batch_size):
                self._plan.append((b, start))
        order = rng.permutation(len(self._plan))
        self._plan = [self._plan[i] for i in order]
        self._cursor = 0

    def next(self):
        if self._cursor >= len(self._plan):
            raise StopIteration
        bucket_idx, start = self._plan[self._cursor]
        self._cursor += 1
        rows = self.data[bucket_idx][start:start + self.batch_size]
        seq_len = self.buckets[bucket_idx]

        labels = np.full_like(rows, self.invalid_label)
        labels[:, :-1] = rows[:, 1:]
        if self.major_axis == 1:      # TN layout
            rows, labels = rows.T, labels.T

        from .. import ndarray as nd
        return DataBatch(
            [nd.array(rows)], [nd.array(labels)], pad=0,
            bucket_key=seq_len,
            provide_data=[DataDesc(self.data_name, rows.shape,
                                   layout=self.layout)],
            provide_label=[DataDesc(self.label_name, labels.shape,
                                    layout=self.layout)])
