/* XS half of AI::MXNetTPU — the Perl binding over the general C ABI
 * (native/include/mxnet_tpu_c.h).
 *
 * Reference counterpart: perl-package/AI-MXNet (AI::MXNet), whose
 * AI::MXNetCAPI swig layer binds include/mxnet/c_api.h. Here the same
 * role is a hand-written XS module: handles cross as IVs (PTR2IV /
 * INT2PTR), arrays as Perl arrayrefs of doubles.
 */
#define PERL_NO_GET_CONTEXT
#include "EXTERN.h"
#include "perl.h"
#include "XSUB.h"

#include "mxnet_tpu_c.h"

static void croak_last(pTHX) {
  croak("mxnet_tpu: %s", MXGetLastError());
}

/* The scalar marshalling below is float32-only; other dtypes would
 * reinterpret (or overflow) the staging buffer. */
static void assert_f32(pTHX_ NDArrayHandle h) {
  int dtype = -1;
  if (MXNDArrayGetDType(h, &dtype) != 0) croak_last(aTHX);
  if (dtype != 0)
    croak("mxnet_tpu: perl marshalling supports float32 only "
          "(got dtype code %d); cast the array first", dtype);
}

MODULE = AI::MXNetTPU    PACKAGE = AI::MXNetTPU

PROTOTYPES: DISABLE

IV
_nd_create(shape_ref)
    SV* shape_ref
  PREINIT:
    AV* av;
    mx_uint dims[8];
    mx_uint nd, i;
    NDArrayHandle h;
  CODE:
    av = (AV*)SvRV(shape_ref);
    nd = (mx_uint)(av_len(av) + 1);
    if (nd > 8) croak("ndim > 8");
    for (i = 0; i < nd; ++i)
      dims[i] = (mx_uint)SvUV(*av_fetch(av, i, 0));
    if (MXNDArrayCreateEx(dims, nd, 1, 0, 0, 0, &h) != 0)
      croak_last(aTHX);
    RETVAL = PTR2IV(h);
  OUTPUT:
    RETVAL

void
_nd_free(h)
    IV h
  CODE:
    MXNDArrayFree(INT2PTR(NDArrayHandle, h));

SV*
_nd_shape(h)
    IV h
  PREINIT:
    mx_uint nd, i;
    const mx_uint* dims;
    AV* out;
  CODE:
    if (MXNDArrayGetShape(INT2PTR(NDArrayHandle, h), &nd, &dims) != 0)
      croak_last(aTHX);
    out = newAV();
    for (i = 0; i < nd; ++i) av_push(out, newSVuv(dims[i]));
    RETVAL = newRV_noinc((SV*)out);
  OUTPUT:
    RETVAL

void
_nd_set(h, vals_ref)
    IV h
    SV* vals_ref
  PREINIT:
    AV* av;
    float* buf;
    size_t n, i;
  CODE:
    assert_f32(aTHX_ INT2PTR(NDArrayHandle, h));
    av = (AV*)SvRV(vals_ref);
    n = (size_t)(av_len(av) + 1);
    Newx(buf, n, float);
    for (i = 0; i < n; ++i)
      buf[i] = (float)SvNV(*av_fetch(av, (SSize_t)i, 0));
    if (MXNDArraySyncCopyFromCPU(INT2PTR(NDArrayHandle, h), buf, n)
        != 0) {
      Safefree(buf);
      croak_last(aTHX);
    }
    Safefree(buf);

SV*
_nd_get(h)
    IV h
  PREINIT:
    mx_uint nd, i;
    const mx_uint* dims;
    size_t n;
    float* buf;
    AV* out;
  CODE:
    assert_f32(aTHX_ INT2PTR(NDArrayHandle, h));
    if (MXNDArrayGetShape(INT2PTR(NDArrayHandle, h), &nd, &dims) != 0)
      croak_last(aTHX);
    n = 1;
    for (i = 0; i < nd; ++i) n *= dims[i];
    Newx(buf, n, float);
    if (MXNDArraySyncCopyToCPU(INT2PTR(NDArrayHandle, h), buf, n) != 0) {
      Safefree(buf);
      croak_last(aTHX);
    }
    out = newAV();
    for (i = 0; i < n; ++i) av_push(out, newSVnv(buf[i]));
    Safefree(buf);
    RETVAL = newRV_noinc((SV*)out);
  OUTPUT:
    RETVAL

SV*
_invoke(op, ins_ref, keys_ref, vals_ref)
    const char* op
    SV* ins_ref
    SV* keys_ref
    SV* vals_ref
  PREINIT:
    AV *ins, *keys, *vals;
    NDArrayHandle in_h[16];
    const char* pk[16];
    const char* pv[16];
    int n_in, n_par, i, n_out;
    NDArrayHandle* outs;
    AV* result;
  CODE:
    ins = (AV*)SvRV(ins_ref);
    keys = (AV*)SvRV(keys_ref);
    vals = (AV*)SvRV(vals_ref);
    n_in = (int)(av_len(ins) + 1);
    n_par = (int)(av_len(keys) + 1);
    if (n_in > 16 || n_par > 16) croak("too many inputs/params");
    for (i = 0; i < n_in; ++i)
      in_h[i] = INT2PTR(NDArrayHandle, SvIV(*av_fetch(ins, i, 0)));
    for (i = 0; i < n_par; ++i) {
      pk[i] = SvPV_nolen(*av_fetch(keys, i, 0));
      pv[i] = SvPV_nolen(*av_fetch(vals, i, 0));
    }
    n_out = 0;
    outs = NULL;
    if (MXImperativeInvoke(op, n_in, in_h, &n_out, &outs, n_par, pk, pv)
        != 0)
      croak_last(aTHX);
    result = newAV();
    for (i = 0; i < n_out; ++i)
      av_push(result, newSViv(PTR2IV(outs[i])));
    free(outs);
    RETVAL = newRV_noinc((SV*)result);
  OUTPUT:
    RETVAL

IV
_sym_from_file(path)
    const char* path
  PREINIT:
    SymbolHandle s;
  CODE:
    if (MXSymbolCreateFromFile(path, &s) != 0) croak_last(aTHX);
    RETVAL = PTR2IV(s);
  OUTPUT:
    RETVAL

IV
_sym_from_json(json)
    const char* json
  PREINIT:
    SymbolHandle s;
  CODE:
    if (MXSymbolCreateFromJSON(json, &s) != 0) croak_last(aTHX);
    RETVAL = PTR2IV(s);
  OUTPUT:
    RETVAL

void
_sym_free(h)
    IV h
  CODE:
    MXSymbolFree(INT2PTR(SymbolHandle, h));

SV*
_sym_arguments(h)
    IV h
  PREINIT:
    mx_uint n, i;
    const char** names;
    AV* out;
  CODE:
    if (MXSymbolListArguments(INT2PTR(SymbolHandle, h), &n, &names) != 0)
      croak_last(aTHX);
    out = newAV();
    for (i = 0; i < n; ++i) av_push(out, newSVpv(names[i], 0));
    RETVAL = newRV_noinc((SV*)out);
  OUTPUT:
    RETVAL

IV
_exec_bind(sym, names_ref, shapes_ref, grad_req)
    IV sym
    SV* names_ref
    SV* shapes_ref
    const char* grad_req
  PREINIT:
    AV *names, *shapes, *shp;
    const char* pk[16];
    mx_uint ndims[16];
    mx_uint dims[64];
    mx_uint n, i, j, off;
    ExecutorHandle ex;
  CODE:
    names = (AV*)SvRV(names_ref);
    shapes = (AV*)SvRV(shapes_ref);
    n = (mx_uint)(av_len(names) + 1);
    if (n > 16) croak("too many bind args");
    off = 0;
    for (i = 0; i < n; ++i) {
      pk[i] = SvPV_nolen(*av_fetch(names, i, 0));
      shp = (AV*)SvRV(*av_fetch(shapes, i, 0));
      ndims[i] = (mx_uint)(av_len(shp) + 1);
      for (j = 0; j < ndims[i]; ++j) {
        if (off >= 64) croak("too many total dims");
        dims[off++] = (mx_uint)SvUV(*av_fetch(shp, j, 0));
      }
    }
    if (MXExecutorSimpleBind(INT2PTR(SymbolHandle, sym), 1, 0, n, pk,
                             ndims, dims, grad_req, &ex) != 0)
      croak_last(aTHX);
    RETVAL = PTR2IV(ex);
  OUTPUT:
    RETVAL

void
_exec_free(h)
    IV h
  CODE:
    MXExecutorFree(INT2PTR(ExecutorHandle, h));

void
_exec_forward(h, is_train)
    IV h
    int is_train
  CODE:
    if (MXExecutorForward(INT2PTR(ExecutorHandle, h), is_train) != 0)
      croak_last(aTHX);

SV*
_exec_outputs(h)
    IV h
  PREINIT:
    mx_uint n, i;
    NDArrayHandle* outs;
    AV* out;
  CODE:
    if (MXExecutorOutputs(INT2PTR(ExecutorHandle, h), &n, &outs) != 0)
      croak_last(aTHX);
    out = newAV();
    for (i = 0; i < n; ++i) av_push(out, newSViv(PTR2IV(outs[i])));
    free(outs);
    RETVAL = newRV_noinc((SV*)out);
  OUTPUT:
    RETVAL

IV
_exec_arg(h, name)
    IV h
    const char* name
  PREINIT:
    NDArrayHandle a;
  CODE:
    if (MXExecutorArgArray(INT2PTR(ExecutorHandle, h), name, &a) != 0)
      croak_last(aTHX);
    RETVAL = PTR2IV(a);
  OUTPUT:
    RETVAL

void
_exec_copy_params(h, names_ref, handles_ref)
    IV h
    SV* names_ref
    SV* handles_ref
  PREINIT:
    AV *names, *handles;
    const char* pk[64];
    NDArrayHandle hs[64];
    mx_uint n, i;
  CODE:
    names = (AV*)SvRV(names_ref);
    handles = (AV*)SvRV(handles_ref);
    n = (mx_uint)(av_len(names) + 1);
    if (n > 64) croak("too many params");
    for (i = 0; i < n; ++i) {
      pk[i] = SvPV_nolen(*av_fetch(names, i, 0));
      hs[i] = INT2PTR(NDArrayHandle, SvIV(*av_fetch(handles, i, 0)));
    }
    if (MXExecutorCopyParamsFrom(INT2PTR(ExecutorHandle, h), n, pk, hs)
        != 0)
      croak_last(aTHX);

void
_load(path)
    const char* path
  PREINIT:
    mx_uint n, nn, i;
    NDArrayHandle* arrs;
    const char** names;
    AV *h_out, *n_out;
  PPCODE:
    if (MXNDArrayLoad(path, &n, &arrs, &nn, &names) != 0)
      croak_last(aTHX);
    h_out = newAV();
    n_out = newAV();
    for (i = 0; i < n; ++i) av_push(h_out, newSViv(PTR2IV(arrs[i])));
    for (i = 0; i < nn; ++i) av_push(n_out, newSVpv(names[i], 0));
    free(arrs);
    XPUSHs(sv_2mortal(newRV_noinc((SV*)h_out)));
    XPUSHs(sv_2mortal(newRV_noinc((SV*)n_out)));
