#!/usr/bin/perl
# End-to-end Perl-binding test: NDArray math, imperative ops, and
# symbol load -> bind -> forward on a saved -symbol.json + .params
# pair written by the Python side (paths come in via ARGV/ENV).
use strict;
use warnings;
use Test::More;
use FindBin;
use lib "$FindBin::Bin/../blib/lib", "$FindBin::Bin/../blib/arch";

use_ok('AI::MXNetTPU');

# ---- NDArray + imperative invoke ----
my $x = AI::MXNetTPU::NDArray->new([2, 3]);
$x->set([-3, -2, -1, 1, 2, 3]);
is_deeply($x->shape, [2, 3], 'shape round trip');

my ($y) = AI::MXNetTPU::invoke('relu', [$x]);
is_deeply($y->aslist, [0, 0, 0, 1, 2, 3], 'relu through the C ABI');

my ($t) = AI::MXNetTPU::invoke('transpose', [$x],
                               { axes => '(1, 0)' });
is_deeply($t->shape, [3, 2], 'attrs travel stringified');

# ---- symbol -> executor, with a checkpoint, if given ----
my ($sym_file, $param_file) = @ARGV;
SKIP: {
    skip 'no model files supplied', 4 unless $sym_file && -e $sym_file;
    my $sym = AI::MXNetTPU::Symbol->load($sym_file);
    my $args = $sym->list_arguments;
    ok(scalar(@$args) >= 3, 'symbol lists arguments');

    my $exec = $sym->simple_bind({ data => [2, 4] });
    my $params = AI::MXNetTPU::load_params($param_file);
    $exec->copy_params_from($params);

    my $data = $exec->arg('data');
    $data->set([0.1, 0.2, 0.3, 0.4, 0.5, 0.6, 0.7, 0.8]);
    $exec->forward(0);
    my $out = $exec->outputs->[0];
    my $vals = $out->aslist;
    is(scalar(@$vals), 2 * 3, 'output shape 2x3');
    my $sum = 0;
    $sum += $_ for @$vals[0 .. 2];
    ok(abs($sum - 1.0) < 1e-4, 'softmax row sums to 1');
    ok((grep { $_ > 0 } @$vals) == scalar(@$vals), 'probabilities > 0');
}

done_testing();
