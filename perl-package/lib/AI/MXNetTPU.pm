package AI::MXNetTPU;

# Perl binding for the TPU-native MXNet-capability framework.
#
# Reference counterpart: perl-package/AI-MXNet (AI::MXNet) over the
# swig'd AI::MXNetCAPI. This rebuild is a compact XS module over the
# general C ABI (native/include/mxnet_tpu_c.h): NDArray, imperative
# invoke, symbol load, executor bind/forward — the inference-and-scoring
# surface a Perl host realistically needs.
#
#   use AI::MXNetTPU;
#   my $x = AI::MXNetTPU::NDArray->new([2, 3]);
#   $x->set([1 .. 6]);
#   my ($y) = AI::MXNetTPU::invoke("relu", [$x]);
#   my $sym  = AI::MXNetTPU::Symbol->load("net-symbol.json");
#   my $exec = $sym->simple_bind({ data => [8, 1, 16, 16] });

use strict;
use warnings;

our $VERSION = '0.05';

require XSLoader;
XSLoader::load('AI::MXNetTPU', $VERSION);

sub invoke {
    my ($op, $inputs, $attrs) = @_;
    $attrs ||= {};
    my @keys = sort keys %$attrs;
    my @vals = map { "$attrs->{$_}" } @keys;
    my $outs = _invoke($op, [map { $_->{h} } @$inputs], \@keys, \@vals);
    return map { AI::MXNetTPU::NDArray->_wrap($_) } @$outs;
}

sub load_params {
    my ($path) = @_;
    my ($handles, $names) = _load($path);
    my %out;
    for my $i (0 .. $#$handles) {
        $out{ $names->[$i] // $i } =
            AI::MXNetTPU::NDArray->_wrap($handles->[$i]);
    }
    return \%out;
}

package AI::MXNetTPU::NDArray;

sub new {
    my ($class, $shape) = @_;
    return bless { h => AI::MXNetTPU::_nd_create($shape), own => 1 },
        $class;
}

sub _wrap {
    my ($class, $h) = @_;
    return bless { h => $h, own => 1 }, $class;
}

sub set   { AI::MXNetTPU::_nd_set($_[0]{h}, $_[1]); $_[0] }
sub aslist { AI::MXNetTPU::_nd_get($_[0]{h}) }
sub shape { AI::MXNetTPU::_nd_shape($_[0]{h}) }

sub DESTROY {
    my ($self) = @_;
    AI::MXNetTPU::_nd_free($self->{h}) if $self->{own};
}

package AI::MXNetTPU::Symbol;

sub load {
    my ($class, $path) = @_;
    return bless { h => AI::MXNetTPU::_sym_from_file($path) }, $class;
}

sub from_json {
    my ($class, $json) = @_;
    return bless { h => AI::MXNetTPU::_sym_from_json($json) }, $class;
}

sub list_arguments { AI::MXNetTPU::_sym_arguments($_[0]{h}) }

sub simple_bind {
    my ($self, $shapes, $grad_req) = @_;
    my @names  = sort keys %$shapes;
    my @dims   = map { $shapes->{$_} } @names;
    my $h = AI::MXNetTPU::_exec_bind($self->{h}, \@names, \@dims,
                                     $grad_req || 'null');
    return bless { h => $h }, 'AI::MXNetTPU::Executor';
}

sub DESTROY { AI::MXNetTPU::_sym_free($_[0]{h}) }

package AI::MXNetTPU::Executor;

sub forward {
    my ($self, $is_train) = @_;
    AI::MXNetTPU::_exec_forward($self->{h}, $is_train ? 1 : 0);
    $self;
}

sub outputs {
    my ($self) = @_;
    return [map { AI::MXNetTPU::NDArray->_wrap($_) }
            @{ AI::MXNetTPU::_exec_outputs($self->{h}) }];
}

sub arg {
    my ($self, $name) = @_;
    return AI::MXNetTPU::NDArray->_wrap(
        AI::MXNetTPU::_exec_arg($self->{h}, $name));
}

sub copy_params_from {
    my ($self, $params) = @_;    # { name => NDArray }
    my @names = sort keys %$params;
    AI::MXNetTPU::_exec_copy_params(
        $self->{h}, \@names, [map { $params->{$_}{h} } @names]);
    $self;
}

sub DESTROY { AI::MXNetTPU::_exec_free($_[0]{h}) }

1;
