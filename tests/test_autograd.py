"""Autograd tests (modeled on reference tests/python/unittest/test_autograd.py)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd, autograd
from mxnet_tpu.base import MXNetError


def test_simple_grad():
    x = nd.array([1., 2., 3.])
    x.attach_grad()
    with autograd.record():
        y = (x * x * 2).sum()
    y.backward()
    np.testing.assert_allclose(x.grad.asnumpy(), 4 * x.asnumpy())


def test_chain_and_branches():
    x = nd.array([[1., 2.], [3., 4.]])
    x.attach_grad()
    with autograd.record():
        y = x * 2
        z = y * x + y.sum()
        out = z.sum()
    out.backward()
    # d/dx [2x^2 + sum(2x)*n_elements...] -> 4x + 2*4 per element? compute numerically
    eps = 1e-3
    xe = x.asnumpy()
    def f(v):
        y = v * 2
        return (y * v + y.sum()).sum()
    num = np.zeros_like(xe)
    for i in np.ndindex(*xe.shape):
        p = xe.copy(); p[i] += eps
        m = xe.copy(); m[i] -= eps
        num[i] = (f(p) - f(m)) / (2 * eps)
    np.testing.assert_allclose(x.grad.asnumpy(), num, rtol=1e-2)


def test_head_gradient():
    x = nd.array([1., 2.])
    x.attach_grad()
    with autograd.record():
        y = x * 3
    y.backward(nd.array([10., 100.]))
    np.testing.assert_allclose(x.grad.asnumpy(), [30., 300.])


def test_grad_req_add():
    x = nd.array([1., 2.])
    x.attach_grad(grad_req="add")
    for _ in range(2):
        with autograd.record():
            y = (x * x).sum()
        y.backward()
    np.testing.assert_allclose(x.grad.asnumpy(), 2 * 2 * x.asnumpy())


def test_detach_blocks_grad():
    x = nd.array([2.])
    x.attach_grad()
    with autograd.record():
        y = x * x
        z = y.detach() * x
    z.backward()
    np.testing.assert_allclose(x.grad.asnumpy(), [4.])  # only d(z)/dx via second factor


def test_stop_gradient_op():
    x = nd.array([2.])
    x.attach_grad()
    with autograd.record():
        y = nd.BlockGrad(x * x) * x
    y.backward()
    np.testing.assert_allclose(x.grad.asnumpy(), [4.])


def test_autograd_grad_function():
    x = nd.array([1., 2., 3.])
    x.attach_grad()
    with autograd.record():
        y = (x * x).sum()
        g = autograd.grad(y, [x], retain_graph=True)
    np.testing.assert_allclose(g[0].asnumpy(), 2 * x.asnumpy())


def test_training_mode_flags():
    assert not autograd.is_training()
    with autograd.record():
        assert autograd.is_training()
        assert autograd.is_recording()
        with autograd.predict_mode():
            assert not autograd.is_training()
            assert autograd.is_recording()
    assert not autograd.is_recording()
    with autograd.train_mode():
        assert autograd.is_training()
        assert not autograd.is_recording()


def test_dropout_respects_mode():
    x = nd.ones((100, 100))
    out_pred = nd.Dropout(x, p=0.5)  # not recording, not training -> identity
    np.testing.assert_allclose(out_pred.asnumpy(), x.asnumpy())
    with autograd.record():
        out_train = nd.Dropout(x, p=0.5)
    frac_zero = (out_train.asnumpy() == 0).mean()
    assert 0.4 < frac_zero < 0.6


def test_backward_non_recorded_raises():
    x = nd.ones((2,))
    with pytest.raises(MXNetError):
        x.backward()


def test_mark_variables():
    x = nd.array([3.])
    g = nd.zeros((1,))
    autograd.mark_variables([x], [g])
    with autograd.record():
        y = x * x
    y.backward()
    np.testing.assert_allclose(g.asnumpy(), [6.])


def test_softmax_output_semantic_grad():
    # SoftmaxOutput backward = softmax(data) - onehot(label), ignoring head grad
    data = nd.array(np.random.randn(4, 5).astype(np.float32))
    label = nd.array([0, 1, 2, 3], dtype=np.float32)
    data.attach_grad()
    with autograd.record():
        out = nd.SoftmaxOutput(data, label)
    out.backward()
    import scipy.special as sp
    expect = sp.softmax(data.asnumpy(), axis=-1)
    oh = np.eye(5, dtype=np.float32)[[0, 1, 2, 3]]
    np.testing.assert_allclose(data.grad.asnumpy(), expect - oh, rtol=1e-5)


def test_rnn_op_grad_flows():
    T, N, I, H = 3, 2, 4, 5
    from mxnet_tpu.ops.nn import rnn_param_size
    psz = rnn_param_size(1, I, H, "lstm")
    data = nd.random.uniform(shape=(T, N, I))
    params = nd.random.normal(scale=0.1, shape=(psz,))
    h0 = nd.zeros((1, N, H))
    c0 = nd.zeros((1, N, H))
    params.attach_grad()
    with autograd.record():
        out = nd.RNN(data, params, h0, c0, state_size=H, num_layers=1,
                     mode="lstm")
        loss = (out * out).sum()
    loss.backward()
    assert np.abs(params.grad.asnumpy()).sum() > 0


def test_grad_create_graph_second_order():
    """d2/dx2 of x^3 = 6x via grad-of-grad (ref autograd.py:274)."""
    x = nd.array(np.array([1.0, 2.0, 3.0], np.float32))
    x.attach_grad()
    with mx.autograd.record():
        y = x * x * x
        (dy_dx,) = mx.autograd.grad(y, [x], create_graph=True)
        z = dy_dx.sum()
    z.backward()
    np.testing.assert_allclose(x.grad.asnumpy(), 6.0 * x.asnumpy(),
                               rtol=1e-5)


def test_grad_create_graph_mixed_expression():
    """Differentiate an expression that mixes first-order grads with the
    forward values: d/dx [ (dy/dx) * x ] with y = x^2 -> d/dx [2x^2] = 4x."""
    x = nd.array(np.array([0.5, -1.5], np.float32))
    x.attach_grad()
    with mx.autograd.record():
        y = x * x
        (g,) = mx.autograd.grad(y, [x], create_graph=True)
        w = (g * x).sum()
    w.backward()
    np.testing.assert_allclose(x.grad.asnumpy(), 4.0 * x.asnumpy(),
                               rtol=1e-5)


def test_getitem_slices_land_on_tape():
    """x[...] views inside record() must carry gradients (they used to
    bypass the tape entirely, silently returning zero grads)."""
    em = nd.array(np.arange(24, dtype=np.float32).reshape(2, 3, 4))
    em.attach_grad()
    lab = nd.array(np.array([1.0, 2.0]))
    with autograd.record():
        s = nd.sum(nd.pick(em[:, 1, :], lab, axis=1))
    s.backward()
    expected = np.zeros((2, 3, 4), np.float32)
    expected[0, 1, 1] = 1
    expected[1, 1, 2] = 1
    np.testing.assert_allclose(em.grad.asnumpy(), expected)

    x = nd.array(np.arange(6, dtype=np.float32))
    x.attach_grad()
    with autograd.record():
        y = nd.sum(x[1:4] * x[1:4])          # overlapping views add up
    y.backward()
    expected = np.zeros(6, np.float32)
    expected[1:4] = 2 * np.arange(1, 4)
    np.testing.assert_allclose(x.grad.asnumpy(), expected)

    idx = nd.array(np.array([0.0, 2.0]))     # fancy indexing too
    x2 = nd.array(np.arange(4, dtype=np.float32))
    x2.attach_grad()
    with autograd.record():
        z = nd.sum(x2[idx])
    z.backward()
    np.testing.assert_allclose(x2.grad.asnumpy(), [1, 0, 1, 0])


def test_view_and_cast_methods_record():
    """.T, .astype, .copy under record() must carry gradients (same
    tape-bypass class as __getitem__)."""
    w = nd.array(np.arange(6, dtype=np.float32).reshape(2, 3))
    w.attach_grad()
    with autograd.record():
        y = nd.sum(w.T * nd.array(np.ones((3, 2), np.float32) * 2))
    y.backward()
    np.testing.assert_allclose(w.grad.asnumpy(), np.full((2, 3), 2.0))

    x = nd.array(np.array([1.0, 2.0], np.float32))
    x.attach_grad()
    with autograd.record():
        z = nd.sum(x.astype("float64") * 3)
    z.backward()
    np.testing.assert_allclose(x.grad.asnumpy(), [3, 3])

    c = nd.array(np.array([1.0, 2.0], np.float32))
    c.attach_grad()
    with autograd.record():
        out = nd.sum(c.copy() * c)      # grad 2c through both paths
    out.backward()
    np.testing.assert_allclose(c.grad.asnumpy(), [2, 4])

    d = nd.array(np.array([1.0, 2.0], np.float32))
    d.attach_grad()
    with autograd.record():
        blocked = nd.sum(d.detach() * d)   # detach severs one path
    blocked.backward()
    np.testing.assert_allclose(d.grad.asnumpy(), [1, 2])
