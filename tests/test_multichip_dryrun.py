"""Tier-1 fast variant of the MULTICHIP dryrun (ISSUE 16 satellite).

Runs the in-process legs of ``__graft_entry__.dryrun_multichip`` — train,
zero1, ep, pp — on the 8-virtual-CPU-device tier-1 mesh, exactly the code
path the driver exercises, minus the subprocess-heavy overlap/multihost
legs (those stay in the full dryrun, where ``--gate-overlap`` is
enforced).  Keeps the SPMD substrate's end-to-end story inside the test
suite instead of only in the driver.
"""
import os
import sys

import jax

sys.path.insert(0, os.path.join(os.path.dirname(__file__), os.pardir))
import __graft_entry__ as graft_entry  # noqa: E402


def test_dryrun_fast_legs(capsys):
    n = len(jax.devices())
    assert n >= 2, "tier-1 harness pins 8 virtual CPU devices"
    graft_entry.dryrun_multichip(n, legs=("train", "zero1", "ep", "pp"))
    out = capsys.readouterr().out
    assert "dryrun_multichip(%d)" % n in out
    assert "zero1" in out
    assert "ep: moe loss" in out
    assert "GPipe pipeline matches sequential" in out
    # the subprocess legs must NOT have run in the fast variant
    assert "overlap" not in out
    assert "multihost" not in out
