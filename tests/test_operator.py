"""Operator correctness via the numeric-gradient oracle + numpy references
(reference tests/python/unittest/test_operator.py doctrine, SURVEY §4)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd
from mxnet_tpu.test_utils import (assert_almost_equal, check_numeric_gradient,
                                  check_symbolic_forward,
                                  check_symbolic_backward, rand_ndarray)


# ---- elementwise unary: forward vs numpy + numeric gradient ---------------
UNARY_CASES = [
    ("relu", lambda x: np.maximum(x, 0), (-2, 2)),
    ("sigmoid", lambda x: 1 / (1 + np.exp(-x)), (-4, 4)),
    ("tanh", np.tanh, (-2, 2)),
    ("exp", np.exp, (-2, 2)),
    ("log", np.log, (0.1, 4)),
    ("sqrt", np.sqrt, (0.1, 4)),
    ("square", np.square, (-2, 2)),
    ("abs", np.abs, (0.3, 2)),
    ("sin", np.sin, (-3, 3)),
    ("cos", np.cos, (-3, 3)),
    ("arctan", np.arctan, (-2, 2)),
    ("cbrt", np.cbrt, (0.1, 4)),
    ("log1p", np.log1p, (-0.5, 3)),
    ("expm1", np.expm1, (-2, 2)),
    ("rsqrt", lambda x: 1 / np.sqrt(x), (0.5, 4)),
    ("reciprocal", lambda x: 1 / x, (0.5, 4)),
]


@pytest.mark.parametrize("name,ref,rng", UNARY_CASES,
                         ids=[c[0] for c in UNARY_CASES])
def test_unary_forward_and_grad(name, ref, rng):
    x = np.random.uniform(rng[0], rng[1], (3, 4)).astype(np.float32)
    fn = getattr(nd, name)
    out = fn(nd.array(x)).asnumpy()
    assert_almost_equal(out, ref(x).astype(np.float32), rtol=1e-4, atol=1e-5)
    check_numeric_gradient(lambda a: fn(a), [x], rtol=5e-2)


# ---- binary broadcast ------------------------------------------------------
BIN_CASES = [
    ("broadcast_add", np.add),
    ("broadcast_sub", np.subtract),
    ("broadcast_mul", np.multiply),
    ("broadcast_div", np.divide),
    ("broadcast_maximum", np.maximum),
    ("broadcast_minimum", np.minimum),
    ("broadcast_power", np.power),
]


@pytest.mark.parametrize("name,ref", BIN_CASES, ids=[c[0] for c in BIN_CASES])
def test_binary_broadcast(name, ref):
    a = np.random.uniform(0.5, 2, (2, 1, 4)).astype(np.float32)
    b = np.random.uniform(0.5, 2, (1, 3, 4)).astype(np.float32)
    fn = getattr(nd, name)
    assert_almost_equal(fn(nd.array(a), nd.array(b)).asnumpy(), ref(a, b),
                        rtol=1e-4, atol=1e-5)
    check_numeric_gradient(lambda x, y: fn(x, y), [a, b], rtol=5e-2)


# ---- reductions ------------------------------------------------------------
def test_reductions():
    x = np.random.uniform(-2, 2, (3, 4, 5)).astype(np.float32)
    for name, ref in [("sum", np.sum), ("mean", np.mean),
                      ("max", np.max), ("min", np.min),
                      ("prod", np.prod)]:
        fn = getattr(nd, name)
        assert_almost_equal(fn(nd.array(x)).asnumpy(), ref(x), rtol=1e-3)
        assert_almost_equal(fn(nd.array(x), axis=1).asnumpy(),
                            ref(x, axis=1), rtol=1e-3)
    check_numeric_gradient(lambda a: nd.sum(a, axis=1), [x], rtol=5e-2)
    assert_almost_equal(nd.argmax(nd.array(x), axis=1).asnumpy(),
                        np.argmax(x, axis=1))
    assert_almost_equal(nd.argmin(nd.array(x), axis=2).asnumpy(),
                        np.argmin(x, axis=2))


# ---- matrix / indexing -----------------------------------------------------
def test_dot_and_batch_dot():
    a = np.random.randn(4, 5).astype(np.float32)
    b = np.random.randn(5, 3).astype(np.float32)
    assert_almost_equal(nd.dot(nd.array(a), nd.array(b)).asnumpy(), a @ b,
                        rtol=1e-4)
    check_numeric_gradient(lambda x, y: nd.dot(x, y), [a, b], rtol=5e-2)
    ba = np.random.randn(2, 4, 5).astype(np.float32)
    bb = np.random.randn(2, 5, 3).astype(np.float32)
    assert_almost_equal(nd.batch_dot(nd.array(ba), nd.array(bb)).asnumpy(),
                        ba @ bb, rtol=1e-4)


def test_transpose_reshape_slice():
    x = np.arange(24, dtype=np.float32).reshape(2, 3, 4)
    assert_almost_equal(nd.transpose(nd.array(x)).asnumpy(), x.T)
    assert_almost_equal(
        nd.transpose(nd.array(x), axes=(1, 0, 2)).asnumpy(),
        x.transpose(1, 0, 2))
    assert_almost_equal(nd.reshape(nd.array(x), shape=(4, 6)).asnumpy(),
                        x.reshape(4, 6))
    assert_almost_equal(
        nd.slice_axis(nd.array(x), axis=1, begin=1, end=3).asnumpy(),
        x[:, 1:3])
    assert_almost_equal(nd.flip(nd.array(x), axis=1).asnumpy(),
                        x[:, ::-1])


def test_take_one_hot_pick_where():
    x = np.random.randn(5, 3).astype(np.float32)
    idx = np.array([0, 3, 1], dtype=np.float32)
    assert_almost_equal(nd.take(nd.array(x), nd.array(idx)).asnumpy(),
                        x[idx.astype(int)])
    oh = nd.one_hot(nd.array(idx), depth=5).asnumpy()
    assert_almost_equal(oh, np.eye(5, dtype=np.float32)[idx.astype(int)])
    p = nd.pick(nd.array(x), nd.array(np.array([0, 1, 2, 0, 1],
                                               dtype=np.float32)), axis=1)
    assert_almost_equal(p.asnumpy(), x[np.arange(5), [0, 1, 2, 0, 1]])
    cond = np.array([[1, 0, 1], [0, 1, 0]], dtype=np.float32)
    a = np.ones((2, 3), np.float32)
    b = np.zeros((2, 3), np.float32)
    assert_almost_equal(
        nd.where(nd.array(cond), nd.array(a), nd.array(b)).asnumpy(), cond)


def test_topk_sort_argsort():
    x = np.random.randn(3, 6).astype(np.float32)
    out = nd.topk(nd.array(x), k=2, axis=1).asnumpy()
    ref = np.argsort(-x, axis=1)[:, :2]
    assert_almost_equal(out, ref.astype(np.float32))
    assert_almost_equal(nd.sort(nd.array(x), axis=1).asnumpy(),
                        np.sort(x, axis=1))
    assert_almost_equal(nd.argsort(nd.array(x), axis=1).asnumpy(),
                        np.argsort(x, axis=1).astype(np.float32))


# ---- NN ops ----------------------------------------------------------------
def test_softmax_log_softmax():
    x = np.random.randn(4, 7).astype(np.float32)
    e = np.exp(x - x.max(axis=1, keepdims=True))
    ref = e / e.sum(axis=1, keepdims=True)
    assert_almost_equal(nd.softmax(nd.array(x)).asnumpy(), ref, rtol=1e-4)
    assert_almost_equal(nd.log_softmax(nd.array(x)).asnumpy(), np.log(ref),
                        rtol=1e-4)
    check_numeric_gradient(lambda a: nd.softmax(a), [x], rtol=5e-2)


def test_fully_connected_grad():
    x = np.random.randn(4, 6).astype(np.float32)
    w = np.random.randn(3, 6).astype(np.float32)
    b = np.random.randn(3).astype(np.float32)
    out = nd.FullyConnected(nd.array(x), nd.array(w), nd.array(b),
                            num_hidden=3).asnumpy()
    assert_almost_equal(out, x @ w.T + b, rtol=1e-4)
    check_numeric_gradient(
        lambda a, ww, bb: nd.FullyConnected(a, ww, bb, num_hidden=3),
        [x, w, b], rtol=5e-2)


def test_convolution_grad():
    x = np.random.randn(2, 3, 7, 7).astype(np.float32)
    w = np.random.randn(4, 3, 3, 3).astype(np.float32)
    b = np.random.randn(4).astype(np.float32)
    check_numeric_gradient(
        lambda a, ww, bb: nd.Convolution(a, ww, bb, kernel=(3, 3),
                                         num_filter=4, pad=(1, 1)),
        [x, w, b], rtol=5e-2, numeric_eps=1e-2)


def test_batchnorm_inference_matches_numpy():
    x = np.random.randn(4, 3, 5, 5).astype(np.float32)
    gamma = np.random.uniform(0.5, 1.5, 3).astype(np.float32)
    beta = np.random.randn(3).astype(np.float32)
    mean = np.random.randn(3).astype(np.float32)
    var = np.random.uniform(0.5, 1.5, 3).astype(np.float32)
    out = nd.BatchNorm(nd.array(x), nd.array(gamma), nd.array(beta),
                       nd.array(mean), nd.array(var), fix_gamma=False,
                       use_global_stats=True, eps=1e-5).asnumpy()
    ref = ((x - mean[None, :, None, None]) /
           np.sqrt(var[None, :, None, None] + 1e-5) *
           gamma[None, :, None, None] + beta[None, :, None, None])
    assert_almost_equal(out, ref, rtol=1e-3, atol=1e-4)


# ---- symbolic check helpers on ops ----------------------------------------
def test_check_symbolic_forward_backward():
    a = mx.sym.var("a")
    b = mx.sym.var("b")
    out = 2 * a + a * b
    av = np.random.randn(3, 4).astype(np.float32)
    bv = np.random.randn(3, 4).astype(np.float32)
    check_symbolic_forward(out, [av, bv], [2 * av + av * bv])
    og = np.ones((3, 4), np.float32)
    check_symbolic_backward(out, [av, bv], [og],
                            {"a": 2 + bv, "b": av})


def test_check_numeric_gradient_symbol_path():
    """The Symbol overload must produce real (non-zero) autograd grads."""
    a = mx.sym.var("a")
    b = mx.sym.var("b")
    out = mx.sym.broadcast_mul(a, b) + a
    av = np.random.uniform(0.5, 1.5, (3, 4)).astype(np.float32)
    bv = np.random.uniform(0.5, 1.5, (3, 4)).astype(np.float32)
    check_numeric_gradient(out, {"a": av, "b": bv}, rtol=5e-2)
    check_numeric_gradient(out, {"a": av, "b": bv}, grad_nodes=["b"],
                           rtol=5e-2)


# ---- random ops ------------------------------------------------------------
def test_random_ops_statistics():
    mx.random.seed(7)
    u = nd.random.uniform(0, 1, shape=(20000,)).asnumpy()
    assert 0.48 < u.mean() < 0.52
    n = nd.random.normal(0, 1, shape=(20000,)).asnumpy()
    assert abs(n.mean()) < 0.03 and 0.95 < n.std() < 1.05
    p = nd.random.poisson(lam=4.0, shape=(20000,)).asnumpy()
    assert 3.8 < p.mean() < 4.2
    g = nd.random.gamma(alpha=3.0, beta=1.0, shape=(20000,)).asnumpy()
    assert 2.8 < g.mean() < 3.2


def test_clip_round_sign():
    x = np.random.uniform(-3, 3, (4, 5)).astype(np.float32)
    assert_almost_equal(nd.clip(nd.array(x), -1, 1).asnumpy(),
                        np.clip(x, -1, 1))
    assert_almost_equal(nd.sign(nd.array(x)).asnumpy(), np.sign(x))
    assert_almost_equal(nd.round(nd.array(x)).asnumpy(), np.round(x))
    assert_almost_equal(nd.floor(nd.array(x)).asnumpy(), np.floor(x))
    assert_almost_equal(nd.ceil(nd.array(x)).asnumpy(), np.ceil(x))
