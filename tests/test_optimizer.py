"""Optimizer update rules vs numpy references (reference
tests/python/unittest/test_optimizer.py doctrine)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd
from mxnet_tpu.test_utils import assert_almost_equal


def _run_steps(opt, w0, g, n=3):
    w = nd.array(w0.copy())
    state = opt.create_state(0, w)
    for _ in range(n):
        opt.update(0, w, nd.array(g), state)
    return w.asnumpy()


def test_sgd_matches_numpy():
    w0 = np.random.randn(4, 3).astype(np.float32)
    g = np.random.randn(4, 3).astype(np.float32)
    opt = mx.optimizer.SGD(learning_rate=0.1, rescale_grad=1.0, wd=0.0)
    out = _run_steps(opt, w0, g, n=3)
    ref = w0 - 3 * 0.1 * g
    assert_almost_equal(out, ref, rtol=1e-5)


def test_sgd_momentum_matches_numpy():
    w0 = np.random.randn(5).astype(np.float32)
    g = np.random.randn(5).astype(np.float32)
    lr, mom = 0.1, 0.9
    opt = mx.optimizer.SGD(learning_rate=lr, momentum=mom, rescale_grad=1.0,
                           wd=0.0)
    out = _run_steps(opt, w0, g, n=3)
    w, m = w0.copy(), np.zeros_like(w0)
    for _ in range(3):
        m = mom * m - lr * g
        w = w + m
    assert_almost_equal(out, w, rtol=1e-5)


def test_sgd_wd_matches_numpy():
    w0 = np.random.randn(5).astype(np.float32)
    g = np.zeros(5, np.float32)
    opt = mx.optimizer.SGD(learning_rate=0.1, rescale_grad=1.0, wd=0.01)
    out = _run_steps(opt, w0, g, n=1)
    assert_almost_equal(out, w0 * (1 - 0.1 * 0.01), rtol=1e-5)


def test_adam_matches_numpy():
    w0 = np.random.randn(6).astype(np.float32)
    g = np.random.randn(6).astype(np.float32)
    lr, b1, b2, eps = 0.01, 0.9, 0.999, 1e-8
    opt = mx.optimizer.Adam(learning_rate=lr, beta1=b1, beta2=b2,
                            epsilon=eps, rescale_grad=1.0, wd=0.0)
    out = _run_steps(opt, w0, g, n=4)
    w = w0.copy().astype(np.float64)
    m = np.zeros_like(w)
    v = np.zeros_like(w)
    for t in range(1, 5):
        m = b1 * m + (1 - b1) * g
        v = b2 * v + (1 - b2) * g * g
        lr_t = lr * np.sqrt(1 - b2 ** t) / (1 - b1 ** t)
        w = w - lr_t * m / (np.sqrt(v) + eps)
    assert_almost_equal(out, w.astype(np.float32), rtol=1e-4)


def test_rmsprop_runs_and_converges_direction():
    w0 = np.ones(4, np.float32)
    g = np.ones(4, np.float32)
    opt = mx.optimizer.RMSProp(learning_rate=0.1, rescale_grad=1.0, wd=0.0)
    out = _run_steps(opt, w0, g, n=5)
    assert (out < w0).all()


@pytest.mark.parametrize("name", ["sgd", "nag", "adam", "adagrad", "rmsprop",
                                  "adadelta", "ftrl", "adamax", "nadam",
                                  "sgld", "dcasgd", "signum"])
def test_all_optimizers_step_finite(name):
    opt = mx.optimizer.create(name)
    w = nd.array(np.random.randn(8).astype(np.float32))
    g = nd.array(np.random.randn(8).astype(np.float32))
    state = opt.create_state(0, w)
    for _ in range(3):
        opt.update(0, w, g, state)
    assert np.isfinite(w.asnumpy()).all()


def test_lr_scheduler_factor():
    sched = mx.lr_scheduler.FactorScheduler(step=2, factor=0.5)
    sched.base_lr = 1.0
    lrs = [sched(i) for i in [1, 2, 3, 4, 5]]
    assert lrs[0] == 1.0 and lrs[-1] <= 0.25 + 1e-6


def test_multifactor_scheduler():
    sched = mx.lr_scheduler.MultiFactorScheduler(step=[2, 4], factor=0.1)
    sched.base_lr = 1.0
    assert abs(sched(5) - 0.01) < 1e-9


def test_updater_states_roundtrip():
    opt = mx.optimizer.SGD(learning_rate=0.1, momentum=0.9)
    upd = mx.optimizer.get_updater(opt)
    w = nd.array(np.random.randn(4).astype(np.float32))
    g = nd.array(np.random.randn(4).astype(np.float32))
    upd(0, g, w)
    blob = upd.get_states()
    upd2 = mx.optimizer.get_updater(mx.optimizer.SGD(learning_rate=0.1,
                                                     momentum=0.9))
    upd2.set_states(blob)
    upd2(0, g, w)
    assert np.isfinite(w.asnumpy()).all()


def test_lr_wd_mult():
    opt = mx.optimizer.SGD(learning_rate=1.0, rescale_grad=1.0, wd=0.0,
                           param_idx2name={0: "a", 1: "b"})
    opt.set_lr_mult({"a": 0.0})
    w = nd.array(np.ones(3, np.float32))
    g = nd.array(np.ones(3, np.float32))
    opt.update(0, w, g, opt.create_state(0, w))
    assert_almost_equal(w.asnumpy(), np.ones(3))  # lr_mult 0 → no change
