"""tracecheck (the JX trace tier): per-rule fixtures + runtime hook.

Every AOT JX rule gets a seeded BAD program that fires and a clean twin
that stays quiet (ISSUE 5 acceptance) — the programs are traced for real
through ``tracecheck.trace_program`` (jax.jit + ShapeDtypeStruct, nothing
executed), not mocked jaxprs.  JX105 is exercised both as a unit
(``explain_retrace`` names the changed axis) and end-to-end through the
``MXNET_TRACECHECK`` compile hook off ``telemetry.watch_jit``.
"""
import json
import os
import subprocess
import sys

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from mxnet_tpu import telemetry as tel
from mxnet_tpu.lint import tracecheck
from mxnet_tpu.lint.tracecheck import (TraceConfig, explain_retrace,
                                       run_rules, signature, trace_program)

# toy-sized thresholds: the fixtures below are a few KB, not the MBs the
# production defaults gate on
CFG = TraceConfig(const_bytes=256, donation_bytes=64, passthrough_bytes=64)


def rules_for(fn, args, select, config=CFG, kwargs=None):
    rec = trace_program("fixture", fn, args, kwargs)
    return [f.rule for f in run_rules(rec, select={select}, config=config)]


def spec(shape, dtype=jnp.float32):
    return jax.ShapeDtypeStruct(shape, dtype)


# ---------------------------------------------------------------------------
# JX101 baked-constant
# ---------------------------------------------------------------------------

def test_jx101_fires_on_closure_baked_array():
    table = jnp.asarray(np.ones((16, 16), np.float32))     # 1 KiB const

    def fwd(x):
        return x @ table

    assert "JX101" in rules_for(jax.jit(fwd), (spec((4, 16)),), "JX101")


def test_jx101_quiet_when_passed_as_argument():
    def fwd(x, table):
        return x @ table

    assert rules_for(jax.jit(fwd), (spec((4, 16)), spec((16, 16))),
                     "JX101") == []


def test_jx101_quiet_below_threshold():
    scale = jnp.asarray(np.float32(3.0))    # tiny closure scalar: fine

    def fwd(x):
        return x * scale

    assert rules_for(jax.jit(fwd), (spec((4, 16)),), "JX101") == []


# ---------------------------------------------------------------------------
# JX102 dtype-widening
# ---------------------------------------------------------------------------

@pytest.fixture
def x64():
    # f64 exists only with x64 enabled; restore so no other test sees it
    jax.config.update("jax_enable_x64", True)
    yield
    jax.config.update("jax_enable_x64", False)


def test_jx102_fires_on_widening_from_f32_inputs(x64):
    def fwd(x):
        acc = x.astype(jnp.float64)          # the forgotten widening
        return (acc * 2.0).sum().astype(jnp.float32)

    assert "JX102" in rules_for(jax.jit(fwd), (spec((4, 16)),), "JX102")


def test_jx102_quiet_on_all_f32(x64):
    def fwd(x):
        return (x * 2.0).sum()

    assert rules_for(jax.jit(fwd), (spec((4, 16)),), "JX102") == []


def test_jx102_quiet_when_caller_asked_for_f64(x64):
    # wide INPUTS mean 64-bit was requested — not an accident to report
    def fwd(x):
        return (x * 2.0).sum()

    assert rules_for(jax.jit(fwd), (spec((4, 16), jnp.float64),),
                     "JX102") == []


# ---------------------------------------------------------------------------
# JX103 host-callback-in-hot-program
# ---------------------------------------------------------------------------

def test_jx103_fires_on_debug_print():
    def fwd(x):
        jax.debug.print("x sum {}", x.sum())
        return x * 2.0

    assert "JX103" in rules_for(jax.jit(fwd), (spec((4, 16)),), "JX103")


def test_jx103_fires_on_pure_callback():
    def fwd(x):
        y = jax.pure_callback(lambda a: np.asarray(a) * 2.0,
                              jax.ShapeDtypeStruct(x.shape, x.dtype), x)
        return y + 1.0

    assert "JX103" in rules_for(jax.jit(fwd), (spec((4, 16)),), "JX103")


def test_jx103_quiet_on_pure_program():
    def fwd(x):
        return x * 2.0

    assert rules_for(jax.jit(fwd), (spec((4, 16)),), "JX103") == []


# ---------------------------------------------------------------------------
# JX104 donation-waste
# ---------------------------------------------------------------------------

def test_jx104_fires_on_unaliasable_donation():
    # donated (64,) input but the only output is a scalar: freed for
    # nothing, and the caller lost the buffer
    def fwd(s):
        return s.sum()

    assert "JX104" in rules_for(jax.jit(fwd, donate_argnums=0),
                                (spec((64,)),), "JX104")


def test_jx104_fires_on_missed_donation():
    # b is donated, a is just as aliasable and large — one HBM copy wasted
    def fwd(a, b):
        return a + 1.0, b + 1.0

    assert "JX104" in rules_for(jax.jit(fwd, donate_argnums=1),
                                (spec((64,)), spec((64,))), "JX104")


def test_jx104_fires_on_passthrough_output():
    def fwd(a, b):
        return a, a + b

    assert "JX104" in rules_for(jax.jit(fwd),
                                (spec((64,)), spec((64,))), "JX104")


def test_jx104_quiet_on_full_donation():
    def fwd(a, b):
        return a + 1.0, b + 1.0

    assert rules_for(jax.jit(fwd, donate_argnums=(0, 1)),
                     (spec((64,)), spec((64,))), "JX104") == []


def test_jx104_quiet_on_donated_passthrough():
    # a donated pass-through aliases for free — nothing to report
    def fwd(a):
        return a, a.sum()

    assert rules_for(jax.jit(fwd, donate_argnums=0),
                     (spec((64,)),), "JX104") == []


# ---------------------------------------------------------------------------
# JX105 retrace-explainer
# ---------------------------------------------------------------------------

def test_jx105_names_the_changed_axis():
    old = signature((np.zeros((8, 64), np.float32),), {})
    new = signature((np.zeros((16, 64), np.float32),), {})
    msg = explain_retrace("step", [old], new)
    assert "axis 0: 8->16" in msg and "step" in msg


def test_jx105_names_dtype_and_static_changes():
    old = signature((np.zeros(4, np.float32),), {"mode": "train"})
    new_dtype = signature((np.zeros(4, np.float16),), {"mode": "train"})
    assert "float32->float16" in explain_retrace("s", [old], new_dtype)
    new_static = signature((np.zeros(4, np.float32),), {"mode": "eval"})
    assert "static value" in explain_retrace("s", [old], new_static)


def test_jx105_diffs_against_closest_variant():
    # two cached variants; the new call matches one except for ONE axis —
    # the diagnosis must name that axis, not diff the farther variant
    a = signature((np.zeros((8, 64), np.float32),), {})
    b = signature((np.zeros((8, 32), np.float16),), {})
    new = signature((np.zeros((9, 64), np.float32),), {})
    msg = explain_retrace("step", [a, b], new)
    assert "axis 0: 8->9" in msg and "float16" not in msg


def test_jx105_no_visible_change_message():
    sig = signature((np.zeros(4, np.float32),), {})
    assert "no visible" in explain_retrace("step", [sig], sig)


def test_runtime_hook_books_jx105_on_recompile(monkeypatch):
    monkeypatch.setenv("MXNET_TRACECHECK", "1")
    tel.refresh_from_env()
    tracecheck.reset_runtime()
    try:
        def fwd(x):
            return x * 2.0

        wf = tel.watch_jit(jax.jit(fwd), "tc_hook_step")
        before = tel.counter("tracecheck_findings")
        wf(jnp.ones((4, 8)))                  # first compile: no history
        wf(jnp.ones((6, 8)))                  # recompile -> JX105
        assert tel.counter("tracecheck_findings") >= before + 1
        from mxnet_tpu.telemetry import flight
        kinds = [e for e in flight._ring if e.get("kind") == "tracecheck"]
        assert any(e.get("name") == "JX105" for e in kinds)
    finally:
        monkeypatch.delenv("MXNET_TRACECHECK")
        tel.refresh_from_env()
        tracecheck.reset_runtime()


def test_runtime_hook_separates_programs_sharing_a_name(monkeypatch):
    """Two distinct jits under one watch name (a cached op's train/eval
    pair, every optimizer instance under 'optimizer_update_step') are
    separate compile caches: each one's FIRST compile must not read as a
    recompile of the other."""
    monkeypatch.setenv("MXNET_TRACECHECK", "1")
    tel.refresh_from_env()
    tracecheck.reset_runtime()
    try:
        wa = tel.watch_jit(jax.jit(lambda x: x * 2.0), "tc_shared_name")
        wb = tel.watch_jit(jax.jit(lambda x: x + 1.0), "tc_shared_name")
        before = tel.counter("tracecheck_findings")
        wa(jnp.ones((4, 8)))
        wb(jnp.ones((6, 8)))      # other program, other shape: no JX105
        assert tel.counter("tracecheck_findings") == before
    finally:
        monkeypatch.delenv("MXNET_TRACECHECK")
        tel.refresh_from_env()
        tracecheck.reset_runtime()


def test_runtime_hook_off_by_default(monkeypatch):
    monkeypatch.delenv("MXNET_TRACECHECK", raising=False)
    tel.refresh_from_env()
    tracecheck.reset_runtime()

    def fwd(x):
        return x + 1.0

    wf = tel.watch_jit(jax.jit(fwd), "tc_off_step")
    before = tel.counter("tracecheck_findings")
    wf(jnp.ones((4, 8)))
    wf(jnp.ones((6, 8)))
    assert tel.counter("tracecheck_findings") == before
    assert not tracecheck._SIG_HISTORY.get("tc_off_step")


# ---------------------------------------------------------------------------
# AOT driver plumbing
# ---------------------------------------------------------------------------

def test_scoped_entry_group_traces_only_its_programs():
    findings, names = tracecheck.check_entry_points(entries={"kvstore"})
    assert set(names) == {"kvstore_stack_sum", "kvstore_bucket_reduce"}
    assert findings == []


def test_cli_trace_rejects_unknown_group():
    from mxnet_tpu.lint import cli
    assert cli.main(["--trace", "nonesuch"]) == 2


def test_cli_trace_json_smoke():
    out = subprocess.run(
        [sys.executable, "-m", "mxnet_tpu.lint", "--trace", "kvstore",
         "-f", "json", "--no-baseline"],
        capture_output=True, text=True, timeout=240,
        env=dict(os.environ, JAX_PLATFORMS="cpu"),
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    assert out.returncode == 0, out.stderr
    payload = json.loads(out.stdout)
    assert payload["new"] == []
    assert "kvstore_stack_sum" in out.stderr      # coverage line


def test_provider_failure_suppresses_baseline_sweep(tmp_path, monkeypatch):
    """A full --trace run with a JX000 (a provider that didn't run) must
    NOT retire trace:// baseline entries: --write-baseline keeps the
    un-re-checked entry instead of silently dropping a group's ledger."""
    from mxnet_tpu.lint import cli
    from mxnet_tpu.lint.core import Finding
    baseline = tmp_path / "base.json"
    baseline.write_text(json.dumps({"version": 1, "entries": [
        {"rule": "JX104", "path": "trace://executor_train",
         "snippet": "donate-missed:arg[0]", "count": 1}]}))
    monkeypatch.setattr(
        tracecheck, "check_entry_points",
        lambda entries=None, select=None: (
            [Finding("JX000", "trace://executor", 0, 0, "provider failed",
                     snippet="provider:executor")], []))
    cli.main(["--trace", "--write-baseline", "--baseline", str(baseline)])
    kept = json.dumps(json.loads(baseline.read_text()))
    assert "trace://executor_train" in kept


def test_list_rules_shows_jx_catalogue():
    from mxnet_tpu.lint import cli
    import io
    from contextlib import redirect_stdout
    buf = io.StringIO()
    with redirect_stdout(buf):
        assert cli.main(["--list-rules"]) == 0
    text = buf.getvalue()
    for code in ("JX101", "JX102", "JX103", "JX104", "JX105"):
        assert code in text
