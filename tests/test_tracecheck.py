"""tracecheck (the JX trace tier): per-rule fixtures + runtime hook.

Every AOT JX rule gets a seeded BAD program that fires and a clean twin
that stays quiet (ISSUE 5 acceptance) — the programs are traced for real
through ``tracecheck.trace_program`` (jax.jit + ShapeDtypeStruct, nothing
executed), not mocked jaxprs.  JX105 is exercised both as a unit
(``explain_retrace`` names the changed axis) and end-to-end through the
``MXNET_TRACECHECK`` compile hook off ``telemetry.watch_jit``.
"""
import json
import os
import subprocess
import sys

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from mxnet_tpu import telemetry as tel
from mxnet_tpu.lint import tracecheck
from mxnet_tpu.lint.tracecheck import (TraceConfig, explain_retrace,
                                       run_rules, signature, trace_program)

# toy-sized thresholds: the fixtures below are a few KB, not the MBs the
# production defaults gate on
CFG = TraceConfig(const_bytes=256, donation_bytes=64, passthrough_bytes=64)


def rules_for(fn, args, select, config=CFG, kwargs=None):
    rec = trace_program("fixture", fn, args, kwargs)
    return [f.rule for f in run_rules(rec, select={select}, config=config)]


def spec(shape, dtype=jnp.float32):
    return jax.ShapeDtypeStruct(shape, dtype)


# ---------------------------------------------------------------------------
# JX101 baked-constant
# ---------------------------------------------------------------------------

def test_jx101_fires_on_closure_baked_array():
    table = jnp.asarray(np.ones((16, 16), np.float32))     # 1 KiB const

    def fwd(x):
        return x @ table

    assert "JX101" in rules_for(jax.jit(fwd), (spec((4, 16)),), "JX101")


def test_jx101_quiet_when_passed_as_argument():
    def fwd(x, table):
        return x @ table

    assert rules_for(jax.jit(fwd), (spec((4, 16)), spec((16, 16))),
                     "JX101") == []


def test_jx101_quiet_below_threshold():
    scale = jnp.asarray(np.float32(3.0))    # tiny closure scalar: fine

    def fwd(x):
        return x * scale

    assert rules_for(jax.jit(fwd), (spec((4, 16)),), "JX101") == []


# ---------------------------------------------------------------------------
# JX102 dtype-widening
# ---------------------------------------------------------------------------

@pytest.fixture
def x64():
    # f64 exists only with x64 enabled; restore so no other test sees it
    jax.config.update("jax_enable_x64", True)
    yield
    jax.config.update("jax_enable_x64", False)


def test_jx102_fires_on_widening_from_f32_inputs(x64):
    def fwd(x):
        acc = x.astype(jnp.float64)          # the forgotten widening
        return (acc * 2.0).sum().astype(jnp.float32)

    assert "JX102" in rules_for(jax.jit(fwd), (spec((4, 16)),), "JX102")


def test_jx102_quiet_on_all_f32(x64):
    def fwd(x):
        return (x * 2.0).sum()

    assert rules_for(jax.jit(fwd), (spec((4, 16)),), "JX102") == []


def test_jx102_quiet_when_caller_asked_for_f64(x64):
    # wide INPUTS mean 64-bit was requested — not an accident to report
    def fwd(x):
        return (x * 2.0).sum()

    assert rules_for(jax.jit(fwd), (spec((4, 16), jnp.float64),),
                     "JX102") == []


# ---------------------------------------------------------------------------
# JX103 host-callback-in-hot-program
# ---------------------------------------------------------------------------

def test_jx103_fires_on_debug_print():
    def fwd(x):
        jax.debug.print("x sum {}", x.sum())
        return x * 2.0

    assert "JX103" in rules_for(jax.jit(fwd), (spec((4, 16)),), "JX103")


def test_jx103_fires_on_pure_callback():
    def fwd(x):
        y = jax.pure_callback(lambda a: np.asarray(a) * 2.0,
                              jax.ShapeDtypeStruct(x.shape, x.dtype), x)
        return y + 1.0

    assert "JX103" in rules_for(jax.jit(fwd), (spec((4, 16)),), "JX103")


def test_jx103_quiet_on_pure_program():
    def fwd(x):
        return x * 2.0

    assert rules_for(jax.jit(fwd), (spec((4, 16)),), "JX103") == []


# ---------------------------------------------------------------------------
# JX104 donation-waste
# ---------------------------------------------------------------------------

def test_jx104_fires_on_unaliasable_donation():
    # donated (64,) input but the only output is a scalar: freed for
    # nothing, and the caller lost the buffer
    def fwd(s):
        return s.sum()

    assert "JX104" in rules_for(jax.jit(fwd, donate_argnums=0),
                                (spec((64,)),), "JX104")


def test_jx104_fires_on_missed_donation():
    # b is donated, a is just as aliasable and large — one HBM copy wasted
    def fwd(a, b):
        return a + 1.0, b + 1.0

    assert "JX104" in rules_for(jax.jit(fwd, donate_argnums=1),
                                (spec((64,)), spec((64,))), "JX104")


def test_jx104_fires_on_passthrough_output():
    def fwd(a, b):
        return a, a + b

    assert "JX104" in rules_for(jax.jit(fwd),
                                (spec((64,)), spec((64,))), "JX104")


def test_jx104_quiet_on_full_donation():
    def fwd(a, b):
        return a + 1.0, b + 1.0

    assert rules_for(jax.jit(fwd, donate_argnums=(0, 1)),
                     (spec((64,)), spec((64,))), "JX104") == []


def test_jx104_quiet_on_donated_passthrough():
    # a donated pass-through aliases for free — nothing to report
    def fwd(a):
        return a, a.sum()

    assert rules_for(jax.jit(fwd, donate_argnums=0),
                     (spec((64,)),), "JX104") == []


# ---------------------------------------------------------------------------
# JX105 retrace-explainer
# ---------------------------------------------------------------------------

def test_jx105_names_the_changed_axis():
    old = signature((np.zeros((8, 64), np.float32),), {})
    new = signature((np.zeros((16, 64), np.float32),), {})
    msg = explain_retrace("step", [old], new)
    assert "axis 0: 8->16" in msg and "step" in msg


def test_jx105_names_dtype_and_static_changes():
    old = signature((np.zeros(4, np.float32),), {"mode": "train"})
    new_dtype = signature((np.zeros(4, np.float16),), {"mode": "train"})
    assert "float32->float16" in explain_retrace("s", [old], new_dtype)
    new_static = signature((np.zeros(4, np.float32),), {"mode": "eval"})
    assert "static value" in explain_retrace("s", [old], new_static)


def test_jx105_diffs_against_closest_variant():
    # two cached variants; the new call matches one except for ONE axis —
    # the diagnosis must name that axis, not diff the farther variant
    a = signature((np.zeros((8, 64), np.float32),), {})
    b = signature((np.zeros((8, 32), np.float16),), {})
    new = signature((np.zeros((9, 64), np.float32),), {})
    msg = explain_retrace("step", [a, b], new)
    assert "axis 0: 8->9" in msg and "float16" not in msg


def test_jx105_no_visible_change_message():
    sig = signature((np.zeros(4, np.float32),), {})
    assert "no visible" in explain_retrace("step", [sig], sig)


def test_runtime_hook_books_jx105_on_recompile(monkeypatch):
    monkeypatch.setenv("MXNET_TRACECHECK", "1")
    tel.refresh_from_env()
    tracecheck.reset_runtime()
    try:
        def fwd(x):
            return x * 2.0

        wf = tel.watch_jit(jax.jit(fwd), "tc_hook_step")
        before = tel.counter("tracecheck_findings")
        wf(jnp.ones((4, 8)))                  # first compile: no history
        wf(jnp.ones((6, 8)))                  # recompile -> JX105
        assert tel.counter("tracecheck_findings") >= before + 1
        from mxnet_tpu.telemetry import flight
        kinds = [e for e in flight._ring if e.get("kind") == "tracecheck"]
        assert any(e.get("name") == "JX105" for e in kinds)
    finally:
        monkeypatch.delenv("MXNET_TRACECHECK")
        tel.refresh_from_env()
        tracecheck.reset_runtime()


def test_runtime_hook_separates_programs_sharing_a_name(monkeypatch):
    """Two distinct jits under one watch name (a cached op's train/eval
    pair, every optimizer instance under 'optimizer_update_step') are
    separate compile caches: each one's FIRST compile must not read as a
    recompile of the other."""
    monkeypatch.setenv("MXNET_TRACECHECK", "1")
    tel.refresh_from_env()
    tracecheck.reset_runtime()
    try:
        wa = tel.watch_jit(jax.jit(lambda x: x * 2.0), "tc_shared_name")
        wb = tel.watch_jit(jax.jit(lambda x: x + 1.0), "tc_shared_name")
        before = tel.counter("tracecheck_findings")
        wa(jnp.ones((4, 8)))
        wb(jnp.ones((6, 8)))      # other program, other shape: no JX105
        assert tel.counter("tracecheck_findings") == before
    finally:
        monkeypatch.delenv("MXNET_TRACECHECK")
        tel.refresh_from_env()
        tracecheck.reset_runtime()


def test_runtime_hook_off_by_default(monkeypatch):
    monkeypatch.delenv("MXNET_TRACECHECK", raising=False)
    tel.refresh_from_env()
    tracecheck.reset_runtime()

    def fwd(x):
        return x + 1.0

    wf = tel.watch_jit(jax.jit(fwd), "tc_off_step")
    before = tel.counter("tracecheck_findings")
    wf(jnp.ones((4, 8)))
    wf(jnp.ones((6, 8)))
    assert tel.counter("tracecheck_findings") == before
    assert not tracecheck._SIG_HISTORY.get("tc_off_step")


# ---------------------------------------------------------------------------
# AOT driver plumbing
# ---------------------------------------------------------------------------

def test_scoped_entry_group_traces_only_its_programs():
    findings, names = tracecheck.check_entry_points(entries={"kvstore"})
    assert set(names) == {"kvstore_stack_sum", "kvstore_bucket_reduce"}
    assert findings == []


def test_cli_trace_rejects_unknown_group():
    from mxnet_tpu.lint import cli
    assert cli.main(["--trace", "nonesuch"]) == 2


def test_cli_trace_json_smoke():
    out = subprocess.run(
        [sys.executable, "-m", "mxnet_tpu.lint", "--trace", "kvstore",
         "-f", "json", "--no-baseline"],
        capture_output=True, text=True, timeout=240,
        env=dict(os.environ, JAX_PLATFORMS="cpu"),
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    assert out.returncode == 0, out.stderr
    payload = json.loads(out.stdout)
    assert payload["new"] == []
    assert "kvstore_stack_sum" in out.stderr      # coverage line


def test_provider_failure_suppresses_baseline_sweep(tmp_path, monkeypatch):
    """A full --trace run with a JX000 (a provider that didn't run) must
    NOT retire trace:// baseline entries: --write-baseline keeps the
    un-re-checked entry instead of silently dropping a group's ledger."""
    from mxnet_tpu.lint import cli
    from mxnet_tpu.lint.core import Finding
    baseline = tmp_path / "base.json"
    baseline.write_text(json.dumps({"version": 1, "entries": [
        {"rule": "JX104", "path": "trace://executor_train",
         "snippet": "donate-missed:arg[0]", "count": 1}]}))
    monkeypatch.setattr(
        tracecheck, "analyze_entry_points",
        lambda entries=None, select=None, memory=True,
        mem_baseline_path=None: (
            [Finding("JX000", "trace://executor", 0, 0, "provider failed",
                     snippet="provider:executor")], [], None))
    cli.main(["--trace", "--write-baseline", "--baseline", str(baseline)])
    kept = json.dumps(json.loads(baseline.read_text()))
    assert "trace://executor_train" in kept


def test_list_rules_shows_jx_catalogue():
    from mxnet_tpu.lint import cli
    import io
    from contextlib import redirect_stdout
    buf = io.StringIO()
    with redirect_stdout(buf):
        assert cli.main(["--list-rules"]) == 0
    text = buf.getvalue()
    for code in ("JX101", "JX102", "JX103", "JX104", "JX105"):
        assert code in text


# ---------------------------------------------------------------------------
# JX2xx SPMD fixtures: a live mesh + the substrate's shard_map
# ---------------------------------------------------------------------------

from jax.sharding import Mesh, PartitionSpec as P  # noqa: E402
from mxnet_tpu.parallel import mesh as mesh_mod  # noqa: E402
from mxnet_tpu.lint.tracecheck import (collective_sequence,  # noqa: E402
                                       run_group_rules)

# the JX203 fixtures are a few KB; the production 64 KiB floor would
# hide them
SPMD_CFG = TraceConfig(replication_bytes=256)


@pytest.fixture(scope="module")
def mesh():
    return Mesh(np.array(jax.devices()), ("x",))


def _smap(body, mesh, out_specs=P("x", None), check=None):
    return mesh_mod.shard_map(body, mesh=mesh, in_specs=P("x", None),
                              out_specs=out_specs, check=check)


def spmd_rules(fn, select, meta=None, name="fixture"):
    rec = trace_program(name, jax.jit(fn), (spec((8, 64)),), meta=meta)
    return [(f.rule, f.snippet)
            for f in run_rules(rec, select={select}, config=SPMD_CFG)]


# ---------------------------------------------------------------------------
# JX201 collective-divergence
# ---------------------------------------------------------------------------

def test_jx201_fires_on_collective_under_one_cond_arm(mesh):
    """The canonical SPMD deadlock: ranks whose data makes the predicate
    disagree take different arms — one enters the psum rendezvous, its
    peers never do."""
    def prog(v):
        def body(s):
            pred = jnp.sum(s) > 0.0
            return jax.lax.cond(pred, lambda t: jax.lax.psum(t, "x"),
                                lambda t: t, s)
        return _smap(body, mesh)(v)

    assert spmd_rules(prog, "JX201") == [("JX201", "cond-divergence")]


def test_jx201_quiet_on_where_skip_twin(mesh):
    """The fix the rule message prescribes: run the collective
    unconditionally, branch the VALUES with jnp.where."""
    def prog(v):
        def body(s):
            pred = jnp.sum(s) > 0.0
            return jnp.where(pred, jax.lax.psum(s, "x"), s)
        return _smap(body, mesh)(v)

    assert spmd_rules(prog, "JX201") == []


def test_jx201_quiet_when_arms_rendezvous_identically(mesh):
    """Both arms psum over the same axis: every rank meets the
    rendezvous whichever arm it takes — safe, must stay quiet."""
    def prog(v):
        def body(s):
            pred = jnp.sum(s) > 0.0
            return jax.lax.cond(pred,
                                lambda t: jax.lax.psum(t, "x"),
                                lambda t: jax.lax.psum(t * 2.0, "x"), s)
        return _smap(body, mesh)(v)

    assert spmd_rules(prog, "JX201") == []


def test_jx201_fires_on_collective_inside_while(mesh):
    """A while trip count is data-dependent by construction: ranks can
    run the rendezvous a different number of times."""
    def prog(v):
        def body(s):
            def w_body(c):
                i, t = c
                return i + 1, jax.lax.psum(t, "x") * 0.5

            def w_cond(c):
                i, t = c
                return (i < 4) & (jnp.sum(t) > 1.0)

            _i, out = jax.lax.while_loop(w_cond, w_body, (0, s))
            return out
        return _smap(body, mesh, check=False)(v)

    assert spmd_rules(prog, "JX201") == [("JX201", "while-collective")]


# ---------------------------------------------------------------------------
# JX202 collective-order
# ---------------------------------------------------------------------------

def test_jx202_fires_on_undeclared_axis(mesh):
    def prog(v):
        def body(s):
            return jax.lax.psum(s, "x")
        return _smap(body, mesh)(v)

    assert spmd_rules(prog, "JX202", meta={"mesh_axes": ("data",)}) \
        == [("JX202", "undeclared-axis:x")]


def test_jx202_quiet_on_declared_axis(mesh):
    def prog(v):
        def body(s):
            return jax.lax.psum(s, "x")
        return _smap(body, mesh)(v)

    assert spmd_rules(prog, "JX202", meta={"mesh_axes": ("x",)}) == []


def test_jx202_quiet_without_declared_axes(mesh):
    """No mesh_axes metadata means the provider opted out of the
    declared-axis contract — not an implicit declare-nothing."""
    def prog(v):
        def body(s):
            return jax.lax.psum(s, "x")
        return _smap(body, mesh)(v)

    assert spmd_rules(prog, "JX202", meta=None) == []


def _lane_pair(mesh, flip):
    perm = [(i, (i + 1) % mesh.devices.size)
            for i in range(mesh.devices.size)]

    def psum_then_permute(v):
        def body(s):
            return jax.lax.ppermute(jax.lax.psum(s, "x"), "x", perm)
        return _smap(body, mesh)(v)

    def permute_then_psum(v):
        def body(s):
            return jax.lax.psum(jax.lax.ppermute(s, "x", perm), "x")
        return _smap(body, mesh)(v)

    lane = {"lane": "fixture-lane"}
    a = trace_program("lane_a", jax.jit(psum_then_permute),
                      (spec((8, 64)),), meta=lane)
    b = trace_program("lane_b", jax.jit(
        permute_then_psum if flip else psum_then_permute),
        (spec((8, 64)),), meta=lane)
    return a, b


def test_jx202_group_fires_on_lane_order_divergence(mesh):
    """Two programs on one lane disagreeing on per-axis collective order
    is the cross-program deadlock: rank A runs P's psum while rank B
    runs Q's ppermute."""
    a, b = _lane_pair(mesh, flip=True)
    assert collective_sequence(a) == {"x": ("psum", "ppermute")}
    assert collective_sequence(b) == {"x": ("ppermute", "psum")}
    found = run_group_rules([a, b], select={"JX202"}, config=SPMD_CFG)
    assert [(f.rule, f.snippet) for f in found] \
        == [("JX202", "lane-order:fixture-lane:x")]


def test_jx202_group_quiet_on_identical_lane_order(mesh):
    a, b = _lane_pair(mesh, flip=False)
    assert run_group_rules([a, b], select={"JX202"}, config=SPMD_CFG) == []


# ---------------------------------------------------------------------------
# JX203 replication-waste
# ---------------------------------------------------------------------------

def test_jx203_fires_on_gathered_output(mesh):
    def prog(v):
        def body(s):
            return jax.lax.all_gather(s, "x", axis=0, tiled=True)
        return _smap(body, mesh, out_specs=P(None, None), check=False)(v)

    assert spmd_rules(prog, "JX203") == [("JX203", "gathered-output:x")]


def test_jx203_quiet_when_gather_is_reduced_before_return(mesh):
    def prog(v):
        def body(s):
            g = jax.lax.all_gather(s, "x", axis=0, tiled=True)
            return jnp.sum(g, axis=0)
        return _smap(body, mesh, out_specs=P(None), check=False)(v)

    assert spmd_rules(prog, "JX203") == []


def test_jx203_quiet_below_replication_threshold(mesh):
    """Same gathered output, production 64 KiB floor: a few-KB fixture
    is below the bar — the rule gates real HBM waste, not toys."""
    def prog(v):
        def body(s):
            return jax.lax.all_gather(s, "x", axis=0, tiled=True)
        return _smap(body, mesh, out_specs=P(None, None), check=False)(v)

    rec = trace_program("fixture", jax.jit(prog), (spec((8, 64)),))
    assert run_rules(rec, select={"JX203"}, config=TraceConfig()) == []


# ---------------------------------------------------------------------------
# JX204 memory-budget
# ---------------------------------------------------------------------------

from mxnet_tpu.lint.tracecheck import (check_memory,  # noqa: E402
                                       measure_programs,
                                       save_mem_baseline)


def _mem_record(name="mem_fixture"):
    def prog(x, w):
        return jnp.tanh(x @ w)
    # 128x128 f32 operands: ~196 KiB total, comfortably above the 4 KiB
    # absolute slack so a halved budget must trip the fractional band
    return trace_program(name, jax.jit(prog),
                         (spec((128, 128)), spec((128, 128))))


def test_jx204_quiet_within_budget(tmp_path):
    rec = _mem_record()
    baseline = save_mem_baseline(measure_programs([rec]),
                                 path=str(tmp_path / "mem.json"))
    findings, report = check_memory([rec], baseline, tolerance=0.25)
    assert findings == []
    entry = report["programs"][0]
    assert entry["name"] == "mem_fixture" and not entry["over_budget"]
    assert entry["budget_total_bytes"] == entry["total_bytes"]


def test_jx204_fires_when_over_budget(tmp_path):
    rec = _mem_record()
    measured = measure_programs([rec])
    measured["mem_fixture"]["total_bytes"] //= 2          # yesterday's
    baseline = save_mem_baseline(measured,                # smaller program
                                 path=str(tmp_path / "mem.json"))
    findings, report = check_memory([rec], baseline, tolerance=0.25)
    assert [(f.rule, f.snippet) for f in findings] \
        == [("JX204", "mem:over")]
    assert report["programs"][0]["over_budget"]


def test_jx204_tolerance_band_absorbs_growth(tmp_path):
    """The same halved budget passes under a wide MXNET_MEM_TOLERANCE:
    the band is the deliberate-growth knob, read per check."""
    rec = _mem_record()
    measured = measure_programs([rec])
    measured["mem_fixture"]["total_bytes"] //= 2
    baseline = save_mem_baseline(measured,
                                 path=str(tmp_path / "mem.json"))
    findings, _report = check_memory([rec], baseline, tolerance=2.0)
    assert findings == []


def test_jx204_tolerance_env_knob(tmp_path, monkeypatch):
    rec = _mem_record()
    measured = measure_programs([rec])
    measured["mem_fixture"]["total_bytes"] //= 2
    baseline = save_mem_baseline(measured,
                                 path=str(tmp_path / "mem.json"))
    monkeypatch.setenv("MXNET_MEM_TOLERANCE", "2.0")
    findings, _report = check_memory([rec], baseline)
    assert findings == []
    monkeypatch.setenv("MXNET_MEM_TOLERANCE", "0.01")
    findings, _report = check_memory([rec], baseline)
    assert [f.snippet for f in findings] == ["mem:over"]


def test_jx204_fires_on_unbudgeted_program(tmp_path):
    rec = _mem_record()
    baseline = save_mem_baseline({}, path=str(tmp_path / "mem.json"))
    findings, report = check_memory([rec], baseline)
    assert [(f.rule, f.snippet) for f in findings] \
        == [("JX204", "mem:unbudgeted")]
    assert report["programs"][0]["unbudgeted"]


def test_jx204_fires_on_specimen_count_drift(tmp_path):
    """Dropping a specimen must be as visible as growing one: the
    count-keyed budget fires when k changes, even if bytes shrink."""
    rec = _mem_record()
    measured = measure_programs([rec, _mem_record()])   # budget: k=2
    baseline = save_mem_baseline(measured,
                                 path=str(tmp_path / "mem.json"))
    findings, _report = check_memory([rec], baseline)   # traced: k=1
    assert "mem:specimens" in {f.snippet for f in findings}


def test_jx204_topology_mismatch_skips_comparison(tmp_path):
    """Memory bytes are a function of device count: a baseline captured
    on a different topology must be SKIPPED (gate exits 4 downstream),
    never compared against."""
    rec = _mem_record()
    measured = measure_programs([rec])
    measured["mem_fixture"]["total_bytes"] //= 2
    baseline = save_mem_baseline(measured, path=str(tmp_path / "mem.json"),
                                 n_devices=2)            # conftest pins 8
    findings, report = check_memory([rec], baseline)
    assert findings == []
    assert not report["topology_match"]
    assert report["programs"][0]["budget_total_bytes"] is None


def test_jx204_stale_budget_listed_on_full_run(tmp_path):
    rec = _mem_record()
    measured = measure_programs([rec])
    measured["renamed_away"] = dict(measured["mem_fixture"])
    baseline = save_mem_baseline(measured,
                                 path=str(tmp_path / "mem.json"))
    _f, report = check_memory([rec], baseline, full=True)
    assert report["stale_budgets"] == ["renamed_away"]
    _f, report = check_memory([rec], baseline, full=False)
    assert report["stale_budgets"] == []
