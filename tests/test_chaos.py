"""Chaos tier (ISSUE 9): deterministic fault injection + a dist
transport that survives dead peers.

Acceptance contract: ``kill -9`` one server mid-training → every worker
raises a structured :class:`~mxnet_tpu.dist_ps.PeerLost` within 2x the
RPC deadline (never a hang); a restarted server re-registers, its shard
state is restored through the kvstore checkpoint-state protocol, and
the resumed CPU loss trajectory is bitwise-identical to an
uninterrupted run.  Same seed + same ``MXNET_CHAOS`` spec → identical
injected-fault sequence; a transient-faults-only chaos run completes
bitwise-identical to a no-chaos run (``tools/chaos_smoke.py``).
"""
import json
import os
import pickle
import socket
import subprocess
import sys
import time

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import chaos, dist_ps, engine, telemetry

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
WORKER = os.path.join(REPO, "tests", "chaos_dist_worker.py")

sys.path.insert(0, os.path.join(REPO, "tools"))


@pytest.fixture(autouse=True)
def _no_chaos_leak():
    """Every test leaves the process chaos-free."""
    yield
    chaos.configure(None)


# ---------------------------------------------------------------------------
# spec grammar + deterministic replay
# ---------------------------------------------------------------------------

def test_spec_grammar_round_trip():
    seed, rules = chaos.parse_spec(
        "seed=42;conn.send.pull:drop@2-4,delay~0.5=5ms;engine.task:exc")
    assert seed == 42
    assert [r.site for r in rules] == ["conn.send.pull", "engine.task"]
    drop, delay = rules[0].faults
    assert (drop.kind, drop.lo, drop.hi) == ("drop", 2, 4)
    assert (delay.kind, delay.prob, delay.value) == ("delay", 0.5, 0.005)
    assert rules[1].faults[0].kind == "exc"
    assert chaos.parse_duration("250us") == pytest.approx(2.5e-4)
    assert chaos.parse_duration("1.5") == 1.5


@pytest.mark.parametrize("bad", [
    "seed=x", "conn.send:frobnicate", "nosuchsite:drop",
    "conn.recv:delay",              # delay needs a duration
    "conn.recv:drop~1.5",           # probability out of range
    "conn.recv:drop@0",             # occurrences are 1-based
    "justgarbage",
])
def test_spec_rejects_garbage(bad):
    with pytest.raises(chaos.ChaosSpecError):
        chaos.parse_spec(bad)


def test_same_seed_same_fault_sequence():
    """The determinism acceptance, in-process: identical spec+seed over
    an identical call sequence injects the identical fault sequence."""
    spec = "seed=9;conn.recv:drop~0.3;conn.send.push:delay@2=1us"
    sites = (["conn.recv"] * 40 + ["conn.send.push"] * 5) * 2

    def run():
        chaos.configure(spec)
        for s in sites:
            chaos.decide(s)
        return chaos.fault_log()

    log1, log2 = run(), run()
    assert log1 == log2
    assert any(entry[2] == "drop" for entry in log1)
    assert [e for e in log1 if e[2] == "delay"] == \
        [("conn.send.push", "conn.send.push", "delay", 2)]
    # a different seed decides differently (probabilistic rules)
    chaos.configure(spec.replace("seed=9", "seed=10"))
    for s in sites:
        chaos.decide(s)
    assert chaos.fault_log() != log1


def test_faults_are_booked_in_counter_and_flight_ring():
    from mxnet_tpu.telemetry import flight
    before = telemetry.counter("chaos_faults")
    chaos.configure("conn.recv:delay@1=1us")
    assert chaos.decide("conn.recv") is not None
    assert telemetry.counter("chaos_faults") == before + 1
    assert any(ev["kind"] == "chaos" and ev["name"] == "conn.recv"
               for ev in flight.events())


# ---------------------------------------------------------------------------
# Conn deadlines: RPCTimeout + mid-frame poisoning
# ---------------------------------------------------------------------------

def test_recv_deadline_and_stream_poisoning():
    a, b = socket.socketpair()
    ca, cb = dist_ps.Conn(a), dist_ps.Conn(b, timeout=0.2)
    t0 = time.monotonic()
    with pytest.raises(dist_ps.RPCTimeout):
        cb.recv()
    assert time.monotonic() - t0 < 5.0
    # nothing was consumed: the stream is still aligned and usable
    ca.send(("ok", 1))
    assert cb.recv() == ("ok", 1)
    # half a header, then silence: the connection must poison itself
    a.sendall(b"MX")
    with pytest.raises(dist_ps.RPCTimeout, match="poisoned"):
        cb.recv()
    with pytest.raises(ConnectionError, match="poisoned"):
        cb.recv()
    with pytest.raises(ConnectionError, match="poisoned"):
        cb.send(("x",))
    a.close()
    b.close()
    assert isinstance(dist_ps.RPCTimeout("x"), dist_ps.PeerLost)
    assert isinstance(dist_ps.PeerLost("x"), ConnectionError)


def test_connect_failure_carries_last_error():
    lsock = socket.socket()
    lsock.bind(("127.0.0.1", 0))
    addr = lsock.getsockname()
    lsock.close()                     # nothing listens here any more
    with pytest.raises(ConnectionError) as ei:
        dist_ps.Conn.connect(addr, retries=2, delay=0.01)
    assert "after 2 attempts" in str(ei.value)
    assert ei.value.__cause__ is not None   # the underlying OSError


def test_chaos_drop_on_send_is_silent_and_close_raises():
    a, b = socket.socketpair()
    ca, cb = dist_ps.Conn(a), dist_ps.Conn(b, timeout=0.2)
    chaos.configure("conn.send.pull:drop@1;conn.send.push:close@1")
    ca.send(("pull", "k"))            # dropped: peer sees nothing
    with pytest.raises(dist_ps.RPCTimeout):
        cb.recv()
    with pytest.raises(ConnectionError, match="chaos"):
        ca.send(("push", "k", 0, None, None))
    a.close()
    b.close()


# ---------------------------------------------------------------------------
# engine.task + ckpt.io + serving.batch seams
# ---------------------------------------------------------------------------

def test_engine_task_chaos_surfaces_at_wait():
    chaos.configure("engine.task:exc@1")
    eng = engine.ThreadedEngine()
    try:
        v = eng.new_variable()
        eng.push(lambda: None, mutable_vars=(v,))
        with pytest.raises(chaos.ChaosError):
            eng.wait_for_var(v)
        # the next task is fault-free and runs normally
        ran = []
        eng.push(lambda: ran.append(1), mutable_vars=(v,))
        eng.wait_for_var(v)
        assert ran == [1]
    finally:
        eng.close()


def test_checkpoint_io_chaos_lands_in_retry_path(tmp_path):
    from tests.test_checkpoint import _build, _run_steps
    from mxnet_tpu import checkpoint
    net, tr, it = _build()
    _run_steps(net, tr, it, 2)
    before = telemetry.counter("checkpoint_write_retries")
    chaos.configure("ckpt.io:fail@1")   # first file write of the commit
    mgr = checkpoint.CheckpointManager(str(tmp_path), trainer=tr,
                                       data_iter=it, num_shards=2)
    try:
        assert mgr.save(2, sync=True), mgr.last_error
    finally:
        mgr.close()
    assert telemetry.counter("checkpoint_write_retries") == before + 1
    assert mgr.last_committed_step == 2


class _FakeProgram:
    """Minimal program contract the batcher needs (no jax, no model)."""

    max_batch = 4
    output_names = ["out"]

    def __init__(self):
        self.fail = False
        self.runs = 0

    def run(self, inputs, total, timings=None):
        self.runs += 1
        if self.fail:
            raise RuntimeError("injected executor failure")
        return [np.asarray(inputs["x"])], self.max_batch, None

    def run_straight(self, inputs, total):
        return self.run(inputs, total)


def _submit_and_wait(batcher, n=1, timeout=5.0):
    req = batcher.submit({"x": np.zeros((n, 2), np.float32)}, n)
    return req.wait(timeout)


def test_serving_circuit_breaker_sheds_and_recovers():
    from mxnet_tpu.serving import batcher as B
    prog = _FakeProgram()
    breaker = B.CircuitBreaker(threshold=2, cooldown_s=0.25)
    b = B.ContinuousBatcher(prog, "brk", timeout_ms=1, use_engine=False,
                            breaker=breaker).start()
    try:
        assert len(_submit_and_wait(b)) == 1      # healthy
        assert b.breaker_state() == "closed"
        prog.fail = True
        for _ in range(2):                        # threshold failures
            with pytest.raises(mx.base.MXNetError):
                _submit_and_wait(b)
        assert b.breaker_state() == "open"
        before = telemetry.counter("serving_breaker_shed")
        with pytest.raises(B.Overloaded, match="circuit breaker"):
            b.submit({"x": np.zeros((1, 2), np.float32)}, 1)
        assert telemetry.counter("serving_breaker_shed") == before + 1
        time.sleep(0.3)                           # cooldown: half-open
        prog.fail = False
        assert len(_submit_and_wait(b)) == 1      # probe succeeds
        assert b.breaker_state() == "closed"
    finally:
        b.stop(drain=False)


def test_breaker_half_open_admits_exactly_one_probe():
    from mxnet_tpu.serving import batcher as B
    br = B.CircuitBreaker(threshold=1, cooldown_s=0.05)
    br.record(ok=False)
    assert not br.allow() and br.state() == "open"
    time.sleep(0.06)
    assert br.allow()            # the single half-open probe
    assert not br.allow()        # everyone else stays shed meanwhile
    assert br.state() == "half-open"
    br.record(ok=False)          # probe failed: re-open, cooldown re-arms
    assert not br.allow()
    time.sleep(0.06)
    assert br.allow()
    br.record(ok=True)           # probe succeeded: closed for business
    assert br.allow() and br.allow() and br.state() == "closed"


def test_serving_request_deadline_drops_stale_queue():
    from mxnet_tpu.serving import batcher as B
    prog = _FakeProgram()
    b = B.ContinuousBatcher(prog, "ddl", timeout_ms=1, use_engine=False,
                            breaker=B.CircuitBreaker(threshold=0))
    # NOT started yet: requests age in the queue past their deadline
    req = b.submit({"x": np.zeros((1, 2), np.float32)}, 1, timeout_ms=20)
    live = b.submit({"x": np.zeros((1, 2), np.float32)}, 1)  # no deadline
    time.sleep(0.06)
    before = telemetry.counter("serving_deadline_drops")
    b.start()
    try:
        with pytest.raises(mx.base.MXNetError, match="timed out"):
            req.wait(5.0)
        assert len(live.wait(5.0)) == 1           # undeadlined one ran
        assert telemetry.counter("serving_deadline_drops") == before + 1
    finally:
        b.stop(drain=False)


def test_serving_batch_chaos_trips_the_breaker():
    from mxnet_tpu.serving import batcher as B
    chaos.configure("serving.batch:exc@1-2")
    prog = _FakeProgram()
    b = B.ContinuousBatcher(prog, "chaos", timeout_ms=1, use_engine=False,
                            breaker=B.CircuitBreaker(threshold=2,
                                                     cooldown_s=30)).start()
    try:
        for _ in range(2):
            with pytest.raises(mx.base.MXNetError):
                _submit_and_wait(b)
        assert b.breaker_state() == "open"
        with pytest.raises(B.Overloaded):
            b.submit({"x": np.zeros((1, 2), np.float32)}, 1)
    finally:
        b.stop(drain=False)


def test_barrier_fails_fast_when_peer_departs(monkeypatch):
    """A crashed worker's atexit still sends finalize — so a finalized
    member must fail a pending barrier exactly like a dead one (found
    by a live drive: the surviving worker hung for the full barrier
    timeout)."""
    import threading
    port = _free_port()
    monkeypatch.setenv("DMLC_PS_ROOT_URI", "127.0.0.1")
    monkeypatch.setenv("DMLC_PS_ROOT_PORT", str(port))
    monkeypatch.setenv("DMLC_NUM_WORKER", "2")
    monkeypatch.setenv("DMLC_NUM_SERVER", "1")
    monkeypatch.delenv("DMLC_WORKER_RANK", raising=False)
    sched = dist_ps.Scheduler(2, 1, port=port)
    threading.Thread(target=sched.run, daemon=True).start()
    threading.Thread(target=dist_ps.run_server, daemon=True).start()
    # rendezvous blocks until the FULL roster registers: both worker
    # transports must dial concurrently (each is its own process in
    # real deployments)
    built = {}

    def _build(slot):
        built[slot] = dist_ps.WorkerTransport()

    builders = [threading.Thread(target=_build, args=(i,), daemon=True)
                for i in range(2)]
    for b in builders:
        b.start()
    for b in builders:
        b.join(30)
    assert sorted(built) == [0, 1], "worker rendezvous wedged"
    w0, w1 = built[0], built[1]
    outcome = {}

    def _barrier():
        try:
            w1.barrier()
            outcome["err"] = None
        except Exception as exc:   # noqa: BLE001
            outcome["err"] = exc

    t = threading.Thread(target=_barrier, daemon=True)
    t.start()
    time.sleep(0.3)               # w1 is parked in the barrier
    w0.finalize()                 # the "crashed peer's atexit" path
    t.join(10)
    assert not t.is_alive(), "barrier hung after the peer departed"
    assert isinstance(outcome["err"], dist_ps.PeerLost), outcome
    # a FUTURE barrier from the survivor fails immediately too
    with pytest.raises(dist_ps.PeerLost):
        w1.barrier()
    w1.finalize()


# ---------------------------------------------------------------------------
# /peers introspection
# ---------------------------------------------------------------------------

def test_peers_endpoint_observe_only():
    import urllib.request
    from mxnet_tpu.telemetry import server as tserver
    srv = tserver.IntrospectionServer(0).start()
    try:
        url = "http://127.0.0.1:%d/peers" % srv.port
        payload = json.loads(urllib.request.urlopen(url).read())
        # dist_ps is imported in this process: the view answers with the
        # local role + transport counters, no network IO
        assert payload["role"] == "worker"
        assert "ps_rpc_timeouts" in payload["counters"]
        assert "ps_peer_lost" in payload["counters"]
    finally:
        srv.stop()


# ---------------------------------------------------------------------------
# the acceptance: kill -9 a server, recover bitwise
# ---------------------------------------------------------------------------

def _free_port():
    s = socket.socket()
    s.bind(("", 0))
    port = s.getsockname()[1]
    s.close()
    return port


RPC_TIMEOUT_S = 3.0


def _dist_env(state_dir, port, iters, expect_kill):
    env = dict(os.environ)
    env.update({
        "JAX_PLATFORMS": "cpu",
        "PYTHONPATH": REPO + os.pathsep + env.get("PYTHONPATH", ""),
        "DMLC_PS_ROOT_URI": "127.0.0.1",
        "DMLC_PS_ROOT_PORT": str(port),
        "DMLC_NUM_WORKER": "2",
        "DMLC_NUM_SERVER": "2",
        "CHAOS_STATE_DIR": str(state_dir),
        "CHAOS_ITERS": str(iters),
        "MXNET_PS_RPC_TIMEOUT_S": str(RPC_TIMEOUT_S),
        "MXNET_PS_HEARTBEAT_S": "0.5",
        "MXNET_FLIGHT_DIR": str(state_dir),
    })
    env["CHAOS_EXPECT_KILL"] = "1" if expect_kill else ""
    env.pop("MXNET_CHAOS", None)
    return env


def _spawn(env, role_name, rank=None):
    e = dict(env, DMLC_ROLE=role_name)
    if rank is not None:
        e["DMLC_WORKER_RANK"] = str(rank)
    return subprocess.Popen([sys.executable, WORKER], env=e)


def _load_results(state_dir, nworkers=2):
    out = []
    for r in range(nworkers):
        with open(os.path.join(str(state_dir), "result-%d.json" % r)) as f:
            out.append(json.load(f))
    return out


def test_kill9_server_peerlost_and_bitwise_recovery(tmp_path):
    """The ISSUE-9 acceptance test, end to end with real processes."""
    iters = 6
    # --- reference: uninterrupted run -----------------------------------
    from launch import launch
    ref_dir = tmp_path / "ref"
    ref_dir.mkdir()
    env = _dist_env(ref_dir, 0, iters, expect_kill=False)
    rcs = launch(2, 2, [sys.executable, WORKER], env_extra=env,
                 timeout=180)
    assert rcs == [0, 0], "reference run failed: %r" % (rcs,)
    reference = _load_results(ref_dir)

    # --- killed run ------------------------------------------------------
    state = tmp_path / "killed"
    state.mkdir()
    env = _dist_env(state, _free_port(), iters, expect_kill=True)
    procs = []
    try:
        procs.append(_spawn(env, "scheduler"))
        victims = [_spawn(env, "server") for _ in range(2)]
        procs.extend(victims)
        workers = [_spawn(env, "worker", rank=r) for r in range(2)]
        procs.extend(workers)

        # wait for the first committed checkpoint (iter >= 2)
        ckpt = os.path.join(str(state), "ckpt.pkl")
        deadline = time.monotonic() + 120
        while True:
            try:
                with open(ckpt, "rb") as fh:
                    if pickle.load(fh)["it"] >= 2:
                        break
            except (OSError, EOFError, pickle.UnpicklingError, KeyError):
                pass
            assert time.monotonic() < deadline, \
                "no checkpoint appeared — setup wedged"
            time.sleep(0.05)

        kill_wall = time.time()
        victims[0].kill()                      # SIGKILL, mid-training
        replacement = _spawn(env, "server")    # the restarted server
        procs.append(replacement)

        for w in workers:
            assert w.wait(timeout=180) == 0, "worker failed post-kill"
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()

    results = _load_results(state)
    for res in results:
        # every worker raised PeerLost and recovered
        assert res["recoveries"], \
            "rank %d never saw PeerLost" % res["rank"]
        rec = res["recoveries"][0]
        assert rec["peer_role"] in ("server", "scheduler", "worker")
        # ... within 2x the RPC deadline of the kill (+1s clock slack)
        detect = rec["detect_wall"] - kill_wall
        assert detect <= 2 * RPC_TIMEOUT_S + 1.0, \
            "rank %d took %.2fs to surface PeerLost" \
            % (res["rank"], detect)
        # ... and the resumed trajectory is bitwise-identical
        assert res["losses_hex"] == reference[res["rank"]]["losses_hex"], \
            "rank %d trajectory diverged after recovery:\n%s\n%s" \
            % (res["rank"], res["losses"],
               reference[res["rank"]]["losses"])
    # both workers agree with each other too
    assert results[0]["losses_hex"] == results[1]["losses_hex"]


# ---------------------------------------------------------------------------
# tier-1 chaos smoke (the fast variant of tools/chaos_smoke.py)
# ---------------------------------------------------------------------------

def test_chaos_smoke_tier1():
    """Transient-faults-only seeded chaos run: completes (no hang),
    bitwise-identical to no-chaos, deterministic replay.  The full knob
    surface lives in tools/chaos_smoke.py; this is the CI-gated fast
    variant."""
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "chaos_smoke.py"),
         "--iters", "2", "--timeout", "150", "--json"],
        capture_output=True, text=True, timeout=500)
    assert out.returncode == 0, \
        "chaos_smoke failed:\n%s\n%s" % (out.stdout, out.stderr)
    summary = json.loads(out.stdout.strip().splitlines()[-1])
    assert summary["ok"], summary
    assert summary["injected_faults"] > 0


# ---------------------------------------------------------------------------
# the grad-seam `nan` fault kind (ISSUE 10: drives the guardian)
# ---------------------------------------------------------------------------

def test_nan_fault_grammar_and_grad_seam():
    seed, rules = chaos.parse_spec("seed=3;grad.bucket:nan@2-4")
    assert rules[0].site == "grad.bucket"
    assert rules[0].faults[0].kind == "nan"
    # deterministic poison: occurrence 2 replaces the FIRST bucket with
    # NaNs, occurrence 1 passes everything through untouched
    import jax.numpy as jnp
    chaos.configure("grad.bucket:nan@2")
    g0 = jnp.ones((4,), jnp.float32)
    g1 = jnp.ones((2, 2), jnp.float32)
    out = chaos.poison_grads([g0, g1])
    assert out[0] is g0 and out[1] is g1           # occurrence 1: clean
    out = chaos.poison_grads([g0, g1])
    assert np.isnan(np.asarray(out[0])).all()      # occurrence 2: poisoned
    assert out[0].shape == g0.shape and out[1] is g1
    assert [e[2] for e in chaos.fault_log()] == ["nan"]


def test_keyed_decide_is_dispatch_order_independent():
    """ISSUE-15 satellite: keyed counters (bucket ids) make decisions a
    function of (key, occurrence), not of arrival order — the same
    calls in any interleaving yield the identical fault_log()."""
    spec = "seed=8;conn.send.push:drop~0.4;grad.bucket:exc@2"
    keys = ["__bucket__a", "__bucket__b", "__bucket__c"]

    def run(order):
        chaos.configure(spec)
        for step in range(4):
            for k in order(step, keys):
                chaos.decide("conn.send.push", key=k)
            for b in order(step, range(len(keys))):
                chaos.decide("grad.bucket", key=b)
        return chaos.fault_log()

    forward = run(lambda s, ks: list(ks))
    reverse = run(lambda s, ks: list(ks)[::-1])
    shuffled = run(lambda s, ks: list(ks)[s % len(list(ks)):]
                   + list(ks)[:s % len(list(ks))])
    assert forward == reverse == shuffled
    assert any(e[2] == "drop" for e in forward)
    # the @2 window fired once per bucket id, at that key's 2nd step
    excs = [e for e in forward if e[2] == "exc"]
    assert [(e[3], e[4]) for e in excs] == [(2, 0), (2, 1), (2, 2)]


def test_overlap_on_off_same_fault_log():
    """The seeded replay acceptance: a bucketed training run injects
    the IDENTICAL fault sequence whether bucket reduces run overlapped
    under backward (MXNET_OVERLAP=1) or synchronously in the step
    (MXNET_OVERLAP=0) — grad.bucket and push counters are keyed by
    bucket id, not dispatch order."""
    import os
    import numpy as np
    from mxnet_tpu import autograd, gluon, kvstore as kvs
    from mxnet_tpu.gluon import nn, overlap

    prev_bucket = os.environ.get("MXNET_KVSTORE_BUCKET_BYTES")
    prev_overlap = os.environ.get("MXNET_OVERLAP")
    os.environ["MXNET_KVSTORE_BUCKET_BYTES"] = "256"   # several buckets
    kvs.refresh_from_env()

    def run(overlap_on):
        os.environ["MXNET_OVERLAP"] = "1" if overlap_on else "0"
        overlap.refresh_from_env()
        chaos.configure("seed=6;grad.bucket:delay~0.5=1us")
        np.random.seed(0)
        mx.random.seed(0)
        net = nn.Sequential()
        for _ in range(3):
            net.add(nn.Dense(16, activation="relu"))
        net.add(nn.Dense(3))
        net.initialize(init=mx.initializer.Xavier())
        tr = gluon.Trainer(net.collect_params(), "sgd",
                           {"learning_rate": 0.05}, kvstore="device")
        loss_fn = gluon.loss.L2Loss()
        rng = np.random.RandomState(1)
        for _ in range(4):
            with autograd.record():
                loss = loss_fn(net(mx.nd.array(
                    rng.randn(4, 6).astype(np.float32))),
                    mx.nd.array(rng.randn(4, 3).astype(np.float32)))
            loss.backward()
            tr.step(4)
        overlap.abandon_session(tr)
        log = chaos.fault_log()
        params = {i: p.data().asnumpy().tobytes()
                  for i, p in enumerate(net.collect_params().values())}
        return log, params

    try:
        log_off, params_off = run(False)
        log_on, params_on = run(True)
    finally:
        for name, prev in (("MXNET_KVSTORE_BUCKET_BYTES", prev_bucket),
                           ("MXNET_OVERLAP", prev_overlap)):
            if prev is None:
                os.environ.pop(name, None)
            else:
                os.environ[name] = prev
        kvs.refresh_from_env()
        overlap.refresh_from_env()
    assert log_off, "the spec injected nothing — the replay is vacuous"
    assert log_on == log_off
    assert params_on == params_off       # transient faults stay bitwise
    # multiple buckets existed, each keyed independently
    assert len({e[4] for e in log_off if len(e) > 4}) > 1


def test_nan_fault_log_is_deterministic():
    spec = "seed=5;grad.bucket:nan~0.5"
    import jax.numpy as jnp
    g = [jnp.ones((2,), jnp.float32)]

    def run():
        chaos.configure(spec)
        for _ in range(16):
            chaos.poison_grads(g)
        return chaos.fault_log()

    assert run() == run()
